"""``python -m polyaxon_tpu.perf`` — the communication audit CLI.

Default: audit every standard schedule point on the 8-device virtual
CPU mesh, print the per-schedule collective table, and write the full
report artifact (``collective_audit.json``). ``--check`` gates against
the committed budgets (the ci.sh audit stage); ``--update-budgets``
regenerates them after an intentional sharding change; ``--aot-probe``
runs the topology-only TPU compile probe instead.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _force_cpu_mesh(n: int) -> None:
    from polyaxon_tpu.utils import cpu_mesh_xla_flags

    cpu_mesh_xla_flags(n)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m polyaxon_tpu.perf",
        description="HLO collective audit over the standard schedule "
                    "points (8-device virtual CPU mesh)")
    parser.add_argument("--schedules", default=None,
                        help="comma-separated subset of standard points "
                             "(default: all)")
    parser.add_argument("--check", action="store_true",
                        help="fail (exit 1) on any budget violation")
    parser.add_argument("--update-budgets", action="store_true",
                        help="regenerate polyaxon_tpu/perf/budgets.json "
                             "from this run")
    parser.add_argument("--json", default="collective_audit.json",
                        help="report artifact path ('' = don't write)")
    parser.add_argument("--inject-reshard", action="store_true",
                        help="deliberately replicate the batch inside the "
                             "step (demonstrates the gate failing)")
    parser.add_argument("--ops", action="store_true",
                        help="include the per-instruction op list in the "
                             "JSON artifact (large)")
    parser.add_argument("--aot-probe", action="store_true",
                        help="run the AOT topology-only TPU compile probe "
                             "and write aot_probe_results.json")
    parser.add_argument("--aot-timeout", type=float, default=None,
                        help="probe subprocess timeout seconds "
                             "(per topology candidate)")
    parser.add_argument("--aot-train-step", default=None, metavar="POINTS",
                        help="comma-separated standard points to also "
                             "compile as full train steps against the "
                             "topology (TPU collective reports), e.g. "
                             "'ulysses-cp,ring-cp'")
    parser.add_argument("--devices", type=int, default=8,
                        help="virtual CPU mesh size (default 8)")
    args = parser.parse_args(argv)

    if args.aot_probe:
        from polyaxon_tpu.perf import aot

        result = aot.run_probe(args.aot_timeout or aot.PROBE_TIMEOUT_S,
                               train_step_points=args.aot_train_step)
        out_path = "aot_probe_results.json"
        with open(out_path, "w") as fh:
            json.dump(result, fh, indent=2)
            fh.write("\n")
        print(json.dumps(result))
        print(f"# wrote {out_path}", file=sys.stderr)
        # A negative probe is a recorded RESULT, not a failure: only a
        # harness-level error (no JSON at all) exits nonzero.
        return 0 if ("topologies" in result or result.get("ok")) else 1

    _force_cpu_mesh(args.devices)

    from polyaxon_tpu.perf import audit, budgets

    points = list(audit.STANDARD_POINTS)
    if args.schedules:
        points = [audit.point_by_name(s.strip())
                  for s in args.schedules.split(",") if s.strip()]

    reports = []
    for point in points:
        print(f"→ {point.name} ...", flush=True, file=sys.stderr)
        reports.append(audit.audit_point(
            point, inject_reshard=args.inject_reshard, keep_ops=args.ops))

    kinds = sorted({k for r in reports for k in r["counts"]})
    header = f"{'schedule':<12} {'mesh':<18} " + " ".join(
        f"{k:>18}" for k in kinds) + f" {'est MiB/step':>13}"
    print(header)
    for r in reports:
        mesh = "x".join(f"{a}{s}" for a, s in r["axes"].items())
        row = f"{r['name']:<12} {mesh:<18} " + " ".join(
            f"{r['counts'].get(k, 0):>18}" for k in kinds)
        row += f" {r['est_wire_bytes_per_step'] / 2**20:>13.2f}"
        print(row)

    if args.json:
        artifact = {"reports": reports}
        ring = next((r for r in reports if r["name"] == "ring-cp"), None)
        uly = next((r for r in reports if r["name"] == "ulysses-cp"), None)
        if ring and uly:
            artifact["ring_vs_ulysses"] = audit.diff_reports(ring, uly)
        with open(args.json, "w") as fh:
            json.dump(artifact, fh, indent=2)
            fh.write("\n")
        print(f"# wrote {args.json}", file=sys.stderr)

    if args.update_budgets:
        if args.inject_reshard:
            print("refusing to bake an injected reshard into budgets",
                  file=sys.stderr)
            return 2
        import jax

        path = budgets.write_budgets(
            reports, meta={"jax": jax.__version__,
                           "backend": "cpu-virtual",
                           "n_devices": args.devices})
        print(f"# wrote {path}", file=sys.stderr)
        return 0

    if args.check:
        violations = budgets.check_reports(reports)
        if violations:
            for v in violations:
                print(f"BUDGET VIOLATION: {v}", file=sys.stderr)
            return 1
        print("# collective budgets OK", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
