from polyaxon_tpu.schemas.base import BaseSchema, to_camel  # noqa: F401
