"""Polyboard-lite: a dependency-free runs dashboard served by the API
server at ``/ui``.

The reference ships a ~100k-LoC React SPA (SURVEY.md §2 "UI"); the
capability core here is a single static page over the same REST surface:
run list + status filter, per-run metric charts (inline SVG, crosshair +
tooltip), raw-table fallback per chart, and live log tail over the SSE
streams endpoint. Light/dark both ship; colors follow the chart-role
tokens (series color only on marks, text in ink tokens, status always
icon + label — never color alone).
"""

from __future__ import annotations

DASHBOARD_HTML = r"""<!doctype html>
<html>
<head>
<meta charset="utf-8">
<meta name="viewport" content="width=device-width, initial-scale=1">
<title>polyaxon_tpu — runs</title>
<style>
  :root {
    color-scheme: light;
    --page: #f9f9f7; --surface: #fcfcfb;
    --ink: #0b0b0b; --ink-2: #52514e; --muted: #898781;
    --grid: #e1e0d9; --axis: #c3c2b7; --ring: rgba(11,11,11,0.10);
    --series-1: #2a78d6; --series-2: #d07c2e; --series-3: #2f9e77;
    --series-4: #8e67c5; --series-5: #c5527a; --series-6: #8a8a2a;
    --status-good: #0ca30c; --status-warning: #fab219;
    --status-serious: #ec835a; --status-critical: #d03b3b;
  }
  @media (prefers-color-scheme: dark) {
    :root:not([data-theme="light"]) {
      color-scheme: dark;
      --page: #0d0d0d; --surface: #1a1a19;
      --ink: #ffffff; --ink-2: #c3c2b7; --muted: #898781;
      --grid: #2c2c2a; --axis: #383835; --ring: rgba(255,255,255,0.10);
      --series-1: #3987e5; --series-2: #e08a3a; --series-3: #37b389;
      --series-4: #a07ad6; --series-5: #d66a91; --series-6: #a3a33a;
    }
  }
  :root[data-theme="dark"] {
    color-scheme: dark;
    --page: #0d0d0d; --surface: #1a1a19;
    --ink: #ffffff; --ink-2: #c3c2b7; --muted: #898781;
    --grid: #2c2c2a; --axis: #383835; --ring: rgba(255,255,255,0.10);
    --series-1: #3987e5; --series-2: #e08a3a; --series-3: #37b389;
    --series-4: #a07ad6; --series-5: #d66a91; --series-6: #a3a33a;
  }
  * { box-sizing: border-box; }
  body { margin: 0; background: var(--page); color: var(--ink);
         font: 14px/1.45 system-ui, -apple-system, "Segoe UI", sans-serif; }
  header { display: flex; align-items: center; gap: 12px;
           padding: 14px 20px; border-bottom: 1px solid var(--ring); }
  header h1 { font-size: 16px; margin: 0; font-weight: 650; }
  header .spacer { flex: 1; }
  select, button, input[type="search"] {
    font: inherit; color: var(--ink); background: var(--surface);
    border: 1px solid var(--ring); border-radius: 6px; padding: 4px 10px;
  }
  select, button { cursor: pointer; }
  input[type="search"] { min-width: 180px; }
  main { padding: 16px 20px; max-width: 1100px; margin: 0 auto; }
  .tiles { display: flex; gap: 12px; flex-wrap: wrap; margin-bottom: 16px; }
  .tile { background: var(--surface); border: 1px solid var(--ring);
          border-radius: 8px; padding: 10px 16px; min-width: 120px; }
  .tile .v { font-size: 22px; font-weight: 650; }
  .tile .k { color: var(--ink-2); font-size: 12px; }
  .history { background: var(--surface); border: 1px solid var(--ring);
             border-radius: 8px; padding: 8px 14px; margin-bottom: 16px;
             font-size: 12px; }
  .history .k { color: var(--ink-2); margin-right: 10px; }
  .hist-line { display: inline-flex; gap: 8px; margin-right: 18px;
               align-items: baseline; }
  .hist-key { color: var(--muted); }
  .hist-spark { font-family: monospace; letter-spacing: 1px; }
  .hist-last { font-variant-numeric: tabular-nums; font-weight: 600; }
  table { width: 100%; border-collapse: collapse; background: var(--surface);
          border: 1px solid var(--ring); border-radius: 8px; overflow: hidden; }
  th { text-align: left; color: var(--muted); font-weight: 500; font-size: 12px; }
  th, td { padding: 7px 12px; border-bottom: 1px solid var(--grid); }
  td.num { font-variant-numeric: tabular-nums; }
  tr.run { cursor: pointer; }
  tr.run:hover td { background: color-mix(in srgb, var(--ink) 4%, transparent); }
  .pill { display: inline-flex; align-items: center; gap: 6px; font-size: 12px;
          color: var(--ink-2); }
  .pill .dot { width: 8px; height: 8px; border-radius: 50%; }
  #detail { margin-top: 20px; }
  .charts { display: grid; grid-template-columns: repeat(auto-fill, minmax(320px, 1fr));
            gap: 14px; margin-top: 10px; }
  .chart { background: var(--surface); border: 1px solid var(--ring);
           border-radius: 8px; padding: 10px 12px; }
  .chart h3 { margin: 0 0 4px; font-size: 13px; font-weight: 600; }
  .chart .sub { color: var(--muted); font-size: 11px; }
  .chart svg { display: block; width: 100%; height: 150px; }
  .chart .tbl { display: none; max-height: 150px; overflow: auto; }
  .chart.show-table svg { display: none; }
  .chart.show-table .tbl { display: block; }
  .chart .tools { float: right; }
  .chart .tools button { font-size: 11px; padding: 1px 7px; }
  .tooltip { position: fixed; pointer-events: none; background: var(--surface);
             border: 1px solid var(--ring); border-radius: 6px; padding: 4px 8px;
             font-size: 12px; box-shadow: 0 2px 8px rgba(0,0,0,.15); display: none;
             z-index: 10; }
  #logs { background: var(--surface); border: 1px solid var(--ring);
          border-radius: 8px; margin-top: 14px; padding: 10px 12px;
          max-height: 260px; overflow: auto; white-space: pre-wrap;
          font: 12px/1.5 ui-monospace, monospace; color: var(--ink-2); }
  a.uuid { color: var(--series-1); text-decoration: none; }
  .legend { display: flex; gap: 12px; flex-wrap: wrap; font-size: 12px;
            color: var(--ink-2); margin: 4px 0 2px; }
  .legend .key { display: inline-flex; align-items: center; gap: 5px; }
  .legend .swatch { width: 10px; height: 10px; border-radius: 2px; }
  .bracket { background: var(--surface); border: 1px solid var(--ring);
             border-radius: 8px; padding: 10px 14px; margin-top: 12px; }
  .bracket h3 { margin: 0 0 6px; font-size: 13px; font-weight: 600; }
  .rung { display: flex; align-items: baseline; gap: 10px; padding: 5px 0;
          border-top: 1px solid var(--grid); flex-wrap: wrap; }
  .rung .rname { min-width: 130px; color: var(--ink-2); font-size: 12px; }
  .chip { display: inline-flex; align-items: center; gap: 6px;
          background: color-mix(in srgb, var(--ink) 4%, transparent);
          border: 1px solid var(--grid); border-radius: 12px;
          padding: 2px 9px; font-size: 12px; cursor: pointer; }
  .chip:hover { border-color: var(--axis); }
  .chip .val { font-variant-numeric: tabular-nums; color: var(--ink); }
  td.cmp, th.cmp { width: 26px; padding-right: 0; }
  .dag svg { display: block; width: 100%; }
  .dag .dagnode { cursor: pointer; }
  .dag .dagnode.inert { cursor: default; }
  .dag .dagnode rect { fill: var(--surface); stroke-width: 1.5; rx: 7; }
  .dag .dagnode:hover rect { filter: brightness(1.06); }
  .dag .dagnode text { fill: var(--ink); font-size: 12px; }
  .dag .dagnode .st { fill: var(--ink-2); font-size: 10px; }
  .dag .edge { fill: none; stroke: var(--axis); stroke-width: 1.3; }
  .tl { background: var(--surface); border: 1px solid var(--ring);
        border-radius: 8px; padding: 10px 14px; margin-top: 12px; }
  .tl h3 { margin: 0 0 6px; font-size: 13px; font-weight: 600; }
  .tl-row { display: flex; align-items: center; gap: 8px; padding: 2px 0;
            font-size: 12px; }
  .tl-name { flex: 0 0 180px; overflow: hidden; text-overflow: ellipsis;
             white-space: nowrap; color: var(--ink-2); }
  .tl-track { position: relative; flex: 1; height: 12px;
              background: color-mix(in srgb, var(--ink) 4%, transparent);
              border-radius: 3px; }
  .tl-bar { position: absolute; top: 1px; height: 10px; min-width: 2px;
            border-radius: 3px; background: var(--series-1); }
  .tl-bar.err { background: var(--bad, #c0392b); }
  .tl-ev { position: absolute; top: 2px; width: 5px; height: 8px;
           border-radius: 50%; background: var(--ink-2); }
  .tl-ev.chaos { background: var(--bad, #c0392b); }
  .tl-dur { flex: 0 0 76px; text-align: right; color: var(--ink-2);
            font-variant-numeric: tabular-nums; }
  .alert { display: flex; gap: 10px; align-items: baseline;
           padding: 6px 10px; margin: 4px 0; border-radius: 6px;
           border-left: 4px solid var(--status-warning);
           background: color-mix(in srgb,
             var(--status-warning) 12%, transparent); }
  .alert.page { border-left-color: var(--status-critical);
                background: color-mix(in srgb,
                  var(--status-critical) 12%, transparent); }
  .alert .alert-val { font-variant-numeric: tabular-nums;
                      color: var(--ink-2); }
  .alert .alert-desc { color: var(--ink-2); }
</style>
</head>
<body>
<header>
  <h1>polyaxon_tpu</h1>
  <span class="spacer"></span>
  <input id="tokenBox" type="password" placeholder="API token" hidden
         aria-label="bearer token for an auth-enabled server">
  <input id="searchBox" type="search" placeholder="filter runs…"
         aria-label="filter runs by name, kind, uuid, or tag">
  <select id="projectFilter" aria-label="project filter">
    <option>default</option>
  </select>
  <select id="statusFilter" aria-label="status filter">
    <option value="">all statuses</option>
    <option>running</option><option>succeeded</option>
    <option>failed</option><option>stopped</option>
    <option>queued</option><option>preempted</option>
  </select>
  <button id="compareBtn" hidden>compare</button>
  <button id="refresh">refresh</button>
  <button id="themeToggle" aria-label="toggle theme">◐</button>
</header>
<main>
  <div class="tiles" id="tiles"></div>
  <div id="historyPanel"></div>
  <div id="alertsPanel" aria-live="polite"></div>
  <div id="projectPanel"></div>
  <div id="slicesPanel"></div>
  <table id="runs">
    <thead><tr>
      <th class="cmp" aria-label="compare"></th>
      <th>run</th><th>name</th><th>kind</th><th>project</th>
      <th>status</th><th>created</th>
    </tr></thead>
    <tbody></tbody>
  </table>
  <section id="detail"></section>
</main>
<div class="tooltip" id="tooltip"></div>
<script>
"use strict";
// Status → {color role, glyph}: icon + label always travel together.
const STATUS = {
  succeeded: ["var(--status-good)", "✓"],
  running:   ["var(--series-1)", "▶"],
  queued:    ["var(--muted)", "…"],
  scheduled: ["var(--muted)", "…"],
  starting:  ["var(--muted)", "…"],
  compiled:  ["var(--muted)", "…"],
  created:   ["var(--muted)", "…"],
  stopped:   ["var(--status-warning)", "■"],
  preempted: ["var(--status-warning)", "⏸"],
  failed:    ["var(--status-critical)", "✕"],
};
const $ = (sel, el) => (el || document).querySelector(sel);
// Auth-enabled servers (plx server --auth-token/--owner-token): the
// token lives in localStorage and rides every fetch; a 401 reveals
// the header's token box so the dashboard is usable without curl.
const getToken = () => localStorage.getItem("plx_token") || "";
const OWNER = localStorage.getItem("plx_owner") || "default";
const base = (project) => `/api/v1/${encodeURIComponent(OWNER)}/${encodeURIComponent(project || "default")}`;
// Header-less browser loads (img/a/EventSource) carry the credential
// as ?token= — the server accepts it on the artifacts + SSE routes.
// URLs leak into proxy logs/history/Referer, so they get a SHORT-LIVED
// derived stream token (minted over an authed header call, refreshed
// before expiry), never the primary secret. Until the first mint
// resolves, URLs fall back to the primary so nothing breaks.
let streamTok = "", streamTokExp = 0, streamTokPending = null;
function refreshStreamToken() {
  if (!getToken()) return Promise.resolve();
  if (streamTok && Date.now() < streamTokExp - 30000) return Promise.resolve();
  if (streamTokPending) return streamTokPending;
  streamTokPending = api("/api/v1/stream-token").then(d => {
    streamTok = d.token;
    streamTokExp = Date.now() + (d.expiresIn || 300) * 1000;
  }).catch(() => {}).finally(() => { streamTokPending = null; });
  return streamTokPending;
}
// First-paint ordering (ADVICE r5 #4): every URL-constructing render
// awaits the mint — retrying once on failure — BEFORE building its
// first SSE/artifact URLs, so the primary secret never rides a URL
// merely because the eager mint hadn't resolved yet. After two failed
// mints tokenQS still falls back to the primary (servers without the
// mint route would otherwise lose SSE/images entirely) — but that is
// now a capability fallback, not a race.
async function ensureStreamToken() {
  if (!getToken()) return;
  await refreshStreamToken();
  if (!(streamTok && Date.now() < streamTokExp)) await refreshStreamToken();
}
const tokenQS = (sep) => {
  if (!getToken()) return "";
  refreshStreamToken();  // async refill for the NEXT url
  const t = (streamTok && Date.now() < streamTokExp)
    ? streamTok : getToken();
  return `${sep}token=${encodeURIComponent(t)}`;
};
const api = (p) => fetch(p, getToken()
    ? {headers: {Authorization: `Bearer ${getToken()}`}} : {})
  .then(r => {
    if (r.status === 401) {
      const box = $("#tokenBox");
      if (box) box.hidden = false;
      throw new Error("401 (set the API token, top right)");
    }
    if (!r.ok) throw new Error(r.status);
    return r.json();
  });
// All user-controlled strings (run names, projects, metric names) go
// through esc() before any innerHTML interpolation — stored XSS guard.
const esc = (s) => String(s ?? "").replace(/[&<>"']/g,
  c => ({"&": "&amp;", "<": "&lt;", ">": "&gt;", '"': "&quot;", "'": "&#39;"}[c]));

function wireRunChips(root) {
  // role=button chips navigate on click AND Enter/Space — one wiring
  // for the sweep/bracket chips, DAG nodes, and slice-pool gangs.
  for (const chip of root.querySelectorAll(
      ".chip[data-uuid], .dagnode[data-uuid], .lingnode[data-uuid]")) {
    if (!chip.dataset.uuid) continue;  // unknown lineage node: inert
    chip.onclick = () => showRun(chip.dataset.uuid);
    chip.onkeydown = (ev) => {
      if (ev.key === "Enter" || ev.key === " ") {
        ev.preventDefault();
        showRun(chip.dataset.uuid);
      }
    };
  }
}

function pill(status) {
  const [color, glyph] = STATUS[status] || ["var(--muted)", "•"];
  return `<span class="pill"><span class="dot" style="background:${color}"></span>${glyph} ${esc(status)}</span>`;
}

function tile(k, v) {
  return `<div class="tile"><div class="v">${v}</div><div class="k">${k}</div></div>`;
}

let lastRows = [];      // last successful fetch — search filters this
let lastProjects = "";  // rendered option set, rebuilt only on change

// Alerts banner (obs.rules): firing alerts from /api/v1/alerts render
// above the run table — a degraded cluster announces itself before
// the operator goes digging. Quiet when nothing fires.
async function loadAlerts() {
  const el = $("#alertsPanel");
  let data;
  try { data = await api("/api/v1/alerts"); }
  catch (e) { return; }  // transient/auth failure: keep the last banner
  const firing = data.alerts || [];
  if (!firing.length) { el.innerHTML = ""; return; }
  el.innerHTML = `<div class="alerts">` + firing.map(a => `
    <div class="alert ${esc(a.severity)}" role="alert">
      <strong>${esc(a.rule)}</strong>
      <span class="alert-val">value=${esc(a.value)} threshold=${esc(a.threshold)}</span>
      <span class="alert-desc">${esc(a.description)}</span>
    </div>`).join("") + `</div>`;
}

// History tile (obs.history): a sparkline over the shared metrics-
// history ring — queue depth over the trailing 15m shows the operator
// the SHAPE of the backlog, not just its current number. Quiet until
// the ring has sampled the series.
const SPARK = "▁▂▃▄▅▆▇█";
function spark(values) {
  const lo = Math.min(...values), hi = Math.max(...values);
  if (!(hi - lo > 1e-12)) return SPARK[0].repeat(values.length);
  return values.map(v =>
    SPARK[Math.floor((v - lo) / (hi - lo) * (SPARK.length - 1))]).join("");
}
async function loadHistory() {
  const el = $("#historyPanel");
  let data;
  try {
    data = await api(
      "/api/v1/metrics/history?name=polyaxon_queue_depth&window=15m");
  } catch (e) { el.innerHTML = ""; return; }  // not sampled yet
  const series = (data.metric || {}).series || {};
  const lines = Object.entries(series).map(([key, pts]) => {
    const vals = pts.map(p =>
      typeof p[1] === "object" ? (p[1].count || 0) : p[1]);
    if (!vals.length) return "";
    return `<div class="hist-line"><span class="hist-key">${esc(key || "fleet")}</span><span class="hist-spark">${spark(vals)}</span><span class="hist-last">${vals[vals.length - 1]}</span></div>`;
  }).filter(Boolean);
  el.innerHTML = lines.length
    ? `<div class="history"><span class="k">queue depth · 15m</span>${lines.join("")}</div>`
    : "";
}

async function loadRuns() {
  const status = $("#statusFilter").value;
  const q = status ? `?status=${encodeURIComponent(status)}` : "";
  // The list route is project-scoped; the dropdown picks which one
  // (run DETAIL stays uuid-addressed, so everything else is unchanged).
  const projSel = $("#projectFilter");
  let projects;
  try { projects = (await api("/api/v1/projects")).map(p => p.name).sort(); }
  catch (e) { projects = null; }  // transient failure: keep the old list
  if (projects && projects.length && projects.join("\n") !== lastProjects) {
    // Rebuild only on a real change — an unconditional rebuild every
    // poll would close the dropdown under the user's cursor.
    const prev = projSel.value;
    const current = projects.includes(prev) ? prev : projects[0];
    projSel.innerHTML = projects.map(p =>
      `<option${p === current ? " selected" : ""}>${esc(p)}</option>`
    ).join("");
    lastProjects = projects.join("\n");
  }
  const project = projSel.value || "default";
  try {
    const data = await api(
      `${base(project)}/runs${q}`);
    lastRows = data.results || [];
  } catch (e) {
    // 401 already revealed the token box; a 403 means the credential
    // does not cover this owner path — surface it instead of showing
    // a silently empty table ("owner:token" scopes the dashboard).
    if (String(e.message).startsWith("403")) {
      const box = $("#tokenBox");
      box.hidden = false;
      box.placeholder = "owner:token (403 for this owner)";
    }
    return;  // otherwise transient: keep the last good table on screen
  }
  renderRuns();
  renderSlices();
  loadAlerts();
  loadHistory();
}

function renderRuns() {
  const keep = new Set(selectedRuns().map(r => r.uuid));  // survive refresh
  let rows = lastRows;
  // Free-text filter over name/kind/uuid/tags — purely client-side,
  // so keystrokes never trigger network I/O.
  const needle = $("#searchBox").value.trim().toLowerCase();
  if (needle)
    rows = rows.filter(r =>
      [r.name, r.kind, r.uuid, ...(r.tags || [])].some(
        v => String(v ?? "").toLowerCase().includes(needle)));
  const counts = {};
  for (const r of rows) counts[r.status] = (counts[r.status] || 0) + 1;
  // Project-level health tiles: success rate over terminal runs and
  // median wall time of succeeded runs, alongside the status counts.
  const terminal = rows.filter(r => r.finished_at);
  const ok = terminal.filter(r => r.status === "succeeded");
  const rate = terminal.length
    ? Math.round(100 * ok.length / terminal.length) + "%" : "–";
  const walls = ok.map(r => toEpoch(r.finished_at) - toEpoch(r.created_at))
    .filter(w => w >= 0).sort((a, b) => a - b);
  const med = walls.length ? fmtDur(walls[walls.length >> 1]) : "–";
  $("#tiles").innerHTML =
    tile("total", rows.length) +
    ["running", "succeeded", "failed"].map(s => tile(s, counts[s] || 0)).join("") +
    tile("success rate", rate) + tile("median wall", med);
  $("#projectPanel").innerHTML = projectPanel(rows);
  $("#runs tbody").innerHTML = rows.map(r => `
    <tr class="run" data-uuid="${esc(r.uuid)}">
      <td class="cmp"><input type="checkbox" class="cmpBox"
          data-uuid="${esc(r.uuid)}" data-name="${esc(r.name || String(r.uuid).slice(0, 8))}"
          aria-label="select for comparison"></td>
      <td><a class="uuid">${esc(String(r.uuid).slice(0, 12))}</a></td>
      <td>${esc(r.name)}</td><td>${esc(r.kind)}</td><td>${esc(r.project)}</td>
      <td>${pill(r.status)}</td>
      <td class="num">${isFinite(toEpoch(r.created_at)) ? new Date(toEpoch(r.created_at) * 1000).toLocaleString() : ""}</td>
    </tr>`).join("");
  for (const tr of document.querySelectorAll("tr.run"))
    tr.onclick = (ev) => {
      if (ev.target.classList.contains("cmpBox")) return;
      showRun(tr.dataset.uuid);
    };
  for (const box of document.querySelectorAll(".cmpBox")) {
    box.checked = keep.has(box.dataset.uuid);
    box.onchange = updateCompareBtn;
  }
  updateCompareBtn();
}

function toEpoch(v) {
  // Records serialize timestamps as ISO-8601 strings (store.py
  // isoformat); accept epoch numbers too. NaN for absent/unparsable.
  if (v == null) return NaN;
  if (typeof v === "number") return v;
  return Date.parse(v) / 1000;
}

function fmtDur(s) {
  if (s < 90) return Math.round(s) + "s";
  if (s < 5400) return (s / 60).toPrecision(2) + "m";
  return (s / 3600).toPrecision(2) + "h";
}

function projectPanel(rows) {
  // Project activity: runs created per day over the last 14 days,
  // stacked by outcome (succeeded / failed / other). Pure client-side
  // over the already-fetched list — no extra API round trips.
  const DAYS = 14, DAY = 86400;
  const today = Math.floor(Date.now() / 1000 / DAY);
  const buckets = Array.from({length: DAYS}, () => ({ok: 0, bad: 0, other: 0}));
  let seen = 0;
  for (const r of rows) {
    const created = toEpoch(r.created_at);
    if (!isFinite(created)) continue;
    const age = today - Math.floor(created / DAY);
    if (age < 0 || age >= DAYS) continue;
    seen++;
    const b = buckets[DAYS - 1 - age];
    if (r.status === "succeeded") b.ok++;
    else if (r.status === "failed" || r.status === "upstream_failed") b.bad++;
    else b.other++;
  }
  if (!seen) return "";
  const W = 980, H = 88, P = {l: 30, r: 6, t: 6, b: 16};
  const max = Math.max(...buckets.map(b => b.ok + b.bad + b.other), 1);
  const bw = (W - P.l - P.r) / DAYS;
  const sy = n => (H - P.t - P.b) * n / max;
  const bars = buckets.map((b, i) => {
    const x = P.l + i * bw + 2, w = Math.max(bw - 4, 2);
    let y = H - P.b;
    const seg = (n, color) => {
      if (!n) return "";
      const h = sy(n); y -= h;
      return `<rect x="${x}" y="${y}" width="${w}" height="${h}" fill="${color}" rx="1"/>`;
    };
    const day = new Date((today - (DAYS - 1 - i)) * DAY * 1000);
    const lbl = (i % 2 === 0)
      ? `<text x="${x + w / 2}" y="${H - 3}" text-anchor="middle" font-size="9" fill="var(--muted)">${day.getMonth() + 1}/${day.getDate()}</text>`
      : "";
    return seg(b.ok, "var(--status-good)") + seg(b.bad, "var(--status-critical)")
      + seg(b.other, "var(--muted)") + lbl;
  }).join("");
  const axis = `<text x="2" y="${P.t + 9}" font-size="9" fill="var(--muted)">${max}</text>
    <line x1="${P.l}" y1="${H - P.b}" x2="${W - P.r}" y2="${H - P.b}" stroke="var(--axis)" stroke-width="1"/>`;
  return `<div class="bracket">
    <h3>project activity · last ${DAYS} days · ${seen} runs</h3>
    <svg viewBox="0 0 ${W} ${H}" width="100%" height="${H}" role="img"
         aria-label="runs per day stacked by outcome">${axis}${bars}</svg>
  </div>`;
}

function selectedRuns() {
  return [...document.querySelectorAll(".cmpBox:checked")]
    .map(b => ({uuid: b.dataset.uuid, name: b.dataset.name}));
}

function updateCompareBtn() {
  const n = selectedRuns().length;
  const btn = $("#compareBtn");
  btn.hidden = n < 2;
  btn.textContent = `compare ${n} runs`;
}

// Shared chart geometry: one source of truth for scales, grid, and
// baseline across lineChart, overlayChart, and the tooltip math.
const CW = 320, CH = 150, CP = {l: 42, r: 10, t: 8, b: 20};
const fmtNum = v => Math.abs(v) >= 1000 ? v.toPrecision(4) : +v.toPrecision(3);

function chartFrame(points) {
  const xs = points.map(p => p.step), ys = points.map(p => p.value);
  const x0 = Math.min(...xs), x1 = Math.max(...xs);
  let y0 = Math.min(...ys), y1 = Math.max(...ys);
  if (y0 === y1) { y0 -= 1; y1 += 1; }
  const sx = s => CP.l + (CW - CP.l - CP.r) * (x1 === x0 ? 0.5 : (s - x0) / (x1 - x0));
  const sy = v => CH - CP.b - (CH - CP.t - CP.b) * ((v - y0) / (y1 - y0));
  const grid = [0, 0.5, 1].map(f => {
    const y = sy(y0 + f * (y1 - y0));
    return `<line x1="${CP.l}" y1="${y}" x2="${CW - CP.r}" y2="${y}" stroke="var(--grid)" stroke-width="1"/>
            <text x="${CP.l - 6}" y="${y + 4}" text-anchor="end" font-size="10" fill="var(--muted)">${fmtNum(y0 + f * (y1 - y0))}</text>`;
  }).join("");
  const baseline = `<line x1="${CP.l}" y1="${CH - CP.b}" x2="${CW - CP.r}" y2="${CH - CP.b}" stroke="var(--axis)" stroke-width="1"/>
    <text x="${CW - CP.r}" y="${CH - 6}" text-anchor="end" font-size="10" fill="var(--muted)">step ${x1}</text>`;
  const linePath = pts => pts.map((p, i) =>
    `${i ? "L" : "M"}${sx(p.step).toFixed(1)},${sy(p.value).toFixed(1)}`).join("");
  return {x0, x1, y0, y1, sx, sy, grid, baseline, linePath};
}

function lineChart(name, points) {
  // Single series per chart: the title names it, so no legend box.
  const W = CW, H = CH, P = CP, fmt = fmtNum;
  const ys = points.map(p => p.value);
  const f = chartFrame(points);
  const {x0, x1, grid} = f;
  const path = f.linePath(points);
  const table = `<table><thead><tr><th>step</th><th>value</th></tr></thead><tbody>
    ${points.map(p => `<tr><td class="num">${p.step}</td><td class="num">${fmt(p.value)}</td></tr>`).join("")}
  </tbody></table>`;
  return `<div class="chart" data-name="${esc(name)}">
    <div class="tools"><button class="toTable">table</button></div>
    <h3>${esc(name)}</h3>
    <div class="sub">${points.length} points · last ${fmt(ys[ys.length - 1])}</div>
    <svg viewBox="0 0 ${W} ${H}" data-points='${esc(JSON.stringify(points))}'
         data-x0="${x0}" data-x1="${x1}" role="img" aria-label="${esc(name)} over steps">
      ${grid}
      ${f.baseline}
      <path d="${path}" fill="none" stroke="var(--series-1)" stroke-width="2"
            stroke-linejoin="round" stroke-linecap="round"/>
      <line class="xhair" y1="${P.t}" y2="${H - P.b}" stroke="var(--axis)" stroke-width="1" visibility="hidden"/>
      <circle class="dot" r="4" fill="var(--series-1)" stroke="var(--surface)" stroke-width="2" visibility="hidden"/>
    </svg>
    <div class="tbl">${table}</div>
  </div>`;
}

function wireChart(el) {
  $(".toTable", el).onclick = () => el.classList.toggle("show-table");
  const svg = $("svg", el);
  if (!svg) return;
  const points = JSON.parse(svg.dataset.points);
  const tooltip = $("#tooltip");
  svg.addEventListener("mousemove", (ev) => {
    const rect = svg.getBoundingClientRect();
    const W = CW, P = CP;
    const fx = (ev.clientX - rect.left) / rect.width * W;
    const x0 = +svg.dataset.x0, x1 = +svg.dataset.x1;
    const step = x0 + (fx - P.l) / (W - P.l - P.r) * (x1 - x0);
    let best = points[0];
    for (const p of points) if (Math.abs(p.step - step) < Math.abs(best.step - step)) best = p;
    const ys = points.map(p => p.value);
    let y0 = Math.min(...ys), y1 = Math.max(...ys);
    if (y0 === y1) { y0 -= 1; y1 += 1; }
    const sx = P.l + (W - P.l - P.r) * (x1 === x0 ? 0.5 : (best.step - x0) / (x1 - x0));
    const sy = CH - P.b - (CH - P.t - P.b) * ((best.value - y0) / (y1 - y0));
    const xh = $(".xhair", svg), dot = $(".dot", svg);
    xh.setAttribute("x1", sx); xh.setAttribute("x2", sx); xh.setAttribute("visibility", "visible");
    dot.setAttribute("cx", sx); dot.setAttribute("cy", sy); dot.setAttribute("visibility", "visible");
    tooltip.style.display = "block";
    tooltip.style.left = (ev.clientX + 12) + "px";
    tooltip.style.top = (ev.clientY - 10) + "px";
    tooltip.textContent = `step ${best.step} · ${+best.value.toPrecision(4)}`;
  });
  svg.addEventListener("mouseleave", () => {
    tooltip.style.display = "none";
    $(".xhair", svg).setAttribute("visibility", "hidden");
    $(".dot", svg).setAttribute("visibility", "hidden");
  });
}

function histChart(name, ev) {
  // Single-hue bar chart of the latest histogram event: thin bars,
  // 2px surface gaps, baseline axis, per-bar hover via <title>.
  const W = 320, H = 150, P = {l: 42, r: 10, t: 8, b: 20};
  const counts = ev.counts, edges = ev.edges;
  const maxC = Math.max(...counts, 1);
  const bw = (W - P.l - P.r) / counts.length;
  const fmt = v => +Number(v).toPrecision(3);
  const bars = counts.map((c, i) => {
    const bh = (H - P.t - P.b) * (c / maxC);
    return `<rect x="${(P.l + i * bw + 1).toFixed(1)}" y="${(H - P.b - bh).toFixed(1)}"
      width="${Math.max(bw - 2, 1).toFixed(1)}" height="${bh.toFixed(1)}"
      rx="2" fill="var(--series-1)"><title>[${fmt(edges[i])}, ${fmt(edges[i + 1])}): ${c}</title></rect>`;
  }).join("");
  return `<div class="chart">
    <h3>${esc(name)}</h3>
    <div class="sub">histogram · ${counts.reduce((a, b) => a + b, 0)} values${ev.step != null ? ` · step ${ev.step}` : ""}</div>
    <svg viewBox="0 0 ${W} ${H}" role="img" aria-label="${esc(name)} histogram">
      <line x1="${P.l}" y1="${H - P.b}" x2="${W - P.r}" y2="${H - P.b}" stroke="var(--axis)" stroke-width="1"/>
      <text x="${P.l}" y="${H - 6}" font-size="10" fill="var(--muted)">${fmt(edges[0])}</text>
      <text x="${W - P.r}" y="${H - 6}" text-anchor="end" font-size="10" fill="var(--muted)">${fmt(edges[edges.length - 1])}</text>
      ${bars}
    </svg>
  </div>`;
}

function imageCard(uuid, name, ev) {
  // URL-encode each path segment (names may carry spaces/#/%), then
  // HTML-escape for the attribute context.
  const rel = String(ev.path).split("/").map(encodeURIComponent).join("/");
  const src = esc(`${base()}/runs/${encodeURIComponent(uuid)}/artifacts/${rel}${tokenQS('?')}`);
  return `<div class="chart">
    <h3>${esc(name)}</h3>
    <div class="sub">image${ev.step != null ? ` · step ${ev.step}` : ""}</div>
    <img src="${src}" alt="${esc(name)}" style="max-width:100%;border-radius:4px">
  </div>`;
}

function fmtSize(n) {
  if (n == null) return "";
  if (n >= 1 << 30) return (n / (1 << 30)).toFixed(2) + " GB";
  if (n >= 1 << 20) return (n / (1 << 20)).toFixed(1) + " MB";
  if (n >= 1024) return (n / 1024).toFixed(1) + " KB";
  return n + " B";
}

function artUrl(uuid, rel) {
  const enc = String(rel).split("/").map(encodeURIComponent).join("/");
  return `${base()}/runs/${encodeURIComponent(uuid)}/artifacts/${enc}${tokenQS('?')}`;
}

function artifactsPanel(uuid, lineage, files) {
  // Run-detail artifact browser: lineage records (kind/name/size) with
  // download links through the streams service, inline <img> for
  // image artifacts and open-in-tab for html (served with real
  // content types), plus the full file listing.
  if (!lineage.length && !files.length) return "";
  const isImg = (p) => /\.(png|jpe?g|gif|svg|webp)$/i.test(p);
  const isHtml = (p) => /\.html?$/i.test(p);
  const rows = lineage.map((r) => {
    const rel = r.rel_path;
    const label = esc(r.name || rel || "(external)");
    // Directories aren't downloadable through the file route — their
    // contents appear in the file listing below.
    const link = rel && !r.is_dir
      ? `<a class="uuid" href="${esc(artUrl(uuid, rel))}" download>${label}</a>`
      : label;
    let preview = "";
    if (r.is_dir) {
      preview = "";
    } else if (rel && isImg(rel)) {
      preview = `<img src="${esc(artUrl(uuid, rel))}" alt="${label}"
                   style="max-height:72px;border-radius:4px">`;
    } else if (rel && isHtml(rel)) {
      // Inline render, sandboxed twice over: iframe sandbox attr here
      // plus the server's CSP sandbox header on the artifact route —
      // run-produced html draws but cannot script or reach the API.
      preview = `<iframe src="${esc(artUrl(uuid, rel))}" sandbox
          title="${label}" loading="lazy"
          style="width:260px;height:120px;border:1px solid var(--axis);border-radius:4px;background:#fff"></iframe>
        <a class="uuid" href="${esc(artUrl(uuid, rel))}" target="_blank">open</a>`;
    }
    return `<tr><td>${esc(r.kind || "artifact")}</td><td>${link}</td>
      <td class="num">${fmtSize(r.size_bytes)}</td><td>${preview}</td></tr>`;
  }).join("");
  const MAX_FILES = 200;
  const fileRows = files.slice(0, MAX_FILES).map((f) =>
    `<tr><td><a class="uuid" href="${esc(artUrl(uuid, f.path))}" download>${esc(f.path)}</a></td>
     <td class="num">${fmtSize(f.size_bytes)}</td></tr>`).join("");
  return `<details class="chart" style="margin-top:14px" open>
    <summary style="cursor:pointer;font-weight:600;font-size:13px">artifacts
      <span class="sub">${files.length} file${files.length === 1 ? "" : "s"}${
        lineage.length ? ` · ${lineage.length} lineage record${lineage.length === 1 ? "" : "s"}` : ""}</span></summary>
    ${rows ? `<table style="margin-top:8px" aria-label="lineage artifacts">
      <tr><th>kind</th><th>artifact</th><th>size</th><th>preview</th></tr>${rows}</table>` : ""}
    ${fileRows ? `<div style="max-height:220px;overflow:auto;margin-top:8px">
      <table aria-label="artifact files"><tr><th>file</th><th>size</th></tr>${fileRows}</table></div>` : ""}
    ${files.length > MAX_FILES ? `<div class="sub">showing ${MAX_FILES} of ${files.length} files</div>` : ""}
  </details>`;
}

function fmtMs(ms) {
  if (ms == null) return "";
  return ms >= 1000 ? (ms / 1000).toFixed(2) + "s" : ms.toFixed(1) + "ms";
}

// Run-lifecycle waterfall over /runs/{uuid}/timeline (obs.trace):
// one bar per span (indented by tree depth, error spans red), chaos
// and retry annotations as dot markers ON the phase they hit.
function timelinePanel(tl) {
  if (!tl || !Array.isArray(tl.spans) || !tl.spans.length) return "";
  const t0 = tl.t0, total = Math.max(tl.duration_ms || 0, 1);
  const pct = (epoch) =>
    Math.max(0, Math.min(((epoch - t0) * 1000) / total * 100, 99.6));
  const rows = [];
  const walk = (s, depth) => {
    const width = Math.max((s.duration_ms || 0) / total * 100, 0.4);
    const events = (s.events || []).map((ev) => {
      const label = ev.name +
        (ev.attributes ? " " + JSON.stringify(ev.attributes) : "");
      return `<span class="tl-ev${/^chaos\\./.test(ev.name) ? " chaos" : ""}"` +
        ` style="left:${pct(ev.time).toFixed(2)}%" title="${esc(label)}"></span>`;
    }).join("");
    const title = s.name + (s.error ? " — " + s.error : "");
    rows.push(`<div class="tl-row">` +
      `<span class="tl-name" style="padding-left:${depth * 12}px"` +
      ` title="${esc(title)}">${esc(s.name)}</span>` +
      `<span class="tl-track"><span class="tl-bar${
        s.status === "error" ? " err" : ""}"` +
      ` style="left:${pct(s.start).toFixed(2)}%;width:${width.toFixed(2)}%">` +
      `</span>${events}</span>` +
      `<span class="tl-dur">${fmtMs(s.duration_ms)}</span></div>`);
    (s.children || []).forEach((c) => walk(c, depth + 1));
  };
  tl.spans.forEach((s) => walk(s, 0));
  const loose = (tl.events || []).map((ev) =>
    `<div class="tl-row"><span class="tl-name" title="${esc(ev.name)}">` +
    `* ${esc(ev.name)}</span><span class="tl-track">` +
    `<span class="tl-ev" style="left:${pct(ev.time).toFixed(2)}%"></span>` +
    `</span><span class="tl-dur">+${fmtMs((ev.time - t0) * 1000)}</span>` +
    `</div>`).join("");
  return `<div class="tl" aria-label="run lifecycle timeline">` +
    `<h3>timeline <span style="font-weight:400;color:var(--muted)">` +
    `${fmtMs(tl.duration_ms)} · ${tl.span_count} spans</span></h3>` +
    rows.join("") + loose + `</div>`;
}

function lineageGraphPanel(uuid, graph) {
  // Cross-run lineage: inputs → run → outputs as a three-column SVG
  // (upstream runs | this run + its artifact records | downstream
  // runs). Edge kinds: param ref, dag dependency, join match, cache
  // adoption. Run nodes navigate like every other chip.
  if (!graph || !graph.edges) return "";
  const ups = graph.edges.filter(e => e.to === uuid);
  const downs = graph.edges.filter(e => e.from === uuid);
  const arts = (graph.artifacts || []).slice(0, 8);
  const outs = Object.keys(graph.outputs || {}).slice(0, 8);
  if (!ups.length && !downs.length && !arts.length && !outs.length) return "";
  const byId = {};
  for (const n of graph.nodes || []) byId[n.uuid] = n;
  const ROW = 34, W = 640, COLW = 200, TOP = 26;
  // The right column stacks artifacts, outputs, AND downstream runs
  // sequentially — size for their SUM or the tail clips off the SVG.
  const rows = Math.max(
    ups.length, arts.length + outs.length + downs.length, 1);
  const H = TOP + rows * ROW + 10;
  const nodeBox = (x, y, n, edge) => {
    const name = esc((n && (n.name || n.uuid.slice(0, 8))) || "?");
    const color = n ? (STATUS[n.status] || ["var(--muted)"])[0] : "var(--muted)";
    const label = edge ? esc(edge.kind + (edge.label ? `:${edge.label}` : "")) : "";
    return `<g class="lingnode" data-uuid="${esc(n ? n.uuid : "")}" style="cursor:pointer">
      <rect x="${x}" y="${y}" width="${COLW - 24}" height="24" rx="5"
        fill="var(--surface-2, rgba(128,128,128,.12))" stroke="${color}"/>
      <text x="${x + 8}" y="${y + 16}" font-size="11" fill="currentColor">${name}</text>
      ${label ? `<text x="${x + COLW - 28}" y="${y + 16}" font-size="9" text-anchor="end" fill="var(--muted)">${label}</text>` : ""}
    </g>`;
  };
  const artBox = (x, y, label, kind) => `<g>
      <rect x="${x}" y="${y}" width="${COLW - 24}" height="24" rx="12"
        fill="none" stroke="var(--axis)" stroke-dasharray="3 2"/>
      <text x="${x + 8}" y="${y + 16}" font-size="10" fill="var(--muted)">${esc(kind)}: ${esc(label)}</text>
    </g>`;
  const midX = COLW + 20, rightX = 2 * COLW + 40;
  let svg = "";
  const midY = TOP + 4;
  // center: the run itself
  svg += `<rect x="${midX}" y="${midY}" width="${COLW - 24}" height="24" rx="5"
      fill="var(--series-1)" opacity="0.15"/>
    <rect x="${midX}" y="${midY}" width="${COLW - 24}" height="24" rx="5"
      fill="none" stroke="var(--series-1)"/>
    <text x="${midX + 8}" y="${midY + 16}" font-size="11" font-weight="600"
      fill="currentColor">${esc((byId[uuid] || {}).name || uuid.slice(0, 8))}</text>`;
  ups.forEach((e, i) => {
    const y = TOP + i * ROW;
    svg += nodeBox(10, y, byId[e.from], e);
    svg += `<line x1="${10 + COLW - 24}" y1="${y + 12}" x2="${midX}" y2="${midY + 12}"
      stroke="var(--axis)" marker-end="url(#lgarrow)"/>`;
  });
  // right column: artifacts/outputs first, then downstream runs
  let ri = 0;
  arts.forEach((a) => {
    const y = TOP + ri++ * ROW;
    svg += artBox(rightX, y, a.name || a.rel_path || "", a.kind || "artifact");
    svg += `<line x1="${midX + COLW - 24}" y1="${midY + 12}" x2="${rightX}" y2="${y + 12}"
      stroke="var(--axis)" stroke-dasharray="3 2"/>`;
  });
  outs.forEach((k) => {
    const y = TOP + ri++ * ROW;
    svg += artBox(rightX, y, k, "output");
    svg += `<line x1="${midX + COLW - 24}" y1="${midY + 12}" x2="${rightX}" y2="${y + 12}"
      stroke="var(--axis)" stroke-dasharray="3 2"/>`;
  });
  downs.forEach((e) => {
    const y = TOP + ri++ * ROW;
    svg += nodeBox(rightX, y, byId[e.to], e);
    svg += `<line x1="${midX + COLW - 24}" y1="${midY + 12}" x2="${rightX}" y2="${y + 12}"
      stroke="var(--axis)" marker-end="url(#lgarrow)"/>`;
  });
  return `<details class="chart" style="margin-top:14px" open id="lineageGraph">
    <summary style="cursor:pointer;font-weight:600;font-size:13px">lineage graph
      <span class="sub">${ups.length} upstream · ${arts.length + outs.length} artifacts/outputs · ${downs.length} downstream</span></summary>
    <svg viewBox="0 0 ${W} ${H}" role="img" aria-label="cross-run lineage graph"
         style="max-width:100%">
      <defs><marker id="lgarrow" viewBox="0 0 8 8" refX="7" refY="4"
        markerWidth="6" markerHeight="6" orient="auto">
        <path d="M0,0 L8,4 L0,8 z" fill="var(--axis)"/></marker></defs>
      <text x="10" y="14" font-size="10" fill="var(--muted)">inputs</text>
      <text x="${midX}" y="14" font-size="10" fill="var(--muted)">run</text>
      <text x="${rightX}" y="14" font-size="10" fill="var(--muted)">outputs</text>
      ${svg}
    </svg>
  </details>`;
}

const SERIES = [1, 2, 3, 4, 5, 6].map(i => `var(--series-${i})`);

function overlayChart(name, seriesList) {
  // Multi-run overlay: one line per run over a shared scale; legend
  // below the title carries the color key (marks only get color).
  const all = seriesList.flatMap(s => s.points);
  if (!all.length) return "";
  const f = chartFrame(all);
  const paths = seriesList.map((s, i) =>
    `<path d="${f.linePath(s.points)}" fill="none" stroke="${SERIES[i % SERIES.length]}"
      stroke-width="2" stroke-linejoin="round" stroke-linecap="round">
      <title>${esc(s.label)}</title></path>`).join("");
  const legend = seriesList.map((s, i) =>
    `<span class="key"><span class="swatch" style="background:${SERIES[i % SERIES.length]}"></span>${esc(s.label)}</span>`).join("");
  return `<div class="chart">
    <h3>${esc(name)}</h3>
    <div class="legend">${legend}</div>
    <svg viewBox="0 0 ${CW} ${CH}" role="img" aria-label="${esc(name)} across runs">
      ${f.grid}
      ${f.baseline}
      ${paths}
    </svg>
  </div>`;
}

async function compareRuns() {
  const sel = selectedRuns();
  const detail = $("#detail");
  const gen = ++renderGen;
  stopDetailTimers();
  const fetched = await Promise.all(sel.map(async r => ({
    ...r,
    metrics: await api(`${base()}/runs/${r.uuid}/metrics`).catch(() => ({})),
  })));
  if (gen !== renderGen) return;  // user navigated mid-fetch
  const names = [...new Set(fetched.flatMap(f => Object.keys(f.metrics)))].sort();
  const charts = names.map(name => overlayChart(
    name,
    fetched
      .map(f => ({label: f.name, points: f.metrics[name] || []}))
      .filter(s => s.points.length)
  )).join("");
  detail.innerHTML = `
    <h2 style="font-size:15px">comparing ${sel.length} runs</h2>
    ${paramDiffTable(sel)}
    <div class="charts">${charts ||
      "<div class='sub' style='color:var(--muted)'>no shared metrics yet</div>"}</div>`;
  detail.scrollIntoView({behavior: "smooth"});
}

function paramDiffTable(sel) {
  // The question a sweep comparison answers is "what was different?":
  // one row per param whose value VARIES across the selected runs
  // (op-level params + meta.trial_params), identical params omitted.
  const uuids = new Set(sel.map(r => r.uuid));
  const rows = lastRows.filter(r => uuids.has(r.uuid));
  if (rows.length < 2) return "";
  const valsOf = r => {
    const out = {};
    for (const [k, v] of Object.entries(r.params || {}))
      out[k] = (v && typeof v === "object" && "value" in v) ? v.value : v;
    Object.assign(out, (r.meta || {}).trial_params || {});
    return out;
  };
  const perRun = rows.map(r => ({
    label: r.name || String(r.uuid).slice(0, 8), vals: valsOf(r)}));
  const keys = [...new Set(perRun.flatMap(p => Object.keys(p.vals)))].sort();
  const differing = keys.filter(k => new Set(
    perRun.map(p => JSON.stringify(p.vals[k]))).size > 1);
  if (!differing.length) return "";
  const fmt = v => v === undefined ? "–"
    // Integers render EXACTLY (this table's one job is showing the
    // difference; 16384 must not display as 16380); floats get
    // 6 significant digits.
    : typeof v === "number"
      ? (Number.isInteger(v) ? String(v) : String(+v.toPrecision(6)))
      : esc(String(v));
  const head = `<tr><th>param</th>${perRun.map(p =>
    `<th>${esc(p.label)}</th>`).join("")}</tr>`;
  const body = differing.map(k => `<tr><td>${esc(k)}</td>${perRun.map(p =>
    `<td class="num">${fmt(p.vals[k])}</td>`).join("")}</tr>`).join("");
  return `<div class="bracket"><h3>differing params</h3>
    <table><thead>${head}</thead><tbody>${body}</tbody></table></div>`;
}

function fmtParams(params) {
  return Object.entries(params || {})
    .map(([k, v]) => {
      // Op-level params are stored as V1Param dicts: unwrap .value.
      if (v && typeof v === "object" && "value" in v) v = v.value;
      return `${k}=${typeof v === "number" ? +v.toPrecision(3) : v}`;
    })
    .join(" ");
}

// Outputs of terminal trials never change: cache them across the 5s
// live rerenders so an N-trial sweep costs O(live trials) per tick.
const outputsCache = new Map();  // uuid -> outputs (terminal only)

async function sweepView(run) {
  // Hyperband bracket / rung visualization: children grouped by
  // (bracket, rung) with live trial statuses and observed metric.
  const children = (await api(
    `${base()}/runs?pipeline=${encodeURIComponent(run.uuid)}`
  ).catch(() => ({results: []}))).results || [];
  if (!children.length) return "";
  const metricName = run.spec?.matrix?.metric?.name;
  const maximize = run.spec?.matrix?.metric?.optimization === "maximize";
  const outputs = await Promise.all(children.map(async c => {
    if (outputsCache.has(c.uuid)) return outputsCache.get(c.uuid);
    const out = await api(
      `${base()}/runs/${c.uuid}/outputs`).catch(() => ({}));
    if (TERMINAL.has(c.status)) outputsCache.set(c.uuid, out);
    return out;
  }));
  const trials = children.map((c, i) => {
    const out = outputs[i] || {};
    const val = metricName != null
      ? (out[`final_${metricName}`] ?? out[metricName])
      : Object.entries(out).find(([k]) => k.startsWith("final_"))?.[1];
    return {...c, metric: typeof val === "number" ? val : null};
  });
  const groups = new Map();
  for (const t of trials) {
    const b = t.meta?.bracket, r = t.meta?.rung;
    const key = b != null ? `bracket ${b} · rung ${r}` : "trials";
    if (!groups.has(key)) groups.set(key, []);
    groups.get(key).push(t);
  }
  const rows = [...groups.entries()].map(([label, ts]) => {
    // Best trial first, honoring the sweep's optimization direction.
    ts.sort((a, b) => {
      const worst = maximize ? -Infinity : Infinity;
      const av = a.metric ?? worst, bv = b.metric ?? worst;
      return maximize ? bv - av : av - bv;
    });
    const chips = ts.map(t => `
      <span class="chip" data-uuid="${esc(t.uuid)}" role="button" tabindex="0">
        ${pill(t.status)} ${esc(fmtParams(t.meta?.trial_params || t.params))}
        ${t.metric != null ? `<span class="val">${+t.metric.toPrecision(4)}</span>` : ""}
      </span>`).join("");
    return `<div class="rung"><span class="rname">${esc(label)} · ${ts.length} trials</span>${chips}</div>`;
  }).join("");
  return `<div class="bracket">
    <h3>sweep${metricName ? ` · ${maximize ? "maximizing" : "minimizing"} ${esc(metricName)}` : ""}</h3>
    ${rows}
  </div>`;
}

async function renderSlices() {
  // The C++ slice pool's operator view: per-slice chip occupancy and
  // placed gangs. Hidden entirely when no agent manages slices.
  const data = await api("/api/v1/agent/slices").catch(() => null);
  const el = $("#slicesPanel");
  if (!data || !data.slices || !data.slices.length) { el.innerHTML = ""; return; }
  const byslice = {};
  for (const g of data.gangs || [])
    (byslice[g.slice] = byslice[g.slice] || []).push(g);
  el.innerHTML = `<div class="bracket"><h3>TPU slice pool</h3>` +
    data.slices.map(s => {
      const used = s.total_chips - s.free_chips;
      const gangs = (byslice[s.name] || []).map(g =>
        `<span class="chip" data-uuid="${esc(g.run_uuid)}" role="button"
           tabindex="0">${pill(g.state)} ${esc(String(g.run_uuid).slice(0, 8))}
           · ${esc(g.topology)}${g.restarts ? ` · ↻${g.restarts}` : ""}</span>`
      ).join("");
      return `<div class="rung"><span class="rname">${esc(s.name)}
          · ${esc(s.topology)}${s.preemptible ? " · spot" : ""}</span>
        <span class="val">${used}/${s.total_chips} chips</span>${gangs}</div>`;
    }).join("") + "</div>";
  wireRunChips(el);
}

async function dagView(run) {
  // Pipeline graph: nodes from the dag spec's operations, statuses
  // from the child runs (created lazily as upstreams finish — a node
  // with no child yet renders as pending). Upstream's flow viz, lite.
  const ops = run.spec?.component?.run?.operations || [];
  if (!ops.length) return "";
  const children = (await api(
    `${base()}/runs?pipeline=${encodeURIComponent(run.uuid)}`
  ).catch(() => ({results: []}))).results || [];
  const byName = new Map(children.map(c => [c.name, c]));
  // Longest-path layering (deps are validated acyclic at submit).
  const deps = new Map(ops.map(o => [o.name, o.dependencies || []]));
  const layerOf = new Map();
  const layer = (name, seen) => {
    if (layerOf.has(name)) return layerOf.get(name);
    if (!seen) seen = new Set();
    if (seen.has(name) || !deps.has(name)) return 0;
    seen.add(name);
    const ds = deps.get(name);
    const v = ds.length ? 1 + Math.max(...ds.map(d => layer(d, seen))) : 0;
    layerOf.set(name, v);
    return v;
  };
  const W = 150, H = 40, GX = 70, GY = 18, PAD = 14;
  const cols = new Map();  // layer -> next row index
  const pos = new Map();
  for (const o of ops) {
    const l = layer(o.name);
    const row = cols.get(l) || 0;
    cols.set(l, row + 1);
    pos.set(o.name, {x: PAD + l * (W + GX), y: PAD + row * (H + GY)});
  }
  const width = PAD * 2 + (Math.max(...[...layerOf.values(), 0]) + 1) * (W + GX) - GX;
  const height = PAD * 2 + Math.max(...[...cols.values()]) * (H + GY) - GY;
  const edges = ops.flatMap(o => (deps.get(o.name) || []).map(d => {
    const a = pos.get(d), b = pos.get(o.name);
    if (!a || !b) return "";
    const x1 = a.x + W, y1 = a.y + H / 2, x2 = b.x, y2 = b.y + H / 2;
    const mx = (x1 + x2) / 2;
    return `<path class="edge" marker-end="url(#dagarrow)"
      d="M ${x1} ${y1} C ${mx} ${y1}, ${mx} ${y2}, ${x2 - 4} ${y2}"/>`;
  })).join("");
  const nodes = ops.map(o => {
    const c = byName.get(o.name);
    const status = c ? c.status : "pending";
    const [color, glyph] = STATUS[status] || ["var(--muted)", "•"];
    const p = pos.get(o.name);
    const label = o.name.length > 18 ? o.name.slice(0, 17) + "…" : o.name;
    // Only nodes with a child run are interactive: a pending node as a
    // focusable dead "button" misleads keyboard/screen-reader users.
    const act = c ? `data-uuid="${esc(c.uuid)}" role="button" tabindex="0"` : "";
    return `<g class="dagnode${c ? "" : " inert"}" ${act}
        aria-label="${esc(o.name)}: ${esc(status)}">
      <rect x="${p.x}" y="${p.y}" width="${W}" height="${H}" rx="7"
            stroke="${color}"/>
      <text x="${p.x + 10}" y="${p.y + 17}">${esc(label)}</text>
      <text class="st" x="${p.x + 10}" y="${p.y + 31}">${glyph} ${esc(status)}</text>
    </g>`;
  }).join("");
  return `<div class="bracket dag"><h3>pipeline · ${ops.length} operations</h3>
    <svg viewBox="0 0 ${width} ${height}" style="height:${Math.min(height, 420)}px"
         aria-label="pipeline graph">
      <defs><marker id="dagarrow" viewBox="0 0 8 8" refX="7" refY="4"
        markerWidth="7" markerHeight="7" orient="auto">
        <path d="M 0 0 L 8 4 L 0 8 z" fill="var(--axis)"/></marker></defs>
      ${edges}${nodes}
    </svg></div>`;
}

let detailTimer = null;
// Monotonic render generation: an in-flight fetch chain whose gen is
// stale (user navigated meanwhile) must not touch the DOM.
let renderGen = 0;
function stopDetailTimers() {
  if (detailTimer) { clearTimeout(detailTimer); detailTimer = null; }
  if (logSource) { logSource.close(); logSource = null; }
}

let logSource = null;
const TERMINAL = new Set(["succeeded", "failed", "stopped", "upstream_failed", "skipped"]);
async function showRun(uuid, opts) {
  const rerender = opts && opts.rerender;
  const detail = $("#detail");
  const gen = ++renderGen;
  stopDetailTimers();
  // Stream token BEFORE any tokenQS-built URL below (img/artifact
  // hrefs, the logs EventSource) — see ensureStreamToken.
  await ensureStreamToken();
  const [run, metrics, images, hists] = await Promise.all([
    api(`${base()}/runs/${uuid}`),
    api(`${base()}/runs/${uuid}/metrics`).catch(() => ({})),
    api(`${base()}/runs/${uuid}/events?kind=image`).catch(() => ({})),
    api(`${base()}/runs/${uuid}/events?kind=histogram`).catch(() => ({})),
  ]);
  const isSweep = run.kind === "matrix";
  const isDag = run.kind === "dag";
  const isPipeline = isSweep || isDag;
  // Artifact listing stats the whole run tree server-side — skip it
  // for pipelines (their artifacts live in child runs) so the 5 s live
  // rerender loop doesn't re-walk the tree forever.
  const [lineage, files, lingraph, timeline] = isPipeline
    ? [[], [], null, null]
    : await Promise.all([
    api(`${base()}/runs/${uuid}/lineage`).catch(() => []),
    api(`${base()}/runs/${uuid}/artifacts?detail=1`).catch(() => []),
    api(`${base()}/runs/${uuid}/lineage/graph`).catch(() => null),
    api(`${base()}/runs/${uuid}/timeline`).catch(() => null),
  ]);
  const sweep = isSweep ? await sweepView(run)
    : isDag ? await dagView(run) : "";
  if (gen !== renderGen) return;  // user navigated mid-fetch
  const charts = Object.entries(metrics)
    .filter(([, pts]) => Array.isArray(pts) && pts.length)
    .map(([name, pts]) => lineChart(name, pts)).join("");
  const media =
    Object.entries(hists).filter(([, evs]) => evs.length)
      .map(([name, evs]) => histChart(name, evs[evs.length - 1])).join("") +
    Object.entries(images).filter(([, evs]) => evs.length)
      .map(([name, evs]) => imageCard(uuid, name, evs[evs.length - 1])).join("");
  detail.innerHTML = `
    <h2 style="font-size:15px">${esc(run.name || run.uuid)} ${pill(run.status)}</h2>
    ${sweep}
    <div class="charts">${charts || (isPipeline ? "" : "<div class='sub' style='color:var(--muted)'>no metrics yet</div>")}</div>
    ${media ? `<div class="charts">${media}</div>` : ""}
    ${timelinePanel(timeline)}
    ${artifactsPanel(uuid, Array.isArray(lineage) ? lineage : [],
                     Array.isArray(files) ? files : [])}
    ${lineageGraphPanel(uuid, lingraph)}
    <div id="logs" aria-label="run logs"${isPipeline ? " hidden" : ""}></div>`;
  for (const el of detail.querySelectorAll(".chart")) wireChart(el);
  wireRunChips(detail);
  if (!isPipeline) {
    const logs = $("#logs");
    // EventSource cannot set headers; the SSE route accepts ?token=
    // (a short-lived stream token when one is minted — see tokenQS).
    logSource = new EventSource(`/streams/v1/${encodeURIComponent(OWNER)}/default/runs/${uuid}/logs?follow=true${tokenQS("&")}`);
    logSource.onmessage = (ev) => { logs.textContent += ev.data + "\n"; logs.scrollTop = logs.scrollHeight; };
    logSource.addEventListener("done", () => { logSource.close(); logSource = null; });
  } else if (!TERMINAL.has(run.status)) {
    // Live pipeline (sweep or dag): re-render while children advance.
    detailTimer = setTimeout(() => showRun(uuid, {rerender: true}), 5000);
  }
  if (!rerender) detail.scrollIntoView({behavior: "smooth"});
}

$("#refresh").onclick = loadRuns;
$("#tokenBox").onchange = () => {
  const v = $("#tokenBox").value.trim();
  // "owner:token" scopes the dashboard to that owner's paths (scoped
  // credentials are path-isolated); a bare value is the admin token.
  const sep = v.indexOf(":");
  if (sep > 0) {
    localStorage.setItem("plx_owner", v.slice(0, sep));
    localStorage.setItem("plx_token", v.slice(sep + 1));
  } else if (v) {
    localStorage.removeItem("plx_owner");
    localStorage.setItem("plx_token", v);
  } else {
    localStorage.removeItem("plx_owner");
    localStorage.removeItem("plx_token");
  }
  location.reload();  // reinitialize every surface (incl. SSE streams)
};
$("#statusFilter").onchange = loadRuns;
$("#projectFilter").onchange = loadRuns;
$("#searchBox").oninput = () => {  // debounced; no network round-trip
  clearTimeout(window._searchTimer);
  window._searchTimer = setTimeout(renderRuns, 150);
};
$("#compareBtn").onclick = compareRuns;
$("#themeToggle").onclick = () => {
  const root = document.documentElement;
  const dark = getComputedStyle(document.body).colorScheme.includes("dark");
  root.dataset.theme = dark ? "light" : "dark";
};
refreshStreamToken();  // mint eagerly so first img/SSE urls use it
loadRuns();
setInterval(loadRuns, 10000);
</script>
</body>
</html>
"""
