"""System-metrics processors: host (psutil) + TPU (libtpu / device API).

Parity: traceml's processors thread samples psutil + NVML every N s
(SURVEY.md §5.1 [K]); the TPU build replaces NVML with two layers of
TPU metrics (SURVEY §2a note 3):

- ``device.memory_stats()`` (PJRT) — HBM usage, portable everywhere;
- the **libtpu monitoring SDK** (``libtpu.sdk.tpumonitoring``) — duty
  cycle, TensorCore utilization, ICI link health, throttle score —
  probed behind import guards and a one-time availability latch, so
  hosts without real TPU hardware (or with an older libtpu) degrade
  silently to psutil + HBM.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

import psutil

logger = logging.getLogger(__name__)


def host_metrics() -> dict[str, float]:
    vm = psutil.virtual_memory()
    disk = psutil.disk_usage("/")
    out = {
        "cpu_percent": psutil.cpu_percent(interval=None),
        "memory_used_gb": vm.used / 2**30,
        "memory_percent": vm.percent,
        "disk_used_percent": disk.percent,
    }
    try:
        load1, _, _ = psutil.getloadavg()
        out["load_1m"] = load1
    except OSError:
        pass
    return out


def tpu_metrics() -> dict[str, float]:
    """Best-effort per-device metrics from the PJRT client; keys are
    ``tpu<i>_*``. Empty off-TPU or when the plugin exposes no stats."""
    out: dict[str, float] = {}
    try:
        import jax

        for i, dev in enumerate(jax.local_devices()):
            if dev.platform != "tpu":
                continue
            try:
                stats = dev.memory_stats() or {}
            except Exception as exc:
                logger.debug("tpu%d memory_stats unavailable: %s", i, exc)
                continue
            in_use = stats.get("bytes_in_use")
            limit = stats.get("bytes_limit") or stats.get("bytes_reservable_limit")
            if in_use is not None:
                out[f"tpu{i}_hbm_used_gb"] = in_use / 2**30
            if in_use is not None and limit:
                out[f"tpu{i}_hbm_percent"] = 100.0 * in_use / limit
            peak = stats.get("peak_bytes_in_use")
            if peak is not None:
                out[f"tpu{i}_hbm_peak_gb"] = peak / 2**30
    except Exception as exc:
        logger.debug("tpu metrics sample failed: %s", exc)
    return out


# libtpu metric name → emitted key prefix. Values parse per-chip where
# the SDK reports lists. Unavailable metrics (older libtpu, no real
# chip) are skipped per-name; a failing SDK disables itself once.
_LIBTPU_METRICS = {
    "duty_cycle_pct": "tpu{i}_duty_cycle_pct",
    "tensorcore_util": "tpu{i}_tensorcore_util",
    "ici_link_health": "tpu{i}_ici_link_health",
    "tpu_throttle_score": "tpu{i}_throttle_score",
}
_libtpu_state: dict = {"disabled": False}


def libtpu_metrics() -> dict[str, float]:
    """Duty cycle / TensorCore utilization / ICI link health via the
    libtpu monitoring SDK — the metrics NVML provides upstream (SURVEY
    §5.1). Best-effort: returns {} without real TPU hardware. A raising
    SDK latches disabled so the sampler never retries a dead surface;
    per-metric failures (unsupported on this libtpu) skip that metric
    only."""
    out: dict[str, float] = {}
    if _libtpu_state["disabled"]:
        return out
    try:
        from libtpu.sdk import tpumonitoring
    except Exception:
        _libtpu_state["disabled"] = True
        return out
    try:
        supported = _libtpu_state.get("supported")
        if supported is None:
            supported = set(tpumonitoring.list_supported_metrics())
            _libtpu_state["supported"] = supported
    except Exception:
        _libtpu_state["disabled"] = True
        return out
    for name, key_fmt in _LIBTPU_METRICS.items():
        if name not in supported:
            continue
        try:
            data = tpumonitoring.get_metric(name).data()
        except Exception as exc:
            # snapshot unavailable right now; not fatal
            logger.debug("libtpu metric %s unavailable: %s", name, exc)
            continue
        for i, raw in enumerate(data if isinstance(data, (list, tuple))
                                else [data]):
            try:
                out[key_fmt.format(i=i)] = float(raw)
            except (TypeError, ValueError):
                continue
    return out


class SystemMetricsMonitor:
    """Background sampler thread; emits through a callback (the tracking
    Run wires it to ``log_metrics(kind='system')``)."""

    def __init__(
        self,
        emit: Callable[[dict[str, float]], None],
        interval_seconds: float = 10.0,
        include_tpu: bool = True,
    ):
        self.emit = emit
        self.interval = interval_seconds
        self.include_tpu = include_tpu
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def sample(self) -> dict[str, float]:
        metrics = host_metrics()
        if self.include_tpu:
            metrics.update(tpu_metrics())
            metrics.update(libtpu_metrics())
        return metrics

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.emit(self.sample())
            except Exception as exc:
                # sampling must never kill the training process
                logger.debug("system metrics sample dropped: %s", exc)

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._loop, name="plx-sysmetrics", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
