from polyaxon_tpu.runtime.config import RuntimeConfig
from polyaxon_tpu.runtime.loop import TrainResult, run_jaxjob

__all__ = ["RuntimeConfig", "TrainResult", "run_jaxjob"]
