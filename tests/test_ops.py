"""Attention ops: flash (Pallas), ring (cp), ulysses (all-to-all) vs the
einsum reference. Runs on the 8-device virtual CPU mesh (conftest), the
same way the driver's dryrun validates sharding."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from polyaxon_tpu.ops.attention import dot_product_attention, xla_attention
from polyaxon_tpu.ops.flash import flash_attention
from polyaxon_tpu.ops.ring import ring_attention
from polyaxon_tpu.ops.ulysses import ulysses_attention


def _qkv(b=2, s=256, h=4, kv=2, d=64, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    q = jax.random.normal(ks[0], (b, s, h, d), dtype)
    k = jax.random.normal(ks[1], (b, s, kv, d), dtype)
    v = jax.random.normal(ks[2], (b, s, kv, d), dtype)
    return q, k, v


class TestFlash:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, causal):
        q, k, v = _qkv()
        ref = xla_attention(q, k, v, causal=causal)
        out = flash_attention(q, k, v, causal=causal, block_q=128, block_k=128)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_gqa_grouping(self):
        q, k, v = _qkv(h=8, kv=2)
        ref = xla_attention(q, k, v, causal=True)
        out = flash_attention(q, k, v, causal=True, block_q=128, block_k=128)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_gradients_match(self):
        q, k, v = _qkv()

        def loss(fn):
            return lambda q, k, v: jnp.sum(
                fn(q, k, v) ** 2
            )

        gf = jax.grad(loss(lambda *a: flash_attention(*a, block_q=128, block_k=128)),
                      argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss(lambda *a: xla_attention(*a)), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(a, b, atol=5e-4, rtol=5e-4)

    def test_sliding_window_matches_band_mask(self):
        """xla window path equals an explicit band-mask softmax, and the
        Pallas kernel (block skipping + in-block band) matches it."""
        q, k, v = _qkv(s=256)
        W = 64

        # Explicit reference: full logits with a band mask.
        from polyaxon_tpu.ops.attention import repeat_kv

        kf, vf = repeat_kv(k, 2), repeat_kv(v, 2)
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, kf) * (64 ** -0.5)
        rows = jnp.arange(256)[:, None]
        cols = jnp.arange(256)[None, :]
        band = (rows >= cols) & (rows - cols < W)
        logits = jnp.where(band[None, None], logits, -1e30)
        ref = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(logits, -1), vf)

        out_xla = xla_attention(q, k, v, causal=True, window=W)
        np.testing.assert_allclose(out_xla, ref, atol=2e-5, rtol=2e-5)
        out_flash = flash_attention(q, k, v, causal=True, window=W,
                                    block_q=128, block_k=128)
        np.testing.assert_allclose(out_flash, ref, atol=2e-5, rtol=2e-5)

    def test_sliding_window_gradients_match(self):
        q, k, v = _qkv(s=256)

        def loss(fn):
            return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

        gf = jax.grad(loss(lambda *a: flash_attention(
            *a, window=64, block_q=128, block_k=128)), argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss(lambda *a: xla_attention(*a, window=64)),
                      argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(a, b, atol=5e-4, rtol=5e-4)

    def test_sliding_window_decode_matches_forward(self):
        """Cache decode with a window reproduces windowed teacher-forced
        logits at the last position."""
        import dataclasses

        from polyaxon_tpu.models import llama

        cfg = dataclasses.replace(llama.CONFIGS["llama_tiny"],
                                  dtype=jnp.float32, sliding_window=8)
        variables = llama.init(cfg, jax.random.key(0))
        toks = jax.random.randint(jax.random.key(1), (2, 24), 0, cfg.vocab_size)
        full = llama.forward(cfg, variables["params"], toks)
        logits, cache = llama.prefill(cfg, variables["params"], toks[:, :-1], 24)
        step_logits, _ = llama.decode_step(
            cfg, variables["params"], cache, toks[:, -1], jnp.int32(23))
        np.testing.assert_allclose(step_logits, full[:, -1], atol=2e-4,
                                   rtol=2e-4)

    def test_packed_segments_match_reference(self):
        """Flash with segment_ids equals the einsum reference's packed
        mask, forward and gradients — including combined with causal."""
        q, k, v = _qkv(s=256)
        seg = jnp.asarray(
            [[0] * 100 + [1] * 156, [0] * 200 + [1] * 56], jnp.int32)

        ref = xla_attention(q, k, v, causal=True, segment_ids=seg)
        out = flash_attention(q, k, v, causal=True, segment_ids=seg,
                              block_q=128, block_k=128)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

        def loss(fn):
            return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

        gf = jax.grad(loss(lambda *a: flash_attention(
            *a, segment_ids=seg, block_q=128, block_k=128)),
            argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss(lambda *a: xla_attention(*a, segment_ids=seg)),
                      argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(a, b, atol=5e-4, rtol=5e-4)

    def test_packed_plus_window_matches_reference(self):
        q, k, v = _qkv(s=256)
        seg = jnp.asarray([[0] * 128 + [1] * 128] * 2, jnp.int32)
        ref = xla_attention(q, k, v, causal=True, segment_ids=seg, window=32)
        out = flash_attention(q, k, v, causal=True, segment_ids=seg,
                              window=32, block_q=128, block_k=128)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_rolling_cache_matches_full_forward_across_wraps(self):
        """Sliding-window decode uses an O(window) ring-buffer cache;
        greedy generation must match feeding the growing sequence through
        the full windowed forward pass — across several ring wraps."""
        import dataclasses

        from polyaxon_tpu.models import llama

        cfg = dataclasses.replace(llama.CONFIGS["llama_tiny"],
                                  dtype=jnp.float32, sliding_window=8)
        variables = llama.init(cfg, jax.random.key(0))
        prompt = jax.random.randint(jax.random.key(1), (1, 4), 0, cfg.vocab_size)
        n_new = 20  # >> window: the ring wraps multiple times

        out = llama.generate(cfg, variables["params"], prompt,
                             max_new_tokens=n_new)
        # Cache really is window-sized (pure shape arithmetic).
        assert llama.cache_len(cfg, 4 + n_new) == 8

        seq = prompt
        for _ in range(n_new):
            logits = llama.forward(cfg, variables["params"], seq)
            nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
            seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(seq[:, 4:]))

    def test_window_zero_rejected_everywhere(self):
        q, k, v = _qkv(s=256)
        for fn in (lambda: xla_attention(q, k, v, causal=True, window=0),
                   lambda: flash_attention(q, k, v, causal=True, window=0),
                   lambda: xla_attention(q, k, v, causal=False, window=8)):
            with pytest.raises(ValueError):
                fn()

    def test_small_seq_falls_back(self):
        q, k, v = _qkv(s=64)  # < 128: cannot tile → xla fallback path
        ref = xla_attention(q, k, v, causal=True)
        out = flash_attention(q, k, v, causal=True)
        np.testing.assert_allclose(out, ref, atol=1e-6)

    def test_dispatch(self):
        q, k, v = _qkv()
        out = dot_product_attention(q, k, v, impl="flash")
        ref = dot_product_attention(q, k, v, impl="xla")
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_auto_blocks_pick(self):
        """The VMEM-budget auto-pick (VERDICT r4 item 3 staged lever):
        tiles divide the seq, stay >= 128 where the seq allows, and a
        tight budget forces smaller tiles than a loose one."""
        from polyaxon_tpu.ops.flash import _tile_bytes, auto_blocks

        bq, bk = auto_blocks(2048, 2048, 64)
        assert 2048 % bq == 0 and 2048 % bk == 0
        assert bq >= 128 and bk >= 128
        assert _tile_bytes(bq, bk, 64) <= 48 * 2**20
        # Tight budget → strictly smaller score tile than the default.
        tq, tk = auto_blocks(2048, 2048, 64, vmem_budget=2**20)
        assert tq * tk < bq * bk
        # Non-power-of-two seq still yields a dividing tile.
        oq, ok_ = auto_blocks(1536, 1536, 128)
        assert 1536 % oq == 0 and 1536 % ok_ == 0

    def test_auto_blocks_committed_pick_table(self):
        """ISSUE 12: device kinds probed by the AOT topology sweep use
        the committed compile-validated pick, still screened by the
        budget and seq-tiling rules; unknown kinds fall back to the
        heuristic unchanged."""
        import json

        from polyaxon_tpu.ops.flash import (FLASH_TILES_PATH, _tile_bytes,
                                            auto_blocks)

        table = {k: v for k, v in
                 json.load(open(FLASH_TILES_PATH)).items()
                 if not k.startswith("_")}
        assert table, "flash_tiles.json must commit at least one pick"
        for kind, pick in table.items():
            bq, bk = pick["block_q"], pick["block_k"]
            # Picks were validated by a real Mosaic compile at the
            # probe shapes (head_dim 64); the budget screen must agree.
            assert _tile_bytes(bq, bk, 64) <= 48 * 2**20
            got = auto_blocks(4096, 4096, 64, device_kind=kind)
            assert got == (min(bq, 4096), min(bk, 4096))
            # A seq the pick doesn't tile falls through to the
            # heuristic rather than forcing a non-dividing block.
            oq, ok_ = auto_blocks(1536, 1536, 64, device_kind=kind)
            assert 1536 % oq == 0 and 1536 % ok_ == 0
        # Unknown kind == no kind: identical heuristic answer.
        assert auto_blocks(2048, 2048, 64, device_kind="TPU v9000") \
            == auto_blocks(2048, 2048, 64)

    def test_auto_blocks_matches_reference(self):
        q, k, v = _qkv()
        ref = xla_attention(q, k, v, causal=True)
        out = flash_attention(q, k, v, causal=True,
                              block_q="auto", block_k="auto")
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)
        # And through the model-config path a training step compiles:
        # "auto" rides cfg.flash_block_q like an int does.
        out2 = dot_product_attention(q, k, v, impl="flash",
                                     block_q="auto", block_k="auto")
        np.testing.assert_allclose(out2, ref, atol=2e-5, rtol=2e-5)


class TestFlashPallasBackward:
    """Grad parity of the Pallas bwd kernels (the real-TPU default,
    exercised here in interpret mode) against the einsum reference —
    the gate before the kernels run on hardware."""

    @staticmethod
    def _grads(fn, q, k, v):
        return jax.grad(lambda q, k, v: jnp.sum(fn(q, k, v) ** 2),
                        argnums=(0, 1, 2))(q, k, v)

    def _check(self, flash_kwargs, ref_kwargs, qkv_kwargs=None,
               atol=5e-4, rtol=5e-4):
        q, k, v = _qkv(**(qkv_kwargs or {}))
        gf = self._grads(
            lambda *a: flash_attention(*a, block_q=128, block_k=128,
                                       bwd_impl="pallas", **flash_kwargs),
            q, k, v)
        gr = self._grads(lambda *a: xla_attention(*a, **ref_kwargs), q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a, np.float32),
                                       np.asarray(b, np.float32),
                                       atol=atol, rtol=rtol)

    @pytest.mark.parametrize("causal", [True, False])
    def test_gradients_match(self, causal):
        self._check({"causal": causal}, {"causal": causal})

    def test_gqa_folds_group_onto_kv_head(self):
        self._check({"causal": True}, {"causal": True},
                    qkv_kwargs={"h": 8, "kv": 2})

    def test_multiple_kv_blocks_per_q_block(self):
        # block 128 over seq 512 → 4×4 blocks: exercises accumulation
        # across inner grid steps in both kernels.
        self._check({"causal": True}, {"causal": True},
                    qkv_kwargs={"s": 512})

    def test_sliding_window(self):
        self._check({"causal": True, "window": 64},
                    {"causal": True, "window": 64})

    def test_packed_segments(self):
        seg = jnp.asarray(
            [[0] * 100 + [1] * 156, [0] * 200 + [1] * 56], jnp.int32)
        self._check({"causal": True, "segment_ids": seg},
                    {"causal": True, "segment_ids": seg})

    def test_bf16_matches_fp32_reference(self):
        """bf16 inputs through the Pallas bwd vs the fp32 einsum
        reference: agreement at bf16-resolution tolerances."""
        q, k, v = _qkv(dtype=jnp.bfloat16)
        qf, kf, vf = (x.astype(jnp.float32) for x in (q, k, v))
        gf = self._grads(
            lambda *a: flash_attention(*a, causal=True, block_q=128,
                                       block_k=128, bwd_impl="pallas"),
            q, k, v)
        gr = self._grads(lambda *a: xla_attention(*a, causal=True),
                         qf, kf, vf)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a, np.float32),
                                       np.asarray(b, np.float32),
                                       atol=0.1, rtol=0.1)


@pytest.fixture()
def cp_mesh(cpu_devices):
    return Mesh(np.array(cpu_devices).reshape(2, 4), ("dp", "cp"))


class TestRing:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, cp_mesh, causal):
        q, k, v = _qkv(b=4, s=256, h=8, kv=4)
        ref = xla_attention(q, k, v, causal=causal)
        with cp_mesh:
            out = jax.jit(lambda q, k, v: ring_attention(q, k, v, causal=causal))(
                q, k, v
            )
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_gradients_match(self, cp_mesh):
        q, k, v = _qkv(b=4, s=256, h=8, kv=4)
        gr = jax.grad(lambda q: jnp.sum(xla_attention(q, k, v) ** 2))(q)
        with cp_mesh:
            gg = jax.jit(
                jax.grad(lambda q: jnp.sum(ring_attention(q, k, v) ** 2))
            )(q)
        np.testing.assert_allclose(gg, gr, atol=5e-4, rtol=5e-4)

    def test_requires_axis(self):
        q, k, v = _qkv()
        with pytest.raises(ValueError, match="mesh axis"):
            ring_attention(q, k, v, axis_name="nonexistent")

    def test_odd_local_seq_pads_to_zigzag_and_matches(self, cp_mesh):
        """s_loc = 63 cannot split into zigzag halves; the global entry
        pads the tail by cp rows (causality keeps the pads unattended),
        runs the FAST zigzag path — no warning, no ~2x einsum fallback
        — and still matches the reference exactly. Gradients flow
        through the pad/slice unchanged."""
        import warnings

        from polyaxon_tpu.ops import ring

        q, k, v = _qkv(b=2, s=252, h=4, kv=2)
        ref = xla_attention(q, k, v, causal=True)
        ring._warned_einsum_fallback = False
        with cp_mesh:
            with warnings.catch_warnings():
                # Only the guarded fallback warning fails the test —
                # unrelated Deprecation/FutureWarnings must not.
                warnings.simplefilter("error", RuntimeWarning)
                out = jax.jit(
                    lambda q, k, v: ring_attention(q, k, v))(q, k, v)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

        gr = jax.grad(lambda q: jnp.sum(xla_attention(q, k, v) ** 2))(q)
        with cp_mesh:
            gg = jax.jit(
                jax.grad(lambda q: jnp.sum(ring_attention(q, k, v) ** 2))
            )(q)
        np.testing.assert_allclose(gg, gr, atol=5e-4, rtol=5e-4)

    def test_odd_local_seq_inside_shard_map_still_warns(self, cp_mesh):
        """Direct in-shard_map callers can't be re-padded from outside:
        the loud masked-einsum fallback remains (no silent slow mode)."""
        import functools

        from jax.sharding import PartitionSpec as P

        from polyaxon_tpu.ops import ring

        q, k, v = _qkv(b=2, s=252, h=4, kv=2)
        ref = xla_attention(q, k, v, causal=True)
        ring._warned_einsum_fallback = False
        spec = P(None, "cp", None, None)
        from polyaxon_tpu.parallel import compat

        fn = compat.shard_map(
            functools.partial(ring._ring_attention_sharded, causal=True,
                              scale=q.shape[-1] ** -0.5, axis_name="cp"),
            mesh=cp_mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False)
        with pytest.warns(RuntimeWarning, match="masked-einsum ring"):
            out = jax.jit(fn)(q, k, v)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    @pytest.mark.perf
    def test_zigzag_halves_causal_work(self, cpu_devices):
        """The v2 zigzag layout skips fully-post-diagonal blocks, so
        causal CP must be decisively faster than the masked contiguous
        fallback (theoretical attention-FLOP ratio 9/16; generous 0.8
        margin for CPU timing noise). Compiled-HLO cost_analysis can't
        assert this — it counts a lax.scan body once regardless of trip
        count — so this is the step-time check VERDICT r1 item 4 asks
        for. Retried: background load on a shared 1-core host can
        squeeze the margin on any single sample set."""
        import functools
        import time

        from polyaxon_tpu.ops import ring

        mesh = Mesh(np.array(cpu_devices[:4]).reshape(4), ("cp",))
        q, k, v = _qkv(b=1, s=4096, h=4, kv=2)
        spec = jax.sharding.PartitionSpec(None, "cp", None, None)

        from polyaxon_tpu.parallel import compat

        def build(fn):
            f = compat.shard_map(
                functools.partial(fn, scale=64 ** -0.5, axis_name="cp"),
                mesh=mesh, in_specs=(spec,) * 3, out_specs=spec,
                check_vma=False)
            return jax.jit(f)

        f2 = build(ring._ring_causal_zigzag)
        f1 = build(lambda q, k, v, scale, axis_name:
                   ring._ring_einsum_causal(q, k, v, scale=scale,
                                            axis_name=axis_name))
        np.testing.assert_allclose(np.asarray(f1(q, k, v)),
                                   np.asarray(f2(q, k, v)),
                                   atol=2e-5, rtol=2e-5)

        # Interleave samples so background-load drift hits both
        # variants equally; compare best-of-5. Measured ratio is ~0.27
        # on an idle host vs the 0.8 assertion bound. Up to 3 attempts:
        # a load spike that distorts one sample set shouldn't fail CI.
        jax.block_until_ready(f2(q, k, v))
        jax.block_until_ready(f1(q, k, v))
        for attempt in range(3):
            t2s, t1s = [], []
            for _ in range(5):
                t0 = time.perf_counter()
                jax.block_until_ready(f2(q, k, v))
                t2s.append(time.perf_counter() - t0)
                t0 = time.perf_counter()
                jax.block_until_ready(f1(q, k, v))
                t1s.append(time.perf_counter() - t0)
            t2, t1 = min(t2s), min(t1s)
            if t2 < 0.8 * t1:
                return
        assert t2 < 0.8 * t1, (
            f"zigzag {t2 * 1e3:.0f}ms not clearly faster than "
            f"masked {t1 * 1e3:.0f}ms (3 attempts)")


class TestUlysses:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, cp_mesh, causal):
        q, k, v = _qkv(b=4, s=256, h=8, kv=4)
        ref = xla_attention(q, k, v, causal=causal)
        with cp_mesh:
            out = jax.jit(
                lambda q, k, v: ulysses_attention(q, k, v, causal=causal)
            )(q, k, v)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_gqa_repeats_to_axis(self, cp_mesh):
        # 2 kv heads < 4-way cp axis: kv heads are repeated to fit.
        q, k, v = _qkv(b=4, s=256, h=8, kv=2)
        ref = xla_attention(q, k, v, causal=True)
        with cp_mesh:
            out = jax.jit(lambda q, k, v: ulysses_attention(q, k, v))(q, k, v)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_gradients_match(self, cp_mesh):
        q, k, v = _qkv(b=4, s=256, h=8, kv=4)
        gr = jax.grad(lambda q: jnp.sum(xla_attention(q, k, v) ** 2))(q)
        with cp_mesh:
            gg = jax.jit(
                jax.grad(lambda q: jnp.sum(ulysses_attention(q, k, v) ** 2))
            )(q)
        np.testing.assert_allclose(gg, gr, atol=5e-4, rtol=5e-4)


class TestModelIntegration:
    def test_llama_ring_attention_forward(self, cp_mesh):
        """Llama forward with impl=ring under a dp×cp mesh matches xla."""
        from polyaxon_tpu.models import llama

        cfg_x = llama.CONFIGS["llama_tiny"]
        import dataclasses

        cfg_x = dataclasses.replace(cfg_x, max_seq_len=256, dtype=jnp.float32)
        cfg_r = dataclasses.replace(cfg_x, attention_impl="ring")
        variables = llama.init(cfg_x, jax.random.key(0))
        tokens = jax.random.randint(jax.random.key(1), (4, 256), 0, cfg_x.vocab_size)
        ref = llama.forward(cfg_x, variables["params"], tokens)
        with cp_mesh:
            out = jax.jit(
                lambda p, t: llama.forward(cfg_r, p, t)
            )(variables["params"], tokens)
        np.testing.assert_allclose(out, ref, atol=2e-4, rtol=2e-4)
