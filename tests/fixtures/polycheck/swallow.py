"""Planted silent swallow (golden: invariant-swallow). The handler
that logs at debug is the negative control — a trace is enough."""
import logging

logger = logging.getLogger(__name__)


def quiet(risky):
    try:
        return risky()
    except Exception:
        pass


def traced(risky):
    try:
        return risky()
    except Exception:
        logger.debug("risky failed", exc_info=True)
        return None
