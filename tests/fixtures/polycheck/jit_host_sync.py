"""Planted host sync inside a jitted step (golden: hotpath-host-sync)."""
import jax


def step(state, batch):
    loss = state + batch
    host = float(loss)
    return host


train = jax.jit(step)
