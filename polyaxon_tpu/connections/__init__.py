from polyaxon_tpu.connections.catalog import (
    ConnectionCatalog,
    ConnectionResolutionError,
)
from polyaxon_tpu.connections.schemas import (
    V1Connection,
    V1ConnectionKind,
    V1ConnectionResource,
)

__all__ = [
    "ConnectionCatalog",
    "ConnectionResolutionError",
    "V1Connection",
    "V1ConnectionKind",
    "V1ConnectionResource",
]
