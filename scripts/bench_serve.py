#!/usr/bin/env python
"""Serving engine load benchmark: tokens/sec and latency under
concurrent requests, across engine configs (dense / paged / +int8).

Drives the real HTTP surface (ServingServer) with N concurrent client
threads issuing mixed-length prompts, and reads /v1/stats occupancy so
the result shows WHY a config wins (slots busy vs admission-bound).
Writes bench_serve_results.json at the repo root.

Usage: python scripts/bench_serve.py [--model llama3_1b] [--clients 8]
       [--requests 32] [--max-new 64] [--slots 8] [--quick]
CPU smoke: JAX_PLATFORMS=cpu ... --model llama_tiny --quick
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from polyaxon_tpu.utils import apply_jax_platforms_override  # noqa: E402

apply_jax_platforms_override()


def drive(url: str, prompts: list[list[int]], max_new: int,
          clients: int) -> dict:
    """Fan the prompts over `clients` threads; returns latency stats."""
    lat: list[float] = []
    errors: list[str] = []
    lock = threading.Lock()
    queue = list(enumerate(prompts))

    def worker():
        while True:
            with lock:
                if not queue:
                    return
                i, prompt = queue.pop()
            body = json.dumps({"tokens": [prompt], "max_new_tokens": max_new,
                               "seed": i}).encode()
            req = urllib.request.Request(
                url + "/v1/generate", method="POST", data=body,
                headers={"Content-Type": "application/json"})
            t0 = time.perf_counter()
            try:
                with urllib.request.urlopen(req, timeout=600) as resp:
                    out = json.load(resp)
                assert len(out["tokens"][0]) == max_new
                with lock:
                    lat.append(time.perf_counter() - t0)
            except Exception as exc:  # noqa: BLE001 — recorded, not fatal
                with lock:
                    errors.append(f"{type(exc).__name__}: {exc}"[:200])

    threads = [threading.Thread(target=worker) for _ in range(clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    lat.sort()
    n = len(lat)
    return {
        "wall_s": round(wall, 2),
        "completed": n,
        "errors": errors[:5],
        "tokens_per_sec": round(n * max_new / wall, 2) if wall else None,
        "latency_p50_s": round(lat[n // 2], 3) if n else None,
        "latency_p95_s": round(lat[int(n * 0.95)], 3) if n else None,
    }


def _stats(url: str) -> dict:
    return json.load(urllib.request.urlopen(url + "/v1/stats", timeout=10))


def _slo_percentiles() -> dict:
    """Per-class TTFT/TPOT p50/p99 straight from the in-process
    registry (ServingServer shares this process): the trajectory
    record item 1's per-class policies will be judged against."""
    from polyaxon_tpu.obs import metrics as obs_metrics

    out: dict[str, dict] = {}
    for stem, hist in (("ttft", obs_metrics.serving_ttft_hist()),
                       ("tpot", obs_metrics.serving_tpot_hist())):
        for klass in hist.snapshot()["series"]:
            entry = out.setdefault(klass or "batch", {})
            for q, tag in ((0.5, "p50"), (0.99, "p99")):
                value = hist.quantile(q, **{"class": klass})
                entry[f"{stem}_{tag}_s"] = (round(value, 4)
                                            if value is not None else None)
    return out


def run_config(name: str, model: str, prompts, max_new, clients,
               **server_kw) -> dict:
    import jax

    from polyaxon_tpu.obs import metrics as obs_metrics
    from polyaxon_tpu.serving import ServingServer

    print(f"→ {name} ...", flush=True)
    with ServingServer(model, batching="continuous", **server_kw) as s:
        # Warm EVERY distinct prompt-length's prefill compile (the
        # engine jits per exact length) outside the timed window —
        # otherwise the timed run measures XLA compile, not serving.
        # This also warms the prefix cache: the timed numbers describe
        # steady-state serving of a repeated-prefix workload.
        seen: dict[int, list[int]] = {}
        for p in prompts:
            seen.setdefault(len(p), p)
        drive(s.url, list(seen.values()), max_new, clients=2)
        # The warm-up polluted the SLO histograms (compile-dominated
        # TTFTs): reset so the per-class percentiles describe the
        # timed window only. Accessor-style recorders re-create their
        # families on next touch, so the engine keeps recording.
        obs_metrics.REGISTRY.reset()
        before = _stats(s.url)
        result = drive(s.url, prompts, max_new, clients)
        after = _stats(s.url)
        slo_by_class = _slo_percentiles()
    # Timed-window deltas (the raw gauges are lifetime counters).
    occupancy = None
    dsteps = (after.get("decode_steps") or 0) - (before.get("decode_steps") or 0)
    if dsteps > 0 and after.get("avg_occupancy") is not None:
        live = (after["avg_occupancy"] * after["decode_steps"]
                - (before["avg_occupancy"] or 0) * before["decode_steps"])
        occupancy = round(live / dsteps, 4)
    row = {"name": name, **result, "avg_occupancy": occupancy,
           # Comparable across pod sizes the day the TPU tunnel
           # returns: per-chip normalization + per-class SLO numbers.
           "tokens_per_sec_per_chip": (
               round(result["tokens_per_sec"] / jax.device_count(), 2)
               if result["tokens_per_sec"] is not None else None),
           "slo_by_class": slo_by_class,
           "rejected": after.get("rejected") or {}}
    if after.get("spec_rounds") is not None:
        row["spec_tokens_per_round"] = after.get("spec_tokens_per_round")
    if after.get("kv_prefix_hits") is not None:
        row["kv_prefix_hits"] = (after["kv_prefix_hits"]
                                 - before["kv_prefix_hits"])
        row["kv_prefix_misses"] = (after["kv_prefix_misses"]
                                   - before["kv_prefix_misses"])
    print(f"  {name}: {result['tokens_per_sec']} tok/s, "
          f"p50 {result['latency_p50_s']}s, "
          f"occupancy {row['avg_occupancy']}", flush=True)
    return row


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", default="llama3_1b")
    parser.add_argument("--clients", type=int, default=8)
    parser.add_argument("--requests", type=int, default=32)
    parser.add_argument("--max-new", type=int, default=64)
    parser.add_argument("--slots", type=int, default=8)
    parser.add_argument("--prompt-len", type=int, default=48)
    parser.add_argument("--draft", default=None,
                        help="also bench continuous speculative with "
                             "this draft model (vocab must match)")
    parser.add_argument("--spec-k", type=int, default=4)
    parser.add_argument("--quick", action="store_true",
                        help="tiny load (CPU smoke of the harness)")
    args = parser.parse_args()
    if args.quick:
        args.clients, args.requests, args.max_new = 3, 6, 8

    import random

    import jax

    rng = random.Random(0)
    # Mixed lengths with a shared "system prompt" prefix on half the
    # requests — the workload prefix caching exists for.
    sys_prefix = [rng.randrange(100) for _ in range(args.prompt_len // 2)]
    prompts = []
    for i in range(args.requests):
        tail_len = rng.randrange(4, max(args.prompt_len // 2, 5))
        tail = [rng.randrange(100) for _ in range(tail_len)]
        prompts.append((sys_prefix + tail) if i % 2 == 0 else
                       ([rng.randrange(100) for _ in range(8)] + tail))

    configs = [
        ("dense", dict(slots=args.slots)),
        ("paged", dict(slots=args.slots, kv="paged")),
        ("paged-int8", dict(slots=args.slots, kv="paged",
                            quantize="int8")),
    ]
    if args.draft:
        # Continuous speculative (r4): ragged per-row acceptance over
        # the slot pool. Greedy-only engine; the drive() load is
        # already greedy (no temperature), so the same workload runs.
        configs.append(("dense-spec", dict(
            slots=args.slots, draft_model=args.draft, spec_k=args.spec_k)))
    results = [run_config(name, args.model, prompts, args.max_new,
                          args.clients, **kw)
               for name, kw in configs]
    out = {
        "backend": jax.devices()[0].platform,
        "device_kind": getattr(jax.devices()[0], "device_kind", "unknown"),
        "model": args.model,
        "load": {"clients": args.clients, "requests": args.requests,
                 "max_new": args.max_new, "slots": args.slots},
        "results": results,
    }
    path = os.path.join(REPO, "bench_serve_results.json")
    with open(path, "w") as fh:
        json.dump(out, fh, indent=2)
    print(f"wrote {path}")
    incomplete = [r["name"] for r in results
                  if r["completed"] < args.requests]
    if incomplete:
        print(f"ERROR: configs with failed requests: {incomplete} "
              "(see errors in the JSON)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
