"""Class-aware serving admission with preemptive slot/KV eviction
(ISSUE 19): rank-tuple goldens, per-class starvation barriers,
eviction page accounting against the pool invariants, suffix-only
re-admission parity, the interactive-never-evicted invariant, and the
e2e drill (saturate with best-effort, interactive still admits)."""

import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from polyaxon_tpu.models import llama
from polyaxon_tpu.serving.batching import (
    ContinuousBatchingEngine,
    DEFAULT_REQUEST_CLASS,
    REQUEST_CLASSES,
    QueueFull,
    _Request,
    resolve_request_class,
)


def _cfg():
    return dataclasses.replace(llama.CONFIGS["llama_tiny"],
                               dtype=jnp.float32)


def _stopped_engine(**kw):
    """A paged engine whose loop is stopped so _pick_next_locked and
    the eviction paths can be driven deterministically by the test."""
    cfg = _cfg()
    params = llama.init(cfg, jax.random.key(0))["params"]
    kw.setdefault("slots", 1)
    kw.setdefault("max_len", 32)
    kw.setdefault("kv", "paged")
    kw.setdefault("page_size", 4)
    engine = ContinuousBatchingEngine("llama_tiny", cfg, params, **kw)
    engine.stop()
    return engine


def _req(tokens, klass="batch", seq=0, **kw):
    r = _Request(list(tokens), 4, 0.0, 0, klass=klass, **kw)
    r.seq = seq
    return r


class TestClassCatalog:
    def test_catalog_shape(self):
        """Priority ordering and preemption roles are the contract the
        admission scan and the eviction policy both read."""
        inter = REQUEST_CLASSES["interactive"]
        batch = REQUEST_CLASSES["batch"]
        be = REQUEST_CLASSES["best-effort"]
        assert inter.priority > batch.priority > be.priority
        assert inter.preempts and not inter.preemptible
        assert be.preemptible and not be.preempts
        assert not batch.preempts and not batch.preemptible
        assert inter.ttft_target < batch.ttft_target < be.ttft_target

    def test_unknown_class_folds_to_batch(self):
        """A client cannot mint priority with a made-up label."""
        assert resolve_request_class("vip") is REQUEST_CLASSES["batch"]
        assert resolve_request_class("interactive").priority == 2
        assert DEFAULT_REQUEST_CLASS == "batch"


class TestRankingGoldens:
    def test_priority_beats_hotness(self):
        """An interactive request with zero cached prefix outranks a
        batch request whose whole chain is hot in the radix tree —
        class priority is the leading tuple element."""
        engine = _stopped_engine()
        pool = engine._pool
        hot = list(range(12))
        assert pool.admit(0, 12, hot)
        pool.release(0)  # hot's chain is resident in the tree
        r_hot_batch = _req(hot, klass="batch", seq=0)
        r_cold_inter = _req(range(100, 112), klass="interactive", seq=1)
        engine._queues["batch"].append(r_hot_batch)
        engine._queues["interactive"].append(r_cold_inter)
        with engine._cv:
            assert engine._pick_next_locked() is r_cold_inter
        # Overtaking across classes does NOT age the loser: the barrier
        # is per class, strict priority handles cross-class order.
        assert r_hot_batch.admit_skips == 0

    def test_overdue_beats_hotness_within_class(self):
        """Past its class TTFT target a request outranks a hotter
        on-time peer: deadline urgency is the second tuple element."""
        engine = _stopped_engine()
        pool = engine._pool
        hot = list(range(12))
        assert pool.admit(0, 12, hot)
        pool.release(0)
        overdue = _req(range(100, 112), klass="batch", seq=0)
        overdue.submitted_at = (
            time.time() - REQUEST_CLASSES["batch"].ttft_target - 1.0)
        r_hot = _req(hot, klass="batch", seq=1)
        engine._queues["batch"].extend([overdue, r_hot])
        with engine._cv:
            assert engine._pick_next_locked() is overdue

    def test_hotness_then_age_within_class(self):
        """On-time same-class requests keep the PR 11 order: hottest
        matched prefix first, global arrival order among ties."""
        engine = _stopped_engine()
        pool = engine._pool
        hot = list(range(12))
        assert pool.admit(0, 12, hot)
        pool.release(0)
        r_cold = _req(range(100, 112), klass="batch", seq=0)
        r_hot = _req(hot, klass="batch", seq=1)
        engine._queues["batch"].extend([r_cold, r_hot])
        with engine._cv:
            assert engine._pick_next_locked() is r_hot
        assert r_cold.admit_skips == 1  # within-class aging
        engine._queues["batch"].clear()
        a = _req(range(100, 112), klass="batch", seq=5)
        b = _req(range(200, 212), klass="batch", seq=6)
        engine._queues["batch"].extend([a, b])
        with engine._cv:
            assert engine._pick_next_locked() is a  # FIFO tie-break

    def test_fifo_mode_merges_classes(self):
        """--no-class-admission: one queue, pre-19 scan semantics —
        arrival order wins regardless of class label."""
        engine = _stopped_engine(class_admission=False)
        assert list(engine._queues) == [DEFAULT_REQUEST_CLASS]
        r_be = _req(range(100, 106), klass="best-effort", seq=0)
        r_inter = _req(range(200, 206), klass="interactive", seq=1)
        engine._queues[DEFAULT_REQUEST_CLASS].extend([r_be, r_inter])
        with engine._cv:
            assert engine._pick_next_locked() is r_be


class TestPerClassStarvationBarrier:
    def test_barrier_blocks_own_class_only(self):
        """A best-effort request at its skip cap stops younger
        best-effort work from passing (its infinite hotness wins its
        tier), but interactive still admits first — the barrier is
        per class, priority stays strict across classes."""
        engine = _stopped_engine()
        pool = engine._pool
        hot = list(range(12))
        assert pool.admit(0, 12, hot)
        pool.release(0)
        starved = _req(range(100, 112), klass="best-effort", seq=0)
        starved.admit_skips = REQUEST_CLASSES["best-effort"].skip_cap
        r_hot_be = _req(hot, klass="best-effort", seq=1)
        r_inter = _req(range(200, 212), klass="interactive", seq=2)
        engine._queues["best-effort"].extend([starved, r_hot_be])
        engine._queues["interactive"].append(r_inter)
        with engine._cv:
            assert engine._pick_next_locked() is r_inter
        with engine._cv:
            assert engine._pick_next_locked() is starved
        with engine._cv:
            assert engine._pick_next_locked() is r_hot_be

    def test_per_class_pending_caps(self):
        """submit() sheds per class: a saturated best-effort queue
        503s while interactive keeps queueing."""
        cfg = _cfg()
        params = llama.init(cfg, jax.random.key(0))["params"]
        engine = ContinuousBatchingEngine(
            "llama_tiny", cfg, params, slots=1, max_len=32,
            kv="paged", page_size=4,
            class_max_pending={"best-effort": 1})
        engine.stop()
        with engine._cv:
            engine._stopped = False  # accept submits; loop stays dead
        try:
            engine._queues["best-effort"].append(
                _req(range(6), klass="best-effort"))
            with pytest.raises(QueueFull) as exc:
                engine.submit(list(range(10, 16)), 2, klass="best-effort")
            assert "best-effort" in str(exc.value)
            assert engine.stats()["rejected"] == {"class_queue_full": 1}
            engine.submit(list(range(20, 26)), 2, klass="interactive")
            assert len(engine._queues["interactive"]) == 1
        finally:
            with engine._cv:
                engine._stopped = True
        assert engine.health()["class_caps"] == {"best-effort": 1}

    def test_class_cap_validation(self):
        cfg = _cfg()
        params = llama.init(cfg, jax.random.key(0))["params"]
        with pytest.raises(ValueError, match="class_max_pending"):
            ContinuousBatchingEngine(
                "llama_tiny", cfg, params, slots=1, max_len=32,
                class_max_pending={"interactive": 0})


class TestPreemptiveEviction:
    def test_evict_releases_exact_pages_and_invariants_hold(self):
        """Evicting a live slot returns exactly the pages it held
        beyond its committed prompt prefix to the free list, parks the
        prefix as reclaimable tree pages, and keeps the pool's
        refcount/CoW invariants clean."""
        engine = _stopped_engine(slots=2)
        pool = engine._pool
        prompt = list(range(8))  # 2 full pages committed at admission
        req = _req(prompt, klass="best-effort")
        assert pool.admit(0, len(prompt), prompt)
        pool.commit_prefix(0)
        engine._slot_req[0] = req
        engine._pos[0] = len(prompt) - 1
        free_before = len(pool._free)
        held = pool.slot_pages(0)
        assert held == 2
        engine._evict_slot(0, reason="slots")
        assert engine._slot_req[0] is None
        assert pool.slot_pages(0) == 0
        # Exact page split: the tail page is private (it holds the
        # decode write position, never tree-matchable) and returns to
        # the free list; the full committed-prefix page stays
        # TREE-owned — resident and reclaimable, ready to serve the
        # re-admission. Every page the slot held is allocatable again.
        assert len(pool._free) == free_before + 1
        assert pool.radix_stats()["resident"] == 1
        assert pool.free_pages == free_before + held
        assert pool.check_invariants() == []
        # The victim went back to the HEAD of its class queue.
        assert engine._queues["best-effort"][0] is req
        assert req.preemptions == 1 and req.out == []
        assert req.first_token_at is None  # TTFT re-observes on retry
        stats = engine.stats()
        assert stats["preemptions"] == {"best-effort": 1}

    def test_interactive_never_evicted(self):
        """No victim exists when every live slot is interactive or
        batch — neither class is preemptible, whatever the pressure."""
        engine = _stopped_engine(slots=2)
        engine._slot_req[0] = _req(range(6), klass="interactive")
        engine._slot_req[1] = _req(range(10, 16), klass="batch")
        assert engine._pick_victim(
            REQUEST_CLASSES["interactive"].priority) is None

    def test_victim_ranking_prefers_most_pages(self):
        """Among preemptible victims the policy evicts the slot
        holding the most KV pages — the most over-budget one."""
        engine = _stopped_engine(slots=2)
        pool = engine._pool
        small, big = list(range(4)), list(range(50, 62))
        assert pool.admit(0, len(small), small)
        assert pool.admit(1, len(big), big)
        engine._slot_req[0] = _req(small, klass="best-effort")
        engine._slot_req[1] = _req(big, klass="best-effort")
        assert pool.slot_pages(1) > pool.slot_pages(0)
        assert engine._pick_victim(
            REQUEST_CLASSES["interactive"].priority) == 1

    def test_no_preemption_flag_disables_eviction(self):
        engine = _stopped_engine(slots=1, preemption=False)
        pool = engine._pool
        prompt = list(range(6))
        assert pool.admit(0, len(prompt), prompt)
        engine._slot_req[0] = _req(prompt, klass="best-effort")
        engine._queues["interactive"].append(
            _req(range(100, 106), klass="interactive"))
        engine._maybe_preempt()
        assert engine._slot_req[0] is not None
        assert engine.stats()["preemptions"] == {}


class TestEndToEnd:
    def test_suffix_only_readmission_parity_vs_dense(self):
        """An evicted best-effort request re-admits with its committed
        prefix served by the radix tree (suffix-only prefill) and
        still produces the dense engine's exact tokens."""
        cfg = _cfg()
        params = llama.init(cfg, jax.random.key(0))["params"]
        prompt = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8]
        dense = ContinuousBatchingEngine("llama_tiny", cfg, params,
                                         slots=1, max_len=64)
        try:
            want = dense.generate([prompt], max_new_tokens=24,
                                  timeout=300)[0]
        finally:
            dense.stop()
        engine = ContinuousBatchingEngine("llama_tiny", cfg, params,
                                          slots=1, max_len=64,
                                          kv="paged", page_size=4)
        try:
            be = engine.submit(prompt, 24, klass="best-effort")
            while not be.out:  # live and decoding before the rival
                time.sleep(0.005)
            ia = engine.submit([7, 7, 7], 2, klass="interactive")
            ia.wait(timeout=300)
            got = be.wait(timeout=300)
            stats = engine.stats()
        finally:
            engine.stop()
        assert be.preemptions >= 1
        assert got == want  # deterministic regeneration after eviction
        # The committed prefix came back from the tree: the suffix the
        # re-admission actually prefilled is shorter than the prompt.
        assert 0 < stats["readmit_suffix_tokens"] < len(prompt)
        assert stats["kv_invariant_violations"] == 0

    def test_interactive_admits_through_saturation(self):
        """The e2e drill: best-effort camps every slot, interactive
        arrivals admit within their TTFT target anyway, with at least
        one preemption observed and the pool invariants clean."""
        cfg = _cfg()
        params = llama.init(cfg, jax.random.key(0))["params"]
        engine = ContinuousBatchingEngine("llama_tiny", cfg, params,
                                          slots=2, max_len=64,
                                          kv="paged", page_size=4)
        try:
            # Warm both prompt shapes so in-flight compiles don't land
            # in the timed window (CPU-CI discipline).
            engine.generate([[9, 9, 9, 9, 9, 9]], max_new_tokens=2,
                            klass="interactive")
            campers = [engine.submit([31 + 17 * i + j for j in range(6)],
                                     48, klass="best-effort")
                       for i in range(3)]
            deadline = time.monotonic() + 30.0
            while (engine.health()["decode_active"] < 2
                   and time.monotonic() < deadline):
                time.sleep(0.005)
            t0 = time.time()
            ia = engine.submit([101, 102, 103, 104, 105, 106], 2,
                               klass="interactive")
            ia.wait(timeout=300)
            ttft = ia.first_token_at - t0
            for r in campers:
                r.wait(timeout=300)
            stats = engine.stats()
            health = engine.health()
        finally:
            engine.stop()
        # Generous multiple of the 0.5s target: CI boxes are slow, but
        # without preemption the wait would be a full 64-token decode.
        assert ttft < REQUEST_CLASSES["interactive"].ttft_target * 4
        assert sum(stats["preemptions"].values()) >= 1
        assert stats["kv_invariant_violations"] == 0
        assert health["class_pending"] == {"interactive": 0, "batch": 0,
                                           "best-effort": 0}


class TestRouterPressureGuard:
    def test_interactive_cap_saturation_counts_as_pressured(self):
        """A replica whose interactive pending is at its class cap is
        pressured even when aggregate prefill_pending looks fine — and
        even when no global spill_depth is configured (ISSUE 19)."""
        from polyaxon_tpu.serving.router import FleetRouter

        router = FleetRouter(["r0", "r1"], spill_depth=None)
        telemetry = {
            "r0": {"prefill_pending": 0,
                   "class_pending": {"interactive": 4},
                   "class_caps": {"interactive": 4}},
            "r1": {"prefill_pending": 0,
                   "class_pending": {"interactive": 1},
                   "class_caps": {"interactive": 4}},
        }
        assert router._pressured("r0", telemetry)
        assert not router._pressured("r1", telemetry)
        # Engines predating the per-class fields keep the old behavior.
        assert not router._pressured("r0", {"r0": {"prefill_pending": 0}})
