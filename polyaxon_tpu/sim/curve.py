"""Standard load points → the committed ``fleet_curve.json``.

Each point builds a fresh throwaway fleet at a fixed load (queue depth
/ live-run churn), measures steady-state reconcile ticks with a clean
metrics registry, and reports tick latency plus the store's query/row
cost per tick. Points are ordered idle → storm so the curve reads as
"where does the control plane knee over".

Queued points run with ``capacity=0``: no starts or reaps mutate the
fleet during the window, so the per-tick query count is DETERMINISTIC
— which is what lets ``budgets.json`` gate on it in CI without latency
flake (the latency ceilings ride along with generous margins).
"""

from __future__ import annotations

import time

from polyaxon_tpu.obs import metrics as obs_metrics
from polyaxon_tpu.sim import traces
from polyaxon_tpu.sim.fleet import FleetSim

# name -> point spec. ``queued``: backlog of compiled QUEUED jobs.
# ``storm``: live fleet + backlog + a 50% preemption wave mid-window.
POINT_SPECS: dict[str, list[tuple[str, dict]]] = {
    "full": [
        ("idle", {"queued": 0, "ticks": 50}),
        ("queued_100", {"queued": 100, "ticks": 40}),
        ("queued_1k", {"queued": 1000, "ticks": 30}),
        ("queued_10k", {"queued": 10000, "ticks": 15}),
        ("storm", {"storm": True, "capacity": 256, "live": 256,
                   "backlog": 2000, "ticks": 40}),
    ],
    "quick": [
        ("idle", {"queued": 0, "ticks": 30}),
        ("queued_50", {"queued": 50, "ticks": 20}),
        ("queued_200", {"queued": 200, "ticks": 15}),
        ("storm", {"storm": True, "capacity": 16, "live": 16,
                   "backlog": 60, "ticks": 25}),
    ],
}


def _registry_tail(point: dict) -> None:
    """Fold the registry's store/admission latency view into the point."""
    reg = obs_metrics.REGISTRY
    store_hist = reg.get("polyaxon_runstore_op_seconds")
    adm_hist = reg.get("polyaxon_admission_pass_seconds")
    tick_hist = reg.get("polyaxon_scheduler_tick_seconds")
    if store_hist is not None:
        p99 = store_hist.quantile_max(0.99)
        point["store_op_p99_ms"] = round((p99 or 0.0) * 1e3, 4)
    if adm_hist is not None:
        p99 = adm_hist.quantile(0.99)
        if p99 is not None:
            point["admission_p99_ms"] = round(p99 * 1e3, 3)
    if tick_hist is not None:
        p99 = tick_hist.quantile(0.99)
        if p99 is not None:
            point["sched_tick_p99_ms"] = round(p99 * 1e3, 3)


def build_point(name: str, spec: dict, *, seed: int = 0,
                legacy: bool = False, deopt: bool = False,
                snapshot: bool = False) -> dict:
    obs_metrics.REGISTRY.reset()
    obs_metrics.ensure_core_metrics()
    storm = spec.get("storm", False)
    capacity = spec.get("capacity", 64) if storm else 0
    sim = FleetSim(capacity=capacity, seed=seed,
                   incremental=not legacy, legacy_scan=legacy,
                   deopt=deopt,
                   mean_duration=0.4 if storm else 0.05,
                   failure_rate=0.05 if storm else 0.0)
    try:
        # Storm points churn (starts/reaps land in the measured ticks),
        # so their store counts are load-dependent — the budget writer
        # gates them on latency only (see budgets.derive_limits).
        point: dict = {"load": name, "dynamic": bool(storm)}
        if storm:
            live = spec.get("live", capacity)
            backlog = spec.get("backlog", 0)
            sim.submit_queued_jobs(live)
            deadline = time.monotonic() + 30
            while (len(sim.executor.active_runs) < min(live, capacity)
                   and time.monotonic() < deadline):
                sim.tick()
            sim.submit_queued_jobs(backlog)
            # The wave: evict half the fleet, then measure the churn.
            for uuid in sim.executor.active_runs[::2]:
                sim.executor.preempt(uuid)
            point["live"] = len(sim.executor.active_runs)
            point["queued"] = backlog
        else:
            sim.submit_queued_jobs(spec.get("queued", 0))
            point["live"] = 0
            point["queued"] = spec.get("queued", 0)
        sim.measure_ticks(spec.get("ticks", 20))
        point.update(sim.tick_report())
        _registry_tail(point)
        if snapshot:
            snap = obs_metrics.REGISTRY.snapshot()
            point["registry"] = {
                k: v for k, v in snap.items()
                if k.startswith(("polyaxon_scheduler", "polyaxon_admission",
                                 "polyaxon_runstore", "polyaxon_queue"))}
        return point
    finally:
        sim.close()


def build_curve(mode: str = "quick", *, seed: int = 0,
                legacy: bool = False, deopt: bool = False,
                snapshot: bool = False,
                progress=None) -> dict:
    points = {}
    for name, spec in POINT_SPECS[mode]:
        if progress:
            progress(f"point {name} ...")
        points[name] = build_point(name, spec, seed=seed, legacy=legacy,
                                   deopt=deopt, snapshot=snapshot)
        if progress:
            progress(f"point {name}: tick p99 "
                     f"{points[name]['tick_p99_ms']}ms, "
                     f"{points[name]['queries_per_tick_p50']} queries/tick")
    return {
        "_meta": {
            "mode": mode,
            "seed": seed,
            "legacy": legacy,
            "deopt": deopt,
            "points": [n for n, _ in POINT_SPECS[mode]],
        },
        "points": points,
    }
