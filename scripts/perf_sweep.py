#!/usr/bin/env python
"""Real-chip perf sweep: runs the VERDICT-r1 item-3 lever matrix through
bench.py and reports a ranked table (tokens/sec/chip + MFU).

Levers: per-device batch (8 vs 16), remat policy (dots vs none),
attention (flash vs xla), flash fwd tile sizes, and backward impl
(pallas kernels vs chunked-XLA recompute). Each point is an isolated
bench.py subprocess so an OOM or compile failure poisons nothing.

Usage: python scripts/perf_sweep.py [--steps N] [--quick]
Writes perf_sweep_results.json next to bench_baseline.json.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def bench_args(**kw) -> list[str]:
    args = []
    for flag, key in (("--batch", "batch"), ("--seq", "seq"),
                      ("--steps", "steps"), ("--remat", "remat"),
                      ("--attention", "attention"), ("--block-q", "block_q"),
                      ("--block-k", "block_k"), ("--bwd", "bwd"),
                      ("--loss-chunk", "loss_chunk"), ("--model", "model")):
        if kw.get(key) is not None:
            args += [flag, str(kw[key])]
    if kw.get("profile"):
        # One jax.profiler trace of a late step per point
        # (VERDICT r3 #2); dumps land under profiles/<config>/.
        args += ["--profile"]
    return args


def run_point(name: str, timeout_s: float = 1200, **kw):
    cmd = [sys.executable, os.path.join(REPO, "bench.py")] + bench_args(**kw)
    t0 = time.time()
    # The sweep is its own retry layer (--resume + the hourly probe
    # cycle), so disable bench.py's internal 45-min probe-retry window:
    # otherwise an outage makes every point sit in bench's retry loop
    # until this 1200 s timeout SIGTERMs it, replacing the structured
    # tpu_unavailable JSON with an unstructured timeout error.
    env = {**os.environ, "POLYAXON_TPU_BENCH_RETRY_S": "0"}
    # Popen + SIGTERM-then-SIGKILL, not subprocess.run(timeout=...):
    # run() SIGKILLs on timeout, and a bench killed mid-TPU-program can
    # wedge the tunnel for every later client (observed 2026-07-31:
    # init hangs >90s for all followers after one hard kill). SIGTERM
    # lets the PJRT client unwind its device lease first.
    with subprocess.Popen(cmd, stdout=subprocess.PIPE,
                          stderr=subprocess.PIPE, text=True,
                          cwd=REPO, env=env) as popen:
        try:
            stdout, stderr = popen.communicate(timeout=timeout_s)
            proc = subprocess.CompletedProcess(cmd, popen.returncode,
                                               stdout, stderr)
        except subprocess.TimeoutExpired:
            popen.terminate()
            try:
                popen.communicate(timeout=60)
            except subprocess.TimeoutExpired:
                popen.kill()
                popen.communicate()
            return {"name": name, "error": f"timeout>{timeout_s:.0f}s", **kw}
    line = _last_json_line(proc.stdout)
    if line is None:
        tail = " | ".join(proc.stderr.strip().splitlines()[-3:])[-300:]
        return {"name": name, "error": f"rc={proc.returncode}: {tail}", **kw}
    out = {"name": name, "wall_s": round(time.time() - t0, 1), **kw, **line}
    # Per-point metrics-registry snapshot (ISSUE 5): bench.py emits the
    # unified registry (training-step histogram, store-op latency,
    # retry counters) in its JSON line; normalize the key so every
    # sweep point in perf_sweep_results.json carries one — None for
    # error points and pre-registry bench binaries.
    out.setdefault("metrics_registry", None)
    # Per-point phase attribution (ISSUE 6): bench.py analyzes its own
    # run's lifecycle spans (obs.analyze) into a compact perf report —
    # normalize the key so every sweep point carries one (None for
    # error points and pre-report bench binaries), and a regression
    # between rounds names the phase that moved, not just the number.
    out.setdefault("perf_report", None)
    # OOM shows up as an error field from bench's catch-all.
    if kw.get("profile") and "error" not in out:
        out.update(_analyze_profile(proc.stderr))
    return out


def _last_json_line(stdout: str):
    """Last parseable JSON object on stdout, or None — the one-JSON-line
    output contract shared by bench.py and analyze_trace.py."""
    for ln in reversed(stdout.strip().splitlines()):
        try:
            parsed = json.loads(ln)
        except json.JSONDecodeError:
            continue
        if isinstance(parsed, dict):
            return parsed
    return None


def _analyze_profile(bench_stderr: str) -> dict:
    """Run scripts/analyze_trace.py on the trace the bench just wrote
    (it announces '# profiler trace -> <dir>/profile' on stderr) and
    attach the summary — so every profiled chip point carries its own
    matmul-ceiling/top-sink analysis in perf_sweep_results.json instead
    of needing a manual per-point analyzer pass in the tunnel window.
    Analysis failure never fails the measurement (the number stands on
    its own; the note says what went wrong)."""
    marker = "# profiler trace -> "
    trace_dir = None
    for ln in bench_stderr.splitlines():
        if ln.startswith(marker):
            trace_dir = ln[len(marker):].strip()
    if not trace_dir:
        return {"profile_analysis": {"error": "no trace dir announced"}}
    try:
        proc = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "scripts", "analyze_trace.py"), trace_dir],
            capture_output=True, text=True, timeout=600, cwd=REPO)
    except (subprocess.TimeoutExpired, OSError) as exc:
        # The measurement stands on its own — a slow/broken analyzer
        # must never cost a completed chip number or the rest of the
        # sweep (the docstring's promise, enforced).
        return {"profile_analysis": {
            "error": f"analyzer failed: {type(exc).__name__}"}}
    summary = _last_json_line(proc.stdout)
    if summary is not None:
        summary.pop("categories", None)  # keep the record compact
        return {"profile_analysis": summary}
    tail = " | ".join(proc.stderr.strip().splitlines()[-2:])[-200:]
    return {"profile_analysis": {
        "error": f"analyzer rc={proc.returncode}: {tail}"}}


def moe_dispatch_sweep(platform: str, steps: int) -> int:
    """Dense one-hot vs ragged all_to_all MoE dispatch, measured
    (VERDICT r2 item 3): train-step wall time at E ∈ {8,16,32} on a
    dp2×ep4 mesh (8-device virtual CPU mesh by default; single-chip
    ep=1 on TPU still measures the einsum-elimination term, which
    dominates as E grows). Writes moe_dispatch_results.json."""
    sys.path.insert(0, REPO)
    if platform == "cpu":
        from polyaxon_tpu.utils import cpu_mesh_xla_flags

        cpu_mesh_xla_flags(8)
    import dataclasses

    import jax

    if platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from polyaxon_tpu.models import moe
    from polyaxon_tpu.parallel.sharding import rules_for_mesh, tree_shardings

    devices = jax.devices()
    if len(devices) >= 8:
        mesh = jax.sharding.Mesh(np.array(devices[:8]).reshape(2, 4),
                                 ("dp", "ep"))
    else:
        mesh = jax.sharding.Mesh(np.array(devices[:1]).reshape(1, 1),
                                 ("dp", "ep"))
    results = []
    for n_experts in (8, 16, 32):
        cfg0 = dataclasses.replace(
            moe.CONFIGS["moe_tiny"], dim=256, ffn_dim=512, n_layers=2,
            n_heads=8, n_kv_heads=4, n_experts=n_experts,
            experts_per_token=2, capacity_factor=1.25, vocab_size=1024,
            dtype=jnp.float32 if platform == "cpu" else jnp.bfloat16)
        variables = moe.init(cfg0, jax.random.key(0))
        shardings = tree_shardings(moe.logical_axes(cfg0)["params"], mesh,
                                   rules_for_mesh(mesh))
        params = jax.device_put(variables["params"], shardings)
        batch = {"tokens": jax.random.randint(jax.random.key(1), (8, 128),
                                              0, cfg0.vocab_size)}
        row = {"n_experts": n_experts}
        for dispatch in ("dense", "ragged"):
            cfg = dataclasses.replace(cfg0, dispatch=dispatch)

            def loss_fn(p, b, cfg=cfg):
                return moe.apply(cfg, {"params": p, "state": {}}, b)[0]

            with mesh:
                step = jax.jit(jax.grad(loss_fn))
                g = step(params, batch)  # compile + warm
                jax.block_until_ready(g)
                times = []
                for _ in range(steps):
                    t0 = time.perf_counter()
                    jax.block_until_ready(step(params, batch))
                    times.append(time.perf_counter() - t0)
            row[dispatch + "_ms"] = round(
                sorted(times)[len(times) // 2] * 1e3, 2)
        row["ragged_speedup"] = round(row["dense_ms"] / row["ragged_ms"], 3)
        results.append(row)
        print(f"E={n_experts}: dense {row['dense_ms']}ms, "
              f"ragged {row['ragged_ms']}ms, "
              f"speedup {row['ragged_speedup']}x", flush=True)

    out_path = os.path.join(REPO, "moe_dispatch_results.json")
    with open(out_path, "w") as fh:
        json.dump({"platform": jax.devices()[0].platform,
                   "mesh": dict(zip(mesh.axis_names, mesh.devices.shape)),
                   "grid": "dim256 ffn512 L2 seq128 batch8 K2 cf1.25",
                   "results": results}, fh, indent=2)
    print(f"wrote {out_path}")
    return 0


def run_audit_artifacts():
    """The communication-audit companion artifacts for a sweep round
    (ISSUE 4): the CPU-mesh collective census per schedule, the AOT
    topology-only TPU evidence, and the overlap audit (ISSUE 12). Each
    runs as its own subprocess with a bounded budget — a hung audit
    costs its timeout, not the sweep. Returns the ingested overlap
    summary (or None) so the sweep record carries per-schedule
    overlap_ratio alongside the throughput points."""
    for name, cmd, budget_s in (
        ("collective audit (CPU mesh)",
         [sys.executable, "-m", "polyaxon_tpu.perf",
          "--json", os.path.join(REPO, "collective_audit.json")], 900),
        ("AOT topology audit (TPU, no device)",
         [sys.executable, "-m", "polyaxon_tpu.perf", "--aot-probe",
          "--aot-train-step", "ulysses-cp,ring-cp"], 1500),
        ("overlap audit (latency-hiding scheduler)",
         [sys.executable, "-m", "polyaxon_tpu.perf", "--audit",
          "--json", os.path.join(REPO, "overlap_audit.json")], 900),
    ):
        print(f"→ {name} ...", flush=True)
        try:
            proc = subprocess.run(cmd, cwd=REPO, timeout=budget_s,
                                  capture_output=True, text=True)
            tail = (proc.stdout or proc.stderr).strip().splitlines()
            print("  " + (tail[-1][:200] if tail else f"rc={proc.returncode}"),
                  flush=True)
        except (subprocess.TimeoutExpired, OSError) as exc:
            print(f"  audit step failed: {type(exc).__name__} "
                  f"(sweep continues)", flush=True)
    return _load_overlap_summary()


def _load_overlap_summary():
    """Structured ingestion of the overlap artifact the audit step just
    wrote — the `{"overlap_audit": {ok, topology, reports}}` contract of
    `python -m polyaxon_tpu.perf --audit --json <path>` — so the sweep
    record carries per-schedule overlap numbers without re-parsing the
    human-facing table text."""
    path = os.path.join(REPO, "overlap_audit.json")
    try:
        with open(path) as fh:
            payload = json.load(fh)
    except (OSError, ValueError):
        return None
    audit = payload.get("overlap_audit")
    if not isinstance(audit, dict):
        return None
    if not audit.get("ok"):
        # The probe found no workable TPU topology on this host: record
        # the skip (with the per-topology errors) instead of nothing,
        # so a sweep round without overlap numbers is distinguishable
        # from one where the audit was never requested.
        return {"ok": False, "topologies": audit.get("topologies", {})}
    reports = audit.get("reports", [])
    summary = {
        "ok": True,
        "topology": audit.get("topology"),
        "overlap_ratio": {r["name"]: r["overlap_ratio"] for r in reports},
        "async_by_kind": {r["name"]: r["overlap"].get("async_by_kind", {})
                          for r in reports},
    }
    for name, ratio in sorted(summary["overlap_ratio"].items()):
        print(f"  overlap[{name}] = {ratio:.4f}", flush=True)
    return summary


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=30)
    parser.add_argument("--model", default="llama_200m")
    parser.add_argument("--seq", type=int, default=2048,
                        help="sequence length (shrink for CPU smokes: the "
                             "8-thread CPU mesh trips XLA's 40s collective "
                             "watchdog on large shapes)")
    parser.add_argument("--quick", action="store_true",
                        help="baseline + the 3 highest-value levers only")
    parser.add_argument("--moe", action="store_true",
                        help="run the MoE dense-vs-ragged dispatch sweep "
                             "instead of the llama lever matrix")
    parser.add_argument("--moe-platform", default="cpu",
                        choices=("cpu", "tpu"),
                        help="--moe backend: cpu = 8-device virtual mesh "
                             "(dp2xep4), tpu = the real chip (ep=1)")
    parser.add_argument("--profile", action="store_true",
                        help="capture a jax.profiler trace of one late "
                             "step per point (profiles/<config>/; "
                             "VERDICT r3 #2's per-point trace)")
    parser.add_argument("--resume", action="store_true",
                        help="rerun only the points that errored in the "
                             "existing perf_sweep_results.json (tunnel "
                             "flakes), keeping prior successes")
    parser.add_argument("--audit", action="store_true",
                        help="also emit the per-point HLO/collective "
                             "report artifacts: the CPU-mesh schedule "
                             "census (collective_audit.json) and the AOT "
                             "topology-only TPU evidence incl. train-step "
                             "collective reports + flash VMEM fits "
                             "(aot_probe_results.json), plus the overlap "
                             "audit (overlap_audit.json, ingested into "
                             "this sweep's record as per-schedule "
                             "overlap_ratio) — all run in isolated "
                             "subprocesses and never block the sweep "
                             "points")
    args = parser.parse_args()

    overlap_summary = run_audit_artifacts() if args.audit else None

    if args.moe:
        return moe_dispatch_sweep(args.moe_platform,
                                  steps=min(args.steps, 15))

    base = dict(model=args.model, steps=args.steps, seq=args.seq,
                profile=args.profile or None)
    points = [
        ("baseline-b8-dots-flash", dict(base, batch=8, remat="dots",
                                        attention="flash")),
        ("b16-dots-flash", dict(base, batch=16, remat="dots",
                                attention="flash")),
        ("b8-dots-flash-bwd-xla", dict(base, batch=8, remat="dots",
                                       attention="flash", bwd="xla")),
        ("b8-none-flash", dict(base, batch=8, remat="none",
                               attention="flash")),
    ]
    if not args.quick:
        points += [
            ("b16-none-flash", dict(base, batch=16, remat="none",
                                    attention="flash")),
            ("b8-dots-xla", dict(base, batch=8, remat="dots",
                                 attention="xla")),
            ("b8-dots-flash-q256k512", dict(base, batch=8, remat="dots",
                                            attention="flash",
                                            block_q=256, block_k=512)),
            ("b8-dots-flash-q512k256", dict(base, batch=8, remat="dots",
                                            attention="flash",
                                            block_q=512, block_k=256)),
            ("b8-dots-flash-q256k256", dict(base, batch=8, remat="dots",
                                            attention="flash",
                                            block_q=256, block_k=256)),
            # VERDICT r4 item 3 staged levers: VMEM-budget auto-pick
            # (currently resolves to 1024-tiles at these shapes) vs the
            # fixed 512 default, plus the explicit 1024-tile point so
            # the auto pick's benefit is attributable.
            ("b8-dots-flash-qkauto", dict(base, batch=8, remat="dots",
                                          attention="flash",
                                          block_q="auto", block_k="auto")),
            ("b8-dots-flash-q1024k1024", dict(base, batch=8, remat="dots",
                                              attention="flash",
                                              block_q=1024, block_k=1024)),
            ("b16-dots-flash-bwd-xla", dict(base, batch=16, remat="dots",
                                            attention="flash", bwd="xla")),
            ("b8-dots-flash-chunk512", dict(base, batch=8, remat="dots",
                                            attention="flash",
                                            loss_chunk=512)),
            ("b8-dots-flash-chunk128", dict(base, batch=8, remat="dots",
                                            attention="flash",
                                            loss_chunk=128)),
            # Bigger proxy: dim-2048 matmuls fill the MXU better than
            # the 200M's dim-1024; reconciles the --estimate projection
            # against a measured point one step closer to the 8B star.
            ("1b-b4-dots-flash", dict(base, model="llama3_1b",
                                      batch=4, remat="dots",
                                      attention="flash")),
            ("1b-b8-dots-flash", dict(base, model="llama3_1b",
                                      batch=8, remat="dots",
                                      attention="flash")),
            ("1b-b4-seq4096-dots-flash", dict(base, model="llama3_1b",
                                              batch=4, seq=4096,
                                              remat="dots",
                                              attention="flash")),
        ]

    out_path = os.path.join(REPO, "perf_sweep_results.json")
    prior: dict[str, dict] = {}
    if args.resume and os.path.exists(out_path):
        with open(out_path) as fh:
            prior = {r["name"]: r for r in json.load(fh).get("results", [])}

    def dump(results):
        # After every point, not just at the end: a Ctrl-C (or a hang
        # killed from outside) must not lose completed measurements —
        # --resume exists for exactly that situation.
        ok = [r for r in results if r.get("value")]
        ok.sort(key=lambda r: -r["value"])
        payload = {"results": results, "best": ok[0] if ok else None}
        if overlap_summary is not None:
            payload["overlap_audit"] = overlap_summary
        with open(out_path, "w") as fh:
            json.dump(payload, fh, indent=2)
        return ok

    results = []
    for name, kw in points:
        kept = prior.get(name) if args.resume else None
        # Reuse only if the prior point measured the SAME config —
        # name alone would merge e.g. a --seq 512 smoke into a
        # seq-2048 table with no warning.
        if kept and kept.get("value") and all(
                kept.get(k) == v for k, v in kw.items()):
            results.append(kept)
            print(f"→ {name}: kept prior "
                  f"{kept['value']} tok/s/chip", flush=True)
            continue
        print(f"→ {name} ...", flush=True)
        res = run_point(name, **kw)
        results.append(res)
        dump(results)
        val = res.get("value")
        print(f"  {name}: "
              + (f"{val} tok/s/chip, mfu={res.get('mfu')}"
                 if val else f"ERROR {res.get('error')}"),
              flush=True)

    ok = dump(results)
    print(f"\nwrote {out_path}\n")
    print(f"{'config':<28} {'tok/s/chip':>12} {'mfu':>8}")
    for r in ok:
        print(f"{r['name']:<28} {r['value']:>12} "
              f"{r.get('mfu') if r.get('mfu') is not None else '-':>8}")
    for r in results:
        if not r.get("value"):
            print(f"{r['name']:<28} ERROR: {r.get('error')}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
