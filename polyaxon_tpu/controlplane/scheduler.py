"""Background scheduler: compiles created runs, expands and advances
pipelines (DAG + matrix/tuner iterations).

haupt's orchestration/scheduler equivalent (SURVEY.md §2 "Scheduler",
§3.2, §3.4 [K]). Everything is driven by idempotent ``tick()`` passes
over the store — no celery; the agent loop (or a test) calls tick.

Matrix state machines live in the pipeline run's ``meta["tuner"]``:
  grid/random/mapping → one-shot fan-out with a concurrency window;
  hyperband           → per-(bracket, rung) advancement with
                         preemption-requeue (SURVEY §7 hard-part 4);
  bayes               → initial batch, then GP-suggested singles.
"""

from __future__ import annotations

import datetime as _dt
import logging
import os
from typing import Any, Optional

from polyaxon_tpu import chaos
from polyaxon_tpu.controlplane.service import ControlPlane
from polyaxon_tpu.controlplane.store import RunRecord
from polyaxon_tpu.lifecycle import V1Statuses, now as _now
from polyaxon_tpu.utils.retries import backoff_delay
from polyaxon_tpu.polyaxonfile import get_operation
from polyaxon_tpu.polyflow.matrix import (
    V1Asha,
    V1Bayes,
    V1GridSearch,
    V1Hyperband,
    V1Hyperopt,
    V1Iterative,
    V1Mapping,
    V1RandomSearch,
)
from polyaxon_tpu.polyflow.operation import V1Operation, V1TriggerPolicy
from polyaxon_tpu.polyflow.runs import V1RunKind
from polyaxon_tpu.tune import (
    AshaManager,
    BayesManager,
    GridSearchManager,
    HyperbandManager,
    HyperoptManager,
    IterativeManager,
    MappingManager,
    Observation,
    RandomSearchManager,
    check_early_stopping,
)

logger = logging.getLogger(__name__)

_DONE = V1Statuses.terminal_values()


def _backoff_params() -> dict:
    """Requeue-backoff knobs (env-tunable; docs/robustness.md)."""
    return {
        "base": float(os.environ.get("POLYAXON_TPU_BACKOFF_BASE", "0.5")),
        "factor": float(os.environ.get("POLYAXON_TPU_BACKOFF_FACTOR", "2.0")),
        "max_delay": float(os.environ.get("POLYAXON_TPU_BACKOFF_MAX", "60")),
        "jitter": float(os.environ.get("POLYAXON_TPU_BACKOFF_JITTER", "0.25")),
    }


def _parse_ts(value: str) -> _dt.datetime:
    ts = _dt.datetime.fromisoformat(value)
    if ts.tzinfo is None:
        ts = ts.replace(tzinfo=_dt.timezone.utc)
    return ts


def _trigger_satisfied(policy: str, statuses: list[V1Statuses]) -> Optional[bool]:
    """True → start, False → won't ever start, None → keep waiting."""
    done = [s for s in statuses if s in _DONE]
    succeeded = [s for s in done if s == V1Statuses.SUCCEEDED]
    # Anything done-but-not-succeeded (incl. SKIPPED) blocks ALL_SUCCEEDED —
    # a skipped upstream must resolve the trigger, never stall it.
    failed = [s for s in done if s != V1Statuses.SUCCEEDED]
    policy = policy or V1TriggerPolicy.ALL_SUCCEEDED
    n = len(statuses)
    if policy == V1TriggerPolicy.ALL_SUCCEEDED:
        if failed:
            return False
        return True if len(succeeded) == n else None
    if policy == V1TriggerPolicy.ALL_FAILED:
        if succeeded:
            return False
        return True if len(failed) == n else None
    if policy == V1TriggerPolicy.ALL_DONE:
        return True if len(done) == n else None
    if policy == V1TriggerPolicy.ONE_SUCCEEDED:
        if succeeded:
            return True
        return False if len(done) == n else None
    if policy == V1TriggerPolicy.ONE_FAILED:
        if failed:
            return True
        return False if len(done) == n else None
    if policy == V1TriggerPolicy.ONE_DONE:
        return True if done else None
    return None


# Parent kinds the pipeline pass advances; matches the agent's skip set.
_PIPELINE_KINDS = ("dag", "matrix", "schedule")


class Scheduler:
    def __init__(self, plane: ControlPlane, *, legacy_scan: bool = False):
        self.plane = plane
        self.store = plane.store
        # FAILED runs that will never restart (no policy / no plan):
        # remembered so the failed pass stays O(new failures) per tick
        # instead of re-parsing every historical failure's spec.
        self._no_restart: set[str] = set()
        # Per-tick memo of pipeline-children lookups: a DAG/tuner tick
        # touches the same child list from several passes; within one
        # tick the store is only mutated by THIS thread, so the memo is
        # exact as long as every submit/stop path invalidates it.
        self._children_cache: dict[str, list[RunRecord]] = {}
        # Bench hook (sim --deopt / the measured A/B): replay the
        # pre-ISSUE-8 six-scans-per-tick behavior, uncached and
        # unbatched, so the budget gate has a "before" it can fail.
        self.legacy_scan = legacy_scan

    # ------------------------------------------------------------------ tick
    def tick(self) -> int:
        """One idempotent scheduling pass; returns number of actions.
        Wall time lands in the ``polyaxon_scheduler_tick_seconds``
        histogram — tick latency is the control plane's heartbeat."""
        import time as _time

        from polyaxon_tpu.obs import metrics as obs_metrics

        t0 = _time.perf_counter()
        try:
            return self._tick()
        finally:
            obs_metrics.scheduler_tick_hist().observe(
                _time.perf_counter() - t0)

    def _tick(self) -> int:
        plan = chaos.active_plan()
        if plan is not None and plan.fire("tick", "skip") is not None:
            # Injected control-plane stall: this tick does nothing; all
            # progress must be recoverable by the next tick (ticks are
            # pure functions of store state).
            return 0
        if self.legacy_scan:
            return self._tick_legacy()
        self._children_cache.clear()
        # All of this tick's writes land in ONE commit: N transitions
        # cost one WAL fsync. Ticks stay idempotent, so a crash that
        # rolls the batch back just means the next tick redoes it.
        with self.store.transaction():
            return self._tick_fast()

    def _tick_fast(self) -> int:
        """One status-partitioned scan instead of six full-status
        queries (sized by the fleet sim — sim/fleet_curve.json): the
        QUEUED/RUNNING partition is kind-filtered AT THE SQL LAYER, so a
        10k-deep backlog of plain jobs contributes zero rows here, and
        lazy RunRecords defer each row's JSON until a pass touches it.
        FAILED is swept via a key-only uuid projection diffed against
        ``_no_restart`` — O(new failures), not O(every failure ever)."""
        snapshot = self.store.scan_runs([
            ([V1Statuses.CREATED, V1Statuses.PREEMPTED,
              V1Statuses.RETRYING], None),
            ([V1Statuses.QUEUED, V1Statuses.RUNNING], _PIPELINE_KINDS),
        ])
        actions = 0
        compiled_pipelines: list[str] = []
        for record in snapshot[V1Statuses.CREATED]:
            actions += self._tick_created(record, compiled_pipelines)
        pipelines = snapshot[V1Statuses.QUEUED] + snapshot[V1Statuses.RUNNING]
        if compiled_pipelines:
            # A pipeline compiled THIS tick is already QUEUED but missed
            # the snapshot above — fold it in so a fresh DAG/tuner still
            # fans out on the tick that compiled it (scan-era behavior).
            pipelines += [r for r in self.store.get_runs(compiled_pipelines)
                          if r.status in (V1Statuses.QUEUED,
                                          V1Statuses.RUNNING)]
        for record in pipelines:
            actions += self._tick_pipeline(record)
        for record in snapshot[V1Statuses.PREEMPTED]:
            actions += self._tick_preempted(record)
        failed_fresh = [u for u in self.store.list_run_uuids(
            statuses=[V1Statuses.FAILED]) if u not in self._no_restart]
        for record in self.store.get_runs(failed_fresh):
            actions += self._tick_failed(record)
        for record in snapshot[V1Statuses.RETRYING]:
            actions += self._tick_retrying(record)
        return actions

    def _tick_created(self, record: RunRecord,
                      compiled_pipelines: list[str]) -> int:
        verdict = self._events_satisfied(record)
        if verdict is None:
            return 0  # still waiting on referenced run events
        if verdict is False:
            self.store.transition(
                record.uuid, V1Statuses.UPSTREAM_FAILED,
                reason="EventNeverFires",
                message="referenced run finished without the awaited event")
            return 1
        try:
            self.plane.compile_run(record.uuid)
            if record.kind in _PIPELINE_KINDS:
                compiled_pipelines.append(record.uuid)
        except Exception as exc:
            # A bad spec (dangling connection, invalid topology...)
            # fails that run; it must not kill the scheduler loop.
            self.store.transition(
                record.uuid, V1Statuses.FAILED,
                reason="CompilationError", message=str(exc)[:500])
        return 1

    def _tick_pipeline(self, record: RunRecord) -> int:
        try:
            if record.kind == "matrix":
                return self._tick_matrix(record)
            if record.kind == V1RunKind.DAG:
                return self._tick_dag(record)
            if record.kind == "schedule":
                return self._tick_schedule(record)
        except Exception as exc:
            # A bad spec (invalid cron, broken matrix...) fails that
            # pipeline; it must never kill the reconcile loop.
            self.store.transition(
                record.uuid, V1Statuses.FAILED,
                reason="PipelineError", message=str(exc)[:500])
            return 1
        return 0

    def _tick_legacy(self) -> int:
        """Pre-ISSUE-8 tick: six separate full-status scans, every row
        eagerly fetched, one commit per transition. Kept as the sim's
        ``--deopt`` baseline and the measured A/B's 'before' side."""
        actions = 0
        for record in self.store.list_runs(statuses=[V1Statuses.CREATED]):
            actions += self._tick_created(record, [])
        for record in self.store.list_runs(
                statuses=[V1Statuses.QUEUED, V1Statuses.RUNNING]):
            if record.kind in _PIPELINE_KINDS:
                actions += self._tick_pipeline(record)
        for record in self.store.list_runs(statuses=[V1Statuses.PREEMPTED]):
            actions += self._tick_preempted(record)
        for record in self.store.list_runs(statuses=[V1Statuses.FAILED]):
            actions += self._tick_failed(record)
        for record in self.store.list_runs(statuses=[V1Statuses.RETRYING]):
            actions += self._tick_retrying(record)
        return actions

    # ------------------------------------------------- children memoization
    def _children(self, pipeline_uuid: str) -> list[RunRecord]:
        """Pipeline-children lookup, memoized for the current tick (the
        DAG/tuner passes re-list the same pipeline's children up to
        three times per tick). Every same-tick mutation path —
        ``_spawn_trial``, the DAG/schedule submits, early-stop — must
        call ``_invalidate_children``. Legacy mode bypasses the memo."""
        if self.legacy_scan:
            return self.store.list_runs(pipeline_uuid=pipeline_uuid)
        cached = self._children_cache.get(pipeline_uuid)
        if cached is None:
            cached = self.store.list_runs(pipeline_uuid=pipeline_uuid)
            self._children_cache[pipeline_uuid] = cached
        return cached

    def _invalidate_children(self, pipeline_uuid: Optional[str]) -> None:
        if pipeline_uuid:
            self._children_cache.pop(pipeline_uuid, None)

    # -------------------------------------------------------------- events
    def _events_satisfied(self, record: RunRecord) -> Optional[bool]:
        """Gate compilation on V1EventTrigger refs.

        True → proceed; None → keep waiting; False → can never fire
        (referenced run is terminal without any awaited status).
        Ref grammar: ``runs.<uuid>``; kinds are lifecycle status names
        (the upstream event vocabulary subset the embedded plane emits).
        """
        events = (record.spec or {}).get("events")
        if not events:
            return True
        for event in events:
            ref = event.get("ref") or ""
            if not ref.startswith("runs."):
                self.store.transition(
                    record.uuid, V1Statuses.FAILED, reason="InvalidEventRef",
                    message=f"event ref {ref!r} must be `runs.<uuid>`")
                return None
            target_uuid = ref[len("runs."):]
            try:
                target = self.store.get_run(target_uuid)
            except Exception:
                self.store.transition(
                    record.uuid, V1Statuses.FAILED, reason="InvalidEventRef",
                    message=f"event ref {ref!r}: run not found")
                return None
            kinds = {str(k).split(".")[-1] for k in event.get("kinds") or []}
            seen = {c["type"] for c in self.store.get_conditions(target_uuid)}
            if not kinds:  # no kinds = "any terminal event"
                if target.is_done:
                    continue
                return None
            if kinds & seen:
                continue
            if target.is_done:
                return False
            return None
        return True

    # ------------------------------------------------- requeue w/ backoff
    def _schedule_requeue(self, record: RunRecord, *, counter: str,
                          delays_key: str, reason: str,
                          force: bool = False) -> float:
        """Move a run into RETRYING with a persisted backoff gate.

        ``meta["backoff"]`` carries the state that makes ticks
        idempotent: per-cause attempt counters, the delay audit trail,
        and ``not_before`` — the wall-clock time before which the
        RETRYING pass refuses to requeue (so a crash-looping run cannot
        hot-loop the scheduler, and a requeued run is never re-popped
        early). Jitter is keyed by (uuid, attempt): recomputing the
        same requeue yields the same delay.
        """
        meta = dict(record.meta or {})
        backoff = dict(meta.get("backoff") or {})
        attempt = int(backoff.get(counter, 0))
        delay = backoff_delay(attempt, key=f"{record.uuid}:{counter}:{attempt}",
                              **_backoff_params())
        not_before = _now() + _dt.timedelta(seconds=delay)
        backoff[counter] = attempt + 1
        backoff[delays_key] = list(backoff.get(delays_key) or []) + [
            round(delay, 4)]
        backoff["not_before"] = not_before.isoformat()
        meta["backoff"] = backoff
        self.store.update_run(record.uuid, meta=meta)
        self.store.transition(
            record.uuid, V1Statuses.RETRYING, reason=reason,
            message=f"requeue attempt {attempt + 1} in {delay:.2f}s",
            force=force)
        # The requeue is a timeline annotation (obs.trace) + a counter:
        # a chaos drill's kill→retry reads off the run's waterfall, and
        # requeue volume per reason is a scrapeable signal.
        from polyaxon_tpu.obs import metrics as obs_metrics
        from polyaxon_tpu.obs import trace as obs_trace

        obs_metrics.requeues_total().inc(reason=reason)
        try:
            obs_trace.record_event(
                self.plane.run_artifacts_dir(record.uuid), record.uuid,
                "requeue", component="controlplane",
                attributes={"reason": reason, "counter": counter,
                            "attempt": attempt + 1,
                            "delay_s": round(delay, 4)})
        except OSError:
            logger.warning("could not record requeue span event for %s",
                           record.uuid, exc_info=True)
        return delay

    def _tick_retrying(self, record: RunRecord) -> int:
        """RETRYING → QUEUED once the backoff gate has passed."""
        backoff = (record.meta or {}).get("backoff") or {}
        not_before = backoff.get("not_before")
        if not_before and _now() < _parse_ts(not_before):
            return 0
        self.store.transition(record.uuid, V1Statuses.QUEUED)
        return 1

    # ------------------------------------------------------------ preemption
    def _tick_preempted(self, record: RunRecord) -> int:
        """Requeue preempted runs per termination policy (preemption does
        not consume a retry unless the spec says so — TPU-native rule).
        The requeue goes through the backoff gate so a flapping spot
        slice cannot hot-loop preempt→requeue→preempt."""
        # A run mid-resize is NOT a requeue candidate: the elastic
        # executor is shrinking/regrowing it in place (runtime.elastic)
        # and will either resume it RUNNING or clear the flag before the
        # PREEMPTED fallback reap — requeueing now would double-run it.
        if ((record.meta or {}).get("elastic") or {}).get("resizing"):
            return 0
        op = get_operation(record.spec)
        term = op.termination or (op.component.termination if op.component else None)
        counts = bool(term and term.preemption_counts_as_retry)
        max_retries = term.max_retries if term and term.max_retries is not None else 3
        if counts:
            if record.retries + 1 > max_retries:
                # Stamp the backoff state exhausted so the failure-
                # restart pass cannot resurrect a run whose preemption
                # budget is already spent.
                meta = dict(record.meta or {})
                meta["backoff"] = {**(meta.get("backoff") or {}),
                                   "exhausted": True}
                self.store.update_run(record.uuid, meta=meta)
                self.store.transition(record.uuid, V1Statuses.FAILED,
                                      reason="RetriesExhausted")
                return 1
            self.store.update_run(record.uuid, retries=record.retries + 1)
            record = self.store.get_run(record.uuid)
        # Control-plane-driven evictions (admission starvation valve —
        # scheduling/admission.py stamps the preemptor) requeue through
        # the same backoff gate but keep the audit trail visible.
        evicted_for = ((record.meta or {}).get("scheduling")
                       or {}).get("evicted_for")
        self._schedule_requeue(
            record, counter="preempts", delays_key="preempt_delays",
            reason="PreemptedForPriority" if evicted_for else "Preempted")
        return 1

    # ------------------------------------------------------ restart policy
    @staticmethod
    def _restart_policy(op: V1Operation) -> Optional[str]:
        """Normalized run-level restart policy: {never, on_failure,
        always} from the run environment (k8s spellings accepted)."""
        run = op.component.run if op.component else None
        env = getattr(run, "environment", None)
        policy = getattr(env, "restart_policy", None)
        if not policy:
            return None
        normalized = str(policy).replace("-", "_").lower()
        if normalized == "onfailure":
            normalized = "on_failure"
        return normalized

    def _tick_failed(self, record: RunRecord) -> int:
        """Enforce ``restart_policy`` ∈ {never, on_failure, always} for
        FAILED runs: requeue through the backoff gate until the retry
        budget (``termination.maxRetries``, default 3) is spent, then
        pin a terminal ``RetriesExhausted`` condition.

        Only runs that actually launched (have a plan) restart —
        re-running a spec that cannot compile converges to the same
        failure without doing work.
        """
        if record.uuid in self._no_restart:
            return 0
        backoff = (record.meta or {}).get("backoff") or {}
        if backoff.get("exhausted"):
            self._no_restart.add(record.uuid)
            return 0
        try:
            op = get_operation(record.spec)
        except Exception:  # noqa: BLE001 — an unparsable spec never restarts
            self._no_restart.add(record.uuid)
            return 0
        policy = self._restart_policy(op)
        if policy not in ("on_failure", "always") or not record.launch_plan:
            self._no_restart.add(record.uuid)
            return 0
        term = op.termination or (op.component.termination if op.component else None)
        max_retries = term.max_retries if term and term.max_retries is not None else 3
        attempts = int(backoff.get("restarts", 0))
        if attempts >= max_retries:
            meta = dict(record.meta or {})
            meta["backoff"] = {**backoff, "exhausted": True}
            self.store.update_run(record.uuid, meta=meta)
            self.store.transition(
                record.uuid, V1Statuses.FAILED, reason="RetriesExhausted",
                message=f"restart_policy={policy} consumed all "
                        f"{max_retries} retries", force=True)
            self._no_restart.add(record.uuid)
            return 1
        self.store.update_run(record.uuid, retries=attempts + 1)
        self._schedule_requeue(record, counter="restarts",
                               delays_key="delays",
                               reason="RestartPolicy", force=True)
        return 1

    # ------------------------------------------------------------------- dag
    @staticmethod
    def _validate_dag(dag) -> Optional[str]:
        """Unknown dependency names or cycles → error message, else None."""
        ops = [
            o if isinstance(o, V1Operation) else get_operation(dict(o))
            for o in dag.operations
        ]
        names = [o.name for o in ops]
        if len(set(names)) != len(names):
            dupes = {n for n in names if names.count(n) > 1}
            return f"duplicate operation names: {sorted(dupes)}"
        # Dedupe: a twice-listed dependency must not skew cycle detection.
        deps = {o.name: sorted(set(o.dependencies or [])) for o in ops}
        known = set(names)
        for name, dep_list in deps.items():
            unknown = [d for d in dep_list if d not in known]
            if unknown:
                return f"operation `{name}` depends on unknown ops: {unknown}"
        # Kahn's algorithm: leftover nodes ⇒ cycle.
        indeg = {n: len(deps[n]) for n in names}
        ready = [n for n, d in indeg.items() if d == 0]
        seen = 0
        while ready:
            node = ready.pop()
            seen += 1
            for other, dep_list in deps.items():
                if node in dep_list:
                    indeg[other] -= 1
                    if indeg[other] == 0:
                        ready.append(other)
        if seen != len(names):
            cyclic = sorted(n for n, d in indeg.items() if d > 0)
            return f"dependency cycle among: {cyclic}"
        return None

    def _tick_dag(self, record: RunRecord) -> int:
        op = get_operation(record.spec)
        dag = op.component.run
        children = self._children(record.uuid)
        by_name = {c.name: c for c in children}
        actions = 0

        if record.status == V1Statuses.QUEUED:
            error = self._validate_dag(dag)
            if error:
                self.store.transition(record.uuid, V1Statuses.FAILED,
                                      reason="InvalidDag", message=error)
                return 1
            self.store.transition(record.uuid, V1Statuses.SCHEDULED)
            self.store.transition(record.uuid, V1Statuses.RUNNING,
                                  reason="PipelineRunning", force=True)
            actions += 1

        for op_data in dag.operations:
            child_op = op_data if isinstance(op_data, V1Operation) else get_operation(dict(op_data))
            cname = child_op.name
            if cname in by_name:
                continue
            deps = child_op.dependencies or []
            dep_statuses = [by_name[d].status for d in deps if d in by_name]
            if len(dep_statuses) < len(deps):
                continue  # upstream not created yet
            verdict = _trigger_satisfied(child_op.trigger, dep_statuses) if deps else True
            if verdict is None:
                continue
            if verdict is False:
                skip = bool(child_op.skip_on_upstream_skip) or any(
                    s == V1Statuses.SKIPPED for s in dep_statuses
                )
                created = self.plane.submit(
                    op=child_op, project=record.project, name=cname,
                    pipeline_uuid=record.uuid, parent_uuid=record.uuid,
                )
                self.store.transition(
                    created.uuid,
                    V1Statuses.SKIPPED if skip else V1Statuses.UPSTREAM_FAILED,
                    reason="UpstreamTrigger", force=True,
                )
                self._invalidate_children(record.uuid)
                actions += 1
                continue
            self.plane.submit(
                op=child_op, project=record.project, name=cname,
                pipeline_uuid=record.uuid, parent_uuid=record.uuid,
            )
            self._invalidate_children(record.uuid)
            actions += 1

        # Pipeline completion: every declared op exists and is done.
        children = self._children(record.uuid)
        declared = len(dag.operations)
        if len(children) == declared and all(c.is_done for c in children):
            failed = any(c.status in (V1Statuses.FAILED, V1Statuses.UPSTREAM_FAILED)
                         for c in children)
            stopped = any(c.status == V1Statuses.STOPPED for c in children)
            if failed:
                target = V1Statuses.FAILED
            elif stopped:  # cancelled work is not success
                target = V1Statuses.STOPPED
            else:
                target = V1Statuses.SUCCEEDED
            self.store.transition(record.uuid, target, reason="PipelineDone")
            actions += 1
        return actions

    # -------------------------------------------------------------- schedule
    def _tick_schedule(self, record: RunRecord, *, now=None) -> int:
        """Recurring parent run: fire child runs per its V1*Schedule.

        ``now`` is injectable for tests. ``last_fire`` advances to the
        computed fire time (not wall clock) so cadence never drifts.
        """
        import datetime as dt

        from polyaxon_tpu.controlplane.cron import next_fire
        from polyaxon_tpu.polyflow.schedules import (
            V1CronSchedule,
            V1DateTimeSchedule,
            V1IntervalSchedule,
        )

        def as_utc(value) -> dt.datetime:
            if isinstance(value, str):
                value = dt.datetime.fromisoformat(value)
            if value.tzinfo is None:
                return value.replace(tzinfo=dt.timezone.utc)
            return value.astimezone(dt.timezone.utc)

        op = get_operation(record.spec)
        sched = op.schedule
        meta = dict(record.meta or {})
        state = dict(meta.get("schedule") or {})
        fired = int(state.get("fired", 0))
        now = as_utc(now) if now is not None else dt.datetime.now(dt.timezone.utc)
        actions = 0

        if record.status == V1Statuses.QUEUED:
            self.store.transition(record.uuid, V1Statuses.SCHEDULED)
            self.store.transition(record.uuid, V1Statuses.RUNNING,
                                  reason="ScheduleActive", force=True)
            actions += 1

        created = as_utc(record.created_at)
        last_fire = as_utc(state["last_fire"]) if state.get("last_fire") else None

        # Next fire time per schedule kind (None ⇒ exhausted).
        next_at: dt.datetime | None
        if isinstance(sched, V1DateTimeSchedule):
            next_at = None if fired else as_utc(sched.start_at)
        elif isinstance(sched, V1IntervalSchedule):
            start = as_utc(sched.start_at) if sched.start_at else created
            next_at = start if (fired == 0 and sched.start_at) else (
                (last_fire or start) + dt.timedelta(seconds=sched.frequency))
        elif isinstance(sched, V1CronSchedule):
            base = last_fire or (as_utc(sched.start_at) if sched.start_at else created)
            next_at = next_fire(sched.cron, base)
        else:
            self.store.transition(record.uuid, V1Statuses.FAILED,
                                  reason="UnsupportedSchedule",
                                  message=type(sched).__name__)
            return actions + 1

        max_runs = getattr(sched, "max_runs", None)
        end_at = getattr(sched, "end_at", None)
        exhausted = (
            next_at is None
            or (max_runs is not None and fired >= max_runs)
            or (end_at is not None and next_at > as_utc(end_at))
        )
        children = self._children(record.uuid)
        if exhausted:
            if all(c.is_done for c in children):
                self.store.transition(record.uuid, V1Statuses.SUCCEEDED,
                                      reason="ScheduleDone",
                                      message=f"fired {fired} runs")
                actions += 1
            return actions

        if now < next_at:
            return actions
        if getattr(sched, "depends_on_past", None) and any(
                not c.is_done for c in children):
            return actions  # wait for the previous fire to finish

        child_op = op.clone()
        child_op.schedule = None
        child_op.name = None
        self.plane.submit(
            op=child_op, project=record.project,
            name=f"{record.name or 'scheduled'}-{fired}",
            pipeline_uuid=record.uuid, parent_uuid=record.uuid,
            iteration=fired,
        )
        self._invalidate_children(record.uuid)
        state.update({"fired": fired + 1, "last_fire": next_at.isoformat()})
        meta["schedule"] = state
        self.store.update_run(record.uuid, meta=meta)
        return actions + 1

    # ---------------------------------------------------------------- matrix
    def _observations(self, record: RunRecord, metric_name: str,
                      children: list[RunRecord]) -> list[Observation]:
        obs = []
        for child in children:
            params = (child.meta or {}).get("trial_params") or {}
            if child.status == V1Statuses.SUCCEEDED:
                value = self.plane.get_metric(child.uuid, metric_name)
                obs.append(Observation(params=params, metric=value,
                                       status="succeeded"))
            elif child.status == V1Statuses.PREEMPTED:
                obs.append(Observation(params=params, metric=None, status="preempted"))
            elif child.is_done:
                obs.append(Observation(params=params, metric=None, status="failed"))
        return obs

    def _spawn_trial(self, record: RunRecord, op: V1Operation, params: dict,
                     index: int, iteration: Optional[int] = None,
                     extra_meta: Optional[dict] = None) -> RunRecord:
        child_spec = op.clone()
        child_spec.matrix = None
        child_spec.name = None
        meta = {"trial_params": params, "trial_index": index}
        if extra_meta:
            meta.update(extra_meta)
        child = self.plane.submit(
            op=child_spec,
            project=record.project,
            name=f"{record.name or 'matrix'}-{index}",
            pipeline_uuid=record.uuid,
            parent_uuid=record.uuid,
            iteration=iteration,
            meta=meta,
        )
        self._invalidate_children(record.uuid)
        return child

    def _tick_matrix(self, record: RunRecord) -> int:
        op = get_operation(record.spec)
        matrix = op.matrix
        meta = dict(record.meta or {})
        tuner: dict[str, Any] = meta.get("tuner") or {}
        children = self._children(record.uuid)
        actions = 0

        if record.status == V1Statuses.QUEUED:
            self.store.transition(record.uuid, V1Statuses.SCHEDULED)
            self.store.transition(record.uuid, V1Statuses.RUNNING,
                                  reason="TunerRunning", force=True)
            actions += 1

        early = self._tick_early_stop(record, matrix, meta, children)
        if early is not None:
            return actions + early

        if isinstance(matrix, (V1GridSearch, V1RandomSearch, V1Mapping)):
            actions += self._tick_oneshot(record, op, matrix, tuner, meta, children)
        elif isinstance(matrix, V1Hyperband):
            actions += self._tick_hyperband(record, op, matrix, tuner, meta, children)
        elif isinstance(matrix, V1Asha):
            actions += self._tick_asha(record, op, matrix, tuner, meta, children)
        elif isinstance(matrix, V1Bayes):
            actions += self._tick_smbo(
                record, op, matrix, BayesManager(matrix), tuner, meta, children,
                num_initial=matrix.num_initial_runs,
                total_budget=matrix.num_initial_runs + matrix.max_iterations,
                reason="BayesDone")
        elif isinstance(matrix, V1Hyperopt):
            actions += self._tick_smbo(
                record, op, matrix, HyperoptManager(matrix), tuner, meta, children,
                num_initial=matrix.startup_trials,
                total_budget=matrix.total_budget,
                reason="HyperoptDone")
        elif isinstance(matrix, V1Iterative):
            actions += self._tick_iterative(record, op, matrix, tuner, meta, children)
        else:
            self.store.transition(record.uuid, V1Statuses.FAILED,
                                  reason="UnsupportedMatrix",
                                  message=f"{type(matrix).__name__}")
            actions += 1
        return actions

    def _tick_early_stop(self, record: RunRecord, matrix, meta: dict,
                         children: list[RunRecord]) -> Optional[int]:
        """Early-stopping policies: once triggered, stop in-flight trials
        and finish the sweep when they drain. Returns None when the sweep
        should keep ticking normally."""
        state = meta.get("early_stopped")
        if state is None:
            action = check_early_stopping(
                getattr(matrix, "early_stopping", None),
                lambda name: self._observations(record, name, children),
            )
            if action is None:
                return None
            meta["early_stopped"] = state = action
            self.store.update_run(record.uuid, meta=meta)
            for child in children:
                if not child.is_done:
                    self.plane.stop(child.uuid)
            self._invalidate_children(record.uuid)
        # Drain phase: wait for every child, then finish.
        if not all(c.is_done for c in children):
            return 0
        if state == "fail":
            self.store.transition(record.uuid, V1Statuses.FAILED,
                                  reason="FailureEarlyStopping")
        else:
            self.store.transition(record.uuid, V1Statuses.SUCCEEDED,
                                  reason="MetricEarlyStopping",
                                  message="target metric reached")
        return 1

    def _tick_iterative(self, record, op, matrix: V1Iterative, tuner, meta,
                        children) -> int:
        """Sequential suggest→run→observe loop (one trial per iteration,
        up to `concurrency` in flight)."""
        if matrix.tuner:
            # Upstream runs custom tuners as services; the embedded plane
            # only ships the builtin policy — fail loudly, never silently
            # substitute random search for the user's strategy.
            self.store.transition(
                record.uuid, V1Statuses.FAILED, reason="UnsupportedTuner",
                message="custom `tuner` services are not supported by the "
                        "embedded plane; omit `tuner` for builtin iteration")
            return 1
        manager = IterativeManager(matrix)
        tuner = tuner or {"spawned": 0}
        active = [c for c in children if not c.is_done]
        actions = 0
        if tuner["spawned"] >= matrix.max_iterations:
            return self._finish_if_done(record, children, matrix.max_iterations)
        concurrency = matrix.concurrency or 1
        while (tuner["spawned"] < matrix.max_iterations
               and len(active) < concurrency):
            params = manager.get_suggestion(tuner["spawned"])
            child = self._spawn_trial(record, op, params, tuner["spawned"],
                                      iteration=tuner["spawned"])
            active.append(child)
            tuner["spawned"] += 1
            actions += 1
        if actions:
            meta["tuner"] = tuner
            self.store.update_run(record.uuid, meta=meta)
        return actions

    def _finish_if_done(self, record: RunRecord, children: list[RunRecord],
                        expected: int) -> int:
        if len(children) >= expected and all(c.is_done for c in children):
            any_ok = any(c.status == V1Statuses.SUCCEEDED for c in children)
            any_stopped = any(c.status == V1Statuses.STOPPED for c in children)
            if any_ok or not children:  # degenerate empty sweep is not a failure
                target = V1Statuses.SUCCEEDED
            elif any_stopped:
                target = V1Statuses.STOPPED
            else:
                target = V1Statuses.FAILED
            self.store.transition(record.uuid, target, reason="TunerDone")
            return 1
        return 0

    def _tick_oneshot(self, record, op, matrix, tuner, meta, children) -> int:
        actions = 0
        if not tuner.get("suggested"):
            if isinstance(matrix, V1GridSearch):
                suggestions = GridSearchManager(matrix).get_suggestions()
            elif isinstance(matrix, V1RandomSearch):
                suggestions = RandomSearchManager(matrix).get_suggestions()
            else:
                suggestions = MappingManager(matrix).get_suggestions()
            tuner = {"suggested": True, "pending": suggestions, "spawned": 0,
                     "total": len(suggestions)}
        concurrency = matrix.concurrency or 0
        pending = list(tuner.get("pending") or [])
        active = len([c for c in children if not c.is_done])
        while pending and (not concurrency or active < concurrency):
            params = pending.pop(0)
            self._spawn_trial(record, op, params, tuner["spawned"])
            tuner["spawned"] += 1
            active += 1
            actions += 1
        tuner["pending"] = pending
        meta["tuner"] = tuner
        self.store.update_run(record.uuid, meta=meta)
        children = self._children(record.uuid)
        actions += self._finish_if_done(record, children, tuner.get("total", 0))
        return actions

    def _spawn_rung(self, record, op, manager: HyperbandManager, tuner, meta,
                    bracket: int, rung) -> int:
        """Spawn every trial of a rung, track uuids in tuner, persist meta."""
        tuner["rung_uuids"] = []
        for params in rung.suggestions:
            trial = dict(params)
            trial[manager.resource_param()] = rung.resource
            child = self._spawn_trial(
                record, op, trial, tuner["spawned"],
                iteration=rung.rung,
                extra_meta={"bracket": bracket, "rung": rung.rung},
            )
            tuner["rung_uuids"].append(child.uuid)
            tuner["spawned"] += 1
        meta["tuner"] = tuner
        self.store.update_run(record.uuid, meta=meta)
        return len(rung.suggestions)

    def _tick_hyperband(self, record, op, matrix: V1Hyperband, tuner, meta, children) -> int:
        manager = HyperbandManager(matrix)
        actions = 0
        if not tuner:
            bracket = manager.brackets()[0]
            rung = manager.first_rung(bracket)
            tuner = {"bracket": bracket, "rung": 0, "spawned": 0,
                     "rung_uuids": [], "bracket_index": 0}
            return self._spawn_rung(record, op, manager, tuner, meta, bracket, rung)

        rung_children = [c for c in children if c.uuid in set(tuner["rung_uuids"])]
        # Requeue preempted trials at the same rung with the same params.
        for child in rung_children:
            if child.status == V1Statuses.PREEMPTED:
                return 0  # scheduler's preemption pass requeues it in place
        if not all(c.is_done for c in rung_children):
            return 0

        obs = self._observations(record, matrix.metric.name, rung_children)
        s, i = tuner["bracket"], tuner["rung"]
        next_rung = manager.next_rung(s, i, obs)
        if next_rung is not None:
            tuner["rung"] = next_rung.rung
            return self._spawn_rung(record, op, manager, tuner, meta, s, next_rung)

        # Bracket exhausted → next bracket or done.
        brackets = manager.brackets()
        next_index = tuner["bracket_index"] + 1
        if next_index < len(brackets):
            bracket = brackets[next_index]
            rung = manager.first_rung(bracket)
            tuner.update({"bracket": bracket, "rung": 0,
                          "bracket_index": next_index})
            return self._spawn_rung(record, op, manager, tuner, meta, bracket, rung)

        all_children = self._children(record.uuid)
        any_ok = any(c.status == V1Statuses.SUCCEEDED for c in all_children)
        self.store.transition(
            record.uuid,
            V1Statuses.SUCCEEDED if any_ok else V1Statuses.FAILED,
            reason="HyperbandDone",
            message=None if any_ok else "all trials failed",
        )
        return actions + 1

    def _tick_asha(self, record, op, matrix: V1Asha, tuner, meta,
                   children) -> int:
        """Asynchronous successive halving: NO rung barrier. Every tick,
        (a) any completed trial ranking in the top 1/eta of COMPLETED
        trials at its rung is promoted to the next rung immediately, and
        (b) free concurrency slots are filled with fresh bottom-rung
        trials — so a straggler or preempted sibling (requeued in place
        by the scheduler's preemption pass) never stalls the sweep. The
        promotion set is recomputed from children state each tick; the
        tuner meta records what was already promoted so ticks stay
        idempotent."""
        import random as _random

        manager = AshaManager(matrix)
        tuner = tuner or {"spawned": 0, "promoted": {}}
        # Unseeded sweeps draw a base seed once (persisted in meta) so
        # re-launching explores NEW points while each sweep stays
        # tick-stable.
        if "seed" not in tuner:
            tuner["seed"] = (matrix.seed if matrix.seed is not None
                             else _random.randrange(2**31))
        actions = 0
        # Falsy concurrency = unlimited, like every other tuner here.
        concurrency = matrix.concurrency or float("inf")

        by_rung: dict[int, list[RunRecord]] = {}
        for child in children:
            by_rung.setdefault((child.meta or {}).get("rung", 0),
                               []).append(child)
        active = sum(1 for c in children if not c.is_done)

        # (a) promotions, bottom-up so a trial can climb one rung/tick.
        for rung_idx in sorted(by_rung):
            if rung_idx + 1 >= manager.n_rungs():
                continue  # top rung is terminal
            # "Completed" includes failed trials: they stay in the
            # rung-size denominator and rank worst (metric None) — the
            # paper's n, not just the success count.
            completed = [
                (c.uuid, (c.meta or {}).get("trial_params") or {},
                 self.plane.get_metric(c.uuid, matrix.metric.name)
                 if c.status == V1Statuses.SUCCEEDED else None)
                for c in by_rung[rung_idx]
                if c.is_done and c.status != V1Statuses.PREEMPTED
            ]
            already = set(tuner["promoted"].get(str(rung_idx), []))
            for uuid in manager.promotable(completed):
                if uuid in already or active >= concurrency:
                    continue
                params = next(p for u, p, _ in completed if u == uuid)
                trial = dict(params)
                trial[matrix.resource.name] = manager.rungs[rung_idx + 1]
                self._spawn_trial(
                    record, op, trial, tuner["spawned"],
                    iteration=rung_idx + 1,
                    extra_meta={"bracket": 0, "rung": rung_idx + 1,
                                "promoted_from": uuid})
                tuner["promoted"].setdefault(str(rung_idx), []).append(uuid)
                tuner["spawned"] += 1
                active += 1
                actions += 1

        # (b) fresh bottom-rung trials into remaining capacity.
        while (tuner.get("sampled", 0) < matrix.num_runs
               and active < concurrency):
            index = tuner.get("sampled", 0)
            trial = manager.sample_params(index, base_seed=tuner["seed"])
            trial[matrix.resource.name] = manager.rungs[0]
            self._spawn_trial(record, op, trial, tuner["spawned"],
                              iteration=0,
                              extra_meta={"bracket": 0, "rung": 0})
            tuner["sampled"] = index + 1
            tuner["spawned"] += 1
            active += 1
            actions += 1

        if actions:
            meta["tuner"] = tuner
            self.store.update_run(record.uuid, meta=meta)
            return actions

        # Done when the budget is drawn, everything finished, and the
        # pass above found nothing left to promote.
        if (tuner.get("sampled", 0) >= matrix.num_runs
                and children and all(c.is_done for c in children)):
            any_ok = any(c.status == V1Statuses.SUCCEEDED for c in children)
            self.store.transition(
                record.uuid,
                V1Statuses.SUCCEEDED if any_ok else V1Statuses.FAILED,
                reason="AshaDone",
                message=None if any_ok else "all trials failed",
            )
            return 1
        return 0

    def _tick_smbo(self, record, op, matrix, manager, tuner, meta, children,
                   *, num_initial: int, total_budget: int, reason: str) -> int:
        """Sequential model-based optimization loop shared by Bayes and
        Hyperopt sweeps: spawn the initial random batch (respecting the
        concurrency cap), then one model-guided suggestion per free
        concurrency slot until the budget is spent."""
        actions = 0
        concurrency = matrix.concurrency or 1
        if not tuner:
            tuner = {"spawned": 0, "phase": "initial",
                     "pending_initial": manager.initial_suggestions()}

        # Drain the startup batch first, never exceeding concurrency.
        pending = list(tuner.get("pending_initial") or [])
        if pending:
            active_n = len([c for c in children if not c.is_done])
            while pending and active_n < concurrency:
                self._spawn_trial(record, op, pending.pop(0),
                                  tuner["spawned"], iteration=0)
                tuner["spawned"] += 1
                active_n += 1
                actions += 1
            if actions:
                tuner["pending_initial"] = pending
                meta["tuner"] = tuner
                self.store.update_run(record.uuid, meta=meta)
            return actions

        active = [c for c in children if not c.is_done]
        obs = self._observations(record, matrix.metric.name, children)
        finished = [o for o in obs if o.status != "preempted"]
        if tuner["spawned"] >= total_budget:
            if not active:
                any_ok = any(c.status == V1Statuses.SUCCEEDED for c in children)
                self.store.transition(
                    record.uuid,
                    V1Statuses.SUCCEEDED if any_ok else V1Statuses.FAILED,
                    reason=reason,
                    message=None if any_ok else "all trials failed",
                )
                actions += 1
            return actions
        if len(active) >= concurrency:
            return 0
        if len(finished) < num_initial:
            return 0  # wait for the initial batch before modeling
        count = min(concurrency - len(active), total_budget - tuner["spawned"])
        for params in manager.get_suggestions(obs, count=count):
            self._spawn_trial(record, op, params, tuner["spawned"],
                              iteration=tuner["spawned"] - num_initial + 1)
            tuner["spawned"] += 1
            actions += 1
        meta["tuner"] = tuner
        self.store.update_run(record.uuid, meta=meta)
        return actions
