"""MoE (expert parallel) + pipeline parallel tests — SURVEY.md §2b EP/PP
obligations, validated on the 8-device CPU mesh."""

import dataclasses
import tempfile

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from polyaxon_tpu.models import llama, moe
from polyaxon_tpu.polyflow.runs import V1JAXJob, V1MeshSpec
from polyaxon_tpu.runtime import run_jaxjob


class TestMoE:
    def test_dispatch_matches_dense_reference(self):
        """Capacity-unconstrained one-hot dispatch == per-expert loop."""
        cfg = dataclasses.replace(
            moe.CONFIGS["moe_tiny"], dtype=jnp.float32, capacity_factor=8.0)
        D, E, F, K = cfg.dim, cfg.n_experts, cfg.ffn_dim, cfg.experts_per_token
        x = jax.random.normal(jax.random.key(0), (2, 16, D), jnp.float32)
        ks = jax.random.split(jax.random.key(1), 4)
        rw = jax.random.normal(ks[0], (D, E)) * 0.1
        wg = jax.random.normal(ks[1], (E, D, F)) * 0.05
        wu = jax.random.normal(ks[2], (E, D, F)) * 0.05
        wd = jax.random.normal(ks[3], (E, F, D)) * 0.05
        out, aux = moe.moe_block(cfg, x, rw, wg, wu, wd)

        tokens = x.reshape(-1, D)
        probs = jax.nn.softmax(tokens @ rw, -1)
        top_p, top_i = jax.lax.top_k(probs, K)
        top_p = top_p / top_p.sum(-1, keepdims=True)
        ref = jnp.zeros_like(tokens)
        for k in range(K):
            for e in range(E):
                h = jax.nn.silu(tokens @ wg[e]) * (tokens @ wu[e]) @ wd[e]
                ref = ref + jnp.where(
                    (top_i[:, k] == e)[:, None], top_p[:, k:k + 1] * h, 0)
        np.testing.assert_allclose(out.reshape(-1, D), ref, atol=1e-5)
        assert float(aux) > 0.9  # ≈1 for near-uniform routing

    def test_expert_choice_matches_dense_mixture(self):
        """With capacity >= T every expert takes every token, so
        expert-choice output equals the fully dense mixture
        sum_e probs[t,e] * ffn_e(token_t), and aux is exactly 0."""
        cfg = dataclasses.replace(
            moe.CONFIGS["moe_tiny"], dtype=jnp.float32,
            router="expert_choice", capacity_factor=100.0)
        D, E, F = cfg.dim, cfg.n_experts, cfg.ffn_dim
        x = jax.random.normal(jax.random.key(0), (2, 8, D), jnp.float32)
        ks = jax.random.split(jax.random.key(1), 4)
        rw = jax.random.normal(ks[0], (D, E)) * 0.1
        wg = jax.random.normal(ks[1], (E, D, F)) * 0.05
        wu = jax.random.normal(ks[2], (E, D, F)) * 0.05
        wd = jax.random.normal(ks[3], (E, F, D)) * 0.05
        out, aux = moe.moe_block(cfg, x, rw, wg, wu, wd)
        assert float(aux) == 0.0

        tokens = x.reshape(-1, D)
        probs = jax.nn.softmax(tokens @ rw, -1)
        ref = jnp.zeros_like(tokens)
        for e in range(E):
            h = jax.nn.silu(tokens @ wg[e]) * (tokens @ wu[e]) @ wd[e]
            ref = ref + probs[:, e:e + 1] * h
        np.testing.assert_allclose(out.reshape(-1, D), ref, atol=1e-5)

    def test_expert_choice_trains(self, cpu_devices):
        cfg = dataclasses.replace(moe.CONFIGS["moe_tiny"],
                                  router="expert_choice")
        v = moe.init(cfg, jax.random.key(0))
        batch = {"tokens": jax.random.randint(jax.random.key(1), (2, 16), 0,
                                              cfg.vocab_size)}
        loss, metrics, _ = moe.apply(cfg, v, batch)
        assert np.isfinite(float(loss))
        grads = jax.grad(
            lambda p: moe.apply(cfg, {"params": p, "state": {}}, batch)[0]
        )(v["params"])
        assert all(bool(jnp.isfinite(g).all()) for g in jax.tree.leaves(grads))
        # Router grads must flow through the expert-choice gather/top_k.
        assert float(jnp.abs(grads["layers"]["router"]).max()) > 0

    def test_capacity_drops_overflow_tokens(self):
        """capacity_factor → tiny: most tokens dropped, output ≈ partial."""
        cfg = dataclasses.replace(
            moe.CONFIGS["moe_tiny"], dtype=jnp.float32, capacity_factor=0.01,
            experts_per_token=1)
        D, E, F = cfg.dim, cfg.n_experts, cfg.ffn_dim
        x = jax.random.normal(jax.random.key(0), (2, 64, D), jnp.float32)
        ks = jax.random.split(jax.random.key(1), 4)
        out, _ = moe.moe_block(
            cfg, x,
            jax.random.normal(ks[0], (D, E)) * 0.1,
            jax.random.normal(ks[1], (E, D, F)) * 0.05,
            jax.random.normal(ks[2], (E, D, F)) * 0.05,
            jax.random.normal(ks[3], (E, F, D)) * 0.05)
        # capacity = max(ceil(128*0.01/4), 1) = 1 slot/expert → ≤E tokens routed
        routed_rows = jnp.sum(jnp.any(out.reshape(-1, D) != 0, axis=-1))
        assert int(routed_rows) <= cfg.n_experts

    def test_ragged_matches_dense_no_drop_single_shard(self):
        """dispatch='ragged' (count-based gather/scatter + batched FFN,
        no one-hot einsums) == dispatch='dense' at no-drop capacity —
        the ep=1 degenerate path, no mesh required (VERDICT r2 item 3)."""
        base = dataclasses.replace(
            moe.CONFIGS["moe_tiny"], dtype=jnp.float32, capacity_factor=8.0)
        D, E, F = base.dim, base.n_experts, base.ffn_dim
        x = jax.random.normal(jax.random.key(0), (2, 16, D), jnp.float32)
        ks = jax.random.split(jax.random.key(1), 4)
        args = (x,
                jax.random.normal(ks[0], (D, E)) * 0.1,
                jax.random.normal(ks[1], (E, D, F)) * 0.05,
                jax.random.normal(ks[2], (E, D, F)) * 0.05,
                jax.random.normal(ks[3], (E, F, D)) * 0.05)
        dense_out, dense_aux = moe.moe_block(base, *args)
        ragged_out, ragged_aux = moe.moe_block(
            dataclasses.replace(base, dispatch="ragged"), *args)
        np.testing.assert_allclose(np.asarray(ragged_out),
                                   np.asarray(dense_out), atol=1e-5)
        np.testing.assert_allclose(float(ragged_aux), float(dense_aux),
                                   rtol=1e-6)

    def test_ragged_matches_dense_under_ep_mesh(self, cpu_devices):
        """Full forward parity dense↔ragged under a dp2×ep4 mesh with
        sharded params: the explicit all_to_all dispatch/combine path
        computes the same function the GSPMD dense path does."""
        from polyaxon_tpu.parallel.sharding import (
            rules_for_mesh,
            tree_shardings,
        )

        base = dataclasses.replace(
            moe.CONFIGS["moe_tiny"], dtype=jnp.float32, capacity_factor=8.0)
        variables = moe.init(base, jax.random.key(0))
        tokens = jax.random.randint(jax.random.key(1), (8, 16), 0,
                                    base.vocab_size)
        want, want_aux = moe.forward(base, variables["params"], tokens)

        mesh = Mesh(np.array(cpu_devices).reshape(2, 4), ("dp", "ep"))
        shardings = tree_shardings(moe.logical_axes(base)["params"], mesh,
                                   rules_for_mesh(mesh))
        params = jax.device_put(variables["params"], shardings)
        cfg_r = dataclasses.replace(base, dispatch="ragged")
        with mesh:
            got, got_aux = jax.jit(
                lambda p, t: moe.forward(cfg_r, p, t))(params, tokens)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-4, rtol=2e-4)
        np.testing.assert_allclose(float(got_aux), float(want_aux),
                                   rtol=1e-4)

    def test_ragged_gradients_match_dense(self):
        """Training-path parity: grads through the ragged dispatch
        (scatter/gather/all_to_all VJPs) == dense one-hot grads at
        no-drop capacity — the ep=1 path."""
        base = dataclasses.replace(
            moe.CONFIGS["moe_tiny"], dtype=jnp.float32, capacity_factor=8.0)
        variables = moe.init(base, jax.random.key(0))
        batch = {"tokens": jax.random.randint(jax.random.key(2), (2, 16), 0,
                                              base.vocab_size)}

        def loss_for(cfg):
            return lambda p: moe.apply(
                cfg, {"params": p, "state": {}}, batch)[0]

        g_dense = jax.grad(loss_for(base))(variables["params"])
        g_ragged = jax.grad(loss_for(
            dataclasses.replace(base, dispatch="ragged")))(
                variables["params"])
        for gd, gr in zip(jax.tree.leaves(g_dense),
                          jax.tree.leaves(g_ragged)):
            np.testing.assert_allclose(np.asarray(gr), np.asarray(gd),
                                       atol=5e-5, rtol=5e-4)

    def test_ragged_gradients_match_dense_under_ep_mesh(self, cpu_devices):
        """Grad parity through the REAL sharded path — shard_map with
        all_to_all and pmean VJPs under dp2×ep4, against unsharded
        dense grads: a wrong psum/pmean scaling in the backward would
        corrupt every ep>1 training run while passing the ep=1 tests."""
        from polyaxon_tpu.parallel.sharding import (
            rules_for_mesh,
            tree_shardings,
        )

        base = dataclasses.replace(
            moe.CONFIGS["moe_tiny"], dtype=jnp.float32, capacity_factor=8.0)
        variables = moe.init(base, jax.random.key(0))
        batch = {"tokens": jax.random.randint(jax.random.key(2), (8, 16), 0,
                                              base.vocab_size)}

        def loss_for(cfg):
            return lambda p: moe.apply(
                cfg, {"params": p, "state": {}}, batch)[0]

        g_dense = jax.grad(loss_for(base))(variables["params"])

        mesh = Mesh(np.array(cpu_devices).reshape(2, 4), ("dp", "ep"))
        shardings = tree_shardings(moe.logical_axes(base)["params"], mesh,
                                   rules_for_mesh(mesh))
        params = jax.device_put(variables["params"], shardings)
        cfg_r = dataclasses.replace(base, dispatch="ragged")
        with mesh:
            g_ragged = jax.jit(jax.grad(loss_for(cfg_r)))(params)
        for gd, gr in zip(jax.tree.leaves(g_dense),
                          jax.tree.leaves(g_ragged)):
            np.testing.assert_allclose(np.asarray(gr), np.asarray(gd),
                                       atol=1e-4, rtol=1e-3)

    def test_trains_on_ep_mesh(self, cpu_devices):
        job = V1JAXJob(
            kind="jaxjob", mesh=V1MeshSpec(axes={"dp": 2, "ep": 4}),
            runtime={"model": "moe_tiny", "dataset": "lm_synthetic",
                     "steps": 3, "seq_len": 128, "global_batch_size": 8},
        )
        with tempfile.TemporaryDirectory() as d:
            res = run_jaxjob(job, artifacts_dir=d)
        assert res.steps == 3
        assert np.isfinite(res.final_metrics["loss"])
        assert res.final_metrics["router_aux"] > 0


class TestPipeline:
    def _cfg(self, **kw):
        return dataclasses.replace(
            llama.CONFIGS["llama_tiny"], max_seq_len=64, n_layers=4,
            dtype=jnp.float32, **kw)

    @pytest.fixture()
    def pp_mesh(self, cpu_devices):
        return Mesh(np.array(cpu_devices).reshape(2, 4), ("dp", "pp"))

    def test_forward_matches_unpipelined(self, pp_mesh):
        cfg = self._cfg()
        cfg_pp = dataclasses.replace(cfg, pipeline_stages=4,
                                     pipeline_microbatches=4)
        variables = llama.init(cfg, jax.random.key(0))
        tokens = jax.random.randint(jax.random.key(1), (8, 64), 0, cfg.vocab_size)
        ref = llama.forward(cfg, variables["params"], tokens)
        with pp_mesh:
            out = jax.jit(lambda p, t: llama.forward(cfg_pp, p, t))(
                variables["params"], tokens)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_gradients_match(self, pp_mesh):
        cfg = self._cfg()
        cfg_pp = dataclasses.replace(cfg, pipeline_stages=4,
                                     pipeline_microbatches=2)
        variables = llama.init(cfg, jax.random.key(0))
        tokens = jax.random.randint(jax.random.key(1), (4, 64), 0, cfg.vocab_size)

        def loss(c):
            return lambda p: jnp.sum(llama.forward(c, p, tokens) ** 2) / 1e4

        g_ref = jax.grad(loss(cfg))(variables["params"])
        with pp_mesh:
            g_pp = jax.jit(jax.grad(loss(cfg_pp)))(variables["params"])
        for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_pp)):
            np.testing.assert_allclose(a, b, atol=5e-4, rtol=5e-4)

    def test_bf16_pipeline_compiles_and_trains(self, cpu_devices):
        """The production dtype path (bf16 compute, f32 boundary) — guards
        the XLA CPU mixed-dtype all-reduce miscompile workaround."""
        job = V1JAXJob(
            kind="jaxjob", mesh=V1MeshSpec(axes={"dp": 2, "pp": 4}),
            runtime={"model": "llama_tiny", "dataset": "lm_synthetic",
                     "steps": 3, "seq_len": 128, "global_batch_size": 8,
                     "n_layers": 4, "pipeline_stages": 4,
                     "pipeline_microbatches": 4},
        )
        with tempfile.TemporaryDirectory() as d:
            res = run_jaxjob(job, artifacts_dir=d)
        assert res.steps == 3
        assert np.isfinite(res.final_metrics["loss"])

    def test_batch_must_divide_microbatches(self, pp_mesh):
        from polyaxon_tpu.parallel.pipeline import pipeline_forward

        with pytest.raises(ValueError, match="microbatches"):
            pipeline_forward(
                pp_mesh, lambda p, x: x, {"w": jnp.zeros((4, 2))},
                jnp.zeros((6, 8)), n_microbatches=4)

    def test_layers_must_divide_stages(self):
        from polyaxon_tpu.parallel.pipeline import stack_stages

        with pytest.raises(ValueError, match="divide"):
            stack_stages({"w": jnp.zeros((6, 2))}, 4)

    def test_stage_count_must_match_mesh(self, pp_mesh):
        cfg_pp = dataclasses.replace(
            self._cfg(), pipeline_stages=2, pipeline_microbatches=2)
        variables = llama.init(cfg_pp, jax.random.key(0))
        tokens = jax.random.randint(jax.random.key(1), (4, 64), 0,
                                    cfg_pp.vocab_size)
        with pp_mesh:  # mesh pp=4 != 2 declared stages
            with pytest.raises(ValueError, match="must match"):
                llama.forward(cfg_pp, variables["params"], tokens)

    def test_explicit_positions_rejected(self, pp_mesh):
        cfg_pp = dataclasses.replace(
            self._cfg(), pipeline_stages=4, pipeline_microbatches=2)
        variables = llama.init(cfg_pp, jax.random.key(0))
        tokens = jax.random.randint(jax.random.key(1), (4, 64), 0,
                                    cfg_pp.vocab_size)
        positions = jnp.zeros((4, 64), jnp.int32)
        with pp_mesh:
            with pytest.raises(ValueError, match="contiguous positions"):
                llama.forward(cfg_pp, variables["params"], tokens, positions)


class TestMoEDecode:
    """KV-cache generation for the MoE family (serving surface). Tests
    use a no-drop capacity factor: routing top-k is per-token, but
    capacity-based DROPPING depends on the dispatch group (B·S tokens
    in teacher forcing vs B in decode), so exact parity requires
    capacity to cover every selection — the standard inference setting."""

    def _cfg(self):
        import dataclasses

        from polyaxon_tpu.models import moe

        return dataclasses.replace(
            moe.CONFIGS["moe_tiny"], dtype=jnp.float32,
            capacity_factor=4.0)

    def test_decode_matches_teacher_forcing(self):
        from polyaxon_tpu.models import moe

        cfg = self._cfg()
        params = moe.init(cfg, jax.random.key(0))["params"]
        toks = jax.random.randint(jax.random.key(1), (2, 12), 0,
                                  cfg.vocab_size)
        full, _ = moe.forward(cfg, params, toks)
        logits, cache = moe.prefill(cfg, params, toks[:, :-1], 16)
        np.testing.assert_allclose(np.asarray(logits),
                                   np.asarray(full[:, -2]),
                                   atol=2e-4, rtol=2e-4)
        step_logits, _ = moe.decode_step(cfg, params, cache, toks[:, -1],
                                         jnp.int32(toks.shape[1] - 1))
        np.testing.assert_allclose(np.asarray(step_logits),
                                   np.asarray(full[:, -1]),
                                   atol=2e-4, rtol=2e-4)

    def test_generate_greedy_matches_stepwise_forward(self):
        from polyaxon_tpu.models import moe

        cfg = self._cfg()
        params = moe.init(cfg, jax.random.key(0))["params"]
        prompt = jax.random.randint(jax.random.key(2), (1, 4), 0,
                                    cfg.vocab_size)
        out = moe.generate(cfg, params, prompt, max_new_tokens=6)
        seq = prompt
        for _ in range(6):
            logits, _ = moe.forward(cfg, params, seq)
            nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
            seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(np.asarray(out),
                                      np.asarray(seq[:, 4:]))

    def test_moe_serves_over_http(self):
        import json as _json
        import urllib.request

        from polyaxon_tpu.serving import ServingServer

        with ServingServer("moe_tiny", seed=0) as s:
            req = urllib.request.Request(
                s.url + "/v1/generate", method="POST",
                data=_json.dumps({"tokens": [[5, 6, 7]],
                                  "max_new_tokens": 5}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=120) as resp:
                out = _json.load(resp)
        assert len(out["tokens"]) == 1 and len(out["tokens"][0]) == 5

    def test_moe_continuous_batching_matches_static(self, monkeypatch):
        """The family-generic slot-pool engine serves MoE decoders too:
        outputs equal the static whole-budget engine. Served with the
        standard inference setting (no-drop capacity, fp32): capacity
        DROPPING depends on the dispatch-group size, which legitimately
        differs between full-prompt prefill (static) and
        prefill+decode (continuous)."""
        import dataclasses
        import json as _json
        import urllib.request

        from polyaxon_tpu.models import moe
        from polyaxon_tpu.serving import ServingServer

        monkeypatch.setitem(
            moe.CONFIGS, "moe_tiny",
            dataclasses.replace(moe.CONFIGS["moe_tiny"], dtype=jnp.float32,
                                capacity_factor=8.0))

        def post(url, payload):
            req = urllib.request.Request(
                url + "/v1/generate", method="POST",
                data=_json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=300) as resp:
                return _json.load(resp)

        rows = [[5, 6, 7], [1, 2, 3, 4]]
        with ServingServer("moe_tiny", seed=0) as static_s:
            expect = post(static_s.url, {"tokens": rows,
                                         "max_new_tokens": 5})["tokens"]
        with ServingServer("moe_tiny", seed=0, batching="continuous",
                           slots=2) as cont_s:
            got = post(cont_s.url, {"tokens": rows,
                                    "max_new_tokens": 5})["tokens"]
        assert got == expect

    def test_expert_choice_decode_rejected(self):
        """Expert-choice routing selects across the dispatch group, so
        decode cannot reproduce training routing — generation must
        refuse loudly, not silently diverge."""
        import dataclasses

        import pytest as _pytest

        from polyaxon_tpu.models import moe

        cfg = dataclasses.replace(moe.CONFIGS["moe_tiny"],
                                  router="expert_choice")
        params = moe.init(cfg, jax.random.key(0))["params"]
        prompt = jnp.ones((1, 4), jnp.int32)
        with _pytest.raises(ValueError, match="top_k"):
            moe.generate(cfg, params, prompt, max_new_tokens=2)

    def test_decode_never_drops_regardless_of_capacity_factor(self):
        """Decode output must be independent of capacity_factor: the
        decode dispatch group is only the live slots, so factor-derived
        capacity is 1-2 slots and any routing skew would silently drop
        tokens (ADVICE r2, moe.py decode capacity). Capacity is floored
        at the group size in decode. Zeroed router weights force ALL
        rows onto the same top-k experts — the worst-case collision a
        tiny factor would drop."""
        import dataclasses

        from polyaxon_tpu.models import moe

        base = dataclasses.replace(moe.CONFIGS["moe_tiny"],
                                   dtype=jnp.float32)
        params = moe.init(base, jax.random.key(0))["params"]
        # Uniform router logits → every token picks experts {0, 1}.
        params = dict(params)
        params["layers"] = dict(params["layers"])
        params["layers"]["router"] = jnp.zeros_like(
            params["layers"]["router"])

        prompt = jax.random.randint(jax.random.key(3), (4, 6), 0,
                                    base.vocab_size)
        # One shared cache from a no-drop prefill (prefill's dispatch
        # group is B·P tokens — its factor semantics are training's and
        # not under test); only the decode step varies the factor.
        _, cache = moe.prefill(
            dataclasses.replace(base, capacity_factor=8.0), params,
            prompt, 8)
        outs = {}
        for cf in (0.01, 8.0):
            cfg = dataclasses.replace(base, capacity_factor=cf)
            logits, _ = moe.decode_step(
                cfg, params, cache, prompt[:, -1], jnp.int32(6))
            outs[cf] = np.asarray(logits)
        np.testing.assert_allclose(outs[0.01], outs[8.0],
                                   atol=1e-6, rtol=1e-6)
