"""Planted un-cataloged metric emission (golden:
invariant-metric-catalog). The second emission uses a cataloged name
and must stay silent."""
from polyaxon_tpu.obs import metrics


def emit():
    metrics.REGISTRY.counter(
        "polycheck_fixture_not_cataloged_total", "planted").inc()
    metrics.REGISTRY.counter(
        "polyaxon_requeues_total", "cataloged").inc()
