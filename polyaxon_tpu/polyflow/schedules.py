"""Schedules for recurring/deferred operations (parity: ``polyflow/schedules`` [K])."""

from __future__ import annotations

import datetime as _dt
from typing import Literal, Optional, Union

from polyaxon_tpu.schemas.base import BaseSchema


class V1CronSchedule(BaseSchema):
    kind: Literal["cron"] = "cron"
    cron: str
    start_at: Optional[_dt.datetime] = None
    end_at: Optional[_dt.datetime] = None
    max_runs: Optional[int] = None
    depends_on_past: Optional[bool] = None


class V1IntervalSchedule(BaseSchema):
    kind: Literal["interval"] = "interval"
    frequency: int  # seconds
    start_at: Optional[_dt.datetime] = None
    end_at: Optional[_dt.datetime] = None
    max_runs: Optional[int] = None
    depends_on_past: Optional[bool] = None

    def next_after(self, t: _dt.datetime) -> _dt.datetime:
        return t + _dt.timedelta(seconds=self.frequency)


class V1DateTimeSchedule(BaseSchema):
    kind: Literal["datetime"] = "datetime"
    start_at: _dt.datetime


Schedule = Union[V1CronSchedule, V1IntervalSchedule, V1DateTimeSchedule]
