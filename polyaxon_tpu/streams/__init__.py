from polyaxon_tpu.streams.service import StreamsService

__all__ = ["StreamsService"]
