"""Real multi-process gang execution: the agent spawns N processes from
the compiled launch plan, each bootstraps `jax.distributed` from the env
contract (SURVEY.md §2c rendezvous), and they train one model together
over the collective fabric (Gloo on CPU here, ICI/DCN on TPU fleets) —
the path upstream never executes in its own tests (SURVEY.md §4
"Multi-node without a cluster")."""

import os
import time

import pytest

from polyaxon_tpu.agent import Agent
from polyaxon_tpu.controlplane import ControlPlane
from polyaxon_tpu.lifecycle import V1Statuses


@pytest.fixture()
def plane(tmp_path):
    return ControlPlane(str(tmp_path / "home"))


class TestMultiProcessGang:
    def test_two_process_jaxjob_trains_together(self, plane, monkeypatch):
        # Gang subprocesses must not inherit the 8-device host flag the
        # test process uses: each rank contributes its own device(s).
        monkeypatch.setenv("XLA_FLAGS", "")
        record = plane.submit({
            "kind": "component",
            "name": "gang2",
            "run": {
                "kind": "jaxjob",
                "numProcesses": 2,
                "runtime": {"model": "llama_tiny", "dataset": "lm_synthetic",
                            "steps": 3, "seq_len": 64,
                            "global_batch_size": 4, "log_every": 1},
            },
        })
        agent = Agent(plane)  # subprocess path (in_process only fits 1-proc)
        status = agent.run_until_done(record.uuid, timeout=420)
        assert status == V1Statuses.SUCCEEDED
        # Both ranks produced logs; rank 0 owned tracking.
        logs = plane.streams.log_files(record.uuid)
        assert {"main-0.log", "main-1.log"} <= set(logs)
        outputs = plane.streams.get_outputs(record.uuid)
        assert outputs["steps"] == 3
        metrics = plane.streams.get_metrics(record.uuid, ["loss"])
        assert metrics["loss"]

    def test_four_process_gang_trains_together(self, plane, monkeypatch):
        """4-rank gang: the realistic minimum for dp×fsdp sharding over
        a process group (VERDICT r1 weak-5)."""
        monkeypatch.setenv("XLA_FLAGS", "")
        record = plane.submit({
            "kind": "component",
            "name": "gang4",
            "run": {
                "kind": "jaxjob",
                "numProcesses": 4,
                "mesh": {"axes": {"dp": 2, "fsdp": 2}},
                "runtime": {"model": "llama_tiny", "dataset": "lm_synthetic",
                            "steps": 3, "seq_len": 64,
                            "global_batch_size": 8, "log_every": 1},
            },
        })
        agent = Agent(plane)
        status = agent.run_until_done(record.uuid, timeout=600)
        assert status == V1Statuses.SUCCEEDED
        logs = plane.streams.log_files(record.uuid)
        assert {f"main-{i}.log" for i in range(4)} <= set(logs)
        outputs = plane.streams.get_outputs(record.uuid)
        assert outputs["steps"] == 3
        assert plane.streams.get_metrics(record.uuid, ["loss"])["loss"]

    def test_eight_process_multislice_dp_over_dcn(self, plane, monkeypatch):
        """8-rank gang as two 4-host virtual slices: dp laid over DCN,
        fsdp over "ICI" — the hybrid-mesh bootstrap path executed
        multi-process, not just in the in-process dryrun (VERDICT r2
        item 6, SURVEY §2c cross-slice row). Each rank contributes one
        CPU device; topology says 2 slices × 4 single-chip hosts, and
        build_mesh's emulated-slice path must put the dp (DCN) axis
        slowest-varying so every fsdp group stays inside one slice's
        contiguous process block."""
        monkeypatch.setenv("XLA_FLAGS", "")
        record = plane.submit({
            "kind": "component",
            "name": "gang8-multislice",
            "run": {
                "kind": "jaxjob",
                "numProcesses": 8,
                "topology": {"accelerator": "v5e", "topology": "4",
                             "chipsPerHost": 1, "slices": 2},
                "mesh": {"axes": {"dp": 2, "fsdp": 4},
                         "dcnAxes": ["dp"]},
                "runtime": {"model": "llama_tiny", "dataset": "lm_synthetic",
                            "steps": 2, "seq_len": 64,
                            "global_batch_size": 8, "log_every": 1},
            },
        })
        agent = Agent(plane)
        status = agent.run_until_done(record.uuid, timeout=900)
        assert status == V1Statuses.SUCCEEDED
        logs = plane.streams.log_files(record.uuid)
        assert {f"main-{i}.log" for i in range(8)} <= set(logs)
        outputs = plane.streams.get_outputs(record.uuid)
        assert outputs["steps"] == 2
        assert plane.streams.get_metrics(record.uuid, ["loss"])["loss"]
        # The lead rank must have gone down the hybrid (DCN-aware) mesh
        # path with the requested logical shape — not a plain reshape.
        lead_log, _ = plane.streams.read_logs(record.uuid, "main-0.log")
        assert "hybrid mesh: dcn_axes=['dp']" in lead_log
        assert "'dp': 2" in lead_log and "'fsdp': 4" in lead_log

    def test_preempted_gang_resumes_checkpoint_exact(self, plane,
                                                     monkeypatch):
        """Preempt a LIVE multi-process gang mid-training; the scheduler
        requeues it without consuming a retry and the restarted gang
        resumes from the last checkpoint: the loss curve continues (no
        step restarts from 1) and the final loss matches an unpreempted
        control run with identical seeds (VERDICT r1 weak-5 — the
        scenario the preemption machinery exists for)."""
        monkeypatch.setenv("XLA_FLAGS", "")
        spec = {
            "kind": "component",
            "name": "gang-preempt",
            "run": {
                "kind": "jaxjob",
                "numProcesses": 2,
                "checkpointing": {"enabled": True, "intervalSteps": 2,
                                  "asyncSave": False},
                "runtime": {"model": "llama_tiny", "dataset": "lm_synthetic",
                            "steps": 6, "seq_len": 64,
                            "global_batch_size": 4, "log_every": 1},
            },
        }
        record = plane.submit(spec)
        agent = Agent(plane)
        ckpt_dir = os.path.join(plane.run_artifacts_dir(record.uuid),
                                "checkpoints")

        # Drive the reconcile loop until the live gang has persisted a
        # checkpoint, then yank its slice.
        deadline = time.monotonic() + 420
        preempted = False
        while time.monotonic() < deadline:
            agent.reconcile_once()
            has_ckpt = os.path.isdir(ckpt_dir) and any(
                name.isdigit() for name in os.listdir(ckpt_dir))
            if record.uuid in agent.executor.active_runs and has_ckpt:
                assert agent.executor.preempt(record.uuid)
                preempted = True
                break
            time.sleep(0.2)
        assert preempted, "gang never wrote a checkpoint before deadline"

        status = agent.run_until_done(record.uuid, timeout=600)
        assert status == V1Statuses.SUCCEEDED
        rec = plane.get_run(record.uuid)
        assert rec.retries == 0, "preemption must not consume a retry"
        conditions = plane.store.get_conditions(record.uuid)
        assert any(c["type"] == V1Statuses.PREEMPTED for c in conditions)

        outputs = plane.streams.get_outputs(record.uuid)
        assert outputs["steps"] == 6
        loss_events = plane.streams.get_metrics(record.uuid, ["loss"])["loss"]
        steps_logged = [e["step"] for e in loss_events]
        assert max(steps_logged) == 6 - 1  # final step index
        # Resumed from the checkpoint, not from scratch: the earliest
        # steps were trained exactly once.
        assert steps_logged.count(min(steps_logged)) == 1

        # Checkpoint-exact: identical seeds + deterministic data stream
        # mean an unpreempted control run lands on the same loss.
        control = plane.submit({**spec, "name": "gang-control"})
        assert agent.run_until_done(control.uuid,
                                    timeout=600) == V1Statuses.SUCCEEDED
        loss_a = plane.streams.get_outputs(record.uuid)["final_loss"]
        loss_b = plane.streams.get_outputs(control.uuid)["final_loss"]
        assert abs(loss_a - loss_b) < 1e-5, (
            f"resumed loss {loss_a} != control loss {loss_b}")
