"""Polytune search-manager interfaces (SURVEY.md §2 "Polytune" [K]).

A manager consumes *observations* (completed trials: params + metric)
and emits *suggestions* (param dicts to run next). Managers are pure
state machines — the tuner loop in the scheduler owns IO, trial
lifecycle, and preemption handling, mirroring upstream's
search_managers/ split from the tuner service.
"""

from __future__ import annotations

import dataclasses
import itertools
import random
from typing import Any, Optional

from polyaxon_tpu.polyflow.matrix import (
    V1GridSearch,
    V1Iterative,
    V1Mapping,
    V1Optimization,
    V1OptimizationMetric,
    V1RandomSearch,
)

Params = dict[str, Any]


@dataclasses.dataclass
class Observation:
    params: Params
    metric: Optional[float]
    status: str = "succeeded"  # succeeded | failed | preempted

    @property
    def usable(self) -> bool:
        return self.metric is not None and self.status == "succeeded"


class GridSearchManager:
    def __init__(self, config: V1GridSearch):
        self.config = config

    def get_suggestions(self) -> list[Params]:
        names = list(self.config.params.keys())
        grids = [self.config.params[n].to_grid() for n in names]
        combos = [dict(zip(names, values)) for values in itertools.product(*grids)]
        if self.config.num_runs:
            combos = combos[: self.config.num_runs]
        return combos


class RandomSearchManager:
    def __init__(self, config: V1RandomSearch):
        self.config = config

    def get_suggestions(self) -> list[Params]:
        rng = random.Random(self.config.seed)
        return [
            {name: hp.sample(rng) for name, hp in self.config.params.items()}
            for _ in range(self.config.num_runs)
        ]


class MappingManager:
    def __init__(self, config: V1Mapping):
        self.config = config

    def get_suggestions(self) -> list[Params]:
        return [dict(v) for v in self.config.values]


class IterativeManager:
    """Sequential sampling, one suggestion per iteration — the embedded
    equivalent of upstream's user-driven V1Iterative tuner loop (each
    iteration can observe everything before it)."""

    def __init__(self, config: V1Iterative):
        self.config = config

    def get_suggestion(self, iteration: int,
                       observations: Optional[list["Observation"]] = None) -> Params:
        del observations  # hook for smarter per-iteration policies
        # seed=None keeps random-search semantics: fresh OS entropy per
        # call (a fixed seed gives reproducible per-iteration draws).
        if self.config.seed is None:
            rng = random.Random()
        else:
            rng = random.Random(self.config.seed * 100003 + iteration)
        return {name: hp.sample(rng) for name, hp in self.config.params.items()}


def check_early_stopping(
    early_stopping: Optional[list],
    observations_for,  # Callable[[str], list[Observation]]
) -> Optional[str]:
    """Evaluate V1MetricEarlyStopping / V1FailureEarlyStopping policies.

    ``observations_for(metric_name)`` supplies trial observations with
    that metric bound (grid/random sweeps carry no sweep-level metric —
    each policy names its own). Returns None (keep going), "succeed"
    (a trial hit the target — the sweep's goal is met), or "fail"
    (failure fraction exceeded).
    """
    if not early_stopping:
        return None
    for policy in early_stopping:
        data = policy if isinstance(policy, dict) else policy.to_dict()
        kind = data.get("kind")
        if kind == "metric_early_stopping":
            optimization = data.get("optimization") or V1Optimization.MINIMIZE
            target = float(data["value"])
            for obs in observations_for(data["metric"]):
                if not obs.usable:
                    continue
                hit = (obs.metric <= target
                       if optimization == V1Optimization.MINIMIZE
                       else obs.metric >= target)
                if hit:
                    return "succeed"
        elif kind == "failure_early_stopping":
            done = [o for o in observations_for("") if o.status != "preempted"]
            failed = [o for o in done if o.status == "failed"]
            if done and 100.0 * len(failed) / len(done) >= float(data["percent"]):
                return "fail"
    return None


def top_k(
    observations: list[Observation],
    metric: V1OptimizationMetric,
    k: int,
) -> list[Observation]:
    """Best-k usable observations; failed trials rank as worst
    (upstream semantics: failure = bad observation)."""
    usable = [o for o in observations if o.usable]
    usable.sort(key=lambda o: metric.sort_key(o.metric))
    return usable[:k]
