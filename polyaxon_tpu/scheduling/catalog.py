"""Scheduling catalog: named queues, priority classes, project quotas.

The multi-tenant policy vocabulary (ISSUE 2; Borg/Kubernetes lineage —
PAPERS.md, Burns et al. 2016): operations name a **queue**
(``V1Operation.queue``) and a **priority class**
(``environment.priority_class_name``); projects carry **quotas** (max
concurrent runs, max TPU chips, fair-share weight). Queues and quotas
persist in the control-plane store (``queues``/``quotas`` tables); the
priority-class catalog is fixed — the k8s-style four-tier ladder below —
so specs stay portable across deployments.

``RunSchedInfo`` is the admission view of one run: resolved once at
compile time into ``meta["scheduling"]`` (so ticks never re-parse
specs), with a dict-walking fallback for runs submitted before this
subsystem existed.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from polyaxon_tpu.schemas.base import BaseSchema

# k8s-flavoured priority ladder. Higher admits/evicts first. "default"
# is deliberately above "low" so an unannotated run can still preempt
# an explicitly best-effort one.
PRIORITY_CLASSES: dict[str, int] = {
    "low": 0,
    "default": 1,
    "high": 2,
    "critical": 3,
}

DEFAULT_QUEUE = "default"
DEFAULT_PRIORITY_CLASS = "default"


class SchedulingError(ValueError):
    """A spec references an unknown queue or priority class."""


class V1Queue(BaseSchema):
    name: str
    priority: int = 0
    concurrency: Optional[int] = None  # None = unlimited
    preemptible: bool = False  # runs admitted here may be evicted
    description: Optional[str] = None


class V1Quota(BaseSchema):
    project: str
    max_runs: Optional[int] = None  # max CONCURRENT runs
    max_chips: Optional[int] = None  # max concurrent TPU chips
    weight: float = 1.0  # fair-share weight across projects


def resolve_priority_class(name: Optional[str]) -> int:
    """Priority-class name → numeric priority; unknown names raise so a
    bad spec fails at compile, not silently at the back of the queue."""
    if not name:
        return PRIORITY_CLASSES[DEFAULT_PRIORITY_CLASS]
    key = str(name).lower()
    if key not in PRIORITY_CLASSES:
        raise SchedulingError(
            f"unknown priority class `{name}` "
            f"(catalog: {sorted(PRIORITY_CLASSES)})")
    return PRIORITY_CLASSES[key]


def gang_priority(queue_priority: int, class_priority: int) -> int:
    """Scalar priority for the native slice pool's eviction compare:
    queue priority dominates, priority class breaks ties within a
    queue. Classes span [0, 4) so queue levels never interleave."""
    return int(queue_priority) * len(PRIORITY_CLASSES) + int(class_priority)


@dataclasses.dataclass
class RunSchedInfo:
    """One run's admission-relevant facts."""

    queue: str = DEFAULT_QUEUE
    priority_class: str = DEFAULT_PRIORITY_CLASS
    priority: int = PRIORITY_CLASSES[DEFAULT_PRIORITY_CLASS]
    chips: int = 0
    preemptible: bool = False
    # Resolved from the queue catalog by the admission pass; stays 0
    # for callers that never looked the queue row up.
    queue_priority: int = 0

    def effective(self, queue_priority: int) -> tuple[int, int]:
        """Lexicographic (queue priority, class priority) — the order
        the admission pass and victim selection both compare by."""
        return (queue_priority, self.priority)

    def to_meta(self) -> dict:
        return {
            "queue": self.queue,
            "priority_class": self.priority_class,
            "priority": self.priority,
            "chips": self.chips,
            "preemptible": self.preemptible,
        }


def _env_dict(spec: Optional[dict]) -> dict:
    """component.run.environment out of a serialized spec dict."""
    run = ((spec or {}).get("component") or {}).get("run") or {}
    return run.get("environment") or {}


def sched_info(record) -> RunSchedInfo:
    """Admission facts for a run record.

    Prefers the ``meta["scheduling"]`` stamp written at compile; falls
    back to walking the serialized launch plan / spec (camelCase
    aliases) so pre-subsystem runs and hand-crafted store rows still
    schedule sanely. Never raises: a malformed stamp degrades to the
    default queue rather than wedging the admission pass.
    """
    stamp = (record.meta or {}).get("scheduling") or {}
    if stamp.get("queue"):
        return RunSchedInfo(
            queue=str(stamp.get("queue") or DEFAULT_QUEUE),
            priority_class=str(
                stamp.get("priority_class") or DEFAULT_PRIORITY_CLASS),
            priority=int(stamp.get("priority") or 0),
            chips=int(stamp.get("chips") or 0),
            preemptible=bool(stamp.get("preemptible")),
        )
    plan = record.launch_plan or {}
    resources = plan.get("resources") or {}
    spec = record.resolved_spec or record.spec or {}
    env = _env_dict(spec)
    queue = plan.get("queue") or spec.get("queue") or DEFAULT_QUEUE
    class_name = (env.get("priorityClassName")
                  or env.get("priority_class_name")
                  or DEFAULT_PRIORITY_CLASS)
    try:
        priority = resolve_priority_class(class_name)
    except SchedulingError:
        class_name, priority = DEFAULT_PRIORITY_CLASS, resolve_priority_class(None)
    return RunSchedInfo(
        queue=str(queue),
        priority_class=str(class_name).lower(),
        priority=priority,
        chips=int(resources.get("chips") or 0),
        preemptible=bool(resources.get("preemptible")),
    )
