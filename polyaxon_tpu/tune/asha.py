"""Asynchronous Successive Halving — ASHA (Li et al., MLSys 2020).

Pure promotion/sampling state machine, mirroring the tune/ manager
split: the scheduler owns IO and trial lifecycle (_tick_asha), this
module owns the math. The async rule: a COMPLETED trial at rung k is
promotable to rung k+1 iff it ranks in the top ``floor(n_completed /
eta)`` of the trials completed at rung k so far. No rung barrier — a
promotion can happen while siblings are still running, and preempted
trials (requeued in place by the scheduler) never stall anyone.
"""

from __future__ import annotations

import random
from typing import Any, Optional

from polyaxon_tpu.polyflow.matrix import V1Asha, V1Optimization
from polyaxon_tpu.tune.base import Params


class AshaManager:
    def __init__(self, config: V1Asha):
        self.config = config
        self.rungs = config.rung_resources()

    def n_rungs(self) -> int:
        return len(self.rungs)

    def sample_params(self, index: int,
                      base_seed: Optional[int] = None) -> Params:
        """Deterministic draw for bottom-rung trial ``index`` — stable
        under manager re-instantiation (the scheduler rebuilds every
        tick). For unseeded sweeps the scheduler draws a random base
        seed ONCE and persists it in the tuner meta, so distinct sweeps
        explore distinct points while each sweep stays tick-stable."""
        if base_seed is None:
            base_seed = self.config.seed if self.config.seed is not None else 0
        rng = random.Random((base_seed << 20) + index)
        return {name: hp.sample(rng)
                for name, hp in self.config.params.items()}

    def promotable(
        self,
        completed: list[tuple[str, Params, Optional[float]]],
    ) -> list[str]:
        """Trial ids (among ``completed`` at one rung) that currently
        rank in the top ``floor(n/eta)`` by the sweep metric. Trials
        without a usable metric (failed) rank worst and are never
        promoted."""
        usable = [(uid, m) for uid, _, m in completed if m is not None]
        k = int(len(completed) // self.config.eta)
        if k < 1 or not usable:
            return []
        maximize = (self.config.metric.optimization
                    == V1Optimization.MAXIMIZE)
        usable.sort(key=lambda t: t[1], reverse=maximize)
        return [uid for uid, _ in usable[:k]]
