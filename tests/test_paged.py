"""Paged-KV serving: block-table decode parity against the dense ring
cache, page-pool allocator semantics, and engine-level behavior under
oversubscription (net-new surface — the reference orchestrator has no
serving path; held to this repo's own bar, VERDICT r2 missing #6)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from polyaxon_tpu.models import llama
from polyaxon_tpu.serving.batching import ContinuousBatchingEngine
from polyaxon_tpu.serving.paged import PagePool


def _cfg():
    return dataclasses.replace(llama.CONFIGS["llama_tiny"],
                               dtype=jnp.float32)


class TestPagedDecodeParity:
    def test_matches_dense_ragged_step_by_step(self):
        """A row whose pages cover 0..p must produce the dense ragged
        step's logits at p exactly — including an idle row, non-trivial
        block-table order, and growth across a page boundary."""
        cfg = _cfg()
        params = llama.init(cfg, jax.random.key(0))["params"]
        max_len, page = 32, 4
        prompt = jax.random.randint(jax.random.key(1), (1, 7), 0,
                                    cfg.vocab_size)

        # Dense reference: slot 0 live, slot 1 idle.
        dense = llama.cb_init_cache(cfg, 2, max_len)
        row = llama.cb_prefill(cfg, params, prompt[:, :-1], max_len)
        dense = llama.insert_cache_row(dense, row, jnp.int32(0))

        # Paged: same row through the paged surface, with deliberately
        # non-contiguous page ids (allocation order must not matter).
        pool_pages = 8
        paged = llama.paged_init_cache(cfg, pool_pages, page)
        tables = np.full((2, max_len // page), -1, np.int32)
        tables[0, :2] = [5, 2]  # positions 0..7 → pages 5 then 2
        k_all, v_all = llama.paged_prefill_kv(cfg, params, prompt[:, :-1])
        paged = llama.paged_insert_prefill(
            paged, k_all, v_all, jnp.asarray(tables[0]), page)

        cur = jnp.asarray([int(prompt[0, -1]), 0], jnp.int32)
        pos = np.array([prompt.shape[1] - 1, -1], np.int32)
        for step_i in range(6):  # crosses the pos=8 page boundary
            want, dense = llama.decode_step_ragged(
                cfg, params, dense, cur, jnp.asarray(pos))
            got, paged = llama.decode_step_paged(
                cfg, params, paged, cur, jnp.asarray(pos),
                jnp.asarray(tables))
            np.testing.assert_allclose(np.asarray(got[0]),
                                       np.asarray(want[0]),
                                       atol=2e-4, rtol=2e-4)
            assert np.isfinite(np.asarray(got[1])).all()  # idle row
            nxt = int(jnp.argmax(want[0]))
            cur = jnp.asarray([nxt, 0], jnp.int32)
            pos[0] += 1
            if pos[0] // page >= 2 and tables[0, pos[0] // page] < 0:
                tables[0, pos[0] // page] = 6  # grow into a fresh page

    def test_refuses_sliding_window(self):
        cfg = dataclasses.replace(_cfg(), sliding_window=8)
        with pytest.raises(ValueError, match="sliding_window"):
            llama.paged_init_cache(cfg, 4, 4)


class TestPagePool:
    def test_admit_grow_release_accounting(self):
        pool = PagePool(slots=2, max_len=16, page_size=4, n_pages=5)
        assert pool.free_pages == 4  # page 0 is scratch
        assert pool.admit(0, 5)  # positions 0..4 → 2 pages
        assert pool.free_pages == 2
        assert (pool.tables[0, :2] >= 1).all() and pool.tables[0, 2] == -1
        assert pool.ensure(0, 5)  # already covered
        assert pool.free_pages == 2
        assert pool.ensure(0, 8)  # new page
        assert pool.free_pages == 1
        assert pool.admit(1, 4)  # exactly the last page
        assert not pool.ensure(1, 4)  # pool dry
        pool.release(0)
        assert pool.free_pages == 3
        assert (pool.tables[0] == -1).all()
        assert pool.ensure(1, 4)  # freed pages are reusable

    def test_admit_all_or_nothing(self):
        pool = PagePool(slots=1, max_len=16, page_size=4, n_pages=3)
        assert not pool.admit(0, 12)  # needs 3, has 2 — nothing taken
        assert pool.free_pages == 2
        assert (pool.tables[0] == -1).all()

    def test_dense_equivalent_sizing(self):
        pool = PagePool.dense_equivalent(slots=4, max_len=32, page_size=8)
        assert pool.n_pages == 4 * 4 + 1
        for s in range(4):  # every slot can hold a full-length row
            assert pool.admit(s, 32)
        assert pool.free_pages == 0


class TestPagedEngine:
    def _params(self, cfg):
        return llama.init(cfg, jax.random.key(0))["params"]

    @pytest.mark.parametrize("page_size", [1, 4])
    def test_matches_dense_engine_greedy(self, page_size):
        """Paged and dense engines share every step above the cache
        layout, so greedy decode must agree token-for-token — mixed
        prompt lengths, more requests than slots (retire→admit reuses
        freed pages). page_size=1 is the degenerate page-per-position
        case."""
        cfg = _cfg()
        params = self._params(cfg)
        rows = [[5, 6, 7], [1, 2, 3, 4], [9, 8], [3, 1, 4, 1, 5], [2, 7]]
        dense = ContinuousBatchingEngine("llama_tiny", cfg, params,
                                         slots=2, max_len=32)
        try:
            want = dense.generate(rows, max_new_tokens=6, timeout=300)
        finally:
            dense.stop()
        paged = ContinuousBatchingEngine("llama_tiny", cfg, params,
                                         slots=2, max_len=32,
                                         kv="paged", page_size=page_size)
        try:
            got = paged.generate(rows, max_new_tokens=6, timeout=300)
            stats = paged.stats()
        finally:
            paged.stop()
        assert got == want
        assert stats["kv"] == "paged"
        assert stats["kv_pages_free"] == stats["kv_pages_total"]  # all freed

    def test_oversubscribed_pool_backpressure(self):
        """A pool HALF the dense reservation still serves all requests
        (admission waits for retirements) — the memory win paged
        exists for."""
        cfg = _cfg()
        params = self._params(cfg)
        rows = [[5, 6, 7], [1, 2, 3, 4], [9, 8, 7]]
        # slots=2, max_len=32, page=4 → dense-equivalent 16 pages; use 8
        # (kv_pages counts usable pages; scratch is internal).
        engine = ContinuousBatchingEngine("llama_tiny", cfg, params,
                                          slots=2, max_len=32, kv="paged",
                                          page_size=4, kv_pages=8)
        try:
            out = engine.generate(rows, max_new_tokens=5, timeout=300)
            assert all(len(r) == 5 for r in out)
        finally:
            engine.stop()

    def test_pool_exhaustion_mid_generation_fails_loudly(self):
        """Each request fits the pool ALONE (passes up-front validation)
        but two growing concurrently drain it: the starved row must
        error with the actionable message — and its released pages let
        the surviving neighbour finish."""
        cfg = _cfg()
        params = self._params(cfg)
        # 4 usable pages of 4. Each request: prompt 3 + 8 new → positions
        # 0..9 → 3 pages alone (feasible). Concurrently: 2 pages each at
        # admission+first growth (4 used, 0 free), then both need a 3rd
        # at pos 8 — slot 0 fails first, its release frees slot 1.
        engine = ContinuousBatchingEngine("llama_tiny", cfg, params,
                                          slots=2, max_len=32, kv="paged",
                                          page_size=4, kv_pages=4)
        try:
            req_a = engine.submit([5, 6, 7], max_new_tokens=8)
            req_b = engine.submit([9, 8, 7], max_new_tokens=8)
            with pytest.raises(RuntimeError, match="pool exhausted"):
                req_a.wait(timeout=300)
            assert len(req_b.wait(timeout=300)) == 8
        finally:
            engine.stop()

    def test_paged_requires_family_surface(self):
        from polyaxon_tpu.models import t5

        cfg = t5.CONFIGS["t5_tiny"]
        params = t5.init(cfg, jax.random.key(0))["params"]
        with pytest.raises(ValueError, match="decode_step_paged"):
            ContinuousBatchingEngine("t5_tiny", cfg, params, kv="paged")

    def test_static_engine_rejects_paged(self):
        from polyaxon_tpu.serving import ServingServer

        with pytest.raises(ValueError, match="continuous"):
            ServingServer("llama_tiny", kv="paged", batching="static")

    def test_impossible_request_rejected_up_front(self):
        """A request that cannot fit the pool even alone must fail at
        submit — parking it at the FIFO head would block the queue
        forever."""
        cfg = _cfg()
        params = self._params(cfg)
        engine = ContinuousBatchingEngine("llama_tiny", cfg, params,
                                          slots=1, max_len=32, kv="paged",
                                          page_size=4, kv_pages=2)
        try:
            with pytest.raises(ValueError, match="KV pages"):
                engine.submit([1] * 10, max_new_tokens=10)  # needs 5 pages
            # And a feasible request afterwards still works.
            assert len(engine.generate([[5, 6, 7]], max_new_tokens=4,
                                       timeout=300)[0]) == 4
        finally:
            engine.stop()


class TestMoEPaged:
    def test_moe_paged_matches_dense_engine(self):
        """The MoE family over the paged pool: greedy parity with its
        own dense engine (expert routing sees the same hidden states
        either way)."""
        from polyaxon_tpu.models import moe

        cfg = dataclasses.replace(moe.CONFIGS["moe_tiny"],
                                  dtype=jnp.float32)
        params = moe.init(cfg, jax.random.key(0))["params"]
        rows = [[5, 6, 7], [1, 2, 3, 4], [9, 8]]
        dense = ContinuousBatchingEngine("moe_tiny", cfg, params,
                                         slots=2, max_len=32)
        try:
            want = dense.generate(rows, max_new_tokens=5, timeout=300)
        finally:
            dense.stop()
        paged = ContinuousBatchingEngine("moe_tiny", cfg, params,
                                         slots=2, max_len=32,
                                         kv="paged", page_size=4)
        try:
            got = paged.generate(rows, max_new_tokens=5, timeout=300)
        finally:
            paged.stop()
        assert got == want


class TestPagedKernel:
    def test_kernel_matches_gather_reference(self):
        """The Pallas paged-decode kernel (interpret mode on CPU) must
        match the XLA gather+masked-softmax formulation on live rows —
        ragged positions, holes in the tables, GQA — and zero idle
        rows."""
        from polyaxon_tpu.ops.attention import repeat_kv
        from polyaxon_tpu.ops.paged_attention import paged_decode_attention

        key = jax.random.key(0)
        B, H, KV, Hd, page, P, maxp = 3, 4, 2, 16, 4, 9, 4
        ks = jax.random.split(key, 4)
        q = jax.random.normal(ks[0], (B, H, Hd), jnp.float32)
        k_pages = jax.random.normal(ks[1], (P, page, KV, Hd), jnp.float32)
        v_pages = jax.random.normal(ks[2], (P, page, KV, Hd), jnp.float32)
        tables = jnp.asarray([[5, 2, -1, -1],
                              [1, -1, -1, -1],
                              [-1, -1, -1, -1]], jnp.int32)
        pos = jnp.asarray([6, 2, -1], jnp.int32)

        got = paged_decode_attention(q, k_pages, v_pages, tables, pos,
                                     interpret=True)

        # Gather reference (the models/llama.py formulation).
        gathered = jnp.maximum(tables, 0)
        keys_r = repeat_kv(k_pages[gathered].reshape(B, -1, KV, Hd),
                           H // KV)
        vals_r = repeat_kv(v_pages[gathered].reshape(B, -1, KV, Hd),
                           H // KV)
        logits = jnp.einsum("bhd,bkhd->bhk", q, keys_r) * Hd ** -0.5
        j = jnp.arange(maxp * page)[None, :]
        allocated = jnp.repeat(tables >= 0, page, axis=1)
        valid = ((j <= jnp.maximum(pos, 0)[:, None]) & (pos[:, None] >= 0)
                 & allocated)[:, None, :]
        probs = jax.nn.softmax(jnp.where(valid, logits, -1e30), axis=-1)
        want = jnp.einsum("bhk,bkhd->bhd", probs, vals_r)

        np.testing.assert_allclose(np.asarray(got[:2]), np.asarray(want[:2]),
                                   atol=1e-5, rtol=1e-5)
        assert (np.asarray(got[2]) == 0).all()  # idle row → zeros

    def test_pallas_impl_matches_gather_in_step(self):
        """decode_step_paged with paged_attention_impl='pallas'
        (interpret off-TPU) equals the gather formulation on live rows
        — the serving-path integration of the kernel."""
        cfg_g = dataclasses.replace(_cfg(), paged_attention_impl="gather")
        cfg_p = dataclasses.replace(_cfg(), paged_attention_impl="pallas")
        params = llama.init(cfg_g, jax.random.key(0))["params"]
        page = 4
        paged = llama.paged_init_cache(cfg_g, 8, page)
        tables = jnp.asarray([[3, 1, -1, -1, -1, -1, -1, -1],
                              [-1] * 8], jnp.int32)
        prompt = jax.random.randint(jax.random.key(2), (1, 6), 0,
                                    cfg_g.vocab_size)
        k_all, v_all = llama.paged_prefill_kv(cfg_g, params, prompt[:, :-1])
        paged = llama.paged_insert_prefill(paged, k_all, v_all,
                                           tables[0], page)
        tokens = jnp.asarray([int(prompt[0, -1]), 0], jnp.int32)
        pos = jnp.asarray([5, -1], jnp.int32)
        want, _ = llama.decode_step_paged(cfg_g, params, paged, tokens,
                                          pos, tables)
        got, _ = llama.decode_step_paged(cfg_p, params, paged, tokens,
                                         pos, tables)
        np.testing.assert_allclose(np.asarray(got[0]), np.asarray(want[0]),
                                   atol=2e-4, rtol=2e-4)
        assert np.isfinite(np.asarray(got[1])).all()


class TestPrefixCache:
    def test_shared_prompt_pages_reused(self):
        pool = PagePool(slots=2, max_len=32, page_size=4, n_pages=9)
        tokens = list(range(10))  # prefill 0..8 → pages 0,1 shareable
        assert pool.admit(0, 10, tokens)
        free_after_first = pool.free_pages
        assert pool.admit(1, 10, tokens)
        assert pool.prefix_hits == 2
        # Second identical prompt costs only its private decode page.
        assert free_after_first - pool.free_pages == 1
        # The shared pages appear in both tables; privates differ.
        assert (pool.tables[0][:2] == pool.tables[1][:2]).all()
        assert pool.tables[0][2] != pool.tables[1][2]

    def test_resident_pages_survive_release_and_rehit(self):
        pool = PagePool(slots=1, max_len=32, page_size=4, n_pages=9)
        tokens = list(range(10))
        assert pool.admit(0, 10, tokens)
        pool.release(0)
        assert pool.free_pages == 8  # resident pages still allocatable
        assert pool.admit(0, 10, tokens)
        assert pool.prefix_hits == 2  # prompt KV reused across requests

    def test_distinct_prompts_do_not_cross_hit(self):
        pool = PagePool(slots=2, max_len=32, page_size=4, n_pages=9)
        assert pool.admit(0, 10, list(range(10)))
        assert pool.admit(1, 10, list(range(100, 110)))
        assert pool.prefix_hits == 0
        # Common-prefix prompts share exactly the common full pages.
        pool.release(0)
        pool.release(1)
        a = [1, 2, 3, 4, 5, 6, 7, 8, 9, 10]
        b = [1, 2, 3, 4, 5, 6, 7, 8, 77, 88]  # diverges in page 2
        pool2 = PagePool(slots=2, max_len=32, page_size=4, n_pages=9)
        assert pool2.admit(0, 10, a)
        assert pool2.admit(1, 10, b)
        assert pool2.prefix_hits == 2  # pages 0,1 shared; page 2 private

    def test_eviction_under_pressure(self):
        pool = PagePool(slots=1, max_len=32, page_size=4, n_pages=4)
        assert pool.admit(0, 10, list(range(10)))  # 3 pages (2 prefix)
        pool.release(0)
        # A distinct prompt needs 3 pages; only 1 truly free → evicts
        # LRU resident prefix pages.
        assert pool.admit(0, 10, list(range(50, 60)))
        assert pool.free_pages == 0

    def test_failed_admission_invalidates_unwritten_keys(self):
        pool = PagePool(slots=1, max_len=32, page_size=4, n_pages=9)
        assert pool.admit(0, 10, list(range(10)))
        pool.release(0, invalidate_prefix=True)  # prefill never ran
        assert pool.admit(0, 10, list(range(10)))
        assert pool.prefix_hits == 0  # keys did not survive

    def test_engine_prefix_reuse_matches_dense(self):
        """Sequential identical prompts: the second hits the prefix
        cache AND produces exactly the dense engine's tokens (the
        resident pages hold the right content)."""
        cfg = _cfg()
        params = llama.init(cfg, jax.random.key(0))["params"]
        prompt = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3]  # 2 full prefix pages
        dense = ContinuousBatchingEngine("llama_tiny", cfg, params,
                                         slots=1, max_len=32)
        try:
            want = dense.generate([prompt], max_new_tokens=5, timeout=300)
        finally:
            dense.stop()
        engine = ContinuousBatchingEngine("llama_tiny", cfg, params,
                                          slots=1, max_len=32,
                                          kv="paged", page_size=4)
        try:
            first = engine.generate([prompt], max_new_tokens=5, timeout=300)
            second = engine.generate([prompt], max_new_tokens=5, timeout=300)
            stats = engine.stats()
        finally:
            engine.stop()
        assert first == want and second == want
        assert stats["kv_prefix_hits"] >= 2  # second request reused KV

    def test_live_shared_pages_cost_nothing_at_admission(self):
        """A prompt whose prefix pages are LIVE in another slot only
        pays for its private pages — the hot-system-prompt workload
        must not be refused under pressure it doesn't create."""
        pool = PagePool(slots=2, max_len=32, page_size=4, n_pages=5)
        tokens = list(range(10))  # 3 pages, 2 shareable
        assert pool.admit(0, 10, tokens)
        assert pool.free_pages == 1  # pages_for(10)=3 would not fit...
        assert pool.can_admit(10, tokens)  # ...but 2 are live shares
        assert pool.admit(1, 10, tokens)
        assert pool.free_pages == 0
        assert pool.prefix_hits == 2


class TestRadixPrefixSharing:
    """The radix-tree prefix index: copy-on-write forks at mid-page
    divergence, refcount/eviction invariants under the chaos paths
    (fork-then-release, failed admission, whole-tree invalidation),
    and cache-aware admission ordering."""

    def test_cow_fork_at_mid_page_divergence(self):
        pool = PagePool(slots=2, max_len=32, page_size=4, n_pages=9)
        a = [1, 2, 3, 4, 5, 6, 7, 8, 9, 10]
        b = [1, 2, 3, 4, 5, 6, 77, 88, 99, 100]  # diverges INSIDE page 1
        assert pool.admit(0, 10, a)
        res = pool.admit(1, 10, b)
        assert res is not None
        # Page 0 fully matched; tokens 4,5 of page 1 match → CoW fork.
        assert res.matched_pages == 1
        assert res.matched_tokens == 6
        assert res.cow is not None
        src, dst = res.cow
        assert src == int(pool.tables[0][1]) and dst == int(pool.tables[1][1])
        assert src != dst  # the fork got its own private copy
        assert int(pool.tables[0][0]) == int(pool.tables[1][0])
        assert pool.cow_forks == 1
        assert pool.check_invariants() == []

    def test_fork_then_release_leaks_nothing(self):
        pool = PagePool(slots=2, max_len=32, page_size=4, n_pages=9)
        a = [1, 2, 3, 4, 5, 6, 7, 8, 9, 10]
        b = [1, 2, 3, 4, 5, 6, 77, 88, 99, 100]
        assert pool.admit(0, 10, a)
        assert pool.admit(1, 10, b)
        pool.release(0)
        assert pool.check_invariants() == []
        pool.release(1)
        assert pool.check_invariants() == []
        # 3 chain pages (a's two + b's forked branch) stay resident but
        # reclaimable; both private decode pages went back to the free
        # list — every usable page is allocatable again.
        assert pool.free_pages == 8
        assert pool.radix_stats()["pages"] == 3
        # And both branches re-hit their own content.
        assert pool.admit(0, 10, a)
        assert pool.admit(1, 10, b)
        assert pool.check_invariants() == []

    def test_eviction_of_live_referenced_page_impossible(self):
        pool = PagePool(slots=2, max_len=32, page_size=4, n_pages=5)
        a = list(range(10))  # 3 pages, 2 in the tree — slot 0 LIVE
        assert pool.admit(0, 10, a)
        distinct = [50, 51, 52, 53, 54, 55]  # needs 2 fresh pages
        # Only 1 page is truly free and the tree pages are referenced
        # by slot 0: nothing may be evicted from under it.
        assert not pool.can_admit(6, distinct)
        assert not pool.admit(1, 6, distinct)
        assert pool.check_invariants() == []
        assert (pool.tables[0][:3] >= 1).all()  # row untouched
        pool.release(0)  # now resident → evictable
        bigger = list(range(50, 60))  # 3 pages: must evict a resident
        assert pool.admit(1, 10, bigger)
        assert pool.prefix_evictions >= 1
        assert pool.check_invariants() == []

    def test_invalidate_prefix_cache_drops_whole_tree(self):
        pool = PagePool(slots=2, max_len=32, page_size=4, n_pages=9)
        a = list(range(10))
        assert pool.admit(0, 10, a)
        pool.release(0)
        assert pool.radix_stats()["pages"] == 2
        pool.invalidate_prefix_cache()
        assert pool.radix_stats() == {"nodes": 0, "pages": 0,
                                      "referenced": 0, "resident": 0}
        assert pool.free_pages == 8
        assert pool.check_invariants() == []
        assert pool.admit(0, 10, a)
        assert pool.prefix_hits == 0  # nothing survived

    def test_invalidate_with_live_rows_keeps_allocations(self):
        pool = PagePool(slots=2, max_len=32, page_size=4, n_pages=9)
        a = list(range(10))
        assert pool.admit(0, 10, a)  # LIVE while the tree is dropped
        pool.invalidate_prefix_cache()
        assert pool.check_invariants() == []
        assert (pool.tables[0][:3] >= 1).all()
        assert pool.admit(1, 10, a)
        assert pool.prefix_hits == 0  # shareability gone, pages intact
        pool.release(0)
        pool.release(1)
        assert pool.check_invariants() == []

    def test_failed_prefill_detaches_only_the_fresh_leaf(self):
        """Mid-prefill failure/requeue chaos: invalidating slot 1's
        admission must forget ONLY the chain pages it registered —
        the prefix it adopted from slot 0 keeps serving hits."""
        pool = PagePool(slots=2, max_len=32, page_size=4, n_pages=9)
        a = list(range(10))            # chain: pages 0..1 (tokens 0..7)
        b = list(range(8)) + list(range(200, 206))  # extends a's chain
        assert pool.admit(0, 10, a)
        res = pool.admit(1, 14, b)
        assert res is not None and res.matched_pages == 2
        pool.release(1, invalidate_prefix=True)  # prefill never ran
        assert pool.check_invariants() == []
        # a's chain still matches; b's extension is gone.
        assert pool.peek_matched_tokens(14, b) == 8
        res2 = pool.admit(1, 14, b)
        assert res2 is not None and res2.matched_pages == 2
        assert pool.check_invariants() == []

    def test_commit_prefix_makes_leaf_durable(self):
        pool = PagePool(slots=1, max_len=32, page_size=4, n_pages=9)
        a = list(range(10))
        assert pool.admit(0, 10, a)
        pool.commit_prefix(0)  # prefill completed
        # invalidate_prefix on release is now a no-op for the leaf.
        pool.release(0, invalidate_prefix=True)
        assert pool.admit(0, 10, a)
        assert pool.prefix_hits == 2
        assert pool.check_invariants() == []

    def test_engine_cow_parity_with_dense(self):
        """Two prompts diverging mid-page: the forked request's tokens
        must match the dense engine exactly (the CoW copy + suffix
        prefill reconstruct the same KV), with zero invariant
        violations afterwards."""
        cfg = _cfg()
        params = llama.init(cfg, jax.random.key(0))["params"]
        p1 = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3]
        p2 = [3, 1, 4, 1, 5, 9, 7, 7, 5, 3]  # diverges at index 6
        dense = ContinuousBatchingEngine("llama_tiny", cfg, params,
                                         slots=1, max_len=32)
        try:
            want1 = dense.generate([p1], max_new_tokens=5, timeout=300)
            want2 = dense.generate([p2], max_new_tokens=5, timeout=300)
        finally:
            dense.stop()
        engine = ContinuousBatchingEngine("llama_tiny", cfg, params,
                                          slots=1, max_len=32,
                                          kv="paged", page_size=4)
        try:
            got1 = engine.generate([p1], max_new_tokens=5, timeout=300)
            got2 = engine.generate([p2], max_new_tokens=5, timeout=300)
            stats = engine.stats()
        finally:
            engine.stop()
        assert got1 == want1 and got2 == want2
        assert stats["kv_cow_forks"] >= 1
        assert stats["prefill_tokens_skipped"] > 0
        assert stats["kv_invariant_violations"] == 0

    def test_engine_full_prefill_cache_hit(self):
        """A prompt whose whole prefill sits in the tree (a previous
        longer prompt wrote it) runs NO prefill program and still
        decodes the dense engine's tokens."""
        cfg = _cfg()
        params = llama.init(cfg, jax.random.key(0))["params"]
        a = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8, 9, 7]  # chain: 12 tokens
        b = a[:13]  # prefill = a[:12] — fully inside a's chain
        dense = ContinuousBatchingEngine("llama_tiny", cfg, params,
                                         slots=1, max_len=32)
        try:
            want = dense.generate([b], max_new_tokens=5, timeout=300)
        finally:
            dense.stop()
        engine = ContinuousBatchingEngine("llama_tiny", cfg, params,
                                          slots=1, max_len=32,
                                          kv="paged", page_size=4)
        try:
            engine.generate([a], max_new_tokens=5, timeout=300)
            got = engine.generate([b], max_new_tokens=5, timeout=300)
            stats = engine.stats()
            timeline = engine.request_timeline(
                engine.recent_requests()[0]["request_id"])
        finally:
            engine.stop()
        assert got == want
        # Second admission skipped its entire 12-token prefill.
        assert stats["prefill_tokens_skipped"] >= 12
        assert stats["kv_invariant_violations"] == 0
        from polyaxon_tpu.obs import analyze

        summary = analyze.request_phases(timeline)
        assert summary["prefix_cached_tokens"] == 12

    def test_prefix_cache_off_disables_sharing(self):
        cfg = _cfg()
        params = llama.init(cfg, jax.random.key(0))["params"]
        prompt = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3]
        engine = ContinuousBatchingEngine("llama_tiny", cfg, params,
                                          slots=1, max_len=32,
                                          kv="paged", page_size=4,
                                          prefix_cache=False)
        try:
            first = engine.generate([prompt], max_new_tokens=4, timeout=300)
            second = engine.generate([prompt], max_new_tokens=4, timeout=300)
            stats = engine.stats()
        finally:
            engine.stop()
        assert first == second
        assert stats["kv_prefix_hits"] == 0
        assert stats["prefill_tokens_skipped"] == 0
        assert stats["kv_invariant_violations"] == 0

    def test_cache_aware_admission_prefers_hot_prefix(self):
        """Among admissible pending requests the one with the hottest
        matched prefix is admitted first; overtaken requests age, and
        a request at the skip cap becomes a barrier nothing younger
        passes."""
        from polyaxon_tpu.serving.batching import _Request

        cfg = _cfg()
        params = llama.init(cfg, jax.random.key(0))["params"]
        engine = ContinuousBatchingEngine("llama_tiny", cfg, params,
                                          slots=1, max_len=32,
                                          kv="paged", page_size=4)
        engine.stop()  # drive _pick_next_locked deterministically
        pool = engine._pool
        hot = list(range(12))
        assert pool.admit(0, 12, hot)
        pool.release(0)  # hot's chain is resident in the tree
        cold = list(range(100, 112))
        r_cold = _Request(list(cold), 4, 0.0, 0)
        r_hot = _Request(list(hot), 4, 0.0, 0)
        engine._queues["batch"].extend([r_cold, r_hot])
        with engine._cv:
            assert engine._pick_next_locked() is r_hot
        assert r_cold.admit_skips == 1  # the overtaken request aged
        engine._queues["batch"].clear()
        # Barrier: a starved request terminates the scan and wins.
        r_starved = _Request(list(cold), 4, 0.0, 0)
        r_starved.admit_skips = engine._admit_skip_cap
        r_hot2 = _Request(list(hot), 4, 0.0, 0)
        engine._queues["batch"].extend([r_starved, r_hot2])
        with engine._cv:
            assert engine._pick_next_locked() is r_starved

    def test_moe_prefix_reuse_matches_dense(self):
        """The MoE family's suffix prefill (expert FFN over the novel
        tokens only): sequential shared-prefix prompts keep greedy
        parity with its dense engine."""
        from polyaxon_tpu.models import moe

        cfg = dataclasses.replace(moe.CONFIGS["moe_tiny"],
                                  dtype=jnp.float32)
        params = moe.init(cfg, jax.random.key(0))["params"]
        prompt = [5, 6, 7, 1, 2, 3, 4, 9, 8, 2]
        dense = ContinuousBatchingEngine("moe_tiny", cfg, params,
                                         slots=1, max_len=32)
        try:
            want = dense.generate([prompt], max_new_tokens=5, timeout=300)
        finally:
            dense.stop()
        paged = ContinuousBatchingEngine("moe_tiny", cfg, params,
                                         slots=1, max_len=32,
                                         kv="paged", page_size=4)
        try:
            first = paged.generate([prompt], max_new_tokens=5, timeout=300)
            second = paged.generate([prompt], max_new_tokens=5, timeout=300)
            stats = paged.stats()
        finally:
            paged.stop()
        assert first == want and second == want
        assert stats["prefill_tokens_skipped"] > 0
        assert stats["kv_invariant_violations"] == 0




class TestSuffixBucketUnit:
    """Pure bucketing math (smoke tier): padded suffix lengths are
    powers of two with a floor, so the distinct-executable count per
    prefix-page count is O(log max_suffix)."""

    def test_power_of_two_with_floor(self):
        from polyaxon_tpu.serving.batching import bucket_suffix_len

        assert bucket_suffix_len(1) == 8
        assert bucket_suffix_len(8) == 8
        assert bucket_suffix_len(9) == 16
        assert bucket_suffix_len(16) == 16
        assert bucket_suffix_len(17) == 32
        assert bucket_suffix_len(1000) == 1024
        with pytest.raises(ValueError, match="suffix length"):
            bucket_suffix_len(0)

    def test_bucket_count_is_logarithmic(self):
        from polyaxon_tpu.serving.batching import bucket_suffix_len

        buckets = {bucket_suffix_len(n) for n in range(1, 1025)}
        assert buckets == {8, 16, 32, 64, 128, 256, 512, 1024}


class TestSuffixBucketing:
    def test_varied_suffix_lengths_bound_compiles_with_parity(self):
        """Shared-prefix prompts with DISTINCT suffix lengths: the
        suffix-prefill executable count is the bucket count (here 4
        lengths → 2 buckets, observed via the lru cache_info), and the
        masked padding changes no tokens vs the dense engine."""
        cfg = _cfg()
        params = llama.init(cfg, jax.random.key(0))["params"]
        base = [3, 1, 4, 1, 5, 9, 2, 6]  # exactly 2 prefix pages
        # Distinct first tokens → divergence at the page boundary →
        # every request skips exactly the 2 base pages (one n_pref).
        # Prefill excludes the prompt's LAST token (fed at decode), so
        # these give prefill-suffix lengths 1, 3, 7, 9.
        suffixes = [[11, 30], [12, 13, 14, 30],
                    [15, 16, 17, 18, 13, 14, 15, 30],
                    [19, 20, 21, 22, 23, 24, 25, 26, 27, 30]]
        prompts = [base + s for s in suffixes]
        dense = ContinuousBatchingEngine("llama_tiny", cfg, params,
                                         slots=1, max_len=32)
        try:
            want = [dense.generate([p], max_new_tokens=4, timeout=300)
                    for p in prompts]
        finally:
            dense.stop()
        engine = ContinuousBatchingEngine("llama_tiny", cfg, params,
                                          slots=1, max_len=32,
                                          kv="paged", page_size=4)
        try:
            # Warmup writes the base chain; its own prefill is
            # monolithic (nothing cached yet) — not a suffix compile.
            engine.generate([base + [10]], max_new_tokens=4, timeout=300)
            got = [engine.generate([p], max_new_tokens=4, timeout=300)
                   for p in prompts]
            info = engine._suffix_prefill.cache_info()
            stats = engine.stats()
        finally:
            engine.stop()
        assert got == want
        # Suffix lengths 1, 3, 7, 9 land in buckets {8, 16}: two
        # executables serve all four requests.
        assert info.misses == 2
        assert info.hits == 2
        assert stats["kv_invariant_violations"] == 0
