// Threaded stress over the C ABI — built under -fsanitize=thread in CI
// (SURVEY.md §5.2: the reference's only race detection is `go test
// -race` on its operator; this is the equivalent for the C++ daemon).
// Several threads hammer one pool handle concurrently: placements,
// heartbeats, ticks, preemptions, releases. Exit 0 = no crash; TSan
// reports any data race on stderr (non-zero exit under halt_on_error).
#include <atomic>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {
void* sliced_new();
void sliced_free(void*);
int sliced_add_slice(void*, const char*, const char*, int);
long long sliced_request_gang(void*, const char*, const char*, int, int);
int sliced_release_gang(void*, long long);
int sliced_heartbeat(void*, long long, int, double);
int sliced_preempt_slice(void*, const char*);
int sliced_tick(void*, double, double, char*, int);
int sliced_gang_info(void*, long long, char*, int);
}

int main() {
  void* pool = sliced_new();
  sliced_add_slice(pool, "a", "8x8", 1);
  sliced_add_slice(pool, "b", "4x4", 0);

  std::atomic<long long> last_gang{0};
  std::vector<std::thread> threads;

  // Requesters + releasers.
  for (int t = 0; t < 3; ++t) {
    threads.emplace_back([&, t] {
      char name[32];
      for (int i = 0; i < 500; ++i) {
        std::snprintf(name, sizeof(name), "run-%d-%d", t, i);
        long long id = sliced_request_gang(pool, name, "2x2", i % 3, 1);
        if (id > 0) {
          last_gang.store(id);
          sliced_heartbeat(pool, id, 0, i * 1.0);
          if (i % 2) sliced_release_gang(pool, id);
        }
      }
    });
  }
  // Reconciler.
  threads.emplace_back([&] {
    char buf[1 << 16];
    for (int i = 0; i < 2000; ++i)
      sliced_tick(pool, i * 0.5, 30.0, buf, sizeof(buf));
  });
  // Preemptor + reader.
  threads.emplace_back([&] {
    char buf[4096];
    for (int i = 0; i < 500; ++i) {
      sliced_preempt_slice(pool, "a");
      long long id = last_gang.load();
      if (id > 0) sliced_gang_info(pool, id, buf, sizeof(buf));
    }
  });

  for (auto& thread : threads) thread.join();
  sliced_free(pool);
  std::puts("stress ok");
  return 0;
}
