"""Tracking + streams + sidecar tests (event contract, SURVEY.md §3.3)."""

import json
import os
import threading
import time

import pytest

from polyaxon_tpu.streams import StreamsService
from polyaxon_tpu.sidecar import SidecarSync, sync_tree
from polyaxon_tpu.tracking import (
    Run,
    V1EventKind,
    host_metrics,
    list_event_names,
    read_events,
)
from polyaxon_tpu.tracking import run as run_mod


class TestRun:
    def test_metrics_jsonl_contract(self, tmp_path):
        rd = str(tmp_path / "r1")
        with Run("r1", rd) as run:
            run.log_metrics(step=1, loss=2.5, accuracy=0.1)
            run.log_metrics(step=2, loss=2.1)
        events = read_events(rd, "metric", "loss")
        assert [e["value"] for e in events] == [2.5, 2.1]
        assert [e["step"] for e in events] == [1, 2]
        assert all("timestamp" in e for e in events)
        assert set(list_event_names(rd, "metric")) == {"loss", "accuracy"}

    def test_auto_step(self, tmp_path):
        rd = str(tmp_path / "r2")
        with Run("r2", rd) as run:
            run.log_metrics(loss=1.0)
            run.log_metrics(loss=0.9)
        assert [e["step"] for e in read_events(rd, "metric", "loss")] == [1, 2]

    def test_rich_event_helpers(self, tmp_path):
        """Image/histogram/confusion/html/dataframe events (traceml
        parity surface) produce assets + typed jsonl records."""
        import numpy as np

        rd = str(tmp_path / "rich")
        with Run("rich", rd) as run:
            img_path = run.log_image("sample", np.zeros((8, 8, 3)), step=1)
            assert img_path.endswith(".png") and os.path.exists(img_path)
            # Namespaced names and repeated unstepped logs must not
            # collide or overwrite.
            nested = run.log_image("eval/sample", np.full((4, 4), 200, np.int32))
            nested2 = run.log_image("eval/sample", np.zeros((4, 4), np.uint8))
            assert os.path.exists(nested) and nested != nested2
            # Integer arrays keep their 0-255 scale (not clipped to 0/1).
            from PIL import Image
            assert np.asarray(Image.open(nested)).max() == 200
            run.log_histogram("weights", np.random.default_rng(0).normal(size=100),
                              bins=10, step=1)
            run.log_confusion_matrix("cm", ["a", "b"], [[3, 1], [0, 4]], step=1)
            run.log_html("report", "<b>done</b>")

            class FakeDf:
                def to_csv(self, path, index=False):
                    with open(path, "w") as fh:
                        fh.write("a,b\n1,2\n")

            csv_path = run.log_dataframe("table", FakeDf())
            assert os.path.exists(csv_path)

        hist = read_events(rd, "histogram", "weights")[0]
        assert sum(hist["counts"]) == 100 and len(hist["edges"]) == 11
        cm = read_events(rd, "confusion", "cm")[0]
        assert cm["matrix"] == [[3, 1], [0, 4]] and cm["labels"] == ["a", "b"]
        # Events carry run-relative asset paths (portable off-host).
        rel = read_events(rd, "image", "sample")[0]["path"]
        assert not os.path.isabs(rel) and os.path.join(rd, rel) == img_path
        assert "<b>" in read_events(rd, "html", "report")[0]["html"]
        # Namespaced names are listed recursively.
        assert "eval/sample" in list_event_names(rd, "image")

    def test_outputs_merge_atomic(self, tmp_path):
        rd = str(tmp_path / "r3")
        with Run("r3", rd) as run:
            run.log_outputs(a=1)
            run.log_outputs(b="two")
        assert run.get_outputs() == {"a": 1, "b": "two"}

    def test_artifact_lineage(self, tmp_path):
        src = tmp_path / "model.bin"
        src.write_bytes(b"weights")
        rd = str(tmp_path / "r4")
        with Run("r4", rd) as run:
            dest = run.log_model(str(src))
        assert os.path.exists(dest)
        with open(os.path.join(rd, "lineage.jsonl")) as fh:
            record = json.loads(fh.readline())
        assert record["kind"] == V1EventKind.MODEL

    def test_statuses(self, tmp_path):
        rd = str(tmp_path / "r5")
        with Run("r5", rd) as run:
            run.log_succeeded()
        svc = StreamsService(str(tmp_path))
        statuses = svc.get_statuses("r5")
        assert statuses[-1]["status"] == "succeeded"

    def test_from_env(self, tmp_path, monkeypatch):
        monkeypatch.setenv(run_mod.ENV_RUN_UUID, "abc")
        monkeypatch.setenv(run_mod.ENV_ARTIFACTS_PATH, str(tmp_path / "abc"))
        run = run_mod.from_env()
        run.log_metrics(step=0, x=1.0)
        run.close()
        assert read_events(str(tmp_path / "abc"), "metric", "x")

    def test_from_env_missing_contract(self, monkeypatch):
        monkeypatch.delenv(run_mod.ENV_RUN_UUID, raising=False)
        monkeypatch.delenv(run_mod.ENV_ARTIFACTS_PATH, raising=False)
        with pytest.raises(RuntimeError):
            run_mod.from_env()


class TestSystemMetrics:
    def test_host_metrics_shape(self):
        metrics = host_metrics()
        assert "cpu_percent" in metrics and "memory_percent" in metrics

    def test_monitor_emits_final_sample(self, tmp_path):
        rd = str(tmp_path / "r6")
        run = Run("r6", rd, collect_system_metrics=True, system_metrics_interval=60)
        run.close()  # triggers the final sample
        names = list_event_names(rd, "system")
        assert "cpu_percent" in names

    def test_libtpu_metrics_degrade_silently(self):
        """Without real TPU hardware the libtpu monitoring probe must
        return quietly ({} or per-chip values) — never raise into the
        sampler; a raising SDK latches itself disabled. Skips where the
        TPU-VM libtpu wheel isn't installed (it is not a declared
        dependency — the probe itself degrades by design there)."""
        import sys as _sys

        import pytest as _pytest

        from polyaxon_tpu.tracking import systemmetrics as sm

        _sdk = _pytest.importorskip("libtpu.sdk")
        if not hasattr(_sdk, "tpumonitoring"):
            _pytest.skip("libtpu too old: no tpumonitoring")

        sm._libtpu_state.clear()
        sm._libtpu_state["disabled"] = False
        out = sm.libtpu_metrics()
        assert isinstance(out, dict)  # empty on a chip-less host

        class _Boom:
            @staticmethod
            def list_supported_metrics():
                raise RuntimeError("sdk broke")

        sm._libtpu_state.clear()
        sm._libtpu_state["disabled"] = False
        real = _sdk.tpumonitoring
        had_key = "libtpu.sdk.tpumonitoring" in _sys.modules
        prev = _sys.modules.get("libtpu.sdk.tpumonitoring")
        try:
            _sdk.tpumonitoring = _Boom
            # also the from-import path resolves via sys.modules
            _sys.modules["libtpu.sdk.tpumonitoring"] = _Boom
            assert sm.libtpu_metrics() == {}
            assert sm._libtpu_state["disabled"] is True
            assert sm.libtpu_metrics() == {}  # latched: no retry
        finally:
            _sdk.tpumonitoring = real
            if had_key:
                _sys.modules["libtpu.sdk.tpumonitoring"] = prev
            else:  # don't leave a synthetic entry the import system
                _sys.modules.pop("libtpu.sdk.tpumonitoring", None)
            sm._libtpu_state.clear()
            sm._libtpu_state["disabled"] = False


class TestSidecarAndStreams:
    def test_sync_tree_incremental(self, tmp_path):
        src, dest = tmp_path / "src", tmp_path / "dest"
        (src / "events" / "metric").mkdir(parents=True)
        (src / "events" / "metric" / "loss.jsonl").write_text('{"value": 1}\n')
        assert sync_tree(str(src), str(dest)) == 1
        assert sync_tree(str(src), str(dest)) == 0  # unchanged
        (src / "events" / "metric" / "loss.jsonl").write_text('{"value": 1}\n{"value": 2}\n')
        assert sync_tree(str(src), str(dest)) == 1

    def test_streams_over_synced_store(self, tmp_path):
        run_dir, store = tmp_path / "live" / "r7", tmp_path / "store" / "r7"
        with Run("r7", str(run_dir)) as run:
            run.log_metrics(step=1, score=0.5)
            run.log_outputs(done=True)
        sync_tree(str(run_dir), str(store))
        svc = StreamsService(str(tmp_path / "store"))
        assert svc.last_metric("r7", "score") == 0.5
        assert svc.get_outputs("r7") == {"done": True}

    def test_follow_logs_until_done(self, tmp_path):
        rd = tmp_path / "r8"
        logs = rd / "logs"
        logs.mkdir(parents=True)
        path = logs / "main.log"
        path.write_text("line1\n")
        svc = StreamsService(str(tmp_path))
        done = threading.Event()

        def writer():
            time.sleep(0.15)
            with open(path, "a") as fh:
                fh.write("line2\n")
            done.set()

        threading.Thread(target=writer).start()
        chunks = list(svc.follow_logs("r8", "main.log", poll_seconds=0.05,
                                      should_stop=done.is_set))
        assert "".join(chunks) == "line1\nline2\n"

    def test_artifact_path_escape_rejected(self, tmp_path):
        svc = StreamsService(str(tmp_path))
        with pytest.raises(ValueError):
            svc.artifact_path("r9", "../../etc/passwd")

    def test_torn_jsonl_line_skipped(self, tmp_path):
        rd = tmp_path / "r10"
        metric_dir = rd / "events" / "metric"
        metric_dir.mkdir(parents=True)
        (metric_dir / "loss.jsonl").write_text('{"value": 1.0}\n{"valu')
        events = read_events(str(rd), "metric", "loss")
        assert len(events) == 1


class TestWalkCache:
    def test_single_flight_under_concurrency(self, tmp_path):
        """N dashboard viewers missing the same TTL'd key concurrently
        must trigger ONE tree walk, with everyone getting its result."""
        svc = StreamsService(str(tmp_path))
        calls = []
        started = threading.Barrier(4)

        def compute():
            calls.append(1)
            time.sleep(0.15)  # long enough for all waiters to pile up
            return 42

        results = []

        def worker():
            started.wait()
            results.append(svc._cached_walk("k", compute))

        threads = [threading.Thread(target=worker) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert results == [42] * 4
        assert len(calls) == 1, f"{len(calls)} concurrent walks ran"
        # And the TTL hit path returns without recomputing.
        assert svc._cached_walk("k", compute) == 42
        assert len(calls) == 1


class TestSidecarRemoteStore:
    """The sidecar's URL-store branch (VERDICT r3 missing #4: the
    remote path had only ever run against injected local errors)
    executed end-to-end through a REAL fsspec backend — a registered
    scheme rides FsspecStore over fsspec's in-process memory
    filesystem, the exact url_to_fs/put_file/exception surface that
    gs://-s3:// destinations use, minus the network this environment
    doesn't have."""

    @pytest.fixture(autouse=True)
    def _fakegs(self, monkeypatch):
        from polyaxon_tpu.fs import store as store_mod

        monkeypatch.setitem(
            store_mod._REGISTRY, "fakegs",
            lambda url: store_mod.FsspecStore(
                url.replace("fakegs://", "memory://", 1)))

    def test_sidecar_ships_run_to_fsspec_store(self, tmp_path):
        import fsspec

        from polyaxon_tpu.fs.store import FsspecStore

        # Unique namespace: fsspec's memory filesystem is process-global.
        ns = f"sidecar-{id(self)}"
        run_dir = tmp_path / "live" / "r9"
        with Run("r9", str(run_dir)) as run:
            run.log_metrics(step=1, loss=2.5)
            run.log_text("note", "shipped")
        sidecar = SidecarSync(str(run_dir), f"fakegs://{ns}/r9",
                              interval_seconds=3600)
        assert isinstance(sidecar._store, FsspecStore)  # the fsspec branch
        shipped = sidecar.sync_once()
        assert shipped >= 2  # metric jsonl + text jsonl (+ outputs)
        # Incremental: an unchanged tree ships nothing...
        assert sidecar.sync_once() == 0
        # ...and an appended event ships exactly the changed file.
        with Run("r9", str(run_dir)) as run:
            run.log_metrics(step=2, loss=2.0)
        assert sidecar.sync_once() >= 1

        # The shipped bytes are REAL on the store side: read the metric
        # series back through the fsspec filesystem itself.
        fs = fsspec.filesystem("memory")
        metric_key = next(p for p in fs.find(f"/{ns}/r9")
                          if p.endswith("loss.jsonl"))
        lines = [json.loads(ln) for ln in
                 fs.cat_file(metric_key).decode().splitlines()]
        assert [ln["value"] for ln in lines] == [2.5, 2.0]

    def test_store_side_failure_is_loud_and_retried(
            self, tmp_path, monkeypatch, caplog):
        """A real fsspec write failure (broken put_file on the backend
        — not an injected local error) is warned and the file retries
        on the next pass after the store heals."""
        import logging

        from polyaxon_tpu.fs.store import FsspecStore

        ns = f"sidecar-ro-{id(self)}"
        run_dir = tmp_path / "live" / "r10"
        with Run("r10", str(run_dir)) as run:
            run.log_metrics(step=1, loss=1.0)
        sidecar = SidecarSync(str(run_dir), f"fakegs://{ns}/r10",
                              interval_seconds=3600)
        store = sidecar._store
        assert isinstance(store, FsspecStore)

        def broken_put(lpath, rpath, **kw):
            raise OSError("store offline (simulated fsspec backend error)")

        with monkeypatch.context() as mp:
            # fsspec caches filesystem singletons: scope the breakage.
            mp.setattr(store.fs, "put_file", broken_put)
            with caplog.at_level(logging.WARNING):
                assert sidecar.sync_once() == 0
        assert any("sync" in r.getMessage().lower()
                   or "failed" in r.getMessage().lower()
                   for r in caplog.records),             [r.getMessage() for r in caplog.records]
        # Store heals -> the same files ship on the next pass.
        assert sidecar.sync_once() >= 1
