"""Telemetry oracle + incident replay (ISSUE 13).

Invariant-kind goldens over hand-built telemetry bundles (exact
verdict/evidence asserts), the schema gate, the fire-then-resolve
interplay with the alert engine (including history-eviction
accounting), registry snapshot deltas, the serving ring dump
round-trip, replay determinism (same postmortem → byte-identical
trace → identical verdicts across two full control-plane runs), and
the ``plx ops verify`` / ``ControlPlane.verify`` surfaces.
"""

import copy
import json
import os

import pytest

from polyaxon_tpu.obs import metrics as obs_metrics
from polyaxon_tpu.obs import oracle as obs_oracle
from polyaxon_tpu.obs import reqtrace
from polyaxon_tpu.obs import rules as obs_rules
from polyaxon_tpu.obs.oracle import (
    Invariant,
    OracleError,
    TelemetryBundle,
)
from polyaxon_tpu.sim import replay as sim_replay

SCENARIO = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "polyaxon_tpu", "sim", "scenarios", "preemption-storm.json")


def _inv(**kw) -> Invariant:
    kw.setdefault("id", "t")
    return Invariant.from_dict(kw)


def _run(status="succeeded", uuid="u1", kind="job") -> dict:
    return {"uuid": uuid, "status": status, "kind": kind,
            "project": "platform", "name": None}


def _one(invariant, bundle) -> dict:
    verdicts = obs_oracle.evaluate([invariant], bundle)
    assert len(verdicts) == 1
    return verdicts[0]


# ================================================================= schema
class TestInvariantSchema:
    def test_committed_set_validates_and_covers_all_kinds(self):
        invariants = obs_oracle.check_invariants()
        ids = [i.id for i in invariants]
        assert len(ids) == len(set(ids))
        assert "all-runs-terminal" in ids
        assert "zero-unresolved-alerts" in ids
        assert {i.kind for i in invariants} == set(obs_oracle.KINDS)

    @pytest.mark.parametrize("bad,match", [
        ({"invariants": [{"id": "x", "kind": "nope"}]}, "unknown kind"),
        ({"invariants": [{"kind": "run_terminal"}]}, "string `id`"),
        ({"invariants": [{"id": "x", "kind": "metric",
                          "metric": "polyaxon_runs", "value": 1,
                          "op": "!="}]}, "unknown op"),
        ({"invariants": [{"id": "x", "kind": "metric",
                          "metric": "polyaxon_runs"}]}, "needs a `value`"),
        ({"invariants": [{"id": "x", "kind": "metric",
                          "metric": "polyaxon_runs", "value": 1,
                          "quantile": 1.5}]}, "outside"),
        ({"invariants": [{"id": "x", "kind": "slo",
                          "metric": "polyaxon_scheduler_tick_seconds",
                          "le": 1.0}]}, "needs `le` and `objective`"),
        ({"invariants": [{"id": "x", "kind": "slo",
                          "metric": "polyaxon_scheduler_tick_seconds",
                          "le": 1.0, "objective": 0.0}]}, "objective"),
        ({"invariants": [{"id": "x", "kind": "run_terminal",
                          "allow": ["definitely-not-a-status"]}]},
         "unknown statuses"),
        ({"invariants": [{"id": "x", "kind": "run_terminal",
                          "missing": "explode"}]}, "missing policy"),
    ])
    def test_malformed_invariants_raise(self, bad, match):
        with pytest.raises(OracleError, match=match):
            obs_oracle.load_invariants(bad)

    def test_duplicate_ids_raise(self):
        with pytest.raises(OracleError, match="duplicate"):
            obs_oracle.load_invariants({"invariants": [
                {"id": "x", "kind": "run_terminal"},
                {"id": "x", "kind": "alerts_resolved"}]})

    def test_unknown_metric_fails_the_gate(self):
        with pytest.raises(OracleError, match="unknown metric"):
            obs_oracle.load_invariants({"invariants": [
                {"id": "x", "kind": "metric",
                 "metric": "polyaxon_made_up_total", "value": 0}]})

    def test_check_cli_exit_codes(self, tmp_path):
        assert obs_oracle._main(["--check"]) == 0
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps({"invariants": [
            {"id": "x", "kind": "metric",
             "metric": "polyaxon_made_up_total", "value": 0}]}))
        assert obs_oracle._main(["--check", str(bad)]) == 1


# =========================================================== run_terminal
class TestRunTerminal:
    def test_all_terminal_passes_with_status_census(self):
        bundle = TelemetryBundle(runs=[_run("succeeded"),
                                       _run("failed", "u2")])
        v = _one(_inv(kind="run_terminal"), bundle)
        assert v["verdict"] == "pass"
        assert v["evidence"]["status_counts"] == {"succeeded": 1,
                                                  "failed": 1}

    def test_stuck_run_fails_with_offender_attached(self):
        bundle = TelemetryBundle(runs=[_run("succeeded"),
                                       _run("queued", "u2")])
        v = _one(_inv(kind="run_terminal"), bundle)
        assert v["verdict"] == "fail"
        assert v["evidence"]["offending_runs"] == [
            {"uuid": "u2", "status": "queued", "kind": "job",
             "project": "platform"}]

    def test_forbid_list_trumps_allow(self):
        bundle = TelemetryBundle(runs=[_run("failed")])
        v = _one(_inv(kind="run_terminal", forbid=["failed"]), bundle)
        assert v["verdict"] == "fail"

    def test_allow_list_narrows_terminal(self):
        bundle = TelemetryBundle(runs=[_run("failed")])
        v = _one(_inv(kind="run_terminal", allow=["succeeded"]), bundle)
        assert v["verdict"] == "fail"

    def test_missing_policy(self):
        empty = TelemetryBundle()
        assert _one(_inv(kind="run_terminal"), empty)["verdict"] == "skip"
        assert _one(_inv(kind="run_terminal", missing="fail"),
                    empty)["verdict"] == "fail"


# =========================================================== phase_budget
class TestPhaseBudget:
    @staticmethod
    def _bundle(wall, phase_sum):
        return TelemetryBundle(reports={"u1": {
            "wall_clock_ms": wall, "phase_sum_ms": phase_sum,
            "phases": {"step": {"ms": phase_sum}}}})

    def test_accounting_within_tolerance_passes(self):
        v = _one(_inv(kind="phase_budget", tolerance=0.35),
                 self._bundle(1000.0, 900.0))
        assert v["verdict"] == "pass"
        assert v["evidence"]["reports_judged"] == 1

    def test_lost_time_fails_with_ratio_evidence(self):
        v = _one(_inv(kind="phase_budget", tolerance=0.35),
                 self._bundle(1000.0, 500.0))
        assert v["verdict"] == "fail"
        assert v["evidence"]["offending_reports"][0]["ratio"] == 0.5

    def test_no_reports_skips(self):
        v = _one(_inv(kind="phase_budget"), TelemetryBundle())
        assert v["verdict"] == "skip"


# ================================================================= metric
class TestMetricPredicates:
    @pytest.fixture()
    def reg(self):
        return obs_metrics.MetricsRegistry()

    def test_value_mode_with_label_selector(self, reg):
        obs_metrics.admission_outcomes(reg).inc(3, outcome="rejected")
        obs_metrics.admission_outcomes(reg).inc(9, outcome="admitted")
        bundle = TelemetryBundle(snapshot=reg.snapshot())
        v = _one(_inv(kind="metric",
                      metric="polyaxon_admission_outcomes_total",
                      labels={"outcome": "rejected"}, op="<=", value=5),
                 bundle)
        assert v["verdict"] == "pass"
        assert v["evidence"]["observed"] == 3.0

    def test_missing_zero_treats_absent_series_as_zero(self, reg):
        bundle = TelemetryBundle(snapshot=reg.snapshot())
        v = _one(_inv(kind="metric",
                      metric="polyaxon_admission_live_divergence_total",
                      op="<=", value=0, missing="zero"), bundle)
        assert v["verdict"] == "pass"
        assert v["evidence"]["observed"] == 0.0

    def test_missing_skip_and_fail_policies(self, reg):
        bundle = TelemetryBundle(snapshot=reg.snapshot())
        spec = dict(kind="metric", metric="polyaxon_requeues_total",
                    op="<=", value=0)
        assert _one(_inv(**spec), bundle)["verdict"] == "skip"
        assert _one(_inv(**spec, missing="fail"),
                    bundle)["verdict"] == "fail"

    def test_delta_mode_judges_movement_not_absolutes(self, reg):
        counter = obs_metrics.requeues_total(reg)
        counter.inc(100, reason="preempted")
        baseline = reg.snapshot()
        counter.inc(2, reason="preempted")
        bundle = TelemetryBundle(snapshot=reg.snapshot(),
                                 baseline=baseline)
        v = _one(_inv(kind="metric", metric="polyaxon_requeues_total",
                      labels={"reason": "preempted"}, mode="delta",
                      op="<=", value=5), bundle)
        assert v["verdict"] == "pass"
        assert v["evidence"]["observed"] == 2.0

    def test_delta_mode_without_baseline_skips(self, reg):
        bundle = TelemetryBundle(snapshot=reg.snapshot())
        v = _one(_inv(kind="metric", metric="polyaxon_requeues_total",
                      mode="delta", op="<=", value=5), bundle)
        assert v["verdict"] == "skip"

    def test_quantile_golden_interpolates_in_bucket(self, reg):
        hist = obs_metrics.scheduler_tick_hist(reg)
        for _ in range(4):
            hist.observe(0.002)  # all land in the (0.001, 0.0025] bucket
        bundle = TelemetryBundle(snapshot=reg.snapshot())
        v = _one(_inv(kind="metric",
                      metric="polyaxon_scheduler_tick_seconds",
                      quantile=0.5, op="<=", value=0.0025), bundle)
        assert v["verdict"] == "pass"
        # rank 2 of 4 inside [0.001, 0.0025): 0.001 + 0.0015 * 2/4
        assert v["evidence"]["observed"] == pytest.approx(0.00175)

    def test_threshold_flips_on_op(self, reg):
        obs_metrics.retry_attempts(reg).inc(7)
        bundle = TelemetryBundle(snapshot=reg.snapshot())
        spec = dict(kind="metric", metric="polyaxon_retry_attempts_total",
                    value=5)
        assert _one(_inv(**spec, op="<="), bundle)["verdict"] == "fail"
        assert _one(_inv(**spec, op=">"), bundle)["verdict"] == "pass"


# ======================================================== loss_continuity
class TestLossContinuity:
    @staticmethod
    def _bundle(windows, restores=0):
        return TelemetryBundle(reports={"u1": {
            "steps": {"windows": windows},
            "phases": ({"restore": {"ms": 1.0, "count": restores}}
                       if restores else {})}})

    def test_contiguous_windows_pass(self):
        bundle = self._bundle([
            {"from_step": 1, "to_step": 50, "loss": 2.5},
            {"from_step": 51, "to_step": 100, "loss": 2.3}])
        v = _one(_inv(kind="loss_continuity"), bundle)
        assert v["verdict"] == "pass"
        assert v["evidence"]["runs_judged"] == 1

    def test_skipped_steps_fail_with_both_windows_attached(self):
        bundle = self._bundle([
            {"from_step": 1, "to_step": 50},
            {"from_step": 61, "to_step": 100}], restores=1)
        v = _one(_inv(kind="loss_continuity"), bundle)
        assert v["verdict"] == "fail"
        disc = v["evidence"]["discontinuities"][0]
        assert disc["problem"] == "skipped 10 step(s)"
        assert disc["window"]["to_step"] == 50
        assert disc["next_window"]["from_step"] == 61
        assert disc["restores"] == 1

    def test_max_gap_steps_allows_bounded_gaps(self):
        bundle = self._bundle([
            {"from_step": 1, "to_step": 50},
            {"from_step": 61, "to_step": 100}])
        v = _one(_inv(kind="loss_continuity", max_gap_steps=10), bundle)
        assert v["verdict"] == "pass"

    def test_loss_jump_across_boundary_fails(self):
        bundle = self._bundle([
            {"from_step": 1, "to_step": 50, "loss": 2.5},
            {"from_step": 51, "to_step": 100, "loss": 9.0}])
        v = _one(_inv(kind="loss_continuity", max_loss_jump=1.0), bundle)
        assert v["verdict"] == "fail"
        assert "loss jumped" in (
            v["evidence"]["discontinuities"][0]["problem"])

    def test_twice_resized_run_passes(self):
        """The elastic-gang golden (ISSUE 14): a run that shrank and
        regrew mid-train produces three mesh segments whose step windows
        stay contiguous and whose loss keeps descending — the oracle
        must certify that as continuity, resize phases and all."""
        bundle = TelemetryBundle(reports={"u1": {
            "steps": {"windows": [
                {"from_step": 1, "to_step": 4, "loss": 3.1},   # 8 devices
                {"from_step": 5, "to_step": 8, "loss": 2.7},   # 4 devices
                {"from_step": 9, "to_step": 12, "loss": 2.4},  # 8 again
            ]},
            "phases": {"resize": {"ms": 120.0, "count": 2},
                       "restore": {"ms": 40.0, "count": 2}}}})
        v = _one(_inv(kind="loss_continuity", max_loss_jump=1.0), bundle)
        assert v["verdict"] == "pass"
        assert v["evidence"]["runs_judged"] == 1

    def test_resize_boundary_gap_fails(self):
        """A resize that loses the batch pointer (window restarts past
        the saved step) is exactly what loss_continuity exists to catch."""
        bundle = TelemetryBundle(reports={"u1": {
            "steps": {"windows": [
                {"from_step": 1, "to_step": 4, "loss": 3.1},
                {"from_step": 7, "to_step": 10, "loss": 2.9},
            ]},
            "phases": {"resize": {"ms": 60.0, "count": 1}}}})
        v = _one(_inv(kind="loss_continuity"), bundle)
        assert v["verdict"] == "fail"
        assert v["evidence"]["discontinuities"][0]["problem"] == \
            "skipped 2 step(s)"

    def test_single_window_skips(self):
        bundle = self._bundle([{"from_step": 1, "to_step": 50}])
        assert _one(_inv(kind="loss_continuity"),
                    bundle)["verdict"] == "skip"


# ======================================================== alerts_resolved
class TestAlertsResolved:
    def test_firing_alert_fails_with_alert_attached(self):
        bundle = TelemetryBundle(alerts={
            "alerts": [{"rule": "retry-storm", "severity": "page"}],
            "rules": [], "history": []})
        v = _one(_inv(kind="alerts_resolved"), bundle)
        assert v["verdict"] == "fail"
        assert v["evidence"]["unresolved_alerts"][0]["rule"] == "retry-storm"

    def test_allowlisted_firing_alert_passes(self):
        bundle = TelemetryBundle(alerts={
            "alerts": [{"rule": "retry-storm"}], "rules": [],
            "history": []})
        v = _one(_inv(kind="alerts_resolved", allow=["retry-storm"]),
                 bundle)
        assert v["verdict"] == "pass"

    def test_resolved_history_passes_and_counts_the_episode(self):
        bundle = TelemetryBundle(alerts={
            "alerts": [], "rules": [],
            "history": [{"event": "fired", "rule": "r"},
                        {"event": "resolved", "rule": "r"}]})
        v = _one(_inv(kind="alerts_resolved"), bundle)
        assert v["verdict"] == "pass"
        assert v["evidence"]["fired_total"] == 1
        assert v["evidence"]["resolved_total"] == 1


# ==================================================================== slo
class TestSlo:
    @pytest.fixture()
    def reg(self):
        return obs_metrics.MetricsRegistry()

    def test_objective_met_passes_with_good_total_evidence(self, reg):
        hist = obs_metrics.serving_ttft_hist(reg)
        for _ in range(19):
            hist.observe(0.1, **{"class": "interactive"})
        hist.observe(9.0, **{"class": "interactive"})
        bundle = TelemetryBundle(snapshot=reg.snapshot())
        v = _one(_inv(kind="slo", metric="polyaxon_serving_ttft_seconds",
                      labels={"class": "interactive"}, le=2.5,
                      objective=0.95), bundle)
        assert v["verdict"] == "pass"
        assert v["evidence"] == {
            "metric": "polyaxon_serving_ttft_seconds",
            "labels": {"class": "interactive"}, "le": 2.5,
            "objective": 0.95, "good": 19, "total": 20, "ratio": 0.95}

    def test_objective_missed_fails(self, reg):
        hist = obs_metrics.serving_ttft_hist(reg)
        hist.observe(0.1, **{"class": "interactive"})
        hist.observe(9.0, **{"class": "interactive"})
        bundle = TelemetryBundle(snapshot=reg.snapshot())
        v = _one(_inv(kind="slo", metric="polyaxon_serving_ttft_seconds",
                      labels={"class": "interactive"}, le=2.5,
                      objective=0.95), bundle)
        assert v["verdict"] == "fail"

    def test_le_must_be_a_bucket_bound(self, reg):
        obs_metrics.serving_ttft_hist(reg).observe(
            0.1, **{"class": "interactive"})
        bundle = TelemetryBundle(snapshot=reg.snapshot())
        v = _one(_inv(kind="slo", metric="polyaxon_serving_ttft_seconds",
                      le=3.14159, objective=0.5), bundle)
        assert v["verdict"] == "skip"
        assert "not a bucket bound" in v["evidence"]["missing"]

    def test_no_observations_skips(self, reg):
        obs_metrics.ensure_serving_metrics(reg)
        bundle = TelemetryBundle(snapshot=reg.snapshot())
        v = _one(_inv(kind="slo", metric="polyaxon_serving_ttft_seconds",
                      le=2.5, objective=0.5), bundle)
        assert v["verdict"] == "skip"


# ========================================================= snapshot_delta
class TestSnapshotDelta:
    def test_counter_gauge_histogram_deltas(self):
        reg = obs_metrics.MetricsRegistry()
        counter = obs_metrics.requeues_total(reg)
        gauge = reg.gauge("polyaxon_queue_depth", "", ("queue",))
        hist = obs_metrics.scheduler_tick_hist(reg)
        counter.inc(5, reason="preempted")
        gauge.set(10, queue="prod")
        hist.observe(0.01)
        baseline = reg.snapshot()
        counter.inc(2, reason="preempted")
        gauge.set(4, queue="prod")
        hist.observe(0.02)
        hist.observe(0.03)
        delta = reg.snapshot_delta(baseline)
        assert delta["absolute"] is False
        deltas = delta["deltas"]
        assert deltas["polyaxon_requeues_total"]["series"] == {
            "preempted": 2.0}
        assert deltas["polyaxon_queue_depth"]["series"] == {"prod": -6.0}
        hd = deltas["polyaxon_scheduler_tick_seconds"]["series"][""]
        assert hd["count"] == 2
        assert hd["sum"] == pytest.approx(0.05)

    def test_unchanged_series_are_omitted(self):
        reg = obs_metrics.MetricsRegistry()
        obs_metrics.requeues_total(reg).inc(5, reason="preempted")
        baseline = reg.snapshot()
        delta = reg.snapshot_delta(baseline)
        assert delta == {"absolute": False, "deltas": {}}

    def test_no_baseline_returns_absolute_snapshot(self):
        reg = obs_metrics.MetricsRegistry()
        obs_metrics.requeues_total(reg).inc(1, reason="x")
        delta = reg.snapshot_delta(None)
        assert delta["absolute"] is True
        assert "polyaxon_requeues_total" in delta["snapshot"]


# ==================================================== rules.py interplay
class _FakeClock:
    def __init__(self, start=1000.0):
        self.now = start

    def __call__(self):
        return self.now


class TestOracleRulesInterplay:
    """The zero-unresolved-alerts invariant against a REAL AlertEngine
    driving its fire→hysteresis→resolve state machine."""

    @staticmethod
    def _engine(reg, clock):
        rule = obs_rules.Rule.from_dict({
            "id": "queue-deep", "kind": "threshold",
            "metric": "polyaxon_queue_depth", "op": ">", "value": 100,
            "for": "0s", "description": "t"})
        return obs_rules.AlertEngine([rule], registry=reg, clock=clock)

    def test_fire_then_resolve_arc(self):
        reg = obs_metrics.MetricsRegistry()
        clock = _FakeClock()
        engine = self._engine(reg, clock)
        gauge = reg.gauge("polyaxon_queue_depth", "", ("queue",))
        inv = _inv(kind="alerts_resolved")

        gauge.set(500, queue="fleet")
        clock.now += 1
        engine.evaluate()
        v = _one(inv, TelemetryBundle(alerts=engine.to_json()))
        assert v["verdict"] == "fail"
        assert (v["evidence"]["unresolved_alerts"][0]["rule"]
                == "queue-deep")

        gauge.set(0, queue="fleet")
        for _ in range(5):  # ride out clear hysteresis
            clock.now += 60
            engine.evaluate()
        v = _one(inv, TelemetryBundle(alerts=engine.to_json()))
        assert v["verdict"] == "pass"
        assert v["evidence"]["fired_total"] == 1
        assert v["evidence"]["resolved_total"] == 1

    def test_history_eviction_is_counted_in_catalogued_metric(self):
        import collections

        reg = obs_metrics.MetricsRegistry()
        engine = self._engine(reg, _FakeClock())
        engine.history = collections.deque(maxlen=2)
        for i in range(5):
            engine._append_history({"event": "fired", "i": i})
        assert len(engine.history) == 2
        snap = reg.snapshot()["polyaxon_alert_history_evictions_total"]
        assert snap["series"][""] == 3
        assert ("polyaxon_alert_history_evictions_total"
                in obs_metrics.catalog_metric_names())


# ============================================================== ring dump
class TestRingDump:
    @staticmethod
    def _ring(n=3):
        ring = reqtrace.TimelineRing(capacity=8)
        for i, klass in zip(range(n), ("interactive", "batch",
                                       "best-effort")):
            trace = reqtrace.RequestTrace(f"req{i:04d}", klass=klass)
            trace.start_phase("queue_wait")
            trace.start_phase("decode")
            trace.finish("ok")
            ring.add(trace)
        return ring

    def test_dump_round_trip(self, tmp_path):
        ring = self._ring()
        path = reqtrace.dump_ring(ring, str(tmp_path))
        assert os.path.basename(path) == reqtrace.TRACE_DUMP_FILE
        dump = reqtrace.read_ring_dump(str(tmp_path))
        assert dump["capacity"] == 8
        assert dump["evicted"] == 0
        assert [r["summary"]["request_id"] for r in dump["requests"]] == [
            "req0000", "req0001", "req0002"]
        # Full span records survive: build_timeline can reconstruct.
        from polyaxon_tpu.obs.trace import build_timeline

        timeline = build_timeline(dump["requests"][0]["records"],
                                  trace_id="req0000")
        assert timeline["spans"][0]["name"] == "request"

    def test_missing_or_corrupt_dump_reads_as_none(self, tmp_path):
        assert reqtrace.read_ring_dump(str(tmp_path / "nope.json")) is None
        bad = tmp_path / "bad.json"
        bad.write_text("{not json")
        assert reqtrace.read_ring_dump(str(bad)) is None

    def test_ring_dump_replays_by_class_queue(self, tmp_path):
        path = reqtrace.dump_ring(self._ring(), str(tmp_path / "r.json"))
        dump = reqtrace.read_ring_dump(path)
        events = sim_replay.trace_from_ring_dump(dump, horizon=4.0)
        assert len(events) == 3
        queues = {e.spec["name"]: e.spec.get("queue") for e in events}
        assert queues == {"req-req0000": "prod", "req-req0001": "batch",
                          "req-req0002": "best-effort"}
        assert all(0.0 <= e.at <= 4.0 for e in events)

    def test_engine_stop_dumps_ring(self, tmp_path, monkeypatch):
        """The batching engine's shutdown hook persists the ring and
        counts the dump — without standing up a device loop (the dump
        path is independent of the model)."""
        from polyaxon_tpu.serving.batching import ContinuousBatchingEngine

        engine = ContinuousBatchingEngine.__new__(ContinuousBatchingEngine)
        engine.request_tracing = True
        engine.trace_dump_path = str(tmp_path / "ring.json")
        engine._ring = self._ring()
        engine._dump_ring()
        dump = reqtrace.read_ring_dump(engine.trace_dump_path)
        assert len(dump["requests"]) == 3
        snap = obs_metrics.REGISTRY.snapshot().get(
            "polyaxon_serving_trace_dumps_total")
        assert snap["series"].get("ok", 0) >= 1


# ================================================================= replay
class TestReplayDeterminism:
    def test_postmortem_conversion_is_byte_identical(self):
        scenario = sim_replay.load_scenario(SCENARIO)
        one = sim_replay.trace_to_json(sim_replay.scenario_trace(scenario))
        two = sim_replay.trace_to_json(
            sim_replay.scenario_trace(copy.deepcopy(scenario)))
        assert one == two

    def test_committed_scenario_shape(self):
        scenario = sim_replay.load_scenario(SCENARIO)
        events = sim_replay.scenario_trace(scenario)
        kinds = {e.kind for e in events}
        assert "storm" in kinds  # the double-preemption replays
        assert sum(1 for e in events if e.kind == "storm") == 2
        incident = [e for e in events
                    if (e.spec or {}).get("name", "").startswith("replay-")]
        assert len(incident) == 1 and incident[0].at == 0.0

    def test_rebase_pins_incident_into_horizon(self):
        pm = {"run_uuid": "abc", "status": "failed", "ring": [
            {"type": "span", "name": "execute", "start": 5000.0,
             "events": [{"name": "requeue"}]},
            {"type": "span", "name": "execute", "start": 5100.0,
             "events": [{"name": "requeue"}]}]}
        events = sim_replay.trace_from_postmortem(pm, horizon=2.0)
        storms = [e.at for e in events if e.kind == "storm"]
        assert storms == [0.0, 2.0]

    def test_malformed_scenarios_raise(self):
        with pytest.raises(ValueError, match="source_kind"):
            sim_replay.load_scenario({"name": "x"})
        with pytest.raises(ValueError, match="missing"):
            sim_replay.load_scenario({"source_kind": "ring"})

    @pytest.mark.sim
    def test_same_scenario_same_verdicts_across_two_runs(self, tmp_path):
        """Full round trip: the committed postmortem replays through
        the REAL control plane twice — via the actual `--replay` CLI,
        each run in its own process so the oracle judges THAT replay's
        registry, not whatever ambient metrics this pytest process
        accumulated — and returns the same verdict per invariant both
        times (timings differ; judgments must not). Background trimmed
        to keep two full drains fast."""
        import subprocess
        import sys

        scenario = sim_replay.load_scenario(SCENARIO)
        scenario["background"] = {"jobs": 8, "churn": 3, "seed": 13}
        spath = tmp_path / "scenario.json"
        spath.write_text(json.dumps(scenario))
        results = []
        for i in range(2):
            out = tmp_path / f"replay{i}.json"
            proc = subprocess.run(
                [sys.executable, "-m", "polyaxon_tpu.sim", "--replay",
                 str(spath), "--json", str(out)],
                capture_output=True, text=True, timeout=300,
                env={**os.environ, "JAX_PLATFORMS": "cpu"})
            assert proc.returncode == 0, proc.stdout + proc.stderr
            results.append(json.loads(out.read_text()))
        verdicts = [[(v["invariant"], v["verdict"])
                     for v in r["oracle"]["verdicts"]] for r in results]
        assert verdicts[0] == verdicts[1]
        assert all(r["oracle"]["passed"] for r in results), verdicts[0]
        by_id = dict(verdicts[0])
        assert by_id["all-runs-terminal"] == "pass"
        assert by_id["zero-unresolved-alerts"] == "pass"


# ============================================================== gauntlet
class TestGauntletUnit:
    def test_trace_is_deterministic_and_composed(self):
        from polyaxon_tpu.sim import gauntlet

        one = gauntlet.build_gauntlet_trace(seed=7)
        two = gauntlet.build_gauntlet_trace(seed=7)
        assert sim_replay.trace_to_json(one) == sim_replay.trace_to_json(two)
        kinds = {e.kind for e in one}
        assert {"serving", "job", "sweep", "churn", "storm"} <= kinds

    def test_unknown_inject_rejected(self):
        from polyaxon_tpu.sim import gauntlet

        with pytest.raises(ValueError, match="unknown inject"):
            gauntlet.run_gauntlet(inject="made-up")


# ======================================================== verify surfaces
class TestVerifySurfaces:
    def test_plane_verify_fleet_and_per_run(self, tmp_path):
        from polyaxon_tpu.controlplane import ControlPlane
        from polyaxon_tpu.sim.traces import job_op

        plane = ControlPlane(str(tmp_path / "home"))
        record = plane.submit(job_op(), project="default")
        result = plane.verify()
        assert result["passed"] is False  # a CREATED run is not terminal
        by_id = {v["invariant"]: v for v in result["verdicts"]}
        offenders = by_id["all-runs-terminal"]["evidence"]["offending_runs"]
        assert offenders[0]["uuid"] == record.uuid
        scoped = plane.verify(record.uuid)
        assert scoped["run_uuid"] == record.uuid
        with pytest.raises(KeyError):
            plane.verify("no-such-uuid")

    def test_cli_ops_verify_and_alert_bounds(self, tmp_path, monkeypatch):
        from click.testing import CliRunner

        from polyaxon_tpu.cli.main import cli

        monkeypatch.setenv("POLYAXON_TPU_HOME", str(tmp_path / "home"))
        runner = CliRunner()
        result = runner.invoke(cli, ["ops", "verify", "--json"])
        assert result.exit_code in (0, 1)
        payload = json.loads(result.output)
        assert {v["invariant"] for v in payload["verdicts"]} >= {
            "all-runs-terminal", "zero-unresolved-alerts"}

        result = runner.invoke(cli, ["ops", "alerts", "--json",
                                     "--since", "15m", "--limit", "5"])
        assert result.exit_code == 0, result.output
        payload = json.loads(result.output)
        assert len(payload["history"]) <= 5

        result = runner.invoke(cli, ["ops", "alerts", "--since", "2 eons"])
        assert result.exit_code != 0
