"""Performance attribution from a run's span tree (ISSUE 6).

Dapper's payoff was never the spans — it was the analysis tooling on
top of them (PAPERS.md). This module folds a run's lifecycle timeline
(obs.trace.build_timeline output) into a report that answers the three
operator questions directly:

1. **Where did the wall clock go?** Leaf-phase spans decompose it:
   compile (control-plane spec compile + the warm-up XLA jit_compile),
   queue wait (compile end → first execute start), scheduling
   (admission + placement passes), init, restore, step — split into
   device compute vs input wait using each window's ``input_wait_ms`` —
   checkpoint, eval, requeue backoff (gaps between execute attempts),
   and sidecar sync; whatever no leaf covers is ``other``. Container
   spans (execute/runtime) are frames, not time sinks, and are
   excluded so phases sum to ~the wall clock instead of double it.
2. **Is step time drifting?** The per-emission-window ``step`` spans
   carry ``step_time_ms``; a rolling median + MAD (the robust pair —
   one checkpoint hiccup must not move the baseline) flags anomalous
   windows at ``|x - median| > 3.5 * 1.4826 * MAD``.
3. **What hit it?** Retry / ``chaos.*`` span events are counted per
   phase, requeues per reason — a chaos drill's report says which
   phase absorbed which fault without reading the raw timeline.

Surfaces: ``GET .../runs/{uuid}/report`` (ControlPlane.report),
``plx ops report <uuid> [--json]``, and a compact form recorded per
point by ``bench.py`` / ``scripts/perf_sweep.py`` so a sweep regression
arrives pre-attributed.
"""

from __future__ import annotations

import statistics
from typing import Any, Iterator, Optional

# Leaf phases, in report order. `step` is further split into device
# compute vs input wait; anything not covered lands in `other`.
PHASE_ORDER = ("compile", "queue_wait", "scheduling", "init", "jit_compile",
               "restore", "step", "input_wait", "checkpoint", "eval",
               "resize", "requeue_wait", "sync", "other")

# Span names that are containers (frames around children), not phases.
_CONTAINER_SPANS = {"execute", "runtime"}
# Leaf span name → phase bucket.
_LEAF_PHASES = {"compile": "compile", "admission": "scheduling",
                "placement": "scheduling", "init": "init",
                "jit_compile": "jit_compile", "restore": "restore",
                "checkpoint": "checkpoint", "eval": "eval", "sync": "sync",
                "resize": "resize"}

MAD_K = 3.5          # deviation threshold, in robust sigmas
MAD_SCALE = 1.4826   # MAD → sigma under normality
TREND_WINDOW = 8     # rolling window, in emission windows


def walk_spans(nodes: list[dict]) -> Iterator[dict]:
    for node in nodes:
        yield node
        yield from walk_spans(node.get("children") or [])


def _rolling_anomalies(windows: list[dict]) -> tuple[Optional[float],
                                                     list[dict]]:
    """Rolling-median/MAD anomaly flags over the step-time series.
    Each window is judged against the median/MAD of the up-to-
    TREND_WINDOW points BEFORE it (never itself — a spike must not
    vouch for itself)."""
    series = [w["step_time_ms"] for w in windows]
    anomalies: list[dict] = []
    for i, value in enumerate(series):
        history = series[max(i - TREND_WINDOW, 0):i]
        if len(history) < 3:
            continue
        median = statistics.median(history)
        mad = statistics.median(abs(x - median) for x in history)
        sigma = max(MAD_SCALE * mad, 1e-3 * max(median, 1.0))
        deviation = (value - median) / sigma
        if abs(deviation) > MAD_K:
            anomalies.append({
                "to_step": windows[i].get("to_step"),
                "step_time_ms": round(value, 3),
                "median_ms": round(median, 3),
                "deviation_sigmas": round(deviation, 2),
            })
    overall = statistics.median(series) if series else None
    return (round(overall, 3) if overall is not None else None), anomalies


def analyze_timeline(timeline: dict[str, Any]) -> dict[str, Any]:
    """Fold one run's span tree + annotations into the attribution
    report. Pure function of the timeline dict — callers attach run
    metadata (status, alerts) themselves."""
    spans = list(walk_spans(timeline.get("spans") or []))
    wall_ms = float(timeline.get("duration_ms") or 0.0)

    phases: dict[str, dict[str, float]] = {
        name: {"ms": 0.0, "count": 0} for name in PHASE_ORDER}

    def credit(name: str, ms: float, n: int = 1) -> None:
        phases[name]["ms"] += max(ms, 0.0)
        phases[name]["count"] += n

    step_windows: list[dict] = []
    executes: list[dict] = []
    compiles: list[dict] = []
    retries: dict[str, int] = {}
    chaos: dict[str, int] = {}
    # Restore-phase audit (ISSUE 16): corrupt steps culled during
    # fallback and the tier each restore was satisfied from, surfaced
    # on the report's restore phase so `plx ops report` shows WHERE a
    # rerun resumed and what it had to skip to get there.
    restore_skipped: list[int] = []
    restore_tiers: dict[str, int] = {}
    for span in spans:
        name = span.get("name") or ""
        duration = float(span.get("duration_ms") or 0.0)
        for event in span.get("events") or []:
            ev_name = event.get("name") or ""
            if ev_name == "retry":
                retries[name] = retries.get(name, 0) + 1
            elif ev_name.startswith("chaos."):
                chaos[name] = chaos.get(name, 0) + 1
        if name in _CONTAINER_SPANS:
            if name == "execute":
                executes.append(span)
            continue
        if name == "step":
            attrs = span.get("attributes") or {}
            steps = int(attrs.get("steps") or 0)
            wait_ms = float(attrs.get("input_wait_ms") or 0.0) * steps
            wait_ms = min(wait_ms, duration)
            credit("input_wait", wait_ms, 0)
            credit("step", duration - wait_ms)
            if attrs.get("step_time_ms") is not None:
                window = {
                    "from_step": attrs.get("from_step"),
                    "to_step": attrs.get("to_step"),
                    "steps": steps,
                    "step_time_ms": float(attrs["step_time_ms"]),
                    "input_wait_ms": float(attrs.get("input_wait_ms") or 0.0),
                }
                # The oracle's loss-continuity invariant reads the loss
                # each window ended at, when the loop recorded one.
                if attrs.get("loss") is not None:
                    window["loss"] = float(attrs["loss"])
                step_windows.append(window)
            continue
        phase = _LEAF_PHASES.get(name)
        if phase is not None:
            credit(phase, duration)
            if name == "compile":
                compiles.append(span)
            elif name == "restore":
                attrs = span.get("attributes") or {}
                restore_skipped.extend(
                    int(s) for s in attrs.get("skipped_steps") or [])
                tier = attrs.get("restore_tier")
                if tier is not None:
                    tier = str(tier)
                    restore_tiers[tier] = restore_tiers.get(tier, 0) + 1

    # Waits between phases: compile end → first execute start is queue
    # time; gaps between execute attempts are requeue backoff.
    executes.sort(key=lambda s: s.get("start") or 0)
    if executes and compiles:
        first_compile = min(compiles, key=lambda s: s.get("start") or 0)
        if (first_compile.get("end") is not None
                and executes[0].get("start") is not None):
            credit("queue_wait",
                   (executes[0]["start"] - first_compile["end"]) * 1e3)
    for prev, nxt in zip(executes, executes[1:]):
        if prev.get("end") is not None and nxt.get("start") is not None:
            credit("requeue_wait", (nxt["start"] - prev["end"]) * 1e3)

    accounted = sum(p["ms"] for name, p in phases.items() if name != "other")
    if wall_ms > accounted:
        phases["other"]["ms"] = wall_ms - accounted
        phases["other"]["count"] = 1

    step_windows.sort(key=lambda w: (w.get("to_step") is None,
                                     w.get("to_step") or 0))
    median_ms, anomalies = _rolling_anomalies(step_windows)

    requeues: dict[str, int] = {}
    for event in timeline.get("events") or []:
        if event.get("name") == "requeue":
            reason = ((event.get("attributes") or {}).get("reason")
                      or "unknown")
            requeues[reason] = requeues.get(reason, 0) + 1

    phase_sum = sum(p["ms"] for p in phases.values())
    report_phases = {}
    for name in PHASE_ORDER:
        entry = phases[name]
        if entry["ms"] <= 0 and not entry["count"]:
            continue
        report_phases[name] = {
            "ms": round(entry["ms"], 3),
            "fraction": (round(entry["ms"] / wall_ms, 4)
                         if wall_ms > 0 else None),
            "count": int(entry["count"]),
        }
    if "restore" in report_phases:
        if restore_skipped:
            report_phases["restore"]["skipped_steps"] = restore_skipped
        if restore_tiers:
            report_phases["restore"]["tiers"] = dict(
                sorted(restore_tiers.items()))
    return {
        "run_uuid": timeline.get("trace_id"),
        "wall_clock_ms": round(wall_ms, 3),
        "phase_sum_ms": round(phase_sum, 3),
        "attempts": len(executes),
        "phases": report_phases,
        "steps": {
            "windows": [
                {**w, "step_time_ms": round(w["step_time_ms"], 3),
                 "input_wait_ms": round(w["input_wait_ms"], 3)}
                for w in step_windows],
            "rolling_median_ms": median_ms,
            "anomalies": anomalies,
        },
        "annotations": {
            "retries": retries,
            "chaos": chaos,
            "requeues": requeues,
        },
    }


def request_phases(timeline: dict[str, Any]) -> dict[str, Any]:
    """Phase decomposition of ONE serving-request timeline
    (obs.reqtrace span tree): queue-wait / prefill / decode
    milliseconds, TTFT (request start → the decode phase's
    ``first_token`` event), tokens out, and the per-phase event tallies
    (chunks streamed, speculative rounds, requeues/evictions). Pure
    function of the timeline dict — GET /requests/{id}/timeline
    attaches it as ``summary`` and ``plx ops request-timeline`` prints
    it above the waterfall."""
    spans = list(walk_spans(timeline.get("spans") or []))
    root = next((s for s in spans if (s.get("name") or "") == "request"),
                None)
    # Behind a fleet (ISSUE 20) the tree's top hop is the router's
    # `route` span; it is an upstream decision, not an engine phase,
    # so it reports as its own field instead of joining phases_ms.
    route = next((s for s in spans if (s.get("name") or "") == "route"),
                 None)
    phases_ms: dict[str, float] = {}
    events: dict[str, int] = {}
    ttft_ms = None
    t0 = root.get("start") if root is not None else None
    for span in spans:
        name = span.get("name") or ""
        if name not in ("request", "route"):
            phases_ms[name] = (phases_ms.get(name, 0.0)
                               + float(span.get("duration_ms") or 0.0))
        for event in span.get("events") or []:
            ev = event.get("name") or ""
            events[ev] = events.get(ev, 0) + 1
            if (ev == "first_token" and ttft_ms is None and t0 is not None
                    and event.get("time") is not None):
                ttft_ms = (float(event["time"]) - float(t0)) * 1e3
    attrs = (root.get("attributes") or {}) if root is not None else {}
    return {
        "request_id": timeline.get("trace_id"),
        "class": attrs.get("class"),
        "status": root.get("status") if root is not None else None,
        "wall_clock_ms": round(float(timeline.get("duration_ms") or 0.0), 3),
        "phases_ms": {name: round(ms, 3)
                      for name, ms in sorted(phases_ms.items())},
        "ttft_ms": round(ttft_ms, 3) if ttft_ms is not None else None,
        "tokens_out": attrs.get("tokens_out"),
        # Radix prefix reuse (paged kv): prefill tokens served from the
        # cache instead of recomputed for THIS request.
        "prefix_cached_tokens": attrs.get("prefix_cached_tokens"),
        "events": events,
        **({"route": {
            "decision": (route.get("attributes") or {}).get("decision"),
            "replica": (route.get("attributes") or {}).get("replica"),
        }} if route is not None else {}),
        **({"replica": root.get("component")}
           if root is not None and root.get("component")
           and root.get("component") != "serving" else {}),
        **({"error": root.get("error")}
           if root is not None and root.get("error") else {}),
    }


def analyze_run_dir(run_dir: str) -> dict[str, Any]:
    """Report straight from a run's artifacts dir (bench/perf_sweep use
    this without a control plane)."""
    from polyaxon_tpu.obs.trace import build_timeline, read_trace

    return analyze_timeline(build_timeline(read_trace(run_dir)))


def compact_report(report: dict[str, Any]) -> dict[str, Any]:
    """The per-point form bench records: phase milliseconds + trend
    verdict, without the full window list."""
    return {
        "wall_clock_ms": report["wall_clock_ms"],
        "phases_ms": {name: entry["ms"]
                      for name, entry in report["phases"].items()},
        "rolling_median_step_ms": report["steps"]["rolling_median_ms"],
        "anomalous_windows": len(report["steps"]["anomalies"]),
        "annotations": report["annotations"],
    }
