"""Joins (query → collected params) + event triggers (run-event gated
compilation) — SURVEY.md §2 Polyflow IR: joins, events/hooks."""

import pytest

from polyaxon_tpu.agent import Agent
from polyaxon_tpu.controlplane import ControlPlane
from polyaxon_tpu.controlplane.joins import JoinError, parse_query, resolve_joins
from polyaxon_tpu.lifecycle import V1Statuses

QUICK = {
    "kind": "component",
    "run": {"kind": "job",
            "container": {"command": ["python", "-c", "print('ok')"]}},
}

WRITER = {
    "kind": "component",
    "inputs": [{"name": "score", "type": "float", "toEnv": "SCORE"}],
    "run": {"kind": "job", "container": {"command": [
        "python", "-c",
        "import os, json\n"
        "d = os.environ['POLYAXON_RUN_ARTIFACTS_PATH']\n"
        "json.dump({'score': float(os.environ['SCORE'])},"
        " open(d+'/outputs.json','w'))\n",
    ]}},
}


@pytest.fixture()
def plane(tmp_path):
    return ControlPlane(str(tmp_path / "home"))


@pytest.fixture()
def agent(plane):
    return Agent(plane, max_concurrent=8)


class TestQueryParsing:
    def test_fields(self):
        assert parse_query("pipeline: abc, status: succeeded") == {
            "pipeline": "abc", "status": "succeeded"}

    def test_bad_clause(self):
        with pytest.raises(JoinError, match="field: value"):
            parse_query("pipeline")

    def test_unknown_field(self):
        with pytest.raises(JoinError, match="unknown join query field"):
            parse_query("planet: mars")


class TestJoins:
    def test_collects_outputs_across_runs(self, plane, agent):
        uuids = []
        for score in (0.5, 0.25):
            record = plane.submit(WRITER, params={"score": score}, tags=["trial"])
            assert agent.run_until_done(record.uuid, timeout=60) == V1Statuses.SUCCEEDED
            uuids.append(record.uuid)

        joined = resolve_joins(
            plane.store, plane.streams,
            [{"query": "status: succeeded, tags: trial", "sort": "created_at",
              "params": {"scores": {"value": "outputs.score"},
                         "run_uuids": {"value": "uuid"}}}],
            project="default")
        assert joined["scores"] == [0.5, 0.25]
        assert joined["run_uuids"] == uuids

    def test_join_feeds_downstream_run(self, plane, agent):
        for score in (1.0, 2.0):
            record = plane.submit(WRITER, params={"score": score}, tags=["j2"])
            agent.run_until_done(record.uuid, timeout=60)

        consumer = {
            "kind": "operation",
            "joins": [{"query": "status: succeeded, tags: j2",
                       "params": {"scores": {"value": "outputs.score"}}}],
            "component": {
                "inputs": [{"name": "scores", "type": "any", "toEnv": "SCORES"}],
                "run": {"kind": "job", "container": {"command": [
                    "python", "-c", "import os; print('got', os.environ['SCORES'])",
                ]}},
            },
        }
        record = plane.submit(consumer)
        assert agent.run_until_done(record.uuid, timeout=60) == V1Statuses.SUCCEEDED
        logs = plane.streams.read_logs(record.uuid, "main-0.log")[0]
        assert "1.0" in logs and "2.0" in logs

    def test_limit_and_sort_desc(self, plane, agent):
        for score in (1.0, 2.0, 3.0):
            record = plane.submit(WRITER, params={"score": score}, tags=["j3"])
            agent.run_until_done(record.uuid, timeout=60)
        joined = resolve_joins(
            plane.store, plane.streams,
            [{"query": "status: succeeded, tags: j3", "sort": "-created_at",
              "limit": 2, "params": {"scores": {"value": "outputs.score"}}}],
            project="default")
        assert joined["scores"] == [3.0, 2.0]


class TestEvents:
    def test_run_waits_for_event_then_fires(self, plane, agent):
        slow = plane.submit({
            "kind": "component",
            "run": {"kind": "job", "container": {"command": [
                "python", "-c", "import time; time.sleep(2)"]}},
        })
        follower = plane.submit({
            "kind": "operation",
            "events": [{"ref": f"runs.{slow.uuid}", "kinds": ["succeeded"]}],
            "component": QUICK,
        })
        agent.reconcile_once()
        # The follower must not compile while the event hasn't fired.
        assert plane.get_run(follower.uuid).status == V1Statuses.CREATED
        assert agent.run_until_done(slow.uuid, timeout=60) == V1Statuses.SUCCEEDED
        assert agent.run_until_done(follower.uuid, timeout=60) == V1Statuses.SUCCEEDED

    def test_event_that_cannot_fire_upstream_fails(self, plane, agent):
        failing = plane.submit({
            "kind": "component",
            "run": {"kind": "job", "container": {"command": [
                "python", "-c", "raise SystemExit(1)"]}},
        })
        follower = plane.submit({
            "kind": "operation",
            "events": [{"ref": f"runs.{failing.uuid}", "kinds": ["succeeded"]}],
            "component": QUICK,
        })
        agent.run_until_done(failing.uuid, timeout=60)
        status = agent.run_until_done(follower.uuid, timeout=30)
        assert status == V1Statuses.UPSTREAM_FAILED

    def test_invalid_ref_fails(self, plane, agent):
        follower = plane.submit({
            "kind": "operation",
            "events": [{"ref": "runs.no-such-run", "kinds": ["succeeded"]}],
            "component": QUICK,
        })
        status = agent.run_until_done(follower.uuid, timeout=30)
        assert status == V1Statuses.FAILED
