"""Model convention for the built-in zoo.

The reference ships no model math at all (SURVEY.md §2b: delegated to
user containers); this zoo is net-new surface that makes the BASELINE
configs runnable end-to-end. Every model is a pure-JAX pytree module:

- ``init(rng) -> Variables``            params + (optional) mutable state
- ``apply(variables, batch, train, rng) -> (loss, metrics, new_state)``
- ``logical_axes() -> Variables``-shaped pytree of logical-axis tuples
  consumed by ``parallel.sharding`` rule tables.

Design choices are TPU-first: weights in fp32 master copies, compute in
bfloat16 (MXU-native), losses/softmax in fp32; transformer layers are
*stacked* along a leading ``layers`` dim and executed with ``lax.scan``
(one compiled layer body instead of L unrolled copies — small HLO, fast
compile, remat-friendly).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

Variables = dict[str, Any]  # {"params": pytree, "state": pytree}
Batch = dict[str, jax.Array]
Metrics = dict[str, jax.Array]


@dataclasses.dataclass(frozen=True)
class ModelDef:
    name: str
    init: Callable[[jax.Array], Variables]
    apply: Callable[..., tuple[jax.Array, Metrics, Any]]
    logical_axes: Callable[[], Variables]
    # tokens (LM) or samples (vision) consumed per batch element; used by
    # the runtime for throughput accounting.
    unit: str = "examples"
    # Metric keys that are mask-independent per-microbatch means (e.g.
    # MoE router aux): gradient accumulation averages them uniformly
    # instead of valid-token-weighted. A model with such a loss term
    # must also expose it as the differentiable ``loss_unweighted``
    # metric so the accumulated gradient stays exact.
    uniform_metrics: tuple = ()


def truncated_normal_init(rng, shape, dtype=jnp.float32, stddev=0.02):
    return stddev * jax.random.truncated_normal(rng, -2.0, 2.0, shape, dtype)


def scaled_init(rng, shape, dtype=jnp.float32, *, fan_in: Optional[int] = None):
    """LeCun-style scaling by fan-in (default: product of all but last axis)."""
    import math

    if fan_in is None:
        fan_in = shape[0] if len(shape) <= 2 else math.prod(shape[:-1])
    stddev = 1.0 / math.sqrt(max(int(fan_in), 1))
    return truncated_normal_init(rng, shape, dtype, stddev=stddev)


def rope_frequencies(d_half: int, theta: float,
                     scaling: Optional[dict] = None) -> jax.Array:
    """Inverse RoPE frequencies, optionally Llama-3.1-style scaled for
    context extension: low-frequency bands are stretched by ``factor``,
    high-frequency bands kept, and the transition smoothed — the
    public "llama3" rope_scaling rule.

    ``scaling``: {"factor": 8, "low_freq_factor": 1,
                  "high_freq_factor": 4,
                  "original_max_position_embeddings": 8192}
    """
    freqs = 1.0 / (theta ** (jnp.arange(0, d_half, dtype=jnp.float32) / d_half))
    if not scaling:
        return freqs
    factor = float(scaling.get("factor", 8.0))
    low = float(scaling.get("low_freq_factor", 1.0))
    high = float(scaling.get("high_freq_factor", 4.0))
    orig = float(scaling.get("original_max_position_embeddings", 8192))
    wavelen = 2.0 * jnp.pi / freqs
    # Per-band rule: long wavelengths (beyond orig/low) are scaled down
    # by `factor`; short ones (below orig/high) untouched; in between,
    # linearly interpolated in "smooth" space.
    smooth = (orig / wavelen - low) / (high - low)
    smooth = jnp.clip(smooth, 0.0, 1.0)
    scaled = freqs / factor
    return (1.0 - smooth) * scaled + smooth * freqs


def rope(x: jax.Array, positions: jax.Array, theta: float,
         scaling: Optional[dict] = None) -> jax.Array:
    """Rotary position embeddings on [B, S, H, D] with fp32 trig (shared
    by the Llama decoder and the T5-style decoder self-attention)."""
    d_half = x.shape[-1] // 2
    freqs = rope_frequencies(d_half, theta, scaling)
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B, S, d_half]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    rotated = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return rotated.astype(x.dtype)


def rms_norm(x: jax.Array, weight: jax.Array, eps: float = 1e-5,
             offset: float = 0.0) -> jax.Array:
    """``offset``: Gemma stores norm gains as deltas applied as
    ``(offset + w)`` with offset 1 (zero-init == identity); llama-style
    weights use offset 0."""
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    normed = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (normed * (offset + weight.astype(jnp.float32))).astype(dtype)


def layer_norm(x: jax.Array, scale: jax.Array, bias: jax.Array, eps: float = 1e-6) -> jax.Array:
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mean), axis=-1, keepdims=True)
    normed = (x32 - mean) * jax.lax.rsqrt(var + eps)
    return (normed * scale.astype(jnp.float32) + bias.astype(jnp.float32)).astype(dtype)


def _w(w, dt):
    """Weight read at the point of CONSUMPTION (shared by every model
    family). Plain arrays cast to the compute dtype; int8
    ``QuantizedTensor`` leaves (duck-typed via ``.dequantize`` —
    serving/quantize.py, no serving import here) dequantize HERE,
    inside whatever scan body is executing, so XLA fuses int8-read →
    convert → matmul and per-step HBM traffic stays int8. Dequantizing
    a whole tree BEFORE a decode scan instead gets hoisted out of the
    loop by XLA, materializing a bf16 copy that every step then
    re-reads — the round-3 0.88x int8 anomaly (VERDICT r3 #3)."""
    if hasattr(w, "dequantize"):
        return w.dequantize().astype(dt)
    return w.astype(dt)


def _lm_chunk_len(V: int, chunk: int):
    """Largest power-of-two chunk <= min(chunk, V // 2), or None when V
    is too small to split (callers fall back to the one-dot path)."""
    cap = min(chunk, V // 2)
    if cap < 1:
        return None
    return 1 << (cap.bit_length() - 1)


def lm_logits(x, w, dt, *, transpose: bool = False, chunk: int = 4096):
    """Final projection ``x [..., D] @ head -> [..., V] fp32``, shared
    by every decoder family's decode paths.

    Plain weights take one dot. ``QuantizedTensor`` heads are computed
    as a ``lax.scan`` over V-chunks instead — NOT an optimization:
    a monolithic ``dequantize()`` here is loop-invariant inside a
    decode scan, and XLA hoists it past every guard tried (ADVICE r4
    #1, all verified in compiled HLO on this backend):
    ``optimization_barrier`` is dropped before the hoist, a full-shape
    ``dynamic_slice`` pin is canonicalized away (clamping proves
    start 0), and a mixed bf16 x s8 dot is legalized by upconverting
    the s8 operand — in every case a full-precision [D, V] table ends
    up riding the while-loop carry, re-read every decode step, erasing
    the int8 HBM saving for the largest per-step matmul. The scan's xs
    mechanism is the one structure that provably stays int8 in-loop
    (it is why scanned LAYER weights were never affected): each chunk
    is dynamic-sliced by the induction variable, so its dequant is
    loop-DEPENDENT and fuses into that chunk's dot operand read. The
    chunk reshape/pad of the s8 table is itself invariant and hoists —
    as int8, which is the point. Per-column math is identical to the
    one-dot path (column chunking does not reorder the contraction),
    so greedy parity with the unquantized tree is preserved.

    ``transpose=True`` reads a tied-embedding head stored [V, D]
    (scale per-D); otherwise [D, V] (scale per-V).
    """
    if not hasattr(w, "dequantize"):
        tab = (w.T if transpose else w).astype(dt)
        return (x @ tab).astype(jnp.float32)
    q, scale = w.q, w.scale
    V = q.shape[0] if transpose else q.shape[1]
    c = _lm_chunk_len(V, chunk)
    if c is None:
        tab = w.dequantize().astype(dt)
        tab = tab.T if transpose else tab
        return (x @ tab).astype(jnp.float32)
    N = -(-V // c)
    pad = N * c - V
    if transpose:  # q [V, D], scale [1, D]
        qs = jnp.pad(q, ((0, pad), (0, 0))).reshape(N, c, -1)

        def body(_, qi):  # qi [c, D]
            tab = (qi.astype(jnp.float32) * scale).astype(dt)
            y = jax.lax.dot_general(
                x, tab, (((x.ndim - 1,), (1,)), ((), ())))
            return None, y.astype(jnp.float32)

        _, ys = jax.lax.scan(body, None, qs)
    else:  # q [D, V], scale [1, V]
        D = q.shape[0]
        qs = jnp.moveaxis(
            jnp.pad(q, ((0, 0), (0, pad))).reshape(D, N, c), 1, 0)
        ss = jnp.moveaxis(
            jnp.pad(scale, ((0, 0), (0, pad))).reshape(1, N, c), 1, 0)

        def body(_, wc):  # [D, c] + [1, c]
            qi, si = wc
            tab = (qi.astype(jnp.float32) * si).astype(dt)
            return None, (x @ tab).astype(jnp.float32)

        _, ys = jax.lax.scan(body, None, (qs, ss))
    out = jnp.moveaxis(ys, 0, -2).reshape(*x.shape[:-1], N * c)
    return out[..., :V]


def _embed_rows(embed, tokens, dt):
    """Embedding gather that keeps int8 reads int8: gather the int8
    rows first, then dequantize only the gathered rows — never the
    whole [V, D] table (llama3-scale tables are the largest single
    weight; a per-step full-table dequant would swamp the decode)."""
    if hasattr(embed, "dequantize"):
        rows = embed.q[tokens].astype(jnp.float32) * embed.scale
        return rows.astype(dt)
    return embed.astype(dt)[tokens]


def cross_entropy_loss(
    logits: jax.Array,  # [..., vocab] any float dtype; upcast internally
    labels: jax.Array,  # [...] int32
    mask: Optional[jax.Array] = None,  # [...] 0/1
) -> tuple[jax.Array, jax.Array]:
    """Mean CE over unmasked positions (fp32), plus accuracy."""
    logits = logits.astype(jnp.float32)
    log_probs = jax.nn.log_softmax(logits, axis=-1)
    labels_clipped = jnp.maximum(labels, 0)
    nll = -jnp.take_along_axis(log_probs, labels_clipped[..., None], axis=-1)[..., 0]
    correct = (jnp.argmax(logits, axis=-1) == labels_clipped).astype(jnp.float32)
    if mask is None:
        mask = (labels >= 0).astype(jnp.float32)
    else:
        mask = mask.astype(jnp.float32) * (labels >= 0).astype(jnp.float32)
    denom = jnp.maximum(mask.sum(), 1.0)
    loss = (nll * mask).sum() / denom
    acc = (correct * mask).sum() / denom
    return loss, acc


def chunked_lm_loss(
    hidden: jax.Array,  # [B, S, D] compute-dtype final hidden states
    head: jax.Array,  # [D, V] projection (compute dtype)
    labels: jax.Array,  # [B, S] int32
    mask: Optional[jax.Array] = None,  # [B, S] 0/1
    chunk: int = 256,
) -> tuple[jax.Array, jax.Array]:
    """Next-token CE without materializing the [B, S, V] logits tensor.

    The lm-head projection + log-softmax run one sequence chunk at a
    time under ``jax.checkpoint``, so peak HBM holds a [B, chunk, V]
    slab instead of the full fp32 logits (2 GB+ at 8×2048×32k) — the
    backward pass recomputes each chunk's logits from the saved hidden
    slab. Numerics are identical to ``cross_entropy_loss`` over full
    logits: per-position log-softmax is independent of chunking.
    """
    from polyaxon_tpu.ops.flash import pick_block

    B, S, D = hidden.shape
    chunk = pick_block(S, chunk)
    n_chunks = S // chunk
    if mask is None:
        mask = (labels >= 0)
    mask = mask.astype(jnp.float32) * (labels >= 0).astype(jnp.float32)
    labels_clipped = jnp.maximum(labels, 0)

    h = hidden.reshape(B, n_chunks, chunk, D).transpose(1, 0, 2, 3)
    y = labels_clipped.reshape(B, n_chunks, chunk).transpose(1, 0, 2)
    m = mask.reshape(B, n_chunks, chunk).transpose(1, 0, 2)

    @jax.checkpoint
    def chunk_stats(args):
        hc, yc, mc = args  # [B, chunk, D], [B, chunk], [B, chunk]
        logits = (hc @ head).astype(jnp.float32)  # [B, chunk, V]
        log_probs = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(log_probs, yc[..., None], axis=-1)[..., 0]
        correct = (jnp.argmax(logits, axis=-1) == yc).astype(jnp.float32)
        return jnp.stack([(nll * mc).sum(), (correct * mc).sum()])

    stats = jax.lax.map(chunk_stats, (h, y, m)).sum(axis=0)
    denom = jnp.maximum(mask.sum(), 1.0)
    return stats[0] / denom, stats[1] / denom


def shift_right(tokens: jax.Array, bos_id: int = 0) -> jax.Array:
    """Next-token LM inputs: tokens shifted right with BOS at position 0."""
    return jnp.concatenate(
        [jnp.full_like(tokens[:, :1], bos_id), tokens[:, :-1]], axis=1
    )


def sample_row(logits: jax.Array, key: jax.Array, temperature,
               top_p, top_k) -> jax.Array:
    """Temperature + nucleus (top-p) + top-k sampling for ONE row of
    logits [V] — fully jittable, no host round-trip; all knobs may be
    traced scalars. ``top_p >= 1`` and ``top_k <= 0`` disable their
    filters. Greedy (temperature == 0) is the caller's branch.

    Sampling happens in descending-sorted space (one ``lax.top_k`` of
    the full vocab): nucleus keeps the minimal prefix whose mass
    reaches ``top_p`` (exclusive-cumsum < p — the first token always
    survives, so the filter can never empty the row), top-k keeps the
    first ``k`` positions, and the drawn sorted index maps back
    through the sort permutation — no scatter needed.
    """
    V = logits.shape[-1]
    scaled = logits / jnp.maximum(temperature, 1e-6)
    sorted_l, sort_idx = jax.lax.top_k(scaled, V)
    probs = jax.nn.softmax(sorted_l)
    cum = jnp.cumsum(probs) - probs  # exclusive prefix mass
    keep = cum < jnp.where(top_p >= 1.0, jnp.inf, top_p)
    keep &= jnp.arange(V) < jnp.where(top_k > 0, top_k, V)
    masked = jnp.where(keep, sorted_l, -jnp.inf)
    return sort_idx[jax.random.categorical(key, masked)].astype(jnp.int32)


def sample_logits(logits: jax.Array, key: jax.Array, temperature,
                  top_p=1.0, top_k=0) -> jax.Array:
    """Batch sampling [B, V] → [B] int32 with SHARED knobs (the family
    ``generate`` path). With both filters statically disabled this is
    exactly the historical ``jax.random.categorical`` draw (bit-stable
    for existing seeds); otherwise rows sample independently through
    :func:`sample_row` on split keys."""
    plain = (not isinstance(top_p, jax.Array) and float(top_p) >= 1.0
             and not isinstance(top_k, jax.Array) and int(top_k) <= 0)
    if plain:
        scaled = logits / jnp.maximum(temperature, 1e-6)
        return jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)
    keys = jax.random.split(key, logits.shape[0])
    return jax.vmap(sample_row, in_axes=(0, 0, None, None, None))(
        logits, keys, temperature, top_p, top_k)
