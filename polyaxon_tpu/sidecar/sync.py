"""Incremental rsync-like tree sync (mtime+size) with a watch loop.

The destination is either a local/mounted directory (the TPU-VM
default) or any artifact-store URL (``gs://``, ``s3://``, ...) — the
upstream sidecar ships to fsspec stores the same way (SURVEY.md §3.3).
"""

from __future__ import annotations

import os
import shutil
import threading
import time
from typing import Optional
from urllib.parse import urlparse


def _should_copy(src: str, dest: str) -> bool:
    if not os.path.exists(dest):
        return True
    s, d = os.stat(src), os.stat(dest)
    return s.st_mtime > d.st_mtime or s.st_size != d.st_size


def sync_tree(src_root: str, dest_root: str) -> int:
    """Copy changed files; returns number synced. Append-heavy files
    (jsonl/logs) are whole-file copied — sizes here are small relative to
    checkpoints, which orbax already writes store-side."""
    synced = 0
    for dirpath, _, filenames in os.walk(src_root):
        rel = os.path.relpath(dirpath, src_root)
        dest_dir = os.path.join(dest_root, rel) if rel != "." else dest_root
        for name in filenames:
            if name.endswith((".tmp", ".lock")):
                continue
            src = os.path.join(dirpath, name)
            dest = os.path.join(dest_dir, name)
            if _should_copy(src, dest):
                os.makedirs(dest_dir, exist_ok=True)
                try:
                    shutil.copy2(src, dest)
                    synced += 1
                except OSError:
                    continue  # file vanished/rotating mid-walk
    return synced


class SidecarSync:
    def __init__(self, run_dir: str, store_dir: str, interval_seconds: float = 5.0):
        self.run_dir = run_dir
        self.store_dir = store_dir
        self.interval = interval_seconds
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # A URL destination ships through the store layer with the
        # incremental mtime state Store.sync_dir keeps; a plain path
        # (or file://) stays on the local fast path below.
        parsed = urlparse(store_dir)
        if parsed.scheme and parsed.scheme != "file":
            from polyaxon_tpu.fs import get_store

            self._store = get_store(store_dir)
            self._store_state: dict[str, float] = {}
        else:
            self._store = None
            if parsed.scheme == "file":
                self.store_dir = parsed.path

    def sync_once(self) -> int:
        if self._store is not None:
            return self._store.sync_dir(self.run_dir,
                                        state=self._store_state)
        return sync_tree(self.run_dir, self.store_dir)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.sync_once()
            except Exception:
                pass

    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop, name="plx-sidecar", daemon=True)
            self._thread.start()

    def stop(self, final_sync: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if final_sync:
            self.sync_once()
