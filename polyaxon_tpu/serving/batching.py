"""Continuous batching for the serving runtime.

The static engine (server.py ``_Engine``) runs each request's whole
generation as one compiled program: a long request blocks the batch and
short ones pad to the longest. Continuous batching instead keeps a
fixed pool of KV-cache **slots** and advances all live requests one
token per loop iteration (the family's ``decode_step_ragged`` — each
slot at its own depth), admitting queued requests into freed slots
between iterations. Throughput scales with slot occupancy instead of
request alignment — the vLLM-style scheduling model, TPU-first:

- one jitted ragged decode step for the whole pool (static shapes:
  ``[slots]`` tokens/positions), so iteration never recompiles;
- admission = a jitted prefill per exact prompt length (LRU-bounded,
  same rule as the static engine) + an in-place cache-row insert;
- per-row sampling fused into the step program (greedy and
  temperature>0 rows coexist in one batch; per-row PRNG keys), so only
  ``[slots]`` token ids cross the host boundary per iteration.

Families exposing the continuous-batching surface are supported: llama
dense decoders, moe expert-FFN decoders, and t5 seq2seq (whose pool
cache carries per-slot encoder state — padded cross-attention K/V plus
a length mask — so requests with different encoder lengths share one
ragged decoder step).
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from dataclasses import dataclass, field
import functools
from functools import lru_cache
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from polyaxon_tpu.obs import metrics as obs_metrics
from polyaxon_tpu.obs import reqtrace
from polyaxon_tpu.serving.speculative import LaneView, SpeculationPolicy

logger = logging.getLogger(__name__)


class QueueFull(RuntimeError):
    """The continuous engine's pending queue is at its cap: the caller
    should shed load (HTTP 503 + Retry-After) instead of queueing
    unbounded work it will serve long after the client gave up."""

    def __init__(self, message: str, retry_after: int = 1):
        super().__init__(message)
        self.retry_after = max(int(retry_after), 1)


def bucket_suffix_len(n: int, floor: int = 8) -> int:
    """Padded length for a radix-suffix prefill of ``n`` novel tokens:
    the next power of two, floored at ``floor``. Suffix lengths are
    arbitrary (prompt length minus whatever prefix the radix cache
    matched), so compiling per exact length would accumulate one
    executable per distinct length; bucketing bounds the compile count
    to O(log max_suffix) per prefix-page count, and the padded tail is
    masked to the scratch page at insert (paged_insert_suffix)."""
    if n < 1:
        raise ValueError(f"suffix length must be >= 1, got {n}")
    return max(floor, 1 << (n - 1).bit_length())


@dataclass(frozen=True)
class RequestClass:
    """One named serving class — the per-request mirror of the PR 2
    queue/priority-class catalog (scheduling.catalog.V1Queue): a
    numeric priority orders admission across classes, a TTFT target
    anchors deadline urgency inside the rank tuple, and the
    preemption flags say who may evict whom under pressure.

    ``skip_cap`` is the PR 11 bounded-starvation barrier generalized
    per class: a request overtaken that many times becomes a barrier
    for younger requests OF ITS OWN CLASS (aging is within-class;
    across classes priority is strict — a saturated high class starves
    a lower one by design, and the per-class pending cap is the
    shed-load bound on that starvation)."""

    name: str
    priority: int          # higher admits first (catalog ordering)
    ttft_target: float     # seconds; past it the request is "overdue"
    preemptible: bool      # may be evicted from a live slot
    preempts: bool         # may trigger eviction when blocked
    skip_cap: int          # within-class starvation barrier


# Mirrors scheduling.catalog.PRIORITY_CLASSES (low=0, default=1,
# high=2): interactive rides the `high` rung with a tight TTFT target
# and is never evicted; `batch` is the default middle; `best-effort`
# is the only preemptible class — its slots and KV pages are the
# reserve an urgent interactive prefill draws down.
REQUEST_CLASSES: dict[str, RequestClass] = {
    "interactive": RequestClass("interactive", priority=2,
                                ttft_target=0.5, preemptible=False,
                                preempts=True, skip_cap=4),
    "batch": RequestClass("batch", priority=1, ttft_target=2.5,
                          preemptible=False, preempts=False,
                          skip_cap=16),
    "best-effort": RequestClass("best-effort", priority=0,
                                ttft_target=30.0, preemptible=True,
                                preempts=False, skip_cap=64),
}
DEFAULT_REQUEST_CLASS = "batch"


def resolve_request_class(name: str) -> RequestClass:
    """Catalog lookup; unknown class names fold to the default class
    (the HTTP layer already bounds the raw string) so an arbitrary
    label can never mint priority or preemption rights."""
    return REQUEST_CLASSES.get(name, REQUEST_CLASSES[DEFAULT_REQUEST_CLASS])


def validate_sampling(top_p: float, top_k: int) -> None:
    """Shared request-sampling validation (HTTP handler AND direct
    engine callers): out-of-range knobs must raise, not silently
    degenerate (top_p=0 would collapse to argmax via the all--inf
    categorical, top_k<0 would silently mean 'disabled')."""
    if not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if top_k < 0:
        raise ValueError(f"top_k must be >= 0, got {top_k}")


@dataclass
class _Request:
    tokens: list[int]
    max_new: int
    temperature: float
    seed: int
    top_p: float = 1.0
    top_k: int = 0
    # Early stop: generation retires at the first of these token ids
    # (the stop token IS included in the output — callers that want it
    # dropped slice it off; including it keeps losslessness trivially
    # comparable across engines).
    eos: frozenset = frozenset()
    out: list[int] = field(default_factory=list)
    done: threading.Event = field(default_factory=threading.Event)
    error: Optional[str] = None
    cancelled: bool = False
    # Stamped at submit; the retire path feeds submit→done wall time
    # into the unified registry's serving-latency histogram (ISSUE 5).
    submitted_at: float = field(default_factory=time.time)
    # Per-request observability (ISSUE 10): the id doubles as the trace
    # id; `klass` labels the SLO histograms and picks the admission
    # queue (REQUEST_CLASSES; unknown labels fold to `batch`);
    # `first_token_at` anchors TTFT at emission and TPOT at retirement.
    id: str = field(default_factory=reqtrace.new_request_id)
    klass: str = "batch"
    trace: Optional[reqtrace.RequestTrace] = None
    first_token_at: Optional[float] = None
    # Cache-aware admission bookkeeping (paged + radix prefix cache):
    # `admit_skips` counts how many times a younger request was
    # admitted past this one (the starvation bound); the cached-token
    # count lands on the request trace at finish.
    admit_skips: int = 0
    prefix_cached_tokens: int = 0
    # Class-aware admission (ISSUE 19): `seq` is the global arrival
    # order (assigned under the engine lock at enqueue) — the FIFO
    # tie-breaker now that pending work lives in per-class queues;
    # `preemptions` counts evictions this request survived, so the
    # re-admission path knows to account its suffix prefill.
    seq: int = 0
    preemptions: int = 0

    def wait(self, timeout: Optional[float] = None) -> list[int]:
        if not self.done.wait(timeout):
            raise TimeoutError("generation did not finish in time")
        if self.error:
            raise RuntimeError(self.error)
        return self.out


class ContinuousBatchingEngine:
    """Slot-pool generation engine. API-compatible with ``_Engine``:
    ``generate(rows, max_new_tokens, temperature, seed)`` blocks; the
    lower-level ``submit()`` returns a waitable request for callers
    that want request-level interleaving (each HTTP thread does)."""

    def __init__(self, model: str, cfg, params, *, slots: int = 4,
                 max_len: Optional[int] = None, kv: str = "dense",
                 page_size: int = 16, kv_pages: Optional[int] = None,
                 prefix_cache: bool = True,
                 draft=None, prefill_chunk: Optional[int] = None,
                 prefill_slots: Optional[int] = None,
                 prefill_lane_budget: int = 1,
                 decode_lane_budget: int = 1,
                 spec_policy: Optional[SpeculationPolicy] = None,
                 max_pending: Optional[int] = None,
                 class_admission: bool = True,
                 class_max_pending: Optional[dict] = None,
                 preemption: bool = True,
                 request_tracing: bool = True,
                 trace_capacity: int = reqtrace.DEFAULT_RING_CAPACITY,
                 trace_dump_path: Optional[str] = None,
                 registry=None):
        from polyaxon_tpu.serving.server import _family

        family = _family(model)
        # Disaggregated prefill/decode (ISSUE 18): `prefill_slots`
        # extra block-table rows form a prefill LANE — admissions land
        # there, stream their novel suffix in chunks via the radix
        # suffix path, and HAND their committed pages to a free decode
        # slot (PagePool.handoff — a block-table row move plus at most
        # the admission-time CoW fork, never a recompute). Per-lane
        # budgets bound interference: at most `prefill_lane_budget`
        # chunk programs run per tick while decode rows are live, and
        # the decode lane gets `decode_lane_budget` steps per tick
        # (0 = deliberately starved, the bench's lane-starve inject).
        if prefill_slots is not None:
            if prefill_slots < 1:
                raise ValueError(
                    f"prefill_slots must be >= 1, got {prefill_slots}")
            if kv != "paged":
                raise ValueError(
                    "disaggregated prefill/decode requires kv='paged' "
                    "(the handoff boundary is a block-table row move)")
            if draft is not None:
                raise ValueError(
                    "prefill_slots and draft are mutually exclusive: "
                    "the draft's verify chunk needs kv='dense' while "
                    "the page handoff needs kv='paged'")
            if not (hasattr(family, "paged_prefill_suffix_kv")
                    and hasattr(family, "paged_insert_suffix")):
                raise ValueError(
                    f"`{model}` ({family.__name__}) has no paged suffix-"
                    "prefill surface; the prefill lane streams chunks "
                    "through paged_prefill_suffix_kv")
        if prefill_lane_budget < 1:
            raise ValueError(
                f"prefill_lane_budget must be >= 1, got "
                f"{prefill_lane_budget}")
        if decode_lane_budget < 0:
            raise ValueError(
                f"decode_lane_budget must be >= 0, got "
                f"{decode_lane_budget}")
        # Chunked prefill (vLLM-style): a long prompt's admission no
        # longer blocks the pool for one monolithic prefill — the
        # prompt streams into a standalone row cache `prefill_chunk`
        # tokens per loop iteration (one fixed-shape decode_chunk
        # program, reused for EVERY prompt length — no per-length
        # compile cache), interleaved with the live slots' decode
        # steps; the finished row then inserts like any admission.
        # Rollback-free by the same slot==position argument as
        # speculative verify: the padded tail chunk's junk writes sit
        # at positions decode rewrites before anything attends them.
        if prefill_chunk is not None:
            if prefill_chunk < 1:
                raise ValueError(
                    f"prefill_chunk must be >= 1, got {prefill_chunk}")
            if kv != "dense" and prefill_slots is None:
                raise ValueError(
                    "chunked prefill requires kv='dense' (the chunk "
                    "writer needs the slot==position row cache) — or "
                    "prefill_slots, where it sizes the lane's per-tick "
                    "suffix chunk instead")
            if kv == "dense":
                if not hasattr(family, "decode_chunk"):
                    raise ValueError(
                        f"`{model}` ({family.__name__}) has no "
                        "decode_chunk surface; chunked prefill supports "
                        "llama/moe-family decoders")
                if getattr(cfg, "sliding_window", None) is not None:
                    raise ValueError(
                        "chunked prefill requires a full-length cache "
                        "(no sliding_window): the padded tail chunk's "
                        "junk writes rely on slot == position")
        # Speculative decoding over the slot pool: ``draft`` =
        # (draft_model, draft_cfg, draft_params, k). Each loop
        # iteration becomes one draft→verify round — every live slot
        # proposes k tokens with its own draft-cache row and accepts
        # 1..k+1 of them raggedly (per-row acceptance counts, per-row
        # budget caps). Greedy-only: acceptance compares the target's
        # own argmax, so the pool serves temperature-0 requests while
        # a draft is configured (submit refuses sampled requests
        # loudly rather than silently starving speculation).
        if draft is not None:
            if kv != "dense":
                raise ValueError(
                    "speculative continuous batching requires kv='dense' "
                    "(the verify chunk needs the slot==position cache)")
            if getattr(cfg, "sliding_window", None) is not None:
                raise ValueError(
                    "speculative decoding requires a full-length cache "
                    "(no sliding_window) — rollback-free acceptance "
                    "depends on slot == position")
            if not hasattr(family, "decode_chunk"):
                raise ValueError(
                    f"`{model}` ({family.__name__}) has no decode_chunk "
                    "verify surface; speculative continuous batching "
                    "supports llama/moe-family decoders")
        # Family-generic: any family exposing the continuous-batching
        # surface (llama dense decoders, moe expert-FFN decoders, t5
        # seq2seq with per-slot encoder state) batches continuously.
        required = ("decode_step_ragged", "cb_init_cache", "cb_prefill",
                    "cb_admission", "cb_validate", "insert_cache_row")
        if kv == "paged":
            required += ("decode_step_paged", "paged_init_cache",
                         "paged_prefill_kv", "paged_insert_prefill")
        elif kv != "dense":
            raise ValueError(f"unknown kv mode `{kv}` "
                             "(expected 'dense' or 'paged')")
        missing = [name for name in required if not hasattr(family, name)]
        if missing:
            alt = "kv='dense'" if kv == "paged" else "the static engine"
            raise ValueError(
                f"continuous batching needs the ragged-decode surface; "
                f"`{model}` ({family.__name__}) lacks {missing} — use {alt}")
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        self.model = model
        self.cfg = cfg
        self.params = params
        self.slots = slots
        self.max_len = max_len or cfg.max_seq_len
        self._family_mod = family
        # Fleet-scoped telemetry (ISSUE 20): `registry` may be a
        # `REGISTRY.scoped(component=...)` view — every series this
        # engine records then carries the replica's identity, and its
        # trace spans name the replica instead of the generic
        # "serving". Standalone engines keep the unscoped global.
        self._obs = registry if registry is not None else obs_metrics.REGISTRY
        self._obs_component = (getattr(self._obs, "component", "")
                               or "serving")
        self.kv = kv
        self._pool = None
        # Prefill-lane rows sit AFTER the decode slots in the block
        # table (rows slots..slots+prefill_slots-1): the decode step's
        # [slots]-shaped tables slice never sees them, and a handoff is
        # a row move inside the same pool.
        self.prefill_slots = int(prefill_slots or 0)
        n_rows = slots + self.prefill_slots
        if kv == "paged":
            from polyaxon_tpu.serving.paged import PagePool

            if kv_pages is None:
                # Sized to every row's dense reservation, lane rows
                # included — staged prefills hold pages concurrently
                # with the decode pool, by design.
                self._pool = PagePool.dense_equivalent(
                    n_rows, self.max_len, page_size,
                    prefix_cache=prefix_cache)
            else:
                # kv_pages counts USABLE pages (what /v1/stats reports
                # as kv_pages_total); the scratch page is internal —
                # validate in the user's units before adding it.
                if kv_pages < 1:
                    raise ValueError(
                        f"kv_pages must be >= 1, got {kv_pages}")
                self._pool = PagePool(n_rows, self.max_len, page_size,
                                      kv_pages + 1,
                                      prefix_cache=prefix_cache)
            self._cache = family.paged_init_cache(
                cfg, self._pool.n_pages, page_size)
        else:
            self._cache = family.cb_init_cache(cfg, slots, self.max_len)
        self.draft = draft
        self._spec_rounds = 0
        self._spec_tokens = 0
        if draft is not None:
            draft_model, draft_cfg, draft_params, spec_k = draft
            if getattr(draft_cfg, "sliding_window", None) is not None:
                raise ValueError(
                    "draft model must not use sliding_window (its cache "
                    "needs slot == position too)")
            if spec_k < 1:
                raise ValueError(f"spec_k must be >= 1, got {spec_k}")
            self._draft_family = _family(draft_model)
            if getattr(self._draft_family, "SEQ2SEQ", False):
                raise ValueError(
                    f"draft `{draft_model}` is seq2seq — a drafting "
                    "decoder must continue the same token stream the "
                    "target decodes (its proposals would be garbage "
                    "and acceptance would silently collapse)")
            draft_required = ("decode_step_ragged", "cb_init_cache",
                              "cb_prefill", "insert_cache_row")
            draft_missing = [name for name in draft_required
                             if not hasattr(self._draft_family, name)]
            if draft_missing:
                raise ValueError(
                    f"draft `{draft_model}` "
                    f"({self._draft_family.__name__}) lacks the ragged "
                    f"decode surface: {draft_missing}")
            self._draft_cfg = draft_cfg
            self._draft_params = draft_params
            self.spec_k = int(spec_k)
            self._draft_cache = self._draft_family.cb_init_cache(
                draft_cfg, slots, self.max_len)
        self.prefill_chunk = prefill_chunk
        # Lane scheduler state (paged disaggregation). `_lane` maps a
        # prefill ROW → [request, prefill tokens, progress, pos0,
        # tok0]; dict insertion order is the staging FIFO. A staged
        # reservation whose progress reached its prompt waits in place
        # for a free decode slot (natural backpressure — no page churn).
        self.prefill_lane_budget = int(prefill_lane_budget)
        self.decode_lane_budget = int(decode_lane_budget)
        self._lane: dict[int, list] = {}
        self._lane_chunk = (int(prefill_chunk) if prefill_chunk
                            else max(2 * page_size, 32))
        self._handoffs = 0
        self._handoff_pages = 0
        # Decode-lane cadence: wall time between CONSECUTIVE decode
        # steps (reset to None whenever the decode lane goes idle, so
        # quiet gaps never pollute the interference histogram).
        self._last_decode_at: Optional[float] = None
        # Per-slot chunked-prefill state: [request, prompt tokens to
        # write, progress, target row cache, draft row cache or None,
        # pos0, tok0]. A slot in this dict is RESERVED but not yet
        # live; dict insertion order IS the admission FIFO. Each
        # reservation holds a standalone full-length row cache (plus
        # the draft's when speculating) on top of the pool cache —
        # peak KV memory grows accordingly (documented at the flag).
        self._prefilling: dict[int, list] = {}
        self._pos = np.full(slots, -1, np.int32)  # -1 = free slot
        self._cur = np.zeros(slots, np.int32)
        self._temps = np.zeros(slots, np.float32)
        self._top_ps = np.ones(slots, np.float32)
        self._top_ks = np.zeros(slots, np.int32)
        self._keys = [jax.random.key(0)] * slots
        self._slot_req: list[Optional[_Request]] = [None] * slots

        # Graceful degradation: a bounded pending queue. None =
        # unbounded (library callers managing their own admission);
        # the HTTP layer maps QueueFull to 503 + Retry-After.
        if max_pending is not None and max_pending < 1:
            raise ValueError(f"max_pending must be >= 1, got {max_pending}")
        self.max_pending = max_pending
        # Class-aware admission (ISSUE 19): pending work lives in
        # PER-CLASS queues (FIFO within a class, arrival `seq` as the
        # cross-class tie-breaker) instead of one deque. With
        # `class_admission` off — the A/B baseline — every request
        # lands in one queue regardless of label and the pre-19
        # FIFO-with-cache-affinity scan runs unchanged.
        self.class_admission = bool(class_admission)
        self.preemption = bool(preemption)
        self._class_caps: dict[str, int] = {}
        for name, cap in (class_max_pending or {}).items():
            if cap is not None:
                cap = int(cap)
                if cap < 1:
                    raise ValueError(
                        f"class_max_pending[{name!r}] must be >= 1, "
                        f"got {cap}")
                self._class_caps[str(name)] = cap
        # Pre-created for every reachable key (unknown labels fold to
        # the default class) so the dict never grows after the ctor —
        # unlocked readers (health/stats/gauges) iterate it safely.
        self._queues: dict[str, collections.deque] = {
            name: collections.deque()
            for name in (REQUEST_CLASSES if self.class_admission
                         else (DEFAULT_REQUEST_CLASS,))}
        self._seq = 0
        # Preemption accounting (stats + the bench gate): evictions by
        # victim class, and the novel tokens re-admissions prefilled
        # (the real recompute cost of eviction — the committed prefix
        # rode the radix cache).
        self._preemptions: dict[str, int] = {}
        self._readmit_suffix_tokens = 0
        # Per-request observability (ISSUE 10): span trees in a bounded
        # ring behind GET /requests/{id}/timeline, shed-load accounting
        # for /v1/stats. Tracing defaults on — the parity check in
        # tests/test_serving.py holds its overhead within 5% — and
        # `request_tracing=False` turns span recording off while the
        # SLO histograms (TTFT/TPOT/queue-wait) keep flowing.
        self.request_tracing = bool(request_tracing)
        self._ring = reqtrace.TimelineRing(trace_capacity)
        # ISSUE 13: where to persist the ring at shutdown (None = the
        # ring dies with the process, the pre-13 behavior).
        self.trace_dump_path = trace_dump_path
        self._rejected: dict[str, int] = {}
        self._cv = threading.Condition()
        self._stopped = False
        self._served = 0
        self._tokens_out = 0
        self._step_failures = 0  # lifetime counter (stats)
        self._consec_step_failures = 0
        # Occupancy accounting: continuous batching wins exactly when
        # slots stay busy — avg_occupancy is THE number that says so.
        self._steps_total = 0
        self._live_slot_steps = 0
        self._queue_depth_peak = 0
        # A device that throws persistently (e.g. OOM) would otherwise
        # burn one rebuilt-cache step per queued request; after this
        # many consecutive failures the engine fails fast instead.
        self.max_step_failures = 3

        def step(params, cache, tokens, pos, keys, temps, top_ps, top_ks,
                 tables, *, filtered: bool):
            from polyaxon_tpu.models.common import sample_row

            # Quantized trees pass through whole — weights unwrap at
            # consumption inside the model (models/llama.py _w), so
            # int8 stays the HBM format in the per-step program.
            if tables is None:
                logits, cache = family.decode_step_ragged(
                    cfg, params, cache, tokens, pos)
            else:
                logits, cache = family.decode_step_paged(
                    cfg, params, cache, tokens, pos, tables)
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            if filtered:
                # Per-row temperature + top-p/top-k fused into the
                # step — greedy and filtered rows coexist in one
                # batch; only [slots] token ids cross the host.
                sampled = jax.vmap(sample_row)(logits, keys, temps,
                                               top_ps, top_ks)
            else:
                # The historical draw, bit-stable for existing seeds —
                # and no full-vocab sort in the hot loop when nothing
                # live uses the filters (the common case).
                scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
                sampled = jax.vmap(jax.random.categorical)(
                    keys, scaled).astype(jnp.int32)
            nxt = jnp.where(temps > 0, sampled, greedy)
            return nxt, cache

        # Two executables; the loop picks per iteration by whether any
        # live row actually uses top-p/top-k (same idea as the static
        # engine's `filtered` compile key).
        self._step_plain = jax.jit(functools.partial(step, filtered=False),
                                   donate_argnums=(1,))
        self._step_filtered = jax.jit(
            functools.partial(step, filtered=True), donate_argnums=(1,))

        # One lru-bounded executable per prompt length for BOTH kv
        # modes; paged folds the page scatter into the same program
        # (a separate jit of the [L, P, ...] insert would accumulate
        # an unbounded compile cache over prompt-length diversity).
        @lru_cache(maxsize=16)
        def compiled_prefill(plen: int):
            if self.kv == "paged":
                ps = page_size

                def run(params, prompt, cache, page_ids):
                    k_all, v_all = family.paged_prefill_kv(
                        cfg, params, prompt)
                    return family.paged_insert_prefill(
                        cache, k_all, v_all, page_ids, ps)

                return jax.jit(run, donate_argnums=(2,))

            def run(params, prompt):
                return family.cb_prefill(cfg, params,
                                         prompt, self.max_len)

            return jax.jit(run)

        self._compiled_prefill = compiled_prefill
        self._insert = (None if kv == "paged" else
                        jax.jit(family.insert_cache_row,
                                donate_argnums=(0,)))

        # Radix prefix reuse (paged only): one jitted page duplicator
        # for copy-on-write forks (src/dst are traced scalars — every
        # fork shares ONE executable), and an lru-bounded suffix
        # prefill per (BUCKETED suffix length, prefix-page count) that
        # computes KV only for the tokens the radix cache did NOT
        # match. The cached-token count `m` and the real (pre-padding)
        # suffix length are traced, so requests with different match
        # depths but equal bucketed shapes share the program — at most
        # O(log max_suffix) compiles per prefix-page count instead of
        # one per distinct suffix length (bucket_suffix_len).
        self._copy_page = None
        self._suffix_prefill = None
        if kv == "paged":
            def copy_page(cache, src, dst):
                return {name: arr.at[:, dst].set(arr[:, src])
                        for name, arr in cache.items()}

            self._copy_page = jax.jit(copy_page, donate_argnums=(0,))
            if hasattr(family, "paged_prefill_suffix_kv"):
                ps = page_size

                # 32, not 16: the prefill LANE reuses this cache with
                # bucketed (chunk length, prefix-page) pairs on top of
                # the classic suffix shapes.
                @lru_cache(maxsize=32)
                def compiled_suffix_prefill(slen: int, n_pref: int):
                    def run(params, suffix, cache, page_ids, m, real_len):
                        pref = jnp.maximum(page_ids[:n_pref], 0)
                        kp = cache["k"][:, pref]
                        kp = kp.reshape(kp.shape[0], n_pref * ps,
                                        *kp.shape[3:])
                        vp = cache["v"][:, pref]
                        vp = vp.reshape(vp.shape[0], n_pref * ps,
                                        *vp.shape[3:])
                        k_suf, v_suf = family.paged_prefill_suffix_kv(
                            cfg, params, suffix, kp, vp, m)
                        # Padded tail positions (>= real_len) carry
                        # garbage KV; the insert routes them to the
                        # scratch page. Real positions are unaffected:
                        # causality already masks padded KEYS from
                        # real queries (padding sits after every real
                        # position), so no extra attention mask.
                        return family.paged_insert_suffix(
                            cache, k_suf, v_suf, page_ids, m, ps,
                            real_len)

                    return jax.jit(run, donate_argnums=(2,))

                self._suffix_prefill = compiled_suffix_prefill
        # Cache-aware admission: scan a bounded window of the pending
        # queue and admit the admissible request with the hottest
        # matched prefix; a request overtaken `_admit_skip_cap` times
        # becomes a barrier (bounded starvation, same shape as the
        # scheduler's aging rule). Rolling per-admission hit window
        # feeds the polyaxon_serving_prefix_hit_rate gauge — unset
        # until it holds enough samples, so cold starts cannot page.
        self._admit_window = 32
        self._admit_skip_cap = 16
        self._prefill_tokens_total = 0
        self._prefill_tokens_skipped = 0
        self._hit_window: collections.deque = collections.deque(maxlen=64)
        self._hit_window_min = 8

        if draft is not None:
            draft_family, draft_cfg = self._draft_family, self._draft_cfg

            @lru_cache(maxsize=16)
            def compiled_draft_prefill(plen: int):
                def run(draft_params, prompt):
                    return draft_family.cb_prefill(
                        draft_cfg, draft_params, prompt, self.max_len)

                return jax.jit(run)

            self._compiled_draft_prefill = compiled_draft_prefill
            self._draft_insert = jax.jit(draft_family.insert_cache_row,
                                         donate_argnums=(0,))

            # One executable PER DRAFT LENGTH (the scan length is
            # static): the speculation policy retunes k per tick, and
            # k only ever takes values in 1..spec_k, so the compile
            # count is bounded by spec_k. Greedy speculation is
            # lossless for ANY k — the target verifies — so varying k
            # across rounds (including k=0 plain-step rounds, which
            # leave draft-cache holes that degrade ACCEPTANCE, never
            # output) changes throughput only.
            @lru_cache(maxsize=16)
            def spec_round_for(k_spec: int):
                def spec_round(params, draft_params, cache_t, cache_d,
                               cur, pos, budget_left):
                    """One draft→verify round for the whole pool.
                    Returns (candidates [B, k+1], emit [B], next cur,
                    caches). Idle rows (pos < 0) run with clamped
                    positions and emit 0 — their cache rows are garbage
                    the next admission's insert replaces wholesale."""
                    B = cur.shape[0]
                    rows = jnp.arange(B)
                    live = pos >= 0
                    p0 = jnp.maximum(pos, 0)

                    def draft_step(carry, _):
                        cache_d, tok, p = carry
                        lg, cache_d = draft_family.decode_step_ragged(
                            draft_cfg, draft_params, cache_d, tok, p)
                        nxt = jnp.argmax(lg, axis=-1).astype(jnp.int32)
                        return (cache_d, nxt, p + 1), nxt

                    # k+1 draft steps for k proposals: the extra step
                    # writes the LAST proposal's draft KV (same
                    # hole-free invariant as speculative.py).
                    (cache_d, _, _), d = jax.lax.scan(
                        draft_step, (cache_d, cur, p0), None,
                        length=k_spec + 1)
                    d = d.T[:, :k_spec]  # [B, k]

                    chunk = jnp.concatenate([cur[:, None], d], axis=1)
                    logits, cache_t = family.decode_chunk(
                        cfg, params, cache_t, chunk, p0)
                    t = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                    match = (d == t[:, :k_spec]).astype(jnp.int32)
                    accepted = jnp.cumprod(match, axis=1).sum(axis=1)
                    emit = jnp.minimum(accepted + 1, budget_left)
                    emit = jnp.where(live, emit, 0)
                    cur_nxt = jnp.where(
                        emit > 0, t[rows, jnp.maximum(emit - 1, 0)], cur)
                    return t, emit, cur_nxt, cache_t, cache_d

                return jax.jit(spec_round, donate_argnums=(2, 3))

            self._spec_round_for = spec_round_for
        # Speculation as a POLICY OUTPUT (ISSUE 18), not a static
        # flag: each decode-lane tick asks the policy for the draft
        # length given live pressure (prefill backlog, decode
        # headroom, oldest queue wait). k=0 falls back to a plain
        # decode step. Injectable for tests; draft-less engines
        # carry no policy.
        self._spec_policy = None
        self._spec_proposed = 0
        self._spec_accepted = 0
        if draft is not None:
            self._spec_policy = (spec_policy if spec_policy is not None
                                 else SpeculationPolicy(self.spec_k))

        if prefill_chunk is not None:
            if draft is not None and not hasattr(self._draft_family,
                                                 "decode_chunk"):
                raise ValueError(
                    "chunked prefill with a draft needs the draft "
                    "family's decode_chunk too")

            def chunk_write(params, row_cache, tokens, pos0):
                """Write one [1, c] chunk of prompt KV into a
                standalone row cache; logits discarded. The padded
                tail's junk writes land at positions decode rewrites
                before anything attends them (slot == position)."""
                _, row_cache = family.decode_chunk(
                    cfg, params, row_cache, tokens, pos0)
                return row_cache

            self._chunk_write = jax.jit(chunk_write, donate_argnums=(1,))
            if draft is not None:
                def draft_chunk_write(draft_params, row_cache, tokens,
                                      pos0):
                    _, row_cache = self._draft_family.decode_chunk(
                        self._draft_cfg, draft_params, row_cache,
                        tokens, pos0)
                    return row_cache

                self._draft_chunk_write = jax.jit(
                    draft_chunk_write, donate_argnums=(1,))

        self._thread = threading.Thread(
            target=self._loop, name="plx-serving-batcher", daemon=True)
        self._thread.start()

    # ------------------------------------------------------------- public
    def _validate(self, tokens: list[int], max_new_tokens: int) -> None:
        if not tokens:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        # Budget semantics are family-specific: decoder-only models
        # share one cache between prompt and generation; seq2seq bounds
        # encoder prompt and decode budget separately.
        self._family_mod.cb_validate(self.cfg, len(tokens), max_new_tokens,
                                     self.max_len)
        if self.draft is not None:
            # Verify rounds write KV up to k positions past the budget
            # (a nearly-done row still runs a full draft window): the
            # full-length cache must hold that headroom or the ring
            # wrap would scribble over the prompt start.
            need = len(tokens) + max_new_tokens + self.spec_k + 1
            if need > self.max_len:
                raise ValueError(
                    f"prompt {len(tokens)} + max_new {max_new_tokens} + "
                    f"draft window {self.spec_k}+1 exceeds the cache "
                    f"length {self.max_len} (speculative rounds need "
                    "the headroom)")
        if self._pool is not None:
            # A request that cannot fit the pool even when it is the
            # only tenant would wait at the FIFO head forever (and
            # block everyone behind it) — reject it up front. Written
            # positions span 0..len+max_new-2.
            need = self._pool.pages_for(len(tokens) + max_new_tokens - 1)
            capacity = self._pool.n_pages - 1
            if need > capacity:
                raise ValueError(
                    f"request needs {need} KV pages (prompt {len(tokens)} "
                    f"+ {max_new_tokens} new) but the pool holds "
                    f"{capacity}; raise --kv-pages or shorten the request")

    def _reject(self, reason: str) -> None:
        """Shed-load accounting: QueueFull 503s and post-stop submits
        must not vanish — the counter is THE load-shedding signal on
        /metrics and the dashboard (ISSUE 10 satellite)."""
        self._rejected[reason] = self._rejected.get(reason, 0) + 1
        obs_metrics.serving_rejected_total(self._obs).inc(reason=reason)

    def submit(self, tokens: list[int], max_new_tokens: int,
               temperature: float = 0.0, seed: int = 0,
               top_p: float = 1.0, top_k: int = 0,
               eos_tokens=None, klass: str = "batch",
               request_id: Optional[str] = None,
               trace_parent: Optional[str] = None,
               route_record: Optional[dict] = None) -> _Request:
        """`request_id`/`trace_parent`/`route_record` carry a
        propagated trace context (ISSUE 20): the fleet front door
        pre-generates the id, opens a `route` span, and the engine's
        `request` root nests under it — one trace id, one cross-
        component timeline."""
        self._validate(tokens, max_new_tokens)
        validate_sampling(top_p, top_k)
        eos = frozenset(int(t) for t in (eos_tokens or ()))
        if self.draft is not None and temperature > 0:
            raise ValueError(
                "this engine speculates with a draft model, which is "
                "greedy-only (acceptance compares the target's own "
                "argmax); send temperature=0 or serve without "
                "--draft-model for sampling")
        req = _Request(list(tokens), max_new_tokens, float(temperature),
                       int(seed), float(top_p), int(top_k), eos,
                       klass=str(klass) or "batch")
        if request_id:
            req.id = str(request_id)
        if self.request_tracing:
            # Built BEFORE the lock (span allocation off the critical
            # section); ringed only AFTER a successful enqueue so
            # rejected requests never occupy ring capacity.
            req.trace = reqtrace.RequestTrace(
                req.id, req.klass,
                component=self._obs_component,
                parent_id=trace_parent,
                extra_records=[route_record] if route_record else None,
                prompt_len=len(req.tokens),
                max_new=int(max_new_tokens))
            req.trace.start_phase("queue_wait")
        with self._cv:
            if self._stopped:
                self._reject("shutdown")
                raise RuntimeError("engine stopped")
            depth = self._queue_depth()
            if self.max_pending is not None and depth >= self.max_pending:
                self._reject("queue_full")
                # Retry-After scales with how much decode work sits
                # ahead of the caller: ~one hint-second per queued
                # request per slot, floored at 1.
                raise QueueFull(
                    f"pending queue is full ({depth}/"
                    f"{self.max_pending}); retry later",
                    retry_after=max(1, depth // max(self.slots, 1)))
            key = self._queue_key(req.klass)
            q = self._queues[key]
            cap = (self._class_caps.get(key)
                   if self.class_admission else None)
            if cap is not None and len(q) >= cap:
                self._reject("class_queue_full")
                raise QueueFull(
                    f"`{key}` pending queue is full ({len(q)}/{cap}); "
                    f"retry later",
                    retry_after=max(1, len(q) // max(self.slots, 1)))
            req.seq = self._seq
            self._seq += 1
            q.append(req)
            self._publish_queue_depth()
            self._cv.notify()
        if req.trace is not None:
            self._ring.add(req.trace)
        return req

    def cancel(self, req: _Request) -> None:
        """Drop a request: dequeued if still waiting, retired at the
        next loop iteration if live. Waiters see error='cancelled'."""
        req.cancelled = True
        with self._cv:
            try:
                self._queue_for(req).remove(req)
                if not req.done.is_set():
                    req.error = "cancelled"
                    self._finish_trace(req)
                    req.done.set()
            except ValueError:
                pass  # live in a slot (or done): the loop retires it

    def submit_all(self, token_rows: list[list[int]], max_new_tokens: int,
                   temperature: float = 0.0, seed: int = 0,
                   top_p: float = 1.0, top_k: int = 0,
                   eos_tokens=None, klass: str = "batch") -> list[_Request]:
        """Submit a batch atomically-ish: validate every row before
        submitting ANY (same no-wasted-work contract as the static
        engine — a bad row must not leave its siblings generating
        discarded output), and if a mid-batch submit is shed
        (QueueFull/stop) cancel the rows already queued before
        re-raising — the caller sees all-or-nothing."""
        for row in token_rows:
            self._validate(row, max_new_tokens)
        reqs: list[_Request] = []
        try:
            for i, row in enumerate(token_rows):
                reqs.append(self.submit(
                    row, max_new_tokens, temperature, seed + i,
                    top_p, top_k, eos_tokens=eos_tokens, klass=klass))
        except Exception:
            for r in reqs:
                self.cancel(r)
            raise
        return reqs

    def generate(self, token_rows: list[list[int]], max_new_tokens: int,
                 temperature: float = 0.0, seed: int = 0,
                 top_p: float = 1.0, top_k: int = 0,
                 timeout: Optional[float] = None,
                 eos_tokens=None, klass: str = "batch") -> list[list[int]]:
        if not token_rows:
            return []
        reqs = self.submit_all(token_rows, max_new_tokens, temperature,
                               seed, top_p, top_k, eos_tokens=eos_tokens,
                               klass=klass)
        try:
            return [r.wait(timeout=timeout) for r in reqs]
        except TimeoutError:
            for r in reqs:  # don't keep burning slots on abandoned work
                if not r.done.is_set():
                    self.cancel(r)
            raise

    def _finalize_stop(self) -> None:
        """After the loop thread has really exited, unblock every waiter
        it will never serve. Runs post-join, so it cannot race the
        loop's own done.set() calls."""
        self._thread.join()
        with self._cv:
            pending = [state[0] for state in self._prefilling.values()]
            pending += [state[0] for state in self._lane.values()]
            for req in self._pending_requests() + self._slot_req + pending:
                if req is not None and not req.done.is_set():
                    req.error = "engine stopped"
                    self._finish_trace(req)
                    req.done.set()
        if self._hit_window:
            # This engine fed the shared prefix-hit-rate gauge; its
            # rolling window dies with it. Unset rather than leave the
            # last value parked: instant threshold rules read the live
            # registry, so a stopped engine's stale low watermark would
            # hold serving-prefix-hit-collapse in a breach that no
            # amount of clock fast-forward can ever resolve. A live
            # engine re-sets the gauge on its next admission.
            obs_metrics.serving_prefix_hit_rate(self._obs).unset()
        self._dump_ring()

    def _dump_ring(self) -> None:
        """Persist the request-timeline ring at shutdown (ISSUE 13):
        the serving mirror of the flight recorder's postmortem, so
        request evidence survives process exit and sim.replay can turn
        it into an arrival trace. Fail-open — a dump failure must not
        turn a clean stop into a crash; both outcomes are counted."""
        if not self.trace_dump_path or not self.request_tracing:
            return
        # The dump path must work on a skeleton engine (no __init__ —
        # postmortem tooling builds one around a recovered ring), so the
        # scoped view is optional here.
        obs = getattr(self, "_obs", None) or obs_metrics.REGISTRY
        try:
            path = reqtrace.dump_ring(self._ring, self.trace_dump_path)
            obs_metrics.serving_trace_dumps_total(obs).inc(outcome="ok")
            logger.info("request-timeline ring dumped to %s", path)
        except Exception:
            obs_metrics.serving_trace_dumps_total(obs).inc(outcome="error")
            logger.warning("request-timeline ring dump to %s failed",
                           self.trace_dump_path, exc_info=True)

    def stop(self) -> None:
        with self._cv:
            self._stopped = True
            self._cv.notify()
        self._thread.join(timeout=60)
        if self._thread.is_alive():
            # A long compile/step is still in flight; the loop exits at
            # its next iteration top. Hand the final bookkeeping to a
            # watcher so waiters are guaranteed to unblock eventually
            # without stop() hanging on a wedged device.
            logger.warning("batching loop still draining at stop(); "
                           "waiters will be released when it exits")
            # polycheck: ignore[invariant-daemon-drain] -- deliberately unjoined: the watcher exists so stop() does NOT hang on a wedged device; it only releases waiters
            threading.Thread(target=self._finalize_stop,
                             name="plx-batcher-finalize",
                             daemon=True).start()
            return
        self._finalize_stop()

    # -------------------------------------------------------------- loop
    def _fail_fast(self, err: str) -> None:
        """Persistent device breakage (e.g. OOM): admitting the queue
        against it would fail serially, one compiled program per
        request. Fail live slots AND drain the queue, then stop the
        engine; submit() refuses new work. Live slots must be retired
        here — the loop thread exits right after, and nothing else
        would ever set their done events (their waiters would hang)."""
        logger.error(
            "%d consecutive device-program failures; draining queue and "
            "stopping engine", self._consec_step_failures)
        for b in range(self.slots):
            if self._slot_req[b] is not None:
                self._slot_req[b].error = f"engine failed: {err}"
                self._retire(b)
        for b, state in list(self._prefilling.items()):
            req = state[0]
            del self._prefilling[b]
            if not req.done.is_set():
                req.error = f"engine failed: {err}"
                self._finish_trace(req)
                req.done.set()
        for p in list(self._lane):
            self._drop_lane_reservation(p, f"engine failed: {err}")
        with self._cv:
            self._stopped = True
            for q in self._queues.values():
                while q:
                    req = q.popleft()
                    if not req.done.is_set():
                        req.error = f"engine failed: {err}"
                        self._finish_trace(req)
                        req.done.set()

    # --------------------------------------------------- pending queues
    def _queue_key(self, klass: str) -> str:
        """Which pending queue a request class lands in. FIFO mode (the
        A/B baseline) merges everything into one queue — the pre-19
        scan semantics depend on global arrival order."""
        if not self.class_admission or klass not in REQUEST_CLASSES:
            return DEFAULT_REQUEST_CLASS
        return klass

    def _queue_for(self, req: _Request) -> collections.deque:
        return self._queues[self._queue_key(req.klass)]

    def _queue_depth(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def _queue_head(self) -> Optional[_Request]:
        """Oldest pending request across every class queue."""
        heads = [q[0] for q in self._queues.values() if q]
        return min(heads, key=lambda r: r.seq) if heads else None

    def _pending_requests(self) -> list[_Request]:
        return [r for q in self._queues.values() for r in q]

    def _publish_queue_depth(self) -> None:
        obs_metrics.serving_queue_depth(self._obs).set(self._queue_depth())
        if self.class_admission:
            gauge = obs_metrics.serving_class_pending(self._obs)
            for name, q in self._queues.items():
                gauge.set(len(q), **{"class": name})

    def _pick_next_locked(self) -> Optional[_Request]:
        """Choose the next request to admit (caller holds ``_cv``).

        FIFO mode (``class_admission=False``): the pre-19 policy,
        unchanged — dense pops strict FIFO; paged scans a bounded
        window of the one queue and picks the admissible request whose
        radix-matched prefix is hottest (most cached tokens), strict
        `>` keeping FIFO among ties, with the skip-cap barrier
        bounding starvation.

        Class mode: every class queue's window is scanned and the
        rank tuple ``(class priority, TTFT-deadline urgency, matched-
        token hotness, age)`` picks the winner. Urgency is a bucket —
        a request past its class TTFT target outranks a hotter fresh
        one of the same class; below target, hotness keeps the radix
        dividend (the PR 11 behavior within a class). The starvation
        barrier is per class: a request at its class skip cap stops
        younger SAME-CLASS requests from passing (if it fits, its
        infinite hotness wins its tier outright); across classes
        priority stays strict, and the per-class pending cap is the
        shed-load bound on that starvation. None = nothing admissible
        right now (backpressure)."""
        if self._pool is None:
            if not self.class_admission:
                return self._queues[DEFAULT_REQUEST_CLASS].popleft()
            best = None
            for name, q in self._queues.items():
                if not q:
                    continue
                key = (resolve_request_class(name).priority, -q[0].seq)
                if best is None or key > best[0]:
                    best = (key, q)
            return best[1].popleft() if best is not None else None
        if not self.class_admission:
            q = self._queues[DEFAULT_REQUEST_CLASS]
            best_i, best_score = None, -1.0
            for i in range(min(len(q), self._admit_window)):
                req = q[i]
                barrier = req.admit_skips >= self._admit_skip_cap
                if self._pool.can_admit(len(req.tokens), req.tokens):
                    score = (float("inf") if barrier else
                             float(self._pool.peek_matched_tokens(
                                 len(req.tokens), req.tokens)))
                    if score > best_score:
                        best_i, best_score = i, score
                if barrier:
                    break
            if best_i is None:
                return None
            for i in range(best_i):
                q[i].admit_skips += 1
            req = q[best_i]
            del q[best_i]
            return req
        now = time.time()
        best = None  # ((priority, overdue, hotness, -seq), queue, index)
        for name, q in self._queues.items():
            if not q:
                continue
            rc = resolve_request_class(name)
            for i in range(min(len(q), self._admit_window)):
                req = q[i]
                barrier = req.admit_skips >= rc.skip_cap
                if self._pool.can_admit(len(req.tokens), req.tokens):
                    hot = (float("inf") if barrier else
                           float(self._pool.peek_matched_tokens(
                               len(req.tokens), req.tokens)))
                    overdue = int(now - req.submitted_at > rc.ttft_target)
                    key = (rc.priority, overdue, hot, -req.seq)
                    if best is None or key > best[0]:
                        best = (key, q, i)
                if barrier:
                    break
        if best is None:
            return None
        _, q, best_i = best
        for i in range(best_i):
            q[i].admit_skips += 1  # within-class aging only
        req = q[best_i]
        del q[best_i]
        return req

    def _note_prefix_outcome(self, req: _Request, res,
                             prefill_len: int) -> int:
        """Per-admission radix-reuse accounting: counters, the rolling
        hit-rate gauge, and the request's cached-token stamp. Returns
        the prefill tokens to skip."""
        skip = min(res.matched_tokens, prefill_len)
        req.prefix_cached_tokens = skip
        outcome = ("full" if skip >= prefill_len
                   else "partial" if skip > 0 else "miss")
        obs_metrics.serving_prefix_hits_total(self._obs).inc(outcome=outcome)
        if skip:
            obs_metrics.serving_prefix_cached_tokens(self._obs).inc(skip)
        self._prefill_tokens_total += prefill_len
        self._prefill_tokens_skipped += skip
        self._hit_window.append((skip, prefill_len))
        if len(self._hit_window) >= self._hit_window_min:
            denom = sum(p for _, p in self._hit_window)
            if denom:
                obs_metrics.serving_prefix_hit_rate(self._obs).set(
                    sum(s for s, _ in self._hit_window) / denom)
        if res.cow is not None and req.trace is not None:
            req.trace.event("cow_fork", src=int(res.cow[0]),
                            dst=int(res.cow[1]))
        if req.preemptions:
            # Re-admission after eviction: the novel suffix is the real
            # recompute cost of preempting this request — the committed
            # prefix came back from the radix tree for free.
            novel = max(prefill_len - skip, 0)
            if novel:
                self._readmit_suffix_tokens += novel
                obs_metrics.serving_readmit_suffix_tokens_total(self._obs).inc(
                    novel)
        return skip

    def _admit(self) -> None:
        for b in range(self.slots):
            if self._slot_req[b] is not None or b in self._prefilling:
                continue
            # Pick under the lock: cancel() mutates the queue from HTTP
            # threads, and an unsynchronized pop can race it empty.
            with self._cv:
                if not self._queue_depth():
                    break
                req = self._pick_next_locked()
                if req is None:
                    # Paged backpressure: nothing in the scan window
                    # fits the pool right now — wait for retirements
                    # to free pages. One head annotation per engine
                    # tick while blocked (the per-span event cap
                    # bounds a long wait): answers "why is my request
                    # stuck in queue_wait" from the timeline alone.
                    head = self._queue_head()
                    if head is not None and head.trace is not None:
                        head.trace.event("kv_backpressure",
                                         pages_free=self._pool.free_pages)
                    break
                self._publish_queue_depth()
            admit_res = None
            if self._pool is not None:
                admit_res = self._pool.admit(b, len(req.tokens),
                                             req.tokens)
                if not admit_res:
                    # can_admit raced/drifted: put the request back at
                    # the head (FIFO preserved) and wait for
                    # retirements — running without pages would stream
                    # scratch-page garbage.
                    obs_metrics.serving_admissions_total(self._obs).inc(
                        outcome="deferred")
                    if req.trace is not None:
                        req.trace.event("requeue", reason="kv_pages")
                    with self._cv:
                        self._queue_for(req).appendleft(req)
                    break
            # Dequeued for real: close the queue_wait phase and feed
            # the SLO histogram (submit → admission dequeue).
            obs_metrics.serving_queue_wait_hist(self._obs).observe(
                time.time() - req.submitted_at, **{"class": req.klass})
            if req.trace is not None:
                req.trace.end_phase(slot=b)
            try:
                pos0, tok0, prefill_tokens = self._family_mod.cb_admission(
                    req.tokens)
                skip = 0
                if admit_res is not None:
                    skip = self._note_prefix_outcome(
                        req, admit_res, len(prefill_tokens or ()))
                    if admit_res.cow is not None:
                        # Fork the partially-shared page ONCE on
                        # device; the suffix prefill then writes only
                        # the divergent tokens into the private copy.
                        src, dst = admit_res.cow
                        self._cache = self._copy_page(
                            self._cache, jnp.int32(src), jnp.int32(dst))
                if (prefill_tokens and self.prefill_chunk is not None
                        and len(prefill_tokens) > self.prefill_chunk):
                    # Long prompt: reserve the slot and stream the
                    # prompt in chunks across loop iterations instead
                    # of blocking the pool on one monolithic prefill.
                    if req.trace is not None:
                        req.trace.start_phase(
                            "prefill", mode="chunked",
                            prompt_tokens=len(prefill_tokens),
                            chunk=self.prefill_chunk)
                    row_t = self._family_mod.cb_init_cache(
                        self.cfg, 1, self.max_len)
                    row_d = (self._draft_family.cb_init_cache(
                        self._draft_cfg, 1, self.max_len)
                        if self.draft is not None else None)
                    self._prefilling[b] = [
                        req, np.asarray(prefill_tokens, np.int32), 0,
                        row_t, row_d, pos0, tok0]
                    continue
                if prefill_tokens:
                    if skip >= len(prefill_tokens):
                        # Whole prefill served from the radix cache:
                        # every page is already written — no program
                        # runs at all, decode starts immediately.
                        if req.trace is not None:
                            req.trace.start_phase(
                                "prefill", mode="cached",
                                prompt_tokens=len(prefill_tokens),
                                cached_tokens=skip)
                    elif skip > 0 and self._suffix_prefill is not None:
                        # Partial hit: compute KV only for the novel
                        # suffix, attending the matched prefix pages
                        # gathered from the pool — O(S·P) instead of
                        # the full O(P²) recompute.
                        if req.trace is not None:
                            req.trace.start_phase(
                                "prefill", mode="suffix",
                                prompt_tokens=len(prefill_tokens),
                                cached_tokens=skip)
                        suffix = prefill_tokens[skip:]
                        n_pref = -(-skip // self._pool.page_size)
                        bucket = bucket_suffix_len(len(suffix))
                        padded = np.zeros(bucket, np.int32)
                        padded[:len(suffix)] = suffix
                        fn = self._suffix_prefill(bucket, n_pref)
                        self._cache = fn(
                            self.params,
                            jnp.asarray([padded], jnp.int32),
                            self._cache,
                            jnp.asarray(self._pool.padded_row(b)),
                            jnp.int32(skip),
                            jnp.int32(len(suffix)))
                    else:
                        if req.trace is not None:
                            req.trace.start_phase(
                                "prefill", mode="monolithic",
                                prompt_tokens=len(prefill_tokens))
                        row = jnp.asarray([prefill_tokens], jnp.int32)
                        fn = self._compiled_prefill(len(prefill_tokens))
                        if self._pool is not None:
                            self._cache = fn(
                                self.params, row, self._cache,
                                jnp.asarray(self._pool.padded_row(b)))
                        else:
                            row_cache = fn(self.params, row)
                            self._cache = self._insert(
                                self._cache, row_cache, jnp.int32(b))
                if prefill_tokens and self.draft is not None:
                    # The draft's cache row prefills the same prompt
                    # prefix; its first query (cur at pos) writes
                    # position pos inside the round. (Drafts require
                    # kv='dense', so the radix skip never applies —
                    # `row` was built by the monolithic branch.)
                    draft_row = self._compiled_draft_prefill(
                        len(prefill_tokens))(self._draft_params, row)
                    self._draft_cache = self._draft_insert(
                        self._draft_cache, draft_row, jnp.int32(b))
                if self._pool is not None:
                    # The prefill (or full cache hit) really wrote the
                    # pages this admission registered: the fresh radix
                    # leaf survives the slot from here on.
                    self._pool.commit_prefix(b)
                self._go_live(b, req, pos0, tok0)
            except Exception as exc:  # noqa: BLE001 — request-scoped
                if self._pool is not None:
                    # Failed admission frees pages AND forgets any
                    # prefix keys registered for content the prefill
                    # never wrote.
                    self._pool.release(b, invalidate_prefix=True)
                obs_metrics.serving_admissions_total(self._obs).inc(
                    outcome="failed")
                req.error = f"{type(exc).__name__}: {exc}"
                self._finish_trace(req)
                req.done.set()
                # Persistent device breakage surfaces in the admission
                # prefill just as readily as in the decode step — count
                # it toward the same fail-fast budget so a broken
                # device doesn't burn one prefill per queued request
                # (_count_request_failure has the counting rules).
                if not self._count_request_failure(exc):
                    return

    # ------------------------------------------------------ prefill lane
    def _admit_lane(self) -> None:
        """Disaggregated admission: queued requests land on free
        prefill-lane ROWS (never directly on a decode slot). The pool
        admission is identical to the classic path — radix match,
        page adoption, CoW fork, fresh-leaf registration — but no
        prefill program runs here; the lane tick streams the novel
        suffix in chunks and the handoff moves the finished row."""
        for p in range(self.slots, self.slots + self.prefill_slots):
            if p in self._lane:
                continue
            with self._cv:
                if not self._queue_depth():
                    break
                req = self._pick_next_locked()
                if req is None:
                    head = self._queue_head()
                    if head is not None and head.trace is not None:
                        head.trace.event(
                            "kv_backpressure",
                            pages_free=self._pool.free_pages)
                    break
                self._publish_queue_depth()
            admit_res = self._pool.admit(p, len(req.tokens), req.tokens)
            if not admit_res:
                obs_metrics.serving_admissions_total(self._obs).inc(
                    outcome="deferred")
                if req.trace is not None:
                    req.trace.event("requeue", reason="kv_pages")
                with self._cv:
                    self._queue_for(req).appendleft(req)
                break
            obs_metrics.serving_queue_wait_hist(self._obs).observe(
                time.time() - req.submitted_at, **{"class": req.klass})
            if req.trace is not None:
                req.trace.end_phase(slot=p)
            try:
                pos0, tok0, prefill_tokens = self._family_mod.cb_admission(
                    req.tokens)
                skip = self._note_prefix_outcome(
                    req, admit_res, len(prefill_tokens or ()))
                if admit_res.cow is not None:
                    src, dst = admit_res.cow
                    self._cache = self._copy_page(
                        self._cache, jnp.int32(src), jnp.int32(dst))
                toks = np.asarray(prefill_tokens or [], np.int32)
                skip = min(skip, len(toks))
                if req.trace is not None:
                    req.trace.start_phase(
                        "prefill",
                        mode="cached" if skip >= len(toks) else "lane",
                        prompt_tokens=int(len(toks)), cached_tokens=skip,
                        slot=p)
                self._lane[p] = [req, toks, skip, pos0, tok0]
            except Exception as exc:  # noqa: BLE001 — request-scoped
                self._pool.release(p, invalidate_prefix=True)
                obs_metrics.serving_admissions_total(self._obs).inc(
                    outcome="failed")
                req.error = f"{type(exc).__name__}: {exc}"
                self._finish_trace(req)
                req.done.set()
                if not self._count_request_failure(exc):
                    return

    def _drop_lane_reservation(self, p: int, error: str) -> None:
        """Abort one staged reservation: pages freed AND the fresh
        radix leaf detached (its content was never fully written —
        exactly the failed-prefill contract `release` documents)."""
        req = self._lane.pop(p)[0]
        self._pool.release(p, invalidate_prefix=True)
        if not req.done.is_set():
            if error != "cancelled" or not req.error:
                req.error = error
            self._finish_trace(req)
            req.done.set()

    def _lane_tick(self, decode_live: int) -> bool:
        """Advance the prefill lane. While decode rows are live, at
        most ``prefill_lane_budget`` chunk programs run — a prefill
        storm can inflate its OWN latency but never occupy more than
        the budgeted share of a tick the decode batch needed. With the
        decode lane idle, every staged reservation advances (the
        cold-start argument from _advance_prefill). Returns False when
        fail-fast stopped the engine."""
        budget = (len(self._lane) if decode_live == 0
                  else self.prefill_lane_budget)
        ran = 0
        for p in list(self._lane):
            if ran >= budget:
                break
            state = self._lane[p]
            req, toks, i, pos0, tok0 = state
            if req.cancelled:
                self._drop_lane_reservation(p, "cancelled")
                continue
            if i >= len(toks):
                continue  # staged, waiting for a free decode slot
            chunk = toks[i:i + self._lane_chunk]
            bucket = bucket_suffix_len(len(chunk))
            padded = np.zeros(bucket, np.int32)
            padded[:len(chunk)] = chunk
            n_pref = self._bucket_pages(-(-i // self._pool.page_size))
            try:
                fn = self._suffix_prefill(bucket, n_pref)
                self._cache = fn(
                    self.params, jnp.asarray([padded], jnp.int32),
                    self._cache,
                    jnp.asarray(self._pool.padded_row(p)),
                    jnp.int32(i), jnp.int32(len(chunk)))
            except Exception as exc:  # noqa: BLE001 — request-scoped
                self._drop_lane_reservation(
                    p, f"{type(exc).__name__}: {exc}")
                obs_metrics.serving_admissions_total(self._obs).inc(
                    outcome="failed")
                if not self._count_request_failure(exc):
                    return False
                continue
            ran += 1
            state[2] = i + len(chunk)
            if req.trace is not None:
                req.trace.event("chunk", pos=int(i), of=int(len(toks)))
        if ran:
            obs_metrics.serving_lane_ticks_total(self._obs).inc(lane="prefill")
        return True

    def _bucket_pages(self, n: int) -> int:
        """Bucket a prefix-page count to the next power of two (capped
        at the row width) so lane chunks share suffix executables
        across progress depths. Safe over-read: table entries past the
        real prefix gather the scratch page and _suffix_mask hides
        every prefix column >= the traced match depth m."""
        if n <= 0:
            return 0
        return min(1 << (n - 1).bit_length(),
                   self._pool.max_pages_per_row)

    def _lane_handoff(self) -> None:
        """Move finished reservations to free decode slots: commit the
        fresh radix leaf (the lane really wrote its pages), transfer
        row ownership (PagePool.handoff — refcounts conserved), and go
        live. Staging order is FIFO among finished rows; an unfinished
        head does not block a finished sibling (per-iteration
        scheduling: the decode lane should never idle on ceremony)."""
        for p in list(self._lane):
            state = self._lane[p]
            req, toks, i, pos0, tok0 = state
            if req.cancelled:
                self._drop_lane_reservation(p, "cancelled")
                continue
            if i < len(toks):
                continue
            b = next((s for s in range(self.slots)
                      if self._slot_req[s] is None), None)
            if b is None:
                return  # decode pool full: staged rows wait in place
            self._pool.commit_prefix(p)
            moved = self._pool.handoff(p, b)
            del self._lane[p]
            self._handoffs += 1
            self._handoff_pages += moved
            obs_metrics.serving_handoff_pages_total(self._obs).inc(moved)
            if req.trace is not None:
                req.trace.event("handoff", src_row=p, dst_slot=b,
                                pages=moved)
            self._go_live(b, req, pos0, tok0)

    def _lane_view(self) -> LaneView:
        """Pressure snapshot for the speculation policy (and the
        health surface): prefill backlog counts everything that still
        owes prefill work — queued, dense chunked reservations, lane
        reservations."""
        with self._cv:
            backlog = (self._queue_depth() + len(self._prefilling)
                       + len(self._lane))
            head = self._queue_head()
            oldest = (time.time() - head.submitted_at
                      if head is not None else 0.0)
        free = sum(1 for b in range(self.slots)
                   if self._slot_req[b] is None
                   and b not in self._prefilling)
        return LaneView(prefill_backlog=backlog, decode_free=free,
                        oldest_wait=oldest)

    def request_timeline(self, request_id: str) -> Optional[dict]:
        """Assembled span tree for one recent request (None = unknown
        id or already evicted from the ring) — the payload behind
        ``GET /requests/{id}/timeline``."""
        return self._ring.timeline(request_id)

    def recent_requests(self) -> list[dict]:
        """Ring summaries, most recent first — ``GET /requests``."""
        return self._ring.summaries()

    def health(self) -> dict:
        """Liveness + load view for /healthz: queue depth, slot
        occupancy, radix hit rate, and paged-KV headroom — ONE polled
        surface, so a balancer (serving.router.FleetRouter) can route
        on affinity and shed on pressure without stitching /metrics
        and /v1/stats by hand."""
        denom = sum(p for _, p in self._hit_window)
        return {
            "status": "stopped" if self._stopped else "ok",
            "model": self.model,
            "engine": "continuous",
            "queued": self._queue_depth(),
            "active": sum(1 for r in self._slot_req if r is not None),
            "slots": self.slots,
            "max_pending": self.max_pending,
            # Per-class admission view (ISSUE 19): the router's
            # pressure guard reads interactive pending against its cap
            # — aggregate prefill_pending can look fine while one class
            # queue is saturated.
            "class_admission": self.class_admission,
            "class_pending": {name: len(q)
                              for name, q in self._queues.items()},
            "class_caps": dict(self._class_caps),
            "preemptions": dict(self._preemptions),
            # Per-lane depths (ISSUE 18): the router spills on PREFILL
            # pressure (work not yet decoding — queued plus staged
            # reservations) instead of total queue depth, so a replica
            # that is merely decode-busy no longer looks crowded; the
            # autoscaler reads both sides separately.
            "prefill_pending": (self._queue_depth()
                                + len(self._prefilling)
                                + len(self._lane)),
            "decode_active": sum(1 for r in self._slot_req
                                 if r is not None),
            # Rolling draft-acceptance rate (None until a draft engine
            # has proposed something): accepted draft tokens over
            # proposed — the policy's throughput dividend observable.
            "spec_tokens_accepted_rate": (
                round(self._spec_accepted / self._spec_proposed, 4)
                if self._spec_proposed else None),
            # Rolling radix prefix hit rate (same admission window as
            # the polyaxon_serving_prefix_hit_rate gauge); None until
            # the window has samples, so cold starts read as unknown,
            # not as a collapse.
            "radix_hit_rate": (
                round(sum(s for s, _ in self._hit_window) / denom, 4)
                if len(self._hit_window) >= self._hit_window_min and denom
                else None),
            # Paged-KV headroom (None on dense engines): the router
            # treats free == 0 as not-routable.
            "kv_headroom": (self._pool.utilization()
                            if self._pool is not None else None),
        }

    def stats(self) -> dict:
        """Live engine counters + occupancy gauges for /v1/stats."""
        return {
            "engine": "continuous",
            "slots": self.slots,
            "active": sum(1 for r in self._slot_req if r is not None),
            "prefilling": len(self._prefilling),
            "queued": self._queue_depth(),
            "queue_depth_peak": self._queue_depth_peak,
            # Class-aware admission accounting (ISSUE 19): evictions by
            # victim class, and the real recompute cost of them — novel
            # suffix tokens prefilled at re-admission (the committed
            # radix prefix served the rest).
            "class_admission": self.class_admission,
            "preemptions": dict(self._preemptions),
            "readmit_suffix_tokens": self._readmit_suffix_tokens,
            "decode_steps": self._steps_total,
            # Mean fraction of slots live per decode step: ~1.0 means
            # continuous batching is actually winning; low values with
            # a deep queue mean admission (prefill) is the bottleneck.
            "avg_occupancy": (
                round(self._live_slot_steps
                      / (self._steps_total * self.slots), 4)
                if self._steps_total else None),
            "requests_served": self._served,
            "tokens_generated": self._tokens_out,
            "step_failures": self._step_failures,
            # Shed-load accounting (ISSUE 10): per-reason totals of
            # requests refused before admission.
            "rejected": dict(self._rejected),
            "request_tracing": self.request_tracing,
            "traced_requests": len(self._ring),
            "stopped": self._stopped,
            "kv": self.kv,
            **({"draft_model": self.draft[0],
                "spec_k": self.spec_k,
                "spec_rounds": self._spec_rounds,
                # Mean tokens emitted per verify round (1..k+1): THE
                # speculation-efficiency number — near 1 means the
                # draft buys nothing, near k+1 means near-full
                # acceptance.
                "spec_tokens_per_round": (
                    round(self._spec_tokens / self._spec_rounds, 3)
                    if self._spec_rounds else None),
                "spec_policy_state": self._spec_policy.state,
                "spec_tokens_accepted_rate": (
                    round(self._spec_accepted / self._spec_proposed, 4)
                    if self._spec_proposed else None)}
               if self.draft is not None else {}),
            **({"prefill_slots": self.prefill_slots,
                "lane_staging": len(self._lane),
                "handoffs": self._handoffs,
                "handoff_pages": self._handoff_pages,
                "decode_lane_budget": self.decode_lane_budget}
               if self.prefill_slots else {}),
            **({"kv_pages_total": self._pool.n_pages - 1,
                "kv_pages_free": self._pool.free_pages,
                "kv_page_size": self._pool.page_size,
                "kv_prefix_hits": self._pool.prefix_hits,
                "kv_prefix_misses": self._pool.prefix_misses,
                # Radix prefix-reuse dividend: prefill tokens the
                # engine did NOT recompute, plus the tree's live shape
                # and the chaos-path invariant check (non-zero means a
                # refcount/CoW accounting bug — bench and CI fail it).
                "prefill_tokens_total": self._prefill_tokens_total,
                "prefill_tokens_skipped": self._prefill_tokens_skipped,
                "kv_prefix_hit_rate": (
                    round(self._prefill_tokens_skipped
                          / self._prefill_tokens_total, 4)
                    if self._prefill_tokens_total else None),
                "kv_cow_forks": self._pool.cow_forks,
                "kv_prefix_evictions": self._pool.prefix_evictions,
                "kv_radix": self._pool.radix_stats(),
                "kv_invariant_violations": len(
                    self._pool.check_invariants())}
               if self._pool is not None else {}),
        }

    def _go_live(self, b: int, req: _Request, pos0: int, tok0: int) -> None:
        """Mark a slot live for decode — the ONE place slot state is
        initialized (monolithic admission and chunked-prefill
        completion both land here)."""
        obs_metrics.serving_admissions_total(self._obs).inc(outcome="admitted")
        if req.trace is not None:
            # Closes the prefill phase when one ran (1-token prompts
            # go straight from queue_wait to decode).
            req.trace.start_phase("decode", slot=b, pos0=int(pos0))
        self._slot_req[b] = req
        self._pos[b] = pos0
        self._cur[b] = tok0
        self._temps[b] = req.temperature
        self._top_ps[b] = req.top_p
        self._top_ks[b] = req.top_k
        self._keys[b] = jax.random.key(req.seed)

    def _count_request_failure(self, exc: Exception) -> bool:
        """Request-scoped device-failure accounting, shared by the
        admission prefill and the chunk writer: only RuntimeErrors
        (XLA device errors) count toward fail-fast — a ValueError is a
        bad REQUEST, and bad requests must not stop a healthy engine —
        and only a successful step resets the counter. Returns False
        when fail-fast stopped the engine."""
        if isinstance(exc, RuntimeError):
            self._step_failures += 1
            self._consec_step_failures += 1
            if self._consec_step_failures >= self.max_step_failures:
                self._fail_fast(f"{type(exc).__name__}: {exc}")
                return False
        return True

    def _advance_prefill(self, all_slots: bool = False) -> bool:
        """Advance prefilling slots by one chunk each: the OLDEST
        reservation only while live rows are decoding (bounded added
        latency per decode step, strict admission FIFO — dict
        insertion order), or every reservation when the pool is
        otherwise idle (``all_slots`` — serializing a cold-start burst
        behind one-slot-at-a-time would beat monolithic prefill at
        nothing). Returns False when fail-fast stopped the engine."""
        c = self.prefill_chunk
        advanced = False
        for b in list(self._prefilling):
            state = self._prefilling[b]
            req = state[0]
            if req.cancelled:
                del self._prefilling[b]
                if not req.done.is_set():
                    req.error = "cancelled"
                    self._finish_trace(req)
                    req.done.set()
                continue
            if advanced and not all_slots:
                break
            req, pending, i, row_t, row_d, pos0, tok0 = state
            chunk = pending[i:i + c]
            if len(chunk) < c:  # padded tail: junk writes land at
                chunk = np.concatenate(  # positions decode rewrites 1st
                    [chunk, np.zeros(c - len(chunk), np.int32)])
            tokens = jnp.asarray(chunk[None, :], jnp.int32)
            p0 = jnp.asarray([i], jnp.int32)
            try:
                state[3] = row_t = self._chunk_write(
                    self.params, row_t, tokens, p0)
                if row_d is not None:
                    state[4] = row_d = self._draft_chunk_write(
                        self._draft_params, row_d, tokens, p0)
            except Exception as exc:  # noqa: BLE001 — request-scoped
                del self._prefilling[b]
                obs_metrics.serving_admissions_total(self._obs).inc(
                    outcome="failed")
                req.error = f"{type(exc).__name__}: {exc}"
                self._finish_trace(req)
                req.done.set()
                if not self._count_request_failure(exc):
                    return False
                continue
            advanced = True
            state[2] = i + c
            if req.trace is not None:
                req.trace.event("chunk", pos=i,
                                of=int(len(pending)))
            if state[2] >= len(pending):
                # Caught up: insert the finished row(s) and go live.
                del self._prefilling[b]
                self._cache = self._insert(self._cache, row_t,
                                           jnp.int32(b))
                if row_d is not None:
                    self._draft_cache = self._draft_insert(
                        self._draft_cache, row_d, jnp.int32(b))
                self._go_live(b, req, pos0, tok0)
        return True

    def _handle_step_failure(self, exc: Exception, what: str) -> bool:
        """Shared device-failure recovery for the plain step AND the
        speculative round: fail every live request with the error,
        count toward the fail-fast budget, and rebuild the donated
        cache(s) so a transient failure doesn't kill the engine.
        Returns False when fail-fast stopped the engine. Must be
        called from an ``except`` block (logger.exception)."""
        logger.exception("%s failed", what)
        self._step_failures += 1
        self._consec_step_failures += 1
        err = f"{type(exc).__name__}: {exc}"
        for b in range(self.slots):
            if self._slot_req[b] is not None:
                self._slot_req[b].error = err
                self._retire(b)
        # Lane reservations die with the cache: their staged pages
        # were in the donated buffer, so the KV they hold is gone —
        # failing them is the only honest option (pages freed, fresh
        # leaves detached).
        for p in list(self._lane):
            self._drop_lane_reservation(p, err)
        if self._consec_step_failures >= self.max_step_failures:
            self._fail_fast(err)
            return False
        # The old cache was donated to the failed program — its buffer
        # is gone (or poisoned). Rebuild. (Every live row was retired
        # above, so a paged pool is fully free.)
        if self._pool is not None:
            self._cache = self._family_mod.paged_init_cache(
                self.cfg, self._pool.n_pages, self._pool.page_size)
            # The rebuilt cache is zeros: resident prefix pages no
            # longer hold the content their keys promise.
            self._pool.invalidate_prefix_cache()
        else:
            self._cache = self._family_mod.cb_init_cache(
                self.cfg, self.slots, self.max_len)
        if self.draft is not None:
            self._draft_cache = self._draft_family.cb_init_cache(
                self._draft_cfg, self.slots, self.max_len)
        return True

    def _spec_iteration(self, k: Optional[int] = None) -> bool:
        """One draft→verify round for the pool: every live slot emits
        1..k+1 tokens (ragged acceptance, per-row budget caps). ``k``
        is the POLICY's draft length for this round (default: the
        configured spec_k); each distinct k compiles once. Returns
        False when a persistent failure stopped the engine. Mirrors the
        plain step's failure semantics, rebuilding BOTH caches on a
        transient device error (they were donated to the failed round).
        """
        k = self.spec_k if k is None else k
        budget = np.zeros(self.slots, np.int32)
        for b in range(self.slots):
            req = self._slot_req[b]
            if req is not None:
                budget[b] = req.max_new - len(req.out)
        try:
            t, emit, cur_nxt, self._cache, self._draft_cache = (
                self._spec_round_for(k)(
                    self.params, self._draft_params,
                    self._cache, self._draft_cache,
                    jnp.asarray(self._cur), jnp.asarray(self._pos),
                    jnp.asarray(budget)))
            t = np.asarray(t)
            emit = np.asarray(emit)
            cur_nxt = np.asarray(cur_nxt)
        except Exception as exc:  # noqa: BLE001 — fail live requests
            return self._handle_step_failure(exc, "speculative round")
        self._consec_step_failures = 0
        self._spec_rounds += 1
        for b in range(self.slots):
            req = self._slot_req[b]
            if req is None:
                continue
            n = int(emit[b])
            self._spec_tokens += n
            # Acceptance accounting for the policy observable: of the
            # k proposals this row verified, emit-1 were the draft's
            # (the last emitted token is always the target's own).
            self._spec_proposed += k
            self._spec_accepted += max(n - 1, 0)
            fresh = [int(tok) for tok in t[b, :n]]
            hit = next((j for j, tok in enumerate(fresh)
                        if tok in req.eos), None)
            if hit is not None:
                # Stop at the eos (inclusive): the accepted tokens past
                # it are the target's real greedy continuation, but the
                # request asked to stop — drop them. Cache/pos state
                # past the retire point is irrelevant (the row is
                # replaced wholesale at the next admission).
                fresh = fresh[:hit + 1]
            req.out.extend(fresh)
            if fresh:
                if req.first_token_at is None:
                    self._observe_first_token(req)
                if req.trace is not None:
                    req.trace.event("spec_round", accepted=n,
                                    emitted=len(fresh))
            self._pos[b] += n
            self._cur[b] = int(cur_nxt[b])
            if len(req.out) >= req.max_new or hit is not None:
                self._retire(b)
        return True

    def _observe_first_token(self, req: _Request) -> None:
        """Stamp first-token emission: TTFT (submit → first token, so
        queue wait and prefill both count — that is the number a client
        feels) plus the timeline annotation."""
        req.first_token_at = time.time()
        obs_metrics.serving_ttft_hist(self._obs).observe(
            req.first_token_at - req.submitted_at,
            **{"class": req.klass})
        if req.trace is not None:
            req.trace.event("first_token")

    def _finish_trace(self, req: _Request) -> None:
        """Close a request's span tree (idempotent — retire and the
        failure paths may both reach it)."""
        if req.trace is None:
            return
        if req.error:
            req.trace.finish(status="error", error=req.error,
                             tokens_out=len(req.out),
                             prefix_cached_tokens=req.prefix_cached_tokens)
        else:
            req.trace.finish(tokens_out=len(req.out),
                             prefix_cached_tokens=req.prefix_cached_tokens)

    def _retire(self, b: int) -> None:
        req = self._slot_req[b]
        self._slot_req[b] = None
        self._pos[b] = -1
        if self._pool is not None:
            self._pool.release(b)
        self._temps[b] = 0.0
        self._top_ps[b] = 1.0
        self._top_ks[b] = 0
        if req is not None:
            if req.cancelled and not req.error:
                req.error = "cancelled"
            if not req.error:  # count only successfully-served requests
                self._served += 1
                self._tokens_out += len(req.out)
            now = time.time()
            obs_metrics.serving_request_hist(self._obs).observe(
                now - req.submitted_at)
            if (not req.error and req.first_token_at is not None
                    and len(req.out) >= 2):
                # TPOT = steady-state decode cadence: the first token
                # (prefill-dominated, already TTFT's job) is excluded.
                obs_metrics.serving_tpot_hist(self._obs).observe(
                    (now - req.first_token_at) / (len(req.out) - 1),
                    **{"class": req.klass})
            self._publish_queue_depth()
            self._finish_trace(req)
            req.done.set()

    # ------------------------------------------------------- preemption
    def _maybe_preempt(self) -> None:
        """Make room for a blocked urgent prefill by evicting one live
        lower-priority slot (ISSUE 19). Runs at the top of every tick,
        at most one eviction per tick (each eviction frees a slot AND
        pages, so re-checking next tick is cheap and avoids cascades).

        Trigger: pending ``preempts``-class demand (interactive)
        exceeds what free capacity can absorb — more urgent requests
        queued than free slot/lane entries, or the pool can't admit
        the oldest one's prompt. Demand-vs-capacity, not
        zero-capacity: under a storm, retirements free one slot per
        tick and a zero-capacity trigger would stall eviction there,
        capping the interactive lane at half width while best-effort
        camps the rest. Victim: a live decode slot
        of a ``preemptible`` class with strictly lower priority,
        preferring the one holding the most KV pages ("most
        over-budget"), fewest emitted tokens as tiebreak. Eviction
        releases the slot's pages through the normal retire path — the
        committed radix prefix stays resident, so the victim's
        re-admission is a suffix-only prefill (pages, not recompute)."""
        if (self._pool is None or not self.class_admission
                or not self.preemption):
            return
        with self._cv:
            cand = None
            demand = 0
            for name, q in self._queues.items():
                if not q or not resolve_request_class(name).preempts:
                    continue
                demand += len(q)
                if cand is None or q[0].seq < cand[0].seq:
                    cand = (q[0], resolve_request_class(name))
        if cand is None:
            return
        req, rc = cand
        if self.prefill_slots:
            free = sum(
                1 for p in range(self.slots,
                                 self.slots + self.prefill_slots)
                if p not in self._lane)
        else:
            free = sum(
                1 for b in range(self.slots)
                if self._slot_req[b] is None
                and b not in self._prefilling)
        fits = self._pool.can_admit(len(req.tokens), req.tokens)
        if free >= demand and fits:
            return  # capacity absorbs every urgent pending request
        victim = self._pick_victim(rc.priority)
        if victim is None:
            return  # nothing evictable (never touch peers/superiors)
        self._evict_slot(victim,
                         reason="kv_pages" if free else "slots")

    def _pick_victim(self, min_priority: int) -> Optional[int]:
        """Best decode slot to evict for a blocked class of
        ``min_priority``: preemptible, strictly lower priority, most
        pages held first. Lane reservations are never victims — their
        fresh leaves are uncommitted, so releasing them would need a
        prefix invalidate and cost full recompute."""
        best = None  # ((priority asc, pages desc, emitted asc), slot)
        for b in range(self.slots):
            victim = self._slot_req[b]
            if victim is None or b in self._prefilling:
                continue
            vrc = resolve_request_class(victim.klass)
            if not vrc.preemptible or vrc.priority >= min_priority:
                continue
            key = (-vrc.priority, self._pool.slot_pages(b),
                   -len(victim.out))
            if best is None or key > best[0]:
                best = (key, b)
        return best[1] if best is not None else None

    def _evict_slot(self, b: int, reason: str) -> None:
        """Preemptively evict slot ``b`` and requeue its request at the
        head of its class queue. Pages release through the same
        fresh-leaf path _retire uses — the committed prompt prefix
        stays resident in the radix tree (reclaimable, and a free
        suffix-only re-admission), while decode-extension pages return
        to the free list. Emitted tokens are discarded and regenerated
        deterministically on resume (greedy argmax / seed folded by
        position), so streaming clients see a consistent prefix; TTFT
        re-observes at the retry's first token — degraded service is
        measured, not hidden."""
        req = self._slot_req[b]
        rc = resolve_request_class(req.klass)
        held = self._pool.slot_pages(b)
        discarded = len(req.out)
        self._slot_req[b] = None
        self._pos[b] = -1
        self._temps[b] = 0.0
        self._top_ps[b] = 1.0
        self._top_ks[b] = 0
        self._pool.release(b)
        req.preemptions += 1
        req.out.clear()
        req.first_token_at = None
        self._preemptions[rc.name] = self._preemptions.get(rc.name, 0) + 1
        obs_metrics.serving_preemptions_total(self._obs).inc(
            **{"class": rc.name, "reason": reason})
        if req.trace is not None:
            req.trace.event("preempted", reason=reason, slot=b,
                            pages_held=held, tokens_discarded=discarded)
            req.trace.start_phase("queue_wait", requeued=True)
        with self._cv:
            self._queue_for(req).appendleft(req)
            self._publish_queue_depth()

    def _loop(self) -> None:
        while True:
            with self._cv:
                while (not self._stopped and not self._queue_depth()
                       and not self._prefilling and not self._lane
                       and all(r is None for r in self._slot_req)):
                    self._cv.wait()
                if self._stopped:
                    return
            # Idle waiting above is excluded from the tick duration:
            # the histogram measures work per iteration (admission +
            # prefill chunk + decode step), not queue quiet time.
            t0 = time.time()
            if not self._tick():
                return
            self._observe_tick(time.time() - t0)

    def _observe_tick(self, dt: float) -> None:
        """Engine-tick telemetry: iteration duration plus the batch
        composition and KV-page gauges a dashboard needs to say WHY
        throughput looks the way it does (decode-bound vs
        prefill-bound vs page-starved)."""
        obs_metrics.serving_tick_hist(self._obs).observe(dt)
        decode = sum(1 for r in self._slot_req if r is not None)
        prefill = len(self._prefilling) + len(self._lane)
        slots = obs_metrics.serving_batch_slots(self._obs)
        slots.set(decode, state="decode")
        slots.set(prefill, state="prefill")
        # Lane rows are capacity ON TOP of the decode slots, so free
        # counts only unreserved decode-pool slots.
        slots.set(max(self.slots - decode - len(self._prefilling), 0),
                  state="free")
        if self._pool is not None:
            util = self._pool.utilization()
            pages = obs_metrics.serving_kv_pages(self._obs)
            pages.set(util["used"], state="used")
            pages.set(util["free"], state="free")
            radix = self._pool.radix_stats()
            obs_metrics.serving_radix_nodes(self._obs).set(radix["nodes"])
            rpages = obs_metrics.serving_radix_pages(self._obs)
            rpages.set(radix["referenced"], state="referenced")
            rpages.set(radix["resident"], state="resident")

    def _tick(self) -> bool:
        """One engine iteration. Classic: drop cancellations, admit,
        advance chunked prefills, one decode step or speculative
        round. Disaggregated (``prefill_slots``): handoff finished
        lane rows, admit into lane rows, run the budgeted lane chunk
        programs, handoff again (a prefill that finished this tick
        goes live this tick), then give the decode lane its budgeted
        steps. Returns False when fail-fast stopped the engine (the
        loop exits); True otherwise — including idle iterations."""
        for b in range(self.slots):  # drop cancelled live requests
            req = self._slot_req[b]
            if req is not None and req.cancelled:
                self._retire(b)
        self._maybe_preempt()
        if self.prefill_slots:
            self._lane_handoff()  # free lane rows before admission
            self._admit_lane()
        else:
            self._admit()
        if self._stopped:  # admission may fail-fast mid-pass
            return False
        self._queue_depth_peak = max(self._queue_depth_peak,
                                     self._queue_depth())
        live = sum(1 for r in self._slot_req if r is not None)
        if self._lane:
            if not self._lane_tick(live):
                return False  # fail-fast stopped the engine
            self._lane_handoff()
            live = sum(1 for r in self._slot_req if r is not None)
        elif self._prefilling:
            # Idle pool → advance every reservation (a cold-start
            # burst must not serialize one slot at a time).
            if not self._advance_prefill(all_slots=(live == 0)):
                return False  # fail-fast stopped the engine
            live = sum(1 for r in self._slot_req if r is not None)
        if live == 0:
            self._last_decode_at = None
            return True
        if self.prefill_slots and self.decode_lane_budget < 1:
            # Red-team knob (bench --inject lane-starve): a zeroed
            # decode budget means staged work goes live and then sits
            # emitting nothing — the lane gate must catch this, so the
            # engine honors it rather than quietly clamping to 1.
            self._last_decode_at = None
            time.sleep(0.005)  # don't spin hot while starved
            return True
        obs_metrics.serving_lane_ticks_total(self._obs).inc(lane="decode")
        steps = self.decode_lane_budget if self.prefill_slots else 1
        for _ in range(max(steps, 1)):
            live = sum(1 for r in self._slot_req if r is not None)
            if live == 0:
                break
            self._steps_total += 1
            self._live_slot_steps += live
            if self.draft is not None:
                k = max(0, min(
                    self._spec_policy.draft_len(self._lane_view()),
                    self.spec_k))
                obs_metrics.serving_spec_draft_len(self._obs).set(k)
                if k > 0:
                    if not self._spec_iteration(k):
                        return False
                    self._note_decode_step()
                    continue
                # Policy says no headroom: fall through to a plain
                # step (lossless either way — the draft cache just
                # accrues holes that degrade later acceptance).
            if not self._plain_step():
                return False
            self._note_decode_step()
        return True

    def _note_decode_step(self) -> None:
        """Decode-lane cadence: the wall gap between CONSECUTIVE
        decode-lane steps, including whatever prefill work the
        scheduler let land in between — THE interference observable
        the decode-tpot-interference rule and the storm-window oracle
        invariant judge. Idle gaps never count (_last_decode_at resets
        whenever the lane goes quiet)."""
        now = time.monotonic()
        if self._last_decode_at is not None:
            obs_metrics.serving_decode_tpot_hist(self._obs).observe(
                now - self._last_decode_at)
        self._last_decode_at = now

    def _plain_step(self) -> bool:
        """One ragged decode step for the decode pool. Returns False
        when fail-fast stopped the engine."""
        try:
            keys = jnp.stack([
                jax.random.fold_in(self._keys[b],
                                   len(r.out) if (r := self._slot_req[b])
                                   else 0)
                for b in range(self.slots)])
            filtered = any(
                r is not None and (r.top_p < 1.0 or r.top_k > 0)
                for r in self._slot_req)
            step_fn = (self._step_filtered if filtered
                       else self._step_plain)
            # Decode sees ONLY the decode-pool rows: lane rows sit
            # past self.slots and belong to staged prefills.
            tables = (jnp.asarray(self._pool.tables[:self.slots])
                      if self._pool is not None else None)
            nxt, self._cache = step_fn(
                self.params, self._cache,
                jnp.asarray(self._cur), jnp.asarray(self._pos),
                keys, jnp.asarray(self._temps),
                jnp.asarray(self._top_ps), jnp.asarray(self._top_ks),
                tables)
            nxt = np.asarray(nxt)
        except Exception as exc:  # noqa: BLE001 — fail live requests
            return self._handle_step_failure(exc, "decode step")
        self._consec_step_failures = 0
        for b in range(self.slots):
            req = self._slot_req[b]
            if req is None:
                continue
            req.out.append(int(nxt[b]))
            if req.first_token_at is None:
                self._observe_first_token(req)
            self._pos[b] += 1
            self._cur[b] = int(nxt[b])
            if len(req.out) >= req.max_new or int(nxt[b]) in req.eos:
                self._retire(b)
            elif (self._pool is not None
                  and not self._pool.ensure(b, int(self._pos[b]))):
                # An oversubscribed pool ran dry mid-generation:
                # fail THIS row loudly (its output so far is
                # surfaced in the error path) rather than let it
                # scribble over a neighbour's pages.
                obs_metrics.serving_evictions_total(self._obs).inc(
                    reason="pool_exhausted")
                if req.trace is not None:
                    req.trace.event("evicted", reason="pool_exhausted",
                                    pos=int(self._pos[b]))
                req.error = (
                    "kv page pool exhausted mid-generation "
                    f"(pos {int(self._pos[b])}); raise --kv-pages "
                    "or lower concurrency")
                self._retire(b)
        return True
