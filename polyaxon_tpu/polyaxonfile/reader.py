"""Polyaxonfile loading: YAML/JSON → validated IR.

Parity target: the reference's ``polyaxonfile`` package (SURVEY.md §2,
§3.1 [K]): load one or more spec files, merge them in order (later files
patch earlier ones), detect the kind (component vs operation), apply CLI
params / presets / patches, and produce a validated ``V1Operation``.
"""

from __future__ import annotations

import copy
import os
from typing import Any, Optional, Sequence, Union

import yaml

from polyaxon_tpu.polyaxonfile.context import default_globals, render_value
from polyaxon_tpu.polyaxonfile.patch import patch_dict
from polyaxon_tpu.polyflow.component import V1Component
from polyaxon_tpu.polyflow.io import V1Param, validate_params_against_io
from polyaxon_tpu.polyflow.operation import V1Operation


class PolyaxonfileError(ValueError):
    pass


def _load_one(source: Union[str, dict]) -> list[dict]:
    """A source may be a path, a YAML payload string, or an already-parsed
    dict. Multi-document YAML streams yield multiple specs."""
    if isinstance(source, dict):
        return [copy.deepcopy(source)]
    text = source
    looks_like_path = isinstance(source, str) and "\n" not in source and (
        os.sep in source or source.endswith((".yaml", ".yml", ".json"))
    )
    if looks_like_path:
        if not os.path.exists(source):
            raise PolyaxonfileError(f"Polyaxonfile not found: {source}")
        with open(source) as handle:
            text = handle.read()
    try:
        docs = [d for d in yaml.safe_load_all(text) if d is not None]
    except yaml.YAMLError as exc:
        raise PolyaxonfileError(f"Invalid YAML: {exc}") from exc
    if not docs:
        raise PolyaxonfileError(f"Empty Polyaxonfile: {source!r}")
    for doc in docs:
        if not isinstance(doc, dict):
            raise PolyaxonfileError(f"Polyaxonfile documents must be mappings, got {type(doc)}")
    return docs


def load_specs(sources: Union[str, dict, Sequence[Union[str, dict]]]) -> dict:
    """Load and merge (post-merge order) one or more spec sources."""
    if isinstance(sources, (str, dict)):
        sources = [sources]
    docs: list[dict] = []
    for src in sources:
        docs.extend(_load_one(src))
    merged = docs[0]
    for doc in docs[1:]:
        merged = patch_dict(merged, doc)
    return merged


def spec_kind(data: dict) -> str:
    kind = data.get("kind")
    if kind in ("component", "operation"):
        return kind
    # Kindless files with a `run` section are components; with `component`
    # or a hub/path ref they are operations (reference behavior [K]).
    if "run" in data:
        return "component"
    if any(key in data for key in ("component", "hubRef", "pathRef", "urlRef")):
        return "operation"
    raise PolyaxonfileError(
        "Cannot determine spec kind: expected `kind: component|operation`, "
        "a `run` section, or a component reference"
    )


def get_component(data: dict) -> V1Component:
    data = dict(data)
    data.setdefault("kind", "component")
    return V1Component.from_dict(data)


def get_operation(data: dict) -> V1Operation:
    data = dict(data)
    data.setdefault("kind", "operation")
    return V1Operation.from_dict(data)


def check_polyaxonfile(
    polyaxonfile: Union[str, dict, Sequence[Union[str, dict]], None] = None,
    *,
    url: Optional[str] = None,
    hub: Optional[str] = None,
    params: Optional[dict[str, Any]] = None,
    presets: Optional[Sequence[Union[str, dict]]] = None,
    patch: Optional[dict] = None,
    patch_strategy: Optional[str] = None,
    validate_params: bool = True,
) -> V1Operation:
    """The front-door used by CLI/client (mirrors ``check_polyaxonfile``
    in the reference's call stack, SURVEY.md §3.1): produce a validated
    ``V1Operation`` from any accepted source + CLI overrides.
    """
    if hub is not None:
        op = V1Operation(hub_ref=hub)
    elif url is not None:
        op = V1Operation(url_ref=url)
    else:
        if polyaxonfile is None:
            raise PolyaxonfileError("No Polyaxonfile source provided")
        data = load_specs(polyaxonfile)
        kind = spec_kind(data)
        if kind == "component":
            component = get_component(data)
            op = V1Operation(component=component)
        else:
            op = get_operation(data)

    if params:
        merged: dict[str, V1Param] = dict(op.params or {})
        for name, value in params.items():
            if isinstance(value, V1Param):
                merged[name] = value
            elif isinstance(value, dict) and ("value" in value or "ref" in value):
                merged[name] = V1Param.from_dict(value)
            else:
                merged[name] = V1Param(value=value)
        op.params = merged

    if presets:
        op = apply_presets(op, presets)

    if patch:
        op_dict = patch_dict(op.to_dict(), patch, patch_strategy)
        op = get_operation(op_dict)

    if validate_params and op.component is not None:
        validate_params_against_io(
            op.params,
            op.component.inputs,
            op.component.outputs,
            provided_externally=matrix_param_names(op),
        )
    return op


def matrix_param_names(op: V1Operation) -> set[str]:
    """Param names a matrix binds per-trial (plus joins), which therefore
    need no operation-level value."""
    names: set[str] = set()
    matrix = op.matrix
    if matrix is not None:
        if hasattr(matrix, "params") and getattr(matrix, "params", None):
            names.update(matrix.params.keys())
        if hasattr(matrix, "values") and getattr(matrix, "values", None):
            for mapping in matrix.values:
                names.update(mapping.keys())
        # Hyperband/iterative also inject the resource param per rung.
        resource = getattr(matrix, "resource", None)
        if resource is not None:
            names.add(resource.name)
    for join in op.joins or []:
        names.update((join.params or {}).keys())
    return names


def apply_presets(
    op: V1Operation, presets: Sequence[Union[str, dict]]
) -> V1Operation:
    """Apply named/inline preset fragments onto an operation, in order.

    A preset is an operation-shaped partial spec (often just
    ``runPatch``/``environment``/``queue``); its ``patchStrategy``
    (default post_merge) governs the merge — the [B] gpu→tpu preset swap
    flows through here.
    """
    op_dict = op.to_dict()
    for preset in presets:
        preset_data = load_specs(preset) if not isinstance(preset, dict) else copy.deepcopy(preset)
        preset_data.pop("isPreset", None)
        preset_data.pop("is_preset", None)
        preset_data.pop("kind", None)
        strategy = preset_data.pop("patchStrategy", preset_data.pop("patch_strategy", None))
        op_dict = patch_dict(op_dict, preset_data, strategy)
    return get_operation(op_dict)


def resolve_operation_context(
    op: V1Operation,
    *,
    params: Optional[dict[str, Any]] = None,
    run_uuid: str = "",
    run_name: str = "",
    project_name: str = "",
    owner_name: str = "default",
    iteration: Optional[int] = None,
    artifacts_root: str = "",
    extra_context: Optional[dict[str, Any]] = None,
) -> V1Operation:
    """Render ``{{ params.* }}`` / ``{{ globals.* }}`` through the whole
    operation once params are bound (the compile step of SURVEY.md §3.1).
    Returns a new, fully-literal ``V1Operation``.
    """
    if op.component is None:
        raise PolyaxonfileError("Cannot resolve an operation without an inline component")
    bound = dict(op.params or {})
    for name, value in (params or {}).items():
        bound[name] = value if isinstance(value, V1Param) else V1Param(value=value)
    unbound = matrix_param_names(op) - set(bound)
    if unbound:
        raise PolyaxonfileError(
            f"Matrix-bound params {sorted(unbound)} must be bound per-trial before "
            "resolution (pass them via `params=`)"
        )
    param_values = validate_params_against_io(
        bound, op.component.inputs, op.component.outputs
    )
    # The rendered operation carries the fully-bound params so downstream
    # consumers (compiler toEnv/toInit routing) see trial bindings too.
    op = op.clone()
    op.params = bound or None
    context = {
        "params": param_values,
        "globals": default_globals(
            run_uuid=run_uuid,
            run_name=run_name or (op.name or ""),
            project_name=project_name,
            owner_name=owner_name,
            iteration=iteration,
            base_path=artifacts_root,
        ),
    }
    if extra_context:
        context.update(extra_context)
    rendered = render_value(op.to_dict(), context)
    # Apply the operation's runPatch onto the component run — this is
    # where preset fragments (e.g. the gpu→tpu environment swap, which
    # apply_presets records as run_patch) take effect.
    patch = rendered.pop("runPatch", None)
    if patch:
        if not rendered.get("component"):
            raise PolyaxonfileError(
                "runPatch/presets need a resolved inline component "
                "(pathRef/urlRef operations must be inlined first)")
        strategy = rendered.get("patchStrategy")
        run = rendered["component"].get("run") or {}
        rendered["component"]["run"] = patch_dict(run, patch, strategy)
    return get_operation(rendered)
