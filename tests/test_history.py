"""Temporal telemetry (ISSUE 15): the bounded metrics-history ring,
its windowed math, the window-scoped oracle kinds, and the read
surfaces.

Covers: change-detection sampling + cadence/monotonic gating, the
coarsening golden, the fixed memory ceiling (series refusal + point
eviction accounting), window-marker bounds, the pure ``windowed_*``
helper goldens, ``metric_during`` / ``slo_during`` /
``quota_violation`` verdict + evidence + missing-policy goldens over
hand-built histories, the schema gate for bad window specs, and the
``GET /api/v1/metrics/history`` + ``plx ops history`` surfaces.
"""

import json
import urllib.error
import urllib.request

import pytest
from click.testing import CliRunner

from polyaxon_tpu.obs import history as obs_history
from polyaxon_tpu.obs import metrics as obs_metrics
from polyaxon_tpu.obs import oracle as obs_oracle
from polyaxon_tpu.obs.oracle import Invariant, OracleError, TelemetryBundle


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += dt
        return self.t


def _inv(**kw) -> Invariant:
    kw.setdefault("id", "t")
    return Invariant.from_dict(kw)


def _one(invariant, bundle) -> dict:
    verdicts = obs_oracle.evaluate([invariant], bundle)
    assert len(verdicts) == 1
    return verdicts[0]


@pytest.fixture()
def registry():
    return obs_metrics.MetricsRegistry()


def _ring(registry, **kw):
    clock = FakeClock()
    kw.setdefault("cadence", 1.0)
    return obs_history.MetricsHistory(registry, clock=clock, **kw), clock


# ================================================================ sampler
class TestSampler:
    def test_unmoved_series_get_no_new_points(self, registry):
        hist, clock = _ring(registry)
        g = registry.gauge("g", "d")
        g.set(5.0)
        assert hist.sample() is True  # first-seen anchor
        clock.advance(1.0)
        assert hist.sample() is True  # a sampling pass ran...
        assert len(hist.points("g")) == 1  # ...but admitted nothing
        g.set(7.0)
        clock.advance(1.0)
        hist.sample()
        pts = hist.points("g")
        assert [p[1] for p in pts] == [5.0, 7.0]

    def test_cadence_gates_and_force_overrides(self, registry):
        hist, clock = _ring(registry, cadence=10.0)
        registry.counter("c", "d").inc()
        assert hist.sample() is True
        clock.advance(1.0)
        assert hist.sample() is False  # inside the cadence
        assert hist.sample(force=True) is True

    def test_backwards_clock_drops_the_sample(self, registry):
        hist, clock = _ring(registry)
        registry.gauge("g", "d").set(1.0)
        hist.sample()
        clock.advance(-5.0)
        assert hist.sample(force=True) is False
        assert hist.coverage()["samples"] == 1

    def test_counter_birth_is_anchored_absolute(self, registry):
        hist, clock = _ring(registry)
        c = registry.counter("c", "d")
        c.inc(3.0)
        hist.sample()
        (t, v), = hist.points("c")
        assert (t, v) == (clock.t, 3.0)

    def test_coarsening_thins_overflow_to_coarse_interval(self, registry):
        hist, clock = _ring(
            registry, cadence=1.0, recent_points=4, coarse_points=8,
            coarse_interval=2.0)
        g = registry.gauge("g", "d")
        for i in range(10):
            g.set(float(i))
            hist.sample()
            clock.advance(1.0)
        pts = hist.points("g")
        # recent ring keeps the full-cadence tail (last 4 samples);
        # everything older coarsened to one survivor per 2s interval.
        recent = [p[1] for p in pts[-4:]]
        assert recent == [6.0, 7.0, 8.0, 9.0]
        coarse = [p[1] for p in pts[:-4]]
        assert coarse == [0.0, 2.0, 4.0]  # every other 1s point survives
        assert hist.point_count() <= hist.max_points()

    def test_series_cap_refuses_and_counts_once(self, registry):
        hist, clock = _ring(registry, max_series=2)
        g = registry.gauge("g", "d", ("k",))
        for key in ("a", "b", "c"):
            g.set(1.0, k=key)
        hist.sample()
        clock.advance(1.0)
        g.set(2.0, k="c")
        hist.sample()  # refused series stays refused, counted once
        assert hist.series_count() == 2

        def refusals():
            snap = registry.snapshot()
            fam = snap["polyaxon_history_evictions_total"]["series"]
            return fam.get("series")

        # g/c plus the ring's own self-accounting families were refused
        # — each exactly once: further movement never recounts them.
        counted = refusals()
        assert counted >= 1
        clock.advance(1.0)
        g.set(3.0, k="c")
        hist.sample()
        assert refusals() == counted

    def test_memory_ceiling_holds_under_hammering(self, registry):
        hist, clock = _ring(
            registry, recent_points=3, coarse_points=2,
            coarse_interval=0.0, max_series=4)
        g = registry.gauge("g", "d", ("k",))
        for i in range(50):
            for key in ("a", "b", "c", "d", "e", "f"):
                g.set(float(i * 7 + hash(key) % 5), k=key)
            hist.sample()
            clock.advance(1.0)
        assert hist.series_count() <= 4
        assert hist.point_count() <= hist.max_points()
        assert hist.max_points() == 4 * (3 + 2)

    def test_window_markers_bounded_and_close_matches_open(self, registry):
        hist, clock = _ring(registry, max_windows=2)
        hist.mark_window("a", start=True)
        clock.advance(1.0)
        hist.mark_window("b", start=True)
        clock.advance(1.0)
        hist.mark_window("c", start=True)  # evicts "a"
        names = [w["name"] for w in hist.windows()]
        assert names == ["b", "c"]
        clock.advance(1.0)
        hist.mark_window("b", end=True)
        b = [w for w in hist.windows() if w["name"] == "b"][0]
        assert b["end"] == clock.t and b["start"] < b["end"]
        # closing what was never opened records a zero-length window,
        # not an exception (fail-open plane).
        hist.mark_window("ghost", end=True)
        ghost = [w for w in hist.windows() if w["name"] == "ghost"][0]
        assert ghost["start"] == ghost["end"]

    def test_sampler_is_fail_open(self):
        class Broken:
            def snapshot(self):
                raise RuntimeError("boom")

        hist = obs_history.MetricsHistory(Broken())
        assert hist.sample() is False  # counted, not raised


# ========================================================== windowed math
class TestWindowedMath:
    def test_value_at_carries_forward(self):
        pts = [[10.0, 1.0], [20.0, 2.0], [30.0, 3.0]]
        assert obs_history.value_at(pts, 5.0) is None
        assert obs_history.value_at(pts, 10.0) == 1.0
        assert obs_history.value_at(pts, 25.0) == 2.0
        assert obs_history.value_at(pts, 99.0) == 3.0

    def test_counter_delta_golden(self):
        pts = [[10.0, 4.0], [80.0, 10.0]]
        assert obs_history.windowed_counter_delta(pts, 70.0, 100.0) == 6.0
        # birth inside the window counts from zero
        assert obs_history.windowed_counter_delta(pts, 0.0, 15.0) == 4.0
        # before any point: nothing to judge
        assert obs_history.windowed_counter_delta(pts, 0.0, 5.0) is None

    def test_gauge_extent_includes_carry_in(self):
        pts = [[10.0, 5.0], [45.0, 9.0], [70.0, 1.0]]
        assert obs_history.windowed_gauge_extent(pts, 40.0, 60.0) == 9.0
        assert obs_history.windowed_gauge_extent(
            pts, 40.0, 60.0, agg="min") == 5.0  # the carry-in at 40
        assert obs_history.windowed_gauge_extent(
            pts, 40.0, 60.0, agg="last") == 9.0
        assert obs_history.windowed_gauge_extent(pts, 0.0, 5.0) is None

    def test_hist_sample_is_bucketwise_difference(self):
        pts = [
            [10.0, {"count": 2, "sum": 1.0, "buckets": {"1": 2, "+Inf": 0}}],
            [50.0, {"count": 6, "sum": 9.0, "buckets": {"1": 3, "+Inf": 3}}],
        ]
        sample = obs_history.windowed_hist_sample(pts, 40.0, 60.0)
        assert sample == {"count": 4, "sum": 8.0,
                          "buckets": {"1": 1, "+Inf": 3}}
        assert obs_history.windowed_hist_sample(pts, 0.0, 5.0) is None

    def test_slo_counts_need_a_matching_bound(self):
        sample = {"count": 10, "sum": 5.0, "buckets": {"1": 9, "+Inf": 1}}
        assert obs_history.sample_slo_counts(sample, 1.0) == (9.0, 10.0)
        assert obs_history.sample_slo_counts(sample, 0.5) is None

    def test_query_history_scopes_and_prepends_carry(self, registry):
        hist, clock = _ring(registry)
        g = registry.gauge("g", "d", ("k",))
        for v in (1.0, 4.0, 9.0):
            g.set(v, k="x")
            hist.sample()
            clock.advance(10.0)
        hist.mark_window("storm", start=True)
        clock.advance(1.0)
        g.set(2.0, k="x")
        hist.sample()
        hist.mark_window("storm", end=True)
        out = obs_history.query_history(
            hist.to_json(), name="g", window="storm", labels={"k": "x"})
        pts = out["metric"]["series"]["x"]
        # carry-in (9.0, restamped at scope start) + the in-window point
        assert [p[1] for p in pts] == [9.0, 2.0]
        assert pts[0][0] == out["scope"]["start"]
        catalog = obs_history.query_history(hist.to_json())
        assert "g" in catalog["metrics"]
        with pytest.raises(ValueError, match="no sampled series"):
            obs_history.query_history(hist.to_json(), name="nope")
        with pytest.raises(ValueError, match="neither a marked window"):
            obs_history.query_history(hist.to_json(), name="g",
                                      window="bogus$")


# =========================================================== during kinds
def _day_history() -> dict:
    """A hand-built day: one gauge, one counter, one histogram, the
    project-quota pair, and a marked storm window [40, 60]."""
    return {
        "cadence": 1.0,
        "coverage": {"start": 0.0, "end": 100.0, "samples": 100},
        "windows": [{"name": "storm", "start": 40.0, "end": 60.0}],
        "series": {
            "queue_depth": {
                "type": "gauge", "labels": ["queue"],
                "series": {"prod": [[10.0, 5.0], [45.0, 9.0], [70.0, 1.0]]},
            },
            "requeues_total": {
                "type": "counter", "labels": [],
                "series": {"": [[10.0, 4.0], [80.0, 10.0]]},
            },
            "ttft": {
                "type": "histogram", "labels": ["class"],
                "series": {"interactive": [
                    [10.0, {"count": 2, "sum": 1.0,
                            "buckets": {"1": 2, "2.5": 0, "+Inf": 0}}],
                    [50.0, {"count": 6, "sum": 9.0,
                            "buckets": {"1": 5, "2.5": 1, "+Inf": 0}}],
                ]},
            },
            "polyaxon_project_usage": {
                "type": "gauge", "labels": ["project", "resource"],
                "series": {
                    "research,runs": [[10.0, 1.0], [45.0, 3.0], [70.0, 1.0]],
                    "platform,runs": [[10.0, 2.0]],
                },
            },
            "polyaxon_project_quota_limit": {
                "type": "gauge", "labels": ["project", "resource"],
                "series": {
                    "research,runs": [[5.0, 2.0]],
                    "platform,runs": [[5.0, 0.0]],  # 0 = unlimited
                },
            },
        },
    }


class TestMetricDuring:
    def test_gauge_max_over_window_includes_carry_in(self):
        bundle = TelemetryBundle(history=_day_history())
        v = _one(_inv(kind="metric_during", metric="queue_depth",
                      labels={"queue": "prod"}, window="storm",
                      op="<=", value=8.0), bundle)
        assert v["verdict"] == "fail"
        assert v["evidence"]["observed"] == 9.0
        assert v["evidence"]["agg"] == "max"
        assert v["evidence"]["scope"] == {"window": "storm",
                                          "start": 40.0, "end": 60.0}

    def test_gauge_agg_min_and_last(self):
        bundle = TelemetryBundle(history=_day_history())
        v = _one(_inv(kind="metric_during", metric="queue_depth",
                      labels={"queue": "prod"}, window="storm",
                      agg="min", op=">=", value=5.0), bundle)
        assert v["verdict"] == "pass"
        assert v["evidence"]["observed"] == 5.0  # the carry-in at 40
        v = _one(_inv(kind="metric_during", metric="queue_depth",
                      labels={"queue": "prod"}, window="storm",
                      agg="last", op="<=", value=9.0), bundle)
        assert v["verdict"] == "pass"

    def test_counter_delta_over_trailing_span(self):
        bundle = TelemetryBundle(history=_day_history())
        v = _one(_inv(kind="metric_during", metric="requeues_total",
                      span="30s", op="<=", value=5.0), bundle)
        # trailing scope [70, 100]: carry 4 at 70 → 10 at 80 = delta 6
        assert v["verdict"] == "fail"
        assert v["evidence"]["observed"] == 6.0
        assert v["evidence"]["scope"] == {"span": 30.0,
                                          "start": 70.0, "end": 100.0}

    def test_histogram_quantile_inside_window(self):
        bundle = TelemetryBundle(history=_day_history())
        v = _one(_inv(kind="metric_during", metric="ttft",
                      labels={"class": "interactive"}, window="storm",
                      quantile=0.99, op="<=", value=2.5), bundle)
        # in-window distribution: buckets {1: 3, 2.5: 1, +Inf: 0}
        assert v["verdict"] == "pass"
        assert v["evidence"]["quantile"] == 0.99
        assert 1.0 <= v["evidence"]["observed"] <= 2.5

    def test_missing_policies(self):
        bundle = TelemetryBundle(history=_day_history())
        quiet = _inv(kind="metric_during", metric="never_sampled",
                     window="storm", op="<=", value=1.0)
        assert _one(quiet, bundle)["verdict"] == "skip"
        hard = _inv(kind="metric_during", metric="never_sampled",
                    window="storm", op="<=", value=1.0, missing="fail")
        assert _one(hard, bundle)["verdict"] == "fail"
        zero = _inv(kind="metric_during", metric="never_sampled",
                    window="storm", op="<=", value=1.0, missing="zero")
        v = _one(zero, bundle)
        assert v["verdict"] == "pass" and v["evidence"]["observed"] == 0.0
        no_window = _inv(kind="metric_during", metric="queue_depth",
                         window="unmarked", op="<=", value=1.0)
        v = _one(no_window, bundle)
        assert v["verdict"] == "skip"
        assert "no window 'unmarked'" in v["evidence"]["missing"]
        no_hist = _inv(kind="metric_during", metric="queue_depth",
                       window="storm", op="<=", value=1.0)
        assert _one(no_hist, TelemetryBundle())["verdict"] == "skip"


class TestSloDuring:
    def test_windowed_ratio_against_objective(self):
        bundle = TelemetryBundle(history=_day_history())
        v = _one(_inv(kind="slo_during", metric="ttft", le=1.0,
                      objective=0.75, window="storm"), bundle)
        # in-window: good(≤1)=3 of 4 → 0.75 meets the objective
        assert v["verdict"] == "pass"
        assert v["evidence"]["good"] == 3
        assert v["evidence"]["total"] == 4
        assert v["evidence"]["ratio"] == 0.75
        v = _one(_inv(kind="slo_during", metric="ttft", le=1.0,
                      objective=0.9, window="storm"), bundle)
        assert v["verdict"] == "fail"

    def test_le_must_be_a_bucket_bound(self):
        bundle = TelemetryBundle(history=_day_history())
        v = _one(_inv(kind="slo_during", metric="ttft", le=0.7,
                      objective=0.9, window="storm"), bundle)
        assert v["verdict"] == "skip"
        assert "not a bucket bound" in v["evidence"]["missing"]

    def test_empty_window_is_missing_not_perfect(self):
        hist = _day_history()
        hist["windows"].append({"name": "calm", "start": 0.0, "end": 5.0})
        bundle = TelemetryBundle(history=hist)
        v = _one(_inv(kind="slo_during", metric="ttft", le=1.0,
                      objective=0.9, window="calm"), bundle)
        assert v["verdict"] == "skip"
        assert "no observations" in v["evidence"]["missing"]


class TestQuotaViolation:
    def test_breach_instant_fails_with_golden_evidence(self):
        bundle = TelemetryBundle(history=_day_history())
        v = _one(_inv(kind="quota_violation"), bundle)
        assert v["verdict"] == "fail"
        assert v["evidence"]["breaches"] == [
            {"series": "research,runs", "at": 45.0,
             "used": 3.0, "limit": 2.0}]
        assert v["evidence"]["breach_total"] == 1
        assert v["evidence"]["series_checked"] == 2
        assert v["evidence"]["instants_checked"] == 4

    def test_under_limit_and_unlimited_pass(self):
        hist = _day_history()
        usage = hist["series"]["polyaxon_project_usage"]["series"]
        usage["research,runs"] = [[10.0, 1.0], [45.0, 2.0]]  # at limit: ok
        usage["platform,runs"] = [[10.0, 50.0]]  # limit 0 = unlimited
        bundle = TelemetryBundle(history=hist)
        v = _one(_inv(kind="quota_violation"), bundle)
        assert v["verdict"] == "pass"

    def test_no_usage_samples_follows_missing_policy(self):
        hist = _day_history()
        del hist["series"]["polyaxon_project_usage"]
        bundle = TelemetryBundle(history=hist)
        assert _one(_inv(kind="quota_violation"), bundle)["verdict"] == "skip"
        assert _one(_inv(kind="quota_violation", missing="fail"),
                    bundle)["verdict"] == "fail"


# ============================================================ schema gate
class TestWindowSchemaGate:
    @pytest.mark.parametrize("bad,match", [
        (dict(kind="metric_during", metric="m", op="<=", value=1.0),
         "exactly one of"),
        (dict(kind="metric_during", metric="m", op="<=", value=1.0,
              window="storm", span="5m"), "exactly one of"),
        (dict(kind="metric_during", metric="m", op="<=", value=1.0,
              span="bogus$"), "span"),
        (dict(kind="metric_during", metric="m", op="<=", value=1.0,
              window=""), "window"),
        (dict(kind="metric_during", metric="m", op="<=", value=1.0,
              window="storm", agg="p99"), "agg"),
        (dict(kind="metric", metric="m", op="<=", value=1.0,
              window="storm"), "only apply to"),
        (dict(kind="run_terminal", span="5m"), "only apply to"),
        (dict(kind="slo_during", metric="m", le=1.0, objective=0.9),
         "exactly one of"),
    ])
    def test_bad_window_specs_raise(self, bad, match):
        bad.setdefault("id", "t")
        with pytest.raises(OracleError, match=match):
            Invariant.from_dict(bad)

    def test_span_strings_parse_to_seconds(self):
        inv = _inv(kind="metric_during", metric="m", op="<=", value=1.0,
                   span="5m")
        assert inv.span == 300.0
        assert inv.window is None


# ============================================================ cluster-day
class TestClusterDayUnit:
    def test_trace_is_deterministic_and_adds_the_hyperband_lane(self):
        from polyaxon_tpu.sim import gauntlet
        from polyaxon_tpu.sim import replay as sim_replay

        one = gauntlet.build_cluster_day_trace("quick", seed=7)
        two = gauntlet.build_cluster_day_trace("quick", seed=7)
        assert sim_replay.trace_to_json(one) == sim_replay.trace_to_json(two)
        assert not any(e.kind == "storm" for e in one)  # driver fires it
        hyperband = [e for e in one
                     if (e.spec or {}).get("matrix", {}).get("kind")
                     == "hyperband"]
        assert len(hyperband) == gauntlet._PROFILES["quick"]["hyperband"][0]
        assert all(e.project == "research" for e in hyperband)

    def test_unknown_inject_rejected(self):
        from polyaxon_tpu.sim import gauntlet

        with pytest.raises(ValueError, match="unknown inject"):
            gauntlet.run_cluster_day(inject="made-up")

    @pytest.mark.slow
    def test_full_day_profile_holds_every_anchor(self):
        """The full cluster-day (1000-capacity fleet, the day trace,
        27-trial Hyperband sweeps, 10s marked storm) judged green —
        the slow tier of the ci.sh `--cluster-day --quick` stage."""
        from polyaxon_tpu.sim import gauntlet

        result = gauntlet.run_cluster_day(profile="full")
        assert result["passed"], result["oracle"]["counts"]
        assert set(result["anchors"].values()) == {"pass"}


# =============================================================== surfaces
class TestHistorySurfaces:
    @pytest.fixture()
    def day_ring(self):
        """A populated default ring over the global REGISTRY, restored
        after the test (the surfaces read ``default_history()``)."""
        prior = obs_history.default_history()
        clock = FakeClock()
        ring = obs_history.MetricsHistory(
            obs_metrics.REGISTRY, cadence=0.001, clock=clock)
        g = obs_metrics.REGISTRY.gauge(
            "polyaxon_queue_depth", "Queued runs per queue", ("queue",))
        for v in (1.0, 4.0, 2.0):
            g.set(v, queue="fleet")
            ring.sample(force=True)
            clock.advance(1.0)
        ring.mark_window("storm", start=True)
        clock.advance(1.0)
        g.set(9.0, queue="fleet")
        ring.sample(force=True)
        ring.mark_window("storm", end=True)
        obs_history.set_default_history(ring)
        try:
            yield ring
        finally:
            obs_history.set_default_history(prior)

    def test_api_route_serves_catalog_scope_and_rejections(
            self, tmp_path, day_ring):
        from polyaxon_tpu.api.server import ApiServer
        from polyaxon_tpu.controlplane import ControlPlane

        plane = ControlPlane(str(tmp_path / "home"))
        with ApiServer(plane) as srv:
            def get(path):
                try:
                    with urllib.request.urlopen(srv.url + path) as r:
                        return r.status, json.loads(r.read())
                except urllib.error.HTTPError as exc:
                    return exc.code, json.loads(exc.read())

            status, body = get("/api/v1/metrics/history")
            assert status == 200
            assert "polyaxon_queue_depth" in body["metrics"]
            status, body = get(
                "/api/v1/metrics/history?name=polyaxon_queue_depth"
                "&window=storm&labels=queue=fleet")
            assert status == 200
            pts = body["metric"]["series"]["fleet"]
            assert [p[1] for p in pts] == [2.0, 9.0]  # carry-in + point
            assert body["scope"]["window"] == "storm"
            assert get("/api/v1/metrics/history?name=nope")[0] == 400
            assert get("/api/v1/metrics/history"
                       "?name=polyaxon_queue_depth&window=bogus$")[0] == 400
            assert get("/api/v1/metrics/history"
                       "?name=polyaxon_queue_depth&labels=oops")[0] == 400

    def test_cli_lists_and_sparklines(self, tmp_path, monkeypatch,
                                      day_ring):
        from polyaxon_tpu.cli.main import cli

        monkeypatch.setenv("POLYAXON_TPU_HOME", str(tmp_path / "home"))
        runner = CliRunner()
        result = runner.invoke(cli, ["ops", "history"])
        assert result.exit_code == 0, result.output
        assert "polyaxon_queue_depth" in result.output
        result = runner.invoke(
            cli, ["ops", "history", "polyaxon_queue_depth",
                  "--window", "storm", "--labels", "queue=fleet"])
        assert result.exit_code == 0, result.output
        assert "last=9" in result.output
        result = runner.invoke(
            cli, ["ops", "history", "polyaxon_queue_depth", "--json"])
        assert result.exit_code == 0, result.output
        payload = json.loads(result.output)
        assert payload["metric"]["name"] == "polyaxon_queue_depth"
        result = runner.invoke(cli, ["ops", "history", "nope"])
        assert result.exit_code != 0
        assert "no sampled series" in result.output
        result = runner.invoke(
            cli, ["ops", "history", "polyaxon_queue_depth",
                  "--labels", "oops"])
        assert result.exit_code != 0
        assert "bad --labels" in result.output
