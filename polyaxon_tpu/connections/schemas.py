"""Typed connection catalog schemas.

Upstream's connections package (SURVEY.md §2 "Connections" [K]:
``V1Connection``/``V1ConnectionKind`` — artifact stores, git sources,
registries — with env/volume materialization). Kinds keep the upstream
vocabulary so existing Polyaxonfiles referencing connections by name
resolve unchanged; TPU-relevant stores (GCS for checkpoints/artifacts
over the TPU-VM service account) are first-class.
"""

from __future__ import annotations

from typing import Any, Optional

from polyaxon_tpu.schemas.base import BaseSchema


class V1ConnectionKind:
    HOST_PATH = "host_path"
    VOLUME_CLAIM = "volume_claim"
    GCS = "gcs"
    S3 = "s3"
    WASB = "wasb"  # azure blob
    GIT = "git"
    REGISTRY = "registry"
    SLACK = "slack"
    DISCORD = "discord"
    WEBHOOK = "webhook"
    PAGERDUTY = "pagerduty"
    CUSTOM = "custom"

    VALUES = frozenset({
        HOST_PATH, VOLUME_CLAIM, GCS, S3, WASB, GIT, REGISTRY,
        SLACK, DISCORD, WEBHOOK, PAGERDUTY, CUSTOM,
    })
    ARTIFACT_STORES = frozenset({HOST_PATH, VOLUME_CLAIM, GCS, S3, WASB})
    NOTIFIERS = frozenset({SLACK, DISCORD, WEBHOOK, PAGERDUTY})


class V1ConnectionResource(BaseSchema):
    """A secret/config-map style reference materialized as env or files."""

    name: str
    mount_path: Optional[str] = None
    items: Optional[list[str]] = None
    is_requested: Optional[bool] = None


class V1Connection(BaseSchema):
    name: str
    kind: str
    description: Optional[str] = None
    # Kind-specific schema: {url}, {bucket}, {host_path, mount_path}, ...
    schema_: Optional[dict[str, Any]] = None
    secret: Optional[V1ConnectionResource] = None
    config_map: Optional[V1ConnectionResource] = None
    env: Optional[dict[str, str]] = None
    tags: Optional[list[str]] = None

    def validate_kind(self) -> None:
        if self.kind not in V1ConnectionKind.VALUES:
            raise ValueError(
                f"connection `{self.name}` has unknown kind `{self.kind}` "
                f"(expected one of {sorted(V1ConnectionKind.VALUES)})")

    @property
    def is_artifact_store(self) -> bool:
        return self.kind in V1ConnectionKind.ARTIFACT_STORES

    @property
    def is_notifier(self) -> bool:
        return self.kind in V1ConnectionKind.NOTIFIERS

    def store_url(self) -> Optional[str]:
        """Canonical store URL for fs.store dispatch (file:///gs:///s3://)."""
        schema = self.schema_ or {}
        # The schema dict is free-form: YAML authors write camelCase,
        # Python callers snake_case — accept both.
        get = lambda *keys: next(
            (schema[k] for k in keys if schema.get(k)), None)
        if self.kind == V1ConnectionKind.HOST_PATH:
            path = get("host_path", "hostPath", "mount_path", "mountPath")
            return f"file://{path}" if path else None
        if self.kind == V1ConnectionKind.VOLUME_CLAIM:
            path = get("mount_path", "mountPath")
            return f"file://{path}" if path else None
        if self.kind == V1ConnectionKind.GCS:
            bucket = (schema.get("bucket") or "").removeprefix("gs://")
            return f"gs://{bucket}" if bucket else None
        if self.kind == V1ConnectionKind.S3:
            bucket = (schema.get("bucket") or "").removeprefix("s3://")
            return f"s3://{bucket}" if bucket else None
        if self.kind == V1ConnectionKind.WASB:
            return schema.get("url") or schema.get("bucket")
        return schema.get("url")

    def env_contract(self) -> dict[str, str]:
        """Env vars injected into pods that request this connection."""
        prefix = f"POLYAXON_CONNECTION_{self.name.upper().replace('-', '_')}"
        env = {f"{prefix}_KIND": self.kind}
        url = self.store_url()
        if url:
            env[f"{prefix}_URL"] = url
        for key, value in (self.env or {}).items():
            env[key] = value
        return env
