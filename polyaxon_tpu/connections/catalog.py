"""Connection catalog: named external systems resolvable at compile time.

Loaded from (first match wins):
1. an explicit path / list of dicts passed by the caller,
2. ``POLYAXON_TPU_CONNECTIONS`` (path to a json/yaml catalog),
3. ``<home>/connections.yaml`` next to the control-plane DB.

The compiler resolves ``init.connection`` / notification connection
names through the catalog; a dangling name is a compile error (matching
upstream behavior where the agent refuses unknown connections) instead
of a silent no-op at runtime.
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional, Sequence, Union

from polyaxon_tpu.connections.schemas import V1Connection

ENV_CONNECTIONS = "POLYAXON_TPU_CONNECTIONS"


class ConnectionResolutionError(ValueError):
    """Catalog lookup/validation failure. Named explicitly so importers
    never shadow the ``ConnectionError`` OSError builtin."""


def _load_entries(source: Union[str, Sequence[dict]]) -> list[dict]:
    if not isinstance(source, str):
        return list(source)
    with open(source) as fh:
        text = fh.read()
    if source.endswith((".yaml", ".yml")):
        import yaml

        data = yaml.safe_load(text)
    else:
        data = json.loads(text)
    if isinstance(data, dict):
        data = data.get("connections", [])
    if not isinstance(data, list):
        raise ConnectionResolutionError(
            f"connection catalog {source!r} must be a list or "
            "{'connections': [...]}")
    return data


class ConnectionCatalog:
    def __init__(self, source: Union[str, Sequence[dict], None] = None, *,
                 home: Optional[str] = None):
        entries: list[dict] = []
        if source is not None:
            entries = _load_entries(source)
        else:
            env_path = os.environ.get(ENV_CONNECTIONS)
            if env_path:
                if not os.path.exists(env_path):
                    raise ConnectionResolutionError(
                        f"{ENV_CONNECTIONS}={env_path!r} does not exist")
                entries = _load_entries(env_path)
            elif home:
                for name in ("connections.yaml", "connections.json"):
                    path = os.path.join(home, name)
                    if os.path.exists(path):
                        entries = _load_entries(path)
                        break
        self._by_name: dict[str, V1Connection] = {}
        for entry in entries:
            conn = entry if isinstance(entry, V1Connection) else (
                V1Connection.from_dict(entry))
            conn.validate_kind()
            if conn.name in self._by_name:
                raise ConnectionResolutionError(f"duplicate connection `{conn.name}`")
            self._by_name[conn.name] = conn

    # ----------------------------------------------------------------- api
    def names(self) -> list[str]:
        return sorted(self._by_name)

    def __contains__(self, name: str) -> bool:
        return name in self._by_name

    def __len__(self) -> int:
        return len(self._by_name)

    def get(self, name: str) -> V1Connection:
        try:
            return self._by_name[name]
        except KeyError:
            known = ", ".join(self.names()) or "<none registered>"
            raise ConnectionResolutionError(
                f"unknown connection `{name}` (known: {known})") from None

    def resolve_all(self, names: Sequence[str]) -> list[V1Connection]:
        return [self.get(n) for n in names]

    def env_for(self, names: Sequence[str]) -> dict[str, str]:
        env: dict[str, str] = {}
        for conn in self.resolve_all(names):
            env.update(conn.env_contract())
        return env

    def artifact_store(self, name: Optional[str] = None) -> Optional[V1Connection]:
        """The named store, or the single registered artifact store."""
        if name:
            conn = self.get(name)
            if not conn.is_artifact_store:
                raise ConnectionResolutionError(
                    f"connection `{name}` (kind={conn.kind}) is not an "
                    "artifact store")
            return conn
        stores = [c for c in self._by_name.values() if c.is_artifact_store]
        return stores[0] if len(stores) == 1 else None

    def to_dict(self) -> dict[str, Any]:
        return {"connections": [c.to_dict() for c in self._by_name.values()]}
