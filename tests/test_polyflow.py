"""Spec/IR unit tests (SURVEY.md §4: the bulk of the reference's suite is
pure spec tests over schemas, parsing, matrix math, lifecycle)."""

import math

import pytest

from polyaxon_tpu import lifecycle
from polyaxon_tpu.lifecycle import StatusTracker, V1Statuses
from polyaxon_tpu.polyflow import (
    V1Bayes,
    V1Component,
    V1GridSearch,
    V1Hyperband,
    V1HpChoice,
    V1IO,
    V1JAXJob,
    V1MeshSpec,
    V1Operation,
    V1Param,
    V1RandomSearch,
    V1TpuTopology,
    validate_params_against_io,
)
from polyaxon_tpu.polyflow.io import parse_value
from polyaxon_tpu.polyflow.matrix import V1HpLinSpace, V1HpLogUniform, V1HpRange


class TestIO:
    def test_parse_scalars(self):
        assert parse_value("3", "int") == 3
        assert parse_value(3.0, "int") == 3
        assert parse_value("0.5", "float") == 0.5
        assert parse_value("true", "bool") is True
        assert parse_value("off", "bool") is False
        assert parse_value(5, "str") == "5"
        with pytest.raises(ValueError):
            parse_value("3.5", "int")
        with pytest.raises(ValueError):
            parse_value({"a": 1}, "str")
        with pytest.raises(ValueError):
            parse_value("maybe", "bool")

    def test_required_and_defaults(self):
        io = V1IO(name="lr", type="float", value=0.1, is_optional=True)
        assert io.validate_value(None) == 0.1
        assert io.validate_value("0.2") == 0.2
        required = V1IO(name="steps", type="int")
        with pytest.raises(ValueError):
            required.validate_value(None)

    def test_options_and_lists(self):
        io = V1IO(name="opt", type="str", options=["adam", "sgd"], is_optional=True, value="adam")
        assert io.validate_value("sgd") == "sgd"
        with pytest.raises(ValueError):
            io.validate_value("lamb")
        lst = V1IO(name="dims", type="int", is_list=True)
        assert lst.validate_value(["1", 2]) == [1, 2]

    def test_params_against_io(self):
        inputs = [
            V1IO(name="lr", type="float"),
            V1IO(name="steps", type="int", value=10, is_optional=True),
        ]
        resolved = validate_params_against_io({"lr": V1Param(value="0.3")}, inputs)
        assert resolved == {"lr": 0.3, "steps": 10}
        with pytest.raises(ValueError):
            validate_params_against_io({"bogus": V1Param(value=1)}, inputs)
        with pytest.raises(ValueError):
            validate_params_against_io({}, [V1IO(name="lr", type="float")])

    def test_ref_params(self):
        p = V1Param(ref="runs.abc123.outputs.accuracy")
        assert p.is_runs_ref
        assert p.get_ref_parts() == ("runs", "abc123", "outputs.accuracy")


class TestMatrix:
    def test_grid_enumeration(self):
        grid = V1GridSearch(
            params={
                "lr": V1HpChoice(kind="choice", value=[0.1, 0.01]),
                "bs": V1HpRange(kind="range", value=[32, 97, 32]),
            }
        )
        assert grid.params["lr"].to_grid() == [0.1, 0.01]
        assert grid.params["bs"].to_grid() == [32, 64, 96]

    def test_linspace(self):
        hp = V1HpLinSpace(kind="linspace", value=[0, 1, 5])
        assert hp.to_grid() == [0, 0.25, 0.5, 0.75, 1.0]

    def test_random_sampling_deterministic(self):
        import random

        hp = V1HpLogUniform(kind="loguniform", value={"low": math.log(1e-4), "high": math.log(1e-1)})
        rng = random.Random(7)
        samples = [hp.sample(rng) for _ in range(50)]
        assert all(1e-4 <= s <= 1e-1 for s in samples)
        rng2 = random.Random(7)
        assert samples == [hp.sample(rng2) for _ in range(50)]

    def test_hyperband_bracket_math(self):
        hb = V1Hyperband.from_dict(
            {
                "kind": "hyperband",
                "maxIterations": 81,
                "eta": 3,
                "resource": {"name": "epochs", "type": "int"},
                "metric": {"name": "loss", "optimization": "minimize"},
                "params": {"lr": {"kind": "choice", "value": [0.1]}},
            }
        )
        assert hb.s_max == 4
        assert hb.B == 5 * 81
        # Hyperband paper (Li et al., JMLR 18) Table: R=81, eta=3 →
        # n = ceil((s_max+1) * eta^s / (s+1)), r = R * eta^-s.
        assert hb.bracket(4) == (81, 1)
        assert hb.bracket(3) == (34, 3)
        assert hb.bracket(2) == (15, 9)
        assert hb.bracket(1) == (8, 27)
        assert hb.bracket(0) == (5, 81)

    def test_bayes_spec(self):
        bayes = V1Bayes.from_dict(
            {
                "kind": "bayes",
                "numInitialRuns": 5,
                "maxIterations": 20,
                "metric": {"name": "loss", "optimization": "minimize"},
                "utilityFunction": {"acquisitionFunction": "ei"},
                "params": {"lr": {"kind": "uniform", "value": {"low": 0.0, "high": 1.0}}},
            }
        )
        assert bayes.metric.is_better(0.1, 0.5)
        assert bayes.utility_function.acquisition_function == "ei"

    def test_pchoice_probabilities(self):
        from polyaxon_tpu.polyflow import V1HpPChoice

        with pytest.raises(ValueError):
            V1HpPChoice(kind="pchoice", value=[("a", 0.5), ("b", 0.2)])


class TestRunKinds:
    def test_jaxjob_mesh_validation(self):
        job = V1JAXJob.from_dict(
            {
                "kind": "jaxjob",
                "runtime": {"model": "llama3_8b"},
                "topology": {"accelerator": "v5e", "topology": "8x8"},
                "mesh": {"axes": {"dp": 1, "fsdp": 64}},
            }
        )
        assert job.get_topology().total_chips() == 64
        assert job.mesh.resolved_axes(64) == {"dp": 1, "fsdp": 64}

    def test_mesh_fill_axis(self):
        mesh = V1MeshSpec(axes={"dp": 2, "fsdp": -1})
        assert mesh.resolved_axes(8) == {"dp": 2, "fsdp": 4}
        with pytest.raises(ValueError):
            mesh.resolved_axes(9)
        with pytest.raises(ValueError):
            V1MeshSpec(axes={"dp": -1, "fsdp": -1})

    def test_topology_math(self):
        topo = V1TpuTopology(accelerator="v5e", topology="4x8", slices=2)
        assert topo.chips_per_slice() == 32
        assert topo.total_chips() == 64
        assert topo.hosts_per_slice() == 8
        with pytest.raises(ValueError):
            V1TpuTopology(accelerator="v5e", topology="4xx")

    def test_jaxjob_requires_payload(self):
        with pytest.raises(ValueError):
            V1JAXJob.from_dict({"kind": "jaxjob"})

    def test_dcn_axes_must_divide_slices(self):
        with pytest.raises(ValueError):
            V1JAXJob.from_dict(
                {
                    "kind": "jaxjob",
                    "runtime": {"model": "x"},
                    "topology": {"accelerator": "v5e", "topology": "2x4", "slices": 1},
                    "mesh": {"axes": {"dp": 2, "fsdp": 4}, "dcnAxes": ["dp"]},
                }
            )

    def test_kubeflow_kinds(self):
        comp = V1Component.from_dict(
            {
                "kind": "component",
                "run": {
                    "kind": "tfjob",
                    "worker": {"replicas": 4, "container": {"image": "x"}},
                },
            }
        )
        assert comp.run_kind == "tfjob"
        assert comp.run.replica_map()["worker"].replicas == 4
        assert not comp.is_native_kind()


class TestOperation:
    def test_requires_component_source(self):
        with pytest.raises(ValueError):
            V1Operation.from_dict({"kind": "operation", "name": "x"})

    def test_single_source(self):
        with pytest.raises(ValueError):
            V1Operation.from_dict(
                {
                    "kind": "operation",
                    "hubRef": "a",
                    "component": {"run": {"kind": "job", "container": {"image": "i"}}},
                }
            )

    def test_camel_round_trip(self):
        op = V1Operation.from_dict(
            {
                "kind": "operation",
                "hubRef": "tensorboard",
                "runPatch": {"container": {"image": "z"}},
                "skipOnUpstreamSkip": True,
            }
        )
        data = op.to_dict()
        assert data["hubRef"] == "tensorboard"
        assert data["skipOnUpstreamSkip"] is True
        assert "skip_on_upstream_skip" not in data


class TestLifecycle:
    def test_happy_path(self):
        tracker = StatusTracker()
        for status in (
            V1Statuses.COMPILED,
            V1Statuses.QUEUED,
            V1Statuses.SCHEDULED,
            V1Statuses.STARTING,
            V1Statuses.RUNNING,
            V1Statuses.SUCCEEDED,
        ):
            tracker.transition(status)
        assert tracker.is_done
        assert len(tracker.conditions) == 7

    def test_illegal_transitions(self):
        tracker = StatusTracker()
        with pytest.raises(lifecycle.LifecycleError):
            tracker.transition(V1Statuses.RUNNING)
        tracker.transition(V1Statuses.COMPILED)
        tracker.transition(V1Statuses.QUEUED)
        tracker.transition(V1Statuses.STOPPED)  # universal edge
        with pytest.raises(lifecycle.LifecycleError):
            tracker.transition(V1Statuses.RUNNING)

    def test_preemption_cycle(self):
        tracker = StatusTracker()
        for status in (
            V1Statuses.COMPILED,
            V1Statuses.QUEUED,
            V1Statuses.SCHEDULED,
            V1Statuses.RUNNING,
            V1Statuses.PREEMPTED,
            V1Statuses.RETRYING,
            V1Statuses.QUEUED,
        ):
            tracker.transition(status)
        assert tracker.status == V1Statuses.QUEUED
