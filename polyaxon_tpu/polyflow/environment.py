"""Execution-environment specs: container, environment, init, termination,
cache, plugins, hooks, notifications.

Capability parity with the reference's ``polyflow/environment`` +
``polyflow/init`` + ``polyflow/termination`` + ``polyflow/cache`` +
``polyflow/plugins`` + ``polyflow/hooks`` (SURVEY.md §2 [K]), recast for
TPU slices: resource requests use ``google.com/tpu`` and carry slice
topology; node selectors become slice selectors; preemptible slices are a
first-class environment flag (BASELINE north star [B]).
"""

from __future__ import annotations

from typing import Any, Optional, Union

from pydantic import ConfigDict, field_validator

from polyaxon_tpu.schemas.base import BaseSchema

TPU_RESOURCE = "google.com/tpu"
GPU_RESOURCE = "nvidia.com/gpu"


class V1EnvVar(BaseSchema):
    name: str
    value: Optional[Any] = None
    value_from: Optional[dict[str, Any]] = None


class V1ResourceSpec(BaseSchema):
    model_config = ConfigDict(extra="allow", populate_by_name=True)
    limits: Optional[dict[str, Union[int, float, str]]] = None
    requests: Optional[dict[str, Union[int, float, str]]] = None

    def tpu_chips(self) -> int:
        for source in (self.limits, self.requests):
            if source and TPU_RESOURCE in source:
                return int(source[TPU_RESOURCE])
        return 0


class V1Container(BaseSchema):
    """The user process spec. A pared-down, k8s-compatible container schema
    (the reference embeds the full k8s ``V1Container`` [K]); unknown k8s
    fields are preserved via ``extra="allow"`` so Polyaxonfiles written for
    the reference parse unchanged.
    """

    model_config = ConfigDict(extra="allow", populate_by_name=True)

    name: Optional[str] = None
    image: Optional[str] = None
    command: Optional[Union[str, list[str]]] = None
    # Args commonly carry interpolated param values ("{{ params.lr }}" →
    # 0.1), so non-string items are allowed and coerced in args_list().
    args: Optional[Union[str, list[Any]]] = None
    env: Optional[list[V1EnvVar]] = None
    resources: Optional[V1ResourceSpec] = None
    working_dir: Optional[str] = None
    volume_mounts: Optional[list[dict[str, Any]]] = None

    def command_list(self) -> list[str]:
        if self.command is None:
            return []
        return [self.command] if isinstance(self.command, str) else list(self.command)

    def args_list(self) -> list[str]:
        if self.args is None:
            return []
        if isinstance(self.args, str):
            return [self.args]
        return [a if isinstance(a, str) else str(a) for a in self.args]


class V1TpuTopology(BaseSchema):
    """TPU-native replacement for GPU count requests: which slice shape a
    run wants. ``accelerator`` + ``topology`` determine chip count and the
    ICI torus; ``slices`` > 1 means multi-slice over DCN.
    """

    accelerator: str = "v5e"  # v4 | v5e | v5p | v6e ...
    topology: Optional[str] = None  # e.g. "2x4", "4x8", "8x16"; None → single host
    slices: int = 1
    chips_per_host: Optional[int] = None
    preemptible: Optional[bool] = None
    reserved: Optional[bool] = None

    @field_validator("topology")
    @classmethod
    def _check_topology(cls, v: Optional[str]) -> Optional[str]:
        if v is None:
            return v
        dims = v.lower().split("x")
        if not (1 <= len(dims) <= 3) or not all(d.isdigit() and int(d) > 0 for d in dims):
            raise ValueError(f"Bad TPU topology `{v}` (expected e.g. '2x4' or '4x4x8')")
        return v.lower()

    def dims(self) -> tuple[int, ...]:
        if not self.topology:
            # No explicit torus: a single host's worth of chips.
            return (self.chips_per_host or _default_chips_per_host(self.accelerator),)
        return tuple(int(d) for d in self.topology.split("x"))

    def chips_per_slice(self) -> int:
        n = 1
        for d in self.dims():
            n *= d
        return n

    def total_chips(self) -> int:
        return self.chips_per_slice() * self.slices

    def hosts_per_slice(self) -> int:
        cph = self.chips_per_host or _default_chips_per_host(self.accelerator)
        return max(1, self.chips_per_slice() // cph)

    def total_hosts(self) -> int:
        return self.hosts_per_slice() * self.slices


def _default_chips_per_host(accelerator: str) -> int:
    return {"v2": 4, "v3": 4, "v4": 4, "v5e": 4, "v5p": 4, "v6e": 4}.get(accelerator, 4)


class V1Environment(BaseSchema):
    """Scheduling/runtime environment applied to every replica.

    The reference carries k8s pod-level knobs (nodeSelector, tolerations,
    affinity, labels, annotations, serviceAccountName, imagePullSecrets —
    [K]); those are preserved for compatibility, and ``tpu`` adds the
    slice topology request that replaces ``nvidia.com/gpu`` counts [B].
    """

    model_config = ConfigDict(extra="allow", populate_by_name=True)

    labels: Optional[dict[str, str]] = None
    annotations: Optional[dict[str, str]] = None
    node_selector: Optional[dict[str, str]] = None
    tolerations: Optional[list[dict[str, Any]]] = None
    affinity: Optional[dict[str, Any]] = None
    node_name: Optional[str] = None
    service_account_name: Optional[str] = None
    image_pull_secrets: Optional[list[str]] = None
    security_context: Optional[dict[str, Any]] = None
    priority_class_name: Optional[str] = None
    restart_policy: Optional[str] = None
    host_network: Optional[bool] = None
    dns_policy: Optional[str] = None
    scheduler_name: Optional[str] = None
    tpu: Optional[V1TpuTopology] = None


class V1Init(BaseSchema):
    """One init phase: clone a repo, fetch artifacts, render a dockerfile,
    download a file, or run an arbitrary init container — plus the
    TPU-native ``tpu_metadata`` initializer that discovers slice metadata
    (coordinator address, process index, topology) before the main process
    starts (north star: "init containers discover TPU-VM slice metadata").
    """

    model_config = ConfigDict(extra="allow", populate_by_name=True)

    git: Optional[dict[str, Any]] = None
    artifacts: Optional[dict[str, Any]] = None
    dockerfile: Optional[dict[str, Any]] = None
    file: Optional[dict[str, Any]] = None
    tensorboard: Optional[dict[str, Any]] = None
    lineage_ref: Optional[str] = None
    model_ref: Optional[str] = None
    connection: Optional[str] = None
    path: Optional[str] = None
    container: Optional[V1Container] = None
    tpu_metadata: Optional[bool] = None


class V1Termination(BaseSchema):
    max_retries: Optional[int] = None
    ttl: Optional[int] = None
    timeout: Optional[int] = None
    # TPU-native: preemption of a preemptible slice does not consume a
    # retry unless this is set.
    preemption_counts_as_retry: Optional[bool] = None


class V1Cache(BaseSchema):
    disable: Optional[bool] = None
    ttl: Optional[int] = None
    io: Optional[list[str]] = None
    sections: Optional[list[str]] = None


class V1Plugins(BaseSchema):
    auth: Optional[bool] = None
    docker: Optional[bool] = None
    shm: Optional[bool] = None
    mount_artifacts_store: Optional[bool] = None
    collect_artifacts: Optional[bool] = None
    collect_logs: Optional[bool] = None
    collect_resources: Optional[bool] = None
    sync_statuses: Optional[bool] = None
    auto_resume: Optional[bool] = None
    log_level: Optional[str] = None
    # TPU-native: stream libtpu metrics (duty cycle, HBM, ICI counters)
    # into tracking alongside psutil host metrics [B].
    collect_tpu_metrics: Optional[bool] = None
    # Capture a jax.profiler trace as a run artifact (SURVEY §5.1).
    capture_profile: Optional[Union[bool, dict[str, Any]]] = None


class V1Hook(BaseSchema):
    trigger: Optional[str] = None  # succeeded | failed | stopped | done
    connection: Optional[str] = None
    hub_ref: Optional[str] = None
    conditions: Optional[str] = None
    presets: Optional[list[str]] = None
    params: Optional[dict[str, Any]] = None
    queue: Optional[str] = None


class V1Notification(BaseSchema):
    connections: list[str]
    trigger: Optional[str] = None
