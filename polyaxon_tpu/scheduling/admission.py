"""Weighted fair-share admission + priority preemption (ISSUE 2).

Replaces the agent's FIFO ``queued[:capacity]`` slice with a policy
pass in the Borg/Kubernetes shape (PAPERS.md): desired-state queues and
quotas enforced by an idempotent per-tick decision, priority preemption
as the pressure valve. Every decision is recomputed from store state,
so a restarted agent converges to the same admissions.

Ordering: eligible QUEUED runs are admitted by

    (queue priority desc, project fair-share deficit desc, age asc)

where the deficit of project *p* is ``weight_p / Σweights − share_p``
over the runs currently live plus the ones tentatively admitted earlier
in the same pass — classic weighted fair queueing, so two projects
flooding one queue converge to their quota weights.

Preemption: a run that stays admissible but capacity-starved for
``POLYAXON_TPU_STARVATION_TICKS`` consecutive passes picks ONE victim —
the lowest-effective-priority RUNNING run on a *preemptible* queue —
which the agent evicts (kill → PREEMPTED → PR 1 backoff requeue).
Quota walls never trigger preemption: exceeding tenants wait, loudly
(a ``reason=QuotaExceeded`` condition is pinned on the blocked run).

Chaos seam ``admission``: a fault ``{"seam": "admission", "op":
"<queue>"}`` starves that queue's candidates for ``times`` decisions,
so drills can prove starvation stays bounded and observable.
"""

from __future__ import annotations

import dataclasses
import logging
import os

from polyaxon_tpu import chaos
from polyaxon_tpu.controlplane.store import RunRecord
from polyaxon_tpu.lifecycle import V1Statuses
from polyaxon_tpu.scheduling.catalog import (
    DEFAULT_QUEUE,
    RunSchedInfo,
    sched_info,
)

logger = logging.getLogger(__name__)

# Statuses that occupy capacity/quota (anything the executor may own).
LIVE_STATUSES = [
    V1Statuses.SCHEDULED,
    V1Statuses.STARTING,
    V1Statuses.RUNNING,
    V1Statuses.PROCESSING,
    V1Statuses.WARNING,
    V1Statuses.STOPPING,
]

_PIPELINE_KINDS = {"matrix", "dag", "schedule"}


def _starvation_ticks() -> int:
    try:
        return max(1, int(os.environ.get("POLYAXON_TPU_STARVATION_TICKS", "3")))
    except ValueError:
        return 3


@dataclasses.dataclass
class AdmissionDecision:
    """One pass's verdict. ``admitted`` is ordered and may be longer
    than capacity: the agent starts entries until capacity is filled,
    skipping ones whose slice placement is still pending — so a single
    unplaceable run can never waste a slot a placeable one needs
    (head-of-line fix)."""

    admitted: list[tuple[RunRecord, RunSchedInfo]]
    victims: list[str]  # run uuids to preempt for starved high-priority work
    blocked: dict[str, str]  # run uuid -> reason (QuotaExceeded, ...)


class AdmissionController:
    def __init__(self, plane, *, starvation_ticks: int | None = None):
        self.plane = plane
        self.store = plane.store
        self.starvation_ticks = starvation_ticks or _starvation_ticks()
        self._starved: dict[str, int] = {}  # uuid -> consecutive starved passes

    # ------------------------------------------------------------ helpers
    def _queue_row(self, queues: dict[str, dict], name: str) -> dict:
        row = queues.get(name)
        if row is not None:
            return row
        # Unknown queue (legacy run / deleted queue): schedule like the
        # implicit default — neutral priority, uncapped, non-preemptible.
        return {"name": name or DEFAULT_QUEUE, "priority": 0,
                "concurrency": None, "preemptible": False}

    def _pin_blocked(self, record: RunRecord, reason: str, message: str) -> None:
        """Surface WHY a run is still queued, once per block streak —
        re-pinning every tick would flood the condition history."""
        last = self.store.last_condition(record.uuid)
        if last is not None and last.get("reason") == reason:
            return
        self.store.add_condition(
            record.uuid, V1Statuses.QUEUED.value, reason=reason,
            message=message)

    # --------------------------------------------------------------- pass
    def plan(self, queued: list[RunRecord], *, capacity: int,
             active: set[str] | None = None) -> AdmissionDecision:
        """Decide this tick's admissions (ordered) and preemptions.

        ``queued``: eligible QUEUED run records (non-pipeline kinds).
        ``capacity``: free executor slots. ``active``: run uuids the
        executor currently owns (the only evictable victims).
        """
        if not queued:
            # Idle ticks stay cheap (no catalog/usage queries), and an
            # empty queue means nothing can be starved.
            self._starved.clear()
            return AdmissionDecision(admitted=[], victims=[], blocked={})
        queues = {q["name"]: q for q in self.store.list_queues()}
        quotas = {q["project"]: q for q in self.store.list_quotas()}
        live = [
            r for r in self.store.list_runs(statuses=LIVE_STATUSES)
            if r.kind not in _PIPELINE_KINDS
        ]
        live_info = {r.uuid: sched_info(r) for r in live}

        # Usage (runs + chips per project, runs per queue), tentatively
        # extended as candidates are admitted within this pass.
        runs_by_project: dict[str, int] = {}
        chips_by_project: dict[str, int] = {}
        runs_by_queue: dict[str, int] = {}
        for r in live:
            info = live_info[r.uuid]
            runs_by_project[r.project] = runs_by_project.get(r.project, 0) + 1
            chips_by_project[r.project] = (
                chips_by_project.get(r.project, 0) + info.chips)
            runs_by_queue[info.queue] = runs_by_queue.get(info.queue, 0) + 1

        candidates = []
        for i, r in enumerate(queued):
            info = sched_info(r)
            info.queue_priority = self._queue_row(queues, info.queue)["priority"]
            candidates.append((i, r, info))
        plan = chaos.active_plan()
        blocked: dict[str, str] = {}
        admitted: list[tuple[RunRecord, RunSchedInfo]] = []

        def weight(project: str) -> float:
            quota = quotas.get(project)
            w = float(quota.get("weight") or 1.0) if quota else 1.0
            return max(w, 1e-9)

        active_projects = ({r.project for r in live}
                           | {r.project for r in queued})
        total_weight = sum(weight(p) for p in active_projects) or 1.0

        def deficit(project: str) -> float:
            total_live = sum(runs_by_project.values())
            share = (runs_by_project.get(project, 0) / total_live
                     if total_live else 0.0)
            return weight(project) / total_weight - share

        remaining = list(candidates)
        while remaining:
            # Re-rank each round: admissions shift the fair-share
            # deficits, which is exactly what makes this converge.
            remaining.sort(key=lambda item: (
                -self._queue_row(queues, item[2].queue)["priority"],
                -deficit(item[1].project),
                item[0],  # age: store order is (created_at, rowid)
            ))
            pick = None
            for entry in remaining:
                _, record, info = entry
                queue = self._queue_row(queues, info.queue)
                if plan is not None and plan.fire(
                        "admission", info.queue, detail=record.uuid) is not None:
                    blocked[record.uuid] = "ChaosStarved"
                    remaining.remove(entry)
                    pick = "retry"  # candidate consumed; re-rank and rescan
                    break
                cap = queue.get("concurrency")
                if cap is not None and runs_by_queue.get(info.queue, 0) >= cap:
                    blocked[record.uuid] = "QueueSaturated"
                    self._pin_blocked(
                        record, "QueueSaturated",
                        f"queue `{info.queue}` at concurrency cap {cap}")
                    remaining.remove(entry)
                    pick = "retry"
                    break
                quota = quotas.get(record.project)
                if quota is not None:
                    max_runs = quota.get("max_runs")
                    max_chips = quota.get("max_chips")
                    used_runs = runs_by_project.get(record.project, 0)
                    used_chips = chips_by_project.get(record.project, 0)
                    if max_runs is not None and used_runs >= max_runs:
                        blocked[record.uuid] = "QuotaExceeded"
                        self._pin_blocked(
                            record, "QuotaExceeded",
                            f"project `{record.project}` at max_runs="
                            f"{max_runs} ({used_runs} live)")
                        remaining.remove(entry)
                        pick = "retry"
                        break
                    if (max_chips is not None
                            and used_chips + info.chips > max_chips):
                        blocked[record.uuid] = "QuotaExceeded"
                        self._pin_blocked(
                            record, "QuotaExceeded",
                            f"project `{record.project}` chips quota "
                            f"{used_chips}+{info.chips} > {max_chips}")
                        remaining.remove(entry)
                        pick = "retry"
                        break
                pick = entry
                break
            if pick is None or pick == "retry":
                if pick is None:
                    break
                continue
            _, record, info = pick
            remaining.remove(pick)
            admitted.append((record, info))
            runs_by_project[record.project] = (
                runs_by_project.get(record.project, 0) + 1)
            chips_by_project[record.project] = (
                chips_by_project.get(record.project, 0) + info.chips)
            runs_by_queue[info.queue] = runs_by_queue.get(info.queue, 0) + 1

        victims = self._select_victims(
            admitted[max(capacity, 0):], queues, live, live_info,
            active or set())

        # Admission outcomes feed the unified registry: per-reason
        # blocked counts, admissions (capped at real capacity — the
        # overflow tail is ranked, not admitted), and evictions.
        from polyaxon_tpu.obs import metrics as obs_metrics

        outcomes = obs_metrics.admission_outcomes()
        for _ in admitted[:max(capacity, 0)]:
            outcomes.inc(outcome="admitted")
        for reason in blocked.values():
            outcomes.inc(outcome=reason)
        for _ in victims:
            outcomes.inc(outcome="victim")

        # Starvation counters only live for runs still queued.
        queued_uuids = {r.uuid for r in queued}
        for uuid in list(self._starved):
            if uuid not in queued_uuids:
                del self._starved[uuid]
        return AdmissionDecision(admitted=admitted, victims=victims,
                                 blocked=blocked)

    # --------------------------------------------------------- preemption
    def _select_victims(self, overflow, queues, live, live_info,
                        active: set[str]) -> list[str]:
        """Pick victims for admissible-but-capacity-starved runs.

        One victim per starved run per tick, strictly lower effective
        priority, on a preemptible queue, currently owned by the
        executor — the gentlest eviction that unblocks the starved run.
        """
        victims: list[str] = []
        overflow_uuids = {r.uuid for r, _ in overflow}
        for record, info in overflow:
            ticks = self._starved.get(record.uuid, 0) + 1
            self._starved[record.uuid] = ticks
            if ticks < self.starvation_ticks:
                continue
            starved_eff = info.effective(
                self._queue_row(queues, info.queue)["priority"])
            best = None
            for candidate in live:
                if candidate.uuid in victims or candidate.uuid not in active:
                    continue
                if candidate.status != V1Statuses.RUNNING:
                    continue
                cinfo = live_info[candidate.uuid]
                cqueue = self._queue_row(queues, cinfo.queue)
                if not cqueue["preemptible"]:
                    continue
                ceff = cinfo.effective(cqueue["priority"])
                if ceff >= starved_eff:
                    continue
                # Lowest priority first; among equals evict the
                # youngest (least progress lost).
                key = (ceff, candidate.started_at or candidate.created_at)
                if best is None or key[0] < best[0] or (
                        key[0] == best[0] and key[1] > best[1]):
                    best = (key[0], key[1], candidate)
            if best is None:
                continue
            victim = best[2]
            victims.append(victim.uuid)
            self._starved[record.uuid] = 0
            meta = dict(victim.meta or {})
            sched = dict(meta.get("scheduling") or {})
            sched["evicted_for"] = record.uuid
            meta["scheduling"] = sched
            self.store.update_run(victim.uuid, meta=meta)
            logger.info("admission: preempting %s (eff=%s) for starved %s "
                        "(eff=%s)", victim.uuid, best[0], record.uuid,
                        starved_eff)
        # Drop counters for runs that were admitted within capacity.
        for uuid in list(self._starved):
            if uuid not in overflow_uuids:
                self._starved.pop(uuid, None)
        return victims
