"""Sharded checkpoint/resume for the JAXJob runtime (orbax-backed).

The reference provides only the outputs-path contract + run-level
restart (SURVEY.md §5.4 [K]); the TPU build owns both halves. Each
process writes its own shards (orbax OCDBT), saves are async by default
so the step loop never blocks on IO, and restore re-lays tensors onto
the current mesh from the saved shardings — preemption-safe resume is
``latest_step() → restore(state_like)``.

:class:`TieredCheckpointManager` (ISSUE 16) layers the cheap restore
tiers from :mod:`runtime.tiers` in front of the store: a rolling
in-memory replica (tier-0) and a local-disk spill (tier-1), published
off the step loop by a daemon thread, restored tier-0-first with
per-step fallback down through the store and the PR 1 corrupt-step
culling — a poisoned tier can never win over an older clean one.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from polyaxon_tpu.polyflow.runs import V1JaxCheckpointing
from polyaxon_tpu.runtime import tiers

logger = logging.getLogger(__name__)


class CheckpointManager:
    def __init__(
        self,
        directory: str,
        spec: Optional[V1JaxCheckpointing] = None,
    ):
        self.spec = spec or V1JaxCheckpointing()
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        options = ocp.CheckpointManagerOptions(
            max_to_keep=self.spec.max_to_keep,
            enable_async_checkpointing=bool(self.spec.async_save),
        )
        self._mgr = ocp.CheckpointManager(self.directory, options=options)
        # Steps skipped by the most recent restore() because their
        # on-disk bytes failed to deserialize (newest first); surfaced
        # through TrainResult → outputs + a WARNING run condition.
        self.last_restore_skipped: list[int] = []
        # Which tier satisfied the most recent restore() ("0" memory /
        # "1" local spill / "2" store) — the meta["checkpoint"] audit.
        self.last_restore_tier: Optional[str] = None
        # Store step listing, shared by latest_step() and restore() so
        # the resume path lists the step directory ONCE; invalidated on
        # every mutation (save, corrupt-step delete).
        self._steps_cache: Optional[list[int]] = None

    @property
    def enabled(self) -> bool:
        return bool(self.spec.enabled)

    def interval(self) -> Optional[int]:
        return self.spec.interval_steps

    def should_save(self, step: int) -> bool:
        if not self.enabled:
            return False
        interval = self.spec.interval_steps
        return bool(interval) and step > 0 and step % interval == 0

    def save(self, step: int, state: Any, *, force: bool = False) -> None:
        if not self.enabled and not force:
            return
        self._mgr.save(step, args=ocp.args.StandardSave(state))
        self._steps_cache = None

    def _list_steps(self) -> list[int]:
        """Committed store steps, newest first — listed once and cached
        so ``latest_step() → restore()`` costs a single directory scan
        (the listing is a store round trip under fsspec)."""
        if self._steps_cache is None:
            self._steps_cache = sorted(self._mgr.all_steps(), reverse=True)
        return self._steps_cache

    def latest_step(self) -> Optional[int]:
        steps = self._list_steps()
        return steps[0] if steps else None

    def restore(self, state_like: Any, step: Optional[int] = None) -> Any:
        """Restore into the sharding/layout of ``state_like`` (an existing
        state pytree or eval_shape'd abstract tree with shardings).

        With no explicit ``step``, a latest checkpoint whose bytes fail
        to deserialize (truncated by an eviction mid-write, bit-rotted,
        chaos-corrupted) falls back to the NEXT-OLDER step instead of
        bricking resume; skipped steps land in ``last_restore_skipped``
        so the run surfaces ``restored_from_step`` + a WARNING instead
        of dying. An explicit ``step`` never falls back — the caller
        asked for those exact bytes.
        """
        self.last_restore_skipped = []
        self.last_restore_tier = None
        t_restore = time.perf_counter()
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, state_like)
        if step is not None:
            restored = self._mgr.restore(
                step, args=ocp.args.StandardRestore(abstract))
            self.last_restore_tier = tiers.TIER_STORE
            tiers._observe_restore(tiers.TIER_STORE,
                                   time.perf_counter() - t_restore)
            logger.info("Restored checkpoint step=%s from %s", step,
                        self.directory)
            return restored
        steps = self._list_steps()
        if not steps:
            raise FileNotFoundError(f"No checkpoint under {self.directory}")
        from polyaxon_tpu import chaos

        plan = chaos.active_plan()
        if plan is not None:
            plan.corrupt_checkpoint(self.directory, steps)
        last_error: Optional[Exception] = None
        for candidate in steps:
            try:
                restored = self._mgr.restore(
                    candidate, args=ocp.args.StandardRestore(abstract))
            except Exception as exc:  # noqa: BLE001 — fall back to older
                last_error = exc
                self.last_restore_skipped.append(candidate)
                logger.warning(
                    "checkpoint step %s under %s failed to restore (%s: "
                    "%s); falling back to the next-older step", candidate,
                    self.directory, type(exc).__name__, str(exc)[:200])
                try:
                    # A corrupt committed step is garbage: left in place
                    # it poisons both the next resume (same fallback
                    # dance) and re-saving that step number.
                    self._mgr.delete(candidate)
                except Exception:  # noqa: BLE001 — best-effort cleanup
                    logger.warning("could not delete corrupt step %s",
                                   candidate)
                self._steps_cache = None
                continue
            if self.last_restore_skipped:
                logger.warning(
                    "restored step %s after skipping corrupt step(s) %s",
                    candidate, self.last_restore_skipped)
            else:
                logger.info("Restored checkpoint step=%s from %s",
                            candidate, self.directory)
            self.last_restore_tier = tiers.TIER_STORE
            tiers._observe_restore(tiers.TIER_STORE,
                                   time.perf_counter() - t_restore)
            return restored
        raise RuntimeError(
            f"no restorable checkpoint under {self.directory}: every step "
            f"{steps} failed to deserialize") from last_error

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()


class TieredCheckpointManager(CheckpointManager):
    """Store-backed manager with the ISSUE 16 cheap tiers in front.

    ``save`` snapshots the state to host (the same copy orbax's async
    path makes) and hands it to a daemon publisher that commits the
    tier-0 in-memory replica and the tier-1 local spill off the step
    loop — rolling, latest-wins, atomic (tmp→rename) on disk. ``restore``
    walks candidate steps newest-first and, per step, tries memory →
    spill → store; a tier that fails validation is culled and the walk
    falls through, so a poisoned tier can never win over an older clean
    one. The winning tier lands in ``last_restore_tier`` and the
    catalogued ``polyaxon_checkpoint_restore_seconds{tier}`` sample.
    """

    def __init__(
        self,
        directory: str,
        spec: Optional[V1JaxCheckpointing] = None,
    ):
        super().__init__(directory, spec)
        self._spill = tiers.LocalSpill(self.directory)
        self._publish_cv = threading.Condition()
        self._pending: Optional[tuple[int, dict[str, np.ndarray]]] = None
        self._publishing = False
        self._publisher_stop = False
        self._publisher: Optional[threading.Thread] = None
        self.publish_errors = 0

    # ------------------------------------------------------------- save
    def save(self, step: int, state: Any, *, force: bool = False) -> None:
        if not self.enabled and not force:
            return
        mode = "async" if self.spec.async_save else "sync"
        t0 = time.perf_counter()
        super().save(step, state, force=force)
        tiers._observe_save(tiers.TIER_STORE, mode,
                            time.perf_counter() - t0)
        try:
            # Host snapshot NOW (the step loop reassigns/donates state
            # buffers); the registry publish + spill IO commit on the
            # publisher thread, off the step loop.
            arrays = {f"leaf_{i}": np.asarray(jax.device_get(leaf))
                      for i, leaf in enumerate(jax.tree.leaves(state))}
        except Exception as exc:  # noqa: BLE001 — tiers are an accelerant,
            # never a correctness dependency: the store save above holds.
            self.publish_errors += 1
            logger.warning("tier-0 snapshot for step %s failed (%s); "
                           "store tier still committed", step, exc)
            return
        with self._publish_cv:
            self._pending = (int(step), arrays)  # rolling: latest wins
            if self._publisher is None or not self._publisher.is_alive():
                self._publisher_stop = False
                self._publisher = threading.Thread(
                    target=self._publish_loop,
                    name="ckpt-tier0-publisher", daemon=True)
                self._publisher.start()
            self._publish_cv.notify_all()

    def _publish_loop(self) -> None:
        while True:
            with self._publish_cv:
                while self._pending is None and not self._publisher_stop:
                    self._publish_cv.wait()
                if self._pending is None:
                    return
                step, arrays = self._pending
                self._pending = None
                self._publishing = True
            try:
                t0 = time.perf_counter()
                tiers.TIER0.publish(self.directory, step, arrays)
                tiers._observe_save(tiers.TIER_MEMORY, "async",
                                    time.perf_counter() - t0)
                t1 = time.perf_counter()
                committed = self._spill.spill(step, arrays)
                tiers._observe_save(tiers.TIER_LOCAL, "async",
                                    time.perf_counter() - t1)
                if not committed:
                    logger.warning("tier-1 commit withheld for step %s",
                                   step)
            except Exception as exc:  # noqa: BLE001 — fail-open (see save)
                self.publish_errors += 1
                logger.warning("tier-0/1 publish for step %s failed: %s",
                               step, exc)
            finally:
                with self._publish_cv:
                    self._publishing = False
                    self._publish_cv.notify_all()

    # ---------------------------------------------------------- restore
    def latest_step(self) -> Optional[int]:
        candidates = list(self._list_steps())
        replica = tiers.TIER0.lookup(self.directory)
        if replica is not None:
            candidates.append(int(replica["step"]))
        candidates.extend(self._spill.steps())
        return max(candidates, default=None)

    def _materialize(self, state_like: Any,
                     arrays: dict[str, np.ndarray]) -> Any:
        """Re-lay flat tier-0/1 leaves onto ``state_like``'s structure
        and shardings (cross-mesh: an elastic resize restores the
        replica straight onto the survivor mesh). Any mismatch raises —
        the caller culls the tier and falls through."""
        leaves_like, treedef = jax.tree.flatten(state_like)
        if len(arrays) != len(leaves_like):
            raise ValueError(
                f"tier replica holds {len(arrays)} leaves, state expects "
                f"{len(leaves_like)}")
        out = []
        for i, like in enumerate(leaves_like):
            leaf = arrays[f"leaf_{i}"]
            want_shape = getattr(like, "shape", None)
            want_dtype = getattr(like, "dtype", None)
            if (want_shape is not None
                    and tuple(leaf.shape) != tuple(want_shape)):
                raise ValueError(
                    f"leaf_{i}: replica shape {tuple(leaf.shape)} != "
                    f"expected {tuple(want_shape)}")
            if (want_dtype is not None
                    and np.dtype(leaf.dtype) != np.dtype(want_dtype)):
                raise ValueError(
                    f"leaf_{i}: replica dtype {leaf.dtype} != expected "
                    f"{want_dtype}")
            sharding = getattr(like, "sharding", None)
            if sharding is not None:
                out.append(jax.device_put(leaf, sharding))
            elif want_shape is not None:
                out.append(jax.device_put(leaf))
            else:
                out.append(leaf)
        return jax.tree.unflatten(treedef, out)

    def _won(self, restored: Any, candidate: int, tier: str,
             t_restore: float) -> Any:
        self.last_restore_tier = tier
        tiers._observe_restore(tier, time.perf_counter() - t_restore)
        if self.last_restore_skipped:
            logger.warning(
                "restored step %s from tier %s after skipping corrupt "
                "step(s) %s", candidate, tier, self.last_restore_skipped)
        else:
            logger.info("Restored checkpoint step=%s tier=%s from %s",
                        candidate, tier, self.directory)
        return restored

    def restore(self, state_like: Any, step: Optional[int] = None) -> Any:
        if step is not None:
            # Explicit step: the caller asked for those exact store
            # bytes — no tier preference, no fallback (base contract).
            return super().restore(state_like, step)
        self.last_restore_skipped = []
        self.last_restore_tier = None
        t_restore = time.perf_counter()
        # Chaos fallback drill: a due tier0-loss fault drops the memory
        # replica AND the spill before we even look at them.
        tiers.tier0_loss_due(self.directory)
        replica = tiers.TIER0.lookup(self.directory)
        spill_steps = set(self._spill.steps())
        store_steps = self._list_steps()
        candidates = sorted(
            set(store_steps) | spill_steps
            | ({int(replica["step"])} if replica is not None else set()),
            reverse=True)
        if not candidates:
            raise FileNotFoundError(f"No checkpoint under {self.directory}")
        from polyaxon_tpu import chaos

        plan = chaos.active_plan()
        if plan is not None and store_steps:
            target = plan.corrupt_checkpoint(self.directory, store_steps)
            if target is not None:
                # The fault models the newest step's bytes rotting
                # wherever they are replicated: the cheap tiers lose
                # that step too, so the drill still proves the
                # fall-back-to-older-step path.
                if replica is not None and int(replica["step"]) == target:
                    tiers.TIER0.drop(self.directory)
                    replica = None
                if target in spill_steps:
                    self._spill.cull(target)
                    spill_steps.discard(target)
        abstract = None
        last_error: Optional[Exception] = None
        for candidate in candidates:
            if replica is not None and int(replica["step"]) == candidate:
                try:
                    restored = self._materialize(state_like,
                                                 replica["arrays"])
                except Exception as exc:  # noqa: BLE001 — cull, fall through
                    last_error = exc
                    tiers.TIER0.drop(self.directory)
                    replica = None
                    logger.warning(
                        "tier-0 replica at step %s unusable (%s: %s); "
                        "falling through", candidate, type(exc).__name__,
                        str(exc)[:200])
                else:
                    return self._won(restored, candidate,
                                     tiers.TIER_MEMORY, t_restore)
            if candidate in spill_steps:
                try:
                    arrays = self._spill.load(candidate)
                    restored = self._materialize(state_like, arrays)
                except Exception as exc:  # noqa: BLE001 — cull, fall through
                    last_error = exc
                    self._spill.cull(candidate)
                    logger.warning(
                        "tier-1 spill step %s unusable (%s: %s); falling "
                        "through", candidate, type(exc).__name__,
                        str(exc)[:200])
                else:
                    # Promote the winning spill into the memory slot so
                    # the NEXT restore is a tier-0 hit.
                    tiers.TIER0.publish(self.directory, candidate, arrays)
                    return self._won(restored, candidate,
                                     tiers.TIER_LOCAL, t_restore)
            if candidate in store_steps:
                if abstract is None:
                    abstract = jax.tree.map(
                        ocp.utils.to_shape_dtype_struct, state_like)
                try:
                    restored = self._mgr.restore(
                        candidate, args=ocp.args.StandardRestore(abstract))
                except Exception as exc:  # noqa: BLE001 — cull, fall back
                    last_error = exc
                    logger.warning(
                        "checkpoint step %s under %s failed to restore "
                        "(%s: %s); falling back to the next-older step",
                        candidate, self.directory, type(exc).__name__,
                        str(exc)[:200])
                    try:
                        self._mgr.delete(candidate)
                    except Exception:  # noqa: BLE001 — best-effort cleanup
                        logger.warning("could not delete corrupt step %s",
                                       candidate)
                    self._steps_cache = None
                else:
                    return self._won(restored, candidate,
                                     tiers.TIER_STORE, t_restore)
            # Every tier that held this step failed: the PR 1 culling
            # audit, now cross-tier.
            self.last_restore_skipped.append(candidate)
        raise RuntimeError(
            f"no restorable checkpoint under {self.directory}: every step "
            f"{candidates} failed across all tiers") from last_error

    # ---------------------------------------------------------- drain
    def wait(self) -> None:
        super().wait()
        with self._publish_cv:
            while self._pending is not None or self._publishing:
                self._publish_cv.wait(timeout=0.1)

    def close(self) -> None:
        self.wait()
        with self._publish_cv:
            self._publisher_stop = True
            self._publish_cv.notify_all()
        if self._publisher is not None:
            self._publisher.join(timeout=5.0)
            self._publisher = None
        super().close()
