from polyaxon_tpu.tune.base import (
    GridSearchManager,
    IterativeManager,
    MappingManager,
    Observation,
    RandomSearchManager,
    check_early_stopping,
    top_k,
)
from polyaxon_tpu.tune.asha import AshaManager
from polyaxon_tpu.tune.bayes import BayesManager, GaussianProcess, acquisition
from polyaxon_tpu.tune.hyperband import HyperbandManager, Rung
from polyaxon_tpu.tune.hyperopt import HyperoptManager

__all__ = [
    "AshaManager",
    "BayesManager",
    "GaussianProcess",
    "GridSearchManager",
    "HyperbandManager",
    "HyperoptManager",
    "IterativeManager",
    "MappingManager",
    "Observation",
    "RandomSearchManager",
    "Rung",
    "acquisition",
    "check_early_stopping",
    "top_k",
]
