from polyaxon_tpu.utils.env import apply_jax_platforms_override

__all__ = ["apply_jax_platforms_override"]
