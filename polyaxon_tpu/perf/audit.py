"""Per-(model, mesh, schedule) collective audit of the REAL train step.

Each point builds the same ``build_train_step`` program the runtime
loop executes (same rule tables, same optimizer, same donation), lowers
and compiles it against an N-device mesh, and censuses the collectives
in the compiled HLO (``perf/hlo.py``). Because the program is the real
one, a sharding-rule regression anywhere — model annotations, rule
tables, a manual schedule's specs — lands in these counts.

``inject_reshard=True`` deliberately re-constrains the batch to
replicated inside the step (the canonical "accidental reshard": one
stray ``with_sharding_constraint`` or a rule-table typo), which is how
tests and docs demonstrate the budget gate actually fails.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Optional

from polyaxon_tpu.perf import hlo as hlo_lib


@dataclasses.dataclass(frozen=True)
class AuditPoint:
    """One (model, mesh, schedule) audit coordinate."""

    name: str
    axes: dict[str, int]
    model: str = "llama_tiny"
    attention: Optional[str] = None  # None = the model's default (xla)
    seq_len: int = 256
    global_batch: int = 8

    def describe(self) -> dict:
        return {
            "name": self.name,
            "model": self.model,
            "axes": dict(self.axes),
            "attention": self.attention or "xla",
            "seq_len": self.seq_len,
            "global_batch": self.global_batch,
        }


# The standing schedule census on the 8-device virtual mesh: one point
# per parallelism family whose collectives CI keeps budgeted. Meshes
# mirror the MULTICHIP dryrun; ring and ulysses share dp2xcp4 so their
# reports diff directly (the r5 4.7x-gap attribution mesh).
STANDARD_POINTS: tuple[AuditPoint, ...] = (
    AuditPoint("dp", {"dp": 8}),
    AuditPoint("fsdp", {"dp": 2, "fsdp": 4}),
    AuditPoint("tp", {"dp": 2, "tp": 4}),
    AuditPoint("ring-cp", {"dp": 2, "cp": 4}, attention="ring"),
    AuditPoint("ulysses-cp", {"dp": 2, "cp": 4}, attention="ulysses"),
)


def point_by_name(name: str) -> AuditPoint:
    for p in STANDARD_POINTS:
        if p.name == name:
            return p
    raise KeyError(
        f"unknown schedule {name!r}; standard points: "
        f"{[p.name for p in STANDARD_POINTS]}")


def audit_point(
    point: AuditPoint,
    *,
    inject_reshard: bool = False,
    devices: Optional[list] = None,
    keep_ops: bool = False,
) -> dict[str, Any]:
    """Compile the point's train step and census its collectives.

    Pure analysis: nothing is executed on the devices — ``lower()`` +
    ``compile()`` only — so a point is safe to run under CI timeouts.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from polyaxon_tpu.models import get_model
    from polyaxon_tpu.parallel.mesh import build_mesh
    from polyaxon_tpu.parallel.sharding import batch_spec, rules_for_mesh
    from polyaxon_tpu.runtime.config import RuntimeConfig
    from polyaxon_tpu.runtime.optim import build_optimizer
    from polyaxon_tpu.runtime.step import build_init, build_train_step

    t0 = time.perf_counter()
    n_needed = 1
    for s in point.axes.values():
        n_needed *= s
    devices = list(devices if devices is not None else jax.devices())
    if len(devices) < n_needed:
        raise ValueError(
            f"point {point.name!r} needs {n_needed} devices, have "
            f"{len(devices)} (CI runs on the 8-device virtual CPU mesh)")
    mesh = build_mesh(axes=dict(point.axes), devices=devices[:n_needed])
    rules = rules_for_mesh(mesh)

    overrides: dict[str, Any] = {"max_seq_len": point.seq_len}
    if point.attention:
        overrides["attention_impl"] = point.attention
    model_def = get_model(point.model, **overrides)
    if inject_reshard:
        base_apply = model_def.apply
        replicated = NamedSharding(mesh, P())

        def bad_apply(variables, batch, train, rng):
            batch = dict(batch)
            batch["tokens"] = jax.lax.with_sharding_constraint(
                batch["tokens"], replicated)
            return base_apply(variables, batch, train, rng)

        model_def = dataclasses.replace(model_def, apply=bad_apply)

    cfg = RuntimeConfig(model=point.model, seq_len=point.seq_len)
    optimizer = build_optimizer(cfg)

    with mesh:
        init_fn = build_init(model_def, optimizer, mesh, rules)
        state = init_fn(jax.random.key(0))
        train_step = build_train_step(model_def, optimizer, mesh, rules)
        tokens = jnp.zeros((point.global_batch, point.seq_len), jnp.int32)
        sharding = NamedSharding(mesh, batch_spec(mesh, rules, ndim=2))
        batch = {"tokens": jax.device_put(tokens, sharding)}
        compiled = train_step.lower(state, batch, jax.random.key(1)).compile()
    hlo_text = compiled.as_text()

    ops = hlo_lib.parse_collectives(hlo_text, n_devices=mesh.devices.size)
    report = point.describe()
    report.update(hlo_lib.summarize_collectives(ops))
    overlap = hlo_lib.summarize_overlap(ops)
    report.update({
        # XLA:CPU emits only sync collectives (no async encoding on
        # that backend), so these rows carry overlap_ratio 0 on the CI
        # mesh; the overlap *budget* is enforced on the AOT TPU
        # topology path only (perf --audit --check).
        "overlap": overlap,
        "overlap_ratio": overlap["overlap_ratio"],
        "n_devices": int(mesh.devices.size),
        "backend": devices[0].platform,
        "compile_s": round(time.perf_counter() - t0, 1),
        "injected_reshard": bool(inject_reshard),
    })
    if keep_ops:
        report["ops"] = [dataclasses.asdict(o) for o in ops]
    return report


def audit_point_aot(point: AuditPoint, topology_name: str = "v5e:2x4",
                    keep_hlo: bool = False,
                    compiler_options: Optional[dict] = None) -> dict[str, Any]:
    """The audit against a TPU *topology description* — no live device.

    Nothing can execute, so the train state is fully abstract:
    ``eval_shape`` over the real ``build_init`` gives the avals, params
    carry their rule-table shardings, and the optimizer state's input
    shardings are left to GSPMD propagation (the one divergence from
    the runtime loop, where opt state is committed like params —
    collective counts here are TPU-backend evidence, not budget
    ground truth, which stays the CPU-mesh concrete path).

    Call this only inside the strictly-timeouted probe subprocess
    (``perf/aot.py``): creating the topology initializes libtpu.
    """
    import os

    os.environ.setdefault("TPU_SKIP_MDS_QUERY", "1")
    import jax
    import jax.numpy as jnp
    from jax.experimental import topologies
    from jax.sharding import NamedSharding, PartitionSpec as P

    from polyaxon_tpu.models import get_model
    from polyaxon_tpu.parallel.mesh import build_mesh
    from polyaxon_tpu.parallel.sharding import batch_spec, rules_for_mesh
    from polyaxon_tpu.runtime.config import RuntimeConfig
    from polyaxon_tpu.runtime.optim import build_optimizer
    from polyaxon_tpu.runtime.step import (
        build_init,
        build_train_step,
        state_shardings,
    )

    t0 = time.perf_counter()
    topo = topologies.get_topology_desc(platform="tpu",
                                        topology_name=topology_name)
    devices = list(topo.devices)
    mesh = build_mesh(axes=dict(point.axes), devices=devices)
    rules = rules_for_mesh(mesh)
    overrides: dict[str, Any] = {"max_seq_len": point.seq_len}
    if point.attention:
        overrides["attention_impl"] = point.attention
    model_def = get_model(point.model, **overrides)
    cfg = RuntimeConfig(model=point.model, seq_len=point.seq_len)
    optimizer = build_optimizer(cfg)

    with mesh:
        init_fn = build_init(model_def, optimizer, mesh, rules)
        rng_aval = jax.eval_shape(lambda: jax.random.key(0))
        avals = jax.eval_shape(init_fn, rng_aval)
        shardings = state_shardings(model_def, mesh, rules)
        abstract = lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype)  # noqa: E731
        state = {
            "params": jax.tree.map(
                lambda a, sh: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                                   sharding=sh),
                avals["params"], shardings["params"]),
            "state": jax.tree.map(abstract, avals["state"]),
            "opt_state": jax.tree.map(abstract, avals["opt_state"]),
            "step": jax.ShapeDtypeStruct((), jnp.int32,
                                         sharding=NamedSharding(mesh, P())),
        }
        train_step = build_train_step(model_def, optimizer, mesh, rules)
        batch = {"tokens": jax.ShapeDtypeStruct(
            (point.global_batch, point.seq_len), jnp.int32,
            sharding=NamedSharding(mesh, batch_spec(mesh, rules, ndim=2)))}
        lowered = train_step.lower(state, batch, rng_aval)
        if compiler_options:
            compiled = lowered.compile(compiler_options=dict(compiler_options))
        else:
            compiled = lowered.compile()
    hlo_text = compiled.as_text()

    ops = hlo_lib.parse_collectives(hlo_text, n_devices=mesh.devices.size)
    report = point.describe()
    report.update(hlo_lib.summarize_collectives(ops))
    overlap = hlo_lib.summarize_overlap(ops)
    report.update({
        "overlap": overlap,
        "overlap_ratio": overlap["overlap_ratio"],
        "n_devices": int(mesh.devices.size),
        "backend": "tpu-topology",
        "topology": topology_name,
        "device_kind": getattr(devices[0], "device_kind", "unknown"),
        "hlo_chars": len(hlo_text),
        "compile_s": round(time.perf_counter() - t0, 1),
        "compiler_options": dict(compiler_options or {}),
    })
    try:
        mem = compiled.memory_analysis()
        report["memory_analysis"] = {
            "temp_size_bytes": int(getattr(mem, "temp_size_in_bytes", -1)),
            "argument_size_bytes": int(
                getattr(mem, "argument_size_in_bytes", -1)),
            "output_size_bytes": int(
                getattr(mem, "output_size_in_bytes", -1)),
        }
    except Exception as exc:  # cost/memory APIs vary per jaxlib
        report["memory_analysis_error"] = type(exc).__name__
    if keep_hlo:
        report["hlo"] = hlo_text
    return report


def diff_reports(a: dict, b: dict) -> dict:
    """Collective-count/byte delta between two point reports (the
    ring-vs-ulysses attribution shape: same mesh, different schedule)."""
    kinds = sorted(set(a.get("counts", {})) | set(b.get("counts", {})))
    return {
        "a": a.get("name"),
        "b": b.get("name"),
        "count_delta": {
            k: b.get("counts", {}).get(k, 0) - a.get("counts", {}).get(k, 0)
            for k in kinds},
        "wire_bytes_delta": (b.get("est_wire_bytes_per_step", 0)
                             - a.get("est_wire_bytes_per_step", 0)),
    }
