"""CLI for the fleet simulator (mirrors ``python -m polyaxon_tpu.perf``).

Modes:
  python -m polyaxon_tpu.sim --quick --check     # CI gate (seconds)
  python -m polyaxon_tpu.sim --full              # full curve (minutes)
  python -m polyaxon_tpu.sim --update-budgets    # lock in a new baseline
  python -m polyaxon_tpu.sim --quick --deopt     # must FAIL the gate
  python -m polyaxon_tpu.sim --trace quick       # replay a whole trace
  python -m polyaxon_tpu.sim --gauntlet          # oracle-judged episode
  python -m polyaxon_tpu.sim --gauntlet --inject stuck-requeue  # must FAIL
  python -m polyaxon_tpu.sim --cluster-day --quick  # compressed day (CI)
  python -m polyaxon_tpu.sim --cluster-day --full   # the full day profile
  python -m polyaxon_tpu.sim --cluster-day --quick --inject quota-breach
  python -m polyaxon_tpu.sim --cluster-day --quick --inject tier0-loss
      # must still PASS: restores fall back to the store tier
  python -m polyaxon_tpu.sim --cluster-day --quick --inject stuck-tier0-commit
      # must FAIL: wedged tier-1 commits strand gangs, runs never terminal
  python -m polyaxon_tpu.sim --replay sim/scenarios/preemption-storm.json
  python -m polyaxon_tpu.sim --fleet-serve --quick  # serving-fleet episode
  python -m polyaxon_tpu.sim --fleet-serve --quick --inject route-blind
      # must FAIL: round-robin routing collapses the prefix hit rate
  python -m polyaxon_tpu.sim --fleet-serve --quick --inject cold-scale
      # must FAIL: unwarmed scale-up breaks during-spike TTFT
  python -m polyaxon_tpu.sim --fleet-serve --quick --inject mute-replica
      # must FAIL: an unscoped replica breaks federated-view coverage
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="python -m polyaxon_tpu.sim")
    parser.add_argument("--quick", action="store_true",
                        help="quick load points (CI profile)")
    parser.add_argument("--full", action="store_true",
                        help="full load points incl. the 10k-queued one")
    parser.add_argument("--check", action="store_true",
                        help="gate the measured curve against budgets.json")
    parser.add_argument("--update-budgets", action="store_true",
                        help="rewrite budgets.json from this run")
    parser.add_argument("--write-curve", action="store_true",
                        help="rewrite the committed fleet_curve.json")
    parser.add_argument("--deopt", action="store_true",
                        help="de-indexed/de-batched/legacy baseline "
                             "(should fail --check)")
    parser.add_argument("--trace", choices=["quick", "day"],
                        help="replay a whole arrival trace instead of "
                             "load points; asserts zero admission "
                             "divergence")
    parser.add_argument("--gauntlet", action="store_true",
                        help="run the oracle-judged mini-gauntlet "
                             "(sim/gauntlet.py); exit reflects verdicts")
    parser.add_argument("--cluster-day", action="store_true",
                        dest="cluster_day",
                        help="run the oracle-judged cluster-day gauntlet "
                             "(--quick = compressed CI form, --full = the "
                             "day profile); exit reflects verdicts")
    parser.add_argument("--no-serving", action="store_true",
                        help="(--cluster-day) skip the real-engine "
                             "serving lane (the serving anchors then "
                             "skip)")
    parser.add_argument("--inject", default=None, metavar="DEOPT",
                        help="(--gauntlet/--cluster-day) apply a named "
                             "deopt, e.g. stuck-requeue or quota-breach; "
                             "the run should then FAIL")
    parser.add_argument("--serving", action="store_true",
                        help="(--gauntlet) include the real-engine "
                             "serving segment (needs jax)")
    parser.add_argument("--fleet-serve", action="store_true",
                        dest="fleet_serve",
                        help="run the serving-fleet episode (spike → "
                             "scale-up → drain → scale-down) over real "
                             "engines, judged by the oracle's scale-up "
                             "window; exit reflects verdicts")
    parser.add_argument("--replay", default=None, metavar="SCENARIO",
                        help="replay a committed incident scenario "
                             "(sim/scenarios/*.json) judged by the "
                             "oracle; exit reflects verdicts")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--json", dest="json_out",
                        help="write the result JSON to this path "
                             "('' = stdout only)")
    args = parser.parse_args(argv)

    if args.fleet_serve:
        from polyaxon_tpu.sim import fleet_serve

        profile = "full" if args.full else "quick"
        result = fleet_serve.run_fleet_serve(
            profile=profile, seed=args.seed, inject=args.inject)
        fleet_serve.print_result(result,
                                 label=f"fleet-serve[{profile}]")
        if args.json_out:
            with open(args.json_out, "w") as fh:
                json.dump(result, fh, indent=2, default=str)
        return 0 if result["passed"] else 1

    if args.cluster_day:
        from polyaxon_tpu.sim import gauntlet

        profile = "full" if args.full else "quick"
        result = gauntlet.run_cluster_day(
            profile=profile, seed=args.seed or gauntlet.GAUNTLET_SEED,
            inject=args.inject, serving=not args.no_serving)
        gauntlet.print_result(result, label=f"cluster-day[{profile}]")
        if args.json_out:
            with open(args.json_out, "w") as fh:
                json.dump(result, fh, indent=2, default=str)
        return 0 if result["passed"] else 1

    if args.gauntlet:
        from polyaxon_tpu.sim import gauntlet

        gauntlet_argv = ["--seed", str(args.seed or gauntlet.GAUNTLET_SEED)]
        if args.inject:
            gauntlet_argv += ["--inject", args.inject]
        if args.serving:
            gauntlet_argv += ["--serving"]
        return gauntlet.main(gauntlet_argv)

    if args.replay:
        from polyaxon_tpu.sim import replay as sim_replay

        result = sim_replay.replay_scenario(args.replay, seed=args.seed)
        print(json.dumps(result, indent=2, default=str))
        if args.json_out:
            with open(args.json_out, "w") as fh:
                json.dump(result, fh, indent=2, default=str)
        if not result["oracle"]["passed"]:
            print("FAIL: oracle invariants failed on replay",
                  file=sys.stderr)
            return 1
        return 0

    from polyaxon_tpu.sim import budgets as sim_budgets
    from polyaxon_tpu.sim import curve as sim_curve

    if args.trace:
        from polyaxon_tpu.sim.fleet import FleetSim
        from polyaxon_tpu.sim.traces import make_trace

        sim = FleetSim(capacity=1000 if args.trace == "day" else 16,
                       seed=args.seed, legacy_scan=args.deopt,
                       incremental=not args.deopt, deopt=args.deopt,
                       rebuild_ticks=25)
        try:
            report = sim.run_trace(
                make_trace(args.trace, seed=args.seed),
                max_wall=1800.0 if args.trace == "day" else 120.0)
        finally:
            sim.close()
        print(json.dumps(report, indent=2))
        if args.json_out:
            with open(args.json_out, "w") as fh:
                json.dump(report, fh, indent=2)
        if report["divergence_total"]:
            print(f"FAIL: admission live-view diverged "
                  f"{report['divergence_total']} times", file=sys.stderr)
            return 1
        if not report["rebuild_checks"] and not args.deopt:
            print("FAIL: no rebuild consistency checks ran",
                  file=sys.stderr)
            return 1
        return 0

    mode = "full" if args.full else "quick"
    curve = sim_curve.build_curve(
        mode, seed=args.seed, legacy=args.deopt, deopt=args.deopt,
        progress=lambda msg: print(f"[sim] {msg}", file=sys.stderr))
    print(json.dumps(curve, indent=2))
    if args.json_out:
        with open(args.json_out, "w") as fh:
            json.dump(curve, fh, indent=2)

    if args.update_budgets:
        if args.deopt:
            print("refusing to write budgets from a --deopt run",
                  file=sys.stderr)
            return 2
        # Budget BOTH modes off one command: the quick table gates CI,
        # the full table gates bench_controlplane full runs.
        curves = {mode: curve}
        other = "quick" if mode == "full" else "full"
        curves[other] = sim_curve.build_curve(
            other, seed=args.seed,
            progress=lambda msg: print(f"[sim:{other}] {msg}",
                                       file=sys.stderr))
        path = sim_budgets.write_budgets(
            curves, meta={"seed": args.seed})
        print(f"budgets written: {path}", file=sys.stderr)
    if args.write_curve:
        path = sim_budgets.write_curve(curve)
        print(f"curve written: {path}", file=sys.stderr)

    if args.check:
        budgets = sim_budgets.load_budgets()
        violations = sim_budgets.check_curve(curve, budgets, mode)
        for v in violations:
            print(f"BUDGET VIOLATION: {v}", file=sys.stderr)
        if violations:
            return 1
        print(f"fleet curve within budget ({mode}, "
              f"{len(curve['points'])} points)", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
