"""Per-schedule collective budgets — the CI regression gate.

``budgets.json`` (checked in next to this module) records, per standard
schedule point, the expected collective op counts and wire-byte
estimate of the compiled train step. The gate fails when a schedule
emits MORE ops of any kind than budgeted, or when estimated traffic
grows past the byte tolerance — i.e. an accidental reshard fails the
build instead of silently costing 4.7x at the next measurement round.

Counts *below* budget pass with a note (a genuine optimization should
be locked in by regenerating: ``python -m polyaxon_tpu.perf
--update-budgets``). Budgets are an artifact of this image's pinned
jax/XLA — regenerate alongside a toolchain bump.
"""

from __future__ import annotations

import json
import os
from typing import Optional

DEFAULT_BUDGET_PATH = os.path.join(os.path.dirname(__file__), "budgets.json")

# Estimated-bytes drift allowed before the gate trips: shape-level
# compiler variation (fusion choices resizing a gathered temp) should
# not fail CI, a doubled all-to-all volume should.
BYTES_TOLERANCE = 0.25


def load_budgets(path: Optional[str] = None) -> dict:
    with open(path or DEFAULT_BUDGET_PATH) as fh:
        return json.load(fh)


def write_budgets(reports: list[dict], path: Optional[str] = None,
                  meta: Optional[dict] = None) -> str:
    out = {"_meta": dict(meta or {})}
    out["_meta"].setdefault("bytes_tolerance", BYTES_TOLERANCE)
    for rep in reports:
        out[rep["name"]] = {
            "counts": rep["counts"],
            "est_wire_bytes_per_step": rep["est_wire_bytes_per_step"],
            "axes": rep["axes"],
            "model": rep["model"],
            "attention": rep["attention"],
            "seq_len": rep["seq_len"],
            "global_batch": rep["global_batch"],
        }
    path = path or DEFAULT_BUDGET_PATH
    with open(path, "w") as fh:
        json.dump(out, fh, indent=2, sort_keys=True)
        fh.write("\n")
    return path


def check_report(report: dict, budgets: dict) -> list[str]:
    """Violations for one point report against the budget table.

    Empty list = within budget. A schedule with no budget entry is
    itself a violation: new schedules must be budgeted the PR they
    land, or the gate silently stops covering them.
    """
    name = report.get("name")
    entry = budgets.get(name)
    if entry is None:
        return [f"{name}: no budget entry (run --update-budgets and "
                f"commit budgets.json)"]
    violations: list[str] = []
    for key in ("axes", "model", "attention", "seq_len", "global_batch"):
        if key in entry and entry[key] != report.get(key):
            violations.append(
                f"{name}: budget was recorded for {key}={entry[key]!r} "
                f"but the audit ran {key}={report.get(key)!r} — "
                f"regenerate budgets for the new point definition")
    if violations:
        return violations

    budget_counts = entry.get("counts", {})
    for kind, count in sorted(report.get("counts", {}).items()):
        allowed = budget_counts.get(kind, 0)
        if count > allowed:
            violations.append(
                f"{name}: {kind} x{count} exceeds budget x{allowed} "
                f"(an unbudgeted reshard?)")
    tol = budgets.get("_meta", {}).get("bytes_tolerance", BYTES_TOLERANCE)
    budget_bytes = entry.get("est_wire_bytes_per_step", 0)
    got = report.get("est_wire_bytes_per_step", 0)
    if budget_bytes and got > budget_bytes * (1 + tol):
        violations.append(
            f"{name}: est wire bytes {got} exceed budget {budget_bytes} "
            f"by more than {tol:.0%}")
    return violations


def check_reports(reports: list[dict],
                  budgets: Optional[dict] = None,
                  path: Optional[str] = None) -> list[str]:
    if budgets is None:
        budgets = load_budgets(path)
    out: list[str] = []
    for rep in reports:
        out.extend(check_report(rep, budgets))
    return out
