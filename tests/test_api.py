"""REST API server + client SDK: the upstream client⇄API boundary
(SURVEY.md §3.1/§3.5) exercised over real HTTP on an ephemeral port,
with the agent reconciling in a background thread."""

import threading
import time

import pytest

from polyaxon_tpu.agent import Agent
from polyaxon_tpu.api import ApiServer
from polyaxon_tpu.client import ApiClientError, PolyaxonClient, RunClient
from polyaxon_tpu.controlplane import ControlPlane
from polyaxon_tpu.lifecycle import V1Statuses

TRIAL = {
    "kind": "component",
    "name": "trial",
    "inputs": [{"name": "lr", "type": "float", "toEnv": "LR"}],
    "run": {
        "kind": "job",
        "container": {"command": [
            "python", "-c",
            "import json, os\n"
            "d = os.environ['POLYAXON_RUN_ARTIFACTS_PATH']\n"
            "os.makedirs(d + '/events/metric', exist_ok=True)\n"
            "print('training with lr', os.environ['LR'])\n"
            "score = (float(os.environ['LR']) - 0.3) ** 2\n"
            "with open(d + '/events/metric/score.jsonl', 'a') as fh:\n"
            "    fh.write(json.dumps({'step': 1, 'value': score}) + '\\n')\n",
        ]},
    },
}


@pytest.fixture(autouse=True)
def _no_ambient_credentials(tmp_path, monkeypatch):
    """Hermetic clients: PolyaxonClient resolves tokens from the env
    and ~/.polyaxon_tpu/config.json — a developer's real credentials
    must never leak into (or break) these assertions."""
    monkeypatch.delenv("POLYAXON_TPU_TOKEN", raising=False)
    monkeypatch.delenv("POLYAXON_TPU_HOST", raising=False)
    import polyaxon_tpu.client.client as client_mod

    monkeypatch.setattr(client_mod, "CONFIG_FILE",
                        str(tmp_path / "no-such-config.json"))


@pytest.fixture()
def stack(tmp_path):
    """plane + HTTP server + background agent thread."""
    plane = ControlPlane(str(tmp_path / "home"))
    agent = Agent(plane, max_concurrent=4)
    stop = threading.Event()

    def loop():
        while not stop.is_set():
            agent.reconcile_once()
            time.sleep(0.05)

    thread = threading.Thread(target=loop, daemon=True)
    thread.start()
    with ApiServer(plane) as server:
        yield plane, server
    stop.set()
    thread.join(timeout=5)


class TestApi:
    def test_health_and_version(self, stack):
        _, server = stack
        client = PolyaxonClient(server.url)
        assert client.healthy()
        from polyaxon_tpu import __version__

        assert client.version() == __version__

    def test_dashboard_served(self, stack):
        import urllib.request

        _, server = stack
        for path in ("/ui", "/"):
            with urllib.request.urlopen(server.url + path, timeout=5) as resp:
                assert resp.headers["Content-Type"].startswith("text/html")
                html = resp.read().decode()
            # Key surface markers: runs table, status filter, chart layer.
            for marker in ("polyaxon_tpu", "statusFilter", "lineChart",
                           "histChart", "imageCard", "EventSource",
                           # r2: multi-run overlay + hyperband brackets
                           "compareBtn", "overlayChart", "sweepView",
                           "cmpBox", "trial_params",
                           # r4: project-level dashboard + compare diff
                           "projectPanel", "success rate",
                           "paramDiffTable"):
                assert marker in html, marker

    def test_run_detail_includes_spec(self, stack):
        """The dashboard's sweep view reads matrix config (metric name)
        from the run-detail payload; list payloads stay lean."""
        import json
        import urllib.request

        plane, server = stack
        record = plane.submit(TRIAL, params={"lr": 0.25})
        base = f"{server.url}/api/v1/default/default/runs"
        with urllib.request.urlopen(f"{base}/{record.uuid}", timeout=5) as r:
            detail = json.loads(r.read())
        # Submission normalizes components into operations.
        assert detail["spec"]["kind"] == "operation"
        with urllib.request.urlopen(base, timeout=5) as r:
            listed = json.loads(r.read())["results"]
        assert all("spec" not in item for item in listed)

    def test_prometheus_metrics(self, stack):
        import urllib.request

        plane, server = stack
        plane.submit({"kind": "component", "run": {
            "kind": "job", "container": {"command": ["python", "-c", "1"]}}})
        with urllib.request.urlopen(server.url + "/metrics", timeout=5) as resp:
            assert resp.headers["Content-Type"].startswith("text/plain")
            text = resp.read().decode()
        assert "polyaxon_tpu_info{version=" in text
        assert 'polyaxon_runs{status="' in text
        assert "polyaxon_uptime_seconds" in text
        # One run exists in some status — the per-status gauges sum to >= 1.
        total = sum(int(line.rsplit(" ", 1)[1])
                    for line in text.splitlines()
                    if line.startswith("polyaxon_runs{"))
        assert total >= 1

    def test_run_end_to_end(self, stack, tmp_path):
        _, server = stack
        run = RunClient(host=server.url)
        data = run.create(TRIAL, params={"lr": 0.5})
        assert data["status"] == "created"
        assert run.wait(timeout=60) == V1Statuses.SUCCEEDED

        metrics = run.get_metrics(["score"])
        assert metrics["score"][-1]["value"] == pytest.approx(0.04)
        assert "training with lr 0.5" in run.get_logs()
        statuses = [s["type"] for s in run.get_statuses()]
        assert "running" in statuses and statuses[-1] == "succeeded"

        arts = run.list_artifacts()
        assert any("score" in a for a in arts)
        rel = next(a for a in arts if "score" in a)
        dest = run.download_artifact(rel, str(tmp_path / "score.jsonl"))
        assert "0.04" in open(dest).read()

    def test_typed_events_endpoint(self, stack):
        """Rich event kinds (histogram here) flow from in-run tracking
        through streams to the /events route and RunClient.get_events."""
        import textwrap

        _, server = stack
        run = RunClient(host=server.url)
        script = textwrap.dedent(
            """
            import os
            from polyaxon_tpu.tracking import Run
            d = os.environ["POLYAXON_RUN_ARTIFACTS_PATH"]
            with Run(os.environ["POLYAXON_RUN_UUID"], d) as r:
                r.log_histogram("w", [1, 1, 2, 3], bins=3, step=1)
                r.log_text("note", "hello")
            """
        ).strip()
        run.create({"kind": "component", "run": {
            "kind": "job", "container": {"command": ["python", "-c", script]}}})
        assert run.wait(timeout=60) == V1Statuses.SUCCEEDED
        hist = run.get_events(kind="histogram")["w"]
        assert sum(hist[0]["counts"]) == 4
        text = run.get_events(kind="text", names=["note"])["note"]
        assert text[0]["text"] == "hello"
        # Unknown kinds and traversal attempts are 400s, not file reads.
        from polyaxon_tpu.client.client import ApiClientError

        for bad in ({"kind": "histgram"},
                    {"kind": "metric", "names": ["../../outputs"]}):
            with pytest.raises(ApiClientError) as err:
                run.get_events(**bad)
            assert err.value.status == 400
        # The guard lives in read_events, so /metrics is covered too.
        with pytest.raises(ApiClientError) as err:
            run.get_metrics(names=["../../outputs"])
        assert err.value.status == 400

    def test_lineage_endpoint(self, stack):
        import textwrap

        _, server = stack
        run = RunClient(host=server.url)
        script = textwrap.dedent(
            """
            import os
            from polyaxon_tpu.tracking import Run
            d = os.environ["POLYAXON_RUN_ARTIFACTS_PATH"]
            with Run(os.environ["POLYAXON_RUN_UUID"], d) as r:
                p = os.path.join(d, "model.bin")
                open(p, "w").write("weights")
                r.log_model(p, name="model.bin")
            """
        ).strip()
        run.create({"kind": "component", "run": {
            "kind": "job", "container": {"command": ["python", "-c", script]}}})
        assert run.wait(timeout=60) == V1Statuses.SUCCEEDED
        lineage = run.get_lineage()
        assert len(lineage) == 1
        assert lineage[0]["name"] == "model.bin"
        assert lineage[0]["kind"] == "model"
        # Browser enrichment: records carry rel_path + size so the
        # dashboard lists and downloads them (VERDICT r2 item 7).
        assert lineage[0]["rel_path"].endswith("model.bin")
        assert lineage[0]["size_bytes"] == len("weights")

    def test_artifact_browser_endpoints(self, stack):
        """The run-detail artifact browser's API surface end-to-end:
        detail listing with sizes, enriched lineage, and inline-
        renderable content types on download."""
        import json as _json
        import textwrap
        import urllib.request

        _, server = stack
        run = RunClient(host=server.url)
        script = textwrap.dedent(
            """
            import os
            from polyaxon_tpu.tracking import Run
            d = os.environ["POLYAXON_RUN_ARTIFACTS_PATH"]
            with Run(os.environ["POLYAXON_RUN_UUID"], d) as r:
                import numpy as np
                r.log_image("sample", np.zeros((4, 4), dtype=np.uint8))
                p = os.path.join(d, "report.html")
                open(p, "w").write("<h1>eval</h1>")
                r.log_artifact(p, name="report.html")
            """
        ).strip()
        record = run.create({"kind": "component", "run": {
            "kind": "job", "container": {"command": ["python", "-c", script]}}})
        assert run.wait(timeout=60) == V1Statuses.SUCCEEDED

        base = f"{server.url}/api/v1/default/default/runs/{record['uuid']}"
        with urllib.request.urlopen(base + "/artifacts?detail=1",
                                    timeout=10) as r:
            files = _json.load(r)
        by_path = {f["path"]: f["size_bytes"] for f in files}
        assert all(isinstance(s, int) and s >= 0 for s in by_path.values())
        png = next(p for p in by_path if p.endswith(".png"))
        assert by_path[png] > 0

        with urllib.request.urlopen(base + "/lineage", timeout=10) as r:
            lineage = _json.load(r)
        html_rec = next(rec for rec in lineage
                        if rec["name"] == "report.html")
        assert html_rec["size_bytes"] > 0

        # Inline rendering depends on real content types.
        assert html_rec["is_dir"] is False
        with urllib.request.urlopen(
                base + "/artifacts/" + html_rec["rel_path"],
                timeout=10) as r:
            assert r.headers["Content-Type"].startswith("text/html")
            # Stored-XSS guard: run-produced html renders sandboxed
            # (no scripts, no same-origin API credentials).
            assert r.headers["Content-Security-Policy"] == "sandbox"
            assert b"eval" in r.read()
        with urllib.request.urlopen(base + "/artifacts/" + png,
                                    timeout=10) as r:
            assert r.headers["Content-Type"] == "image/png"

        # The dashboard page ships the browser panel.
        with urllib.request.urlopen(f"{server.url}/ui", timeout=10) as r:
            page = r.read().decode()
        assert "artifactsPanel" in page and "artifacts?detail=1" in page

    def test_lineage_graph_endpoint(self, stack):
        """VERDICT r4 item 7: the cross-run lineage graph surface —
        a run consuming another run's output via a `runs.<uuid>` param
        ref appears as an upstream edge of the consumer AND a
        downstream edge of the producer; artifact records ride along;
        the dashboard ships the renderer."""
        import json as _json
        import textwrap
        import urllib.request

        _, server = stack
        producer = RunClient(host=server.url)
        script = textwrap.dedent(
            """
            import os
            from polyaxon_tpu.tracking import Run
            d = os.environ["POLYAXON_RUN_ARTIFACTS_PATH"]
            with Run(os.environ["POLYAXON_RUN_UUID"], d) as r:
                p = os.path.join(d, "model.bin")
                open(p, "w").write("weights")
                r.log_model(p, name="model.bin")
                r.log_outputs(accuracy=0.9)
            """
        ).strip()
        prod = producer.create({"kind": "component", "name": "producer",
                                "run": {"kind": "job", "container": {
                                    "command": ["python", "-c", script]}}})
        assert producer.wait(timeout=60) == V1Statuses.SUCCEEDED

        consumer = RunClient(host=server.url)
        cons = consumer.create({
            "kind": "operation",
            "name": "consumer",
            "params": {"acc": {"ref": f"runs.{prod['uuid']}",
                               "value": "outputs.accuracy"}},
            "component": {
                "inputs": [{"name": "acc", "type": "float",
                            "isOptional": True, "value": 0.0}],
                "run": {"kind": "job", "container": {
                    "command": ["python", "-c", "print('ok')"]}},
            },
        })
        consumer.wait(timeout=60)

        base = f"{server.url}/api/v1/default/default/runs"
        with urllib.request.urlopen(
                f"{base}/{cons['uuid']}/lineage/graph", timeout=10) as r:
            graph = _json.load(r)
        uuids = {n["uuid"] for n in graph["nodes"]}
        assert {prod["uuid"], cons["uuid"]} <= uuids
        edge = next(e for e in graph["edges"] if e["from"] == prod["uuid"])
        assert edge["to"] == cons["uuid"]
        assert edge["kind"] == "param" and edge["label"] == "acc"

        # The same edge from the producer's side is downstream.
        with urllib.request.urlopen(
                f"{base}/{prod['uuid']}/lineage/graph", timeout=10) as r:
            pgraph = _json.load(r)
        assert any(e["from"] == prod["uuid"] and e["to"] == cons["uuid"]
                   for e in pgraph["edges"])
        # Producer's own artifacts/outputs are the terminal nodes.
        assert any(a.get("name") == "model.bin"
                   for a in pgraph["artifacts"])
        assert pgraph["outputs"].get("accuracy") == 0.9

        # The dashboard ships the graph renderer + iframe inline render.
        with urllib.request.urlopen(f"{server.url}/ui", timeout=10) as r:
            page = r.read().decode()
        assert "lineageGraphPanel" in page and "lineage/graph" in page
        assert "<iframe" in page and "stream-token" in page

    def test_dag_view_data_surface(self, stack):
        """Everything the dashboard's pipeline graph consumes: run-detail
        spec carries the dag operations + dependencies, the pipeline
        filter lists the children by operation name, and the page ships
        the dagView renderer."""
        import json as _json
        import urllib.request

        _, server = stack
        run = RunClient(host=server.url)
        ok = {"kind": "job",
              "container": {"command": ["python", "-c", "print('ok')"]}}
        record = run.create({
            "kind": "component", "name": "pipe",
            "run": {"kind": "dag", "operations": [
                {"name": "a", "component": {"run": ok}},
                {"name": "b", "dependencies": ["a"],
                 "component": {"run": ok}},
            ]},
        })
        assert run.wait(timeout=120) == V1Statuses.SUCCEEDED

        base = f"{server.url}/api/v1/default/default/runs"
        with urllib.request.urlopen(f"{base}/{record['uuid']}",
                                    timeout=10) as r:
            detail = _json.load(r)
        assert detail["kind"] == "dag"
        ops = detail["spec"]["component"]["run"]["operations"]
        assert [o["name"] for o in ops] == ["a", "b"]
        assert ops[1]["dependencies"] == ["a"]

        with urllib.request.urlopen(
                f"{base}?pipeline={record['uuid']}", timeout=10) as r:
            children = _json.load(r)["results"]
        assert {c["name"] for c in children} == {"a", "b"}
        assert all(c["status"] == "succeeded" for c in children)

        with urllib.request.urlopen(f"{server.url}/ui", timeout=10) as r:
            page = r.read().decode()
        assert "dagView" in page and "dagnode" in page

    def test_list_runs_and_filters(self, stack):
        _, server = stack
        client = PolyaxonClient(server.url)
        run = RunClient(host=server.url, client=client)
        run.create(TRIAL, params={"lr": 0.1}, tags=["t1"])
        run.wait(timeout=60)
        runs = client.list_runs()
        assert any(r["uuid"] == run.run_uuid for r in runs)
        done = client.list_runs(status="succeeded")
        assert any(r["uuid"] == run.run_uuid for r in done)
        assert client.list_runs(status="failed") == []

    def test_stop_and_restart(self, stack):
        _, server = stack
        slow = {
            "kind": "component",
            "run": {"kind": "job", "container": {"command": [
                "python", "-c", "import time; time.sleep(30)"]}},
        }
        run = RunClient(host=server.url)
        run.create(slow)
        deadline = time.monotonic() + 20
        while run.status != V1Statuses.RUNNING:
            assert time.monotonic() < deadline
            time.sleep(0.1)
        run.stop()
        assert run.wait(timeout=30) == V1Statuses.STOPPED

        restarted = run.restart()
        assert restarted.run_uuid != run.run_uuid
        restarted.stop()

    def test_watch_logs_sse(self, stack):
        _, server = stack
        chatty = {
            "kind": "component",
            "run": {"kind": "job", "container": {"command": [
                "python", "-u", "-c",
                "import time\n"
                "for i in range(5):\n"
                "    print('line', i, flush=True)\n"
                "    time.sleep(0.2)\n",
            ]}},
        }
        run = RunClient(host=server.url)
        run.create(chatty)
        deadline = time.monotonic() + 20
        while run.status in (V1Statuses.CREATED, V1Statuses.COMPILED,
                             V1Statuses.QUEUED, V1Statuses.SCHEDULED,
                             V1Statuses.STARTING):
            assert time.monotonic() < deadline
            time.sleep(0.1)
        lines = list(run.watch_logs())
        assert any("line 4" in line for line in lines)
        assert run.wait(timeout=30) == V1Statuses.SUCCEEDED

    def test_errors_are_typed(self, stack):
        _, server = stack
        client = PolyaxonClient(server.url)
        with pytest.raises(ApiClientError) as err:
            client.get("/api/v1/default/default/runs/nope-nope")
        assert err.value.status == 404
        with pytest.raises(ApiClientError) as err:
            client.post("/api/v1/default/default/runs", body={"content": {"bad": 1}})
        assert err.value.status == 400
        bad_host = PolyaxonClient("http://127.0.0.1:1")
        assert not bad_host.healthy()

    def test_watch_logs_on_finished_run_still_yields(self, stack):
        """SSE contract holds even when the run finished before follow."""
        run = RunClient(host=stack[1].url)
        run.create(TRIAL, params={"lr": 0.2})
        assert run.wait(timeout=60) == V1Statuses.SUCCEEDED
        lines = list(run.watch_logs())
        assert any("training with lr 0.2" in line for line in lines)

    def test_artifact_with_space_roundtrips(self, stack, tmp_path):
        plane, server = stack
        run = RunClient(host=server.url)
        run.create(TRIAL, params={"lr": 0.3})
        assert run.wait(timeout=60) == V1Statuses.SUCCEEDED
        art_dir = plane.run_artifacts_dir(run.run_uuid)
        with open(f"{art_dir}/my report.txt", "w") as fh:
            fh.write("spaced")
        assert "my report.txt" in run.list_artifacts()
        dest = run.download_artifact("my report.txt", str(tmp_path / "r.txt"))
        assert open(dest).read() == "spaced"


class TestSlicePoolApi:
    def test_agent_slices_endpoint_and_panel(self, tmp_path):
        """The C++ pool's operator view over the API: slice capacity
        drops while a gang is placed, recovers on release; the
        dashboard ships the panel; servers without a manager answer
        empty instead of 404."""
        import json as _json
        import urllib.request

        from polyaxon_tpu.agent import SliceManager

        plane = ControlPlane(str(tmp_path / "home"))
        manager = SliceManager([("pool0", "2x4", False),
                                ("spot0", "2x2", True)])
        try:
            with ApiServer(plane, slice_manager=manager) as server:
                state = manager.ensure_placed("run-a", "2x2")
                assert state == "running"
                with urllib.request.urlopen(
                        server.url + "/api/v1/agent/slices", timeout=10) as r:
                    data = _json.load(r)
                names = {s["name"]: s for s in data["slices"]}
                assert names["pool0"]["total_chips"] == 8
                assert names["spot0"]["preemptible"] is True
                placed_free = sum(s["free_chips"] for s in data["slices"])
                assert placed_free == 8 + 4 - 4
                gangs = {g["run_uuid"]: g for g in data["gangs"]}
                assert gangs["run-a"]["state"] == "running"
                assert gangs["run-a"]["chips"] == 4

                manager.release("run-a")
                with urllib.request.urlopen(
                        server.url + "/api/v1/agent/slices", timeout=10) as r:
                    after = _json.load(r)
                assert sum(s["free_chips"] for s in after["slices"]) == 12

                with urllib.request.urlopen(server.url + "/ui",
                                            timeout=10) as r:
                    page = r.read().decode()
                assert "slicesPanel" in page and "agent/slices" in page
        finally:
            manager.close()

        # No manager: the route answers empty, not 404.
        with ApiServer(plane) as server:
            with urllib.request.urlopen(
                    server.url + "/api/v1/agent/slices", timeout=10) as r:
                assert _json.load(r) == {"slices": [], "gangs": []}


class TestRunFilters:
    def test_project_scoped_lists_and_search_surface(self, stack):
        """The dashboard's project dropdown + search box: projects
        endpoint lists every project, the list route scopes by its
        path project, and the page ships both controls."""
        import json as _json
        import urllib.request

        plane, server = stack
        plane.submit(TRIAL, params={"lr": 0.1})
        plane.submit(TRIAL, params={"lr": 0.2}, project="research")

        with urllib.request.urlopen(server.url + "/api/v1/projects",
                                    timeout=10) as r:
            names = {p["name"] for p in _json.load(r)}
        assert {"default", "research"} <= names

        for project, expected in (("default", 1), ("research", 1)):
            with urllib.request.urlopen(
                    f"{server.url}/api/v1/default/{project}/runs",
                    timeout=10) as r:
                listed = _json.load(r)["results"]
            assert len(listed) == expected
            assert all(item["project"] == project for item in listed)

        with urllib.request.urlopen(server.url + "/ui", timeout=10) as r:
            page = r.read().decode()
        assert "searchBox" in page and "projectFilter" in page


@pytest.fixture()
def auth_stack(tmp_path):
    """plane + auth-enabled server + background agent (VERDICT r3 #6:
    shared-secret admin token + per-owner scoped tokens)."""
    plane = ControlPlane(str(tmp_path / "home"))
    agent = Agent(plane, max_concurrent=4)
    stop = threading.Event()

    def loop():
        while not stop.is_set():
            agent.reconcile_once()
            time.sleep(0.05)

    thread = threading.Thread(target=loop, daemon=True)
    thread.start()
    with ApiServer(plane, auth_token="admin-secret",
                   owner_tokens={"alice": "tk-alice",
                                 "bob": "tk-bob"}) as server:
        yield plane, server
    stop.set()
    thread.join(timeout=5)


class TestAuth:
    """Bearer-token auth + per-owner isolation (haupt-CE scope)."""

    def test_anonymous_401_on_data_routes(self, auth_stack):
        _, server = auth_stack
        client = PolyaxonClient(server.url, owner="alice")
        assert client.token is None
        with pytest.raises(ApiClientError) as exc:
            client.list_runs()
        assert exc.value.status == 401
        with pytest.raises(ApiClientError) as exc:
            client.post(f"/api/v1/alice/default/runs", body={"content": TRIAL})
        assert exc.value.status == 401

    def test_open_routes_stay_open(self, auth_stack):
        _, server = auth_stack
        client = PolyaxonClient(server.url)
        assert client.healthy()
        assert client.version()

    def test_invalid_token_401(self, auth_stack):
        _, server = auth_stack
        client = PolyaxonClient(server.url, owner="alice", token="wrong")
        with pytest.raises(ApiClientError) as exc:
            client.list_runs()
        assert exc.value.status == 401

    def test_primary_token_shaped_like_stream_token(self, tmp_path):
        """ADVICE r5: a PRIMARY token that happens to start with `st:`
        and carry ≥3 colons used to be routed unconditionally into
        stream-token verification on ?token= routes and always 401 —
        locking that credential out of SSE/artifact loads. Verification
        failure now falls back to the primary comparison."""
        import urllib.error
        import urllib.parse
        import urllib.request

        weird = "st:alice:12345:not-an-hmac"
        plane = ControlPlane(str(tmp_path / "home"))
        with ApiServer(plane, owner_tokens={"alice": weird}) as server:
            alice = PolyaxonClient(server.url, owner="alice", token=weird)
            mine = alice.post("/api/v1/alice/default/runs",
                              body={"content": TRIAL,
                                    "params": {"lr": 0.1}})
            logs = (f"{server.url}/streams/v1/alice/default/runs/"
                    f"{mine['uuid']}/logs")
            quoted = urllib.parse.quote(weird, safe="")
            with urllib.request.urlopen(f"{logs}?token={quoted}",
                                        timeout=10) as r:
                assert r.status == 200
            # Tokens that match NEITHER a valid stream token NOR a
            # primary still 401 through the fallback.
            bad = urllib.parse.quote("st:alice:12345:wrong-sig-too",
                                     safe="")
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(f"{logs}?token={bad}", timeout=10)
            assert err.value.code == 401

    def test_admin_token_full_access(self, auth_stack):
        _, server = auth_stack
        admin = PolyaxonClient(server.url, owner="anyone",
                               token="admin-secret")
        created = admin.post("/api/v1/anyone/default/runs",
                             body={"content": TRIAL,
                                   "params": {"lr": 0.1}})
        assert created["uuid"]
        assert admin.list_runs()
        assert admin.list_projects()

    def test_owner_scoping_on_list_and_mutate(self, auth_stack):
        _, server = auth_stack
        alice = PolyaxonClient(server.url, owner="alice", token="tk-alice")
        bob = PolyaxonClient(server.url, owner="bob", token="tk-bob")

        mine = alice.post("/api/v1/alice/default/runs",
                          body={"content": TRIAL, "params": {"lr": 0.1}})
        # Path scoping: bob's token cannot touch alice's path at all.
        with pytest.raises(ApiClientError) as exc:
            bob.get("/api/v1/alice/default/runs")
        assert exc.value.status == 403
        # Record scoping: alice's run uuid under bob's OWN path is
        # still refused (path spoofing).
        with pytest.raises(ApiClientError) as exc:
            bob.get(f"/api/v1/bob/default/runs/{mine['uuid']}")
        assert exc.value.status == 403
        with pytest.raises(ApiClientError) as exc:
            bob.post(f"/api/v1/bob/default/runs/{mine['uuid']}/stop", body={})
        assert exc.value.status == 403
        # List isolation: bob sees none of alice's runs.
        assert bob.list_runs() == []
        assert [r["uuid"] for r in alice.list_runs()] == [mine["uuid"]]
        # The owner can read and mutate their own run.
        assert alice.get(
            f"/api/v1/alice/default/runs/{mine['uuid']}")["uuid"] == mine["uuid"]
        alice.post(f"/api/v1/alice/default/runs/{mine['uuid']}/stop", body={})

    def test_scoped_token_cannot_list_projects(self, auth_stack):
        _, server = auth_stack
        alice = PolyaxonClient(server.url, owner="alice", token="tk-alice")
        with pytest.raises(ApiClientError) as exc:
            alice.list_projects()
        assert exc.value.status == 403

    def test_sweep_children_inherit_owner(self, auth_stack):
        """Matrix trials spawned by the scheduler stay visible to the
        owner who submitted the sweep (meta.owner inheritance)."""
        _, server = auth_stack
        alice = PolyaxonClient(server.url, owner="alice", token="tk-alice")
        sweep = {
            "kind": "operation",
            "name": "sweep",
            "matrix": {
                "kind": "grid",
                "concurrency": 2,
                "params": {"lr": {"kind": "choice", "value": [0.1, 0.2]}},
            },
            "component": TRIAL,
        }
        parent = alice.post("/api/v1/alice/default/runs",
                            body={"content": sweep})
        deadline = time.time() + 60
        while time.time() < deadline:
            children = alice.get(
                f"/api/v1/alice/default/runs?pipeline={parent['uuid']}"
            )["results"]
            if len(children) == 2:
                break
            time.sleep(0.2)
        assert len(children) == 2, "sweep children not visible to owner"
        bob = PolyaxonClient(server.url, owner="bob", token="tk-bob")
        assert bob.list_runs() == []

    def test_sse_query_token_on_logs_route_only(self, auth_stack):
        """EventSource cannot set headers, so the SSE log route (and
        only it) accepts ?token=; every other route ignores the query
        credential and still requires the header."""
        import urllib.error
        import urllib.request

        _, server = auth_stack
        alice = PolyaxonClient(server.url, owner="alice", token="tk-alice")
        mine = alice.post("/api/v1/alice/default/runs",
                          body={"content": TRIAL, "params": {"lr": 0.1}})
        base = (f"{server.url}/streams/v1/alice/default/runs/"
                f"{mine['uuid']}/logs")
        with urllib.request.urlopen(f"{base}?token=tk-alice",
                                    timeout=10) as r:
            assert r.status == 200
        for url, code in (
                (f"{base}?token=wrong", 401),
                (f"{base}?token=tk-bob", 403),  # valid token, not alice
                (f"{server.url}/api/v1/alice/default/runs?token=tk-alice",
                 401),  # non-SSE routes never read the query credential
        ):
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(url, timeout=10)
            assert err.value.code == code, url

    def test_artifact_files_accept_query_token(self, auth_stack):
        """<img src>/<a href> loads cannot set headers either: artifact
        FILE reads accept ?token=; the artifacts LISTING (an api()
        fetch) still requires the header."""
        import urllib.error
        import urllib.request

        _, server = auth_stack
        alice = PolyaxonClient(server.url, owner="alice", token="tk-alice")
        mine = alice.post("/api/v1/alice/default/runs",
                          body={"content": TRIAL, "params": {"lr": 0.3}})
        deadline = time.time() + 60
        while time.time() < deadline:
            arts = alice.get(
                f"/api/v1/alice/default/runs/{mine['uuid']}/artifacts")
            if any("score" in a for a in arts):
                break
            time.sleep(0.2)
        rel = next(a for a in arts if "score" in a)
        url = (f"{server.url}/api/v1/alice/default/runs/{mine['uuid']}"
               f"/artifacts/{rel}")
        with urllib.request.urlopen(f"{url}?token=tk-alice",
                                    timeout=10) as r:
            assert b"value" in r.read()
        for bad, code in ((f"{url}?token=wrong", 401),
                          (f"{url}?token=tk-bob", 403),
                          (f"{url}", 401)):
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(bad, timeout=10)
            assert err.value.code == code, bad
        # The listing route ignores the query credential.
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(
                f"{server.url}/api/v1/alice/default/runs/{mine['uuid']}"
                f"/artifacts?token=tk-alice", timeout=10)
        assert err.value.code == 401

    def test_stream_token_mint_and_use(self, auth_stack):
        """ADVICE r4 #3: browser ?token= URLs should carry a short-lived
        DERIVED credential, not the primary secret. The mint route is
        header-auth-only; the derived token works on the SSE and
        artifact-file routes with the minter's scope; tampered or
        expired tokens are 401."""
        import urllib.error
        import urllib.request

        _, server = auth_stack
        alice = PolyaxonClient(server.url, owner="alice", token="tk-alice")
        minted = alice.get("/api/v1/stream-token")
        tok = minted["token"]
        assert tok.startswith("st:alice:") and minted["expiresIn"] > 0
        assert "tk-alice" not in tok, "derived token embeds the secret"

        mine = alice.post("/api/v1/alice/default/runs",
                          body={"content": TRIAL, "params": {"lr": 0.1}})
        logs = (f"{server.url}/streams/v1/alice/default/runs/"
                f"{mine['uuid']}/logs")
        quoted = urllib.parse.quote(tok, safe="")
        with urllib.request.urlopen(f"{logs}?token={quoted}",
                                    timeout=10) as r:
            assert r.status == 200
        # Scope rides along: alice's stream token is still alice.
        bob_logs = logs.replace("/alice/", "/bob/")
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(f"{bob_logs}?token={quoted}", timeout=10)
        assert err.value.code == 403
        # Tampered signature → 401.
        bad = urllib.parse.quote(tok[:-4] + "0000", safe="")
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(f"{logs}?token={bad}", timeout=10)
        assert err.value.code == 401
        # Expired → 401 (forge the same HMAC with a past timestamp is
        # impossible; simulate by minting with a past exp via the
        # server's own key material).
        import hmac as _hmac
        import time as _time

        past = int(_time.time()) - 10
        msg = f"st:alice:{past}"
        sig = _hmac.new(b"tk-alice", msg.encode(), "sha256").hexdigest()
        expired = urllib.parse.quote(f"{msg}:{sig}", safe="")
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(f"{logs}?token={expired}", timeout=10)
        assert err.value.code == 401
        # A stream token in the HEADER cannot mint another one.
        with pytest.raises(ApiClientError) as exc:
            PolyaxonClient(server.url, owner="alice",
                           token=tok).get("/api/v1/stream-token")
        assert exc.value.status == 401
        # Anonymous mint is refused.
        with pytest.raises(ApiClientError) as exc:
            PolyaxonClient(server.url).get("/api/v1/stream-token")
        assert exc.value.status == 401

    def test_logs_route_scoped(self, auth_stack):
        _, server = auth_stack
        alice = PolyaxonClient(server.url, owner="alice", token="tk-alice")
        mine = alice.post("/api/v1/alice/default/runs",
                          body={"content": TRIAL, "params": {"lr": 0.1}})
        with pytest.raises(ApiClientError) as exc:
            PolyaxonClient(server.url, token="tk-bob").get(
                f"/streams/v1/bob/default/runs/{mine['uuid']}/logs")
        assert exc.value.status == 403
        # Owner reads own logs (may be empty while queued).
        alice.get(f"/streams/v1/alice/default/runs/{mine['uuid']}/logs")

    def test_config_token_paired_with_config_host(self, tmp_path, monkeypatch):
        """A config-file credential must not be disclosed to a server
        the config does not name (review: credential-leak guard)."""
        import json as _json

        import polyaxon_tpu.client.client as client_mod

        cfg = tmp_path / "config.json"
        cfg.write_text(_json.dumps(
            {"host": "http://trusted:8000", "token": "secret"}))
        monkeypatch.setattr(client_mod, "CONFIG_FILE", str(cfg))
        assert PolyaxonClient("http://trusted:8000").token == "secret"
        assert PolyaxonClient("http://other:9000").token is None
        # Explicit + env tokens stay unconditional (deliberate choice).
        assert PolyaxonClient("http://other:9000", token="t2").token == "t2"
        monkeypatch.setenv("POLYAXON_TPU_TOKEN", "t3")
        assert PolyaxonClient("http://other:9000").token == "t3"
