"""Disaggregated prefill/decode (ISSUE 18): the speculation-policy
state machine, PagePool handoff accounting, and engine-level lane
behavior — output parity with the interleaved scheduler, page handoffs
actually happening, per-lane health fields, and the lane-starve knob
(a zeroed decode budget must starve, and restoring it must drain)."""

import pytest

from polyaxon_tpu.serving.paged import PagePool
from polyaxon_tpu.serving.speculative import LaneView, SpeculationPolicy


class TestSpeculationPolicy:
    """Pure state machine — no jax, no engine."""

    def test_idle_headroom_speculates_at_k_max(self):
        policy = SpeculationPolicy(4)
        assert policy.draft_len(LaneView(prefill_backlog=0,
                                         decode_free=2)) == 4
        assert policy.state == "speculate"

    def test_backlog_throttles_draft_len(self):
        policy = SpeculationPolicy(4, k_min=2)
        assert policy.draft_len(LaneView(prefill_backlog=1,
                                         decode_free=1)) == 3
        assert policy.state == "throttled"
        # Deep (but sub-off) backlog clamps at k_min, never below.
        policy2 = SpeculationPolicy(4, k_min=2, off_backlog=10)
        assert policy2.draft_len(LaneView(prefill_backlog=9,
                                          decode_free=1)) == 2
        assert policy2.state == "throttled"

    def test_full_decode_lane_throttles_even_without_backlog(self):
        policy = SpeculationPolicy(4)
        assert policy.draft_len(LaneView(prefill_backlog=0,
                                         decode_free=0)) == 4
        assert policy.state == "throttled"

    def test_off_at_backlog_threshold(self):
        policy = SpeculationPolicy(4, off_backlog=3)
        assert policy.draft_len(LaneView(prefill_backlog=3,
                                         decode_free=2)) == 0
        assert policy.state == "off"

    def test_off_when_ttft_budget_burning(self):
        policy = SpeculationPolicy(4, ttft_budget=0.5)
        assert policy.draft_len(LaneView(prefill_backlog=0,
                                         decode_free=2,
                                         oldest_wait=0.6)) == 0
        assert policy.state == "off"

    def test_recovers_when_pressure_clears(self):
        policy = SpeculationPolicy(4, off_backlog=2)
        policy.draft_len(LaneView(prefill_backlog=2))
        assert policy.state == "off"
        policy.draft_len(LaneView(prefill_backlog=1, decode_free=1))
        assert policy.state == "throttled"
        assert policy.draft_len(LaneView(decode_free=2)) == 4
        assert policy.state == "speculate"

    def test_constructor_validation(self):
        with pytest.raises(ValueError, match="k_max"):
            SpeculationPolicy(0)
        with pytest.raises(ValueError, match="k_min"):
            SpeculationPolicy(2, k_min=3)
        with pytest.raises(ValueError, match="k_min"):
            SpeculationPolicy(2, k_min=0)
        with pytest.raises(ValueError, match="off_backlog"):
            SpeculationPolicy(2, off_backlog=0)
        with pytest.raises(ValueError, match="ttft_budget"):
            SpeculationPolicy(2, ttft_budget=0)


class TestHandoffAccounting:
    """PagePool.handoff is pure bookkeeping: the block-table row and
    the fresh-leaf marker move, refcounts/invariants hold, and release
    semantics follow the pages to their new row."""

    def _pool(self):
        # 4 rows: 0..1 "decode", 2..3 "lane" (the pool itself is
        # lane-agnostic — the engine's convention is rows >= slots).
        return PagePool(slots=4, max_len=32, page_size=4, n_pages=17)

    def test_row_and_refcounts_move(self):
        pool = self._pool()
        tokens = list(range(10))
        res = pool.admit(2, len(tokens), tokens)
        assert res is not None
        src_pages = [int(p) for p in pool.tables[2] if p >= 0]
        free_before = pool.free_pages
        moved = pool.handoff(2, 0)
        assert moved == len(src_pages)
        assert [int(p) for p in pool.tables[0] if p >= 0] == src_pages
        assert (pool.tables[2] < 0).all()
        # Pure ownership transfer: nothing allocated, nothing freed.
        assert pool.free_pages == free_before
        assert pool.check_invariants() == []

    def test_fresh_leaf_follows_the_handoff(self):
        pool = self._pool()
        tokens = list(range(12))
        pool.admit(2, len(tokens), tokens)
        assert 2 in pool._fresh_leaf
        pool.handoff(2, 1)
        assert 2 not in pool._fresh_leaf and 1 in pool._fresh_leaf
        # A failed prefill detected AFTER handoff must still be able
        # to forget exactly its own fresh leaf via the new row.
        pool.release(1, invalidate_prefix=True)
        assert pool.check_invariants() == []
        # The invalidated chain is gone: a re-admission of the same
        # prompt matches nothing.
        assert pool.peek_matched_tokens(len(tokens), tokens) == 0

    def test_release_after_handoff_frees_everything(self):
        pool = self._pool()
        free0 = pool.free_pages
        tokens = list(range(10))
        pool.admit(3, len(tokens), tokens)
        pool.handoff(3, 0)
        pool.commit_prefix(0)
        pool.release(0)
        # Shareable prefix pages stay resident in the tree but count
        # as reclaimable, so the allocatable total is fully restored.
        assert pool.free_pages == free0
        assert pool.check_invariants() == []

    def test_handoff_into_occupied_row_asserts(self):
        pool = self._pool()
        pool.admit(2, 6, list(range(6)))
        pool.admit(0, 6, list(range(100, 106)))
        with pytest.raises(AssertionError, match="still holds pages"):
            pool.handoff(2, 0)


class TestDisaggregatedEngine:
    """Engine-level: the lane scheduler must be output-invisible
    (greedy parity with the interleaved engine) while actually moving
    pages prefill→decode, and the per-lane health/stat surfaces must
    report it."""

    def _params(self):
        from polyaxon_tpu.serving.server import load_params
        return load_params("llama_tiny", seed=0)

    def test_parity_handoffs_and_health(self):
        from polyaxon_tpu.serving.batching import ContinuousBatchingEngine

        cfg, params = self._params()
        prompts = [[5, 6, 7, 8, 9, 10, 11, 12, 13],
                   [1, 2, 3],
                   [7, 3, 9, 11, 2, 4, 6, 8, 10, 12, 1, 5],
                   [42, 43, 44, 45]]
        plain = ContinuousBatchingEngine("llama_tiny", cfg, params,
                                         slots=2, kv="paged",
                                         page_size=4)
        try:
            want = [plain.submit(p, 6).wait(timeout=300)
                    for p in prompts]
        finally:
            plain.stop()
        engine = ContinuousBatchingEngine(
            "llama_tiny", cfg, params, slots=2, kv="paged",
            page_size=4, prefill_slots=2, prefill_chunk=8,
            prefill_lane_budget=2, decode_lane_budget=2)
        try:
            got = [r.wait(timeout=300)
                   for r in [engine.submit(p, 6) for p in prompts]]
            health = engine.health()
            stats = engine.stats()
        finally:
            engine.stop()
        assert got == want
        assert stats["handoffs"] == len(prompts)
        assert stats["handoff_pages"] > 0
        assert stats["kv_invariant_violations"] == 0
        assert stats["prefill_slots"] == 2
        # The router/autoscaler surface: per-lane depths + the
        # speculation observable (None — no draft engine here).
        assert health["prefill_pending"] == 0
        assert health["decode_active"] == 0
        assert health["spec_tokens_accepted_rate"] is None

    def test_lane_starve_and_recover(self):
        from polyaxon_tpu.serving.batching import ContinuousBatchingEngine

        cfg, params = self._params()
        engine = ContinuousBatchingEngine(
            "llama_tiny", cfg, params, slots=2, kv="paged",
            page_size=4, prefill_slots=2, prefill_chunk=8,
            decode_lane_budget=0)
        try:
            req = engine.submit([5, 6, 7, 8, 9], 4)
            # Prefill + handoff happen, but with a zeroed decode
            # budget the live row never steps: no tokens, ever.
            with pytest.raises(TimeoutError):
                req.wait(timeout=3)
            assert engine.stats()["handoffs"] >= 1
            # Restoring the budget drains the staged work.
            engine.decode_lane_budget = 2
            assert len(req.wait(timeout=300)) == 4
            assert engine.stats()["kv_invariant_violations"] == 0
        finally:
            engine.stop()

    def test_spec_policy_parity_under_forced_states(self):
        """A draft engine whose policy cycles through throttled/off
        draft lengths must still match the plain engine exactly —
        speculation is lossless at EVERY k the policy can emit."""
        from polyaxon_tpu.serving.batching import ContinuousBatchingEngine

        cfg, params = self._params()
        prompts = [[5, 6, 7, 8, 9, 10, 11], [1, 2, 3]]
        plain = ContinuousBatchingEngine("llama_tiny", cfg, params,
                                         slots=2)
        try:
            want = [plain.submit(p, 8).wait(timeout=300)
                    for p in prompts]
        finally:
            plain.stop()

        class CyclingPolicy(SpeculationPolicy):
            """Ignores the lane view; emits 3, 1, 0, 3, 1, 0, ..."""

            def __init__(self):
                super().__init__(3)
                self._i = 0

            def draft_len(self, view):
                k = (3, 1, 0)[self._i % 3]
                self._i += 1
                self.state = "off" if k == 0 else (
                    "speculate" if k == 3 else "throttled")
                return k

        engine = ContinuousBatchingEngine(
            "llama_tiny", cfg, params, slots=2,
            draft=("llama_tiny", cfg, params, 3),
            spec_policy=CyclingPolicy())
        try:
            got = [r.wait(timeout=300)
                   for r in [engine.submit(p, 8) for p in prompts]]
            state = engine.stats()["spec_policy_state"]
        finally:
            engine.stop()
        assert got == want
        assert state in SpeculationPolicy.STATES
