"""Speculative decoding: lossless-greedy guarantee (output == the
target's own greedy sequence, token for token), draft quality only
affecting speed; engine/HTTP integration with silent fallbacks."""

import dataclasses
import json
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from polyaxon_tpu.models import llama
from polyaxon_tpu.serving import ServingServer
from polyaxon_tpu.serving.speculative import generate_speculative


def _cfg():
    return dataclasses.replace(llama.CONFIGS["llama_tiny"],
                               dtype=jnp.float32)


class TestSpeculative:
    def test_lossless_vs_plain_greedy(self):
        """Self-draft (full acceptance) AND an independent random draft
        (low acceptance) both reproduce plain greedy exactly — the
        defining property of the scheme."""
        cfg = _cfg()
        params = llama.init(cfg, jax.random.key(0))["params"]
        indep = llama.init(cfg, jax.random.key(7))["params"]
        prompt = jax.random.randint(jax.random.key(1), (2, 9), 0,
                                    cfg.vocab_size)
        want = np.asarray(llama.generate(cfg, params, prompt,
                                         max_new_tokens=12))
        for draft_params, label in ((params, "self"), (indep, "indep")):
            got = np.asarray(generate_speculative(
                cfg, params, cfg, draft_params, prompt,
                max_new_tokens=12, k=4))
            np.testing.assert_array_equal(got, want, err_msg=label)

    @pytest.mark.parametrize("k", [1, 3, 7])
    def test_k_never_changes_output(self, k):
        cfg = _cfg()
        params = llama.init(cfg, jax.random.key(0))["params"]
        draft = llama.init(cfg, jax.random.key(3))["params"]
        prompt = jnp.asarray([[5, 6, 7, 8, 9]], jnp.int32)
        want = np.asarray(llama.generate(cfg, params, prompt,
                                         max_new_tokens=10))
        got = np.asarray(generate_speculative(
            cfg, params, cfg, draft, prompt, max_new_tokens=10, k=k))
        np.testing.assert_array_equal(got, want)

    def test_self_draft_accepts_everything_every_round(self):
        """A self-draft must sustain FULL acceptance across rounds:
        exactly ceil((max_new-1)/(k+1)) verify rounds. This is the
        regression guard for the draft-KV bonus-position hole — output
        stays lossless with the hole, but acceptance collapses and
        rounds balloon."""
        cfg = _cfg()
        params = llama.init(cfg, jax.random.key(0))["params"]
        prompt = jnp.asarray([[5, 6, 7, 8, 9]], jnp.int32)
        k, max_new = 4, 16
        out, rounds = generate_speculative(
            cfg, params, cfg, params, prompt, max_new_tokens=max_new,
            k=k, return_rounds=True)
        want = np.asarray(llama.generate(cfg, params, prompt,
                                         max_new_tokens=max_new))
        np.testing.assert_array_equal(np.asarray(out), want)
        assert int(rounds) == -(-(max_new - 1) // (k + 1)), int(rounds)

    def test_headroom_validated(self):
        cfg = _cfg()  # max_seq_len 128
        params = llama.init(cfg, jax.random.key(0))["params"]
        prompt = jnp.zeros((1, 100), jnp.int32)
        with pytest.raises(ValueError, match="max_seq_len"):
            generate_speculative(cfg, params, cfg, params, prompt,
                                 max_new_tokens=30, k=4)

    def test_sliding_window_refused_in_chunk(self):
        cfg = dataclasses.replace(_cfg(), sliding_window=8)
        params = llama.init(cfg, jax.random.key(0))["params"]
        cache = {"k": jnp.zeros((2, 1, 32, 2, 16)),
                 "v": jnp.zeros((2, 1, 32, 2, 16))}
        with pytest.raises(ValueError, match="sliding_window"):
            llama.decode_chunk(cfg, params, cache,
                               jnp.zeros((1, 3), jnp.int32),
                               jnp.zeros((1,), jnp.int32))


class TestSpeculativeServing:
    def test_http_greedy_matches_undrafted_server(self):
        """plx serve --draft-model end-to-end: greedy responses equal a
        draft-less server's; sampled requests fall back and still work."""
        def gen(url, payload):
            req = urllib.request.Request(
                url + "/v1/generate", method="POST",
                data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"})
            return json.load(urllib.request.urlopen(req, timeout=300))

        greedy = {"tokens": [[5, 6, 7], [1, 2, 3]], "max_new_tokens": 8}
        sampled = {"tokens": [[5, 6, 7]], "max_new_tokens": 8,
                   "temperature": 0.9, "seed": 3}
        with ServingServer("llama_tiny", seed=0) as plain:
            want = gen(plain.url, greedy)
            want_sampled = gen(plain.url, sampled)
        with ServingServer("llama_tiny", seed=0, draft_model="llama_tiny",
                           spec_k=3) as spec:
            got = gen(spec.url, greedy)
            got_sampled = gen(spec.url, sampled)
        assert got["tokens"] == want["tokens"]
        # Sampled path bypasses speculation but stays bit-stable.
        assert got_sampled["tokens"] == want_sampled["tokens"]

    def test_t5_target_refused(self):
        with pytest.raises(ValueError, match="decode_chunk"):
            ServingServer("t5_tiny", draft_model="t5_tiny")


class TestContinuousSpeculative:
    """Speculative decoding over the slot pool (ragged per-row
    acceptance): each loop iteration is one draft→verify round; every
    live slot emits 1..k+1 tokens capped by its own remaining budget."""

    def _engine(self, draft_seed=0, slots=2, k=3, **kw):
        import jax

        from polyaxon_tpu.models import llama
        from polyaxon_tpu.serving.batching import ContinuousBatchingEngine

        cfg = llama.CONFIGS["llama_tiny"]
        params = llama.init(cfg, jax.random.key(0))["params"]
        draft_params = (params if draft_seed == 0 else
                        llama.init(cfg, jax.random.key(draft_seed))["params"])
        return ContinuousBatchingEngine(
            "llama_tiny", cfg, params, slots=slots,
            draft=("llama_tiny", cfg, draft_params, k), **kw), cfg, params

    def test_lossless_and_ragged_budgets(self):
        """Greedy outputs equal the draft-less continuous engine's,
        across staggered budgets and more requests than slots (retire/
        re-admit churn mid-speculation), for self- and independent
        drafts."""
        import jax

        from polyaxon_tpu.models import llama
        from polyaxon_tpu.serving.batching import ContinuousBatchingEngine

        cfg = llama.CONFIGS["llama_tiny"]
        params = llama.init(cfg, jax.random.key(0))["params"]
        prompts = [[5, 6, 7], [1, 2, 3, 4], [9], [2, 8, 2, 8, 1]]
        budgets = [9, 4, 7, 12]

        plain = ContinuousBatchingEngine("llama_tiny", cfg, params, slots=2)
        try:
            want = [plain.submit(p, n).wait(timeout=300)
                    for p, n in zip(prompts, budgets)]
        finally:
            plain.stop()

        for seed in (0, 7):  # self-draft and independent draft
            engine, _, _ = self._engine(draft_seed=seed, slots=2)
            try:
                reqs = [engine.submit(p, n)
                        for p, n in zip(prompts, budgets)]
                got = [r.wait(timeout=300) for r in reqs]
            finally:
                engine.stop()
            assert got == want, f"draft_seed={seed} diverged"
            assert [len(o) for o in got] == budgets

    def test_self_draft_emits_multiple_per_round(self):
        """Efficiency observable: a self-draft accepts (nearly)
        everything, so mean tokens/round must clearly beat 1 — the
        whole point of speculating."""
        engine, _, _ = self._engine(draft_seed=0, slots=2, k=3)
        try:
            engine.submit([5, 6, 7], 12).wait(timeout=300)
            stats = engine.stats()
        finally:
            engine.stop()
        assert stats["spec_rounds"] >= 1
        assert stats["spec_tokens_per_round"] > 1.5, stats
        assert stats["draft_model"] == "llama_tiny"

    def test_moe_target_with_dense_draft(self):
        """Mixtral-style continuous target speculated by a dense llama
        draft (the realistic pairing): lossless vs the plain
        continuous engine."""
        import jax

        from polyaxon_tpu.models import llama, moe
        from polyaxon_tpu.serving.batching import ContinuousBatchingEngine

        cfg = moe.CONFIGS["moe_tiny"]
        params = moe.init(cfg, jax.random.key(0))["params"]
        dcfg = llama.CONFIGS["llama_tiny"]
        dparams = llama.init(dcfg, jax.random.key(1))["params"]
        prompts = [[5, 6, 7], [1, 2, 3, 4]]

        plain = ContinuousBatchingEngine("moe_tiny", cfg, params, slots=2)
        try:
            want = [plain.submit(p, 7).wait(timeout=300) for p in prompts]
        finally:
            plain.stop()
        engine = ContinuousBatchingEngine(
            "moe_tiny", cfg, params, slots=2,
            draft=("llama_tiny", dcfg, dparams, 3))
        try:
            got = [r.wait(timeout=300)
                   for r in [engine.submit(p, 7) for p in prompts]]
        finally:
            engine.stop()
        assert got == want

    def test_sampled_request_refused(self):
        engine, _, _ = self._engine()
        try:
            with pytest.raises(ValueError, match="greedy-only"):
                engine.submit([5, 6, 7], 4, temperature=0.8)
        finally:
            engine.stop()

    def test_headroom_validated(self):
        import jax

        from polyaxon_tpu.models import llama
        from polyaxon_tpu.serving.batching import ContinuousBatchingEngine

        cfg = llama.CONFIGS["llama_tiny"]
        params = llama.init(cfg, jax.random.key(0))["params"]
        engine = ContinuousBatchingEngine(
            "llama_tiny", cfg, params, slots=1,
            draft=("llama_tiny", cfg, params, 4))
        try:
            # Passes the family's own prompt+max_new bound but leaves
            # no room for the k+1 verify window — only the NEW
            # speculative-headroom branch can reject it.
            fits_plain = cfg.max_seq_len - 8
            with pytest.raises(ValueError, match="draft window"):
                engine.submit([1] * 8, fits_plain)
            # With the window accounted for, the same request shape
            # admits fine.
            engine.submit([1] * 8, fits_plain - 5).wait(timeout=300)
        finally:
            engine.stop()

    def test_seq2seq_draft_refused(self):
        import jax

        from polyaxon_tpu.models import llama, t5
        from polyaxon_tpu.serving.batching import ContinuousBatchingEngine

        cfg = llama.CONFIGS["llama_tiny"]
        params = llama.init(cfg, jax.random.key(0))["params"]
        with pytest.raises(ValueError, match="seq2seq"):
            ContinuousBatchingEngine(
                "llama_tiny", cfg, params,
                draft=("t5_tiny", t5.CONFIGS["t5_tiny"], {}, 4))

    def test_paged_kv_refused(self):
        import jax

        from polyaxon_tpu.models import llama
        from polyaxon_tpu.serving.batching import ContinuousBatchingEngine

        cfg = llama.CONFIGS["llama_tiny"]
        params = llama.init(cfg, jax.random.key(0))["params"]
        with pytest.raises(ValueError, match="dense"):
            ContinuousBatchingEngine(
                "llama_tiny", cfg, params, kv="paged",
                draft=("llama_tiny", cfg, params, 4))

    def test_server_end_to_end_continuous_spec(self):
        """plx serve --batching continuous --draft-model: HTTP greedy
        responses equal a draft-less continuous server's."""
        def gen(url, payload):
            req = urllib.request.Request(
                url + "/v1/generate", method="POST",
                data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"})
            return json.load(urllib.request.urlopen(req, timeout=300))

        greedy = {"tokens": [[5, 6, 7], [1, 2, 3]], "max_new_tokens": 8}
        with ServingServer("llama_tiny", seed=0,
                           batching="continuous") as plain:
            want = gen(plain.url, greedy)
        with ServingServer("llama_tiny", seed=0, batching="continuous",
                           draft_model="llama_tiny", spec_k=3) as spec:
            got = gen(spec.url, greedy)
        assert got["tokens"] == want["tokens"]


class TestMoESpeculative:
    def test_moe_target_lossless(self):
        """Mixtral-style target: per-token top-k routing with no-drop
        capacity makes the chunked verify group-size-independent, so
        speculation stays lossless for MoE targets too — with a dense
        llama draft (the realistic pairing) and a self-draft."""
        from polyaxon_tpu.models import moe

        cfg = dataclasses.replace(moe.CONFIGS["moe_tiny"],
                                  dtype=jnp.float32)
        params = moe.init(cfg, jax.random.key(0))["params"]
        lcfg = _cfg()
        lparams = llama.init(lcfg, jax.random.key(5))["params"]
        prompt = jax.random.randint(jax.random.key(1), (2, 7), 0,
                                    min(cfg.vocab_size, lcfg.vocab_size))
        want = np.asarray(moe.generate(cfg, params, prompt,
                                       max_new_tokens=10))
        got_self = np.asarray(generate_speculative(
            cfg, params, cfg, params, prompt, max_new_tokens=10, k=3,
            family=moe, draft_family=moe))
        np.testing.assert_array_equal(got_self, want)
        got_llama_draft = np.asarray(generate_speculative(
            cfg, params, lcfg, lparams, prompt, max_new_tokens=10, k=3,
            family=moe, draft_family=llama))
        np.testing.assert_array_equal(got_llama_draft, want)

    def test_moe_serving_with_draft(self):
        with ServingServer("moe_tiny", seed=0, draft_model="llama_tiny",
                           spec_k=2) as s:
            req = urllib.request.Request(
                s.url + "/v1/generate", method="POST",
                data=json.dumps({"tokens": [[5, 6, 7]],
                                 "max_new_tokens": 6}).encode(),
                headers={"Content-Type": "application/json"})
            out = json.load(urllib.request.urlopen(req, timeout=300))
        with ServingServer("moe_tiny", seed=0) as plain:
            req = urllib.request.Request(
                plain.url + "/v1/generate", method="POST",
                data=json.dumps({"tokens": [[5, 6, 7]],
                                 "max_new_tokens": 6}).encode(),
                headers={"Content-Type": "application/json"})
            want = json.load(urllib.request.urlopen(req, timeout=300))
        assert out["tokens"] == want["tokens"]


class TestDraftVocab:
    def test_vocab_mismatch_refused_at_startup(self):
        # llama3_draft_200m carries the 128k llama-3 vocab; llama_tiny
        # is 256 — serving must refuse the pairing loudly.
        with pytest.raises(ValueError, match="token space"):
            ServingServer("llama_tiny", draft_model="llama3_draft_200m")


class TestSpeculativeEdges:
    def test_max_new_one(self):
        """Budget of 1: the prefill's own argmax is the whole output —
        the while_loop body must never need to run."""
        cfg = _cfg()
        params = llama.init(cfg, jax.random.key(0))["params"]
        prompt = jnp.asarray([[5, 6, 7]], jnp.int32)
        want = np.asarray(llama.generate(cfg, params, prompt,
                                         max_new_tokens=1))
        got = np.asarray(generate_speculative(
            cfg, params, cfg, params, prompt, max_new_tokens=1, k=4))
        np.testing.assert_array_equal(got, want)
