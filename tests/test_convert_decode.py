"""HF checkpoint interop + KV-cache decode.

The HF parity test is the strongest external validation of the native
Llama implementation: logits must match ``transformers``' reference to
float32 rounding on converted weights."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from polyaxon_tpu.models import llama
from polyaxon_tpu.models.convert import from_hf_llama, to_hf_llama


@pytest.fixture(scope="module")
def tiny():
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    cfg = dataclasses.replace(
        llama.CONFIGS["llama_tiny"], dtype=jnp.float32, max_seq_len=64)
    hf_cfg = transformers.LlamaConfig(
        vocab_size=cfg.vocab_size, hidden_size=cfg.dim,
        intermediate_size=cfg.ffn_dim, num_hidden_layers=cfg.n_layers,
        num_attention_heads=cfg.n_heads, num_key_value_heads=cfg.n_kv_heads,
        max_position_embeddings=64, rope_theta=cfg.rope_theta,
        rms_norm_eps=cfg.norm_eps, attention_bias=False,
        tie_word_embeddings=False)
    torch.manual_seed(0)
    hf = transformers.LlamaForCausalLM(hf_cfg).eval()
    return cfg, hf, torch


class TestHFInterop:
    def test_logit_parity_with_transformers(self, tiny):
        cfg, hf, torch = tiny
        variables = from_hf_llama(hf.state_dict(), cfg)
        tokens = np.random.RandomState(0).randint(0, cfg.vocab_size, (2, 16))
        with torch.no_grad():
            hf_logits = hf(torch.tensor(tokens)).logits.numpy()
        ours = llama.forward(cfg, variables["params"], jnp.asarray(tokens))
        np.testing.assert_allclose(ours, hf_logits, atol=2e-5, rtol=2e-5)

    def test_roundtrip_exact(self, tiny):
        cfg, hf, _ = tiny
        variables = from_hf_llama(hf.state_dict(), cfg)
        back = to_hf_llama(variables["params"], cfg)
        for key, value in hf.state_dict().items():
            np.testing.assert_allclose(back[key], value.numpy(), atol=1e-6,
                                       err_msg=key)

    def test_missing_key_is_actionable(self, tiny):
        cfg, hf, _ = tiny
        sd = dict(hf.state_dict())
        del sd["model.layers.0.self_attn.q_proj.weight"]
        with pytest.raises(KeyError, match="q_proj"):
            from_hf_llama(sd, cfg)


class TestDecode:
    def _setup(self):
        cfg = dataclasses.replace(
            llama.CONFIGS["llama_tiny"], dtype=jnp.float32, max_seq_len=64)
        variables = llama.init(cfg, jax.random.key(0))
        prompt = jax.random.randint(jax.random.key(1), (2, 12), 0,
                                    cfg.vocab_size)
        return cfg, variables, prompt

    def test_greedy_decode_matches_teacher_forced(self):
        cfg, variables, prompt = self._setup()
        gen = llama.generate(cfg, variables["params"], prompt,
                             max_new_tokens=4)
        full = prompt
        for _ in range(4):
            logits = llama.forward(cfg, variables["params"], full)
            nxt = jnp.argmax(logits[:, -1], -1).astype(jnp.int32)[:, None]
            full = jnp.concatenate([full, nxt], 1)
        np.testing.assert_array_equal(gen, full[:, prompt.shape[1]:])

    def test_decode_step_logits_match_forward(self):
        cfg, variables, prompt = self._setup()
        B, P = prompt.shape
        cache = llama.init_cache(cfg, B, P)
        for t in range(P):
            step_logits, cache = llama.decode_step(
                cfg, variables["params"], cache, prompt[:, t], t)
        fwd = llama.forward(cfg, variables["params"], prompt)
        np.testing.assert_allclose(step_logits, fwd[:, -1], atol=2e-4,
                                   rtol=2e-4)

    def test_sampling_needs_rng(self):
        cfg, variables, prompt = self._setup()
        with pytest.raises(ValueError, match="rng"):
            llama.generate(cfg, variables["params"], prompt,
                           max_new_tokens=2, temperature=0.7)

    def test_sampled_decode_runs(self):
        cfg, variables, prompt = self._setup()
        gen = llama.generate(cfg, variables["params"], prompt,
                             max_new_tokens=3, temperature=0.8,
                             rng=jax.random.key(5))
        assert gen.shape == (2, 3)
        assert bool((gen >= 0).all()) and bool((gen < cfg.vocab_size).all())


class TestConvertGuards:
    def test_bf16_torch_tensors_convert(self, tiny):
        cfg, hf, torch = tiny
        sd = {k: v.to(torch.bfloat16) for k, v in hf.state_dict().items()}
        variables = from_hf_llama(sd, cfg)
        assert variables["params"]["embed"].dtype == jnp.float32

    def test_layer_count_mismatch_raises(self, tiny):
        cfg, hf, _ = tiny
        small = dataclasses.replace(cfg, n_layers=1)
        with pytest.raises(ValueError, match="more than 1 layers"):
            from_hf_llama(hf.state_dict(), small)


class TestExportToHF:
    def test_export_cli_roundtrips_through_transformers(self, tiny,
                                                        tmp_path):
        """plx convert --from-orbax: a saved train state exports to an
        HF dir that transformers loads, with logit parity against the
        native forward — the full interop circle (import is tested
        above)."""
        from click.testing import CliRunner

        from polyaxon_tpu.cli.main import cli
        from polyaxon_tpu.polyflow.runs import V1JaxCheckpointing
        from polyaxon_tpu.runtime.checkpoint import CheckpointManager

        transformers = pytest.importorskip("transformers")
        cfg, _, torch = tiny
        params = llama.init(cfg, jax.random.key(3))["params"]
        ckpt_dir = str(tmp_path / "ckpt")
        mgr = CheckpointManager(ckpt_dir, V1JaxCheckpointing(
            enabled=True, interval_steps=1, async_save=False))
        try:
            mgr.save(0, {"params": params}, force=True)
        finally:
            mgr.close()

        out = str(tmp_path / "hf")
        result = CliRunner().invoke(cli, [
            "convert", "--model", "llama_tiny", "--from-orbax", ckpt_dir,
            "--out", out])
        assert result.exit_code == 0, result.output
        assert "exported" in result.output

        hf = transformers.LlamaForCausalLM.from_pretrained(out).eval()
        tokens = np.random.RandomState(1).randint(0, cfg.vocab_size,
                                                  (2, 12))
        with torch.no_grad():
            hf_logits = hf(torch.tensor(tokens)).logits.numpy()
        ours = llama.forward(cfg, params, jnp.asarray(tokens))
        np.testing.assert_allclose(np.asarray(ours), hf_logits,
                                   atol=2e-5, rtol=2e-5)

    def test_export_requires_exactly_one_source(self, tmp_path):
        from click.testing import CliRunner

        from polyaxon_tpu.cli.main import cli

        result = CliRunner().invoke(cli, [
            "convert", "--model", "llama_tiny",
            "--out", str(tmp_path / "x")])
        assert result.exit_code != 0
        assert "exactly one" in result.output
