"""fs store layer: scheme dispatch, the fsspec-backed cloud store
(exercised offline through fsspec's built-in ``memory://`` protocol),
and the connection-kind → store round trip VERDICT r1 item 6 requires:
every advertised artifact-store kind either yields a working Store or
fails loudly with an actionable error — never a silent gap."""

import os

import pytest

from polyaxon_tpu.connections import V1Connection
from polyaxon_tpu.fs import (
    FsspecStore,
    LocalStore,
    MemoryStore,
    StoreError,
    get_store,
    register_store,
)


def _fsspec_memory_store(ns: str) -> FsspecStore:
    """An FsspecStore over fsspec's in-process memory filesystem —
    the same code path gs:// takes, no network needed."""
    store = FsspecStore(f"memory://{ns}")
    store.fs.store.clear()  # fsspec MemoryFileSystem state is global
    return store


class TestFsspecStore:
    def test_round_trip(self):
        store = _fsspec_memory_store("rt")
        store.write_bytes("a/b.txt", b"hello")
        assert store.read_bytes("a/b.txt") == b"hello"
        assert store.exists("a/b.txt")
        assert not store.exists("a/missing")
        store.write_text("a/c.txt", "world")
        assert store.read_text("a/c.txt") == "world"
        assert store.list() == ["a/b.txt", "a/c.txt"]
        assert store.list("a") == ["a/b.txt", "a/c.txt"]
        store.delete("a/b.txt")
        assert store.list() == ["a/c.txt"]

    def test_missing_key_raises_typed(self):
        store = _fsspec_memory_store("miss")
        with pytest.raises(StoreError, match="no such key"):
            store.read_bytes("nope")

    def test_dir_upload_download_sync(self, tmp_path):
        store = _fsspec_memory_store("dirs")
        src = tmp_path / "src"
        (src / "sub").mkdir(parents=True)
        (src / "one.txt").write_text("1")
        (src / "sub" / "two.txt").write_text("2")

        assert store.upload_dir(str(src), "runs/x") == 2
        assert store.list("runs/x") == ["runs/x/one.txt",
                                        "runs/x/sub/two.txt"]
        dest = tmp_path / "dest"
        assert store.download_dir("runs/x", str(dest)) == 2
        assert (dest / "sub" / "two.txt").read_text() == "2"

        # Incremental sync: second call with no changes ships nothing;
        # touching one file ships exactly it.
        state: dict[str, float] = {}
        assert store.sync_dir(str(src), "runs/y", state=state) == 2
        assert store.sync_dir(str(src), "runs/y", state=state) == 0
        os.utime(src / "one.txt", (0, 2_000_000_000))
        assert store.sync_dir(str(src), "runs/y", state=state) == 1

    def test_sync_dir_skips_inflight_files(self, tmp_path):
        """.tmp/.lock (atomic-publish convention) never ship to the
        store — parity with the local sidecar sync_tree path."""
        store = _fsspec_memory_store("inflight")
        src = tmp_path / "run"
        src.mkdir()
        (src / "ckpt.bin").write_text("done")
        (src / "ckpt.bin.tmp").write_text("half-written")
        (src / "events.lock").write_text("")
        assert store.sync_dir(str(src)) == 1
        assert store.list() == ["ckpt.bin"]

    def test_sync_dir_store_failure_is_loud_and_retried(self, tmp_path,
                                                        caplog):
        """A store-side OSError (auth/permission/network — NOT a file
        vanishing mid-walk) must be logged at warning and retried next
        pass, never silently swallowed: a persistently broken gs://
        destination that skipped files forever would lose artifacts
        (ADVICE r2, fs/store.py sync_dir)."""
        import logging

        from polyaxon_tpu.sidecar import sync as sidecar_sync

        # The warnings are once-per-path + rate-limited process-wide;
        # reset so this test observes them regardless of suite order.
        sidecar_sync._warned_paths.clear()
        sidecar_sync._last_summary_warn = 0.0

        store = _fsspec_memory_store("broken")
        src = tmp_path / "run"
        src.mkdir()
        (src / "a.txt").write_text("a")

        real_upload = store.upload_file
        calls = {"n": 0}

        def flaky_upload(path, key):
            calls["n"] += 1
            if calls["n"] == 1:
                raise PermissionError("403 on destination bucket")
            return real_upload(path, key)

        store.upload_file = flaky_upload
        state: dict[str, float] = {}
        with caplog.at_level(logging.WARNING,
                             "polyaxon_tpu.sidecar.sync"):
            assert store.sync_dir(str(src), state=state) == 0
        assert "sync failed for" in caplog.text  # loud, not silent
        assert "failed to ship" in caplog.text  # pass summary
        assert state == {}  # mtime NOT recorded → retried next pass
        assert store.sync_dir(str(src), state=state) == 1  # retry ships it
        assert store.list() == ["a.txt"]

    def test_sync_tree_dest_failure_is_loud(self, tmp_path, caplog,
                                            monkeypatch):
        """The local sidecar fast path has the same contract: a broken
        DESTINATION volume (read-only/full) warns instead of silently
        skipping forever; a vanished source stays silent."""
        import logging
        import shutil as _shutil

        from polyaxon_tpu.sidecar import sync as sidecar_sync

        sidecar_sync._warned_paths.clear()
        sidecar_sync._last_summary_warn = 0.0

        src = tmp_path / "run"
        src.mkdir()
        (src / "a.txt").write_text("a")
        dest = tmp_path / "dest"

        def broken_copy(s, d):
            raise PermissionError("read-only file system")

        monkeypatch.setattr(_shutil, "copy2", broken_copy)
        with caplog.at_level(logging.WARNING,
                             "polyaxon_tpu.sidecar.sync"):
            assert sidecar_sync.sync_tree(str(src), str(dest)) == 0
        assert "sync failed for" in caplog.text
        monkeypatch.undo()
        assert sidecar_sync.sync_tree(str(src), str(dest)) == 1


class TestGetStoreDispatch:
    def test_file_and_memory(self, tmp_path):
        assert isinstance(get_store(f"file://{tmp_path}"), LocalStore)
        assert isinstance(get_store(str(tmp_path)), LocalStore)
        assert isinstance(get_store("memory://ns"), MemoryStore)

    def test_gcs_constructs(self):
        # gcsfs is baked into this image: gs:// must yield a live
        # FsspecStore (no network touched at construction).
        store = get_store("gs://some-bucket/prefix")
        assert isinstance(store, FsspecStore)
        assert store.root == "some-bucket/prefix"

    def test_missing_protocol_package_raises_actionable(self):
        # s3fs/adlfs are absent here: the error must name the package.
        with pytest.raises(StoreError, match="s3fs"):
            get_store("s3://bucket/x")
        with pytest.raises(StoreError, match="adlfs"):
            get_store("wasb://container/x")

    def test_unknown_scheme(self):
        with pytest.raises(StoreError, match="unknown store scheme"):
            get_store("ftp://nope")

    def test_register_store_override(self):
        register_store("customfs", lambda url: MemoryStore("custom"))
        try:
            assert isinstance(get_store("customfs://x"), MemoryStore)
        finally:
            from polyaxon_tpu.fs import store as store_mod

            store_mod._REGISTRY.pop("customfs", None)


class TestConnectionStoreRoundTrip:
    """Every advertised artifact-store connection kind resolves through
    store_url() → get_store() to a Store or a loud typed error."""

    def _conn(self, kind, schema):
        return V1Connection.from_dict(
            {"name": f"c-{kind}", "kind": kind, "schema": schema})

    def test_host_path_and_volume_claim(self, tmp_path):
        for kind, schema in (
            ("host_path", {"hostPath": str(tmp_path)}),
            ("volume_claim", {"mountPath": str(tmp_path),
                              "volumeClaim": "pvc-1"}),
        ):
            conn = self._conn(kind, schema)
            store = get_store(conn.store_url())
            assert isinstance(store, LocalStore)
            store.write_text("probe.txt", kind)
            assert store.read_text("probe.txt") == kind

    def test_gcs_resolves_to_fsspec_store(self):
        conn = self._conn("gcs", {"bucket": "gs://my-ckpts"})
        assert conn.store_url() == "gs://my-ckpts"
        assert isinstance(get_store(conn.store_url()), FsspecStore)

    def test_s3_and_wasb_fail_loudly_without_packages(self):
        s3 = self._conn("s3", {"bucket": "s3://my-data"})
        with pytest.raises(StoreError, match="s3fs"):
            get_store(s3.store_url())
        wasb = self._conn("wasb", {"url": "wasb://logs/x"})
        with pytest.raises(StoreError, match="adlfs"):
            get_store(wasb.store_url())


class TestSidecarStoreDestination:
    def test_sidecar_ships_to_store_url(self, tmp_path):
        """SidecarSync with a store URL destination syncs through the
        fs layer, incrementally."""
        from polyaxon_tpu.sidecar import SidecarSync

        register_store("sidecarmem",
                       lambda url: FsspecStore(
                           url.replace("sidecarmem://", "memory://", 1)))
        try:
            run_dir = tmp_path / "run"
            (run_dir / "logs").mkdir(parents=True)
            (run_dir / "logs" / "out.log").write_text("line1\n")
            sync = SidecarSync(str(run_dir), "sidecarmem://side-ns",
                               interval_seconds=0.1)
            assert sync.sync_once() == 1
            assert sync.sync_once() == 0  # unchanged → nothing shipped
            (run_dir / "metrics.jsonl").write_text('{"loss": 1}\n')
            assert sync.sync_once() == 1
            store = FsspecStore("memory://side-ns")
            # Each shipping pass also records + ships a `sync` lifecycle
            # span (docs/observability.md) — shipped within the same
            # pass (its mtime recorded), which is exactly why the
            # unchanged pass above still synced 0.
            assert store.list() == ["events/span/lifecycle.jsonl",
                                    "logs/out.log", "metrics.jsonl"]
            assert store.read_text("metrics.jsonl") == '{"loss": 1}\n'
        finally:
            from polyaxon_tpu.fs import store as store_mod

            store_mod._REGISTRY.pop("sidecarmem", None)
            FsspecStore("memory://side-ns").fs.store.clear()


class TestInitArtifactsFromStore:
    def test_artifacts_init_phase_downloads_store_prefix(self, tmp_path):
        """An artifacts init phase whose path is a store URL downloads
        the prefix into the run's inputs dir (SURVEY §3.3)."""
        from polyaxon_tpu.agent.executor import LocalExecutor
        from polyaxon_tpu.compiler.plan import (
            V1InitPhase,
            V1LaunchPlan,
            V1ResourceRequest,
        )

        seed = _fsspec_memory_store("init-src")
        seed.write_text("data/train.txt", "corpus")
        seed.write_text("data/valid.txt", "dev")

        register_store("initmem",
                       lambda url: FsspecStore(
                           url.replace("initmem://", "memory://", 1)))
        try:
            plan = V1LaunchPlan(
                run_uuid="r1", run_name="init-test", run_kind="jaxjob",
                artifacts_dir=str(tmp_path / "run"),
                outputs_dir=str(tmp_path / "run" / "outputs"),
                resources=V1ResourceRequest(),
                init=[V1InitPhase(kind="artifacts",
                                  config={"path": "initmem://init-src/data"})])
            LocalExecutor.__new__(LocalExecutor)._run_init_phases(plan)
            inputs = tmp_path / "run" / "inputs" / "data"
            assert (inputs / "train.txt").read_text() == "corpus"
            assert (inputs / "valid.txt").read_text() == "dev"
        finally:
            from polyaxon_tpu.fs import store as store_mod

            store_mod._REGISTRY.pop("initmem", None)
            FsspecStore("memory://init-src").fs.store.clear()

    def test_artifacts_init_phase_single_object_url(self, tmp_path):
        """A store URL naming one object (not a prefix) downloads as a
        single file instead of erroring on an empty listing."""
        from polyaxon_tpu.agent.executor import LocalExecutor
        from polyaxon_tpu.compiler.plan import (
            V1InitPhase,
            V1LaunchPlan,
            V1ResourceRequest,
        )

        seed = _fsspec_memory_store("single-src")
        seed.write_text("model.ckpt", "weights")
        register_store("singlemem",
                       lambda url: FsspecStore(
                           url.replace("singlemem://", "memory://", 1)))
        try:
            plan = V1LaunchPlan(
                run_uuid="r2", run_name="single", run_kind="jaxjob",
                artifacts_dir=str(tmp_path / "run"),
                outputs_dir=str(tmp_path / "run" / "outputs"),
                resources=V1ResourceRequest(),
                init=[V1InitPhase(
                    kind="artifacts",
                    config={"path": "singlemem://single-src/model.ckpt"})])
            LocalExecutor.__new__(LocalExecutor)._run_init_phases(plan)
            assert (tmp_path / "run" / "inputs"
                    / "model.ckpt").read_text() == "weights"
        finally:
            from polyaxon_tpu.fs import store as store_mod

            store_mod._REGISTRY.pop("singlemem", None)
            FsspecStore("memory://single-src").fs.store.clear()

    def test_artifacts_init_phase_file_url(self, tmp_path):
        """file:// URLs resolve to the local copy path — not silently
        skipped."""
        from polyaxon_tpu.agent.executor import LocalExecutor
        from polyaxon_tpu.compiler.plan import (
            V1InitPhase,
            V1LaunchPlan,
            V1ResourceRequest,
        )

        src = tmp_path / "dataset"
        src.mkdir()
        (src / "x.txt").write_text("local")
        plan = V1LaunchPlan(
            run_uuid="r3", run_name="fileurl", run_kind="jaxjob",
            artifacts_dir=str(tmp_path / "run"),
            outputs_dir=str(tmp_path / "run" / "outputs"),
            resources=V1ResourceRequest(),
            init=[V1InitPhase(kind="artifacts",
                              config={"path": f"file://{src}"})])
        LocalExecutor.__new__(LocalExecutor)._run_init_phases(plan)
        assert (tmp_path / "run" / "inputs" / "dataset"
                / "x.txt").read_text() == "local"
