"""Arrival traces: seeded, compressed-time fleet workload generators.

A trace is a flat list of ``TraceEvent``s — (offset seconds, workload
kind, op spec / action payload) — composing the workloads the repo
already supports, in the proportions a real fleet day mixes them:

- ``job``      — plain one-gang training jobs across tenant projects
- ``sweep``    — tune sweeps (``matrix`` mapping → trial fan-out)
- ``dag``      — multi-step pipelines (chain + diamond shapes)
- ``schedule`` — interval schedules firing child runs all day
- ``serving``  — long-lived deploys that sit on capacity
- ``churn``    — jobs with ``restartPolicy: onFailure`` and a high
                 synthetic failure rate → restart/backoff churn
- ``storm``    — a preemption storm action: evict a fraction of the
                 executor's active gangs at that instant

Events carry *specs*, not store handles — the replayer (fleet.py)
submits through the real ``ControlPlane``. All randomness comes from
one seeded ``random.Random`` so a trace is reproducible byte-for-byte.
"""

from __future__ import annotations

import dataclasses
import random

PROJECTS = ("platform", "research", "serving", "growth")
QUEUES = ({"name": "prod", "priority": 10, "preemptible": False},
          {"name": "batch", "priority": 0, "preemptible": True},
          {"name": "best-effort", "priority": -10, "preemptible": True})


@dataclasses.dataclass
class TraceEvent:
    at: float  # seconds from trace start (compressed time)
    kind: str  # job | sweep | dag | schedule | serving | churn | storm
    #          # | elastic | slice-loss (the elastic resize lane)
    spec: dict | None = None  # operation spec for submit kinds
    project: str = "platform"
    payload: dict | None = None  # non-submit actions (storm fraction, ...)


def _job_run(*, sleep: float = 0.01, restart: bool = False) -> dict:
    env = {"restartPolicy": "onFailure"} if restart else {}
    return {
        "kind": "job",
        **({"environment": env} if env else {}),
        "container": {"command": [
            "python", "-c", f"import time; time.sleep({sleep})"]},
    }


def job_op(*, queue: str | None = None, priority_class: str | None = None,
           restart: bool = False, name: str | None = None) -> dict:
    run = _job_run(restart=restart)
    if priority_class:
        run.setdefault("environment", {})["priorityClassName"] = priority_class
    spec = {"kind": "operation", "component": {"run": run}}
    if queue:
        spec["queue"] = queue
    if name:
        spec["name"] = name
    return spec


def sweep_op(n_trials: int, *, queue: str | None = None) -> dict:
    spec = {
        "kind": "operation",
        "matrix": {"kind": "mapping",
                   "values": [{"lr": round(0.01 * (i + 1), 4)}
                              for i in range(n_trials)]},
        "component": {
            "inputs": [{"name": "lr", "type": "float", "toEnv": "LR"}],
            "run": _job_run(),
        },
    }
    if queue:
        spec["queue"] = queue
    return spec


def hyperband_op(*, queue: str | None = None, max_iterations: int = 4,
                 eta: float = 2.0, seed: int = 0) -> dict:
    """A Hyperband sweep (successive halving through tune.hyperband):
    the cluster-day gauntlet's tuning lane. Synthetic trials report no
    metric, so rungs never promote — each bracket runs its first rung
    and the matrix still terminates, which is exactly the fan-out/
    drain behavior the control plane is judged on."""
    spec = {
        "kind": "operation",
        "matrix": {
            "kind": "hyperband",
            "maxIterations": max_iterations,
            "eta": eta,
            "seed": seed,
            "resource": {"name": "epochs", "type": "int"},
            "metric": {"name": "loss", "optimization": "minimize"},
            "params": {"lr": {"kind": "uniform",
                              "value": {"low": 0.001, "high": 0.1}}},
        },
        "component": {
            "inputs": [
                {"name": "lr", "type": "float", "toEnv": "LR"},
                {"name": "epochs", "type": "int", "value": 1,
                 "isOptional": True, "toEnv": "EPOCHS"},
            ],
            "run": _job_run(),
        },
    }
    if queue:
        spec["queue"] = queue
    return spec


def dag_op(shape: str = "chain") -> dict:
    step = {"run": _job_run()}
    if shape == "diamond":
        ops = [
            {"name": "a", "component": dict(step)},
            {"name": "b", "dependencies": ["a"], "component": dict(step)},
            {"name": "c", "dependencies": ["a"], "component": dict(step)},
            {"name": "d", "dependencies": ["b", "c"], "component": dict(step)},
        ]
    else:
        ops = [
            {"name": "a", "component": dict(step)},
            {"name": "b", "dependencies": ["a"], "component": dict(step)},
            {"name": "c", "dependencies": ["b"], "component": dict(step)},
        ]
    return {"kind": "operation",
            "component": {"run": {"kind": "dag", "operations": ops}}}


def schedule_op(*, frequency: int, max_runs: int) -> dict:
    return {
        "kind": "operation",
        "schedule": {"kind": "interval", "frequency": frequency,
                     "maxRuns": max_runs},
        "component": {"run": _job_run()},
    }


def serving_op(*, queue: str = "prod") -> dict:
    # Long-lived deploy: the synthetic executor reads the duration hint
    # stamped into meta by the replayer (see FleetSim._submit_event).
    return job_op(queue=queue, priority_class="high", name="deploy")


def make_trace(profile: str = "quick", *, seed: int = 0) -> list[TraceEvent]:
    """Build a seeded arrival trace.

    ``quick``: a few hundred runs over ~8s of compressed time — the CI
    gate and smoke-test profile. ``day``: ~100k runs (counting sweep
    trials and schedule fires) over a compressed day — the full-curve
    profile bench_controlplane runs.
    """
    rng = random.Random(seed)
    if profile == "quick":
        horizon, jobs, sweeps, dags, serving, churn = 8.0, 120, 6, 4, 3, 30
        sweep_width, storm_times = 8, (4.0,)
        schedules = [(2, 3)]  # (frequency s, max_runs)
    elif profile == "day":
        # ~86400 fleet-seconds compressed into ~180s wall: ≈90k trial
        # runs via sweeps + ~6k directs; sized for the 100k-run day.
        horizon, jobs, sweeps, dags, serving, churn = 180.0, 4000, 180, 120, 40, 1500
        sweep_width, storm_times = 500, (60.0, 120.0)
        schedules = [(5, 30)] * 8
    else:
        raise ValueError(f"unknown trace profile {profile!r}")

    events: list[TraceEvent] = []

    def t() -> float:
        return rng.uniform(0, horizon)

    def project() -> str:
        return rng.choice(PROJECTS)

    for _ in range(jobs):
        queue = rng.choice(("batch", "best-effort", None))
        events.append(TraceEvent(t(), "job", job_op(queue=queue),
                                 project()))
    for _ in range(sweeps):
        events.append(TraceEvent(t(), "sweep",
                                 sweep_op(sweep_width, queue="batch"),
                                 project()))
    for _ in range(dags):
        shape = rng.choice(("chain", "diamond"))
        events.append(TraceEvent(t(), "dag", dag_op(shape), project()))
    for freq, max_runs in schedules:
        events.append(TraceEvent(0.0, "schedule",
                                 schedule_op(frequency=freq,
                                             max_runs=max_runs),
                                 project()))
    for _ in range(serving):
        events.append(TraceEvent(t() * 0.3, "serving", serving_op(),
                                 "serving"))
    for _ in range(churn):
        events.append(TraceEvent(t(), "churn",
                                 job_op(queue="best-effort", restart=True),
                                 project()))
    for at in storm_times:
        events.append(TraceEvent(at, "storm", None, payload={"fraction": 0.5}))
    events.sort(key=lambda e: e.at)
    return events
