"""Sharded train/eval step construction (the only true hot loop —
SURVEY.md §3 boundary summary: everything else orchestrates around the
compiled step function).

Placement strategy: params/state get explicit NamedShardings from the
model's logical axes + the mesh's rule table; optimizer state inherits
them through XLA sharding propagation (mu/nu are ``zeros_like(params)``
inside the jitted init, so propagation is exact); gradients are reduced
by the compiler-inserted psums over dp/fsdp. ``donate`` on the state
keeps HBM flat across steps.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from polyaxon_tpu.models.common import ModelDef
from polyaxon_tpu.parallel.sharding import Rules, tree_shardings

TrainState = dict[str, Any]  # {"params", "state", "opt_state", "step"}


def state_shardings(model_def: ModelDef, mesh: Mesh, rules: Rules) -> dict:
    logical = model_def.logical_axes()
    return {
        "params": tree_shardings(logical["params"], mesh, rules),
        "state": tree_shardings(logical.get("state", {}), mesh, rules),
    }


def build_init(
    model_def: ModelDef,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    rules: Rules,
) -> Callable[[jax.Array], TrainState]:
    shardings = state_shardings(model_def, mesh, rules)

    def init_fn(rng: jax.Array) -> TrainState:
        variables = model_def.init(rng)
        params = jax.lax.with_sharding_constraint(variables["params"], shardings["params"])
        mutable = variables.get("state", {})
        if mutable:
            mutable = jax.lax.with_sharding_constraint(mutable, shardings["state"])
        opt_state = optimizer.init(params)
        return {
            "params": params,
            "state": mutable,
            "opt_state": opt_state,
            "step": jnp.zeros((), dtype=jnp.int32),
        }

    return jax.jit(init_fn)


def build_train_step(
    model_def: ModelDef,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    rules: Rules,
    accum_steps: int = 1,
) -> Callable[[TrainState, dict, jax.Array], tuple[TrainState, dict]]:
    """One optimizer update per call. With ``accum_steps > 1`` the batch
    (still the full per-update global batch) is split into that many
    microbatches and gradients accumulate inside a ``lax.scan`` — one
    compiled program, peak activation memory divided by ``accum_steps``.
    """
    shardings = state_shardings(model_def, mesh, rules)

    def grads_of(params, mutable, batch, rng):
        def loss_fn(p):
            loss, metrics, new_mutable = model_def.apply(
                {"params": p, "state": mutable}, batch, True, rng
            )
            return loss, (metrics, new_mutable)

        (_, (metrics, new_mutable)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(params)
        return grads, metrics, new_mutable

    def train_step(state: TrainState, batch: dict, rng: jax.Array):
        if accum_steps == 1:
            grads, metrics, new_mutable = grads_of(
                state["params"], state["state"], batch, rng)
        else:
            # [G, ...] → [k, G/k, ...] microbatches, re-constrained to
            # the batch layout so dp/fsdp sharding survives the reshape.
            from polyaxon_tpu.parallel.sharding import batch_spec

            micro = jax.tree.map(
                lambda x: x.reshape(accum_steps, x.shape[0] // accum_steps,
                                    *x.shape[1:]),
                batch)
            rngs = jax.random.split(rng, accum_steps)

            def constrain(mb):
                return jax.tree.map(
                    lambda x: jax.lax.with_sharding_constraint(
                        x, NamedSharding(
                            mesh, batch_spec(mesh, rules, ndim=x.ndim))),
                    mb)

            def weight_of(mb) -> jax.Array:
                # Masked losses are per-valid-token means; weight each
                # microbatch's gradient by its valid-token count so the
                # accumulated gradient equals the full-batch one. This
                # assumes the loss is fully mask-weighted (true for the
                # LM/CE losses here); a loss mixing mask-independent
                # terms (e.g. MoE router aux) is approximated — keep
                # microbatches mask-balanced or use accum_steps=1 there.
                if isinstance(mb, dict) and mb.get("mask") is not None:
                    return mb["mask"].astype(jnp.float32).sum()
                return jnp.float32(1.0)

            def body(carry, mb_and_rng):
                grads_acc, w_acc, mutable = carry
                mb, r = mb_and_rng
                mb = constrain(mb)
                w = weight_of(mb)
                g, m, new_mutable = grads_of(state["params"], mutable, mb, r)
                grads_acc = jax.tree.map(
                    lambda acc, gi: acc + w * gi, grads_acc, g)
                m = jax.tree.map(lambda v: w * v, dict(m))
                return (grads_acc, w_acc + w, new_mutable), m

            zeros = jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.float32), state["params"])
            (grads, w_total, new_mutable), metrics_seq = jax.lax.scan(
                body, (zeros, jnp.float32(0.0), state["state"]),
                (micro, rngs))
            # Clamp: a fully-masked batch (w_total == 0) must yield zero
            # grads like the accum=1 path, not 0/0 = NaN params.
            w_safe = jnp.maximum(w_total, 1.0)
            grads = jax.tree.map(
                lambda g, p: (g / w_safe).astype(p.dtype),
                grads, state["params"])
            metrics = jax.tree.map(
                lambda m: m.sum() / w_safe, metrics_seq)

        updates, new_opt_state = optimizer.update(
            grads, state["opt_state"], state["params"]
        )
        new_params = optax.apply_updates(state["params"], updates)
        new_params = jax.lax.with_sharding_constraint(new_params, shardings["params"])
        metrics = dict(metrics)
        metrics["grad_norm"] = optax.global_norm(grads)
        new_state = {
            "params": new_params,
            "state": new_mutable,
            "opt_state": new_opt_state,
            "step": state["step"] + 1,
        }
        return new_state, metrics

    return jax.jit(train_step, donate_argnums=(0,))


def build_eval_step(model_def: ModelDef) -> Callable[[TrainState, dict], dict]:
    def eval_step(state: TrainState, batch: dict) -> dict:
        _, metrics, _ = model_def.apply(
            {"params": state["params"], "state": state["state"]}, batch, False, None
        )
        return metrics

    return jax.jit(eval_step)
