"""Sidecar: periodic sync of a run's local dir to the artifacts store.

Parity with the reference's sidecar container (SURVEY.md §2 "Sidecar",
§3.3 [K]): watch the run dir, incrementally upload logs/events/outputs,
final sync on exit. Store IO goes through ``polyaxon_tpu.fs`` (local fs
today, fsspec-compatible providers when available).
"""

from polyaxon_tpu.sidecar.sync import SidecarSync, sync_tree

__all__ = ["SidecarSync", "sync_tree"]
