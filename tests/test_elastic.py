"""Elastic gangs (ISSUE 14): shrink and regrow a live jaxjob on slice
loss instead of killing it.

Covers the full stack: topology scaling units (``scaled_axes``), the
thread-safe resize channel (``ElasticController``), the chaos
``slice-loss`` seam, the prewarm contract (inline / subprocess /
skip), the slice pool's partial vacate + rollback, the scheduler's
resizing-hold, and the acceptance drill — chaos kills a slice
mid-train, capacity returns, the run reaches SUCCEEDED with loss-curve
continuity across both resizes judged by the telemetry oracle.
"""

import json
import os
import time
import types

import pytest

from polyaxon_tpu import chaos
from polyaxon_tpu.agent import Agent
from polyaxon_tpu.controlplane import ControlPlane
from polyaxon_tpu.lifecycle import V1Statuses
from polyaxon_tpu.runtime import elastic


@pytest.fixture(autouse=True)
def _fast_backoff(monkeypatch):
    """Sub-second backoff so the PREEMPTED-fallback drills stay quick,
    and a clean chaos slate around every test."""
    monkeypatch.setenv("POLYAXON_TPU_BACKOFF_BASE", "0.05")
    monkeypatch.setenv("POLYAXON_TPU_BACKOFF_MAX", "2")
    chaos.uninstall()
    yield
    chaos.uninstall()


@pytest.fixture()
def plane(tmp_path):
    return ControlPlane(str(tmp_path / "home"))


def drive(agent, plane, uuid, until, timeout=120.0, poll=0.03):
    """Reconcile until ``until(record)`` or fail the test."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        agent.reconcile_once()
        record = plane.get_run(uuid)
        if until(record):
            return record
        time.sleep(poll)
    raise AssertionError(
        f"run {uuid} never satisfied the predicate; last status "
        f"{plane.get_run(uuid).status}: {plane.get_statuses(uuid)}")


def jaxjob_spec(*, steps=12, global_batch=8, max_retries=2):
    """The drill jaxjob: dp=8 over the 8 host CPU devices, checkpoint
    every 2 steps with a deep keep-window (the slice-loss seam gates on
    persisted checkpoint COUNT, so pruning must not race the fault)."""
    return {
        "kind": "operation",
        "termination": {"maxRetries": max_retries},
        "component": {
            "name": "elastic-drill",
            "run": {
                "kind": "jaxjob",
                "numProcesses": 1,
                "environment": {"restartPolicy": "on_failure"},
                "mesh": {"axes": {"dp": 8}},
                "checkpointing": {"enabled": True, "intervalSteps": 2,
                                  "maxToKeep": 20, "asyncSave": False,
                                  "restoreOnStart": True},
                "runtime": {
                    "model": "llama_tiny",
                    "dataset": "lm_synthetic",
                    "steps": steps,
                    "seq_len": 64,
                    "global_batch_size": global_batch,
                },
            },
        },
    }


def make_job(**runtime_over):
    from polyaxon_tpu.polyflow.runs import V1JAXJob

    run = jaxjob_spec()["component"]["run"]
    run["runtime"].update(runtime_over)
    return V1JAXJob.from_dict(run)


def flat_spans(timeline):
    out = []
    stack = list(timeline.get("spans") or [])
    while stack:
        node = stack.pop()
        out.append(node)
        stack.extend(node.get("children") or [])
    return out


# ============================================================ topology math
class TestScaledAxes:
    def test_shrink_scales_only_dp(self):
        assert elastic.scaled_axes({"dp": 4, "fsdp": 2}, 8, 4) == \
            {"dp": 2, "fsdp": 2}

    def test_grow_back_restores_base(self):
        assert elastic.scaled_axes({"dp": 2, "fsdp": 2}, 4, 8) == \
            {"dp": 4, "fsdp": 2}

    def test_identity_returns_copy(self):
        base = {"dp": 8}
        out = elastic.scaled_axes(base, 8, 8)
        assert out == base and out is not base

    def test_fractional_dp_rejected(self):
        # dp=1 cannot halve: the model-parallel axes are fixed, so a
        # 8→4 target would need dp=0.5.
        with pytest.raises(elastic.PrewarmError, match="non-integer"):
            elastic.scaled_axes({"dp": 1, "tp": 8}, 8, 4)

    def test_resolved_base_axes_defaults_to_pure_dp(self):
        job = types.SimpleNamespace(mesh=None)
        assert elastic.resolved_base_axes(job, 4) == {"dp": 4}

    def test_elastic_capable_needs_ckpt_and_restore(self):
        def job(ckpt):
            return types.SimpleNamespace(checkpointing=ckpt)

        assert not elastic.elastic_capable(job(None))
        assert not elastic.elastic_capable(job(types.SimpleNamespace(
            enabled=True, restore_on_start=False)))
        assert not elastic.elastic_capable(job(types.SimpleNamespace(
            enabled=False, restore_on_start=True)))
        assert elastic.elastic_capable(job(types.SimpleNamespace(
            enabled=True, restore_on_start=True)))


# ============================================================ resize channel
class TestElasticController:
    def test_full_shrink_grow_arc_spends_the_budget(self):
        c = elastic.ElasticController("u1", budget=2)
        assert c.request("grow") is False  # never shrunk: nothing to grow
        assert c.request("shrink", reason="SliceLost")
        assert c.request("shrink") is False  # one in flight at a time
        assert c.resizing  # granted-but-untaken counts: hold new events
        req = c.take()
        assert req == {"direction": "shrink", "reason": "SliceLost",
                       "target_devices": None}
        assert c.resizing
        assert c.request("grow") is False  # still resizing
        a = c.begin_attempt("shrink", "SliceLost", 8, 4)
        c.finish_attempt(a, "ok", duration_s=0.1)
        assert not c.resizing
        assert c.shrunk and not c.exhausted()

        assert c.request("grow", reason="CapacityReturned")
        c.take()
        a2 = c.begin_attempt("grow", "CapacityReturned", 4, 8)
        c.finish_attempt(a2, "ok")
        assert not c.shrunk
        assert c.exhausted()
        assert c.request("shrink") is False  # budget spent

    def test_failed_attempt_does_not_mark_shrunk(self):
        c = elastic.ElasticController("u1", budget=2)
        assert c.request("shrink")
        c.take()
        a = c.begin_attempt("shrink", "r", 8, 4)
        c.finish_attempt(a, "failed", error="no compile")
        assert not c.shrunk
        assert a["error"] == "no compile"
        # The channel reopened: the failed attempt still spent budget.
        assert c.request("shrink")

    def test_budget_env_and_zero_budget(self, monkeypatch):
        monkeypatch.setenv(elastic.ENV_ELASTIC_BUDGET, "0")
        c = elastic.ElasticController("u1")
        assert c.budget == 0
        assert c.request("shrink") is False
        monkeypatch.setenv(elastic.ENV_ELASTIC_BUDGET, "garbage")
        assert elastic.ElasticController("u2").budget == elastic.DEFAULT_BUDGET

    def test_snapshot_consume_dirty_is_write_free_at_steady_state(self):
        c = elastic.ElasticController("u1", budget=1)
        first = c.snapshot(consume_dirty=True)
        assert first == {"budget": 1, "used": 0, "resizing": False,
                         "shrunk": False, "attempts": []}
        assert c.snapshot(consume_dirty=True) is None  # unchanged
        assert c.request("shrink")
        snap = c.snapshot(consume_dirty=True)
        assert snap["used"] == 1 and snap["resizing"] is True
        assert c.snapshot(consume_dirty=True) is None
        # Plain snapshot never consumes.
        assert c.snapshot() is not None

    def test_invalid_direction_raises(self):
        with pytest.raises(ValueError, match="shrink|grow"):
            elastic.ElasticController("u1", budget=1).request("sideways")


# ======================================================== chaos slice-loss
class TestSliceLossSeam:
    def test_restore_only_after_kill(self, tmp_path):
        # The restore fault is LISTED FIRST but cannot fire before a
        # kill has: a plan cannot regrow a gang it never shrank.
        plan = chaos.ChaosPlan.from_dict({"faults": [
            {"seam": "slice-loss", "op": "restore"},
            {"seam": "slice-loss", "op": "kill"},
        ]})
        ckpt = str(tmp_path)
        assert plan.slice_loss_due("u1", ckpt) == "kill"
        assert plan.slice_loss_due("u1", ckpt) == "restore"
        assert plan.slice_loss_due("u1", ckpt) is None
        assert plan.done

    def test_min_checkpoints_gates_without_consuming(self, tmp_path):
        plan = chaos.ChaosPlan.from_dict({"faults": [
            {"seam": "slice-loss", "op": "kill",
             "config": {"min_checkpoints": 2}},
        ]})
        ckpt = tmp_path / "checkpoints"
        ckpt.mkdir()
        (ckpt / "2").mkdir()
        # One persisted step: not an eligible event, nothing consumed.
        for _ in range(3):
            assert plan.slice_loss_due("u1", str(ckpt)) is None
        (ckpt / "4").mkdir()
        assert plan.slice_loss_due("u1", str(ckpt)) == "kill"
        assert plan.done

    def test_wildcard_op_means_kill(self, tmp_path):
        plan = chaos.ChaosPlan.from_dict({"faults": [
            {"seam": "slice-loss", "op": "*"}]})
        assert plan.slice_loss_due("u1", str(tmp_path)) == "kill"


# ========================================================= slice pool resize
class TestSliceManagerElastic:
    def _manager(self):
        from polyaxon_tpu.agent.slices import SliceManager

        return SliceManager([("s0", "2x4", True)])

    def test_shrink_frees_chips_then_regrow(self):
        mgr = self._manager()
        try:
            assert mgr.ensure_placed("r1", "2x4", priority=1) == "running"
            assert not mgr.capacity_available("2x2")
            assert mgr.resize_placement("r1", "2x2", priority=1) == "running"
            assert mgr.placement("r1").topology == "2x2"
            # Partial vacate: half the slice is free again.
            assert mgr.capacity_available("2x2")
            assert mgr.resize_placement("r1", "2x4", priority=1) == "running"
            assert mgr.placement("r1").topology == "2x4"
        finally:
            mgr.close()

    def test_unplaceable_grow_rolls_back_old_footprint(self):
        mgr = self._manager()
        try:
            assert mgr.ensure_placed("r1", "2x2", priority=1) == "running"
            assert mgr.ensure_placed("r2", "2x2", priority=1) == "running"
            # r2 holds the other half: r1's grow cannot place NOW (the
            # pool would park it pending) and must land back on its
            # original chips, still running.
            assert mgr.resize_placement("r1", "2x4", priority=1) != "running"
            placed = mgr.placement("r1")
            assert placed is not None and placed.topology == "2x2"
            assert placed.state == "running"
            mgr.release("r2")
            assert mgr.resize_placement("r1", "2x4", priority=1) == "running"
        finally:
            mgr.close()


# ================================================================= prewarm
class TestPrewarm:
    def test_skip_mode_trusts_the_topology(self):
        out = elastic.prewarm(make_job(), 4, {"dp": 4}, mode="skip")
        assert out == {"ok": True, "mode": "skip", "devices": 4}

    def test_unknown_mode_raises(self):
        with pytest.raises(elastic.PrewarmError, match="unknown prewarm"):
            elastic.prewarm(make_job(), 4, {"dp": 4}, mode="warp")

    def test_mode_read_from_env(self, monkeypatch):
        monkeypatch.setenv(elastic.ENV_ELASTIC_PREWARM, "skip")
        assert elastic.prewarm(make_job(), 4, {"dp": 4})["mode"] == "skip"

    def test_inline_validates_survivor_mesh(self):
        out = elastic.prewarm(make_job(), 4, {"dp": 4}, mode="inline")
        assert out["ok"] and out["mode"] == "inline"
        assert out["devices"] == 4 and out["axes"] == {"dp": 4}

    def test_inline_rejects_more_devices_than_host(self):
        with pytest.raises(elastic.PrewarmError, match="needs 64 devices"):
            elastic.prewarm(make_job(), 64, {"dp": 64}, mode="inline")

    def test_inline_rejects_indivisible_batch(self):
        job = make_job(global_batch_size=6)
        with pytest.raises(elastic.PrewarmError, match="divisible"):
            elastic.prewarm(job, 4, {"dp": 4}, mode="inline")

    def test_child_main_contains_failures_to_one_json_line(self, capsys):
        # Containment contract: a broken target never raises out of the
        # child — one machine-readable line, nonzero exit.
        rc = elastic._child_main([
            "--spec", json.dumps(jaxjob_spec()["component"]["run"]),
            "--devices", "64", "--axes", json.dumps({"dp": 64})])
        assert rc == 1
        lines = capsys.readouterr().out.strip().splitlines()
        payload = json.loads(lines[-1])
        assert payload["ok"] is False
        assert "64 devices" in payload["error"]

    @pytest.mark.slow
    def test_subprocess_prewarm_compiles_one_real_step(self):
        out = elastic.prewarm(make_job(), 4, {"dp": 4}, mode="subprocess",
                              timeout=240.0)
        assert out["ok"] and out["mode"] == "subprocess"
        assert out["devices"] == 4 and out["axes"] == {"dp": 4}


# ======================================================= scheduler interplay
class TestSchedulerResizingHold:
    def test_resizing_run_is_not_a_requeue_candidate(self, plane):
        from polyaxon_tpu.controlplane.scheduler import Scheduler

        record = plane.submit(jaxjob_spec())
        plane.compile_run(record.uuid)
        plane.store.transition(record.uuid, V1Statuses.PREEMPTED,
                               reason="SlicePreempted", force=True)
        meta = dict(plane.get_run(record.uuid).meta or {})
        meta["elastic"] = {"budget": 2, "used": 1, "resizing": True,
                           "shrunk": False, "attempts": []}
        plane.store.update_run(record.uuid, meta=meta)

        sched = Scheduler(plane)
        for _ in range(3):
            sched.tick()
        held = plane.get_run(record.uuid)
        assert held.status == V1Statuses.PREEMPTED
        assert "backoff" not in (held.meta or {})  # no requeue scheduled

        # Flag cleared (resize finished or was flushed failed): the
        # ordinary backoff-requeue path resumes ownership.
        meta["elastic"]["resizing"] = False
        plane.store.update_run(record.uuid, meta=meta)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            sched.tick()
            if plane.get_run(record.uuid).status != V1Statuses.PREEMPTED:
                break
            time.sleep(0.02)
        assert plane.get_run(record.uuid).status != V1Statuses.PREEMPTED


# =========================================================== acceptance drill
class TestElasticDrill:
    def test_shrink_then_regrow_succeeds_with_continuity(
            self, plane, monkeypatch):
        """Acceptance: chaos takes a slice mid-train (shrink 8→4 in
        place), capacity returns (grow 4→8), and the run reaches
        SUCCEEDED without a single requeue round trip — both resizes on
        the timeline, loss-curve continuity certified by the oracle."""
        monkeypatch.setenv(elastic.ENV_ELASTIC_PREWARM, "inline")
        chaos.install(chaos.ChaosPlan.from_dict({"seed": 14, "faults": [
            {"seam": "slice-loss", "op": "kill",
             "config": {"min_checkpoints": 1}},
            {"seam": "slice-loss", "op": "restore",
             "config": {"min_checkpoints": 2}},
        ]}))
        record = plane.submit(jaxjob_spec(steps=12))
        agent = Agent(plane, in_process=True)

        def settled(rec):
            if rec.status == V1Statuses.SUCCEEDED:
                return True
            reasons = [c.get("reason") for c in plane.get_statuses(rec.uuid)]
            assert "RetriesExhausted" not in reasons, reasons
            return False

        final = drive(agent, plane, record.uuid, settled, timeout=420)
        assert final.status == V1Statuses.SUCCEEDED
        # In place: the resize path never paid the PREEMPTED→requeue
        # round trip the pre-elastic behavior would have.
        assert final.retries == 0
        assert "backoff" not in (final.meta or {})

        plan = chaos.active_plan()
        assert plan.done, f"unconsumed faults; fired: {plan.consumed}"
        assert [c["seam"] for c in plan.consumed] == \
            ["slice-loss", "slice-loss"]

        # Each post-resize segment restored tier-0-first: the in-memory
        # replica answered (same process, same artifacts dir), audited
        # into the run meta by the executor's checkpoint flush.
        ckpt_audit = final.meta["checkpoint"]
        assert ckpt_audit["restore_tier"] == "0"
        assert ckpt_audit["restored_from_step"] >= 1

        audit = final.meta["elastic"]
        assert audit["budget"] == 2 and audit["used"] == 2
        assert audit["resizing"] is False and audit["shrunk"] is False
        assert [(a["direction"], a["outcome"], a["from_devices"],
                 a["to_devices"]) for a in audit["attempts"]] == \
            [("shrink", "ok", 8, 4), ("grow", "ok", 4, 8)]
        assert all(a["duration_s"] >= 0 for a in audit["attempts"])

        # Every step trained exactly once across three mesh segments.
        outputs = plane.streams.get_outputs(record.uuid)
        assert outputs["steps"] == 12

        # Both resizes are first-class spans on the ops timeline.
        resizes = [s for s in flat_spans(plane.timeline(record.uuid))
                   if s["name"] == "resize"]
        assert [(s["attributes"]["direction"], s["attributes"]["outcome"])
                for s in sorted(resizes, key=lambda s: s["start"])] == \
            [("shrink", "ok"), ("grow", "ok")]

        # ... and the report attributes their wall time to a dedicated
        # phase, not the `other` bucket.
        report = plane.report(record.uuid)
        assert "resize" in report["phases"]
        assert report["phases"]["resize"]["ms"] > 0

        # The oracle certifies the loss curve never skipped or repeated
        # a step window across either mesh change.
        verdicts = {v["invariant"]: v["verdict"]
                    for v in plane.verify(record.uuid)["verdicts"]}
        assert verdicts["loss-continuity"] == "pass", verdicts

    def test_exhausted_budget_degrades_to_preempt_requeue(
            self, plane, monkeypatch):
        """Acceptance (fallback): with a zero resize budget the same
        slice loss takes the pre-elastic path — PREEMPTED, backoff,
        requeue — and the restarted run still completes."""
        monkeypatch.setenv(elastic.ENV_ELASTIC_BUDGET, "0")
        monkeypatch.setenv(elastic.ENV_ELASTIC_PREWARM, "inline")
        chaos.install(chaos.ChaosPlan.from_dict({"faults": [
            {"seam": "slice-loss", "op": "kill",
             "config": {"min_checkpoints": 1}},
        ]}))
        record = plane.submit(jaxjob_spec(steps=6))
        agent = Agent(plane, in_process=True)
        final = drive(agent, plane, record.uuid,
                      lambda rec: rec.status == V1Statuses.SUCCEEDED,
                      timeout=420)

        conditions = [c["type"] for c in plane.get_statuses(record.uuid)]
        assert "preempted" in conditions
        preempted = [c for c in plane.get_statuses(record.uuid)
                     if c["type"] == "preempted"]
        assert preempted[-1]["reason"] == "SlicePreempted"
        # The requeue went through the backoff gate.
        assert final.meta["backoff"]["preempts"] >= 1
        assert len(final.meta["backoff"]["preempt_delays"]) >= 1
        # The denied channel never spent budget it did not have.
        assert final.meta["elastic"]["used"] == 0
        # The requeued rerun restored tier-0-first: the replica the
        # first attempt published survived the in-process gang death.
        assert final.meta["checkpoint"]["restore_tier"] == "0"
        assert final.meta["checkpoint"]["restored_from_step"] >= 1
        assert plane.streams.get_outputs(record.uuid)["steps"] == 6
        # Preemption is a death the operator did not ask for: the black
        # box landed next to the run artifacts.
        assert os.path.exists(os.path.join(
            plane.run_artifacts_dir(record.uuid), "postmortem.json"))


# ===================================================== prewarm-failure paths
class TestPrewarmFailureFallbacks:
    @pytest.mark.slow
    def test_failed_shrink_prewarm_falls_back_to_requeue(
            self, plane, monkeypatch):
        """A shrink whose survivor mesh cannot be validated must NOT
        strand the run: ResizeAborted → PREEMPTED → backoff requeue,
        and the rerun (fault budget spent) completes."""
        def doomed(job, n, axes, **kw):
            raise elastic.PrewarmError("induced: survivor mesh rejected")

        monkeypatch.setattr(elastic, "prewarm", doomed)
        chaos.install(chaos.ChaosPlan.from_dict({"faults": [
            {"seam": "slice-loss", "op": "kill",
             "config": {"min_checkpoints": 1}},
        ]}))
        record = plane.submit(jaxjob_spec(steps=6))
        agent = Agent(plane, in_process=True)
        final = drive(agent, plane, record.uuid,
                      lambda rec: rec.status == V1Statuses.SUCCEEDED,
                      timeout=420)

        audit = final.meta["elastic"]
        assert audit["attempts"][0]["direction"] == "shrink"
        assert audit["attempts"][0]["outcome"] == "failed"
        assert "induced" in audit["attempts"][0]["error"]
        assert audit["resizing"] is False  # never strands the hold
        conditions = [c["type"] for c in plane.get_statuses(record.uuid)]
        assert "preempted" in conditions
        assert plane.streams.get_outputs(record.uuid)["steps"] == 6
        assert os.path.exists(os.path.join(
            plane.run_artifacts_dir(record.uuid), "postmortem.json"))

    @pytest.mark.slow
    def test_failed_grow_prewarm_keeps_training_shrunk(
            self, plane, monkeypatch):
        """A grow that cannot prewarm is a non-event for the run: it
        stays on the shrunk mesh, records the failed attempt (plus a
        postmortem for the evidence trail), and still SUCCEEDS."""
        real = elastic._prewarm_inline

        def grow_doomed(job, n, axes, **kw):
            if n > 4:
                raise elastic.PrewarmError("induced: capacity flapped away")
            return real(job, n, axes, devices=kw.get("devices"))

        monkeypatch.setattr(elastic, "prewarm", grow_doomed)
        chaos.install(chaos.ChaosPlan.from_dict({"faults": [
            {"seam": "slice-loss", "op": "kill",
             "config": {"min_checkpoints": 1}},
            {"seam": "slice-loss", "op": "restore",
             "config": {"min_checkpoints": 2}},
        ]}))
        record = plane.submit(jaxjob_spec(steps=8))
        agent = Agent(plane, in_process=True)
        final = drive(agent, plane, record.uuid,
                      lambda rec: rec.status == V1Statuses.SUCCEEDED,
                      timeout=420)

        assert final.retries == 0  # the run itself never died
        audit = final.meta["elastic"]
        assert [(a["direction"], a["outcome"])
                for a in audit["attempts"]] == \
            [("shrink", "ok"), ("grow", "failed")]
        assert audit["shrunk"] is True  # finished on the survivor mesh
        assert plane.streams.get_outputs(record.uuid)["steps"] == 8
        # The failed resize dumped the flight ring even though the run
        # survived it.
        assert os.path.exists(os.path.join(
            plane.run_artifacts_dir(record.uuid), "postmortem.json"))
