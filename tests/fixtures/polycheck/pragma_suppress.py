"""Pragma semantics: reasoned suppression (above-line and trailing)
silences the rule; a reason-less pragma is itself a finding AND does
not suppress (golden: pragma-syntax + the unsuppressed swallow)."""
import threading
import time

_mutex = threading.Lock()


def quiet_sleep():
    with _mutex:
        # polycheck: ignore[lock-blocking-call] -- fixture: reasoned suppression on the line above
        time.sleep(0.01)


def trailing(risky):
    try:
        return risky()
    except Exception:  # polycheck: ignore[invariant-swallow] -- fixture: reasoned trailing suppression
        pass


def unreasoned(risky):
    try:
        return risky()
    except Exception:
        # polycheck: ignore[invariant-swallow]
        pass
