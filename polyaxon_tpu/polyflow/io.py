"""Typed inputs/outputs (``V1IO``) and params (``V1Param``).

Capability parity with the reference's ``polyaxon/polyflow/io`` +
``polyflow/params`` (SURVEY.md §2 [K]): components declare typed IO with
defaults/optionality; operations bind params by value or by reference to
another run's outputs; params can be routed into init containers
(``toInit``) or the process env (``toEnv``), or kept context-only.
"""

from __future__ import annotations

import datetime as _dt
from typing import Any, Optional, Union

from pydantic import field_validator

from polyaxon_tpu.schemas.base import BaseSchema


class IOTypes:
    ANY = "any"
    INT = "int"
    FLOAT = "float"
    BOOL = "bool"
    STR = "str"
    DICT = "dict"
    LIST = "list"
    URI = "uri"
    AUTH = "auth"
    PATH = "path"
    METRIC = "metric"
    METADATA = "metadata"
    DATETIME = "datetime"
    DATE = "date"
    UUID = "uuid"
    GIT = "git"
    IMAGE = "image"
    DOCKERFILE = "dockerfile"
    EVENT = "event"
    ARTIFACTS = "artifacts"
    TENSORBOARD = "tensorboard"
    # TPU-native addition: a slice topology literal such as "v5e-64" or
    # "2x4" — validated by the compiler against the accelerator catalog.
    TPU_TOPOLOGY = "tpu_topology"

    VALUES = {
        ANY, INT, FLOAT, BOOL, STR, DICT, LIST, URI, AUTH, PATH, METRIC,
        METADATA, DATETIME, DATE, UUID, GIT, IMAGE, DOCKERFILE, EVENT,
        ARTIFACTS, TENSORBOARD, TPU_TOPOLOGY,
    }


_TRUE = {"true", "1", "yes", "y", "on", "t"}
_FALSE = {"false", "0", "no", "n", "off", "f"}


def parse_value(value: Any, type_: Optional[str], *, name: str = "") -> Any:
    """Coerce/validate ``value`` against an IO type name."""
    if value is None or type_ in (None, IOTypes.ANY):
        return value
    try:
        if type_ == IOTypes.INT:
            if isinstance(value, bool):
                raise ValueError
            if isinstance(value, float) and not value.is_integer():
                raise ValueError
            return int(value)
        if type_ in (IOTypes.FLOAT, IOTypes.METRIC):
            if isinstance(value, bool):
                raise ValueError
            return float(value)
        if type_ == IOTypes.BOOL:
            if isinstance(value, bool):
                return value
            text = str(value).strip().lower()
            if text in _TRUE:
                return True
            if text in _FALSE:
                return False
            raise ValueError
        if type_ in (IOTypes.STR, IOTypes.URI, IOTypes.PATH, IOTypes.IMAGE,
                     IOTypes.UUID, IOTypes.TPU_TOPOLOGY):
            if isinstance(value, (dict, list)):
                raise ValueError
            return str(value)
        if type_ in (IOTypes.DICT, IOTypes.METADATA, IOTypes.GIT,
                     IOTypes.DOCKERFILE, IOTypes.EVENT, IOTypes.ARTIFACTS,
                     IOTypes.AUTH, IOTypes.TENSORBOARD):
            if not isinstance(value, dict):
                raise ValueError
            return value
        if type_ == IOTypes.LIST:
            if not isinstance(value, list):
                raise ValueError
            return value
        if type_ in (IOTypes.DATETIME, IOTypes.DATE):
            if isinstance(value, (_dt.datetime, _dt.date)):
                return value
            return _dt.datetime.fromisoformat(str(value))
    except (TypeError, ValueError):
        raise ValueError(
            f"Param{' ' + name if name else ''}: value {value!r} is not a valid `{type_}`"
        ) from None
    raise ValueError(f"Unknown IO type `{type_}`")


class V1IO(BaseSchema):
    name: str
    description: Optional[str] = None
    type: Optional[str] = None
    value: Optional[Any] = None
    is_optional: Optional[bool] = None
    is_list: Optional[bool] = None
    is_flag: Optional[bool] = None
    arg_format: Optional[str] = None
    connection: Optional[str] = None
    to_init: Optional[bool] = None
    to_env: Optional[str] = None
    options: Optional[list[Any]] = None

    @field_validator("type")
    @classmethod
    def _check_type(cls, v: Optional[str]) -> Optional[str]:
        if v is not None and v not in IOTypes.VALUES:
            raise ValueError(f"Unknown IO type `{v}`")
        return v

    def validate_value(self, value: Any) -> Any:
        if value is None:
            if self.is_optional or self.value is not None:
                return self.value
            raise ValueError(f"Input `{self.name}` is required and no value was provided")
        if self.is_list:
            if not isinstance(value, list):
                raise ValueError(f"Input `{self.name}` expects a list, got {value!r}")
            value = [parse_value(item, self.type, name=self.name) for item in value]
        else:
            value = parse_value(value, self.type, name=self.name)
        if self.options and value not in self.options:
            raise ValueError(
                f"Input `{self.name}`: {value!r} not in allowed options {self.options}"
            )
        return value


class V1Param(BaseSchema):
    value: Optional[Any] = None
    ref: Optional[str] = None
    connection: Optional[str] = None
    context_only: Optional[bool] = None
    to_init: Optional[bool] = None
    to_env: Optional[str] = None

    @property
    def is_ref(self) -> bool:
        return self.ref is not None

    @property
    def is_runs_ref(self) -> bool:
        return bool(self.ref) and self.ref.startswith("runs.")

    @property
    def is_ops_ref(self) -> bool:
        return bool(self.ref) and self.ref.startswith("ops.")

    def get_ref_parts(self) -> tuple[str, str, str]:
        """``runs.<uuid>.outputs.<name>`` → ("runs", "<uuid>", "outputs.<name>")."""
        if not self.ref:
            raise ValueError("Param has no ref")
        parts = self.ref.split(".", 2)
        if len(parts) != 3:
            raise ValueError(f"Malformed param ref `{self.ref}`")
        return parts[0], parts[1], parts[2]


def validate_params_against_io(
    params: Optional[dict[str, V1Param]],
    inputs: Optional[list[V1IO]],
    outputs: Optional[list[V1IO]] = None,
    *,
    allow_extra: bool = False,
    provided_externally: Optional[set[str]] = None,
) -> dict[str, Any]:
    """Check every non-ref param against declared IO and fill defaults.

    Returns the fully-resolved ``{name: value}`` mapping the interpolation
    context will expose as ``params.*``.
    """
    params = dict(params or {})
    declared_inputs = {io.name: io for io in (inputs or [])}
    declared = dict(declared_inputs)
    declared.update({io.name: io for io in (outputs or []) if io.name not in declared})
    resolved: dict[str, Any] = {}
    for name, param in params.items():
        if param.context_only:
            continue
        if name not in declared:
            if allow_extra:
                resolved[name] = param.value
                continue
            raise ValueError(
                f"Param `{name}` was provided but the component declares no matching input/output"
            )
        if param.is_ref:
            # Ref params are resolved by the compiler once the upstream run
            # exists; type checking is deferred to resolution time.
            continue
        resolved[name] = declared[name].validate_value(param.value)
    # Only *inputs* can be required: outputs are produced by the run.
    for name, io in declared_inputs.items():
        if name in resolved:
            continue
        param = params.get(name)
        if param is not None and param.is_ref:
            continue
        if provided_externally and name in provided_externally:
            # A matrix/join/tuner binds this param per-trial at compile time.
            continue
        resolved[name] = io.validate_value(None)
    return resolved
