from polyaxon_tpu.fs.store import (
    LocalStore,
    MemoryStore,
    Store,
    StoreError,
    get_store,
    register_store,
)

__all__ = [
    "LocalStore",
    "MemoryStore",
    "Store",
    "StoreError",
    "get_store",
    "register_store",
]
