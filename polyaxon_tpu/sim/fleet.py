"""The fleet replayer: real control plane, synthetic slices, one loop.

``FleetSim`` owns a throwaway ``ControlPlane`` home, a real ``Agent``
whose executor is the ``SyntheticExecutor``, and the catalog (queues,
tenant quotas) every trace assumes. It replays a trace in compressed
wall time, measures every reconcile tick (wall seconds + store query /
row deltas from ``Store.stats``), and exposes the same numbers the
budget gate and bench entry point consume.

Nothing under test is mocked: scheduler ticks, admission passes, and
every store access are the production code paths.
"""

from __future__ import annotations

import os
import shutil
import statistics
import tempfile
import time

from polyaxon_tpu.agent import Agent
from polyaxon_tpu.controlplane import ControlPlane
from polyaxon_tpu.controlplane.scheduler import Scheduler
from polyaxon_tpu.lifecycle import V1Statuses
from polyaxon_tpu.obs import history as obs_history
from polyaxon_tpu.obs import metrics as obs_metrics
from polyaxon_tpu.scheduling import AdmissionController
from polyaxon_tpu.sim import traces
from polyaxon_tpu.sim.executor import SyntheticExecutor

# Synthetic workload meta hints (read by SyntheticExecutor).
_SERVING_DURATION = 30.0  # deploys hold capacity ~forever at sim scale
_CHURN_FAILURE_RATE = 0.7
_ELASTIC_DURATION = 4.0  # elastic train jobs outlive the resize lane
_STORM_WINDOW = 3.0  # marked-window span a storm event opens (sim seconds)


def percentile(values: list[float], q: float) -> float:
    if not values:
        return 0.0
    ordered = sorted(values)
    idx = min(len(ordered) - 1, max(0, round(q * (len(ordered) - 1))))
    return ordered[idx]


class FleetSim:
    def __init__(self, home: str | None = None, *, capacity: int = 64,
                 seed: int = 0, incremental: bool = True,
                 legacy_scan: bool = False, deopt: bool = False,
                 mean_duration: float = 0.05, failure_rate: float = 0.02,
                 rebuild_ticks: int = 50, checkpoint_lane: bool = False):
        self._tmp = None
        if home is None:
            self._tmp = tempfile.mkdtemp(prefix="polyaxon-sim-")
            home = self._tmp
        self.plane = ControlPlane(home)
        self.store = self.plane.store
        self.executor = SyntheticExecutor(
            self.plane, mean_duration=mean_duration,
            failure_rate=failure_rate, seed=seed,
            checkpoint_dir=(os.path.join(home, "ckpt-tiers")
                            if checkpoint_lane else None))
        self.admission = AdmissionController(
            self.plane, incremental=incremental,
            rebuild_ticks=rebuild_ticks)
        self.agent = Agent(self.plane, executor=self.executor,
                           max_concurrent=capacity,
                           admission=self.admission)
        self.agent.scheduler = Scheduler(self.plane,
                                         legacy_scan=legacy_scan)
        if deopt:
            # The "what CI must catch" baseline: hot index dropped,
            # same-tick write batching off (store), six-scan scheduler,
            # full-rebuild admission — à la PR 4's --inject-reshard.
            self.store.deoptimize()
        for q in traces.QUEUES:
            self.plane.upsert_queue(q["name"], priority=q["priority"],
                                    preemptible=q["preemptible"])
        weights = {"platform": 2.0, "research": 1.0, "serving": 4.0,
                   "growth": 1.0}
        for project, weight in weights.items():
            self.plane.set_quota(project, weight=weight)
        self._depth_gauge = obs_metrics.REGISTRY.gauge(
            "polyaxon_queue_depth", "Queued runs per queue", ("queue",))
        # Per-tick measurements (parallel lists).
        self.tick_seconds: list[float] = []
        self.tick_queries: list[int] = []
        self.tick_rows: list[int] = []
        self.submitted_total = 0
        self._elastic_uuids: list[str] = []  # slice-loss lane targets
        self._open_windows: dict[str, float] = {}  # name -> close deadline

    # ------------------------------------------------------------ submit
    def _submit_event(self, event: traces.TraceEvent) -> None:
        if event.kind == "storm":
            payload = event.payload or {}
            fraction = float(payload.get("fraction", 0.5))
            # The storm opens (or extends) a named history window so
            # during-window oracle invariants can scope to it; tick()
            # closes it once the window span elapses.
            window = str(payload.get("window", "storm"))
            deadline = time.monotonic() + float(
                payload.get("window_seconds", _STORM_WINDOW))
            if window not in self._open_windows:
                obs_history.default_history().mark_window(
                    window, start=True)
            self._open_windows[window] = max(
                self._open_windows.get(window, 0.0), deadline)
            active = self.executor.active_runs
            for uuid in active[: int(len(active) * fraction)]:
                self.executor.preempt(uuid)
            return
        if event.kind == "slice-loss":
            # Elastic lane: "kill" shrinks a live elastic gang in place,
            # "restore" offers the grow back — the sim twin of the
            # chaos slice-loss seam (runtime.elastic / ISSUE 14).
            op = (event.payload or {}).get("op", "kill")
            direction = "shrink" if op == "kill" else "grow"
            for uuid in self._elastic_uuids:
                if uuid in self.executor.active_runs:
                    self.executor.request_resize(
                        uuid, direction, reason="ChaosSliceLoss")
                    break
            return
        record = self.plane.submit(event.spec, project=event.project)
        hints = {}
        if event.kind == "serving":
            hints["sim_duration"] = _SERVING_DURATION
        elif event.kind == "churn":
            hints["sim_failure_rate"] = _CHURN_FAILURE_RATE
        elif event.kind == "elastic":
            hints["sim_duration"] = _ELASTIC_DURATION
            self._elastic_uuids.append(record.uuid)
        if hints:
            meta = dict(record.meta or {})
            meta.update(hints)
            self.store.update_run(record.uuid, meta=meta)
        self.submitted_total += 1

    def submit_queued_jobs(self, n: int, *, compile: bool = True) -> None:
        """Load-point setup: ``n`` compiled QUEUED jobs, batched writes."""
        rng_queues = ("batch", "best-effort", None)
        uuids = []
        for i in range(n):
            with self.store.transaction():
                record = self.plane.submit(
                    traces.job_op(queue=rng_queues[i % 3]),
                    project=traces.PROJECTS[i % len(traces.PROJECTS)])
                uuids.append(record.uuid)
                if compile:
                    self.plane.compile_run(record.uuid)

    # -------------------------------------------------------------- tick
    def tick(self) -> None:
        """One measured reconcile pass (the real ``Agent`` loop)."""
        stats = self.store.stats
        q0, r0 = stats["queries"], stats["rows"]
        t0 = time.perf_counter()
        self.agent.reconcile_once()
        self.tick_seconds.append(time.perf_counter() - t0)
        self.tick_queries.append(stats["queries"] - q0)
        self.tick_rows.append(stats["rows"] - r0)
        self._depth_gauge.set(
            self.store.count_runs(statuses=[V1Statuses.QUEUED]),
            queue="fleet")
        if self._open_windows:
            self._close_due_windows(time.monotonic())

    def _close_due_windows(self, now: float) -> None:
        for name, deadline in list(self._open_windows.items()):
            if now >= deadline:
                obs_history.default_history().mark_window(name, end=True)
                del self._open_windows[name]

    def reset_measurements(self) -> None:
        self.tick_seconds.clear()
        self.tick_queries.clear()
        self.tick_rows.clear()

    def tick_report(self) -> dict:
        """Aggregate the measurement window into the curve-point shape."""
        return {
            "ticks": len(self.tick_seconds),
            "tick_p50_ms": round(
                percentile(self.tick_seconds, 0.50) * 1e3, 3),
            "tick_p99_ms": round(
                percentile(self.tick_seconds, 0.99) * 1e3, 3),
            "queries_per_tick_p50": int(
                statistics.median(self.tick_queries)
                if self.tick_queries else 0),
            "queries_per_tick_max": max(self.tick_queries, default=0),
            "rows_per_tick_p50": int(
                statistics.median(self.tick_rows)
                if self.tick_rows else 0),
            "rows_per_tick_max": max(self.tick_rows, default=0),
        }

    # ------------------------------------------------------------- replay
    def run_trace(self, events: list[traces.TraceEvent], *,
                  max_wall: float = 600.0, drain: bool = True) -> dict:
        """Replay a trace in compressed wall time, then drain.

        Each loop iteration submits every event whose offset has come
        due and runs one measured tick — so a burst of arrivals lands
        inside a single tick exactly like a real agent under a thundering
        herd, and tick latency reflects it.
        """
        start = time.monotonic()
        idx = 0
        while True:
            now = time.monotonic() - start
            while idx < len(events) and events[idx].at <= now:
                self._submit_event(events[idx])
                idx += 1
            self.tick()
            if idx >= len(events):
                if not drain:
                    break
                if self.idle():
                    break
            if time.monotonic() - start > max_wall:
                break
        return {
            "events": idx,
            "submitted": self.submitted_total,
            "started": self.executor.started_total,
            "reaped": self.executor.reaped_total,
            "wall_seconds": round(time.monotonic() - start, 3),
            "divergence_total": self.admission.divergence_total,
            "rebuild_checks": self.admission.rebuild_checks,
            **self.tick_report(),
        }

    def idle(self) -> bool:
        """Fleet fully drained: nothing schedulable, nothing live."""
        if self.executor.active_runs:
            return False
        pending = self.store.count_runs(statuses=[
            V1Statuses.CREATED, V1Statuses.QUEUED, V1Statuses.SCHEDULED,
            V1Statuses.STARTING, V1Statuses.RUNNING, V1Statuses.STOPPING,
            V1Statuses.PREEMPTED, V1Statuses.RETRYING])
        return pending == 0

    def measure_ticks(self, n: int) -> dict:
        """Measure ``n`` steady-state reconcile ticks (no arrivals)."""
        self.reset_measurements()
        for _ in range(n):
            self.tick()
        return self.tick_report()

    def measure_scheduler_ticks(self, n: int) -> dict:
        """Measure the scheduler tick ALONE (the ISSUE 8 A/B unit):
        isolates the six-scan vs single-pass cost from admission."""
        samples = []
        for _ in range(n):
            t0 = time.perf_counter()
            self.agent.scheduler.tick()
            samples.append(time.perf_counter() - t0)
        return {
            "ticks": n,
            "sched_tick_p50_ms": round(percentile(samples, 0.50) * 1e3, 3),
            "sched_tick_p99_ms": round(percentile(samples, 0.99) * 1e3, 3),
        }

    def close(self) -> None:
        if self._open_windows:
            # Never leave a marker dangling past the sim's lifetime.
            self._close_due_windows(float("inf"))
        self.executor.close_checkpoints()
        if self._tmp:
            shutil.rmtree(self._tmp, ignore_errors=True)
