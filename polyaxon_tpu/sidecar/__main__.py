"""``python -m polyaxon_tpu.sidecar`` — the sidecar process entrypoint
spawned next to each run's main process by the executor."""

from __future__ import annotations

import argparse
import os
import signal
import sys
import time

from polyaxon_tpu.sidecar.sync import SidecarSync


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--run-dir", required=True)
    parser.add_argument("--store-dir", required=True)
    parser.add_argument("--interval", type=float, default=5.0)
    args = parser.parse_args()

    sync = SidecarSync(args.run_dir, args.store_dir, args.interval)
    stop = {"flag": False}

    def handle(signum, frame):
        stop["flag"] = True

    signal.signal(signal.SIGTERM, handle)
    signal.signal(signal.SIGINT, handle)

    while not stop["flag"]:
        try:
            sync.sync_once()
        except Exception as exc:
            print(f"sidecar sync error: {exc}", file=sys.stderr)
        time.sleep(args.interval)
    sync.sync_once()
    return 0


if __name__ == "__main__":
    sys.exit(main())
