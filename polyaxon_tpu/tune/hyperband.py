"""Hyperband successive halving (Li et al., JMLR 2018) — [B] names it as
a core Polytune capability; bracket math per the paper:

    s_max = floor(log_eta R);  B = (s_max+1) R
    bracket s: n = ceil((s_max+1) eta^s / (s+1)),  r = R eta^-s
    rung i in bracket s: n_i = floor(n eta^-i) configs at r_i = r eta^i

Preemption-safe rung accounting (SURVEY.md §7 hard-part 4): a PREEMPTED
trial is *re-issued with the same params and budget* instead of scoring
as a failure — failures score as worst, preemptions never poison the
bracket.
"""

from __future__ import annotations

import dataclasses
import math
import random
from typing import Any, Optional

from polyaxon_tpu.polyflow.matrix import V1Hyperband
from polyaxon_tpu.tune.base import Observation, Params, top_k


@dataclasses.dataclass
class Rung:
    bracket: int
    rung: int
    n_configs: int
    resource: int | float
    suggestions: list[Params]


class HyperbandManager:
    def __init__(self, config: V1Hyperband):
        self.config = config
        self.rng = random.Random(config.seed)

    # -- static structure --------------------------------------------------
    def brackets(self) -> list[int]:
        """Bracket ids, most exploratory first (s_max → 0)."""
        return list(range(self.config.s_max, -1, -1))

    def rungs_in_bracket(self, s: int) -> int:
        return s + 1

    def rung_shape(self, s: int, i: int) -> tuple[int, int | float]:
        """(n_i, r_i) for rung ``i`` of bracket ``s``."""
        n, r = self.config.bracket(s)
        n_i = int(math.floor(n * self.config.eta ** (-i)))
        r_i = r * (self.config.eta**i)
        resource = self.config.resource.cast(
            min(r_i, self.config.max_iterations)
        )
        return max(n_i, 1), resource

    def total_trials(self) -> int:
        return sum(self.rung_shape(s, 0)[0] for s in self.brackets())

    # -- iteration ---------------------------------------------------------
    def sample_params(self, n: int, rng: Optional[random.Random] = None) -> list[Params]:
        rng = rng or self.rng
        return [
            {name: hp.sample(rng) for name, hp in self.config.params.items()}
            for _ in range(n)
        ]

    def first_rung(self, s: int) -> Rung:
        n, resource = self.rung_shape(s, 0)
        # Per-bracket RNG: deterministic under manager re-instantiation
        # (the scheduler rebuilds the manager every tick) yet distinct
        # across brackets — each bracket must draw FRESH configs.
        base_seed = self.config.seed if self.config.seed is not None else 0
        rng = random.Random((base_seed << 16) + s)
        return Rung(bracket=s, rung=0, n_configs=n, resource=resource,
                    suggestions=self.sample_params(n, rng))

    def next_rung(self, s: int, i: int, observations: list[Observation]) -> Optional[Rung]:
        """Promote the top 1/eta of rung ``i`` into rung ``i+1``; None when
        the bracket is exhausted."""
        if i + 1 > s:
            return None
        n_next, resource = self.rung_shape(s, i + 1)
        survivors = top_k(observations, self.config.metric, n_next)
        if not survivors:
            return None
        return Rung(
            bracket=s, rung=i + 1, n_configs=len(survivors), resource=resource,
            suggestions=[dict(o.params) for o in survivors],
        )

    def reissue_preempted(self, observations: list[Observation]) -> list[Params]:
        """Params of preempted trials to requeue at the same rung."""
        return [dict(o.params) for o in observations if o.status == "preempted"]

    def resource_param(self) -> str:
        return self.config.resource.name
