"""North-star acceptance (BASELINE.md / SURVEY.md §6): a
Polyaxonfile-driven Llama pretrain with the tpu preset swap, plus a
Hyperband sweep whose trials are real JAXJobs — end-to-end through the
control plane, scheduler, agent, tracking, and runtime, no GPU anywhere.
Scaled to the test environment (tiny model, 8-device virtual CPU mesh)."""

import textwrap

import pytest

from polyaxon_tpu.agent import Agent
from polyaxon_tpu.controlplane import ControlPlane
from polyaxon_tpu.lifecycle import V1Statuses

LLAMA_PRETRAIN = textwrap.dedent(
    """
    version: 1.1
    kind: operation
    name: llama-pretrain
    params:
      lr: {value: 0.001}
    component:
      name: llama
      inputs:
        - name: lr
          type: float
      run:
        kind: jaxjob
        numProcesses: 1
        mesh:
          axes: {dp: 2, fsdp: 4}
        checkpointing:
          enabled: true
          intervalSteps: 2
        runtime:
          model: llama_tiny
          dataset: lm_synthetic
          steps: 4
          seq_len: 128
          global_batch_size: 8
          learning_rate: "{{ params.lr }}"
    """
)

HYPERBAND_SWEEP = {
    "kind": "operation",
    "name": "lr-sweep",
    "matrix": {
        "kind": "hyperband",
        "maxIterations": 4,
        "eta": 2,
        "resource": {"name": "steps", "type": "int"},
        "metric": {"name": "loss", "optimization": "minimize"},
        "resume": False,
        "seed": 7,
        # loguniform takes natural-log bounds: lr in [exp(-9.2), exp(-2.3)]
        # ≈ [1e-4, 1e-1].
        "params": {"lr": {"kind": "loguniform", "value": {"low": -9.2, "high": -2.3}}},
    },
    "component": {
        "inputs": [
            {"name": "lr", "type": "float"},
            {"name": "steps", "type": "int", "value": 2, "isOptional": True},
        ],
        "run": {
            "kind": "jaxjob",
            "mesh": {"axes": {"dp": 8}},
            "runtime": {
                "model": "llama_tiny",
                "dataset": "lm_synthetic",
                "steps": "{{ params.steps }}",
                "seq_len": 64,
                "global_batch_size": 8,
                "learning_rate": "{{ params.lr }}",
            },
        },
    },
}


@pytest.fixture()
def plane(tmp_path):
    return ControlPlane(str(tmp_path / "home"))


class TestNorthStar:
    def test_llama_pretrain_with_tpu_preset(self, plane, tmp_path):
        """The [B] bar: an existing Polyaxonfile runs unchanged after
        swapping the environment preset from gpu to tpu."""
        path = tmp_path / "llama.yaml"
        path.write_text(LLAMA_PRETRAIN)
        record = plane.submit(
            str(path), presets=["tests/fixtures/presets/tpu.yaml"])
        # The preset lands as a runPatch on the operation...
        tpu = record.spec["runPatch"]["environment"]["tpu"]
        assert tpu["accelerator"] == "v5e" and tpu["preemptible"] is True

        agent = Agent(plane, in_process=True)
        status = agent.run_until_done(record.uuid, timeout=300)
        assert status == V1Statuses.SUCCEEDED

        # ...and is applied onto the resolved run at compile time.
        resolved = plane.get_run(record.uuid).resolved_spec
        resolved_tpu = resolved["component"]["run"]["environment"]["tpu"]
        assert resolved_tpu["accelerator"] == "v5e"

        # Tracking contract: metrics flowed, checkpoint written.
        metrics = plane.streams.get_metrics(record.uuid, ["loss"])
        assert metrics["loss"], "no loss events tracked"
        outputs = plane.streams.get_outputs(record.uuid)
        assert outputs["steps"] == 4
        arts = plane.streams.list_artifacts(record.uuid)
        assert any("checkpoints" in a for a in arts)

    def test_hyperband_sweep_of_jaxjobs(self, plane):
        """Polytune Hyperband where every trial is a real JAXJob."""
        record = plane.submit(HYPERBAND_SWEEP)
        agent = Agent(plane, max_concurrent=2, in_process=True)
        status = agent.run_until_done(record.uuid, timeout=600)
        assert status == V1Statuses.SUCCEEDED
        trials = plane.list_runs(pipeline_uuid=record.uuid)
        assert len(trials) >= 3  # first rung + ≥1 promotion
        assert any(t.status == V1Statuses.SUCCEEDED for t in trials)
        # Promoted trials trained with more steps (the hyperband resource).
        rungs = {(t.meta or {}).get("rung", 0) for t in trials}
        assert max(rungs) >= 1
        steps_by_rung = {}
        for t in trials:
            rung = (t.meta or {}).get("rung", 0)
            steps_by_rung.setdefault(rung, set()).add(
                t.meta["trial_params"]["steps"])
        assert min(steps_by_rung[max(rungs)]) > min(steps_by_rung[0])



    def test_sweep_best_trial_serves(self, plane, monkeypatch):
        """The COMPOSED product loop the north star describes: tune →
        pick the best trial by its metric → serve that trial's own
        checkpoint. No GPU, no user code anywhere in the chain."""
        import copy
        import json as _json
        import os
        import urllib.request

        from polyaxon_tpu.serving import ServingServer

        sweep = copy.deepcopy(HYPERBAND_SWEEP)
        runtime = sweep["component"]["run"]["runtime"]
        runtime["log_every"] = 1
        sweep["component"]["run"]["checkpointing"] = {
            "enabled": True, "intervalSteps": 1, "asyncSave": False}
        record = plane.submit(sweep)
        agent = Agent(plane, max_concurrent=2, in_process=True)
        assert agent.run_until_done(record.uuid,
                                    timeout=600) == V1Statuses.SUCCEEDED

        trials = plane.list_runs(pipeline_uuid=record.uuid)
        scored = [(plane.get_metric(t.uuid, "loss"), t)
                  for t in trials if t.status == V1Statuses.SUCCEEDED]
        scored = [(v, t) for v, t in scored if v is not None]
        assert scored, "no succeeded trial carries the sweep metric"
        best = min(scored, key=lambda vt: vt[0])[1]

        ckpt = os.path.join(plane.run_artifacts_dir(best.uuid),
                            "checkpoints")
        assert os.path.isdir(ckpt), "best trial left no checkpoint"
        with ServingServer("llama_tiny", ckpt) as server:
            req = urllib.request.Request(
                server.url + "/v1/generate", method="POST",
                data=_json.dumps({"tokens": [[5, 6, 7]],
                                  "max_new_tokens": 5}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=120) as resp:
                out = _json.load(resp)
        assert len(out["tokens"][0]) == 5


class TestEstimate:
    def test_bench_estimate_contract(self):
        """bench.py --estimate: the roofline/MFU-transfer projection
        (VERDICT r2 item 8) emits one JSON line with labeled
        assumptions and proves the sharded step compiles — exercised
        on the tiny config so CI stays fast."""
        import json
        import os
        import subprocess
        import sys

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        proc = subprocess.run(
            [sys.executable, os.path.join(repo, "bench.py"),
             "--estimate", "llama_tiny", "--seq", "64", "--batch", "2"],
            capture_output=True, text=True, timeout=600, cwd=repo)
        assert proc.returncode == 0, proc.stderr[-800:]
        line = json.loads(proc.stdout.strip().splitlines()[-1])
        assert line["unit"] == "tokens/sec/chip"
        assert line["value"] > 0
        assert line["sharded_step_compiles"] is True
        assert line["roofline_upper_bound_mfu1"] >= line["value"]
        assert line["kind"] in ("mfu_transfer_estimate",
                                "roofline_upper_bound_mfu1")
        assert "peak_bf16_tflops" in line["assumptions"]
        assert line["flops_per_token"] > 0
