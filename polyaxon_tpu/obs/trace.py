"""Run-lifecycle tracing (ISSUE 5 tentpole): Dapper-shaped spans over
the whole orchestration spine.

The trace id IS the run uuid; spans carry parent links and typed
attributes, and persist as ``events/span/lifecycle.jsonl`` under the
run's artifacts dir — the existing :class:`EventWriter` contract — so
the sidecar ships timelines to the store and streams serve them back
with zero new plumbing. Producers across process boundaries:

- control plane: ``compile`` (ControlPlane.compile_run);
- agent: ``admission`` (the pass that cleared the run), ``placement``
  (slice-pool clearance), ``execute`` (gang lifetime) with an ``init``
  child per start attempt;
- runtime loop: ``runtime`` → ``jit_compile`` / ``restore`` / ``step``
  (one per metrics-emission window, reusing ``step_time_ms`` /
  ``input_wait_ms``) / ``checkpoint`` / ``eval``;
- sidecar: ``sync`` per pass that shipped files.

Propagation follows the graft-entry env plumbing: the executor stamps
``POLYAXON_TRACE_PARENT=<trace_id>:<span_id>`` into every gang
process's env, and :meth:`RunTracer.from_env` picks it up so subprocess
runtime spans parent under the agent's ``execute`` span.

Cross-cutting seams attach ANNOTATIONS instead of spans: the active
span rides a per-thread :mod:`contextvars` slot, and
:func:`add_event` lets deep layers (chaos fault firings, retry
attempts) stamp events onto whatever lifecycle phase is running —
that is how a chaos drill reads as an annotated timeline instead of a
log-archaeology session.
"""

from __future__ import annotations

import contextlib
import contextvars
import datetime as _dt
import json
import logging
import os
import time
from dataclasses import dataclass, field
from typing import Any, Iterator, Optional

from polyaxon_tpu.tracking.events import EventWriter, V1EventKind, read_jsonl

ENV_TRACE_PARENT = "POLYAXON_TRACE_PARENT"
SPAN_STREAM = "lifecycle"  # events/span/lifecycle.jsonl


def _iso(epoch: float) -> str:
    return _dt.datetime.fromtimestamp(
        epoch, _dt.timezone.utc).isoformat()


def new_span_id() -> str:
    return os.urandom(8).hex()


@dataclass
class Span:
    trace_id: str
    name: str
    span_id: str = field(default_factory=new_span_id)
    parent_id: Optional[str] = None
    component: str = ""
    start: float = field(default_factory=time.time)
    end: Optional[float] = None
    status: str = "ok"
    error: Optional[str] = None
    attributes: dict[str, Any] = field(default_factory=dict)
    events: list[dict[str, Any]] = field(default_factory=list)

    def set(self, **attrs: Any) -> "Span":
        self.attributes.update(attrs)
        return self

    def add_event(self, name: str, **attrs: Any) -> None:
        self.events.append({"name": name, "time": time.time(),
                            **({"attributes": attrs} if attrs else {})})

    def to_record(self) -> dict[str, Any]:
        end = self.end if self.end is not None else time.time()
        return {
            "type": "span",
            "timestamp": _iso(end),
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "component": self.component,
            "start": self.start,
            "end": end,
            "duration_ms": round((end - self.start) * 1e3, 3),
            "status": self.status,
            **({"error": self.error} if self.error else {}),
            "attributes": self.attributes,
            "events": list(self.events),
        }


# The active span of the CURRENT thread/context: deep seams (chaos
# firings, store retries) annotate whatever lifecycle phase is running
# without threading a tracer through every call signature.
_CURRENT: contextvars.ContextVar[Optional[Span]] = contextvars.ContextVar(
    "polyaxon_tpu_span", default=None)


def current_span() -> Optional[Span]:
    return _CURRENT.get()


def add_event(name: str, **attrs: Any) -> bool:
    """Attach an event to the active span, if any. Never raises — this
    is called from failure paths that must not grow new failure modes."""
    try:
        span = _CURRENT.get()
        if span is None:
            return False
        span.add_event(name, **attrs)
        return True
    except Exception:  # noqa: BLE001 — observability must stay passive
        return False


def parse_trace_parent(raw: Optional[str]) -> tuple[Optional[str],
                                                    Optional[str]]:
    """``<trace_id>:<span_id>`` → (trace_id, span_id); (None, None) on
    anything malformed."""
    if not raw or ":" not in raw:
        return None, None
    trace_id, _, span_id = raw.rpartition(":")
    if not trace_id or not span_id:
        return None, None
    return trace_id, span_id


def format_trace_parent(trace_id: str, span_id: str) -> str:
    return f"{trace_id}:{span_id}"


class RunTracer:
    """Span writer for one run directory.

    Completed spans append to ``events/span/lifecycle.jsonl`` through a
    lazily-opened :class:`EventWriter` handle; call :meth:`close` (the
    runtime loop registers it on its ExitStack; the executor closes at
    gang reap) to release it. ``parent_id`` is the default parent for
    spans started without an explicit one — the propagated remote
    parent (e.g. the agent's ``execute`` span for a runtime tracer).
    """

    def __init__(self, run_dir: str, trace_id: str, *,
                 parent_id: Optional[str] = None, component: str = ""):
        self.run_dir = run_dir
        self.trace_id = trace_id
        self.parent_id = parent_id
        self.component = component
        self._writer = EventWriter(run_dir)

    @classmethod
    def from_env(cls, run_dir: str, *, component: str = "") -> "RunTracer":
        """Tracer from the compiled env contract: trace id from
        ``POLYAXON_RUN_UUID`` (falling back to the run-dir basename —
        artifacts dirs are ``<root>/<uuid>``), remote parent from
        ``POLYAXON_TRACE_PARENT``."""
        trace_id = (os.environ.get("POLYAXON_RUN_UUID")
                    or os.path.basename(os.path.abspath(run_dir)))
        _, parent_id = parse_trace_parent(
            os.environ.get(ENV_TRACE_PARENT))
        return cls(run_dir, trace_id, parent_id=parent_id,
                   component=component)

    # -- span lifecycle ----------------------------------------------------
    def start_span(self, name: str, *, parent: Optional[Span] = None,
                   parent_id: Optional[str] = None,
                   attributes: Optional[dict] = None) -> Span:
        return Span(
            trace_id=self.trace_id,
            name=name,
            parent_id=(parent.span_id if parent is not None
                       else parent_id if parent_id is not None
                       else self.parent_id),
            component=self.component,
            attributes=dict(attributes or {}),
        )

    def finish(self, span: Span, *, status: str = "ok",
               error: Optional[str] = None) -> Span:
        if span.end is None:
            span.end = time.time()
        span.status = status
        if error:
            span.error = error[:500]
        self.write(span.to_record())
        return span

    @contextlib.contextmanager
    def span(self, name: str, *, parent: Optional[Span] = None,
             parent_id: Optional[str] = None,
             attributes: Optional[dict] = None) -> Iterator[Span]:
        """Context-managed span: becomes the thread's current span for
        its body (so :func:`add_event` seams land on it), nests under
        the enclosing current span by default, records error status on
        an exception, and always writes on exit."""
        enclosing = _CURRENT.get()
        span = self.start_span(
            name, parent=parent if parent is not None else enclosing,
            parent_id=parent_id, attributes=attributes)
        token = _CURRENT.set(span)
        try:
            yield span
        except BaseException as exc:
            span.end = time.time()
            self.finish(span, status="error",
                        error=f"{type(exc).__name__}: {exc}")
            raise
        finally:
            _CURRENT.reset(token)
            if span.end is None:
                self.finish(span)

    def record_completed(self, name: str, *, start: float, end: float,
                         parent_id: Optional[str] = None,
                         status: str = "ok", error: Optional[str] = None,
                         attributes: Optional[dict] = None,
                         events: Optional[list] = None) -> Span:
        """Write a span whose boundaries were measured by the caller
        (emission windows, admission passes)."""
        span = self.start_span(name, parent_id=parent_id,
                               attributes=attributes)
        span.start = start
        span.end = end
        span.events = list(events or [])
        return self.finish(span, status=status, error=error)

    def event(self, name: str, *, parent_id: Optional[str] = None,
              attributes: Optional[dict] = None) -> None:
        """Standalone timeline annotation not tied to an open span
        (e.g. the scheduler's requeue decision)."""
        now = time.time()
        self.write({
            "type": "event",
            "timestamp": _iso(now),
            "trace_id": self.trace_id,
            "parent_id": (parent_id if parent_id is not None
                          else self.parent_id),
            "name": name,
            "time": now,
            "attributes": dict(attributes or {}),
        })

    # -- io ---------------------------------------------------------------
    def write(self, record: dict[str, Any]) -> None:
        self._writer.write(V1EventKind.SPAN, SPAN_STREAM, record)
        # Every written span/event also lands in the run's flight-
        # recorder ring (obs.flight): the postmortem of a dead run is
        # fed as a side effect of normal tracing, no second producer.
        try:
            from polyaxon_tpu.obs import flight as _flight

            _flight.RECORDER.record_trace(self.trace_id, record)
        except Exception as exc:  # the recorder is fail-open
            logging.getLogger(__name__).debug(
                "flight-recorder trace tap failed: %s", exc)

    def flush(self) -> None:
        self._writer.flush()

    def close(self) -> None:
        self._writer.close()

    def __enter__(self) -> "RunTracer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def span_file(run_dir: str) -> str:
    return os.path.join(run_dir, "events", V1EventKind.SPAN,
                        f"{SPAN_STREAM}.jsonl")


def record_completed(run_dir: str, trace_id: str, name: str, *,
                     start: float, end: float, component: str = "",
                     parent_id: Optional[str] = None, status: str = "ok",
                     error: Optional[str] = None,
                     attributes: Optional[dict] = None) -> str:
    """One-shot completed-span append without a long-lived tracer —
    the control-plane seams (compile, admission, placement) fire a few
    times per run, so open-append-close per span is the simple safe
    choice (O_APPEND keeps concurrent writers line-atomic). Returns the
    lifecycle file path (the sidecar ships it eagerly)."""
    with RunTracer(run_dir, trace_id, parent_id=parent_id,
                   component=component) as tracer:
        tracer.record_completed(name, start=start, end=end, status=status,
                                error=error, attributes=attributes)
    return span_file(run_dir)


def record_event(run_dir: str, trace_id: str, name: str, *,
                 component: str = "", parent_id: Optional[str] = None,
                 attributes: Optional[dict] = None) -> str:
    """One-shot standalone event append (see :meth:`RunTracer.event`)."""
    with RunTracer(run_dir, trace_id, parent_id=parent_id,
                   component=component) as tracer:
        tracer.event(name, attributes=attributes)
    return span_file(run_dir)


# ------------------------------------------------------------- timeline
def read_trace(run_dir: str) -> list[dict[str, Any]]:
    """All span/event records of a run (tolerant of torn sidecar
    writes, like every jsonl reader here)."""
    return read_jsonl(span_file(run_dir))


def build_timeline(records: list[dict[str, Any]],
                   trace_id: Optional[str] = None) -> dict[str, Any]:
    """Ordered span tree from raw lifecycle records.

    Spans nest under their ``parent_id`` (an unknown parent — e.g. the
    parent's record not yet synced — degrades to a root, never drops
    the span); siblings and roots sort by start time; standalone events
    attach to their parent span's ``events`` list, or surface in the
    top-level ``events`` when unparented. ``t0``/``duration_ms`` give
    waterfall consumers the frame without re-deriving it.
    """
    spans: dict[str, dict] = {}
    loose_events: list[dict] = []
    for rec in records:
        if rec.get("type") == "span" and rec.get("span_id"):
            node = dict(rec)
            node["children"] = []
            node["events"] = list(rec.get("events") or [])
            spans[node["span_id"]] = node
        elif rec.get("type") == "event":
            loose_events.append(rec)

    roots: list[dict] = []
    for node in spans.values():
        parent = spans.get(node.get("parent_id") or "")
        if parent is not None and parent is not node:
            parent["children"].append(node)
        else:
            roots.append(node)

    top_events: list[dict] = []
    for rec in loose_events:
        parent = spans.get(rec.get("parent_id") or "")
        event = {"name": rec.get("name"), "time": rec.get("time"),
                 **({"attributes": rec["attributes"]}
                    if rec.get("attributes") else {})}
        if parent is not None:
            parent["events"].append(event)
        else:
            top_events.append(event)

    def sort_tree(nodes: list[dict]) -> None:
        # span_id as the final tie-break: same-millisecond siblings with
        # the same name (e.g. two per-attempt init spans) would otherwise
        # order by dict insertion — i.e. file order, which the sidecar
        # may interleave — and golden report/timeline output would
        # wobble across runs.
        nodes.sort(key=lambda n: (n.get("start") or 0, n.get("name") or "",
                                  n.get("span_id") or ""))
        for node in nodes:
            node["events"].sort(key=lambda e: (e.get("time") or 0,
                                               e.get("name") or ""))
            sort_tree(node["children"])

    sort_tree(roots)
    top_events.sort(key=lambda e: e.get("time") or 0)

    starts = [n.get("start") for n in spans.values()
              if n.get("start") is not None]
    starts += [e["time"] for e in top_events if e.get("time") is not None]
    ends = [n.get("end") for n in spans.values() if n.get("end") is not None]
    t0 = min(starts) if starts else None
    t_end = max(ends + ([t0] if t0 is not None else [])) if ends or t0 else None
    if trace_id is None and spans:
        trace_id = next(iter(spans.values())).get("trace_id")
    return {
        "trace_id": trace_id,
        "t0": t0,
        "duration_ms": (round((t_end - t0) * 1e3, 3)
                        if t0 is not None and t_end is not None else 0.0),
        "span_count": len(spans),
        "spans": roots,
        "events": top_events,
    }


def _json_default(value):  # pragma: no cover - debugging aid
    return str(value)


def dump_timeline(timeline: dict[str, Any]) -> str:
    return json.dumps(timeline, default=_json_default, indent=2)
