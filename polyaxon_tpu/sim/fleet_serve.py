"""Fleet-serve episode: traffic spike → scale-up → drain → scale-down.

One compressed serving-fleet day over REAL ``ContinuousBatchingEngine``
replicas (llama_tiny), judged the gauntlet way — by the telemetry
oracle over a marked history window, never by reaching into internals:

1. **Warm traffic.** A handful of multi-turn conversations (shared
   12-token prefixes, fresh suffix per turn) flows through the
   router; the prefix→replica affinity map forms and every replica's
   radix tree holds exactly its own conversations.
2. **Marked spike.** ``mark_window("scale-up")`` brackets a burst of
   interactive requests. Per-replica queues cross the
   ``fleet-replica-hot`` threshold, the alert engine fires, and the
   autoscaler promotes the warm standby — ring ownership moves ~1/N
   of prefixes onto the new replica and queue-pressure spill routes
   them there. The ``serving-ttft-during-scaleup`` oracle invariant
   judges interactive TTFT p99 over ONLY this window: the
   prewarm-before-commit discipline is exactly why it holds.
3. **Drain + scale-down.** Traffic stops, rules resolve (alert-clock
   fast-forward — the fire→resolve arc is the evidence), the idle
   hold elapses, and the autoscaler drains the newest replica before
   release.

Red-team injects (ci.sh must show each flips the gate):

* ``route-blind`` — the router round-robins, ignoring affinity AND
  the hash. Conversations spray across replicas, every replica's tree
  churns through everyone's prefixes under eviction pressure, and the
  fleet-wide prefix hit rate collapses below the gate floor.
* ``cold-scale`` — prewarm is skipped, so the promoted standby's jit
  caches are empty and its first in-window requests eat the XLA
  compiles; the during-spike TTFT invariant must fail.
* ``mute-replica`` — one replica is built WITHOUT its component-scoped
  registry view (ISSUE 20): its TTFT/queue series record unscoped, so
  the federated per-component view silently under-covers the fleet.
  The ``fleet_view_covers_replicas`` gate (every replica that served
  requests appears as a component in the federated serving-TTFT view)
  must flip the episode — a fleet whose telemetry cannot name which
  replica produced a sample is unobservable, even when every SLO
  number still looks healthy.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Optional

from polyaxon_tpu.obs import history as obs_history
from polyaxon_tpu.obs import metrics as obs_metrics
from polyaxon_tpu.obs import oracle as obs_oracle
from polyaxon_tpu.obs import rules as obs_rules

logger = logging.getLogger(__name__)

FLEET_SERVE_INJECTS = ("route-blind", "cold-scale", "mute-replica")

# Fleet-wide prefix hit rate the episode must clear (skipped / total
# prefill tokens summed over replicas). On the spec workload affinity
# routing holds ~0.61 while blind round-robin under the same KV
# budget thrashes down to ~0.39 (both near-deterministic at fixed
# profile+seed) — the floor sits between the two distributions so the
# route-blind inject fails on the hit rate itself, not only on the
# TTFT collateral its re-prefill storms sometimes cause.
FLEET_HIT_RATE_FLOOR = 0.45

# Oracle verdicts that must PASS (not skip) for the episode to pass.
# The federated invariant judges TTFT over every replica's merged
# component series — the fleet-aggregate SLO surface (ISSUE 20).
FLEET_SERVE_REQUIRED = ("serving-ttft-during-scaleup",
                        "serving-ttft-federated-during-scaleup",
                        "zero-unresolved-alerts")

# Sizing is load-bearing, not incidental. Each 12-token prefix is 3
# pages (page_size=4); suffix leaves are evicted first (they have no
# children), so the per-replica KV budget is really a PREFIX budget.
# 7 conversations × 3 = 21 pages: more than one replica's 16-page
# pool, so a router that sprays conversations everywhere forces every
# replica to evict prefixes it will need again next turn. An affinity
# split (≤4 conversations ≈ 12 prefix pages + ~4 transient) fits.
# The conversation count is deliberately ODD: round-robin over an even
# replica count with an even conversation count would partition the
# set perfectly by accident and hide the blindness.
_PROFILES = {
    "quick": {
        "replicas": 2, "standby": 1, "min_replicas": 1,
        "slots": 2, "page_size": 4, "kv_pages": 16,
        "conversations": 7, "prefix_tokens": 12, "suffix_tokens": 4,
        "warm_turns": 4, "burst": 36, "max_new": 2,
        "cadence": 0.25, "spike_wall": 90.0,
    },
    "full": {
        "replicas": 2, "standby": 1, "min_replicas": 1,
        "slots": 2, "page_size": 4, "kv_pages": 16,
        "conversations": 7, "prefix_tokens": 12, "suffix_tokens": 4,
        "warm_turns": 6, "burst": 72, "max_new": 2,
        "cadence": 0.25, "spike_wall": 180.0,
    },
}


# ------------------------------------------------------------ workload
def make_conversations(vocab: int, n: int, prefix_tokens: int,
                       seed: int) -> list[list[int]]:
    """Deterministic shared prefixes, one per conversation, drawn from
    the LOWER vocab half (warmup rows use the upper half, so fleet
    prewarm never pre-seeds the traffic prefixes into any tree)."""
    half = max(2, vocab // 2)
    return [[(seed * 101 + c * 37 + j * 7) % half
             for j in range(prefix_tokens)] for c in range(n)]


def turn_row(prefix: list[int], t: int, vocab: int, suffix_tokens: int,
             seed: int) -> list[int]:
    """Turn ``t`` of a conversation: shared prefix + fresh suffix."""
    half = max(2, vocab // 2)
    return prefix + [half + (seed * 13 + t * 29 + j * 11) % (half - 1)
                     for j in range(suffix_tokens)]


def warmup_rows(vocab: int, prefix_tokens: int, suffix_tokens: int,
                seed: int) -> list[list[int]]:
    """Compile-coverage rows at the exact traffic length, disjoint
    token region: the engine jits per prompt length, so two warm
    passes build the full-prefill, suffix-prefill, and decode
    programs without warming any traffic prefix."""
    half = max(2, vocab // 2)
    length = prefix_tokens + suffix_tokens
    return [[half + (seed * 17 + r * 31 + j * 13) % (half - 1)
             for j in range(length)] for r in range(2)]


# ------------------------------------------------------------- episode
def build_fleet(*, profile: str = "quick", seed: int = 0,
                inject: Optional[str] = None, replicas: Optional[int] = None,
                standby: Optional[int] = None):
    """(fleet, vocab, spec): real-engine fleet per the profile, with
    the inject seams applied (blind router / cold standby). Blocking —
    all build+prewarm compile cost lands here, before any window."""
    from polyaxon_tpu.serving.fleet import ServingFleet, engine_factory
    from polyaxon_tpu.serving.router import FleetRouter
    from polyaxon_tpu.serving.server import load_params

    spec = dict(_PROFILES[profile])
    if replicas is not None:
        spec["replicas"] = replicas
    if standby is not None:
        spec["standby"] = standby
    cfg, _ = load_params("llama_tiny", seed=0)
    vocab = cfg.vocab_size
    factory = engine_factory(
        "llama_tiny", slots=spec["slots"], kv="paged",
        page_size=spec["page_size"], kv_pages=spec["kv_pages"])
    if inject == "mute-replica":
        # The FIRST engine built (replica r0, a ready member that
        # serves real traffic) is constructed without its scoped
        # registry view — everything it records lands unscoped, so the
        # federated per-component view under-covers the fleet from the
        # first sample on. The coverage gate must catch exactly this.
        real_factory = factory
        built = [0]

        def factory(registry=None):
            built[0] += 1
            if built[0] == 1:
                return real_factory()
            return real_factory(registry=registry)
    # Prefix window == the workload's shared-prefix length: a window
    # that swallowed the per-turn suffix would make every turn a
    # distinct key and affinity could never form.
    router = FleetRouter(seed=seed, prefix_window=spec["prefix_tokens"],
                         blind=(inject == "route-blind"))
    fleet = ServingFleet(
        factory, replicas=spec["replicas"], standby=spec["standby"],
        min_replicas=spec["min_replicas"],
        max_replicas=spec["replicas"] + spec["standby"],
        prewarm=(inject != "cold-scale"),
        warmup_rows=warmup_rows(vocab, spec["prefix_tokens"],
                                spec["suffix_tokens"], seed),
        router=router, cooldown=2.0, idle_hold=0.5)
    fleet.start()
    return fleet, vocab, spec


def _firing(engine: obs_rules.AlertEngine) -> set:
    return {a["rule"] for a in engine.active()}


def telemetry_gaps(fleet) -> list:
    """Replica ids that served requests but are ABSENT as components
    from the federated serving-TTFT view (empty == full coverage).

    This is the fleet-telemetry gate: a replica recording outside its
    scoped view (mute-replica inject, or a regression in the factory →
    registry plumbing) keeps every aggregate SLO number looking
    healthy while the per-component breakdown silently loses a
    replica. Must run BEFORE drain/stop — a released replica's scoped
    series are dropped by design, so post-drain the gap would be
    indistinguishable from legitimate GC."""
    snap = fleet.fleet_snapshot()
    covered = set(snap["components"])
    served = {rid for rid, s in snap["stats"]["replicas"].items()
              if s.get("served", 0) > 0}
    return sorted(served - covered)


def warm_phase(fleet, vocab: int, spec: dict, seed: int) -> None:
    """Pre-spike conversation turns: builds the affinity map and each
    replica's radix working set (no window open yet)."""
    convs = make_conversations(vocab, spec["conversations"],
                               spec["prefix_tokens"], seed)
    for t in range(spec["warm_turns"]):
        for prefix in convs:
            fleet.generate(
                [turn_row(prefix, t, vocab, spec["suffix_tokens"], seed)],
                spec["max_new"], klass="interactive")
        fleet.poll()


def spike_phase(fleet, vocab: int, spec: dict, seed: int,
                history: obs_history.MetricsHistory,
                alert_engine: obs_rules.AlertEngine,
                plane: Any = None) -> dict:
    """The marked scale-up window: burst traffic, rule-driven scale-up,
    and in-window samples on BOTH the old and the joining replica.
    Returns the spike summary (the caller folds it into its result)."""
    convs = make_conversations(vocab, spec["conversations"],
                               spec["prefix_tokens"], seed)
    deadline = time.monotonic() + spec["spike_wall"]
    history.mark_window("scale-up", start=True)
    try:
        reqs = []
        for i in range(spec["burst"]):
            prefix = convs[i % len(convs)]
            t = spec["warm_turns"] + i // len(convs)
            row = turn_row(prefix, t, vocab, spec["suffix_tokens"], seed)
            klass = "interactive" if i % 4 != 3 else "batch"
            req, _ = fleet.submit(row, spec["max_new"], klass=klass)
            reqs.append(req)
            if (i + 1) % 6 == 0:
                fleet.poll()
                alert_engine.evaluate(plane=plane)
                fleet.maybe_scale(_firing(alert_engine))
        # Pump the control loop until the burst drains AND a scale-up
        # committed — in-window traffic keeps flowing through the
        # grown fleet so the invariant really judges "through" the
        # scale event, not just up to it.
        trickle = 0
        while time.monotonic() < deadline:
            fleet.poll()
            alert_engine.evaluate(plane=plane)
            fleet.maybe_scale(_firing(alert_engine))
            scaled = any(e["direction"] == "up" and e["outcome"] == "ok"
                         for e in fleet.scale_events)
            pending = [r for r in reqs if not r.done.is_set()]
            if scaled and trickle < 2 * len(convs):
                # Post-commit turns: ring ownership moved, so some of
                # these land on the joining replica (cold-scale makes
                # exactly these eat the compile).
                prefix = convs[trickle % len(convs)]
                t = 100 + trickle // len(convs)
                row = turn_row(prefix, t, vocab, spec["suffix_tokens"],
                               seed)
                reqs.append(fleet.submit(row, spec["max_new"],
                                         klass="interactive")[0])
                trickle += 1
                continue
            if scaled and not pending:
                break
            time.sleep(0.02)
        for r in reqs:
            r.wait(timeout=60.0)
        # In-window TTFT observations are all in the registry now;
        # force a history sample stamped before the window closes.
        fleet.poll()
        history.sample(force=True)
    finally:
        history.mark_window("scale-up", end=True)
    scale_up_ok = any(e["direction"] == "up" and e["outcome"] == "ok"
                      for e in fleet.scale_events)
    return {"requests": len(reqs), "scale_up_committed": scale_up_ok}


def drain_phase(fleet, alert_engine: obs_rules.AlertEngine,
                clock_skew: list, plane: Any = None,
                max_wall: float = 20.0) -> bool:
    """Post-spike: fast-forward the alert clock so spike firings
    resolve, then let the idle hold elapse and the autoscaler drain
    and release the newest replica. True when a scale-down landed."""
    clock_skew[0] += 30.0
    deadline = time.monotonic() + max_wall
    while time.monotonic() < deadline:
        fleet.poll()
        alert_engine.evaluate(plane=plane)
        fleet.maybe_scale(_firing(alert_engine))
        if any(e["direction"] == "down" and e["outcome"] == "ok"
               for e in fleet.scale_events):
            return fleet.wait_settled(timeout=max_wall)
        time.sleep(0.05)
    return False


def run_fleet_serve(*, profile: str = "quick", seed: int = 0,
                    inject: Optional[str] = None,
                    oracle_source: Any = None) -> dict:
    """One standalone fleet-serve episode → ``{passed, ...}``.

    Pass criteria: the required oracle verdicts PASS (during-window
    TTFT — labeled AND federated over per-component series — plus
    alerts resolved), the fleet-wide prefix hit rate clears
    :data:`FLEET_HIT_RATE_FLOOR`, every replica's pool reports zero
    ``check_invariants()`` violations, the federated view covers every
    replica that served (:func:`telemetry_gaps` — the mute-replica
    inject flips this), and a scale-up committed plus a scale-down
    drained — the full spike → grow → drain → shrink arc.
    """
    if inject is not None and inject not in FLEET_SERVE_INJECTS:
        raise ValueError(
            f"unknown inject {inject!r} (one of {FLEET_SERVE_INJECTS})")
    invariants = obs_oracle.load_invariants(oracle_source)
    t_start = time.monotonic()
    fleet, vocab, spec = build_fleet(profile=profile, seed=seed,
                                     inject=inject)
    clock_skew = [0.0]
    alert_engine = obs_rules.AlertEngine(
        obs_rules.load_ruleset(),
        clock=lambda: time.time() + clock_skew[0])
    prior_history = obs_history.default_history()
    history = obs_history.MetricsHistory(
        obs_metrics.REGISTRY, cadence=spec["cadence"])
    obs_history.set_default_history(history)
    baseline = obs_metrics.REGISTRY.snapshot()
    try:
        warm_phase(fleet, vocab, spec, seed)
        spike = spike_phase(fleet, vocab, spec, seed, history,
                            alert_engine)
        # Coverage gate runs while every replica's scoped series are
        # still live (drain/release drops them by design).
        gaps = telemetry_gaps(fleet)
        scaled_down = drain_phase(fleet, alert_engine, clock_skew)
        stats = fleet.stats()
        fleet.stop()
        # Fast-forward past every rate/burn window so anything still
        # firing resolves; unresolved-at-end is then real evidence.
        clock_skew[0] = 600.0
        alert_engine.evaluate()
        history.sample(force=True)
        bundle = obs_oracle.TelemetryBundle(
            snapshot=obs_metrics.REGISTRY.snapshot(), baseline=baseline,
            alerts=alert_engine.to_json(), history=history.to_json())
        verdicts = obs_oracle.evaluate(invariants, bundle)
    finally:
        fleet.stop()
        obs_history.set_default_history(prior_history)
    oracle_result = obs_oracle.summarize(verdicts)
    by_id = {v["invariant"]: v["verdict"] for v in verdicts}
    anchors_held = all(by_id.get(i) == "pass"
                       for i in FLEET_SERVE_REQUIRED)
    hit_rate = stats["prefix_hit_rate"] or 0.0
    checks = {
        "prefix_hit_rate_above_floor": hit_rate >= FLEET_HIT_RATE_FLOOR,
        "zero_kv_invariant_violations":
            stats["kv_invariant_violations"] == 0,
        "scale_up_committed": spike["scale_up_committed"],
        "scale_down_drained": scaled_down,
        "fleet_view_covers_replicas": not gaps,
    }
    window = obs_history.window_bounds(bundle.history or {}, "scale-up")
    return {
        "passed": (oracle_result["passed"] and anchors_held
                   and all(checks.values())),
        "profile": profile,
        "inject": inject,
        "anchors": {i: by_id.get(i, "missing")
                    for i in FLEET_SERVE_REQUIRED},
        "checks": checks,
        "prefix_hit_rate": round(hit_rate, 4),
        "hit_rate_floor": FLEET_HIT_RATE_FLOOR,
        "telemetry_gaps": gaps,
        "requests": spike["requests"],
        "scale_events": stats["scale_events"],
        "routed": stats["router"]["routed"],
        "scale_up_window": ([round(t, 3) for t in window] if window
                            else None),
        "wall_seconds": round(time.monotonic() - t_start, 3),
        "oracle": oracle_result,
    }


def print_result(result: dict, label: str = "fleet-serve") -> None:
    """Human summary (mirrors gauntlet.print_result)."""
    import json as _json

    print(f"{label}: {result['requests']} requests, "
          f"hit-rate {result['prefix_hit_rate']} "
          f"(floor {result['hit_rate_floor']}), "
          f"routed {result['routed']}, "
          f"{result['wall_seconds']}s")
    for v in result["oracle"]["verdicts"]:
        marker = {"pass": "ok  ", "skip": "skip", "fail": "FAIL"}
        detail = ("" if v["verdict"] == "pass"
                  else f"  {_json.dumps(v['evidence'], default=str)[:160]}")
        print(f"  [{marker[v['verdict']]}] {v['invariant']}{detail}")
    print(f"checks: {result['checks']}; anchors: {result['anchors']}; "
          f"scale events: "
          f"{[(e['direction'], e['outcome'], e['mode']) for e in result['scale_events']]}")
    print("FLEET-SERVE " + ("PASSED" if result["passed"] else "FAILED"))
