"""Collective-overlap scheduling: pin XLA's latency-hiding knobs.

The MFU headroom case (ISSUE 12 / ROADMAP item 5): the communication
audit counts every collective, but the step time depends on whether
their latency is *hidden* behind independent compute. On TPU that is
the latency-hiding scheduler's job — it hoists async collective issues
away from their consumers so the transfer flies under compute (GSPMD
§3.4). The knobs default on in current libtpu builds, but "default"
is not "pinned": a toolchain bump that flips one silently costs a
multiple. This module pins them in both places they can act:

- **per-compile** (`latency_hiding_options()`): TPU compiler options
  passed to ``lowered.compile(compiler_options=...)``. This is what the
  AOT overlap audit (``perf --audit``) compiles with, so the budgeted
  ``overlap_ratio`` floors measure exactly the pinned configuration.
  ``serialize=True`` is the deopt twin: it forces the scheduler OFF,
  which demonstrably flips the budget gate (the ``--inject-serialize``
  self-test in ci.sh).
- **per-process** (`pin_runtime_flags()`): the same flags appended to
  ``LIBTPU_INIT_ARGS`` before backend init, via ``utils/env.py``'s
  append-only/never-override idiom. NEVER via ``XLA_FLAGS``: XLA
  CHECK-aborts the whole process on unknown flags there, and a
  CPU-only jaxlib does not parse the ``xla_tpu_*`` family.

Empirical note (v5e:2x4 topology, jax 0.4.37): with the scheduler on,
the fsdp train step's all-gathers get issued early with real compute
windows; with it off the same annotated ops sit immediately before
their consumers — the window-based ratio in ``perf/hlo.py`` is what
separates the two, not the async-op count (which can even be *higher*
in the serialized schedule).
"""

from __future__ import annotations

# Per-compile TPU compiler options (string values: the compiler-options
# API takes textual flag values). Keep this dict and
# utils.env.TPU_OVERLAP_INIT_ARGS in lockstep — one is the per-compile
# spelling, the other the process-wide one.
LATENCY_HIDING_COMPILER_OPTIONS: dict[str, str] = {
    "xla_tpu_enable_latency_hiding_scheduler": "true",
    "xla_enable_async_all_gather": "true",
    "xla_tpu_enable_async_collective_fusion": "true",
}

# The deopt: force collectives to schedule synchronously. Used by
# `perf --audit --inject-serialize` to prove the overlap gate can fail.
SERIALIZE_COMPILER_OPTIONS: dict[str, str] = {
    "xla_tpu_enable_latency_hiding_scheduler": "false",
    "xla_enable_async_all_gather": "false",
    "xla_tpu_enable_async_collective_fusion": "false",
}


def latency_hiding_options(serialize: bool = False) -> dict[str, str]:
    """The TPU ``compiler_options`` dict for overlap-pinned compiles
    (``serialize=True`` = the forced-sync deopt)."""
    return dict(SERIALIZE_COMPILER_OPTIONS if serialize
                else LATENCY_HIDING_COMPILER_OPTIONS)


def pin_runtime_flags() -> bool:
    """Pin the overlap scheduler for THIS process's TPU runtime.

    Call before first backend touch (the runtime entrypoint does, next
    to ``cpu_mesh_xla_flags``). No-op (returns False) on hosts without
    libtpu, and never overrides flags an operator already set.
    """
    from polyaxon_tpu.utils.env import tpu_overlap_libtpu_args

    return tpu_overlap_libtpu_args()
