from polyaxon_tpu.cli.main import cli

cli()
