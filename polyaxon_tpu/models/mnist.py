"""MNIST CNN — the quick-start model (BASELINE config 1)."""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp

from polyaxon_tpu.models.common import (
    Batch,
    ModelDef,
    Variables,
    cross_entropy_loss,
    scaled_init,
)


@dataclasses.dataclass(frozen=True)
class MnistConfig:
    num_classes: int = 10
    dtype: Any = jnp.bfloat16


CONFIGS = {"mnist_cnn": MnistConfig()}


def _conv(x, w, b):
    y = jax.lax.conv_general_dilated(
        x, w, window_strides=(1, 1), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    return y + b


def init(cfg: MnistConfig, rng: jax.Array) -> Variables:
    keys = jax.random.split(rng, 4)
    params = {
        "conv1": scaled_init(keys[0], (3, 3, 1, 32), fan_in=9),
        "conv1_b": jnp.zeros((32,)),
        "conv2": scaled_init(keys[1], (3, 3, 32, 64), fan_in=288),
        "conv2_b": jnp.zeros((64,)),
        "dense1": scaled_init(keys[2], (7 * 7 * 64, 256), fan_in=7 * 7 * 64),
        "dense1_b": jnp.zeros((256,)),
        "dense2": scaled_init(keys[3], (256, cfg.num_classes), fan_in=256),
        "dense2_b": jnp.zeros((cfg.num_classes,)),
    }
    return {"params": params, "state": {}}


def logical_axes(cfg: MnistConfig) -> Variables:
    return {
        "params": {
            "conv1": (None, None, "conv_in", "conv_out"),
            "conv1_b": ("conv_out",),
            "conv2": (None, None, "conv_in", "conv_out"),
            "conv2_b": ("conv_out",),
            "dense1": (None, "mlp"),
            "dense1_b": ("mlp",),
            "dense2": ("mlp", "classes"),
            "dense2_b": ("classes",),
        },
        "state": {},
    }


def forward(cfg: MnistConfig, params: dict, images: jax.Array) -> jax.Array:
    dt = cfg.dtype
    x = images.astype(dt)
    if x.ndim == 3:
        x = x[..., None]
    x = jax.nn.relu(_conv(x, params["conv1"].astype(dt), params["conv1_b"].astype(dt)))
    x = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "VALID")
    x = jax.nn.relu(_conv(x, params["conv2"].astype(dt), params["conv2_b"].astype(dt)))
    x = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "VALID")
    x = x.reshape(x.shape[0], -1)
    x = jax.nn.relu(x @ params["dense1"].astype(dt) + params["dense1_b"].astype(dt))
    return (x @ params["dense2"].astype(dt) + params["dense2_b"].astype(dt)).astype(jnp.float32)


def apply(cfg: MnistConfig, variables: Variables, batch: Batch, train: bool = True,
          rng: Optional[jax.Array] = None):
    logits = forward(cfg, variables["params"], batch["image"])
    loss, acc = cross_entropy_loss(logits, batch["label"])
    return loss, {"loss": loss, "accuracy": acc}, variables["state"]


def model_def(name: str = "mnist_cnn", **overrides) -> ModelDef:
    cfg = dataclasses.replace(CONFIGS[name], **overrides)
    return ModelDef(
        name=name,
        init=functools.partial(init, cfg),
        apply=functools.partial(apply, cfg),
        logical_axes=functools.partial(logical_axes, cfg),
        unit="examples",
    )
