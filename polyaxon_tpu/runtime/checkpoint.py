"""Sharded checkpoint/resume for the JAXJob runtime (orbax-backed).

The reference provides only the outputs-path contract + run-level
restart (SURVEY.md §5.4 [K]); the TPU build owns both halves. Each
process writes its own shards (orbax OCDBT), saves are async by default
so the step loop never blocks on IO, and restore re-lays tensors onto
the current mesh from the saved shardings — preemption-safe resume is
``latest_step() → restore(state_like)``.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp

from polyaxon_tpu.polyflow.runs import V1JaxCheckpointing

logger = logging.getLogger(__name__)


class CheckpointManager:
    def __init__(
        self,
        directory: str,
        spec: Optional[V1JaxCheckpointing] = None,
    ):
        self.spec = spec or V1JaxCheckpointing()
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        options = ocp.CheckpointManagerOptions(
            max_to_keep=self.spec.max_to_keep,
            enable_async_checkpointing=bool(self.spec.async_save),
        )
        self._mgr = ocp.CheckpointManager(self.directory, options=options)
        # Steps skipped by the most recent restore() because their
        # on-disk bytes failed to deserialize (newest first); surfaced
        # through TrainResult → outputs + a WARNING run condition.
        self.last_restore_skipped: list[int] = []

    @property
    def enabled(self) -> bool:
        return bool(self.spec.enabled)

    def interval(self) -> Optional[int]:
        return self.spec.interval_steps

    def should_save(self, step: int) -> bool:
        if not self.enabled:
            return False
        interval = self.spec.interval_steps
        return bool(interval) and step > 0 and step % interval == 0

    def save(self, step: int, state: Any, *, force: bool = False) -> None:
        if not self.enabled and not force:
            return
        self._mgr.save(step, args=ocp.args.StandardSave(state))

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, state_like: Any, step: Optional[int] = None) -> Any:
        """Restore into the sharding/layout of ``state_like`` (an existing
        state pytree or eval_shape'd abstract tree with shardings).

        With no explicit ``step``, a latest checkpoint whose bytes fail
        to deserialize (truncated by an eviction mid-write, bit-rotted,
        chaos-corrupted) falls back to the NEXT-OLDER step instead of
        bricking resume; skipped steps land in ``last_restore_skipped``
        so the run surfaces ``restored_from_step`` + a WARNING instead
        of dying. An explicit ``step`` never falls back — the caller
        asked for those exact bytes.
        """
        self.last_restore_skipped = []
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, state_like)
        if step is not None:
            restored = self._mgr.restore(
                step, args=ocp.args.StandardRestore(abstract))
            logger.info("Restored checkpoint step=%s from %s", step,
                        self.directory)
            return restored
        steps = sorted(self._mgr.all_steps(), reverse=True)
        if not steps:
            raise FileNotFoundError(f"No checkpoint under {self.directory}")
        from polyaxon_tpu import chaos

        plan = chaos.active_plan()
        if plan is not None:
            plan.corrupt_checkpoint(self.directory, steps)
        last_error: Optional[Exception] = None
        for candidate in steps:
            try:
                restored = self._mgr.restore(
                    candidate, args=ocp.args.StandardRestore(abstract))
            except Exception as exc:  # noqa: BLE001 — fall back to older
                last_error = exc
                self.last_restore_skipped.append(candidate)
                logger.warning(
                    "checkpoint step %s under %s failed to restore (%s: "
                    "%s); falling back to the next-older step", candidate,
                    self.directory, type(exc).__name__, str(exc)[:200])
                try:
                    # A corrupt committed step is garbage: left in place
                    # it poisons both the next resume (same fallback
                    # dance) and re-saving that step number.
                    self._mgr.delete(candidate)
                except Exception:  # noqa: BLE001 — best-effort cleanup
                    logger.warning("could not delete corrupt step %s",
                                   candidate)
                continue
            if self.last_restore_skipped:
                logger.warning(
                    "restored step %s after skipping corrupt step(s) %s",
                    candidate, self.last_restore_skipped)
            else:
                logger.info("Restored checkpoint step=%s from %s",
                            candidate, self.directory)
            return restored
        raise RuntimeError(
            f"no restorable checkpoint under {self.directory}: every step "
            f"{steps} failed to deserialize") from last_error

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()
