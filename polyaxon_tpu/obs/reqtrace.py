"""Per-request span trees for the serving path (ISSUE 10 tentpole).

Training runs persist their lifecycle spans to the run dir and the
sidecar ships them (obs.trace.RunTracer); a serving request has no run
dir and lives for milliseconds, so its spans stay **in memory**: each
request gets a :class:`RequestTrace` (the trace id IS the request id)
holding the Dapper-shaped phase tree —

    request                     (root; class/prompt_len/max_new attrs)
      queue_wait                (submit → admission dequeue; paged
                                backpressure annotates `requeue` here)
      prefill                   (monolithic admission prefill, or the
                                chunked stream — one `chunk` event per
                                segment, bounded)
      decode                    (go-live → retire; `first_token`,
                                `spec_round`, `evicted` events land on
                                whatever phase is current)

— and a :class:`TimelineRing` keeps the most recent N traces so
``GET /requests/{id}/timeline`` (serving/server.py) and
``plx ops request-timeline`` can replay any recent request without
unbounded growth. Records reuse the obs.trace Span shape, so
:func:`obs.trace.build_timeline` assembles the same tree JSON the run
timeline endpoint serves — one waterfall renderer fits both.

Everything here is passive observability: mutators never raise into
the engine loop, snapshots copy under a per-trace lock (the loop
thread records while HTTP handler threads read), and per-span events
are capped so a pathological request cannot grow a span without bound.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Any, Optional

from polyaxon_tpu.obs.trace import Span, build_timeline

# Per-span annotation cap: a 10k-token speculative request must not
# accumulate 10k `spec_round` events in a ring entry. The cap-hit count
# lands in the span's attributes so truncation is visible, not silent.
MAX_EVENTS_PER_SPAN = 64

DEFAULT_RING_CAPACITY = 256


def new_request_id() -> str:
    return os.urandom(8).hex()


class RequestTrace:
    """Span scaffolding for ONE serving request.

    The engine drives phases in order (``start_phase`` closes the
    previous one implicitly — request phases never overlap); deep seams
    annotate whatever phase is current via :meth:`event`. ``finish`` is
    idempotent: every failure path may call it without coordinating
    with the retire path.

    Fleet propagation (ISSUE 20): the fleet front door pre-generates
    the request id, opens a ``route`` span under the same trace id,
    and hands the engine its span record plus a ``parent_id`` — the
    request root nests under the route decision and the finished
    timeline is ONE tree across components. ``component`` names the
    recording replica on every span, so an eviction→readmit arc reads
    with per-hop identity.
    """

    def __init__(self, request_id: str, klass: str = "batch",
                 component: str = "serving",
                 parent_id: Optional[str] = None,
                 extra_records: Optional[list] = None,
                 **attrs: Any):
        self.request_id = request_id
        self.klass = klass
        self.component = component or "serving"
        self._lock = threading.Lock()
        self.root = Span(trace_id=request_id, name="request",
                         component=self.component, parent_id=parent_id,
                         attributes={"class": klass, **attrs})
        self._spans: list[Span] = [self.root]
        # Upstream span records (the router's `route` span) replay
        # verbatim into records(), so build_timeline sees the whole
        # cross-component tree without any join step.
        self._extra_records = list(extra_records or [])
        self._phase: Optional[Span] = None
        self._done = False

    # -- phases ------------------------------------------------------------
    def start_phase(self, name: str, **attrs: Any) -> Optional[Span]:
        with self._lock:
            if self._done:
                return None
            if self._phase is not None and self._phase.end is None:
                self._phase.end = time.time()
            span = Span(trace_id=self.request_id, name=name,
                        parent_id=self.root.span_id,
                        component=self.component,
                        attributes=dict(attrs))
            self._spans.append(span)
            self._phase = span
            return span

    def end_phase(self, status: str = "ok",
                  error: Optional[str] = None, **attrs: Any) -> None:
        with self._lock:
            span = self._phase
            if span is None or span.end is not None:
                return
            span.end = time.time()
            span.status = status
            if error:
                span.error = error[:500]
            span.attributes.update(attrs)

    def event(self, name: str, **attrs: Any) -> None:
        """Annotate the current phase (the root before any phase
        opened). Bounded: past :data:`MAX_EVENTS_PER_SPAN` the event is
        counted into ``events_dropped`` instead of appended."""
        with self._lock:
            span = self._phase if self._phase is not None else self.root
            if len(span.events) >= MAX_EVENTS_PER_SPAN:
                span.attributes["events_dropped"] = (
                    int(span.attributes.get("events_dropped") or 0) + 1)
                return
            span.add_event(name, **attrs)

    def finish(self, status: str = "ok", error: Optional[str] = None,
               **attrs: Any) -> None:
        """Close any open phase and the root. Idempotent — the first
        caller's verdict wins (retire vs a racing failure path)."""
        with self._lock:
            if self._done:
                return
            self._done = True
            now = time.time()
            if self._phase is not None and self._phase.end is None:
                self._phase.end = now
                if status != "ok":
                    self._phase.status = status
                    if error:
                        self._phase.error = error[:500]
            self.root.end = now
            self.root.status = status
            if error:
                self.root.error = error[:500]
            self.root.attributes.update(attrs)

    @property
    def done(self) -> bool:
        return self._done

    # -- snapshots ---------------------------------------------------------
    def records(self) -> list[dict[str, Any]]:
        """Span records (open spans snapshot with end=now), consumable
        by :func:`obs.trace.build_timeline` — upstream records (the
        route span) first, so the tree root is the earliest hop."""
        with self._lock:
            return ([dict(r) for r in self._extra_records]
                    + [span.to_record() for span in self._spans])

    def summary(self) -> dict[str, Any]:
        """One listing row for ``GET /requests``."""
        with self._lock:
            return {
                "request_id": self.request_id,
                "class": self.klass,
                "status": self.root.status,
                "done": self._done,
                "phase": (self._phase.name
                          if self._phase is not None and not self._done
                          else None),
                "start": self.root.start,
                **({"error": self.root.error} if self.root.error else {}),
            }


class TimelineRing:
    """Bounded most-recent-N request traces, keyed by request id.

    Insertion order is submission order; past ``capacity`` the oldest
    entry drops (even if still in flight — the engine keeps recording
    into its own reference, the trace just stops being queryable).
    """

    def __init__(self, capacity: int = DEFAULT_RING_CAPACITY):
        if capacity < 1:
            raise ValueError(f"ring capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._lock = threading.Lock()
        self._traces: collections.OrderedDict[str, RequestTrace] = (
            collections.OrderedDict())
        self.evicted = 0

    def add(self, trace: RequestTrace) -> None:
        with self._lock:
            self._traces[trace.request_id] = trace
            while len(self._traces) > self.capacity:
                self._traces.popitem(last=False)
                self.evicted += 1

    def get(self, request_id: str) -> Optional[RequestTrace]:
        with self._lock:
            return self._traces.get(request_id)

    def __len__(self) -> int:
        with self._lock:
            return len(self._traces)

    def summaries(self) -> list[dict[str, Any]]:
        """Most recent first."""
        with self._lock:
            traces = list(self._traces.values())
        return [t.summary() for t in reversed(traces)]

    def timeline(self, request_id: str) -> Optional[dict[str, Any]]:
        trace = self.get(request_id)
        if trace is None:
            return None
        return build_timeline(trace.records(), trace_id=request_id)

    def to_dump(self) -> dict[str, Any]:
        """The whole ring as plain data (oldest first), the serving
        mirror of a training run's ``postmortem.json``: summaries for
        the listing view plus full span records per request so
        ``build_timeline`` — and ``sim.replay`` — can reconstruct any
        request after the process is gone."""
        with self._lock:
            traces = list(self._traces.values())
            evicted = self.evicted
        return {
            "dumped_at": time.time(),
            "capacity": self.capacity,
            "evicted": evicted,
            "requests": [{
                "summary": t.summary(),
                "records": t.records(),
            } for t in traces],
        }


TRACE_DUMP_FILE = "request-timelines.json"


def dump_ring(ring: TimelineRing, path: str) -> str:
    """Persist a ring dump atomically (tmp + replace, the postmortem
    idiom). A directory path gets :data:`TRACE_DUMP_FILE` appended.
    Raises on I/O failure — the caller owns fail-open policy."""
    import json

    if os.path.isdir(path) or path.endswith(os.sep):
        path = os.path.join(path, TRACE_DUMP_FILE)
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(ring.to_dump(), fh, indent=2, default=str)
    os.replace(tmp, path)
    return path


def read_ring_dump(path: str) -> Optional[dict[str, Any]]:
    """Load a persisted ring dump (None when absent/corrupt — same
    posture as ``flight.read_postmortem``)."""
    import json

    if os.path.isdir(path):
        path = os.path.join(path, TRACE_DUMP_FILE)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as fh:
            return json.load(fh)
    except (OSError, json.JSONDecodeError):
        return None
