"""Small environment helpers shared by the CLI and runtime entrypoints."""

from __future__ import annotations

import os


def cpu_mesh_xla_flags(n_devices: int = 8, *,
                       watchdog_timeout_s: int = 600) -> None:
    """Point ``XLA_FLAGS`` at an ``n_devices`` virtual CPU mesh, with
    the collective-rendezvous watchdog sized for an oversubscribed
    host. Must run BEFORE any jax backend initializes (this module
    imports no jax).

    Two flags, both append-only and NEVER overriding an operator's
    explicit setting (XLA's repeated-flag parsing is last-wins, so we
    skip appending when the flag is already present):

    - ``--xla_force_host_platform_device_count=N``: the virtual mesh.
    - ``--xla_cpu_collective_call_terminate_timeout_seconds``: XLA:CPU
      CHECK-aborts the whole process when any device thread misses a
      collective rendezvous for 40 s; with N device threads sharing
      one physical core a straggler starves past that easily
      (reproduced standalone at seq 16k, 2026-08-01 — the former
      "full-suite segfault", see tests/conftest.py). 600 s keeps the
      watchdog as a deadlock backstop without killing slow-but-live
      programs.
    """
    flags = os.environ.get("XLA_FLAGS", "").split()
    if not any(f.startswith("--xla_force_host_platform_device_count")
               for f in flags):
        flags.append(f"--xla_force_host_platform_device_count={n_devices}")
    if (_jaxlib_knows_collective_watchdog()
            and not any(
                f.startswith("--xla_cpu_collective_call_terminate_timeout")
                for f in flags)):
        flags.append("--xla_cpu_collective_call_terminate_timeout_seconds"
                     f"={watchdog_timeout_s}")
    os.environ["XLA_FLAGS"] = " ".join(flags)


def _jaxlib_knows_collective_watchdog() -> bool:
    """Whether this jaxlib parses the collective-watchdog flag.

    XLA CHECK-aborts the WHOLE process on any unknown flag in
    ``XLA_FLAGS`` ("Unknown flags in XLA_FLAGS: ..." at first backend
    init), so on a jaxlib predating the flag (< 0.5, e.g. the 0.4.36 in
    some images) appending it turns every jax-touching test into a
    fatal abort. Skipping it there only loses the watchdog-extension
    mitigation — strictly better than guaranteed process death. The
    version probe imports jaxlib metadata only (no backend init).
    """
    try:
        import jaxlib

        parts = tuple(int(p) for p in jaxlib.__version__.split(".")[:2])
    except Exception:  # noqa: BLE001 — unknown jaxlib: don't risk it
        return False
    return parts >= (0, 5)


# Latency-hiding scheduler pins for TPU runtimes (parallel/overlap.py
# owns the rationale and the per-compile compiler_options twin). These
# are libtpu flags: they go through LIBTPU_INIT_ARGS, NEVER XLA_FLAGS —
# XLA:CPU CHECK-aborts the whole process on any unknown XLA_FLAGS entry,
# and a CPU-only jaxlib does not know the xla_tpu_* family.
TPU_OVERLAP_INIT_ARGS: tuple[str, ...] = (
    "--xla_tpu_enable_latency_hiding_scheduler=true",
    "--xla_enable_async_all_gather=true",
    "--xla_tpu_enable_async_collective_fusion=true",
)


def tpu_overlap_libtpu_args() -> bool:
    """Pin the collective-overlap scheduler flags into
    ``LIBTPU_INIT_ARGS``. Must run BEFORE the TPU backend initializes.

    Same contract as :func:`cpu_mesh_xla_flags`: append-only, never
    overriding an operator's explicit setting (skip any flag whose key
    is already present), and gated on the runtime actually shipping
    libtpu (metadata probe only, no backend init) so a CPU-only image
    is untouched. Returns whether anything was pinned.
    """
    if not _libtpu_available():
        return False
    args = os.environ.get("LIBTPU_INIT_ARGS", "").split()
    appended = False
    for flag in TPU_OVERLAP_INIT_ARGS:
        key = flag.split("=", 1)[0]
        if not any(a.split("=", 1)[0] == key for a in args):
            args.append(flag)
            appended = True
    os.environ["LIBTPU_INIT_ARGS"] = " ".join(args)
    return appended


def _libtpu_available() -> bool:
    """Whether a libtpu wheel is importable (metadata-only probe)."""
    try:
        import importlib.util

        return any(importlib.util.find_spec(name) is not None
                   for name in ("libtpu", "libtpu_nightly"))
    except Exception:  # noqa: BLE001 — unknown packaging: don't pin
        return False


def apply_jax_platforms_override() -> None:
    """Honor ``JAX_PLATFORMS`` even where a sitecustomize hook (e.g. the
    axon TPU-emulator plugin) pinned ``jax_platforms`` before our code
    ran — required to target the virtual CPU mesh from the CLI:
    ``JAX_PLATFORMS=cpu plx run ...``. No-op when unset or when jax is
    unavailable/already initialized with the same value.
    """
    platforms = os.environ.get("JAX_PLATFORMS")
    if not platforms:
        return
    try:
        import jax

        jax.config.update("jax_platforms", platforms)
    except ImportError:
        pass
