"""Serving runtime tests: HTTP generate endpoint, exact-length grouping
correctness, checkpoint loading, error surfaces."""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from polyaxon_tpu.serving import ServingServer, load_params


def _post(url, payload, timeout=120):
    req = urllib.request.Request(
        url + "/v1/generate", method="POST",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.load(resp)


@pytest.fixture(scope="module")
def server():
    with ServingServer("llama_tiny", seed=0) as s:
        yield s


class TestServing:
    def test_health_and_models(self, server):
        with urllib.request.urlopen(server.url + "/healthz", timeout=10) as r:
            assert json.load(r) == {"status": "ok", "model": "llama_tiny"}
        with urllib.request.urlopen(server.url + "/v1/models", timeout=10) as r:
            assert json.load(r) == {"models": ["llama_tiny"]}

    def test_generate_shapes_and_determinism(self, server):
        out = _post(server.url, {"tokens": [[5, 6, 7]], "max_new_tokens": 9})
        assert len(out["tokens"]) == 1 and len(out["tokens"][0]) == 9
        again = _post(server.url, {"tokens": [[5, 6, 7]], "max_new_tokens": 9})
        assert again["tokens"] == out["tokens"]  # greedy is deterministic

    def test_ragged_batch_matches_single_rows(self, server):
        """Grouping by exact length must give each row the same result it
        would get alone (no padding contamination)."""
        rows = [[5, 6, 7], [9, 8, 7, 6, 5], [1, 2, 3]]
        batch = _post(server.url, {"tokens": rows, "max_new_tokens": 6})
        for row, expect in zip(rows, batch["tokens"]):
            solo = _post(server.url, {"tokens": [row], "max_new_tokens": 6})
            assert solo["tokens"][0] == expect

    def test_sampling_uses_seed(self, server):
        a = _post(server.url, {"tokens": [[3, 4]], "max_new_tokens": 8,
                               "temperature": 1.0, "seed": 1})
        b = _post(server.url, {"tokens": [[3, 4]], "max_new_tokens": 8,
                               "temperature": 1.0, "seed": 1})
        c = _post(server.url, {"tokens": [[3, 4]], "max_new_tokens": 8,
                               "temperature": 1.0, "seed": 2})
        assert a["tokens"] == b["tokens"]
        assert a["tokens"] != c["tokens"]  # overwhelmingly likely

    def test_errors_are_typed(self, server):
        for payload in (
            {"tokens": []},                       # empty batch → []
            {"tokens": [[]]},                     # empty prompt
            {"tokens": [[1]], "max_new_tokens": 10**6},  # budget too big
            {"tokens": "nope"},                   # wrong type
        ):
            try:
                out = _post(server.url, payload)
                assert payload == {"tokens": []} and out == {"tokens": []}
            except urllib.error.HTTPError as exc:
                assert exc.code == 400
                assert "error" in json.load(exc)

    def test_negative_budget_rejected(self, server):
        for bad in (-1, 0):
            with pytest.raises(urllib.error.HTTPError) as err:
                _post(server.url, {"tokens": [[1, 2]], "max_new_tokens": bad})
            assert err.value.code == 400

    def test_temperature_sweep_reuses_executable(self, server):
        """Temperature is a traced argument — distinct values must not
        recompile (only greedy vs sampling switches programs)."""
        before = server.engine._compiled.cache_info()
        for t in (0.7, 0.8, 0.95):
            _post(server.url, {"tokens": [[4, 5, 6, 7]], "max_new_tokens": 5,
                               "temperature": t, "seed": 0})
        after = server.engine._compiled.cache_info()
        assert after.misses - before.misses <= 1  # one sampling program

    def test_serve_from_trained_jaxjob_checkpoint(self, tmp_path):
        """The advertised flow: train with checkpointing, then serve the
        artifacts/<uuid>/checkpoints dir (full train-state layout)."""
        from polyaxon_tpu.polyflow import V1JAXJob
        from polyaxon_tpu.runtime import run_jaxjob

        art = str(tmp_path / "run")
        job = V1JAXJob.from_dict({
            "kind": "jaxjob", "mesh": {"axes": {"dp": -1}},
            "checkpointing": {"enabled": True, "intervalSteps": 2,
                              "asyncSave": False},
            "runtime": {"model": "llama_tiny", "steps": 3, "batch_size": 1,
                        "seq_len": 16},
        })
        run_jaxjob(job, artifacts_dir=art)
        with ServingServer("llama_tiny", art + "/checkpoints") as s:
            out = _post(s.url, {"tokens": [[5, 6, 7]], "max_new_tokens": 4})
            assert len(out["tokens"][0]) == 4

    def test_serves_t5_seq2seq(self):
        with ServingServer("t5_tiny", seed=0) as s:
            out = _post(s.url, {"tokens": [[5, 6, 7, 8]], "max_new_tokens": 6})
            assert len(out["tokens"][0]) == 6
            again = _post(s.url, {"tokens": [[5, 6, 7, 8]],
                                  "max_new_tokens": 6})
            assert again["tokens"] == out["tokens"]
            with urllib.request.urlopen(s.url + "/v1/models", timeout=10) as r:
                assert json.load(r) == {"models": ["t5_tiny"]}

    def test_unknown_model_rejected(self):
        with pytest.raises(ValueError, match="not servable"):
            ServingServer("resnet50")

    def test_load_params_restores_checkpoint(self, tmp_path):
        import jax

        from polyaxon_tpu.runtime.checkpoint import CheckpointManager
        from polyaxon_tpu.polyflow.runs import V1JaxCheckpointing

        cfg, params = load_params("llama_tiny", seed=3)
        mutated = jax.tree.map(lambda x: x + 1.0, params)
        ckpt = CheckpointManager(
            str(tmp_path / "ck"),
            V1JaxCheckpointing(enabled=True, interval_steps=1, async_save=False))
        ckpt.save(5, {"params": mutated}, force=True)
        ckpt.close()

        _, restored = load_params("llama_tiny", str(tmp_path / "ck"), seed=3)
        leaf = jax.tree.leaves(restored)[0]
        orig = jax.tree.leaves(params)[0]
        np.testing.assert_allclose(np.asarray(leaf), np.asarray(orig) + 1.0)


class TestContinuousBatching:
    """Slot-pool engine (serving/batching.py): per-request correctness
    must be independent of what else occupies the pool."""

    def test_decode_step_ragged_matches_scalar(self):
        """Rows at different depths in one ragged step == each row run
        alone with the scalar-position decode_step; idle rows stay
        finite."""
        import dataclasses

        import jax
        import jax.numpy as jnp

        from polyaxon_tpu.models import llama

        cfg = dataclasses.replace(llama.CONFIGS["llama_tiny"],
                                  dtype=jnp.float32)
        params = llama.init(cfg, jax.random.key(0))["params"]
        max_len = 32
        rows = [jax.random.randint(jax.random.key(i + 1), (1, 5 + 3 * i),
                                   0, cfg.vocab_size) for i in range(3)]
        ref = []
        for r in rows:
            _, cache = llama.prefill(cfg, params, r[:, :-1], max_len)
            lg, _ = llama.decode_step(cfg, params, cache, r[0, -1:],
                                      jnp.int32(r.shape[1] - 1))
            ref.append(np.asarray(lg[0]))

        cache = llama.init_cache(cfg, len(rows) + 1, max_len)
        for i, r in enumerate(rows):
            _, c1 = llama.prefill(cfg, params, r[:, :-1], max_len)
            cache = {
                "k": cache["k"].at[:, i].set(c1["k"][:, 0]),
                "v": cache["v"].at[:, i].set(c1["v"][:, 0]),
            }
        tokens = jnp.asarray([r[0, -1] for r in rows] + [0], jnp.int32)
        pos = jnp.asarray([r.shape[1] - 1 for r in rows] + [-1], jnp.int32)
        out, _ = llama.decode_step_ragged(cfg, params, cache, tokens, pos)
        for i in range(len(rows)):
            np.testing.assert_allclose(np.asarray(out[i]), ref[i],
                                       atol=2e-4, rtol=2e-4)
        assert np.isfinite(np.asarray(out[len(rows)])).all()

    def test_matches_static_engine_greedy(self):
        """Continuous batching with mixed prompt lengths and budgets,
        more requests than slots (exercises retire→admit), must equal
        the whole-budget reference generation per request."""
        from polyaxon_tpu.models import llama
        from polyaxon_tpu.serving.batching import ContinuousBatchingEngine
        from polyaxon_tpu.serving.server import load_params

        cfg, params = load_params("llama_tiny", seed=0)
        engine = ContinuousBatchingEngine("llama_tiny", cfg, params,
                                          slots=2, max_len=64)
        try:
            prompts = [[5, 6, 7], [9, 8, 7, 6, 5], [1, 2], [3, 4, 5, 6]]
            budgets = [6, 9, 4, 7]
            reqs = [engine.submit(p, b) for p, b in zip(prompts, budgets)]
            outs = [r.wait(timeout=600) for r in reqs]
            import jax.numpy as jnp

            for p, b, got in zip(prompts, budgets, outs):
                expect = np.asarray(llama.generate(
                    cfg, params, jnp.asarray([p], jnp.int32),
                    max_new_tokens=b))[0].tolist()
                assert got == expect, (p, b)
        finally:
            engine.stop()

    def test_http_concurrent_requests(self):
        """Concurrent HTTP clients against --batching continuous each
        get the same tokens the static server produces."""
        import threading

        with ServingServer("llama_tiny", seed=0) as static_s, \
                ServingServer("llama_tiny", seed=0, batching="continuous",
                              slots=3) as cont_s:
            rows = [[5, 6, 7], [9, 8, 7, 6, 5], [1, 2, 3, 4]]
            expect = [
                _post(static_s.url,
                      {"tokens": [r], "max_new_tokens": 5})["tokens"][0]
                for r in rows]
            got: dict[int, list] = {}
            errs: list[Exception] = []

            def worker(i):
                try:
                    got[i] = _post(
                        cont_s.url,
                        {"tokens": [rows[i]],
                         "max_new_tokens": 5})["tokens"][0]
                except Exception as exc:  # noqa: BLE001
                    errs.append(exc)

            threads = [threading.Thread(target=worker, args=(i,))
                       for i in range(len(rows))]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=600)
            assert not errs, errs
            assert [got[i] for i in range(len(rows))] == expect

    def test_persistent_step_failure_fails_fast(self):
        """A device that throws on every decode step (e.g. persistent
        OOM) must NOT burn one rebuilt-cache step per queued request:
        after max_step_failures consecutive failures the engine drains
        the queue and stops (ADVICE r2, batching.py fail loop)."""
        from polyaxon_tpu.serving.batching import ContinuousBatchingEngine
        from polyaxon_tpu.serving.server import load_params

        cfg, params = load_params("llama_tiny", seed=0)
        engine = ContinuousBatchingEngine("llama_tiny", cfg, params,
                                          slots=1, max_len=32)
        calls = {"n": 0}

        def broken_step(*args, **kwargs):
            calls["n"] += 1
            raise RuntimeError("RESOURCE_EXHAUSTED: persistent OOM")

        engine._step_plain = engine._step_filtered = broken_step
        try:
            reqs = [engine.submit([1, 2, 3], 4) for _ in range(6)]
            errs = []
            for r in reqs:
                with pytest.raises(RuntimeError) as exc_info:
                    r.wait(timeout=120)
                errs.append(str(exc_info.value))
            # Fail-fast: exactly max_step_failures device steps, not
            # one per request; the rest drained with a typed error.
            assert calls["n"] == engine.max_step_failures
            assert sum("engine failed" in e for e in errs) == 3
            assert engine.stats()["stopped"] is True
            assert engine.stats()["step_failures"] == 3
            with pytest.raises(RuntimeError, match="engine stopped"):
                engine.submit([1, 2, 3], 4)
        finally:
            engine.stop()

    def test_persistent_admission_failure_fails_fast(self):
        """Device breakage can surface in the admission prefill instead
        of the decode step (each request compiles/runs its own prefill)
        — it must hit the same fail-fast budget, not burn one prefill
        per queued request."""
        from polyaxon_tpu.serving.batching import ContinuousBatchingEngine
        from polyaxon_tpu.serving.server import load_params

        cfg, params = load_params("llama_tiny", seed=0)
        engine = ContinuousBatchingEngine("llama_tiny", cfg, params,
                                          slots=1, max_len=32)
        calls = {"n": 0}

        def broken_prefill(plen):
            def run(params, prompt):
                calls["n"] += 1
                raise RuntimeError("RESOURCE_EXHAUSTED: prefill OOM")

            return run

        engine._compiled_prefill = broken_prefill
        try:
            reqs = [engine.submit([1, 2, 3], 4) for _ in range(6)]
            for r in reqs:
                with pytest.raises(RuntimeError):
                    r.wait(timeout=120)
            assert calls["n"] == engine.max_step_failures
            assert engine.stats()["stopped"] is True
        finally:
            engine.stop()

    def test_fail_fast_releases_live_slots(self):
        """Fail-fast triggered from the admission path must error-and-
        retire requests still LIVE in slots — the loop thread exits, so
        an unretired slot's waiter would block forever."""
        import time as _time

        from polyaxon_tpu.serving.batching import ContinuousBatchingEngine
        from polyaxon_tpu.serving.server import load_params

        cfg, params = load_params("llama_tiny", seed=0)
        engine = ContinuousBatchingEngine("llama_tiny", cfg, params,
                                          slots=4, max_len=256)
        try:
            live = engine.submit([1, 2, 3], 200)  # long-running
            deadline = _time.time() + 60
            while engine.stats()["active"] == 0:
                assert _time.time() < deadline, "request never went live"
                _time.sleep(0.05)

            def broken_prefill(plen):
                def run(params, prompt):
                    raise RuntimeError("RESOURCE_EXHAUSTED")

                return run

            engine._compiled_prefill = broken_prefill
            # One _admit pass hits 3 free slots → 3 consecutive
            # failures before any step can reset the counter.
            bad = [engine.submit([4, 5], 50) for _ in range(3)]
            for r in bad:
                with pytest.raises(RuntimeError):
                    r.wait(timeout=120)
            with pytest.raises(RuntimeError, match="engine failed"):
                live.wait(timeout=120)  # released, not hung
            assert engine.stats()["stopped"] is True
        finally:
            engine.stop()

    def test_bad_request_admission_errors_do_not_stop_engine(self):
        """Request-scoped admission errors (ValueError — not an XLA
        RuntimeError) must not trip the device fail-fast: three bad
        requests in a row would otherwise deny service to everyone."""
        from polyaxon_tpu.serving.batching import ContinuousBatchingEngine
        from polyaxon_tpu.serving.server import load_params

        cfg, params = load_params("llama_tiny", seed=0)
        engine = ContinuousBatchingEngine("llama_tiny", cfg, params,
                                          slots=1, max_len=32)
        real_admission = engine._family_mod.cb_admission
        state = {"bad": True}

        def sometimes_bad(tokens):
            if state["bad"]:
                raise ValueError("family rejected this prompt")
            return real_admission(tokens)

        import types

        engine._family_mod = types.SimpleNamespace(
            **{n: getattr(engine._family_mod, n)
               for n in dir(engine._family_mod) if not n.startswith("__")})
        engine._family_mod.cb_admission = sometimes_bad
        try:
            bad = [engine.submit([1, 2, 3], 4) for _ in range(4)]
            for r in bad:
                with pytest.raises(RuntimeError, match="rejected"):
                    r.wait(timeout=120)
            assert engine.stats()["stopped"] is False
            state["bad"] = False
            good = engine.submit([1, 2, 3], 4)
            assert len(good.wait(timeout=120)) == 4  # still serving
        finally:
            engine.stop()

    def test_transient_step_failure_recovers(self):
        """One failed step fails only the live requests; the engine
        rebuilds the cache and keeps serving the queue."""
        from polyaxon_tpu.serving.batching import ContinuousBatchingEngine
        from polyaxon_tpu.serving.server import load_params

        cfg, params = load_params("llama_tiny", seed=0)
        engine = ContinuousBatchingEngine("llama_tiny", cfg, params,
                                          slots=1, max_len=32)
        real_step = engine._step_plain
        calls = {"n": 0}

        def flaky_step(*args, **kwargs):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("transient")
            return real_step(*args, **kwargs)

        engine._step_plain = flaky_step
        try:
            r1 = engine.submit([1, 2, 3], 4)
            with pytest.raises(RuntimeError, match="transient"):
                r1.wait(timeout=120)
            r2 = engine.submit([1, 2, 3], 4)
            out = r2.wait(timeout=120)
            assert len(out) == 4
            assert engine.stats()["stopped"] is False
            assert engine.stats()["step_failures"] == 1
        finally:
            engine.stop()

    def test_over_budget_rejected(self):
        from polyaxon_tpu.serving.batching import ContinuousBatchingEngine
        from polyaxon_tpu.serving.server import load_params

        cfg, params = load_params("llama_tiny", seed=0)
        engine = ContinuousBatchingEngine("llama_tiny", cfg, params,
                                          slots=1, max_len=16)
        try:
            with pytest.raises(ValueError, match="exceeds max_len"):
                engine.submit([1] * 10, 10)
        finally:
            engine.stop()

    def test_t5_continuous_matches_static(self, monkeypatch):
        """Seq2seq continuous batching: per-slot encoder state (padded
        cross-KV + length mask) lets requests with different encoder
        lengths share one ragged decoder step — outputs equal the
        static engine. fp32: bf16 reduction-order noise can flip
        argmax between the two decode paths."""
        import dataclasses

        import jax.numpy as jnp

        from polyaxon_tpu.models import t5

        monkeypatch.setitem(
            t5.CONFIGS, "t5_tiny",
            dataclasses.replace(t5.CONFIGS["t5_tiny"], dtype=jnp.float32))
        rows = [[5, 6, 7], [9, 8, 7, 6, 5, 4]]
        with ServingServer("t5_tiny", seed=0) as static_s:
            expect = _post(static_s.url, {"tokens": rows,
                                          "max_new_tokens": 5})["tokens"]
        with ServingServer("t5_tiny", seed=0, batching="continuous",
                           slots=2) as cont_s:
            got = _post(cont_s.url, {"tokens": rows,
                                     "max_new_tokens": 5})["tokens"]
        assert got == expect

    def test_t5_ragged_decode_matches_scalar(self):
        """T5 decode_step_ragged at mixed per-row depths == per-row
        scalar decode_step with its own cross-KV."""
        import dataclasses

        import jax
        import jax.numpy as jnp

        from polyaxon_tpu.models import t5

        cfg = dataclasses.replace(t5.CONFIGS["t5_tiny"], dtype=jnp.float32)
        params = t5.init(cfg, jax.random.key(0))["params"]
        max_new = 8
        prompts = [jnp.asarray([[5, 6, 7]], jnp.int32),
                   jnp.asarray([[9, 8, 7, 6, 5]], jnp.int32)]
        # Reference: run each request alone, stepping to depth d_i.
        depths = [0, 2]
        refs, pool = [], t5.cb_init_cache(cfg, 3, max_new)
        toks, poss = [], []
        for i, (prompt, depth) in enumerate(zip(prompts, depths)):
            enc = t5.encode(cfg, params, prompt)
            cross = t5.precompute_cross_kv(cfg, params, enc)
            cache = t5.init_decoder_cache(cfg, 1, max_new)
            tok = jnp.asarray([0], jnp.int32)
            for d in range(depth + 1):
                lg, cache = t5.decode_step(cfg, params, cross, cache,
                                           tok, d)
                tok = jnp.argmax(lg, axis=-1).astype(jnp.int32)
            refs.append((lg, cache, tok))
            # Seed the pool slot: encoder row + replayed decoder KV.
            row = t5.cb_prefill(cfg, params, prompt, max_new)
            pool = t5.insert_cache_row(pool, row, jnp.int32(i))
            pool = {
                **pool,
                "k": pool["k"].at[:, i].set(cache["k"][:, 0]),
                "v": pool["v"].at[:, i].set(cache["v"][:, 0]),
            }
        # One ragged step at each row's NEXT depth (+ an idle row).
        import numpy as np

        tokens = jnp.asarray([int(refs[0][2][0]), int(refs[1][2][0]), 0],
                             jnp.int32)
        pos = jnp.asarray([depths[0] + 1, depths[1] + 1, -1], jnp.int32)
        rag_lg, _ = t5.decode_step_ragged(cfg, params, pool, tokens, pos)
        for i, (prompt, depth) in enumerate(zip(prompts, depths)):
            enc = t5.encode(cfg, params, prompt)
            cross = t5.precompute_cross_kv(cfg, params, enc)
            lg, cache, tok = refs[i]
            want, _ = t5.decode_step(cfg, params, cross, cache, tok,
                                     depth + 1)
            np.testing.assert_allclose(np.asarray(rag_lg[i]),
                                       np.asarray(want[0]),
                                       atol=2e-4, rtol=2e-4)
        assert np.isfinite(np.asarray(rag_lg[2])).all()  # idle row


class TestShardedServing:
    """Mesh-sharded weights: serving an 8B-class model tensor-parallel
    (SURVEY §2b TP row) must be output-identical to single-device."""

    def test_tp_sharded_matches_unsharded(self):
        rows = [[5, 6, 7], [9, 8, 7, 6, 5]]
        with ServingServer("llama_tiny", seed=0) as ref_s:
            expect = _post(ref_s.url,
                           {"tokens": rows, "max_new_tokens": 6})["tokens"]
        with ServingServer("llama_tiny", seed=0,
                           mesh_axes={"tp": 4}) as tp_s:
            assert tp_s.mesh is not None
            got = _post(tp_s.url,
                        {"tokens": rows, "max_new_tokens": 6})["tokens"]
        assert got == expect

    def test_fsdp_all_devices_continuous(self):
        """fsdp=-1 absorbs the whole 8-device mesh; the continuous
        batcher runs on sharded weights too."""
        rows = [[5, 6, 7], [1, 2, 3, 4]]
        with ServingServer("llama_tiny", seed=0) as ref_s:
            expect = _post(ref_s.url,
                           {"tokens": rows, "max_new_tokens": 5})["tokens"]
        with ServingServer("llama_tiny", seed=0, batching="continuous",
                           slots=2, mesh_axes={"fsdp": -1}) as s:
            got = _post(s.url,
                        {"tokens": rows, "max_new_tokens": 5})["tokens"]
        assert got == expect


class TestStreaming:
    @staticmethod
    def _stream(url, payload, timeout=300):
        import urllib.request

        req = urllib.request.Request(
            url + "/v1/generate", method="POST",
            data=json.dumps(dict(payload, stream=True)).encode(),
            headers={"Content-Type": "application/json"})
        events = []
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            assert resp.headers["Content-Type"].startswith(
                "text/event-stream")
            event_name = None
            for raw in resp:
                line = raw.decode().rstrip("\n")
                if line.startswith("event: "):
                    event_name = line[len("event: "):]
                elif line.startswith("data: "):
                    events.append((event_name or "token",
                                   json.loads(line[len("data: "):])))
                    event_name = None
        return events

    def test_streaming_matches_nonstreaming_continuous(self):
        rows = [[5, 6, 7], [9, 8, 7, 6, 5]]
        with ServingServer("llama_tiny", seed=0, batching="continuous",
                           slots=2) as s:
            expect = _post(s.url, {"tokens": rows,
                                   "max_new_tokens": 6})["tokens"]
            events = self._stream(s.url, {"tokens": rows,
                                          "max_new_tokens": 6})
        done = [p for name, p in events if name == "done"]
        assert len(done) == 1 and done[0]["tokens"] == expect
        # Per-token events reassemble into the same rows, in order.
        streamed = [[], []]
        for name, p in events:
            if name == "token":
                streamed[p["index"]].append(p["token"])
        assert streamed == expect

    def test_streaming_static_engine_bursts(self):
        rows = [[5, 6, 7]]
        with ServingServer("llama_tiny", seed=0) as s:
            expect = _post(s.url, {"tokens": rows,
                                   "max_new_tokens": 5})["tokens"]
            events = self._stream(s.url, {"tokens": rows,
                                          "max_new_tokens": 5})
        done = [p for name, p in events if name == "done"]
        assert done and done[0]["tokens"] == expect
        assert [p["token"] for n, p in events if n == "token"] == expect[0]

    @pytest.mark.parametrize("batching", ["continuous", "static"])
    def test_streaming_bad_request_is_http_400(self, batching):
        """Over-budget streaming requests are proper HTTP 400s on BOTH
        engines — never a 200 stream carrying an error event."""
        import urllib.error
        import urllib.request

        with ServingServer("llama_tiny", seed=0, batching=batching,
                           slots=1) as s:
            req = urllib.request.Request(
                s.url + "/v1/generate", method="POST",
                data=json.dumps({"tokens": [[1] * 100],
                                 "max_new_tokens": 10_000,
                                 "stream": True}).encode(),
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(req, timeout=60)
            assert err.value.code == 400


class TestStats:
    def test_stats_counters_both_engines(self):
        for batching, engine_name in (("static", "static"),
                                      ("continuous", "continuous")):
            with ServingServer("llama_tiny", seed=0,
                               batching=batching, slots=2) as s:
                _post(s.url, {"tokens": [[5, 6, 7], [1, 2, 3]],
                              "max_new_tokens": 4})
                with urllib.request.urlopen(s.url + "/v1/stats",
                                            timeout=10) as r:
                    stats = json.load(r)
            assert stats["engine"] == engine_name
            assert stats["requests_served"] == 2
            assert stats["tokens_generated"] == 8
            if batching == "continuous":
                assert stats["active"] == 0 and stats["queued"] == 0

    def test_occupancy_gauges_during_burst(self):
        """A burst of more requests than slots must surface in the
        occupancy gauges: queue_depth_peak >= 1 and avg_occupancy in
        (0, 1] — the number that says continuous batching is winning
        (VERDICT r2 item 5)."""
        with ServingServer("llama_tiny", seed=0, batching="continuous",
                           slots=2) as s:
            rows = [[5, 6, 7], [9, 8, 7], [1, 2, 3], [4, 5, 6]]
            _post(s.url, {"tokens": rows, "max_new_tokens": 6},
                  timeout=300)
            with urllib.request.urlopen(s.url + "/v1/stats",
                                        timeout=10) as r:
                stats = json.load(r)
        assert stats["decode_steps"] > 0
        assert stats["queue_depth_peak"] >= 1  # 4 requests, 2 slots
        assert stats["avg_occupancy"] is not None
        assert 0.0 < stats["avg_occupancy"] <= 1.0


class TestSampling:
    """top-p/top-k fused into the compiled step (VERDICT r2 item 5):
    distribution checks at fixed seed, greedy-equivalence over HTTP
    for all families, and request validation."""

    def test_top_k_one_is_argmax(self):
        import jax
        import jax.numpy as jnp

        from polyaxon_tpu.models.common import sample_row

        logits = jnp.asarray([0.3, 2.0, -1.0, 1.4, 0.0])
        for seed in range(8):
            tok = sample_row(logits, jax.random.key(seed),
                             jnp.float32(3.0), jnp.float32(1.0),
                             jnp.int32(1))
            assert int(tok) == 1

    def test_top_k_distribution_matches_renormalized_softmax(self):
        import jax
        import jax.numpy as jnp

        from polyaxon_tpu.models.common import sample_row

        logits = jnp.asarray([2.0, 1.5, 0.5, -0.5, -3.0, 1.0])
        n = 4000
        keys = jax.random.split(jax.random.key(0), n)
        draws = np.asarray(jax.vmap(
            lambda k: sample_row(logits, k, jnp.float32(1.0),
                                 jnp.float32(1.0), jnp.int32(2)))(keys))
        assert set(np.unique(draws)) <= {0, 1}  # only the top-2 ids
        p = jax.nn.softmax(jnp.asarray([2.0, 1.5]))  # renormalized pair
        freq0 = float(np.mean(draws == 0))
        assert abs(freq0 - float(p[0])) < 0.03

    def test_top_p_keeps_minimal_nucleus(self):
        import jax
        import jax.numpy as jnp

        from polyaxon_tpu.models.common import sample_row

        # softmax ≈ [0.63, 0.23, 0.09, 0.03, 0.01]: p=0.5 → nucleus is
        # exactly the argmax; p=0.8 → the top-2.
        logits = jnp.asarray([3.0, 2.0, 1.0, 0.0, -1.0])
        keys = jax.random.split(jax.random.key(1), 500)

        def draw(p):
            return np.asarray(jax.vmap(
                lambda k: sample_row(logits, k, jnp.float32(1.0),
                                     jnp.float32(p), jnp.int32(0)))(keys))

        assert set(np.unique(draw(0.5))) == {0}
        assert set(np.unique(draw(0.8))) <= {0, 1}

    def test_plain_sampling_bit_stable_with_historical_draw(self):
        """sample_logits with filters disabled must reproduce the exact
        jax.random.categorical draw older clients' seeds produced."""
        import jax
        import jax.numpy as jnp

        from polyaxon_tpu.models.common import sample_logits

        logits = jax.random.normal(jax.random.key(3), (4, 16))
        key = jax.random.key(7)
        want = jax.random.categorical(key, logits / 0.7, axis=-1)
        got = sample_logits(logits, key, jnp.float32(0.7))
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    @pytest.mark.parametrize("model,batching", [
        ("llama_tiny", "static"), ("llama_tiny", "continuous"),
        ("t5_tiny", "static"), ("t5_tiny", "continuous"),
    ])
    def test_top_k_one_equals_greedy_over_http(self, model, batching):
        """temperature high + top_k=1 must equal greedy output for
        every family on both engines — the end-to-end proof the filter
        runs inside the step."""
        kw = {"batching": batching, "slots": 2} if batching == "continuous" \
            else {}
        with ServingServer(model, seed=0, **kw) as s:
            greedy = _post(s.url, {"tokens": [[5, 6, 7]],
                                   "max_new_tokens": 6}, timeout=300)
            topk1 = _post(s.url, {"tokens": [[5, 6, 7]],
                                  "max_new_tokens": 6,
                                  "temperature": 4.0, "top_k": 1,
                                  "seed": 9}, timeout=300)
        assert topk1["tokens"] == greedy["tokens"]

    def test_invalid_sampling_params_rejected(self, server):
        for payload in ({"top_p": 0.0}, {"top_p": 1.5}, {"top_k": -1}):
            with pytest.raises(urllib.error.HTTPError) as err:
                _post(server.url, {"tokens": [[1, 2]],
                                   "max_new_tokens": 2, **payload})
            assert err.value.code == 400

    def test_direct_engine_callers_validated_too(self):
        """Range checks live in the engines, not just the HTTP layer:
        a Python caller passing top_p=0 must get a ValueError, not a
        silent argmax degeneration."""
        from polyaxon_tpu.serving.batching import ContinuousBatchingEngine
        from polyaxon_tpu.serving.server import load_params

        cfg, params = load_params("llama_tiny", seed=0)
        engine = ContinuousBatchingEngine("llama_tiny", cfg, params,
                                          slots=1, max_len=32)
        try:
            with pytest.raises(ValueError, match="top_p"):
                engine.submit([1, 2], 2, temperature=1.0, top_p=0.0)
            with pytest.raises(ValueError, match="top_k"):
                engine.submit([1, 2], 2, temperature=1.0, top_k=-1)
        finally:
            engine.stop()

    def test_plain_temperature_continuous_seed_stable(self):
        """The continuous engine keeps the historical per-row
        categorical draw when no filter is active — the filtered step
        variant (full-vocab sort) only engages for rows that use
        top_p/top_k, so pre-existing (seed → tokens) mappings hold."""
        import jax
        import jax.numpy as jnp

        from polyaxon_tpu.models import llama
        from polyaxon_tpu.serving.batching import ContinuousBatchingEngine
        from polyaxon_tpu.serving.server import load_params

        cfg, params = load_params("llama_tiny", seed=0)
        engine = ContinuousBatchingEngine("llama_tiny", cfg, params,
                                          slots=1, max_len=32)
        try:
            got = engine.submit([5, 6, 7], 3, temperature=0.8,
                                seed=42).wait(timeout=300)
            # Reference: the engine's documented draw — fold_in(step)
            # per emitted token over the ragged decode step's logits.
            cache = engine._family_mod.cb_init_cache(cfg, 1, 32)
            pos0, tok0, pre = engine._family_mod.cb_admission([5, 6, 7])
            row_cache = engine._family_mod.cb_prefill(
                cfg, params, jnp.asarray([pre], jnp.int32), 32)
            cache = engine._family_mod.insert_cache_row(
                cache, row_cache, jnp.int32(0))
            key, cur, pos, want = jax.random.key(42), tok0, pos0, []
            for step_i in range(3):
                logits, cache = llama.decode_step_ragged(
                    cfg, params, cache, jnp.asarray([cur], jnp.int32),
                    jnp.asarray([pos], jnp.int32))
                k = jax.random.fold_in(key, step_i)
                nxt = int(jax.random.categorical(k, logits[0] / 0.8))
                want.append(nxt)
                cur, pos = nxt, pos + 1
            assert got == want
        finally:
            engine.stop()


class TestQuantize:
    """Int8 weight-only serving (VERDICT r2 item 10): per-channel
    symmetric quantization over the contraction axis, dequantized
    inside the jitted programs."""

    def test_roundtrip_error_bounded_per_channel(self):
        import jax
        import jax.numpy as jnp

        from polyaxon_tpu.serving.quantize import quantize_leaf

        w = jax.random.normal(jax.random.key(0), (3, 64, 32), jnp.float32)
        qt = quantize_leaf(w)
        assert qt.q.dtype == jnp.int8
        assert qt.scale.shape == (3, 1, 32)  # per-layer per-out-channel
        # Symmetric rounding: |w - deq| <= scale/2 elementwise.
        err = jnp.abs(w - qt.dequantize())
        assert bool(jnp.all(err <= qt.scale / 2 + 1e-7))

    def test_dequantize_tree_identity_on_plain_trees(self):
        import jax
        import jax.numpy as jnp

        from polyaxon_tpu.serving.quantize import dequantize_tree

        tree = {"w": jnp.ones((4, 4)), "b": jnp.zeros(4), "n": 3}
        out = dequantize_tree(tree)
        assert out["w"] is tree["w"] and out["b"] is tree["b"]
        assert out["n"] == 3

    def test_tree_bytes_roughly_halved(self):
        import jax

        from polyaxon_tpu.models import llama
        from polyaxon_tpu.serving.quantize import quantize_tree, tree_bytes

        params = llama.init(llama.CONFIGS["llama_tiny"],
                            jax.random.key(0))["params"]
        full = tree_bytes(params)
        q = quantize_tree(params)
        # bf16 matmul weights -> int8 + f32 scales; 1-D norm gains stay.
        assert tree_bytes(q) < 0.62 * full

    def test_logit_parity_bounded(self):
        """Quantization noise must stay small relative to the logit
        scale: the int8 forward tracks the bf16 forward closely on a
        randomly-initialized llama_tiny."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from polyaxon_tpu.models import llama
        from polyaxon_tpu.serving.quantize import (dequantize_tree,
                                                   quantize_tree)

        cfg = llama.CONFIGS["llama_tiny"]
        params = llama.init(cfg, jax.random.key(0))["params"]
        tokens = jax.random.randint(jax.random.key(1), (2, 16), 0,
                                    cfg.vocab_size)
        ref = np.asarray(llama.forward(cfg, params, tokens))
        deq = dequantize_tree(quantize_tree(params))
        got = np.asarray(llama.forward(cfg, deq, tokens))
        denom = np.maximum(np.abs(ref).max(), 1e-6)
        rel = np.abs(got - ref).max() / denom
        assert rel < 0.05, f"int8 logits off by {rel:.3f} of logit scale"
        # And the distributions stay essentially identical.
        cos = float(np.sum(ref * got)
                    / (np.linalg.norm(ref) * np.linalg.norm(got)))
        assert cos > 0.999

    def test_static_serving_end_to_end_int8(self):
        with ServingServer("llama_tiny", seed=0, quantize="int8") as s:
            out = _post(s.url, {"tokens": [[5, 6, 7]], "max_new_tokens": 8})
            assert len(out["tokens"][0]) == 8
            again = _post(s.url, {"tokens": [[5, 6, 7]],
                                  "max_new_tokens": 8})
            assert again["tokens"] == out["tokens"]  # greedy deterministic

    def test_continuous_matches_static_int8(self):
        """Both engines dequantize the same tree, so int8 greedy decode
        must agree token-for-token between them."""
        import jax

        from polyaxon_tpu.models import llama
        from polyaxon_tpu.serving.batching import ContinuousBatchingEngine
        from polyaxon_tpu.serving.quantize import quantize_tree
        from polyaxon_tpu.serving.server import _Engine

        cfg = llama.CONFIGS["llama_tiny"]
        params = quantize_tree(
            llama.init(cfg, jax.random.key(0))["params"])
        static = _Engine("llama_tiny", cfg, params)
        engine = ContinuousBatchingEngine("llama_tiny", cfg, params,
                                          slots=2)
        try:
            rows = [[5, 6, 7], [1, 2, 3, 4]]
            want = static.generate(rows, max_new_tokens=6)
            got = engine.generate(rows, max_new_tokens=6, timeout=120)
            assert got == want
        finally:
            engine.stop()


class TestStatsPage:
    def test_serving_dashboard_served(self, server):
        import urllib.request

        for path in ("/", "/ui"):
            with urllib.request.urlopen(server.url + path, timeout=10) as r:
                page = r.read().decode()
                assert r.headers["Content-Type"].startswith("text/html")
        assert "/v1/stats" in page and "tokens generated" in page


def _full_tables_on_while_carries(hlo: str, V: int, D: int) -> list:
    """Full-precision [V,D]/[D,V] buffers riding any while-loop carry —
    the hoisted-dequant regression signature both orientation tests
    scan for. Assumes the carry-tuple type prints on the `while(` line
    (XLA text format); the single shared copy is the one to fix when
    that changes."""
    import re

    carried = []
    for m in re.finditer(r"while\(", hlo):
        line = hlo[hlo.rfind("\n", 0, m.start()) + 1:m.start()]
        carried += re.findall(r"(?:bf16|f32)\[(\d+),(\d+)\]", line)
    return [s for s in carried if {int(s[0]), int(s[1])} == {V, D}]


class TestQuantizeInLoop:
    """VERDICT r3 #3: int8 must stay the HBM-resident format through
    the decode scan — the model unwraps each weight at its consumption
    site, so the compiled loop body consumes s8 operands instead of a
    hoisted bf16 copy of the tree."""

    def test_norm_gains_never_quantized(self):
        import jax

        from polyaxon_tpu.models import llama
        from polyaxon_tpu.serving.quantize import QuantizedTensor, quantize_tree

        cfg = llama.CONFIGS["llama_tiny"]
        q = quantize_tree(llama.init(cfg, jax.random.key(0))["params"])
        assert not isinstance(q["layers"]["attn_norm"], QuantizedTensor)
        assert not isinstance(q["final_norm"], QuantizedTensor)
        assert isinstance(q["layers"]["wq"], QuantizedTensor)
        assert isinstance(q["embed"], QuantizedTensor)

    def test_quantized_tree_flows_through_decode_scan(self):
        """Greedy parity with the plain tree, AND the compiled program
        keeps int8 live: s8 buffers present, and no full-table bf16
        embed ([V, D]) is materialized (rows are gathered int8-first)."""
        import jax
        import jax.numpy as jnp

        from polyaxon_tpu.models import llama
        from polyaxon_tpu.serving.quantize import quantize_tree

        cfg = llama.CONFIGS["llama_tiny"]
        plain = llama.init(cfg, jax.random.key(0))["params"]
        quant = quantize_tree(plain)
        prompt = jnp.zeros((2, 8), jnp.int32)

        def run(params, prompt):
            return llama.generate(cfg, params, prompt, max_new_tokens=12)

        out_q = jax.jit(run)(quant, prompt)
        out_p = jax.jit(run)(plain, prompt)
        assert (out_q == out_p).all(), "int8 greedy decode diverged"

        hlo = jax.jit(run).lower(quant, prompt).compile().as_text()
        assert "s8[" in hlo, "quantized weights vanished from the program"
        V, D = cfg.vocab_size, cfg.dim
        assert f"bf16[{V},{D}]" not in hlo, (
            "full embed table dequantized to bf16 — the int8-first "
            "row gather regressed")
        # ADVICE r4 #1/#2: the UNTIED lm_head is [D, V], so a hoisted
        # dequant materializes the TRANSPOSED table — which the [V, D]
        # assert above cannot see. The regression signature is a full-
        # precision full-table buffer riding a while-loop carry (the
        # hoisted table is re-read every decode step).
        full_tables = _full_tables_on_while_carries(hlo, V, D)
        assert not full_tables, (
            f"full-precision lm_head/embed table {full_tables} rides "
            "the decode loop carry — the dequant was hoisted out of "
            "the loop (pin_in_loop regressed)")

    def test_tied_embeddings_quantized_decode(self):
        """The TIED head ([V, D] embed consumed transposed) through the
        full decode scan: greedy parity with the plain tree, and the
        same while-carry guarantee — no full-precision table in either
        orientation rides the loop (the tied table is the embed, so a
        hoist here would double-count the biggest weight)."""
        import jax
        import jax.numpy as jnp

        from polyaxon_tpu.models import llama
        from polyaxon_tpu.serving.quantize import quantize_tree

        cfg = llama.CONFIGS["llama_tiny_tied"]
        assert cfg.tie_embeddings
        plain = llama.init(cfg, jax.random.key(0))["params"]
        assert "lm_head" not in plain  # tied: embed IS the head
        quant = quantize_tree(plain)
        prompt = jnp.zeros((2, 8), jnp.int32)

        def run(params, prompt):
            return llama.generate(cfg, params, prompt, max_new_tokens=10)

        out_q = jax.jit(run)(quant, prompt)
        out_p = jax.jit(run)(plain, prompt)
        assert (out_q == out_p).all(), "tied int8 greedy decode diverged"

        hlo = jax.jit(run).lower(quant, prompt).compile().as_text()
        full = _full_tables_on_while_carries(
            hlo, cfg.vocab_size, cfg.dim)
        assert not full, (
            f"full-precision tied table {full} rides the decode loop "
            "carry — the transposed lm_logits branch regressed")

    def test_families_serve_int8(self):
        """int8 must work for EVERY servable family end-to-end (review
        regression: the t5 encoder stack missed the unwrap-at-
        consumption conversion and only llama was tested). t5 holds
        exact greedy parity; moe does NOT get a parity assert — int8
        error through the top-k router is a discrete re-route, so
        tiny random-init models legitimately diverge mid-sequence —
        but must serve, deterministically."""
        # llama_tiny_tied: no parity assert either — a tied head is the
        # [V, D] embed consumed transposed, so its per-D quant scales sit
        # on the logits CONTRACTION axis and int8 noise flips argmax on
        # tiny random models (prompt-dependent; observed [5,6,7,8]).
        # The load-bearing tied guarantees are serve + determinism here
        # and the while-carry scan in test_tied_embeddings_quantized_decode.
        for model, parity in (("t5_tiny", True), ("moe_tiny", False),
                              ("llama_tiny_tied", False)):
            with ServingServer(model, seed=0) as plain:
                ref = _post(plain.url,
                            {"tokens": [[5, 6, 7, 8]], "max_new_tokens": 5})
            with ServingServer(model, seed=0, quantize="int8") as q:
                out = _post(q.url,
                            {"tokens": [[5, 6, 7, 8]], "max_new_tokens": 5})
                again = _post(q.url,
                              {"tokens": [[5, 6, 7, 8]], "max_new_tokens": 5})
            assert len(out["tokens"][0]) == 5, f"{model} int8 failed"
            assert out["tokens"] == again["tokens"], (
                f"{model} int8 nondeterministic")
            if parity:
                assert out["tokens"] == ref["tokens"], (
                    f"{model} int8 diverged")


class TestChunkedPrefill:
    """vLLM-style chunked prefill on the continuous engine: long
    prompts stream into a standalone row cache N tokens per loop
    iteration instead of blocking the pool on one monolithic prefill;
    the finished row inserts like any admission."""

    def _params(self):
        import jax

        from polyaxon_tpu.models import llama

        cfg = llama.CONFIGS["llama_tiny"]
        return cfg, llama.init(cfg, jax.random.key(0))["params"]

    def test_outputs_identical_to_monolithic_prefill(self):
        """Every prompt-length shape (shorter than the chunk, exact
        multiples, padded tails, single-token) produces the same
        greedy AND sampled output as the unchunked engine."""
        from polyaxon_tpu.serving.batching import ContinuousBatchingEngine

        cfg, params = self._params()
        prompts = [[7], [1, 2, 3], [5, 6, 7, 8, 9],
                   [4] * 8, [2, 9] * 6 + [1]]  # 1, 3, 5, 8, 13
        want, got = [], []
        for chunk in (None, 4):
            engine = ContinuousBatchingEngine(
                "llama_tiny", cfg, params, slots=2, prefill_chunk=chunk)
            try:
                reqs = [engine.submit(p, 6, temperature=t, seed=11)
                        for p in prompts for t in (0.0, 0.7)]
                outs = [r.wait(timeout=300) for r in reqs]
            finally:
                engine.stop()
            (want if chunk is None else got).append(outs)
        assert got[0] == want[0]

    def test_live_rows_keep_decoding_during_long_admission(self):
        """A short request admitted first must FINISH while the long
        prompt is still observably prefilling — the property chunking
        exists for. (A blocking monolithic prefill can never show
        requests_served >= 1 and prefilling == 1 at the same instant.)"""
        import time as _time

        from polyaxon_tpu.serving.batching import ContinuousBatchingEngine

        cfg, params = self._params()
        engine = ContinuousBatchingEngine(
            "llama_tiny", cfg, params, slots=2, prefill_chunk=2)
        try:
            short = engine.submit([5, 6], 4)
            long = engine.submit(list(range(1, 60)), 4)  # ~29 chunks
            interleaved = False
            deadline = _time.monotonic() + 300
            while _time.monotonic() < deadline:
                s = engine.stats()
                if s["requests_served"] >= 1 and s["prefilling"] >= 1:
                    interleaved = True  # short done, long still streaming
                    break
                if s["requests_served"] >= 2:
                    break  # both finished without the window being seen
                _time.sleep(0.005)
            short_out = short.wait(timeout=300)
            long_out = long.wait(timeout=300)
        finally:
            engine.stop()
        assert len(short_out) == 4 and len(long_out) == 4
        assert interleaved, (
            "short request never observed finished while the long "
            "prompt was still prefilling — admission blocked the pool")

    def test_spec_and_chunked_compose(self):
        """Speculative rounds + chunked admission together still equal
        the plain continuous engine's greedy output."""
        from polyaxon_tpu.serving.batching import ContinuousBatchingEngine

        cfg, params = self._params()
        prompts = [[5, 6, 7, 8, 9, 10, 11], [1, 2, 3]]
        plain = ContinuousBatchingEngine("llama_tiny", cfg, params, slots=2)
        try:
            want = [plain.submit(p, 8).wait(timeout=300) for p in prompts]
        finally:
            plain.stop()
        engine = ContinuousBatchingEngine(
            "llama_tiny", cfg, params, slots=2, prefill_chunk=3,
            draft=("llama_tiny", cfg, params, 3))
        try:
            got = [r.wait(timeout=300)
                   for r in [engine.submit(p, 8) for p in prompts]]
        finally:
            engine.stop()
        assert got == want

    def test_paged_and_static_refused(self):
        from polyaxon_tpu.serving import ServingServer
        from polyaxon_tpu.serving.batching import ContinuousBatchingEngine

        cfg, params = self._params()
        with pytest.raises(ValueError, match="dense"):
            ContinuousBatchingEngine("llama_tiny", cfg, params,
                                     kv="paged", prefill_chunk=8)
        with pytest.raises(ValueError, match="continuous"):
            ServingServer("llama_tiny", prefill_chunk=8)


class TestEosStop:
    """Per-request early stop: generation retires at the first of the
    request's eos_tokens (inclusive), on every engine."""

    def _expect(self, full, eos_set):
        hit = next((i for i, t in enumerate(full) if t in eos_set), None)
        return full if hit is None else full[:hit + 1]

    def test_static_engine_truncates_at_eos(self, server):
        full = _post(server.url,
                     {"tokens": [[5, 6, 7]], "max_new_tokens": 9}
                     )["tokens"][0]
        eos = full[3]  # guaranteed to occur
        got = _post(server.url, {"tokens": [[5, 6, 7]],
                                 "max_new_tokens": 9,
                                 "eos_token": eos})["tokens"][0]
        assert got == self._expect(full, {eos})
        assert len(got) < 9

    def test_continuous_engines_truncate_at_eos(self):
        import jax

        from polyaxon_tpu.models import llama
        from polyaxon_tpu.serving.batching import ContinuousBatchingEngine

        cfg = llama.CONFIGS["llama_tiny"]
        params = llama.init(cfg, jax.random.key(0))["params"]
        prompts = [[5, 6, 7], [1, 2, 3, 4]]
        plain = ContinuousBatchingEngine("llama_tiny", cfg, params, slots=2)
        try:
            full = [plain.submit(p, 10).wait(timeout=300) for p in prompts]
        finally:
            plain.stop()
        eos = full[0][2]
        for draft in (None, ("llama_tiny", cfg, params, 3)):
            engine = ContinuousBatchingEngine(
                "llama_tiny", cfg, params, slots=2, draft=draft)
            try:
                got = [engine.submit(p, 10, eos_tokens=[eos])
                       .wait(timeout=300) for p in prompts]
            finally:
                engine.stop()
            label = "spec" if draft else "plain"
            for g, f in zip(got, full):
                assert g == self._expect(f, {eos}), (label, g, f)

    def test_bad_eos_rejected(self, server):
        import urllib.error

        with pytest.raises(urllib.error.HTTPError) as err:
            _post(server.url, {"tokens": [[1, 2]], "max_new_tokens": 4,
                               "eos_tokens": ["nope"]})
        assert err.value.code == 400

class TestLmLogitsChunked:
    """common.lm_logits — the chunked quantized head consumption that
    keeps int8 on decode-loop carries (ADVICE r4 #1). The llama_tiny
    e2e tests only exercise the exact-divide path; these cover padding
    (V not a multiple of the chunk) and the tied/transposed layout."""

    def _check(self, D, V, transpose, chunk):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from polyaxon_tpu.models.common import lm_logits
        from polyaxon_tpu.serving.quantize import quantize_leaf

        shape = (V, D) if transpose else (D, V)
        w = jax.random.normal(jax.random.key(0), shape, jnp.float32) * 0.1
        q = quantize_leaf(w)
        x = jax.random.normal(jax.random.key(1), (3, D), jnp.bfloat16)
        got = lm_logits(x, q, jnp.bfloat16, transpose=transpose,
                        chunk=chunk)
        tab = q.dequantize().astype(jnp.bfloat16)
        want = (x @ (tab.T if transpose else tab)).astype(jnp.float32)
        assert got.shape == (3, V)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-2, rtol=1e-2)

    def test_pad_path(self):
        # V=300: chunk 128 → 3 chunks with 84 pad columns sliced off.
        self._check(D=32, V=300, transpose=False, chunk=128)

    def test_tied_transpose_path(self):
        self._check(D=32, V=300, transpose=True, chunk=128)

    def test_tiny_vocab_falls_back(self):
        # V too small to split: the one-dot fallback path.
        self._check(D=16, V=3, transpose=False, chunk=128)
        self._check(D=16, V=3, transpose=True, chunk=128)

    def test_3d_hidden_states(self):
        """decode_chunk passes [B, c, D] hidden states — the chunked
        path must broadcast like the plain matmul does."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from polyaxon_tpu.models.common import lm_logits
        from polyaxon_tpu.serving.quantize import quantize_leaf

        D, V = 16, 256
        w = jax.random.normal(jax.random.key(0), (D, V), jnp.float32) * 0.1
        q = quantize_leaf(w)
        x = jax.random.normal(jax.random.key(1), (2, 5, D), jnp.bfloat16)
        got = lm_logits(x, q, jnp.bfloat16, chunk=64)
        want = (x @ q.dequantize().astype(jnp.bfloat16)).astype(jnp.float32)
        assert got.shape == (2, 5, V)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-2, rtol=1e-2)


# ===================================================== request obs (ISSUE 10)
class TestRequestObservability:
    """ISSUE 10 e2e: concurrent streams against a real continuous
    server leave queue_wait→prefill→decode span timelines behind
    `/requests/{id}/timeline`, per-class SLO series on a line-parsed
    `/metrics` scrape, and shed-load accounting when admission says
    no."""

    _SAMPLE_RE = None  # compiled lazily in _parse_metrics

    @pytest.fixture(scope="class")
    def obs_server(self):
        with ServingServer("llama_tiny", seed=0, batching="continuous",
                           slots=2, prefill_chunk=4) as s:
            yield s

    @staticmethod
    def _timeline(url, request_id):
        with urllib.request.urlopen(
                f"{url}/requests/{request_id}/timeline", timeout=30) as r:
            return json.load(r)

    @staticmethod
    def _parse_metrics(url):
        """Strict 0.0.4 line parse: ({name: type}, {sample: value});
        an unparseable exposition line fails the test, not just the
        missing-series assertion."""
        import re

        with urllib.request.urlopen(url + "/metrics", timeout=30) as r:
            text = r.read().decode()
        sample_re = re.compile(
            r'^([a-zA-Z_:][a-zA-Z0-9_:]*)'
            r'(\{(?:[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*",?)*\})?'
            r' ([-+0-9.eE]+|\+Inf|-Inf|NaN)$')
        types, samples = {}, {}
        for line in text.splitlines():
            if not line.strip():
                continue
            if line.startswith("# TYPE "):
                _, _, name, mtype = line.split(" ")
                types[name] = mtype
            elif not line.startswith("#"):
                match = sample_re.match(line)
                assert match, f"unparseable exposition line: {line!r}"
                samples[match.group(1) + (match.group(2) or "")] = float(
                    match.group(3))
        return types, samples

    def test_concurrent_streams_leave_phase_timelines(self, obs_server):
        import threading

        rows = [[5, 6, 7, 8, 9, 10], [9, 8, 7, 6, 5, 4], [1, 2, 3, 4, 5, 6]]
        results: dict[int, list] = {}
        errs: list[Exception] = []

        def worker(i):
            try:
                results[i] = TestStreaming._stream(
                    obs_server.url,
                    {"tokens": [rows[i]], "max_new_tokens": 6,
                     "class": "interactive"})
            except Exception as exc:  # noqa: BLE001
                errs.append(exc)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(len(rows))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
        assert not errs, errs

        for i in range(len(rows)):
            done = [p for name, p in results[i] if name == "done"]
            assert len(done) == 1
            assert len(done[0]["tokens"][0]) == 6
            (rid,) = done[0]["request_ids"]
            payload = self._timeline(obs_server.url, rid)
            assert payload["trace_id"] == rid
            (root,) = payload["spans"]
            assert root["name"] == "request"
            phases = [c["name"] for c in root["children"]]
            assert phases[0] == "queue_wait" and phases[-1] == "decode"
            assert "prefill" in phases

            summary = payload["summary"]
            assert summary["request_id"] == rid
            assert summary["class"] == "interactive"
            assert summary["status"] == "ok"
            assert summary["tokens_out"] == 6
            assert summary["events"].get("first_token") == 1
            # 6-token prompt through a 4-token chunked prefill streams
            # at least one chunk.
            assert summary["events"].get("chunk", 0) >= 1
            assert summary["ttft_ms"] is not None and summary["ttft_ms"] > 0
            assert set(summary["phases_ms"]) >= {"queue_wait", "prefill",
                                                 "decode"}

    def test_metrics_scrape_has_per_class_slo_series(self, obs_server):
        _post(obs_server.url, {"tokens": [[5, 6, 7], [7, 6, 5]],
                               "max_new_tokens": 5, "class": "scrape"})
        types, samples = self._parse_metrics(obs_server.url)
        for name in ("polyaxon_serving_ttft_seconds",
                     "polyaxon_serving_tpot_seconds",
                     "polyaxon_serving_queue_wait_seconds",
                     "polyaxon_serving_engine_tick_seconds"):
            assert types[name] == "histogram", name
        assert types["polyaxon_serving_rejected_total"] == "counter"
        assert types["polyaxon_serving_batch_slots"] == "gauge"
        # Both rows of the labeled request landed in every SLO family.
        for stem in ("ttft", "tpot", "queue_wait"):
            key = (f'polyaxon_serving_{stem}_seconds_count'
                   '{class="scrape"}')
            assert samples.get(key, 0) >= 2, key
        assert samples['polyaxon_serving_engine_tick_seconds_count'] > 0
        assert ('polyaxon_serving_admissions_total{outcome="admitted"}'
                in samples)
        # Tick telemetry gauges expose the batch composition states.
        for state in ("decode", "prefill", "free"):
            assert (f'polyaxon_serving_batch_slots{{state="{state}"}}'
                    in samples), state

    def test_requests_listing_and_unknown_id_404(self, obs_server):
        out = _post(obs_server.url, {"tokens": [[4, 5, 6]],
                                     "max_new_tokens": 3})
        (rid,) = out["request_ids"]
        with urllib.request.urlopen(obs_server.url + "/requests",
                                    timeout=30) as r:
            listing = json.load(r)["requests"]
        mine = [row for row in listing if row["request_id"] == rid]
        assert mine and mine[0]["class"] == "batch"
        assert mine[0]["done"] is True and mine[0]["status"] == "ok"
        with pytest.raises(urllib.error.HTTPError) as err:
            self._timeline(obs_server.url, "deadbeef" * 2)
        assert err.value.code == 404
        assert "unknown or evicted" in json.load(err.value)["error"]

    def test_static_engine_has_no_timelines(self, server):
        for path in ("/requests", "/requests/deadbeef/timeline"):
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(server.url + path, timeout=30)
            assert err.value.code == 404
            assert "continuous" in json.load(err.value)["error"]

    def test_shed_load_is_accounted(self):
        """queue_full and shutdown rejections land in the labeled
        rejected counter AND stats()["rejected"]; a rejected request
        never occupies timeline-ring capacity."""
        import time

        from polyaxon_tpu.obs import metrics as obs_metrics
        from polyaxon_tpu.serving.batching import (ContinuousBatchingEngine,
                                                   QueueFull)

        cfg, params = load_params("llama_tiny", seed=0)
        engine = ContinuousBatchingEngine("llama_tiny", cfg, params,
                                          slots=1, max_len=32,
                                          max_pending=1)
        rejected = obs_metrics.serving_rejected_total()
        base_full = rejected.value(reason="queue_full")
        base_stop = rejected.value(reason="shutdown")
        try:
            real_plain = engine._step_plain

            def slow_step(*args, **kwargs):
                time.sleep(0.05)
                return real_plain(*args, **kwargs)

            engine._step_plain = slow_step
            accepted = [engine.submit([1, 2, 3], 8)]
            with pytest.raises(QueueFull) as err:
                for _ in range(4):  # 1-deep queue: full within a few
                    accepted.append(engine.submit([1, 2, 3], 8))
            assert err.value.retry_after >= 1
            for req in accepted:
                req.wait(timeout=600)
            stats = engine.stats()
            assert stats["rejected"]["queue_full"] >= 1
            assert rejected.value(reason="queue_full") > base_full
            # Ring holds exactly the accepted requests.
            assert stats["traced_requests"] == len(accepted)
        finally:
            engine.stop()
        with pytest.raises(RuntimeError, match="stopped"):
            engine.submit([1, 2, 3], 4)
        assert engine.stats()["rejected"]["shutdown"] >= 1
        assert rejected.value(reason="shutdown") > base_stop


@pytest.mark.slow
class TestTracingOverhead:
    """ISSUE 10 acceptance: request tracing ON vs OFF must cost <= 5%
    throughput on the same workload (min-of-3 wall clock; a small
    absolute allowance absorbs scheduler jitter on the CPU-tiny
    model)."""

    def test_tracing_overhead_within_five_percent(self):
        import time

        from polyaxon_tpu.serving.batching import ContinuousBatchingEngine

        cfg, params = load_params("llama_tiny", seed=0)

        def best_wall(tracing):
            engine = ContinuousBatchingEngine(
                "llama_tiny", cfg, params, slots=4, max_len=64,
                request_tracing=tracing)
            try:
                engine.submit([7] * 8, 4).wait(timeout=600)  # warm
                best = None
                for _ in range(3):
                    t0 = time.perf_counter()
                    reqs = [engine.submit([7] * 8, 24) for _ in range(16)]
                    for req in reqs:
                        req.wait(timeout=600)
                    wall = time.perf_counter() - t0
                    best = wall if best is None else min(best, wall)
                assert engine.stats()["traced_requests"] == (
                    49 if tracing else 0)
                return best
            finally:
                engine.stop()

        untraced = best_wall(False)
        traced = best_wall(True)
        assert traced <= untraced * 1.05 + 0.025, (
            f"tracing overhead: {traced:.3f}s traced vs "
            f"{untraced:.3f}s untraced")
