"""Fleet front door: prefix-affinity routing over replicated engines.

The router answers one question per request — *which replica* — using
three signals in strict precedence order:

1. **Radix-prefix affinity.** The PR 11 radix tree makes prefill cost
   depend on *where* a prompt lands: a replica that already holds the
   prompt's prefix skips those tokens entirely. The router lifts that
   signal fleet-wide as a prefix→replica map (learned from its own
   routing history — the map IS the affinity): a request whose prefix
   was last served on replica 3 goes back to replica 3.
2. **Hotness-cap spill.** Affinity concentrates; one viral prefix must
   not melt a single replica. When the affinity target already owns
   more than ``hot_fraction`` of the recent routing window — or its
   polled pending queue is past ``spill_depth`` — the request spills
   to the prefix's consistent-hash owner instead: a deterministic
   second home, so the spilled prefix still warms ONE other radix
   tree rather than spraying across the fleet.
3. **Consistent hash.** No affinity entry (cold prefix) → the ring
   owner. Replica add/remove moves only ~1/N of the keyspace, so a
   scale event does not invalidate the whole fleet's cache placement.

Replica health gates every step: a replica that is not ready (warming,
draining, released) or reports no KV headroom is skipped, falling to
the least-loaded healthy replica (reason ``spill``).

Everything here is pure Python over ``hashlib`` — deterministic for a
fixed replica set + seed, no jax, testable at unit speed.
"""

from __future__ import annotations

import bisect
import collections
import hashlib
from dataclasses import dataclass
from typing import Iterable, Optional, Sequence

from polyaxon_tpu.obs import metrics as obs_metrics

# First-K tokens identify a shared prefix. 16 tokens spans the system
# prompt / few-shot preamble at real scale and the whole conversation
# stem at sim scale; radix granularity below that is noise to a router.
PREFIX_WINDOW = 16

ROUTE_REASONS = ("affinity", "hash", "spill")


def prefix_key(tokens: Sequence[int], window: int = PREFIX_WINDOW) -> str:
    """Stable hex digest of the first ``window`` tokens."""
    head = ",".join(str(int(t)) for t in tokens[:window])
    return hashlib.sha1(head.encode()).hexdigest()[:16]


class ConsistentHashRing:
    """Classic vnode consistent-hash ring over replica ids.

    ``vnodes`` virtual points per replica smooth the keyspace split;
    removal of one replica moves only that replica's arcs (~1/N of
    keys) to its ring successors — the property the fleet tests pin.
    Hashing is ``hashlib``-based so placement is stable across
    processes and runs (Python's ``hash()`` is salted per process).
    """

    def __init__(self, nodes: Iterable[str] = (), *, vnodes: int = 64,
                 seed: int = 0):
        self.vnodes = int(vnodes)
        self.seed = int(seed)
        self._points: list[int] = []  # sorted vnode hashes
        self._owners: dict[int, str] = {}  # vnode hash -> replica id
        self._nodes: set[str] = set()
        for node in nodes:
            self.add(node)

    def _hash(self, key: str) -> int:
        digest = hashlib.sha1(f"{self.seed}:{key}".encode()).digest()
        return int.from_bytes(digest[:8], "big")

    def add(self, node: str) -> None:
        if node in self._nodes:
            return
        self._nodes.add(node)
        for i in range(self.vnodes):
            h = self._hash(f"{node}#{i}")
            # Collisions are ~impossible at 64-bit; deterministic
            # tie-break by id keeps add-order irrelevant anyway.
            if h in self._owners and self._owners[h] <= node:
                continue
            if h not in self._owners:
                bisect.insort(self._points, h)
            self._owners[h] = node

    def remove(self, node: str) -> None:
        if node not in self._nodes:
            return
        self._nodes.discard(node)
        for h, owner in list(self._owners.items()):
            if owner == node:
                del self._owners[h]
                idx = bisect.bisect_left(self._points, h)
                if idx < len(self._points) and self._points[idx] == h:
                    del self._points[idx]

    @property
    def nodes(self) -> frozenset:
        return frozenset(self._nodes)

    def owner(self, key: str) -> Optional[str]:
        """First vnode clockwise of ``hash(key)``, or None when empty."""
        if not self._points:
            return None
        h = self._hash(key)
        idx = bisect.bisect_right(self._points, h)
        if idx == len(self._points):
            idx = 0
        return self._owners[self._points[idx]]


@dataclass
class RouteDecision:
    replica: str
    reason: str  # affinity | hash | spill
    prefix: str


class FleetRouter:
    """Prefix-affinity router with hotness-cap spill and hash fallback.

    ``route(tokens, telemetry=...)`` returns a :class:`RouteDecision`.
    ``telemetry`` maps replica id → its ``health()`` dict (one polled
    surface — queue depth, KV headroom, radix hit rate); replicas
    absent from the map are assumed healthy, replicas whose payload
    says not-ok are skipped. ``blind=True`` is the red-team seam: the
    affinity map AND the hash are ignored and requests round-robin
    across ready replicas — prefix locality collapses, which is
    exactly what the ci.sh ``route-blind`` inject must demonstrate.
    """

    def __init__(self, replicas: Iterable[str] = (), *, vnodes: int = 64,
                 seed: int = 0, prefix_window: int = PREFIX_WINDOW,
                 hot_fraction: float = 0.5, recent: int = 128,
                 hot_min: int = 16, spill_depth: Optional[int] = 8,
                 blind: bool = False, registry=None):
        self.ring = ConsistentHashRing(replicas, vnodes=vnodes, seed=seed)
        self.prefix_window = int(prefix_window)
        self.hot_fraction = float(hot_fraction)
        self.hot_min = int(hot_min)
        self.spill_depth = spill_depth if spill_depth is None \
            else int(spill_depth)
        self.blind = bool(blind)
        self._registry = registry or obs_metrics.REGISTRY
        self._affinity: dict[str, str] = {}  # prefix -> replica id
        self._recent: collections.deque = collections.deque(maxlen=recent)
        self._rr = 0  # round-robin cursor (blind mode)
        self.routed_total: collections.Counter = collections.Counter()

    # ------------------------------------------------------------ fleet
    def add_replica(self, replica: str) -> None:
        self.ring.add(replica)

    def remove_replica(self, replica: str) -> None:
        self.ring.remove(replica)
        # Drop the departed replica's affinity entries so its prefixes
        # re-home via the ring instead of bouncing off the dead id.
        self._affinity = {p: r for p, r in self._affinity.items()
                          if r != replica}

    @property
    def replicas(self) -> frozenset:
        return self.ring.nodes

    # ----------------------------------------------------------- health
    @staticmethod
    def _healthy(replica: str, telemetry: Optional[dict]) -> bool:
        if not telemetry or replica not in telemetry:
            return True
        view = telemetry[replica] or {}
        if view.get("status", "ok") != "ok":
            return False
        headroom = view.get("kv_headroom")
        if headroom is not None and headroom.get("free", 1) <= 0:
            return False
        return True

    def _ready(self, telemetry: Optional[dict]) -> list[str]:
        return sorted(r for r in self.ring.nodes
                      if self._healthy(r, telemetry))

    @staticmethod
    def _least_loaded(candidates: list[str],
                      telemetry: Optional[dict]) -> str:
        def load(r: str) -> tuple:
            view = (telemetry or {}).get(r) or {}
            return (view.get("queued", 0) + view.get("active", 0), r)
        return min(candidates, key=load)

    # ------------------------------------------------------------ route
    def route(self, tokens: Sequence[int], *,
              telemetry: Optional[dict] = None) -> RouteDecision:
        ready = self._ready(telemetry)
        if not ready:
            raise RuntimeError("no healthy replicas to route to")
        key = prefix_key(tokens, self.prefix_window)

        if self.blind:
            # Red-team mode: ignore the prefix signal entirely.
            replica = ready[self._rr % len(ready)]
            self._rr += 1
            return self._commit(replica, "hash", key, learn=False)

        target = self._affinity.get(key)
        if target is not None and target in ready:
            owner = self.ring.owner(key)
            crowded = (self._hot(target)
                       or self._pressured(target, telemetry))
            if not crowded or owner == target:
                # At its hash home the cap is a no-op (there is no
                # deterministic second home to send it to — sustained
                # heat there is the AUTOSCALER's problem, and a
                # scale-up moves ~1/N of ring ownership, which is what
                # un-sticks a viral prefix: see the branch below).
                return self._commit(target, "affinity", key)
            # Hotness cap tripped on a prefix whose affinity drifted
            # off its hash home (typically: ownership moved under it
            # when a replica joined/left): spill it back to the ring
            # owner — the deterministic second home (tests pin this).
            if owner in ready:
                return self._commit(owner, "spill", key, learn=False)
            return self._commit(self._least_loaded(ready, telemetry),
                                "spill", key, learn=False)

        owner = self.ring.owner(key)
        if owner in ready:
            return self._commit(owner, "hash", key)
        # Ring owner unhealthy/draining: deflect to least-loaded.
        return self._commit(self._least_loaded(ready, telemetry),
                            "spill", key)

    def _pressured(self, replica: str,
                   telemetry: Optional[dict]) -> bool:
        """Queue-depth half of the hotness cap: a target whose PREFILL
        backlog is past ``spill_depth`` is deflected exactly like a
        routing-share hog — this is what lets a freshly-committed
        replica actually RELIEVE a spike (ring ownership moved ~1/N of
        prefixes onto it; pressure unsticks their affinity).

        Prefill depth, not total queue depth (ISSUE 18): a replica
        whose slots are merely decode-busy admits new work next tick —
        spilling away from it would shred affinity for nothing. Falls
        back to `queued` for engines predating the per-lane fields.

        Per-class saturation also counts (ISSUE 19): a replica whose
        `interactive` pending has reached its class cap sheds the very
        requests the fleet most wants served, even when the aggregate
        prefill_pending looks fine — treat it as pressured so urgent
        traffic deflects before it 503s. This guard is cap-relative,
        so it applies whether or not a global spill_depth is set."""
        view = (telemetry or {}).get(replica) or {}
        pending = view.get("class_pending") or {}
        caps = view.get("class_caps") or {}
        cap = caps.get("interactive")
        if cap is not None and pending.get("interactive", 0) >= cap:
            return True
        if self.spill_depth is None:
            return False
        depth = view.get("prefill_pending")
        if depth is None:
            depth = view.get("queued", 0)
        return depth > self.spill_depth

    def _hot(self, replica: str) -> bool:
        # The cap needs a populated window to mean anything: the first
        # few routes of a quiet fleet trivially give one replica 100%
        # share, and spilling THOSE would defeat affinity entirely.
        if len(self._recent) < self.hot_min:
            return False
        share = sum(1 for r in self._recent if r == replica)
        return share / len(self._recent) > self.hot_fraction

    def _commit(self, replica: str, reason: str, key: str,
                learn: bool = True) -> RouteDecision:
        if learn:
            self._affinity[key] = replica
        self._recent.append(replica)
        self.routed_total[reason] += 1
        obs_metrics.fleet_routed_total(self._registry).inc(reason=reason)
        return RouteDecision(replica=replica, reason=reason, prefix=key)

    # ------------------------------------------------------------ stats
    def stats(self) -> dict:
        return {
            "replicas": sorted(self.ring.nodes),
            "affinity_entries": len(self._affinity),
            "routed": dict(self.routed_total),
            "blind": self.blind,
        }
