"""Run lifecycle: statuses, conditions, and the legal transition graph.

Mirrors the capability of the reference's ``polyaxon/lifecycle`` layer
(SURVEY.md §2 "Lifecycle", [K]): a run advances
created → compiled → queued → scheduled → starting → running →
{succeeded, failed, stopped, skipped, upstream_failed, done}, with
auxiliary states (resuming, retrying, on_schedule, awaiting_cache) and
a monotonic condition list recorded on every transition.

TPU-native addition: ``PREEMPTED`` is first-class (preemptible TPU-VM
slices are part of the north star) and is restartable without counting
against ``max_retries`` unless the spec says otherwise.
"""

from __future__ import annotations

import datetime as _dt
from enum import Enum
from typing import Optional

from pydantic import BaseModel, Field


def now() -> _dt.datetime:
    return _dt.datetime.now(_dt.timezone.utc)


class V1Statuses(str, Enum):
    CREATED = "created"
    ON_SCHEDULE = "on_schedule"
    RESUMING = "resuming"
    AWAITING_CACHE = "awaiting_cache"
    COMPILED = "compiled"
    QUEUED = "queued"
    SCHEDULED = "scheduled"
    STARTING = "starting"
    RUNNING = "running"
    PROCESSING = "processing"
    STOPPING = "stopping"
    SUCCEEDED = "succeeded"
    FAILED = "failed"
    UPSTREAM_FAILED = "upstream_failed"
    STOPPED = "stopped"
    SKIPPED = "skipped"
    WARNING = "warning"
    UNSCHEDULABLE = "unschedulable"
    PREEMPTED = "preempted"
    RETRYING = "retrying"
    UNKNOWN = "unknown"
    DONE = "done"

    @classmethod
    def terminal_values(cls) -> set["V1Statuses"]:
        return {
            cls.SUCCEEDED,
            cls.FAILED,
            cls.UPSTREAM_FAILED,
            cls.STOPPED,
            cls.SKIPPED,
            cls.DONE,
        }


DONE_STATUSES = V1Statuses.terminal_values()
RUNNABLE_STATUSES = {V1Statuses.QUEUED, V1Statuses.SCHEDULED, V1Statuses.STARTING}
PENDING_STATUSES = {
    V1Statuses.CREATED,
    V1Statuses.ON_SCHEDULE,
    V1Statuses.AWAITING_CACHE,
    V1Statuses.COMPILED,
    V1Statuses.RESUMING,
}
LIVE_STATUSES = {V1Statuses.RUNNING, V1Statuses.PROCESSING, V1Statuses.STOPPING}

# Legal forward edges of the state machine. Anything may move to a terminal
# failure/stop state; PREEMPTED and RETRYING loop back into the queue.
_TRANSITIONS: dict[V1Statuses, set[V1Statuses]] = {
    V1Statuses.CREATED: {
        V1Statuses.ON_SCHEDULE,
        V1Statuses.RESUMING,
        V1Statuses.AWAITING_CACHE,
        V1Statuses.COMPILED,
        V1Statuses.SKIPPED,
    },
    V1Statuses.ON_SCHEDULE: {V1Statuses.COMPILED, V1Statuses.AWAITING_CACHE},
    V1Statuses.RESUMING: {V1Statuses.COMPILED, V1Statuses.AWAITING_CACHE},
    V1Statuses.AWAITING_CACHE: {V1Statuses.COMPILED, V1Statuses.SUCCEEDED, V1Statuses.SKIPPED},
    V1Statuses.COMPILED: {V1Statuses.QUEUED},
    V1Statuses.QUEUED: {V1Statuses.SCHEDULED, V1Statuses.UNSCHEDULABLE},
    V1Statuses.UNSCHEDULABLE: {V1Statuses.QUEUED, V1Statuses.SCHEDULED},
    V1Statuses.SCHEDULED: {V1Statuses.STARTING, V1Statuses.RUNNING, V1Statuses.PREEMPTED},
    V1Statuses.STARTING: {V1Statuses.RUNNING, V1Statuses.PREEMPTED},
    V1Statuses.RUNNING: {
        V1Statuses.PROCESSING,
        V1Statuses.STOPPING,
        V1Statuses.SUCCEEDED,
        V1Statuses.FAILED,
        V1Statuses.WARNING,
        V1Statuses.PREEMPTED,
    },
    V1Statuses.PROCESSING: {V1Statuses.RUNNING, V1Statuses.SUCCEEDED, V1Statuses.FAILED},
    V1Statuses.WARNING: {V1Statuses.RUNNING, V1Statuses.SUCCEEDED, V1Statuses.FAILED},
    V1Statuses.STOPPING: {V1Statuses.STOPPED, V1Statuses.FAILED},
    V1Statuses.PREEMPTED: {V1Statuses.RETRYING, V1Statuses.QUEUED, V1Statuses.FAILED},
    V1Statuses.RETRYING: {V1Statuses.QUEUED, V1Statuses.COMPILED},
    V1Statuses.UNKNOWN: set(V1Statuses),
}
# Universal edges: any non-terminal state can be stopped or fail outright.
_UNIVERSAL_TARGETS = {
    V1Statuses.STOPPING,
    V1Statuses.STOPPED,
    V1Statuses.FAILED,
    V1Statuses.UPSTREAM_FAILED,
    V1Statuses.UNKNOWN,
    V1Statuses.DONE,
}


class V1StatusCondition(BaseModel):
    type: V1Statuses
    status: bool = True
    reason: Optional[str] = None
    message: Optional[str] = None
    last_update_time: _dt.datetime = Field(default_factory=now)
    last_transition_time: _dt.datetime = Field(default_factory=now)

    @classmethod
    def get_condition(
        cls,
        type: V1Statuses,  # noqa: A002 - mirrors upstream kwarg name
        status: bool = True,
        reason: Optional[str] = None,
        message: Optional[str] = None,
    ) -> "V1StatusCondition":
        return cls(type=type, status=status, reason=reason, message=message)


class LifecycleError(Exception):
    pass


def is_done(status: V1Statuses) -> bool:
    return status in DONE_STATUSES


def can_transition(current: V1Statuses, target: V1Statuses) -> bool:
    if current == target:
        return False
    if is_done(current) and target != V1Statuses.DONE:
        return False
    if target in _UNIVERSAL_TARGETS:
        return True
    return target in _TRANSITIONS.get(current, set())


def validate_transition(current: V1Statuses, target: V1Statuses) -> None:
    if not can_transition(current, target):
        raise LifecycleError(f"Illegal lifecycle transition: {current.value} -> {target.value}")


class StatusTracker(BaseModel):
    """Holds the current status plus the condition history for one run."""

    status: V1Statuses = V1Statuses.CREATED
    conditions: list[V1StatusCondition] = Field(
        default_factory=lambda: [V1StatusCondition(type=V1Statuses.CREATED)]
    )

    def transition(
        self,
        target: V1Statuses,
        reason: Optional[str] = None,
        message: Optional[str] = None,
        force: bool = False,
    ) -> V1StatusCondition:
        if not force:
            validate_transition(self.status, target)
        cond = V1StatusCondition.get_condition(type=target, reason=reason, message=message)
        self.status = target
        self.conditions.append(cond)
        return cond

    @property
    def is_done(self) -> bool:
        return is_done(self.status)
