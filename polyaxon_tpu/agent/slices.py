"""SliceManager: bridges the agent reconcile loop and the native C++
slice pool (polyaxon_tpu/native/sliced.py — SURVEY.md §2a).

The agent asks it before starting any gang whose launch plan requests a
TPU topology: placement either succeeds (gang pinned to ICI-contiguous
chips of a registered slice), stays pending (no capacity — run stays
QUEUED), or triggers priority eviction of lower-priority gangs on
preemptible slices (victims transition PREEMPTED and the scheduler
requeues them — SURVEY.md §5.3). Heartbeats come from the agent's own
poll of live gang processes; a stale gang follows the native restart
policy and surfaces RESTART/FAILED events back into run statuses.
"""

from __future__ import annotations

import logging
import time
from typing import Optional

from polyaxon_tpu.native import SlicePool, SlicedError

logger = logging.getLogger(__name__)


def _chips_of(topology: str) -> int:
    n = 1
    for d in topology.lower().split("x"):
        n *= int(d)
    return n


class SliceManager:
    def __init__(
        self,
        slices: Optional[list[tuple[str, str, bool]]] = None,
        *,
        heartbeat_timeout: float = 60.0,
    ):
        self.pool = SlicePool()
        self.heartbeat_timeout = heartbeat_timeout
        self._gangs: dict[str, int] = {}  # run_uuid -> gang id
        self._slices: list[tuple[str, str, bool]] = list(slices or [])
        for name, topology, preemptible in self._slices:
            self.pool.add_slice(name, topology, preemptible=preemptible)

    def close(self) -> None:
        self.pool.close()

    # ------------------------------------------------------------ placement
    def ensure_placed(self, run_uuid: str, topology: Optional[str], *,
                      priority: Optional[int] = None, max_restarts: int = 0,
                      preemptible: bool = False) -> str:
        """Returns the gang state (``running`` means cleared to start).

        Runs without a topology request bypass placement entirely.
        ``priority`` is the scheduling catalog's gang priority (queue ×
        priority class — ``scheduling.gang_priority``); ``None`` falls
        back to the legacy preemptible/reserved split. 0 is a real
        priority (the ``low`` class on a priority-0 queue), not "unset".
        """
        if not topology:
            return "running"
        gang_id = self._gangs.get(run_uuid)
        if gang_id is not None:
            # A preempted/failed gang must be re-requested from scratch
            # (the scheduler requeued the run; chips were already vacated).
            try:
                state = self.pool.gang(gang_id).state
            except SlicedError:  # gang already erased pool-side
                state = "released"
            if state in ("preempted", "failed", "released"):
                self.release(run_uuid)
                gang_id = None
        if gang_id is None:
            try:
                gang_id = self.pool.request_gang(
                    run_uuid, topology,
                    priority=(priority if priority is not None
                              else (0 if preemptible else 1)),
                    max_restarts=max_restarts,
                )
            except SlicedError as exc:
                logger.warning("placement rejected for %s: %s", run_uuid, exc)
                return "unplaceable"
            self._gangs[run_uuid] = gang_id
        return self.pool.gang(gang_id).state

    def resize_placement(self, run_uuid: str, topology: str, *,
                         priority: Optional[int] = None,
                         max_restarts: int = 0,
                         preemptible: bool = False) -> str:
        """Partial vacate / regrow (elastic gangs — ISSUE 14): re-place
        a LIVE gang at a different topology without the all-or-nothing
        preempted→requeue round trip. The current subgrid is released
        and the new one requested in its place; a grow that does not
        place *immediately* (``unplaceable`` OR parked ``pending`` in
        the pool queue) restores the old placement, so the still-running
        gang never trains on chips it no longer holds — a queued resize
        would let the pool hand its working set to someone else."""
        if not topology:
            return "running"
        try:
            placed = self.placement(run_uuid)
        except SlicedError:  # gang erased pool-side (e.g. slice removed)
            placed = None
        old_topology = placed.topology if placed is not None else None
        self.release(run_uuid)
        state = self.ensure_placed(run_uuid, topology, priority=priority,
                                   max_restarts=max_restarts,
                                   preemptible=preemptible)
        if state != "running" and old_topology:
            # Roll back: drop the failed/queued request and re-pin the
            # old footprint — its chips were just freed, so the
            # original placement always fits again.
            self.release(run_uuid)
            self.ensure_placed(run_uuid, old_topology, priority=priority,
                               max_restarts=max_restarts,
                               preemptible=preemptible)
        return state

    def capacity_available(self, topology: str) -> bool:
        """Capacity-return notification: True when some registered
        slice has enough free chips for ``topology`` right now — the
        signal the agent polls to grow shrunk elastic gangs back. Free
        chips are necessary, not sufficient (ICI contiguity is decided
        by the pool), so callers must treat a later placement rejection
        as a non-event."""
        need = _chips_of(topology)
        for name, _topo, _pre in self._slices:
            try:
                if self.pool.free_chips(name) >= need:
                    return True
            except SlicedError:
                continue
        return False

    def placement(self, run_uuid: str):
        gang_id = self._gangs.get(run_uuid)
        return self.pool.gang(gang_id) if gang_id is not None else None

    def tracked_runs(self) -> list[str]:
        return list(self._gangs)

    def release(self, run_uuid: str) -> None:
        gang_id = self._gangs.pop(run_uuid, None)
        if gang_id is not None:
            try:
                self.pool.release_gang(gang_id)
            except SlicedError:
                pass

    def stats(self) -> dict:
        """Pool state for the API/dashboard: per-slice capacity and the
        gangs currently placed (the operator view of the C++ pool)."""
        slices = []
        for name, topology, preemptible in self._slices:
            total = _chips_of(topology)
            try:
                free = self.pool.free_chips(name)
            except SlicedError:  # removed from the pool since init
                continue
            slices.append({"name": name, "topology": topology,
                           "preemptible": preemptible,
                           "free_chips": free, "total_chips": total})
        gangs = []
        # Snapshot: API handler threads poll this while the agent
        # thread mutates placements.
        for run_uuid, gang_id in list(self._gangs.items()):
            try:
                g = self.pool.gang(gang_id)
            except SlicedError:
                continue
            gangs.append({"run_uuid": run_uuid, "state": g.state,
                          "slice": g.slice, "topology": g.topology,
                          "chips": len(g.chips), "restarts": g.restarts})
        return {"slices": slices, "gangs": gangs}

    # -------------------------------------------------------------- signals
    def heartbeat(self, run_uuid: str, *, proc: int = 0,
                  now: Optional[float] = None) -> None:
        gang_id = self._gangs.get(run_uuid)
        if gang_id is not None:
            self.pool.heartbeat(gang_id, proc, time.time() if now is None else now)

    def preempt_slice(self, name: str) -> int:
        return self.pool.preempt_slice(name)

    # ------------------------------------------------------------ reconcile
    def tick(self, now: Optional[float] = None) -> dict[str, list[str]]:
        """Advance the native pool; returns {run_uuid: [event kinds]}."""
        events = self.pool.tick(
            time.time() if now is None else now,
            heartbeat_timeout=self.heartbeat_timeout,
        )
        by_gang = {gid: uuid for uuid, gid in self._gangs.items()}
        out: dict[str, list[str]] = {}
        for event in events:
            uuid = by_gang.get(event.gang_id)
            if uuid is not None:
                out.setdefault(uuid, []).append(event.kind)
        return out
