"""Speculative decoding: lossless-greedy guarantee (output == the
target's own greedy sequence, token for token), draft quality only
affecting speed; engine/HTTP integration with silent fallbacks."""

import dataclasses
import json
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from polyaxon_tpu.models import llama
from polyaxon_tpu.serving import ServingServer
from polyaxon_tpu.serving.speculative import generate_speculative


def _cfg():
    return dataclasses.replace(llama.CONFIGS["llama_tiny"],
                               dtype=jnp.float32)


class TestSpeculative:
    def test_lossless_vs_plain_greedy(self):
        """Self-draft (full acceptance) AND an independent random draft
        (low acceptance) both reproduce plain greedy exactly — the
        defining property of the scheme."""
        cfg = _cfg()
        params = llama.init(cfg, jax.random.key(0))["params"]
        indep = llama.init(cfg, jax.random.key(7))["params"]
        prompt = jax.random.randint(jax.random.key(1), (2, 9), 0,
                                    cfg.vocab_size)
        want = np.asarray(llama.generate(cfg, params, prompt,
                                         max_new_tokens=12))
        for draft_params, label in ((params, "self"), (indep, "indep")):
            got = np.asarray(generate_speculative(
                cfg, params, cfg, draft_params, prompt,
                max_new_tokens=12, k=4))
            np.testing.assert_array_equal(got, want, err_msg=label)

    @pytest.mark.parametrize("k", [1, 3, 7])
    def test_k_never_changes_output(self, k):
        cfg = _cfg()
        params = llama.init(cfg, jax.random.key(0))["params"]
        draft = llama.init(cfg, jax.random.key(3))["params"]
        prompt = jnp.asarray([[5, 6, 7, 8, 9]], jnp.int32)
        want = np.asarray(llama.generate(cfg, params, prompt,
                                         max_new_tokens=10))
        got = np.asarray(generate_speculative(
            cfg, params, cfg, draft, prompt, max_new_tokens=10, k=k))
        np.testing.assert_array_equal(got, want)

    def test_self_draft_accepts_everything_every_round(self):
        """A self-draft must sustain FULL acceptance across rounds:
        exactly ceil((max_new-1)/(k+1)) verify rounds. This is the
        regression guard for the draft-KV bonus-position hole — output
        stays lossless with the hole, but acceptance collapses and
        rounds balloon."""
        cfg = _cfg()
        params = llama.init(cfg, jax.random.key(0))["params"]
        prompt = jnp.asarray([[5, 6, 7, 8, 9]], jnp.int32)
        k, max_new = 4, 16
        out, rounds = generate_speculative(
            cfg, params, cfg, params, prompt, max_new_tokens=max_new,
            k=k, return_rounds=True)
        want = np.asarray(llama.generate(cfg, params, prompt,
                                         max_new_tokens=max_new))
        np.testing.assert_array_equal(np.asarray(out), want)
        assert int(rounds) == -(-(max_new - 1) // (k + 1)), int(rounds)

    def test_headroom_validated(self):
        cfg = _cfg()  # max_seq_len 128
        params = llama.init(cfg, jax.random.key(0))["params"]
        prompt = jnp.zeros((1, 100), jnp.int32)
        with pytest.raises(ValueError, match="max_seq_len"):
            generate_speculative(cfg, params, cfg, params, prompt,
                                 max_new_tokens=30, k=4)

    def test_sliding_window_refused_in_chunk(self):
        cfg = dataclasses.replace(_cfg(), sliding_window=8)
        params = llama.init(cfg, jax.random.key(0))["params"]
        cache = {"k": jnp.zeros((2, 1, 32, 2, 16)),
                 "v": jnp.zeros((2, 1, 32, 2, 16))}
        with pytest.raises(ValueError, match="sliding_window"):
            llama.decode_chunk(cfg, params, cache,
                               jnp.zeros((1, 3), jnp.int32),
                               jnp.zeros((1,), jnp.int32))


class TestSpeculativeServing:
    def test_http_greedy_matches_undrafted_server(self):
        """plx serve --draft-model end-to-end: greedy responses equal a
        draft-less server's; sampled requests fall back and still work."""
        def gen(url, payload):
            req = urllib.request.Request(
                url + "/v1/generate", method="POST",
                data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"})
            return json.load(urllib.request.urlopen(req, timeout=300))

        greedy = {"tokens": [[5, 6, 7], [1, 2, 3]], "max_new_tokens": 8}
        sampled = {"tokens": [[5, 6, 7]], "max_new_tokens": 8,
                   "temperature": 0.9, "seed": 3}
        with ServingServer("llama_tiny", seed=0) as plain:
            want = gen(plain.url, greedy)
            want_sampled = gen(plain.url, sampled)
        with ServingServer("llama_tiny", seed=0, draft_model="llama_tiny",
                           spec_k=3) as spec:
            got = gen(spec.url, greedy)
            got_sampled = gen(spec.url, sampled)
        assert got["tokens"] == want["tokens"]
        # Sampled path bypasses speculation but stays bit-stable.
        assert got_sampled["tokens"] == want_sampled["tokens"]

    def test_draft_requires_static_engine(self):
        with pytest.raises(ValueError, match="static"):
            ServingServer("llama_tiny", batching="continuous",
                          draft_model="llama_tiny")

    def test_t5_target_refused(self):
        with pytest.raises(ValueError, match="decode_chunk"):
            ServingServer("t5_tiny", draft_model="t5_tiny")


class TestMoESpeculative:
    def test_moe_target_lossless(self):
        """Mixtral-style target: per-token top-k routing with no-drop
        capacity makes the chunked verify group-size-independent, so
        speculation stays lossless for MoE targets too — with a dense
        llama draft (the realistic pairing) and a self-draft."""
        from polyaxon_tpu.models import moe

        cfg = dataclasses.replace(moe.CONFIGS["moe_tiny"],
                                  dtype=jnp.float32)
        params = moe.init(cfg, jax.random.key(0))["params"]
        lcfg = _cfg()
        lparams = llama.init(lcfg, jax.random.key(5))["params"]
        prompt = jax.random.randint(jax.random.key(1), (2, 7), 0,
                                    min(cfg.vocab_size, lcfg.vocab_size))
        want = np.asarray(moe.generate(cfg, params, prompt,
                                       max_new_tokens=10))
        got_self = np.asarray(generate_speculative(
            cfg, params, cfg, params, prompt, max_new_tokens=10, k=3,
            family=moe, draft_family=moe))
        np.testing.assert_array_equal(got_self, want)
        got_llama_draft = np.asarray(generate_speculative(
            cfg, params, lcfg, lparams, prompt, max_new_tokens=10, k=3,
            family=moe, draft_family=llama))
        np.testing.assert_array_equal(got_llama_draft, want)

    def test_moe_serving_with_draft(self):
        with ServingServer("moe_tiny", seed=0, draft_model="llama_tiny",
                           spec_k=2) as s:
            req = urllib.request.Request(
                s.url + "/v1/generate", method="POST",
                data=json.dumps({"tokens": [[5, 6, 7]],
                                 "max_new_tokens": 6}).encode(),
                headers={"Content-Type": "application/json"})
            out = json.load(urllib.request.urlopen(req, timeout=300))
        with ServingServer("moe_tiny", seed=0) as plain:
            req = urllib.request.Request(
                plain.url + "/v1/generate", method="POST",
                data=json.dumps({"tokens": [[5, 6, 7]],
                                 "max_new_tokens": 6}).encode(),
                headers={"Content-Type": "application/json"})
            want = json.load(urllib.request.urlopen(req, timeout=300))
        assert out["tokens"] == want["tokens"]


class TestDraftVocab:
    def test_vocab_mismatch_refused_at_startup(self):
        # llama3_draft_200m carries the 128k llama-3 vocab; llama_tiny
        # is 256 — serving must refuse the pairing loudly.
        with pytest.raises(ValueError, match="token space"):
            ServingServer("llama_tiny", draft_model="llama3_draft_200m")


class TestSpeculativeEdges:
    def test_max_new_one(self):
        """Budget of 1: the prefill's own argmax is the whole output —
        the while_loop body must never need to run."""
        cfg = _cfg()
        params = llama.init(cfg, jax.random.key(0))["params"]
        prompt = jnp.asarray([[5, 6, 7]], jnp.int32)
        want = np.asarray(llama.generate(cfg, params, prompt,
                                         max_new_tokens=1))
        got = np.asarray(generate_speculative(
            cfg, params, cfg, params, prompt, max_new_tokens=1, k=4))
        np.testing.assert_array_equal(got, want)
