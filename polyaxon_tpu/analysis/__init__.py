"""polycheck: the repo-native static-analysis gate (ISSUE 9).

``python -m polyaxon_tpu.analysis --check`` runs three AST rule
families over ``polyaxon_tpu/**`` and fails CI on any finding that is
neither pragma'd at the site (``# polycheck: ignore[rule] -- why``)
nor in the committed ``analysis/baseline.json`` (which only shrinks):

- concurrency  — lock-order inversions, locks held across blocking
  I/O, self-deadlocks; plus an opt-in RUNTIME lockdep shim
  (``analysis.lockdep``) that records real acquisition orders during
  the chaos/sim drills and fails on observed cycles.
- hotpath      — host syncs in jitted/step code, unseeded randomness
  and wall-clock reads in resume-relevant ``runtime/`` paths, python
  branches on tracers.
- invariants   — silent ``except Exception: pass`` swallows,
  un-cataloged metric emissions, unbatched multi-write store
  sequences, daemon threads nothing drains.

See docs/static-analysis.md for the rule catalog and pragma/baseline
semantics.
"""

from polyaxon_tpu.analysis.core import (  # noqa: F401
    ALL_RULES,
    BASELINE_PATH,
    Finding,
    RULE_FAMILIES,
    SourceFile,
    analyze,
    check,
    load_sources,
    rule_family,
)
from polyaxon_tpu.analysis import lockdep  # noqa: F401
