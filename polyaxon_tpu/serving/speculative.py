"""Speculative decoding: draft-model proposals verified by the target
in one chunked forward — lossless for greedy decoding (the output is
PROVABLY the target's own greedy sequence; tests assert token
equality), with the target's sequential decode steps replaced by one
``decode_chunk`` per accepted run.

TPU-first mechanics:
- the whole draft→verify→accept loop runs inside ONE ``lax.while_loop``
  under jit — no host round-trips between rounds;
- full-length caches (slot == position) make acceptance rollback-free:
  entries written for rejected candidates sit at positions the next
  round rewrites before anything attends them (``decode_chunk``
  docstring has the invariant);
- per-row positions/acceptance are vectors, so a batch of rows at
  different depths shares the compiled program (same ragged philosophy
  as the continuous engine).

The reference orchestrator has no serving math at all (SURVEY.md §2);
the algorithm is the standard greedy speculative scheme (Leviathan et
al. / Chen et al., public), implemented against this repo's own cache
contracts.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def generate_speculative(
    cfg,
    params,
    draft_cfg,
    draft_params,
    prompt: jax.Array,  # [B, P] int32
    *,
    max_new_tokens: int,
    k: int = 4,
    family=None,
    draft_family=None,
    return_rounds: bool = False,
):
    """Greedy generation of ``max_new_tokens`` per row, draft-accelerated.

    Returns [B, max_new_tokens] int32 — bit-identical to
    ``family.generate(..., temperature=0)``. ``k`` = draft tokens per
    round; each round emits between 1 (no proposals accepted: the
    target's own token) and k+1 (all accepted + bonus) tokens.
    ``return_rounds``: also return the number of verify rounds (the
    efficiency observable — self-draft at high acceptance needs
    ~max_new/(k+1) rounds).

    Rows that finish early still ride along until the deepest row is
    done — the same cost shape as the plain path's fixed-length
    ``lax.scan``, not an added inefficiency.
    """
    from polyaxon_tpu.models import llama

    family = family or llama
    draft_family = draft_family or llama
    B, P = prompt.shape
    max_new = int(max_new_tokens)
    # Full-length caches with verify headroom: positions reach at most
    # P + max_new + k.
    max_len = P + max_new + k + 1
    if max_len > cfg.max_seq_len or max_len > draft_cfg.max_seq_len:
        raise ValueError(
            f"prompt {P} + max_new {max_new} + draft window {k}+1 "
            f"exceeds max_seq_len (target {cfg.max_seq_len}, draft "
            f"{draft_cfg.max_seq_len})")

    logits_t, cache_t = family.prefill(cfg, params, prompt, max_len)
    t0 = jnp.argmax(logits_t, axis=-1).astype(jnp.int32)  # token @ pos P
    _, cache_d = draft_family.prefill(draft_cfg, draft_params, prompt,
                                      max_len)

    rows = jnp.arange(B)
    width = max_new + k + 2  # + trash column for masked writes
    trash = width - 1
    out = jnp.zeros((B, width), jnp.int32).at[:, 0].set(t0)
    n0 = jnp.ones((B,), jnp.int32)  # t0 already emitted
    pos0 = jnp.full((B,), P, jnp.int32)  # cur sits at position P

    def cond(state):
        return jnp.any(state[1] < max_new)

    def body(state):
        out, n, cur, pos, cache_t, cache_d, rounds = state
        live = n < max_new

        def draft_step(carry, _):
            cache_d, tok, p = carry
            lg, cache_d = draft_family.decode_step_ragged(
                draft_cfg, draft_params, cache_d, tok, p)
            nxt = jnp.argmax(lg, axis=-1).astype(jnp.int32)
            return (cache_d, nxt, p + 1), nxt

        # k+1 steps for k proposals: the extra step writes the LAST
        # proposal's draft KV (position pos+k). Without it, a fully-
        # accepted round leaves a permanent zero-KV hole there that
        # every later draft query attends — output stays lossless (the
        # target verifies) but acceptance silently collapses.
        (cache_d, _, _), d = jax.lax.scan(
            draft_step, (cache_d, cur, pos), None, length=k + 1)
        d = d.T[:, :k]  # [B, k] proposals for positions pos+1..pos+k

        chunk = jnp.concatenate([cur[:, None], d], axis=1)  # [B, k+1]
        logits, cache_t = family.decode_chunk(cfg, params, cache_t,
                                              chunk, pos)
        t = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, k+1]
        # Leading proposals the target agrees with; emit those plus the
        # target's own token at the first disagreement (the "bonus").
        match = (d == t[:, :k]).astype(jnp.int32)
        a = jnp.cumprod(match, axis=1).sum(axis=1)  # [B] in 0..k
        emit = jnp.minimum(a + 1, max_new - n)  # capped at the budget
        emit = jnp.where(live, emit, 0)

        idx = jnp.arange(k + 1)[None, :]
        col = jnp.where(idx < emit[:, None], n[:, None] + idx, trash)
        out = out.at[rows[:, None], col].set(t)
        cur = jnp.where(live, t[rows, jnp.maximum(emit - 1, 0)], cur)
        n = n + emit
        pos = pos + emit
        return out, n, cur, pos, cache_t, cache_d, rounds + 1

    out, _, _, _, _, _, rounds = jax.lax.while_loop(
        cond, body,
        (out, n0, t0, pos0, cache_t, cache_d, jnp.int32(0)))
    if return_rounds:
        return out[:, :max_new], rounds
    return out[:, :max_new]


# --------------------------------------------------------------- policy
class LaneView:
    """What the speculation policy sees each decode-lane tick: queue +
    prefill-lane pressure, decode-lane headroom, and how long the
    oldest waiting request has been burning its TTFT budget. Built by
    the engine; plain data so the policy is testable without jax."""

    __slots__ = ("prefill_backlog", "decode_free", "oldest_wait")

    def __init__(self, prefill_backlog: int = 0, decode_free: int = 0,
                 oldest_wait: float = 0.0):
        self.prefill_backlog = int(prefill_backlog)
        self.decode_free = int(decode_free)
        self.oldest_wait = float(oldest_wait)

    def __repr__(self):  # pragma: no cover - debug aid
        return (f"LaneView(prefill_backlog={self.prefill_backlog}, "
                f"decode_free={self.decode_free}, "
                f"oldest_wait={self.oldest_wait:.3f})")


class SpeculationPolicy:
    """Speculation as a scheduler output, not a static flag.

    Greedy speculative decoding is lossless for ANY draft length — the
    target verifies every proposal — so the policy is free to retune
    ``k`` per tick purely on throughput/latency grounds:

    state       | condition                                 | draft len
    ----------- | ----------------------------------------- | ---------
    speculate   | decode lane has idle headroom, no backlog  | k_max
    throttled   | prefill backlog > 0 or decode lane full    | k_max - backlog (>= k_min)
    off         | oldest wait > ttft_budget, or backlog >=   | 0
                | off_backlog (TTFT budget burning)          |

    Rationale: each extra draft token is speculative compute the decode
    tick must verify; under prefill pressure that compute competes with
    the chunk programs that bound TTFT, so the draft shrinks first and
    disappears entirely once the backlog is burning the TTFT budget.
    ``state`` after a ``draft_len`` call names the branch taken (the
    tests drive the machine through all three).
    """

    STATES = ("speculate", "throttled", "off")

    def __init__(self, k_max: int, *, k_min: int = 1, off_backlog: int = 4,
                 ttft_budget: float = 0.5):
        if k_max < 1:
            raise ValueError(f"k_max must be >= 1, got {k_max}")
        if not 1 <= k_min <= k_max:
            raise ValueError(
                f"need 1 <= k_min <= k_max, got k_min={k_min} "
                f"k_max={k_max}")
        if off_backlog < 1:
            raise ValueError(
                f"off_backlog must be >= 1, got {off_backlog}")
        if ttft_budget <= 0:
            raise ValueError(
                f"ttft_budget must be > 0, got {ttft_budget}")
        self.k_max = int(k_max)
        self.k_min = int(k_min)
        self.off_backlog = int(off_backlog)
        self.ttft_budget = float(ttft_budget)
        self.state = "speculate"

    def draft_len(self, view: LaneView) -> int:
        """Draft tokens the next decode tick should propose (0 = run a
        plain decode step)."""
        if (view.oldest_wait > self.ttft_budget
                or view.prefill_backlog >= self.off_backlog):
            self.state = "off"
            return 0
        if view.prefill_backlog > 0 or view.decode_free == 0:
            self.state = "throttled"
            return max(self.k_min, self.k_max - view.prefill_backlog)
        self.state = "speculate"
        return self.k_max
