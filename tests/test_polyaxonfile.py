"""Polyaxonfile reader tests: loading, kind detection, presets/patching,
interpolation — the [B] acceptance bar ("run unchanged after swapping the
environment preset from gpu to tpu") is asserted directly here."""

import os

import pytest

from polyaxon_tpu.polyaxonfile import (
    PolyaxonfileError,
    apply_presets,
    check_polyaxonfile,
    patch_dict,
    render_value,
    resolve_operation_context,
    spec_kind,
)

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")


def fixture(name: str) -> str:
    return os.path.join(FIXTURES, name)


class TestLoading:
    def test_all_baseline_configs_parse(self):
        """The five BASELINE.json configs must parse and round-trip."""
        for name in (
            "mnist.yaml",
            "resnet_tfjob.yaml",
            "bert_pytorchjob.yaml",
            "llama3_8b.yaml",
            "hyperband_vit.yaml",
        ):
            op = check_polyaxonfile(fixture(name))
            assert op.component is not None
            round_tripped = check_polyaxonfile(op.to_dict())
            assert round_tripped.to_dict() == op.to_dict()

    def test_all_shipped_examples_parse(self):
        """Every Polyaxonfile under examples/ must validate (deploy.yaml
        is a deploy-values file, validated by test_deploy)."""
        examples = os.path.join(os.path.dirname(FIXTURES), "..", "examples")
        names = [n for n in sorted(os.listdir(examples))
                 if n.endswith(".yaml") and n != "deploy.yaml"]
        assert len(names) >= 7
        for name in names:
            op = check_polyaxonfile(os.path.join(examples, name))
            assert op.component is not None, name

    def test_kind_detection(self):
        assert spec_kind({"kind": "component", "run": {}}) == "component"
        assert spec_kind({"run": {}}) == "component"
        assert spec_kind({"hubRef": "x"}) == "operation"
        with pytest.raises(PolyaxonfileError):
            spec_kind({"foo": 1})

    def test_component_becomes_operation(self):
        op = check_polyaxonfile(fixture("mnist.yaml"))
        assert op.kind == "operation"
        assert op.component.name == "mnist-quickstart"
        assert op.component.run_kind == "jaxjob"

    def test_cli_params_override(self):
        op = check_polyaxonfile(fixture("mnist.yaml"), params={"lr": 0.01})
        assert op.params["lr"].value == 0.01

    def test_unknown_param_rejected(self):
        with pytest.raises(ValueError):
            check_polyaxonfile(fixture("mnist.yaml"), params={"nope": 1})


class TestPatch:
    def test_post_merge(self):
        base = {"a": {"x": 1, "y": 2}, "keep": True, "lst": [1, 2]}
        patch = {"a": {"y": 3, "z": 4}, "lst": [9]}
        out = patch_dict(base, patch, "post_merge")
        assert out == {"a": {"x": 1, "y": 3, "z": 4}, "keep": True, "lst": [9]}

    def test_pre_merge(self):
        out = patch_dict({"a": {"y": 2}}, {"a": {"y": 3, "z": 4}}, "pre_merge")
        assert out == {"a": {"y": 2, "z": 4}}

    def test_isnull(self):
        out = patch_dict({"a": None, "b": 1}, {"a": 5, "b": 9}, "isnull")
        assert out == {"a": 5, "b": 1}

    def test_replace(self):
        out = patch_dict({"a": {"deep": 1}}, {"a": {"flat": 2}}, "replace")
        assert out == {"a": {"flat": 2}}


class TestPresets:
    def test_gpu_to_tpu_preset_swap(self):
        """[B] acceptance: same Polyaxonfile, swap preset gpu→tpu."""
        op_gpu = check_polyaxonfile(fixture("mnist.yaml"), presets=[fixture("presets/gpu.yaml")])
        env = op_gpu.run_patch["environment"]
        assert "gke-accelerator" in str(env.get("nodeSelector", {}))

        op_tpu = check_polyaxonfile(fixture("mnist.yaml"), presets=[fixture("presets/tpu.yaml")])
        env = op_tpu.run_patch["environment"]
        assert env["tpu"]["accelerator"] == "v5e"
        assert env["tpu"]["topology"] == "2x4"
        # The underlying component spec is untouched — only the patch differs.
        assert op_tpu.component.to_dict() == op_gpu.component.to_dict()

    def test_presets_apply_in_order(self):
        op = check_polyaxonfile(
            fixture("mnist.yaml"),
            presets=[fixture("presets/gpu.yaml"), fixture("presets/tpu.yaml")],
        )
        env = op.run_patch["environment"]
        assert env["tpu"]["accelerator"] == "v5e"


class TestInterpolation:
    def test_render_preserves_types(self):
        ctx = {"params": {"lr": 0.1, "steps": 10, "name": "x"}}
        assert render_value("{{ params.lr }}", ctx) == 0.1
        assert render_value("{{ params.steps }}", ctx) == 10
        assert render_value("lr={{ params.lr }}", ctx) == "lr=0.1"
        assert render_value(["--lr", "{{ params.lr }}"], ctx) == ["--lr", 0.1]

    def test_resolve_operation(self):
        op = check_polyaxonfile(fixture("llama3_8b.yaml"))
        resolved = resolve_operation_context(
            op, run_uuid="abc", project_name="llm", artifacts_root="/tmp/store"
        )
        runtime = resolved.component.run.runtime
        assert runtime["learning_rate"] == 0.0003
        assert runtime["seq_len"] == 8192

    def test_globals_paths(self):
        op = check_polyaxonfile(
            {
                "kind": "component",
                "run": {
                    "kind": "job",
                    "container": {
                        "image": "busybox",
                        "command": ["echo", "{{ globals.run_outputs_path }}"],
                    },
                },
            }
        )
        resolved = resolve_operation_context(op, run_uuid="u1", artifacts_root="/store")
        assert resolved.component.run.container.command[1] == "/store/u1/outputs"

    def test_strict_undefined_raises(self):
        from polyaxon_tpu.polyaxonfile import ContextError

        with pytest.raises(ContextError):
            render_value("{{ params.missing }}", {"params": {}})
