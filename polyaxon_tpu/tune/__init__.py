from polyaxon_tpu.tune.base import (
    GridSearchManager,
    MappingManager,
    Observation,
    RandomSearchManager,
    top_k,
)
from polyaxon_tpu.tune.bayes import BayesManager, GaussianProcess, acquisition
from polyaxon_tpu.tune.hyperband import HyperbandManager, Rung

__all__ = [
    "BayesManager",
    "GaussianProcess",
    "GridSearchManager",
    "HyperbandManager",
    "MappingManager",
    "Observation",
    "RandomSearchManager",
    "Rung",
    "acquisition",
    "top_k",
]
