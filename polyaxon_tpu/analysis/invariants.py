"""Repo-invariant analyzers.

- ``invariant-swallow`` — ``except Exception: pass`` (or bare
  ``except``) whose handler does NOTHING: no log, no flight-recorder
  note, no re-raise, no fallback assignment. The chaos harness proved
  these hide real faults; a swallow must at least leave a debug line
  or a flight note so the postmortem can see it.
- ``invariant-metric-catalog`` — a metric emitted by literal name
  (``registry.counter("...")`` / ``.gauge`` / ``.histogram``) that is
  not in ``obs.metrics.catalog_metric_names()``. An un-cataloged name
  is invisible to the alert-rule schema gate: a rule against it would
  validate as a typo and an alert on it could never be written.
- ``invariant-store-batch`` — a function that performs 2+ control-plane
  store writes with no ``transaction()`` in sight (neither lexically
  nor via a same-module caller that wraps it): each write pays its own
  WAL fsync and a crash between them leaves partial state. Single
  writes are fine — they are atomic on their own.
- ``invariant-daemon-drain`` — a ``threading.Thread(daemon=True)``
  that nothing ever joins: on interpreter exit the thread is killed
  mid-operation (half-written file, dropped queue item). Every daemon
  needs a drain path (``stop()``+``join``) or a reasoned pragma.
"""

from __future__ import annotations

import ast
from typing import Optional

from polyaxon_tpu.analysis.core import Finding, SourceFile, register

STORE_MUTATORS = frozenset({
    "transition", "update_run", "create_run", "add_condition",
    "create_project", "upsert_queue", "set_quota", "delete_queue",
    "delete_quota",
})
METRICS_FILE = "polyaxon_tpu/obs/metrics.py"  # defines the catalog itself
_LOG_HINTS = ("log", "warn", "error", "debug", "info", "exception",
              "note", "print", "add_event", "record", "inc", "observe")


def _dotted(node: ast.AST) -> str:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    else:
        return ""
    parts.reverse()
    return ".".join(parts)


def _iter_functions(sf: SourceFile):
    def walk(body, prefix):
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield f"{prefix}{node.name}", node
                yield from walk(node.body, f"{prefix}{node.name}.")
            elif isinstance(node, ast.ClassDef):
                yield from walk(node.body, f"{node.name}.")

    yield from walk(sf.tree.body, "")


# ---------------------------------------------------------------- swallow
def _is_broad(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True
    names = []
    t = handler.type
    if isinstance(t, ast.Tuple):
        names = [_dotted(e) for e in t.elts]
    else:
        names = [_dotted(t)]
    return any(n.rsplit(".", 1)[-1] in ("Exception", "BaseException")
               for n in names)


def _handler_acts(handler: ast.ExceptHandler) -> bool:
    """Does the handler DO anything observable? A log/flight/metric
    call, a raise, a return/assignment fallback, setting state — all
    count. Only `pass` (and docstring-style constants) does not."""
    for stmt in handler.body:
        if isinstance(stmt, ast.Pass):
            continue
        if isinstance(stmt, ast.Expr) and \
                isinstance(stmt.value, ast.Constant):
            continue  # bare string/Ellipsis, still silent
        if isinstance(stmt, ast.Continue):
            continue  # loop skip with no trace is still a swallow
        return True
    return False


@register
def analyze_swallow(files: list[SourceFile]) -> list[Finding]:
    findings = []
    for sf in files:
        for qualname, fn in _iter_functions(sf):
            for node in (n for stmt in fn.body for n in ast.walk(stmt)):
                if not isinstance(node, ast.ExceptHandler):
                    continue
                if not _is_broad(node) or _handler_acts(node):
                    continue
                f = sf.finding(
                    "invariant-swallow", node.lineno,
                    "broad except swallows the error with no trace: "
                    "log at debug, leave a flight-recorder note, or "
                    "pragma with the reason the silence is safe",
                    qualname=qualname)
                if f:
                    findings.append(f)
        # module-level try/except too
        for node in sf.tree.body:
            if isinstance(node, ast.Try):
                for handler in node.handlers:
                    if _is_broad(handler) and not _handler_acts(handler):
                        f = sf.finding(
                            "invariant-swallow", handler.lineno,
                            "broad except swallows the error with no "
                            "trace at module scope", qualname="<module>")
                        if f:
                            findings.append(f)
    return findings


# ---------------------------------------------------------- metric catalog
def _catalog() -> set[str]:
    from polyaxon_tpu.obs.metrics import catalog_metric_names

    return catalog_metric_names()


@register
def analyze_metric_catalog(files: list[SourceFile]) -> list[Finding]:
    findings = []
    vocabulary: Optional[set[str]] = None
    for sf in files:
        if sf.path == METRICS_FILE:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call) or \
                    not isinstance(node.func, ast.Attribute):
                continue
            if node.func.attr not in ("counter", "gauge", "histogram"):
                continue
            if not node.args or not isinstance(node.args[0], ast.Constant) \
                    or not isinstance(node.args[0].value, str):
                continue
            recv = _dotted(node.func.value)
            tail = recv.rsplit(".", 1)[-1].lower() if recv else ""
            if "registry" not in tail and tail != "metrics" and \
                    not recv.endswith("REGISTRY"):
                continue
            name = node.args[0].value
            if vocabulary is None:
                vocabulary = _catalog()
            if name in vocabulary:
                continue
            f = sf.finding(
                "invariant-metric-catalog", node.lineno,
                f"metric {name!r} is not in catalog_metric_names(): "
                "alert rules cannot reference it (the obs-rules schema "
                "gate validates against the catalog). Add it to the "
                "obs.metrics catalog/SCRAPE_TIME_METRICS",
                qualname="")
            if f:
                findings.append(f)
    return findings


# ------------------------------------------------------------- store batch
def _store_method(call: ast.Call) -> Optional[str]:
    if not isinstance(call.func, ast.Attribute):
        return None
    recv = _dotted(call.func.value)
    last = recv.rsplit(".", 1)[-1] if recv else ""
    if last == "store":
        return call.func.attr
    return None


class _StoreScan(ast.NodeVisitor):
    def __init__(self):
        self.mutations: list[int] = []
        self.txn_lines: list[int] = []
        self.in_txn_depth = 0
        self.mutations_outside_txn: list[int] = []
        self.calls: set[str] = set()

    def visit_With(self, node: ast.With):
        is_txn = any(
            isinstance(i.context_expr, ast.Call)
            and _store_method(i.context_expr) == "transaction"
            for i in node.items)
        if is_txn:
            self.txn_lines.append(node.lineno)
            self.in_txn_depth += 1
        self.generic_visit(node)
        if is_txn:
            self.in_txn_depth -= 1

    def visit_Call(self, node: ast.Call):
        method = _store_method(node)
        if method in STORE_MUTATORS:
            self.mutations.append(node.lineno)
            if not self.in_txn_depth:
                self.mutations_outside_txn.append(node.lineno)
        if isinstance(node.func, ast.Name):
            self.calls.add(node.func.id)
        elif isinstance(node.func, ast.Attribute) and \
                isinstance(node.func.value, ast.Name):
            base = node.func.value.id
            self.calls.add(f"{node.func.attr}" if base in ("self", "cls")
                           else f"{base}.{node.func.attr}")
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        pass  # nested defs analyzed separately

    def visit_AsyncFunctionDef(self, node):
        pass


@register
def analyze_store_batch(files: list[SourceFile]) -> list[Finding]:
    findings = []
    for sf in files:
        if sf.path == "polyaxon_tpu/controlplane/store.py":
            continue  # the store's own internals ARE the batching layer
        scans: dict[str, _StoreScan] = {}
        nodes: dict[str, ast.AST] = {}
        for qualname, fn in _iter_functions(sf):
            scan = _StoreScan()
            for stmt in fn.body:
                scan.visit(stmt)
            scans[qualname] = scan
            nodes[qualname] = fn
        # Functions (by trailing name) called from inside a transaction
        # block somewhere in this module are covered by that batch.
        covered: set[str] = set()
        for qualname, scan in scans.items():
            if scan.txn_lines:
                covered |= {c.rsplit(".", 1)[-1] for c in scan.calls}
        # ...transitively: callees of covered functions are covered too.
        changed = True
        while changed:
            changed = False
            for qualname, scan in scans.items():
                if qualname.rsplit(".", 1)[-1] in covered:
                    fresh = {c.rsplit(".", 1)[-1] for c in scan.calls}
                    if not fresh <= covered:
                        covered |= fresh
                        changed = True
        for qualname, scan in scans.items():
            if len(scan.mutations_outside_txn) < 2:
                continue
            if qualname.rsplit(".", 1)[-1] in covered:
                continue
            f = sf.finding(
                "invariant-store-batch", scan.mutations_outside_txn[0],
                f"{len(scan.mutations_outside_txn)} store writes in one "
                "function with no transaction(): each pays its own WAL "
                "fsync and a crash between them leaves partial state — "
                "wrap the sequence in `with store.transaction():`",
                qualname=qualname)
            if f:
                findings.append(f)
    return findings


# ------------------------------------------------------------ daemon drain
@register
def analyze_daemon_drain(files: list[SourceFile]) -> list[Finding]:
    findings = []
    for sf in files:
        has_join_on: set[str] = set()
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "join":
                recv = _dotted(node.func.value)
                if recv:
                    has_join_on.add(recv.rsplit(".", 1)[-1])
                    has_join_on.add(recv)
        for qualname, fn in _iter_functions(sf):
            for node in (n for stmt in fn.body for n in ast.walk(stmt)):
                if not isinstance(node, ast.Call):
                    continue
                name = _dotted(node.func)
                if name.rsplit(".", 1)[-1] != "Thread":
                    continue
                daemon = any(kw.arg == "daemon" and
                             isinstance(kw.value, ast.Constant) and
                             kw.value.value is True
                             for kw in node.keywords)
                if not daemon:
                    continue
                target = _assign_target_for(sf, node)
                if target is not None and (
                        target in has_join_on or
                        target.rsplit(".", 1)[-1] in has_join_on):
                    continue
                f = sf.finding(
                    "invariant-daemon-drain", node.lineno,
                    "daemon thread with no join anywhere in the module: "
                    "interpreter exit kills it mid-operation. Add a "
                    "drain path (stop()+join, or register close on the "
                    "ExitStack) or pragma the reason it is safe to kill",
                    qualname=qualname)
                if f:
                    findings.append(f)
    return findings


def _assign_target_for(sf: SourceFile, call: ast.Call) -> Optional[str]:
    """The name a Thread(...) result is bound to, if any (searched by
    position: the Assign whose value contains this call)."""
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Assign):
            for sub in ast.walk(node.value):
                if sub is call:
                    target = node.targets[0]
                    return _dotted(target) or None
    return None
