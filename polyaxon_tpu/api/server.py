"""REST API server over the embedded control plane.

The reference's control plane is a Django/DRF service ("haupt",
SURVEY.md §2) exposing ``/api/v1/{owner}/{project}/runs...`` plus a
streams service for logs/events. Django is not available in this
environment (SURVEY.md §7 [E]) and a TPU-cluster control plane doesn't
need an ORM stack — this server maps the same REST surface onto the
embedded ``ControlPlane`` with stdlib ``ThreadingHTTPServer``:

    POST /api/v1/{owner}/{project}/runs              submit operation
    GET  /api/v1/{owner}/{project}/runs              list (status=, pipeline=)
    GET  /api/v1/{owner}/{project}/runs/{uuid}       run detail
    POST /api/v1/{owner}/{project}/runs/{uuid}/stop|restart|resume
    GET  .../statuses | metrics | outputs | artifacts[/{path}]
    GET  /streams/v1/{owner}/{project}/runs/{uuid}/logs[?follow=true]  (SSE)
    GET  /healthz | /api/v1/version | /api/v1/projects

Authentication (SURVEY.md §2 "API server": haupt's owner/user model,
scaled to haupt-CE scope): ``ApiServer(auth_token=...)`` turns on
bearer-token auth — the shared secret grants admin access to every
owner; ``owner_tokens={"alice": "tk"}`` adds per-owner tokens that can
only read/mutate runs under their own ``{owner}`` path segment (and
only runs stamped with that owner at submit). Without either, the
server stays open (embedded single-user default; the ``owner`` path
segment is then accepted for upstream URL compatibility and ignored).
"""

from __future__ import annotations

import hmac
import json
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

from polyaxon_tpu import __version__
from polyaxon_tpu.controlplane.service import ControlPlane
from polyaxon_tpu.controlplane.store import RunRecord


def _record_json(record: RunRecord) -> dict[str, Any]:
    return {
        "uuid": record.uuid,
        "name": record.name,
        "project": record.project,
        "kind": record.kind,
        "status": record.status.value,
        "created_at": record.created_at,
        "finished_at": record.finished_at,
        "params": record.params,
        "tags": record.tags,
        "meta": record.meta,
        "pipeline_uuid": record.pipeline_uuid,
        "parent_uuid": record.parent_uuid,
        "retries": record.retries,
    }


class ApiError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status
        self.message = message


class _Handler(BaseHTTPRequestHandler):
    plane: ControlPlane  # injected by ApiServer via class attribute
    auth_token: Optional[str] = None  # admin shared secret (None = open)
    owner_tokens: dict[str, str] = {}  # owner -> per-owner token
    protocol_version = "HTTP/1.1"

    # -- auth --------------------------------------------------------------
    @property
    def _auth_enabled(self) -> bool:
        return bool(self.auth_token or self.owner_tokens)

    # Derived stream tokens (ADVICE r4 #3): ?token= URLs land in
    # reverse-proxy logs, browser history, and Referer headers, so the
    # header-less routes (SSE/EventSource, <img> artifact loads) should
    # never carry a long-lived primary secret. /api/v1/stream-token
    # (header-auth) mints an HMAC-derived credential with a short TTL;
    # the dashboard uses those in URLs and only ever sends the primary
    # in an Authorization header. Primary tokens are still accepted in
    # the query for curl-style use — the mint is the browser fix, not a
    # protocol break.
    STREAM_TOKEN_TTL = 300

    def _stream_key(self, caller: str) -> Optional[str]:
        return (self.auth_token if caller == "*"
                else self.owner_tokens.get(caller))

    def _mint_stream_token(self, caller: str) -> str:
        key = self._stream_key(caller)
        if not key:
            raise ApiError(400, "no primary token to derive from")
        exp = int(time.time()) + self.STREAM_TOKEN_TTL
        msg = f"st:{caller}:{exp}"
        sig = hmac.new(key.encode(), msg.encode(), "sha256").hexdigest()
        return f"{msg}:{sig}"

    def _verify_stream_token(self, raw: str) -> str:
        parts = raw.split(":")
        # st:{caller}:{exp}:{sig} — caller may itself contain ':'.
        caller, exp_s, sig = ":".join(parts[1:-2]), parts[-2], parts[-1]
        key = self._stream_key(caller)
        if not key or not exp_s.isdigit():
            raise ApiError(401, "invalid token")
        msg = f"st:{caller}:{exp_s}"
        want = hmac.new(key.encode(), msg.encode(), "sha256").hexdigest()
        if not hmac.compare_digest(sig.encode(), want.encode()):
            raise ApiError(401, "invalid token")
        if int(exp_s) < time.time():
            raise ApiError(401, "stream token expired")
        return caller

    def _caller(self, query_token: Optional[str] = None) -> Optional[str]:
        """``"*"`` for the admin secret, the owner name for a per-owner
        token, ``None`` for no credentials. Unknown tokens are 401 —
        constant-time compares so the check can't leak secret prefixes.
        ``query_token``: header-less fallback used by the SSE log route
        ONLY — the browser EventSource API cannot set headers.
        """
        if not self._auth_enabled:
            return "*"  # open server: any credentials are ignored
        header = self.headers.get("Authorization", "")
        if not header.startswith("Bearer "):
            if not query_token:
                return None
            if query_token.startswith("st:") and query_token.count(":") >= 3:
                # A primary token may itself look like a stream token
                # ("st:"-prefixed with colons); if stream verification
                # rejects, fall through to the primary comparison below
                # instead of locking that credential out of the
                # header-less routes (ADVICE r5). Forged/expired stream
                # tokens still 401 there — they match no primary.
                try:
                    return self._verify_stream_token(query_token)
                except ApiError:
                    pass
            raw = query_token
        else:
            raw = header[len("Bearer "):]
        # Compare as bytes: compare_digest raises TypeError on
        # non-ASCII str (http.server decodes headers latin-1), which
        # would turn attacker-controlled input into a 500, not a 401.
        token = raw.strip().encode("utf-8", "replace")
        if self.auth_token and hmac.compare_digest(
                token, self.auth_token.encode("utf-8", "replace")):
            return "*"
        for owner, expected in self.owner_tokens.items():
            if hmac.compare_digest(token, expected.encode("utf-8", "replace")):
                return owner
        raise ApiError(401, "invalid token")

    def _require(self, caller: Optional[str], owner: Optional[str] = None,
                 admin: bool = False) -> None:
        """401 without credentials; 403 when the token's scope does not
        cover ``owner`` (or ``admin`` is required). No-op when auth is
        off."""
        if not self._auth_enabled:
            return
        if caller is None:
            raise ApiError(401, "missing bearer token")
        if caller == "*":
            return
        if admin:
            raise ApiError(403, "admin token required")
        if owner is not None and caller != owner:
            raise ApiError(
                403, f"token for owner `{caller}` cannot access "
                     f"owner `{owner}`")

    def _require_run(self, caller: Optional[str], record: RunRecord) -> None:
        """Record-level isolation: a scoped token only touches runs
        stamped with its owner at submit — path spoofing (A's run uuid
        under B's path) and pre-auth legacy runs both fall to admin."""
        if not self._auth_enabled or caller in (None, "*"):
            return
        if (record.meta or {}).get("owner") != caller:
            raise ApiError(
                403, f"run {record.uuid} is not owned by `{caller}`")

    # -- plumbing ----------------------------------------------------------
    def log_message(self, *args):  # quiet; the agent log is the log
        pass

    def _json(self, payload: Any, status: int = 200) -> None:
        body = json.dumps(payload, default=str).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _read_body(self) -> dict:
        length = int(self.headers.get("Content-Length") or 0)
        if not length:
            return {}
        try:
            return json.loads(self.rfile.read(length).decode())
        except json.JSONDecodeError as exc:
            raise ApiError(400, f"invalid JSON body: {exc}") from exc

    # -- routing -----------------------------------------------------------
    def do_GET(self):  # noqa: N802
        self._route("GET")

    def do_POST(self):  # noqa: N802
        self._route("POST")

    def _route(self, method: str) -> None:
        parsed = urllib.parse.urlparse(self.path)
        parts = [urllib.parse.unquote(p) for p in parsed.path.split("/") if p]
        query = urllib.parse.parse_qs(parsed.query)
        try:
            self._dispatch(method, parts, query)
        except ApiError as exc:
            self._json({"error": exc.message}, status=exc.status)
        except (ValueError, KeyError) as exc:
            self._json({"error": str(exc)}, status=400)
        except BrokenPipeError:
            pass
        except Exception as exc:  # pragma: no cover - last resort
            self._json({"error": f"{type(exc).__name__}: {exc}"}, status=500)

    def _get_run(self, uuid: str) -> RunRecord:
        try:
            return self.plane.get_run(uuid)
        except Exception as exc:
            raise ApiError(404, f"run {uuid} not found") from exc

    def _dispatch(self, method: str, parts: list[str], query: dict) -> None:
        # Open routes: liveness, scrape, the dashboard page itself, and
        # version. Everything that exposes run DATA authenticates.
        if parts == ["healthz"]:
            return self._json({"status": "ok"})
        if parts == ["metrics"]:
            return self._prometheus()
        if parts in ([], ["ui"]):
            return self._dashboard()
        caller = self._caller()  # may raise 401 on a bad token
        if parts[:2] == ["api", "v1"]:
            rest = parts[2:]
            if rest == ["version"]:
                return self._json({"version": __version__})
            if rest == ["stream-token"]:
                # Header auth ONLY (a stream token cannot mint another).
                self._require(caller)
                return self._json({
                    "token": self._mint_stream_token(caller),
                    "expiresIn": self.STREAM_TOKEN_TTL,
                })
            if rest == ["projects"]:
                self._require(caller, admin=True)
                return self._json(self.plane.store.list_projects())
            if rest == ["alerts"]:
                # Live alert-rule state (obs.rules): firing alerts,
                # per-rule values vs thresholds, fired/resolved history.
                # Any authenticated caller may read — alert state is how
                # tenants learn the cluster (not another tenant's data)
                # is degraded. Evaluated on read so a plane without a
                # reconciling agent still answers truthfully.
                self._require(caller)
                return self._json(self._alerts())
            if rest == ["metrics", "history"]:
                # Sampled metrics history (obs.history): the bounded
                # ring the alert engine and the telemetry oracle share.
                # Same read posture as /alerts — cluster telemetry, any
                # authenticated caller; ?name= picks a family, ?window=
                # scopes to a marked window or a trailing span, ?labels=
                # (k=v,...) picks one series.
                self._require(caller)
                return self._json(self._history(query))
            if rest and rest[0] == "queues":
                return self._queues(method, caller, rest[1:])
            if rest and rest[0] == "quotas":
                return self._quotas(method, caller, rest[1:])
            if rest == ["agent", "slices"]:
                # The C++ slice pool's operator view (empty when this
                # server runs without a slice-managing agent).
                self._require(caller, admin=True)
                manager = getattr(self, "slice_manager", None)
                return self._json(manager.stats() if manager is not None
                                  else {"slices": [], "gangs": []})
            # /{owner}/{project}/runs...
            if len(rest) >= 3 and rest[2] == "runs":
                if (caller is None and "token" in query and method == "GET"
                        and len(rest) >= 6 and rest[4] == "artifacts"):
                    # <img src>/<a href> loads cannot set headers (same
                    # constraint as EventSource): artifact-FILE reads
                    # (only) accept ?token= as the credential.
                    caller = self._caller(query_token=query["token"][0])
                self._require(caller, owner=rest[0])
                return self._runs(method, caller, rest[0], rest[1],
                                  rest[3:], query)
        if parts[:2] == ["streams", "v1"]:
            rest = parts[2:]
            # /{owner}/{project}/runs/{uuid}/logs
            if len(rest) >= 5 and rest[2] == "runs" and rest[4] == "logs":
                if caller is None and "token" in query:
                    # EventSource cannot set headers: the SSE route
                    # (only) accepts ?token= as the credential.
                    caller = self._caller(query_token=query["token"][0])
                self._require(caller, owner=rest[0])
                return self._logs(caller, rest[3], query)
        raise ApiError(404, f"no route for {method} {'/'.join(parts)}")

    # -- scheduling catalog ------------------------------------------------
    def _queues(self, method: str, caller: Optional[str],
                rest: list[str]) -> None:
        """GET /api/v1/queues            — queues + live depth/usage
           GET /api/v1/queues/{name}     — one queue + its queued runs
           POST /api/v1/queues           — create/update (admin)
           POST /api/v1/queues/{name}/delete (admin)
        Reads are open to any authenticated caller (queue depth is how
        tenants see where their run sits); writes are operator-only."""
        stats = None
        if method == "GET":
            self._require(caller)
            stats = self.plane.scheduling_stats()
            if not rest:
                return self._json(stats["queues"])
            name = rest[0]
            for queue in stats["queues"]:
                if queue["name"] == name:
                    return self._json(queue)
            raise ApiError(404, f"queue {name} not found")
        self._require(caller, admin=True)
        if not rest:
            body = self._read_body()
            name = body.get("name")
            if not name:
                raise ApiError(400, "queue body requires `name`")
            queue = self.plane.upsert_queue(
                name,
                priority=int(body.get("priority") or 0),
                concurrency=body.get("concurrency"),
                preemptible=bool(body.get("preemptible")),
                description=body.get("description") or "",
            )
            return self._json(queue, status=201)
        if len(rest) == 2 and rest[1] == "delete":
            try:
                removed = self.plane.delete_queue(rest[0])
            except ValueError as exc:
                raise ApiError(400, str(exc)) from exc
            if not removed:
                raise ApiError(404, f"queue {rest[0]} not found")
            return self._json({"deleted": rest[0]})
        raise ApiError(404, f"no queue route for {'/'.join(rest)}")

    def _quotas(self, method: str, caller: Optional[str],
                rest: list[str]) -> None:
        """GET /api/v1/quotas — per-project quota rows + usage;
           POST /api/v1/quotas — set a project quota (admin)."""
        if method == "GET":
            self._require(caller)
            stats = self.plane.scheduling_stats()
            return self._json({"quotas": stats["quotas"],
                               "projects": stats["projects"]})
        self._require(caller, admin=True)
        if not rest:
            body = self._read_body()
            project = body.get("project")
            if not project:
                raise ApiError(400, "quota body requires `project`")
            quota = self.plane.set_quota(
                project,
                max_runs=body.get("maxRuns", body.get("max_runs")),
                max_chips=body.get("maxChips", body.get("max_chips")),
                weight=float(body.get("weight") or 1.0),
            )
            return self._json(quota, status=201)
        if len(rest) == 2 and rest[1] == "delete":
            if not self.plane.delete_quota(rest[0]):
                raise ApiError(404, f"quota for {rest[0]} not found")
            return self._json({"deleted": rest[0]})
        raise ApiError(404, f"no quota route for {'/'.join(rest)}")

    def _alerts(self) -> dict:
        from polyaxon_tpu.obs import rules as obs_rules

        engine = obs_rules.default_engine()
        engine.evaluate(plane=self.plane)
        return engine.to_json()

    def _history(self, query: dict) -> dict:
        from polyaxon_tpu.obs import history as obs_history

        ring = obs_history.default_history()
        ring.sample()  # cadence-gated freshness on read
        name = (query.get("name") or [None])[0]
        window = (query.get("window") or [None])[0]
        raw = (query.get("labels") or [None])[0]
        labels = None
        if raw:
            labels = {}
            for part in raw.split(","):
                key, sep, value = part.partition("=")
                if not sep or not key.strip():
                    raise ApiError(400, f"bad labels selector {raw!r} "
                                        "(want k=v[,k2=v2])")
                labels[key.strip()] = value.strip()
        try:
            return obs_history.query_history(
                ring.to_json(), name=name, window=window, labels=labels)
        except ValueError as exc:
            raise ApiError(400, str(exc))

    def _dashboard(self) -> None:
        """Polyboard-lite (api.ui): the static runs dashboard."""
        from polyaxon_tpu.api.ui import DASHBOARD_HTML

        body = DASHBOARD_HTML.encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/html; charset=utf-8")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _prometheus(self) -> None:
        """Prometheus text exposition backed by the unified registry
        (obs.metrics — ISSUE 5; the reference's haupt exposes server
        metrics the same way, SURVEY.md §5.5).

        Scrape-time gauges rebuilt from store state here: per-lifecycle-
        phase run counts (every V1Statuses phase, zeros included) and
        queue depth/occupancy. Everything else — scheduler tick
        histograms, admission outcomes, retry/requeue counters, store
        op latency, training step time — accumulates in the registry as
        the co-located agent/runtime records it, and renders with the
        same scrape."""
        import time

        from polyaxon_tpu.lifecycle import V1Statuses
        from polyaxon_tpu.obs import metrics as obs_metrics

        registry = obs_metrics.REGISTRY
        registry.gauge("polyaxon_tpu_info", "Build info",
                       ("version",)).set(1, version=__version__)
        runs = registry.gauge(
            "polyaxon_runs", "Runs per lifecycle phase", ("status",))
        counts: dict[str, int] = {s.value: 0 for s in V1Statuses}
        for record in self.plane.list_runs():
            counts[record.status.value] = counts.get(record.status.value, 0) + 1
        for status, n in counts.items():
            runs.set(n, status=status)
        depth = registry.gauge(
            "polyaxon_queue_depth", "Queued runs per queue", ("queue",))
        running = registry.gauge(
            "polyaxon_queue_running", "Live runs per queue", ("queue",))
        depth.clear()
        running.clear()
        for q in self.plane.scheduling_stats()["queues"]:
            depth.set(q["depth"], queue=q["name"])
            running.set(q["running"], queue=q["name"])
        started = getattr(self.server, "started_at", None)
        if started is not None:
            registry.gauge("polyaxon_uptime_seconds",
                           "API server uptime").set(time.time() - started)
        # Stable scrape schema: the documented families (incl. the
        # histograms) exist even before their first sample.
        obs_metrics.ensure_core_metrics(registry)
        body = registry.render().encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/plain; version=0.0.4")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    # -- runs --------------------------------------------------------------
    def _runs(self, method: str, caller: Optional[str], owner: str,
              project: str, rest: list[str], query: dict) -> None:
        plane = self.plane
        if not rest:
            if method == "POST":
                body = self._read_body()
                try:
                    record = plane.submit(
                        body.get("content"),
                        project=project,
                        params=body.get("params"),
                        presets=body.get("presets"),
                        name=body.get("name"),
                        tags=body.get("tags"),
                        # Stamped from the authenticated PATH (not the
                        # body): record-level isolation keys off it.
                        meta={"owner": owner},
                    )
                except ApiError:
                    raise
                except Exception as exc:
                    raise ApiError(400, f"submit failed: {exc}") from exc
                return self._json(_record_json(record), status=201)
            from polyaxon_tpu.lifecycle import V1Statuses

            kwargs: dict[str, Any] = {"project": project}
            if "status" in query:
                try:
                    kwargs["statuses"] = [V1Statuses(s) for s in query["status"]]
                except ValueError as exc:
                    raise ApiError(400, str(exc)) from exc
            if "pipeline" in query:
                kwargs["pipeline_uuid"] = query["pipeline"][0]
            records = plane.list_runs(**kwargs)
            if self._auth_enabled and caller != "*":
                # Per-owner isolation on list: scoped tokens only see
                # runs stamped with their owner.
                records = [r for r in records
                           if (r.meta or {}).get("owner") == caller]
            return self._json({"count": len(records),
                               "results": [_record_json(r) for r in records]})

        uuid = rest[0]
        record = self._get_run(uuid)
        self._require_run(caller, record)
        action = rest[1] if len(rest) > 1 else None
        if action is None:
            if method == "POST":
                raise ApiError(405, "POST not allowed on run detail")
            payload = _record_json(record)
            # Detail view only: the spec carries matrix config (metric
            # name, bracket budgets) the dashboard's sweep view needs.
            payload["spec"] = record.spec
            return self._json(payload)
        if method == "POST":
            if action == "stop":
                plane.stop(uuid, message=(self._read_body().get("message") or ""))
                return self._json({"status": "stopping"})
            if action == "restart":
                body = self._read_body()
                new = plane.restart(uuid, copy=bool(body.get("copy")))
                return self._json(_record_json(new), status=201)
            if action == "resume":
                return self._json(_record_json(plane.resume(uuid)), status=201)
            raise ApiError(404, f"unknown action {action}")
        if action == "statuses":
            return self._json(plane.get_statuses(uuid))
        if action == "timeline":
            # Ordered lifecycle span tree (obs.trace): compile →
            # admission → placement → execute → runtime → sync, with
            # chaos/retry annotations. Backs the dashboard waterfall
            # and `plx ops timeline`.
            return self._json(plane.timeline(uuid))
        if action == "report":
            # Performance attribution (obs.analyze): wall clock by
            # phase, step-time trend + anomaly flags, fault annotations
            # per phase. Backs `plx ops report`.
            return self._json(plane.report(uuid))
        if action == "verify":
            # Telemetry-oracle verdicts (obs.oracle) scoped to this
            # run: committed invariants judged against its timeline,
            # report, the registry, and alert state. Backs
            # `plx ops verify`.
            return self._json(plane.verify(uuid))
        if action == "metrics":
            names = query.get("names")
            return self._json(plane.streams.get_metrics(uuid, names))
        if action == "events":
            kind = (query.get("kind") or ["metric"])[0]
            names = query.get("names")
            return self._json(plane.streams.get_events(uuid, kind, names))
        if action == "lineage":
            if rest[2:] == ["graph"]:
                # Cross-run inputs → run → outputs graph. Scoped tokens:
                # the node set is filtered to the caller's own runs so a
                # graph cannot leak another owner's run names.
                graph = plane.lineage_graph(uuid)
                if caller not in (None, "*"):
                    # Nodes carry their owner stamp — no per-node
                    # store fetch needed to filter foreign runs out.
                    visible = {n["uuid"] for n in graph["nodes"]
                               if n.get("owner") == caller}
                    graph["nodes"] = [n for n in graph["nodes"]
                                      if n["uuid"] in visible]
                    graph["edges"] = [e for e in graph["edges"]
                                      if e["from"] in visible
                                      and e["to"] in visible]
                return self._json(graph)
            return self._json(plane.streams.get_lineage(uuid))
        if action == "outputs":
            return self._json(plane.streams.get_outputs(uuid))
        if action == "artifacts":
            if len(rest) > 2:
                return self._artifact(uuid, "/".join(rest[2:]))
            if (query.get("detail") or ["0"])[0] in ("1", "true"):
                return self._json(plane.streams.list_artifacts_detail(uuid))
            return self._json(plane.streams.list_artifacts(uuid))
        raise ApiError(404, f"unknown sub-resource {action}")

    def _artifact(self, uuid: str, rel: str) -> None:
        import mimetypes
        import os

        path = self.plane.streams.artifact_path(uuid, rel)
        if not os.path.isfile(path):
            raise ApiError(404, f"artifact {rel} not found")
        size = os.path.getsize(path)
        # Real content types so the dashboard renders logged images/
        # html inline (a jsonl/log/unknown file stays a download).
        # CSP sandbox: artifacts are run-produced content served from
        # the API origin — an html/svg artifact must render without
        # script execution or API credentials (stored-XSS guard).
        ctype = (mimetypes.guess_type(path)[0]
                 or "application/octet-stream")
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("X-Content-Type-Options", "nosniff")
        self.send_header("Content-Security-Policy", "sandbox")
        self.send_header("Content-Length", str(size))
        self.end_headers()
        # Stream exactly `size` bytes: a live run may append between the
        # stat and the read, and extra bytes would corrupt keep-alive
        # framing (the client parses them as the next response).
        remaining = size
        with open(path, "rb") as fh:
            while remaining > 0:
                chunk = fh.read(min(1 << 16, remaining))
                if not chunk:
                    break
                remaining -= len(chunk)
                self.wfile.write(chunk)

    # -- streams -----------------------------------------------------------
    def _logs(self, caller: Optional[str], uuid: str, query: dict) -> None:
        import time

        record = self._get_run(uuid)
        self._require_run(caller, record)
        follow = query.get("follow", ["false"])[0].lower() == "true"
        streams = self.plane.streams
        if not follow:
            text = ""
            for name in streams.log_files(uuid):
                chunk, _ = streams.read_logs(uuid, name)
                text += chunk
            return self._json({"logs": text})

        # SSE, ALWAYS (even when the run already finished — the client
        # contract is `data:` events then `event: done`): tail every log
        # file of the gang, interleaved as content appears.
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.end_headers()
        offsets: dict[str, int] = {}

        def emit_available() -> bool:
            wrote = False
            for name in streams.log_files(uuid):
                chunk, offsets[name] = streams.read_logs(
                    uuid, name, offsets.get(name, 0))
                if chunk:
                    payload = "".join(
                        f"data: {line}\n" for line in chunk.splitlines())
                    self.wfile.write((payload + "\n").encode())
                    wrote = True
            if wrote:
                self.wfile.flush()
            return wrote

        try:
            while True:
                wrote = emit_available()
                if self.plane.get_run(uuid).is_done:
                    emit_available()  # final drain after terminal status
                    break
                if not wrote:
                    time.sleep(0.2)
            self.wfile.write(b"event: done\ndata: \n\n")
        except BrokenPipeError:
            pass


class ApiServer:
    """Owns the HTTP server thread; ``with ApiServer(plane) as s: s.port``."""

    def __init__(self, plane: ControlPlane, host: str = "127.0.0.1",
                 port: int = 0, slice_manager=None,
                 auth_token: Optional[str] = None,
                 owner_tokens: Optional[dict[str, str]] = None):
        import time

        handler = type("BoundHandler", (_Handler,),
                       {"plane": plane, "slice_manager": slice_manager,
                        "auth_token": auth_token,
                        "owner_tokens": owner_tokens or {}})
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.httpd.started_at = time.time()
        self.host = host
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "ApiServer":
        self._thread = threading.Thread(target=self.httpd.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            # Drain the serve loop so in-flight handlers finish before
            # teardown (a daemon thread dies mid-response at exit).
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self) -> "ApiServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


