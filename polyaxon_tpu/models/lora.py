"""LoRA fine-tuning as config (net-new surface — the reference
orchestrates containers and owns no training math, SURVEY.md §2b).

Design: a :class:`ModelDef` wrapper, so the train step, checkpointing,
sharding, and loop machinery stay untouched. The wrapped state is
``{"params": {"base": <frozen full tree>, "lora": {path: {"a","b"}}}}``:

- ``apply`` merges ``W_eff = stop_gradient(W) + (alpha/rank)·A@B``
  inside the jitted step — ``stop_gradient`` lets XLA dead-code the
  base weight-gradient GEMMs, so backward cost tracks the adapters,
  not the full model;
- the optimizer is wrapped in ``optax.masked`` over the lora subtree,
  so moment/velocity state exists ONLY for adapters — the memory that
  makes fine-tuning an 8B on small slices possible;
- adapter shardings derive from the base leaf's logical axes
  (``W: (row, col)`` → ``A: (row, None)``, ``B: (None, col)``), so
  FSDP/TP layouts carry over to A/B unchanged.

Init follows the public LoRA recipe: A ~ N(0, 1/rank), B = 0 — the
adapted model starts exactly at the base model.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from polyaxon_tpu.models.common import ModelDef

# Matmul weights adapted by default: attention + MLP projections of
# the decoder families (embeddings/norms/lm_head stay frozen).
DEFAULT_TARGETS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")
# T5 adds fused encoder QKV and the cross-attention projections; pass
# as ``lora_targets`` when fine-tuning the seq2seq family.
T5_TARGETS = DEFAULT_TARGETS + ("wqkv", "xq", "xkv", "xo")


def _path_str(path) -> str:
    """'/'-joined pytree key path (DictKey/SequenceKey agnostic) — the
    stable leaf address the lora tree is keyed by."""
    return "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                    for k in path)


def _match(path: tuple, targets) -> bool:
    leaf_name = str(path[-1])
    return any(re.fullmatch(t, leaf_name) for t in targets)


def init_lora(params: Any, rank: int, targets, key: jax.Array) -> dict:
    """A/B adapters for every eligible leaf (ndim >= 2, name matches
    ``targets``). Keyed by '/'-joined path so the lora tree is a flat
    dict that checkpoints/shards like any other params tree."""
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    lora: dict[str, dict] = {}
    for path, leaf in flat:
        p = tuple(str(getattr(k, "key", getattr(k, "idx", k)))
                  for k in path)
        if leaf.ndim < 2 or not _match(p, targets):
            continue
        key, sub = jax.random.split(key)
        *stack, d_in, d_out = leaf.shape
        a = jax.random.normal(sub, (*stack, d_in, rank),
                              jnp.float32) * (rank ** -0.5)
        b = jnp.zeros((*stack, rank, d_out), jnp.float32)
        lora["/".join(p)] = {"a": a.astype(leaf.dtype),
                             "b": b.astype(leaf.dtype)}
    if not lora:
        raise ValueError(
            f"no params matched lora targets {tuple(targets)} — check "
            "the target names against the model's param tree")
    return lora


def with_meta(lora: dict, rank: int, alpha: float) -> dict:
    """Persist the merge hyperparameters INSIDE the lora tree (scalar
    leaves, masked from the optimizer) so a checkpoint is
    self-describing — serving must never have to guess alpha."""
    # Both as f32: these leaves ride through value_and_grad (zero
    # gradient, masked from updates), and grad refuses integer inputs.
    return {**lora, "_meta": {"alpha": jnp.float32(alpha),
                              "rank": jnp.float32(rank)}}


def split_meta(lora: dict) -> tuple[dict, Optional[dict]]:
    adapters = {k: v for k, v in lora.items() if k != "_meta"}
    return adapters, lora.get("_meta")


def merge(base: Any, lora: dict, alpha: float, rank: int) -> Any:
    """``W_eff = stop_gradient(W) + (alpha/rank)·A@B`` for adapted
    leaves; plain ``stop_gradient`` for the rest (backward never
    touches base weights)."""
    scale = alpha / rank

    def rebuild(path, leaf):
        leaf = jax.lax.stop_gradient(leaf)
        ab = lora.get(_path_str(path))
        if ab is None:
            return leaf
        delta = jnp.einsum("...ir,...ro->...io", ab["a"].astype(jnp.float32),
                           ab["b"].astype(jnp.float32))
        return leaf + (scale * delta).astype(leaf.dtype)

    return jax.tree_util.tree_map_with_path(rebuild, base)


def merge_saved(base: Any, lora: dict, alpha: Optional[float] = None,
                rank: Optional[int] = None, host: bool = False) -> Any:
    """Fold saved adapters into dense weights (serving a fine-tune:
    load the checkpoint, merge, serve — zero runtime overhead). Alpha
    and rank come from the checkpoint's own ``_meta`` when present;
    the arguments are fallbacks for pre-meta checkpoints. ``host=True``
    merges with numpy (no device materialization — an 8B's stacked
    leaves would otherwise land unsharded on device 0)."""
    lora, meta = split_meta(lora)
    if meta is not None:
        alpha = float(np.asarray(meta["alpha"]))
        rank = int(np.asarray(meta["rank"]))
    if alpha is None:
        raise ValueError("checkpoint has no lora _meta; pass alpha= "
                         "explicitly (must match training)")
    if rank is None:
        rank = int(next(iter(lora.values()))["a"].shape[-1])
    if not host:
        return merge(base, lora, alpha, rank)

    scale = alpha / rank

    def rebuild(path, leaf):
        ab = lora.get(_path_str(path))
        if ab is None:
            return leaf
        leaf = np.asarray(leaf)
        delta = np.einsum("...ir,...ro->...io",
                          np.asarray(ab["a"], np.float32),
                          np.asarray(ab["b"], np.float32))
        return leaf + (scale * delta).astype(leaf.dtype)

    return jax.tree_util.tree_map_with_path(rebuild, base)


def _lora_logical_axes(base_logical: Any, lora_shapes: dict) -> dict:
    """Adapter shardings from the base leaf's logical axes: A keeps the
    row axis, B keeps the col axis, the rank axis is unsharded."""
    flat = {
        _path_str(path): axes
        for path, axes in jax.tree_util.tree_flatten_with_path(
            base_logical, is_leaf=lambda x: isinstance(x, tuple))[0]
    }
    out = {}
    for name, ab in lora_shapes.items():
        if name == "_meta":
            out[name] = {"alpha": (), "rank": ()}  # replicated scalars
            continue
        axes = flat.get(name)
        if isinstance(axes, tuple) and len(axes) >= 2:
            *stack, row, col = axes
            out[name] = {"a": tuple(stack) + (row, None),
                         "b": tuple(stack) + (None, col)}
        else:  # replicated adapters for leaves with unknown layout
            out[name] = {"a": (None,) * ab["a"].ndim,
                         "b": (None,) * ab["b"].ndim}
    return out


def lora_model_def(model_def: ModelDef, rank: int, alpha: float,
                   targets: Optional[tuple] = None) -> ModelDef:
    """Wrap a ModelDef for LoRA: same train-step/loop/checkpoint
    machinery, state = {base (frozen), lora (trained)}."""
    targets = tuple(targets or DEFAULT_TARGETS)

    def init(rng: jax.Array):
        variables = model_def.init(rng)
        base = variables["params"]
        lora = with_meta(
            init_lora(base, rank, targets, jax.random.fold_in(rng, 51)),
            rank, alpha)
        out = dict(variables)
        out["params"] = {"base": base, "lora": lora}
        return out

    def apply(variables, batch, train=True, rng=None):
        p = variables["params"]
        adapters, _ = split_meta(p["lora"])
        merged = merge(p["base"], adapters, alpha, rank)
        inner = dict(variables)
        inner["params"] = merged
        return model_def.apply(inner, batch, train, rng)

    def logical_axes():
        logical = model_def.logical_axes()
        base_logical = logical["params"]
        # The lora tree's axes need the lora STRUCTURE, which needs an
        # init — derive lazily from a shape-only eval.
        shapes = jax.eval_shape(lambda k: init(k)["params"]["lora"],
                                jax.random.key(0))
        out = dict(logical)
        out["params"] = {"base": base_logical,
                         "lora": _lora_logical_axes(base_logical, shapes)}
        return out

    return dataclasses.replace(
        model_def, name=f"{model_def.name}+lora{rank}",
        init=init, apply=apply, logical_axes=logical_axes)


def lora_optimizer_mask(params: dict) -> dict:
    """optax.masked mask: True (train) for the adapters, False (frozen,
    no optimizer state) for base and the ``_meta`` scalars."""
    return {
        "base": jax.tree.map(lambda _: False, params["base"]),
        "lora": {k: jax.tree.map(lambda _: k != "_meta", v)
                 for k, v in params["lora"].items()},
    }


def wrap_optimizer(optimizer: optax.GradientTransformation
                   ) -> optax.GradientTransformation:
    """Moment/velocity state only for adapters; base updates are
    structurally zero."""
    return optax.masked(optimizer, lora_optimizer_mask)
