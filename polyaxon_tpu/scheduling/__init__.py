"""Multi-tenant scheduling: queues, priority classes, quotas, fair-share
admission, and priority preemption (ISSUE 2; docs/scheduling.md)."""

from polyaxon_tpu.scheduling.admission import (
    AdmissionController,
    AdmissionDecision,
    LIVE_STATUSES,
)
from polyaxon_tpu.scheduling.catalog import (
    DEFAULT_PRIORITY_CLASS,
    DEFAULT_QUEUE,
    PRIORITY_CLASSES,
    RunSchedInfo,
    SchedulingError,
    V1Queue,
    V1Quota,
    gang_priority,
    resolve_priority_class,
    sched_info,
)

__all__ = [
    "AdmissionController",
    "AdmissionDecision",
    "DEFAULT_PRIORITY_CLASS",
    "DEFAULT_QUEUE",
    "LIVE_STATUSES",
    "PRIORITY_CLASSES",
    "RunSchedInfo",
    "SchedulingError",
    "V1Queue",
    "V1Quota",
    "gang_priority",
    "resolve_priority_class",
    "sched_info",
]
