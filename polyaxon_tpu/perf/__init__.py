"""Communication-efficiency audit (VERDICT r5 next-round #2/#4).

Static accounting of what the compiler actually emits for each
(model, mesh, schedule) point:

- ``hlo``     parses compiled HLO for collectives (all-reduce,
  all-gather, reduce-scatter, all-to-all, collective-permute) and
  estimates bytes moved per op from shapes + replica groups — the
  GSPMD-style "communication is explicit in the sharded program"
  property, turned into a report.
- ``audit``   lowers/compiles the real ``build_train_step`` program per
  schedule point on the 8-device virtual CPU mesh and summarizes its
  collectives.
- ``budgets`` per-schedule collective budgets checked in CI: an
  accidental reshard fails the build instead of silently costing 4.7x.
- ``aot``     strictly-timeouted subprocess probe of AOT topology-only
  TPU compilation, so tunnel-down rounds still produce TPU HLO/cost
  stats — or a recorded negative result.

Run ``python -m polyaxon_tpu.perf --help`` (docs/performance.md
"Communication audit" has the playbook).
"""

from polyaxon_tpu.perf.hlo import (
    CollectiveOp,
    parse_collectives,
    summarize_collectives,
)

__all__ = ["CollectiveOp", "parse_collectives", "summarize_collectives"]
