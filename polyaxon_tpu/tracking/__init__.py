from polyaxon_tpu.tracking.events import (
    EventWriter,
    V1EventKind,
    list_event_names,
    read_events,
    tail_file,
)
from polyaxon_tpu.tracking.run import (
    ENV_ARTIFACTS_PATH,
    ENV_OUTPUTS_PATH,
    ENV_PROJECT,
    ENV_RUN_NAME,
    ENV_RUN_UUID,
    Run,
    from_env,
    get_or_create_run,
)
from polyaxon_tpu.tracking.systemmetrics import SystemMetricsMonitor, host_metrics, tpu_metrics

__all__ = [
    "ENV_ARTIFACTS_PATH",
    "ENV_OUTPUTS_PATH",
    "ENV_PROJECT",
    "ENV_RUN_NAME",
    "ENV_RUN_UUID",
    "EventWriter",
    "Run",
    "SystemMetricsMonitor",
    "V1EventKind",
    "from_env",
    "get_or_create_run",
    "host_metrics",
    "list_event_names",
    "read_events",
    "tail_file",
    "tpu_metrics",
]
