"""Mesh + sharding-rule tests on the 8-device virtual CPU mesh —
SURVEY.md §4: real collective execution is testable in-process here,
which the reference never had for NCCL."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from polyaxon_tpu.parallel import (
    build_mesh,
    logical_to_spec,
    mesh_summary,
    merge_rules,
    rules_for_mesh,
    tree_shardings,
)
from polyaxon_tpu.parallel.bootstrap import read_env_contract
from polyaxon_tpu.parallel.sharding import FSDP_RULES, TP_RULES
from polyaxon_tpu.polyflow import V1MeshSpec, V1TpuTopology


class TestMesh:
    def test_build_from_spec(self, cpu_devices):
        mesh = build_mesh(V1MeshSpec(axes={"dp": 2, "fsdp": 4}))
        assert mesh.axis_names == ("dp", "fsdp")
        assert mesh.devices.shape == (2, 4)

    def test_fill_axis(self, cpu_devices):
        mesh = build_mesh(V1MeshSpec(axes={"dp": 2, "fsdp": -1}))
        assert dict(zip(mesh.axis_names, mesh.devices.shape)) == {"dp": 2, "fsdp": 4}

    def test_axis_aliases_and_order(self, cpu_devices):
        mesh = build_mesh(axes={"model": 2, "data": 4})
        # canonical order: dp before tp regardless of spec order
        assert mesh.axis_names == ("dp", "tp")
        assert mesh.devices.shape == (4, 2)

    def test_size_mismatch(self, cpu_devices):
        with pytest.raises(ValueError):
            build_mesh(axes={"dp": 3})

    def test_hybrid_multislice_mesh(self, cpu_devices):
        """2 slices of 4 chips: dp over DCN, fsdp over ICI."""
        topo = V1TpuTopology(accelerator="v5e", topology="2x2", slices=2)
        spec = V1MeshSpec(axes={"dp": 2, "fsdp": 4}, dcn_axes=["dp"])
        mesh = build_mesh(spec, topo)
        assert mesh.devices.shape == (2, 4)
        summary = mesh_summary(mesh)
        assert summary["n_devices"] == 8

    def test_collective_on_mesh(self, cpu_devices):
        mesh = build_mesh(axes={"dp": 8})
        x = jax.device_put(
            jnp.arange(16.0).reshape(8, 2), NamedSharding(mesh, P("dp"))
        )
        total = jax.jit(lambda a: a.sum())(x)
        assert float(total) == sum(range(16))


class TestRules:
    def test_fsdp_spec_mapping(self):
        spec = logical_to_spec(("embed", "heads"), FSDP_RULES)
        assert spec == P("fsdp")
        spec = logical_to_spec(("batch", None), FSDP_RULES)
        assert spec == P(("dp", "fsdp"))

    def test_axis_used_once(self):
        # embed->fsdp twice in one tensor: second occurrence replicates.
        spec = logical_to_spec(("embed", "embed"), FSDP_RULES)
        assert spec == P("fsdp")

    def test_mesh_filtering(self, cpu_devices):
        mesh = build_mesh(axes={"dp": 8})  # no fsdp axis in mesh
        spec = logical_to_spec(("embed", "mlp"), FSDP_RULES, mesh=mesh)
        assert spec == P()

    def test_merge_rules_later_wins(self):
        rules = merge_rules(FSDP_RULES, TP_RULES)
        table = dict(rules)
        assert table["mlp"] == "tp"
        assert table["batch"] == ("dp", "fsdp")

    def test_rules_for_mesh_composition(self, cpu_devices):
        mesh = build_mesh(axes={"dp": 2, "fsdp": 2, "tp": 2})
        table = dict(rules_for_mesh(mesh))
        assert table["mlp"] == "tp"
        assert table["embed"] == "fsdp"

    def test_tree_shardings(self, cpu_devices):
        mesh = build_mesh(V1MeshSpec(axes={"dp": 2, "fsdp": 4}))
        tree = {"w": ("embed", "mlp"), "b": ("mlp",)}
        sh = tree_shardings(tree, mesh, rules_for_mesh(mesh))
        assert sh["w"].spec == P("fsdp")
        assert sh["b"].spec == P()


class TestBootstrap:
    def test_env_contract(self):
        group = read_env_contract(
            {
                "POLYAXON_TPU_COORDINATOR": "10.0.0.1:8476",
                "POLYAXON_TPU_NUM_PROCESSES": "16",
                "POLYAXON_TPU_PROCESS_ID": "3",
            }
        )
        assert group.coordinator == "10.0.0.1:8476"
        assert group.num_processes == 16
        assert group.process_id == 3
        assert group.is_multiprocess

    def test_single_process_default(self):
        group = read_env_contract({})
        assert not group.is_multiprocess
